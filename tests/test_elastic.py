"""Elastic world-size training: unit coverage for the pieces the 8→4→8
chaos run composes (tests/test_chaos.py::TestElasticResize).

  * the resharding map — truncate-or-zero-pad exactness, shrink/grow
    round-trip, movement interval arithmetic;
  * the membership policy — schedule grammar, attempt clamping, rescale
    policies and their provenance;
  * checkpoint world provenance — ``committed_world`` peeks, restore at
    a different world size reshards, and a torn shard at a mismatched
    world STILL quarantines-and-walks-back (resharding must not weaken
    commit-or-quarantine);
  * the supervisor's progress probe tolerating a mixed-world ckpt dir;
  * the launcher consuming the ``TPUFRAME_ELASTIC`` schedule;
  * ``partial_sigterm`` (reclaim k of n hosts) rank semantics;
  * the TF116 cached-world-size lint.
"""

import json
import os
import signal

import numpy as np
import pytest

from tpuframe import ckpt, elastic
from tpuframe.analysis import shardflow
from tpuframe.analysis.source_lint import lint_source
from tpuframe.ckpt.checkpoint import committed_world, latest_step
from tpuframe.elastic import resharding
from tpuframe.launch import launcher as launcher_mod
from tpuframe.obs import goodput
from tpuframe.resilience import faults


@pytest.fixture(autouse=True)
def _clean_elastic_env(monkeypatch):
    monkeypatch.delenv(elastic.ENV_SCHEDULE, raising=False)
    monkeypatch.delenv(elastic.ENV_RESCALE, raising=False)
    monkeypatch.delenv("TPUFRAME_FAULTS", raising=False)
    monkeypatch.delenv("TPUFRAME_PROCESS_ID", raising=False)
    faults.reset_from_env()
    yield
    faults.reset_from_env({})


# ---------------------------------------------------------------------------
# Membership schedule + rescale policy.
# ---------------------------------------------------------------------------


class TestMembership:
    def test_schedule_grammar(self):
        assert elastic.parse_schedule("8,4,8") == (8, 4, 8)
        assert elastic.parse_schedule(" 8 , 4 ") == (8, 4)
        assert elastic.parse_schedule("") == ()
        with pytest.raises(ValueError, match="must be integers"):
            elastic.parse_schedule("8,four")
        with pytest.raises(ValueError, match="must be positive"):
            elastic.parse_schedule("8,0")

    def test_world_for_attempt_clamps_to_last_leg(self):
        sched = (8, 4, 8)
        assert [elastic.world_for_attempt(a, sched)
                for a in (0, 1, 2, 3, 99)] == [8, 4, 8, 8, 8]
        with pytest.raises(ValueError, match="empty schedule"):
            elastic.world_for_attempt(0, ())

    def test_schedule_from_env(self, monkeypatch):
        assert elastic.schedule_from_env() == ()
        monkeypatch.setenv(elastic.ENV_SCHEDULE, "4,2")
        assert elastic.schedule_from_env() == (4, 2)

    def test_rescale_hold_is_identity(self):
        assert elastic.rescale(32, 0.1, 8, 4, "hold") == (32, 0.1)
        # n unchanged: every policy is the identity.
        assert elastic.rescale(32, 0.1, 8, 8, "linear") == (32, 0.1)

    def test_rescale_linear_and_sqrt(self):
        b, lr = elastic.rescale(32, 0.1, 8, 4, "linear")
        assert (b, lr) == (16, pytest.approx(0.05))
        b, lr = elastic.rescale(32, 0.1, 4, 8, "sqrt")
        assert b == 64
        assert lr == pytest.approx(0.1 * np.sqrt(2.0))

    def test_rescale_keeps_batch_a_multiple_of_n_to(self):
        # 10 * (3/4) = 7.5 → rounds to 8, floors to a multiple of 3 → 6.
        b, _ = elastic.rescale(10, 0.1, 4, 3, "linear")
        assert b % 3 == 0 and b > 0
        # Extreme shrink never drops below one example per replica.
        b, _ = elastic.rescale(4, 0.1, 64, 2, "linear")
        assert b >= 2 and b % 2 == 0

    def test_resolve_rescale_provenance(self, monkeypatch):
        assert elastic.resolve_rescale() == ("hold", "default")
        monkeypatch.setenv(elastic.ENV_RESCALE, "sqrt")
        assert elastic.resolve_rescale() == ("sqrt", "env")
        monkeypatch.setenv(elastic.ENV_RESCALE, "exponential")
        with pytest.raises(ValueError, match="unknown elastic rescale"):
            elastic.resolve_rescale()


# ---------------------------------------------------------------------------
# The resharding map.
# ---------------------------------------------------------------------------


class TestResharding:
    def test_reshard_flat_shrink_drops_only_pad(self):
        # True size 10, saved at n=8 (padded 16): rows 10..15 are zero.
        vec = np.zeros(16, np.float32)
        vec[:10] = np.arange(10, dtype=np.float32) + 1
        out = resharding.reshard_flat(vec, 12)  # n=4 layout
        np.testing.assert_array_equal(out[:10], vec[:10])
        np.testing.assert_array_equal(out[10:], 0)

    def test_reshard_flat_roundtrip_is_identity(self):
        vec = np.zeros(16, np.float32)
        vec[:10] = np.random.default_rng(0).normal(size=10)
        back = resharding.reshard_flat(
            resharding.reshard_flat(vec, 12), 16)
        np.testing.assert_array_equal(back, vec)

    def test_reshard_flat_rejects_non_flat(self):
        with pytest.raises(ValueError, match="flat 1-D"):
            resharding.reshard_flat(np.zeros((2, 3)), 4)

    def test_moved_elems_identity_and_bounds(self):
        assert resharding.moved_elems(100, 8, 8) == 0
        assert resharding.moved_elems(0, 8, 4) == 0
        for size in (1, 7, 10, 100, 4097):
            for nf, nt in ((8, 4), (4, 8), (8, 3), (3, 8)):
                m = resharding.moved_elems(size, nf, nt)
                assert 0 <= m <= size

    def test_moved_elems_matches_bruteforce(self):
        # Exactness against the O(size) definition: owner = i // chunk.
        for size, nf, nt in ((10, 8, 4), (10, 4, 8), (100, 8, 3),
                             (17, 2, 5), (64, 8, 4)):
            cf = resharding.padded_len(size, nf) // nf
            ct = resharding.padded_len(size, nt) // nt
            brute = sum(1 for i in range(size) if i // cf != i // ct)
            assert resharding.moved_elems(size, nf, nt) == brute

    def test_resize_movement_totals(self):
        leaves = [("w", 10, 4), ("b", 3, 4)]
        mv = resharding.resize_movement(leaves, 8, 4, moment_vectors=2)
        assert mv["n_leaves"] == 2
        assert mv["state_bytes"] == (12 + 4) * 4 * 2
        assert mv["moved_bytes"] == sum(
            r["moved_bytes"] for r in mv["leaves"])
        assert 0.0 <= mv["moved_frac"] <= 1.0

    def test_gate_self_check_is_clean(self):
        assert elastic.check() == []


# ---------------------------------------------------------------------------
# Checkpoint world provenance + restore-at-a-different-world.
# ---------------------------------------------------------------------------


def _flat_state(n_shards):
    """A ZeRO-1-shaped host tree: replicated params + flat padded
    moments for a true size of 10 (padded 16 at n=8, 12 at n=4)."""
    pad = resharding.padded_len(10, n_shards)
    mu = np.zeros(pad, np.float32)
    mu[:10] = np.arange(10, dtype=np.float32) + 1
    return {"params": {"w": np.arange(10.0, dtype=np.float32)},
            "opt_state": {"mu": mu, "nu": mu * 2.0}}


class TestElasticRestore:
    def test_committed_world_peeks_newest_manifest(self, tmp_path):
        assert committed_world(str(tmp_path)) is None
        mgr = ckpt.CheckpointManager(str(tmp_path), async_write=False)
        mgr.save(3, _flat_state(8))
        world = committed_world(str(tmp_path))
        import jax

        assert world == {"step": 3, "processes": jax.process_count(),
                         "devices": jax.device_count()}

    def test_committed_world_none_for_pre_elastic_manifest(self, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path), async_write=False)
        mgr.save(1, _flat_state(8))
        mpath = tmp_path / "step_00000001" / "manifest.json"
        manifest = json.loads(mpath.read_text())
        del manifest["world"]
        mpath.write_text(json.dumps(manifest))
        assert committed_world(str(tmp_path)) is None
        # ...and the peek never quarantines, even on a garbled manifest.
        mpath.write_text("{torn")
        assert committed_world(str(tmp_path)) is None
        assert not (tmp_path / "step_00000001.corrupt").exists()
        assert latest_step(str(tmp_path)) == 1

    def test_restore_latest_reshards_to_new_world(self, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path), async_write=False)
        mgr.save(5, _flat_state(8))
        step, tree = mgr.restore_latest(target=_flat_state(4))
        assert step == 5
        saved = _flat_state(8)
        # Params (replicated; shapes match) restore unchanged; moments
        # reshard 16 → 12, dropping only provably-zero pad rows.
        np.testing.assert_array_equal(np.asarray(tree["params"]["w"]),
                                      saved["params"]["w"])
        for key in ("mu", "nu"):
            got = np.asarray(tree["opt_state"][key])
            assert got.shape == (12,)
            np.testing.assert_array_equal(got[:10],
                                          saved["opt_state"][key][:10])
            np.testing.assert_array_equal(got[10:], 0)
        # Grow direction: 16-target from a 12-length save.
        mgr2 = ckpt.CheckpointManager(str(tmp_path / "grow"),
                                      async_write=False)
        mgr2.save(5, _flat_state(4))
        _, tree = mgr2.restore_latest(target=_flat_state(8))
        got = np.asarray(tree["opt_state"]["mu"])
        assert got.shape == (16,)
        np.testing.assert_array_equal(got[10:], 0)

    def test_restore_mismatch_outside_opt_state_still_raises(self, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path), async_write=False)
        mgr.save(1, _flat_state(8))
        target = _flat_state(8)
        target["params"]["w"] = np.zeros(7, np.float32)  # not opt state
        with pytest.raises(ValueError, match="no resharding map"):
            mgr.restore_latest(target=target)

    def test_torn_shard_at_new_world_quarantines_and_walks_back(
            self, tmp_path, capsys):
        """Resharding must not weaken commit-or-quarantine: a corrupt
        newest checkpoint read at a DIFFERENT world size is quarantined
        and resume walks back to the previous committed step — which is
        then itself resharded."""
        mgr = ckpt.CheckpointManager(str(tmp_path), async_write=False)
        mgr.save(3, _flat_state(8))
        mgr.save(6, _flat_state(8))
        shard = next((tmp_path / "step_00000006").glob(
            "opt_state.mu.shard_*.npy"))
        shard.write_bytes(b"\x00" * 64)  # CRC mismatch on reassembly
        step, tree = mgr.restore_latest(target=_flat_state(4))
        assert step == 3
        assert np.asarray(tree["opt_state"]["mu"]).shape == (12,)
        assert (tmp_path / "step_00000006.corrupt").is_dir()
        assert not (tmp_path / "step_00000006").exists()
        assert "quarantin" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Supervisor: mixed-world progress probe + schedule consumption.
# ---------------------------------------------------------------------------


def _fake_committed(ck, step, devices):
    d = ck / f"step_{step:08d}"
    os.makedirs(d)
    (d / "manifest.json").write_text(json.dumps(
        {"world": {"processes": 1, "devices": devices}}))
    (d / "COMMIT").write_text("done")


class TestSupervisorElastic:
    def test_progress_probe_tolerates_world_resize(self, tmp_path, capsys):
        """Satellite: a ckpt dir whose committed world differs from the
        relaunch world must not confuse the probe — steps are world-size
        invariant, so progress accounting is unchanged."""
        probe = launcher_mod._progress_probe(
            ["prog", "--ckpt-dir", str(tmp_path)])
        _fake_committed(tmp_path, 10, devices=8)
        assert probe() == 10
        _fake_committed(tmp_path, 20, devices=4)  # shrank across relaunch
        assert probe() == 20
        out = capsys.readouterr().out
        assert "resized 8" in out and "4 devices" in out
        _fake_committed(tmp_path, 30, devices=4)  # steady state: no relog
        assert probe() == 30
        assert "resized" not in capsys.readouterr().out

    def test_progress_probe_survives_pre_elastic_manifests(self, tmp_path):
        d = tmp_path / "step_00000010"
        os.makedirs(d)
        (d / "manifest.json").write_text("{}")  # no world key
        (d / "COMMIT").write_text("done")
        probe = launcher_mod._progress_probe(
            ["prog", "--ckpt-dir", str(tmp_path)])
        assert probe() == 10

    def test_launcher_sizes_attempts_from_schedule(self, monkeypatch):
        """The launcher's elastic leg arithmetic: world_for_attempt
        drives devices-per-process, and a world not divisible by the
        process count is a config error, not a truncation."""
        sched = elastic.parse_schedule("8,4,8")
        for attempt, want in ((0, 8), (1, 4), (2, 8), (7, 8)):
            n = elastic.world_for_attempt(attempt, sched)
            assert n == want and n % 2 == 0  # 2 procs × n/2 devices
        assert elastic.world_for_attempt(1, sched) % 3 != 0


# ---------------------------------------------------------------------------
# partial_sigterm: reclaim k of n hosts.
# ---------------------------------------------------------------------------


class TestPartialSigterm:
    def test_parse_k_option(self):
        f = faults.parse("host:step=4:kind=partial_sigterm:k=2")[0]
        assert (f.seam, f.kind, f.step, f.k) == ("host",
                                                 "partial_sigterm", 4, 2)
        with pytest.raises(ValueError, match="k must be >= 1"):
            faults.parse("host:kind=partial_sigterm:k=0")

    def test_spares_hosts_at_or_beyond_k(self, monkeypatch, capsys):
        monkeypatch.setenv("TPUFRAME_PROCESS_ID", "2")
        reg = faults.FaultRegistry(
            faults.parse("host:kind=partial_sigterm:k=2"))
        reg.fire("host")  # rank 2 >= k=2: survives
        assert "spared host 2" in capsys.readouterr().out

    def test_signals_hosts_below_k(self, monkeypatch, capsys):
        monkeypatch.setenv("TPUFRAME_PROCESS_ID", "1")
        got = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: got.append(s))
        try:
            reg = faults.FaultRegistry(
                faults.parse("host:kind=partial_sigterm:k=2"))
            reg.fire("host")
        finally:
            signal.signal(signal.SIGTERM, prev)
        assert got == [signal.SIGTERM]
        assert "raising SIGTERM on host 1" in capsys.readouterr().out

    def test_budget_spent_once(self, monkeypatch, capsys):
        monkeypatch.setenv("TPUFRAME_PROCESS_ID", "5")
        reg = faults.FaultRegistry(
            faults.parse("host:kind=partial_sigterm:times=1"))
        reg.fire("host")
        reg.fire("host")  # budget spent: no-op
        assert capsys.readouterr().out.count("spared") == 1


# ---------------------------------------------------------------------------
# TF116: world size cached at module import.
# ---------------------------------------------------------------------------


class TestTF116:
    def test_flags_module_level_cache(self):
        src = "import jax\nN_DEVICES = jax.device_count()\n"
        found = lint_source(src, "tpuframe/obs/widget.py")
        assert [f.rule for f in found] == ["TF116"]
        assert "current_world" in found[0].message

    def test_allows_call_time_reads_and_sanctioned_seams(self):
        in_fn = "import jax\ndef f():\n    return jax.device_count()\n"
        assert lint_source(in_fn, "tpuframe/obs/widget.py") == []
        cached = "import jax\nN = jax.process_count()\n"
        assert lint_source(cached, "tpuframe/parallel/mesh2.py") == []
        assert lint_source(cached, "tpuframe/elastic/thing.py") == []
        assert lint_source(cached, "tpuframe/launch/thing.py") == []

    def test_suppression(self):
        src = ("import jax\n"
               "# static probe, never survives a relaunch\n"
               "N = jax.device_count()  # tf-lint: ok[TF116]\n")
        assert lint_source(src, "tpuframe/obs/widget.py") == []


# ---------------------------------------------------------------------------
# Stitcher + budget surfacing.
# ---------------------------------------------------------------------------


class TestResizeAccounting:
    def test_goodput_surfaces_transitions(self):
        events = [
            {"type": "step", "step": 1, "attempt": 0, "t": 1.0,
             "wall_ms": 10.0},
            {"type": "step", "step": 2, "attempt": 0, "t": 2.0,
             "wall_ms": 10.0},
            {"type": "elastic_resize", "attempt": 1, "t": 3.0,
             "n_from": 8, "n_to": 4, "policy": "hold"},
            {"type": "step", "step": 2, "attempt": 1, "t": 4.0,
             "wall_ms": 10.0},  # the one replayed step
            {"type": "step", "step": 3, "attempt": 1, "t": 5.0,
             "wall_ms": 10.0},
        ]
        g = goodput.from_events(events)
        assert g["attempts"] == 2
        assert g["retrained_steps"] == 1
        assert g["elastic_resizes"] == 1
        assert g["elastic_transitions"] == ["8->4"]

    def test_goodput_omits_keys_without_resizes(self):
        g = goodput.from_events([{"type": "step", "step": 1, "attempt": 0,
                                  "t": 1.0, "wall_ms": 10.0}])
        assert "elastic_resizes" not in g

    def test_resize_drift_gating(self):
        # Missing entry is a finding only when the jax version matches.
        stale = {"jax": "not-this-version", "strategies": {}}
        assert shardflow.resize_drift(stale) == []
        assert shardflow.resize_drift(None) == []
        current = {"jax": shardflow._jax_version(), "strategies": {}}
        problems = shardflow.resize_drift(current)
        assert problems and "elastic-resize budget missing" in problems[0]

    def test_resize_drift_detects_mismatch(self):
        fresh = shardflow.derive_resize(8)
        ok = {"jax": shardflow._jax_version(), "strategies": {},
              "elastic_resize": fresh}
        assert shardflow.resize_drift(ok, n_devices=8) == []
        tampered = {k: dict(v) for k, v in fresh.items()}
        next(iter(tampered.values()))["moved_bytes"] += 1
        bad = {"jax": shardflow._jax_version(), "strategies": {},
              "elastic_resize": tampered}
        problems = shardflow.resize_drift(bad, n_devices=8)
        assert problems and "drift" in problems[0]
