"""Pin the static HBM-traffic model (perf/traffic_model.py).

The model's credibility rests on its layer enumeration being exactly
ResNet-50 v1.5 — pinned here against the canonical torchvision parameter
count — and on its outputs being stable (the PERF.md attribution cites
specific numbers; a silent drift in the model would orphan them).
"""

import functools
import json
import pathlib
import subprocess
import sys

_SCRIPT = pathlib.Path(__file__).resolve().parents[1] / "perf" / "traffic_model.py"


@functools.lru_cache(maxsize=4)
def _run(batch):
    out = subprocess.run(
        [sys.executable, str(_SCRIPT), str(batch)],
        capture_output=True, text=True, check=True)
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_param_count_matches_torchvision_resnet50():
    rec = _run(512)
    assert rec["param_count_model"] == 25_557_032
    assert rec["param_count_model"] == rec["param_count_reference"]


def test_batch512_numbers_pinned():
    rec = _run(512)
    # Conservative-variant logical total: within 1% of the on-chip
    # XLA-counted 143.5 GB/step (perf/exp_breakdown.py) — the PERF.md §6
    # "traffic is structural, not padding" claim.
    assert rec["logical_gb"] == 144.18
    assert rec["padded_gb"] == 195.61
    # Fusion-aware variant's split brackets the measured fwd/bwd split.
    assert rec["variant_b_total_gb"] == 149.91
    assert rec["variant_b_bwd_gb"] == 102.97


def test_traffic_scales_linearly_with_batch():
    r256, r512 = _run(256), _run(512)
    # Activation traffic dominates and is batch-proportional; the small
    # constant term (weights + optimizer) keeps the ratio just under 2.
    ratio = r512["logical_gb"] / r256["logical_gb"]
    assert 1.97 < ratio <= 2.0
