"""TPU cross-platform lowering guard for the flash-attention kernels.

The CPU suite runs the kernels under the Pallas interpreter, which skips
the pallas→Mosaic lowering stage entirely — historically the place
on-chip-only breakage hides (tiling, scratch shapes, compiler params:
round-2 verdict #2).  ``jax.export`` can lower for platform "tpu" from a
CPU host, running kernel tracing, BlockSpec/grid validation, and Mosaic
custom-call serialization without hardware.  This does NOT cover the
final Mosaic→TPU codegen (tests/test_flash_attention_tpu.py does, on
chip), but it catches the lowering class in CI.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import export

from tpuframe.ops.flash_attention import flash_mha

pytestmark = pytest.mark.skipif(
    jax.default_backend() == "tpu",
    reason="cross-platform lowering guard; redundant on a real TPU")


def _qkv(dtype=jnp.bfloat16, s=256):
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(rng.normal(size=(2, s, 4, 64)), dtype)  # noqa: E731
    return mk(), mk(), mk()


def _assert_tpu_lowerable(fn, *args):
    exp = export.export(jax.jit(fn), platforms=["tpu"])(*args)
    assert b"tpu_custom_call" in exp.mlir_module_serialized


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
def test_fwd_lowers_for_tpu(causal, dtype):
    q, k, v = _qkv(dtype)
    _assert_tpu_lowerable(
        lambda q, k, v: flash_mha(q, k, v, causal=causal, interpret=False),
        q, k, v)


def test_fwd_with_mask_lowers_for_tpu():
    q, k, v = _qkv()
    mask = jnp.ones((2, 256), jnp.int32)
    _assert_tpu_lowerable(
        lambda q, k, v, m: flash_mha(q, k, v, mask=m, interpret=False),
        q, k, v, mask)


@pytest.mark.parametrize("causal", [False, True])
def test_bwd_lowers_for_tpu(causal):
    q, k, v = _qkv()

    def loss(q, k, v):
        return jnp.sum(flash_mha(q, k, v, causal=causal,
                                 interpret=False).astype(jnp.float32) ** 2)

    _assert_tpu_lowerable(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def test_nondefault_blocks_lower_for_tpu():
    # The queue-5 sweep's block shapes must at least lower.
    q, k, v = _qkv(s=1024)
    _assert_tpu_lowerable(
        lambda q, k, v: flash_mha(q, k, v, causal=True, block_q=256,
                                  block_k=512, interpret=False), q, k, v)
