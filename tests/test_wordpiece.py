"""WordPiece tokenizer: token-for-token parity with HF BertTokenizer on the
same vocab (the reference's GLUE tokenization, SURVEY.md §3a), plus the
glue_sst2 wiring that makes it the default when a vocab.txt is present."""

import numpy as np
import pytest

from tpuframe.data.wordpiece import WordPieceTokenizer

VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "the", "quick", "brown", "fox", "jump", "##ed", "##s", "over", "a",
    "lazy", "dog", "un", "##believ", "##able", "!", ",", ".", "'", "cafe",
    "it", "was", "good", "bad", "movie", "this", "film", "is",
]

SENTENCES = [
    "The quick brown fox jumped over a lazy dog!",
    "unbelievable, it was GOOD.",
    "café dog",              # accent strip: café -> cafe
    "it's a movie",               # punctuation split on the apostrophe
    "xyzzyplugh dog",             # unknown word -> [UNK]
    "this film is unbelievable",
]


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    p = tmp_path_factory.mktemp("wp") / "vocab.txt"
    p.write_text("\n".join(VOCAB) + "\n")
    return str(p)


@pytest.fixture(scope="module")
def hf_tokenizer(vocab_file):
    transformers = pytest.importorskip("transformers")

    return transformers.BertTokenizer(vocab_file, do_lower_case=True)


def test_tokenize_matches_hf(vocab_file, hf_tokenizer):
    tok = WordPieceTokenizer(vocab_file)
    for s in SENTENCES:
        assert tok.tokenize(s) == hf_tokenizer.tokenize(s), s


def test_encode_matches_hf(vocab_file, hf_tokenizer):
    tok = WordPieceTokenizer(vocab_file)
    enc = tok.encode_batch(SENTENCES, max_len=16)
    ref = hf_tokenizer(SENTENCES, padding="max_length", truncation=True,
                       max_length=16, return_tensors="np")
    np.testing.assert_array_equal(enc["input_ids"], ref["input_ids"])
    np.testing.assert_array_equal(enc["attention_mask"],
                                  ref["attention_mask"])
    np.testing.assert_array_equal(enc["token_type_ids"],
                                  ref["token_type_ids"])


def test_pair_encoding_matches_hf(vocab_file, hf_tokenizer):
    tok = WordPieceTokenizer(vocab_file)
    pairs = [("the quick fox", "a lazy dog"),
             ("this film is unbelievable", "it was good")]
    enc = tok.encode_batch(pairs, max_len=12)
    ref = hf_tokenizer([p[0] for p in pairs], [p[1] for p in pairs],
                       padding="max_length", truncation="longest_first",
                       max_length=12, return_tensors="np")
    np.testing.assert_array_equal(enc["input_ids"], ref["input_ids"])
    np.testing.assert_array_equal(enc["token_type_ids"],
                                  ref["token_type_ids"])


def test_glue_sst2_uses_vocab_when_present(tmp_path):
    from tpuframe.data import datasets

    tsv = "sentence\tlabel\n" + "\n".join(
        f"{s}\t{i % 2}" for i, s in enumerate(SENTENCES))
    (tmp_path / "train.tsv").write_text(tsv)
    (tmp_path / "dev.tsv").write_text(tsv)
    (tmp_path / "vocab.txt").write_text("\n".join(VOCAB) + "\n")

    train, dev = datasets.glue_sst2(str(tmp_path), seq_len=16)
    tok = WordPieceTokenizer(str(tmp_path / "vocab.txt"))
    ref = tok.encode_batch(SENTENCES, max_len=16)
    np.testing.assert_array_equal(train[:len(SENTENCES)]["input_ids"],
                                  ref["input_ids"])
    assert train[:2]["label"].dtype == np.int32
    # [CLS] leads every row; padding rows end in pad_id
    assert (train[:len(SENTENCES)]["input_ids"][:, 0] == tok.cls_id).all()


def test_pair_truncation_tiebreak_matches_hf(vocab_file, hf_tokenizer):
    """Equal-length pairs force the tie-break: HF's longest_first removes
    from the SECOND sequence on ties."""
    tok = WordPieceTokenizer(vocab_file)
    pairs = [("the quick fox", "a lazy dog")]  # 3 vs 3 tokens
    for max_len in (8, 7, 6, 5):
        enc = tok.encode_batch(pairs, max_len=max_len)
        ref = hf_tokenizer([p[0] for p in pairs], [p[1] for p in pairs],
                           padding="max_length", truncation="longest_first",
                           max_length=max_len, return_tensors="np")
        np.testing.assert_array_equal(enc["input_ids"], ref["input_ids"],
                                      err_msg=f"max_len={max_len}")


def test_empty_batch(vocab_file):
    tok = WordPieceTokenizer(vocab_file)
    enc = tok.encode_batch([], max_len=16)
    assert enc["input_ids"].shape == (0, 16)


def test_explicit_missing_vocab_raises(tmp_path):
    from tpuframe.data import datasets

    (tmp_path / "train.tsv").write_text("sentence\tlabel\nhi\t0")
    (tmp_path / "dev.tsv").write_text("sentence\tlabel\nhi\t0")
    with pytest.raises(FileNotFoundError, match="vocab_file"):
        datasets.glue_sst2(str(tmp_path), vocab_file=str(tmp_path / "no.txt"))


def test_unknown_and_long_words(vocab_file):
    tok = WordPieceTokenizer(vocab_file)
    assert tok.tokenize("zzz") == ["[UNK]"]
    assert tok.tokenize("x" * 200) == ["[UNK]"]
    assert tok.tokenize("") == []
