"""On-chip (non-interpreted) proof of the Pallas flash-attention kernel.

VERDICT r2 #2: every other flash-attention test runs under the Pallas
interpreter on CPU; Mosaic lowering failures (tiling, scratch shapes,
lane-broadcast stats) only surface on real hardware.  These tests run the
kernel through the actual Mosaic compiler and assert numerics against the
XLA einsum path — fwd AND bwd, causal + padding-mask variants, bf16.

Run on the bench chip (the fixture skips everywhere else):

    TPUFRAME_TPU_TESTS=1 python -m pytest tests/test_flash_attention_tpu.py -v

The conftest honors TPUFRAME_TPU_TESTS=1 by not forcing the CPU backend.
Measured numbers from this chip live in BASELINE.md (pallas-vs-xla table).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuframe.ops import attention as attn_ops
from tpuframe.ops.flash_attention import flash_mha

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="on-chip Mosaic test; needs the real TPU (TPUFRAME_TPU_TESTS=1)")


def _qkv(b=2, s=256, n=4, d=64, dtype=jnp.bfloat16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.normal(0, 0.5, size=(b, s, n, d)), dtype)
    return mk(), mk(), mk()


def _xla_ref(q, k, v, mask=None, causal=False):
    return attn_ops.multihead_attention(q, k, v, mask=mask, causal=causal,
                                        impl="xla")


def _tol(dtype):
    # bf16 inputs: products accumulate in f32 inside both paths, but input
    # rounding dominates.  f32 inputs: at JAX's DEFAULT matmul precision the
    # MXU computes f32 dots as single-pass bf16 products (~2^-8 relative),
    # and the blocked kernel rounds differently from the one-shot XLA einsum
    # — measured max |diff| 4.2e-3 on this chip — so the f32 bound is the
    # bf16-product level, not 1e-5-class; bf16 is the contract dtype.
    return dict(atol=2e-2, rtol=2e-2) if dtype == jnp.bfloat16 \
        else dict(atol=5e-3, rtol=2e-2)


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
@pytest.mark.parametrize("causal", [False, True])
def test_fwd_matches_xla_on_chip(dtype, causal):
    q, k, v = _qkv(dtype=dtype)
    out = jax.jit(
        lambda q, k, v: flash_mha(q, k, v, causal=causal, interpret=False)
    )(q, k, v)
    ref = _xla_ref(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), **_tol(dtype))


def test_fwd_padding_mask_on_chip():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    mask = jnp.asarray(np.concatenate(
        [np.ones((2, 192)), np.zeros((2, 64))], axis=1), jnp.int32)
    out = jax.jit(
        lambda q, k, v, m: flash_mha(q, k, v, mask=m, interpret=False)
    )(q, k, v, mask)
    ref = _xla_ref(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               **_tol(jnp.bfloat16))


@pytest.mark.parametrize("causal", [False, True])
def test_bwd_matches_xla_on_chip(causal):
    q, k, v = _qkv(dtype=jnp.float32, s=256)

    def loss_flash(q, k, v):
        return jnp.sum(flash_mha(q, k, v, causal=causal,
                                 interpret=False) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_xla_ref(q, k, v, causal=causal) ** 2)

    g_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    # f32 at DEFAULT precision = bf16 MXU products (see _tol): rows whose
    # true dq is exactly 0 (causal row 0: p == 1 so ds = p*(dp - delta) == 0
    # analytically) pick up dp-vs-delta rounding noise at the 4e-3 level.
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-3, rtol=2e-2,
                                   err_msg=f"d{name} mismatch on chip")


def _f64_ref(q, k, v, causal=False):
    """Attention computed fully in float64 on the host — the precision
    yardstick (no MXU, no blocking)."""
    qf, kf, vf = (np.asarray(t, np.float64) for t in (q, k, v))
    s = np.einsum("bqnd,bknd->bnqk", qf, kf) / np.sqrt(qf.shape[-1])
    if causal:
        s_q, s_k = s.shape[-2], s.shape[-1]
        s = np.where(np.tril(np.ones((s_q, s_k), bool)), s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bnqk,bknd->bqnd", p, vf)


@pytest.mark.parametrize("causal", [False, True])
def test_f32_highest_precision_tightens_on_chip(causal):
    """Round-3 verdict weak #4: the f32 tolerance story must not be
    self-judged.  At DEFAULT precision the MXU computes f32 dots as
    single-pass bf16 products (~4e-3 error vs f64); precision=HIGHEST
    requests multi-pass f32-true products.  Assert HIGHEST (a) lands
    well below the 4e-3 bf16-product level (bound 2e-4; interpret-mode
    true-f32 measures ~1e-7, so the bound leaves margin for blocked
    on-chip accumulation) and (b) is >=10x tighter than DEFAULT on
    identical inputs — the direct on-chip evidence that Mosaic honors the
    precision plumbed through the kernels (commit ee16cc0)."""
    q, k, v = _qkv(dtype=jnp.float32)
    ref = _f64_ref(q, k, v, causal=causal)

    def err(precision):
        out = jax.jit(lambda q, k, v: flash_mha(
            q, k, v, causal=causal, interpret=False, precision=precision)
        )(q, k, v)
        return float(np.max(np.abs(np.asarray(out, np.float64) - ref)))

    err_default = err(jax.lax.Precision.DEFAULT)
    err_highest = err(jax.lax.Precision.HIGHEST)
    assert err_highest < 2e-4, (
        f"HIGHEST not well below the bf16-product level: {err_highest:.3e}")
    assert err_highest < err_default / 10, (
        f"HIGHEST ({err_highest:.3e}) not meaningfully tighter than "
        f"DEFAULT ({err_default:.3e}) — Mosaic ignoring precision?")


def test_long_seq_2k_bf16_on_chip():
    # The long-context shape class the flagship LM runs (seq ≫ block).
    q, k, v = _qkv(b=1, s=2048, n=8, d=64, dtype=jnp.bfloat16)
    out = jax.jit(
        lambda q, k, v: flash_mha(q, k, v, causal=True, interpret=False)
    )(q, k, v)
    ref = _xla_ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               **_tol(jnp.bfloat16))
