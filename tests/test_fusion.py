"""Gradient-fusion buffers (tpuframe.parallel.fusion): the knob must
*demonstrably change the compiled program* — VERDICT r2 item #4.

The decisive assertions lower the SAME many-tensor train step at different
TPUFRAME_FUSION_THRESHOLD values and count ``all-reduce`` ops in the
optimized HLO: threshold 0 → one collective per gradient leaf (Horovod's
fusion-off semantics); a large threshold → the leaves ride a handful of
packed buffers.  The golden-loss tests then prove the packing is
semantics-preserving against the default implicit pmean-of-loss path —
including the staged (overlapped) pass and its ZeRO-1 composition — and
the bucket census pins the HLO collective count arithmetically:
``bucket_census`` predicts exactly how many gradient all-reduces the
compiled program carries at every threshold."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from tpuframe.parallel import fusion, mesh as mesh_lib, step as step_lib
from tpuframe.parallel import zero1
from tpuframe.tune import db as tune_db


def _bucket_sizes(shapes_dtypes, threshold):
    leaves = [jnp.zeros(s, d) for s, d in shapes_dtypes]
    return [len(b) for b in fusion._bucketize(leaves, threshold)]


class TestBucketize:
    def test_packs_up_to_threshold(self):
        # 4 f32 leaves of 100 bytes → threshold 250 packs 2+2.
        shapes = [((25,), jnp.float32)] * 4
        assert _bucket_sizes(shapes, 250) == [2, 2]

    def test_zero_threshold_never_called_but_single_leaf_buckets(self):
        shapes = [((25,), jnp.float32)] * 3
        assert _bucket_sizes(shapes, 1) == [1, 1, 1]

    def test_dtype_boundary_splits_bucket(self):
        shapes = [((4,), jnp.float32), ((4,), jnp.bfloat16),
                  ((4,), jnp.bfloat16)]
        assert _bucket_sizes(shapes, 1 << 20) == [1, 2]

    def test_big_leaf_gets_own_bucket(self):
        shapes = [((4,), jnp.float32), ((1024,), jnp.float32),
                  ((4,), jnp.float32)]
        assert _bucket_sizes(shapes, 64) == [1, 1, 1]

    def test_census_accounts_every_leaf_and_byte(self):
        leaves = [jax.ShapeDtypeStruct((25,), jnp.float32)] * 4 + \
                 [jax.ShapeDtypeStruct((8,), jnp.bfloat16)]
        census = fusion.bucket_census(leaves, 250)
        assert census["n_leaves"] == 5
        assert sum(r["leaves"] for r in census["buckets"]) == 5
        assert census["total_bytes"] == 4 * 100 + 16
        assert census["total_bytes"] == \
            sum(r["bytes"] for r in census["buckets"])
        # dtype boundary respected even under a roomy threshold
        assert census["buckets"][-1]["dtype"] == "bfloat16"

    def test_census_nonpositive_threshold_is_per_leaf(self):
        leaves = [jax.ShapeDtypeStruct((25,), jnp.float32)] * 3
        assert fusion.bucket_census(leaves, 0)["n_buckets"] == 3


class TestFusedPsum:
    # step_lib._shard_map (not jax.shard_map): the wrapper serves the
    # jax-0.4.37 floor via jax.experimental.shard_map(check_rep=False).
    def test_matches_per_leaf_psum(self, mesh8):
        tree = {
            "a": jnp.arange(24, dtype=jnp.float32).reshape(2, 12),
            "b": jnp.ones((5,), jnp.float32) * 3,
            "c": jnp.full((3, 2), 2.0, jnp.bfloat16),
        }

        def body(x):
            fused = fusion.fused_psum(x, "data", threshold_bytes=1 << 20)
            plain = jax.tree.map(lambda l: lax.psum(l, "data"), x)
            return fused, plain

        fused, plain = jax.jit(step_lib._shard_map(
            body, mesh=mesh8, in_specs=P(), out_specs=P()))(tree)
        for k in tree:
            np.testing.assert_array_equal(np.asarray(fused[k]),
                                          np.asarray(plain[k]))

    def test_mean_divides_by_axis_size(self, mesh8):
        x = {"w": jnp.ones((4,), jnp.float32)}
        out = jax.jit(step_lib._shard_map(
            lambda t: fusion.fused_pmean(t, "data", threshold_bytes=0),
            mesh=mesh8, in_specs=P(), out_specs=P()))(x)
        np.testing.assert_allclose(np.asarray(out["w"]), np.ones(4))

    def test_staged_matches_sync_reference(self, mesh8):
        # The overlapped pass is the same math as the sync pack — the
        # psum-linearity identity the fusion gate leg also pins.
        tree = {
            "a": jnp.arange(24, dtype=jnp.float32).reshape(2, 12),
            "b": jnp.ones((70,), jnp.float32) * 3,
            "c": jnp.full((3, 2), 2.0, jnp.bfloat16),
        }

        def body(x):
            return (fusion.staged_psum(x, "data", threshold_bytes=128),
                    fusion.fused_psum(x, "data", threshold_bytes=128))

        staged, packed = jax.jit(step_lib._shard_map(
            body, mesh=mesh8, in_specs=P(), out_specs=P()))(tree)
        for k in tree:
            np.testing.assert_allclose(np.asarray(staged[k]),
                                       np.asarray(packed[k]),
                                       rtol=1e-6, atol=1e-6)


class TestScatterPacking:
    # The ZeRO-1 composition's shard-aligned packing: reduce-scatter
    # shard k of the packed buffer must equal the concatenation of each
    # leaf's own shard k, or the bucketed update would mix leaves.
    def test_pack_for_scatter_shard_alignment(self):
        n = 4
        flats = [jnp.arange(8, dtype=jnp.float32),
                 jnp.arange(100, 112, dtype=jnp.float32)]
        chunks = [f.size // n for f in flats]
        packed = fusion.pack_for_scatter(flats, n)
        assert packed.size == sum(f.size for f in flats)
        rows = packed.reshape(n, -1)
        for k in range(n):
            expect = jnp.concatenate([f.reshape(n, -1)[k] for f in flats])
            np.testing.assert_array_equal(np.asarray(rows[k]),
                                          np.asarray(expect))
        # split_scattered undoes one shard row into per-leaf shards
        parts = fusion.split_scattered(rows[1], chunks)
        for f, part in zip(flats, parts):
            np.testing.assert_array_equal(np.asarray(part),
                                          np.asarray(f.reshape(n, -1)[1]))
        # split_gathered undoes the full gathered buffer into full leaves
        full = fusion.split_gathered(packed, n, chunks)
        for f, got in zip(flats, full):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(f))


def _many_tensor_step(mesh, fusion_threshold, weight_update="replicated"):
    """A 12-leaf model (BERT-in-miniature: many small params)."""
    layers = [(jnp.zeros((16, 16), jnp.float32), jnp.zeros((16,), jnp.float32))
              for _ in range(6)]
    params = {f"l{i}": {"w": w, "b": b} for i, (w, b) in enumerate(layers)}
    tx = optax.sgd(0.1)

    def loss_fn(params, model_state, batch, rng):
        y = batch["x"]
        for i in range(6):
            y = jnp.tanh(y @ params[f"l{i}"]["w"] + params[f"l{i}"]["b"])
        return jnp.mean((y - batch["t"]) ** 2), ({}, {})

    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False,
                                    fusion_threshold=fusion_threshold,
                                    weight_update=weight_update)
    if weight_update == "zero1":
        state = zero1.make_state(params, tx, mesh)
    else:
        state = step_lib.TrainState.create(params, tx)
        if mesh is not None:
            state = step_lib.replicate_state(state, mesh)
    rng = np.random.default_rng(0)
    batch = {"x": rng.normal(size=(16, 16)).astype(np.float32),
             "t": rng.normal(size=(16, 16)).astype(np.float32)}
    if mesh is not None:
        batch = jax.tree.map(
            lambda a: jax.device_put(a, mesh_lib.batch_sharding(mesh)), batch)
    return step, state, batch


def _grad_leaf_structs():
    """ShapeDtypeStructs of _many_tensor_step's gradient leaves, in
    jax.tree.flatten order — what bucket_census predicts buckets from."""
    structs = []
    for _ in range(6):
        structs.append(jax.ShapeDtypeStruct((16,), jnp.float32))   # b
        structs.append(jax.ShapeDtypeStruct((16, 16), jnp.float32))  # w
    return structs


def _all_reduce_stats(step, state, batch):
    """(op count, total operand count, largest operand element count) over
    every all-reduce in the optimized HLO.  XLA merges adjacent same-group
    reductions into one *variadic* all-reduce op, so the program-level
    signature of fusion is the operand list, not the op count."""
    txt = step.lower(state, batch).compile().as_text()
    ops = 0
    operands = 0
    largest = 0
    for line in txt.splitlines():
        line = line.strip()
        m = re.search(r"=.*\ball-reduce(?:-start)?\((.*?)\)", line)
        if not m:
            continue
        ops += 1
        args = [a for a in m.group(1).split(",") if "." in a or "%" in a]
        operands += len(args)
        lhs = re.split(r"\ball-reduce(?:-start)?\(", line)[0]
        for shape in re.findall(r"(?:f32|bf16|f16)\[([\d,]*)\]", lhs):
            n = 1
            for d in filter(None, shape.split(",")):
                n *= int(d)
            largest = max(largest, n)
    return ops, operands, largest


def test_threshold_changes_compiled_hlo(mesh8):
    # threshold=0 (fusion off): one collective per gradient leaf — 12 grad
    # operands (+1 loss) ride the wire separately.  64 MB: all 12 f32 leaves
    # pack into ONE contiguous 1632-element buffer.  The compiled programs
    # must differ — VERDICT r2 #4's "all-reduce count/operand sizes".
    s0 = _all_reduce_stats(*_many_tensor_step(mesh8, 0))
    sN = _all_reduce_stats(*_many_tensor_step(mesh8, 64 << 20))
    assert s0[1] >= 13, f"per-leaf path: {s0}"
    assert sN[1] <= 4, f"fused path still ships {sN[1]} operands: {sN}"
    assert sN[2] >= 6 * (16 * 16 + 16), (
        f"no packed fusion buffer in HLO: {sN}")
    assert s0 != sN


def test_bucket_census_pins_all_reduce_count(mesh8):
    # The census is not advisory: at every threshold the compiled HLO
    # must carry exactly n_buckets gradient all-reduces (plus a constant
    # metric overhead independent of the threshold).  A scheduler change
    # that merges or fragments the staged buckets breaks this pin.
    structs = _grad_leaf_structs()
    offsets = set()
    counts = []
    for threshold in (256, 2048, 64 << 20):
        census = fusion.bucket_census(structs, threshold)
        ops, _, _ = _all_reduce_stats(*_many_tensor_step(mesh8, threshold))
        offsets.add(ops - census["n_buckets"])
        counts.append(census["n_buckets"])
    assert len(offsets) == 1, (
        f"gradient all-reduce count drifted from the census: "
        f"offsets {offsets} over buckets {counts}")
    assert counts[0] > counts[1] > counts[2], counts
    assert offsets.pop() >= 0


def test_implicit_path_is_grouped_per_leaf(mesh8):
    # fusion_threshold=None keeps the implicit pmean-of-loss program: the
    # autodiff transpose reduces each leaf, and XLA groups them into (a)
    # variadic all-reduce op(s) with one operand per leaf — fusion at the
    # scheduling level without the packing copy.  Pin the shape so a
    # regression that fragments or repacks the default program is caught.
    ops, operands, largest = _all_reduce_stats(*_many_tensor_step(mesh8, None))
    if step_lib._LEGACY_SHARD_MAP:
        # the 0.4.x lowering keeps one all-reduce per leaf instead of
        # the variadic grouping — still per-leaf, never repacked
        assert ops >= 13, f"legacy path repacked into {ops} ops"
    else:
        assert ops <= 2, f"default path fragmented into {ops} all-reduce ops"
    assert operands >= 13  # 12 grad leaves + loss, individually visible


def test_fusion_golden_loss(mesh8):
    # All three reduction programs are the same math.
    def losses(threshold):
        step, state, batch = _many_tensor_step(mesh8, threshold)
        out = []
        for _ in range(3):
            state, m = step(state, batch)
            out.append(float(m["loss"]))
        return out

    ref = losses(None)
    np.testing.assert_allclose(losses(0), ref, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(losses(64 << 20), ref, rtol=1e-6, atol=1e-7)
    assert ref[-1] < ref[0]


N_GOLDEN_STEPS = 50


@pytest.mark.parametrize("weight_update", ["replicated", "zero1"])
def test_staged_fusion_golden_loss_50_steps(mesh8, weight_update):
    # The staged overlapped pass (and its ZeRO-1 bucketed scatter/gather
    # composition) reproduces the unfused trajectory over a real run
    # length — the same 50-step bar the zero1 equivalence tests hold.
    def run(threshold):
        step, state, batch = _many_tensor_step(mesh8, threshold,
                                               weight_update=weight_update)
        out = []
        for _ in range(N_GOLDEN_STEPS):
            state, m = step(state, batch)
            out.append(float(m["loss"]))
        return out

    golden = run(None)
    fused = run(2048)  # several buckets: the staged path, genuinely staged
    np.testing.assert_allclose(fused, golden, rtol=1e-5, atol=1e-6)
    assert golden[-1] < golden[0], "training should make progress"


def test_registry_threshold_matches_strategies():
    # strategies.py duplicates the constant to stay jax-free at import;
    # the two must never drift.
    from tpuframe.analysis import strategies

    assert strategies._FUSED_REGISTRY_THRESHOLD == fusion.REGISTRY_THRESHOLD


def test_seeded_overlap_positive_and_static_check():
    # The live gate must fail the all-exposed declared_overlapped seed —
    # a gate that cannot see a wasted async window is blind.
    assert fusion.seeded_overlap_positive() == []
    assert fusion.check_static() == []


def test_env_knob_reaches_step_threshold(monkeypatch):
    from tpuframe.parallel import tuning

    assert fusion.ENV_VAR == tuning.ENV_KNOB
    monkeypatch.setenv(tuning.ENV_KNOB, str(32 << 20))
    assert tuning.step_threshold() == 32 << 20
    monkeypatch.delenv(tuning.ENV_KNOB)
    assert tuning.step_threshold() is None


# ----------------------------------------------------------------------
# resolution precedence: env > tune DB (generation-gated) > default
# ----------------------------------------------------------------------

class TestResolution:
    @pytest.fixture(autouse=True)
    def clean_env(self, monkeypatch):
        monkeypatch.delenv(fusion.ENV_VAR, raising=False)
        monkeypatch.delenv("TPUFRAME_TUNE_GEN", raising=False)
        monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
        monkeypatch.setenv("TPUFRAME_TUNE_DB", "off")

    def _seed_db(self, tmp_path, monkeypatch, value):
        path = str(tmp_path / "tune_db.json")
        db = tune_db.TuningDB(path)
        db.add({"program": "train_resnet50_b512",
                "family": "fusion_threshold",
                "fingerprint": "fp0", "topology": "v5e:2x2",
                "generation": "v5e",
                "config": {"fusion_threshold": value, "batch": 512},
                "predicted": {"predicted_ms": 5.0,
                              "overlap_potential": 1.0}})
        db.save()
        monkeypatch.setenv("TPUFRAME_TUNE_DB", path)

    def test_default_is_per_leaf_none(self):
        assert fusion.resolve() == (None, "default")
        assert fusion.resolve(default=131072) == (131072, "default")

    def test_env_override_wins(self, tmp_path, monkeypatch):
        self._seed_db(tmp_path, monkeypatch, 1 << 20)
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        monkeypatch.setenv(fusion.ENV_VAR, str(64 << 10))
        assert fusion.resolve(program="train_resnet50_b512") == \
            (64 << 10, "env")

    def test_env_bogus_value_raises(self, monkeypatch):
        monkeypatch.setenv(fusion.ENV_VAR, "lots")
        with pytest.raises(ValueError, match="TPUFRAME_FUSION_THRESHOLD"):
            fusion.resolve()

    def test_db_winner_engages_with_generation(self, tmp_path,
                                               monkeypatch):
        self._seed_db(tmp_path, monkeypatch, 1 << 20)
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        assert fusion.resolve(program="train_resnet50_b512") == \
            (1 << 20, "tune_db")
        # family fallback for a program the sweep never compiled verbatim
        assert fusion.resolve(program="train_resnet50_b1024",
                              family="fusion_threshold") == \
            (1 << 20, "tune_db")

    def test_no_generation_means_default(self, tmp_path, monkeypatch):
        # the tier-1 guarantee: CPU runs never see DB layout decisions
        self._seed_db(tmp_path, monkeypatch, 1 << 20)
        assert fusion.resolve(program="train_resnet50_b512") == \
            (None, "default")

    def test_stale_db_value_falls_back(self, tmp_path, monkeypatch):
        # a stale/bogus DB row must never break a run — silent demotion
        self._seed_db(tmp_path, monkeypatch, "not-an-int")
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        assert fusion.resolve(program="train_resnet50_b512") == \
            (None, "default")


def test_hvd_average_gradients_honors_fusion_knob(mesh8, monkeypatch):
    """The hvd facade's DistributedOptimizer routes through
    collectives.average_gradients; with TPUFRAME_FUSION_THRESHOLD set the
    varying leaves must reduce through the packed buffers with identical
    values to the per-leaf path."""
    from tpuframe.parallel import collectives, tuning

    tree = {
        "a": jnp.arange(24, dtype=jnp.float32).reshape(2, 12),
        "b": jnp.full((5,), 3.0, jnp.float32),
        "c": jnp.full((3, 2), 2.0, jnp.float32),
    }

    def body(x):
        # pvary (where this jax has it) so leaves are genuinely
        # per-replica — the hand-built-grads case in average_gradients'
        # contract.  The legacy shard_map wrapper runs check_rep=False,
        # where every leaf is already local/varying.
        if hasattr(lax, "pcast"):
            x = jax.tree.map(
                lambda l: lax.pcast(l, ("data",), to="varying"), x)
        return collectives.average_gradients(x, axis="data")

    monkeypatch.delenv(tuning.ENV_KNOB, raising=False)
    run = jax.jit(step_lib._shard_map(body, mesh=mesh8, in_specs=P(),
                                      out_specs=P()))
    ref = run(tree)  # knob unset: per-leaf pmean

    monkeypatch.setenv(tuning.ENV_KNOB, str(1 << 20))
    run2 = jax.jit(step_lib._shard_map(body, mesh=mesh8, in_specs=P(),
                                       out_specs=P()))
    got = run2(tree)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(ref[k]))
