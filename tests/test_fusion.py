"""Gradient-fusion buffers (tpuframe.parallel.fusion): the knob must
*demonstrably change the compiled program* — VERDICT r2 item #4.

The decisive assertions lower the SAME many-tensor train step at different
TPUFRAME_FUSION_THRESHOLD values and count ``all-reduce`` ops in the
optimized HLO: threshold 0 → one collective per gradient leaf (Horovod's
fusion-off semantics); a large threshold → the leaves ride a handful of
packed buffers.  The golden-loss test then proves the packing is
semantics-preserving against the default implicit pmean-of-loss path."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuframe.parallel import fusion, mesh as mesh_lib, step as step_lib


def _bucket_sizes(shapes_dtypes, threshold):
    leaves = [jnp.zeros(s, d) for s, d in shapes_dtypes]
    return [len(b) for b in fusion._bucketize(leaves, threshold)]


class TestBucketize:
    def test_packs_up_to_threshold(self):
        # 4 f32 leaves of 100 bytes → threshold 250 packs 2+2.
        shapes = [((25,), jnp.float32)] * 4
        assert _bucket_sizes(shapes, 250) == [2, 2]

    def test_zero_threshold_never_called_but_single_leaf_buckets(self):
        shapes = [((25,), jnp.float32)] * 3
        assert _bucket_sizes(shapes, 1) == [1, 1, 1]

    def test_dtype_boundary_splits_bucket(self):
        shapes = [((4,), jnp.float32), ((4,), jnp.bfloat16),
                  ((4,), jnp.bfloat16)]
        assert _bucket_sizes(shapes, 1 << 20) == [1, 2]

    def test_big_leaf_gets_own_bucket(self):
        shapes = [((4,), jnp.float32), ((1024,), jnp.float32),
                  ((4,), jnp.float32)]
        assert _bucket_sizes(shapes, 64) == [1, 1, 1]


class TestFusedPsum:
    def test_matches_per_leaf_psum(self, mesh8):
        tree = {
            "a": jnp.arange(24, dtype=jnp.float32).reshape(2, 12),
            "b": jnp.ones((5,), jnp.float32) * 3,
            "c": jnp.full((3, 2), 2.0, jnp.bfloat16),
        }

        def body(x):
            fused = fusion.fused_psum(x, "data", threshold_bytes=1 << 20)
            plain = jax.tree.map(lambda l: lax.psum(l, "data"), x)
            return fused, plain

        fused, plain = jax.jit(jax.shard_map(
            body, mesh=mesh8, in_specs=P(), out_specs=P()))(tree)
        for k in tree:
            np.testing.assert_array_equal(np.asarray(fused[k]),
                                          np.asarray(plain[k]))

    def test_mean_divides_by_axis_size(self, mesh8):
        x = {"w": jnp.ones((4,), jnp.float32)}
        out = jax.jit(jax.shard_map(
            lambda t: fusion.fused_pmean(t, "data", threshold_bytes=0),
            mesh=mesh8, in_specs=P(), out_specs=P()))(x)
        np.testing.assert_allclose(np.asarray(out["w"]), np.ones(4))


def _many_tensor_step(mesh, fusion_threshold):
    """A 12-leaf model (BERT-in-miniature: many small params)."""
    layers = [(jnp.zeros((16, 16), jnp.float32), jnp.zeros((16,), jnp.float32))
              for _ in range(6)]
    params = {f"l{i}": {"w": w, "b": b} for i, (w, b) in enumerate(layers)}
    tx = optax.sgd(0.1)

    def loss_fn(params, model_state, batch, rng):
        y = batch["x"]
        for i in range(6):
            y = jnp.tanh(y @ params[f"l{i}"]["w"] + params[f"l{i}"]["b"])
        return jnp.mean((y - batch["t"]) ** 2), ({}, {})

    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False,
                                    fusion_threshold=fusion_threshold)
    state = step_lib.TrainState.create(params, tx)
    if mesh is not None:
        state = step_lib.replicate_state(state, mesh)
    rng = np.random.default_rng(0)
    batch = {"x": rng.normal(size=(16, 16)).astype(np.float32),
             "t": rng.normal(size=(16, 16)).astype(np.float32)}
    if mesh is not None:
        batch = jax.tree.map(
            lambda a: jax.device_put(a, mesh_lib.batch_sharding(mesh)), batch)
    return step, state, batch


def _all_reduce_stats(step, state, batch):
    """(op count, total operand count, largest operand element count) over
    every all-reduce in the optimized HLO.  XLA merges adjacent same-group
    reductions into one *variadic* all-reduce op, so the program-level
    signature of fusion is the operand list, not the op count."""
    txt = step.lower(state, batch).compile().as_text()
    ops = 0
    operands = 0
    largest = 0
    for line in txt.splitlines():
        line = line.strip()
        m = re.search(r"=.*\ball-reduce(?:-start)?\((.*?)\)", line)
        if not m:
            continue
        ops += 1
        args = [a for a in m.group(1).split(",") if "." in a or "%" in a]
        operands += len(args)
        lhs = re.split(r"\ball-reduce(?:-start)?\(", line)[0]
        for shape in re.findall(r"(?:f32|bf16|f16)\[([\d,]*)\]", lhs):
            n = 1
            for d in filter(None, shape.split(",")):
                n *= int(d)
            largest = max(largest, n)
    return ops, operands, largest


def test_threshold_changes_compiled_hlo(mesh8):
    # threshold=0 (fusion off): one collective per gradient leaf — 12 grad
    # operands (+1 loss) ride the wire separately.  64 MB: all 12 f32 leaves
    # pack into ONE contiguous 1632-element buffer.  The compiled programs
    # must differ — VERDICT r2 #4's "all-reduce count/operand sizes".
    s0 = _all_reduce_stats(*_many_tensor_step(mesh8, 0))
    sN = _all_reduce_stats(*_many_tensor_step(mesh8, 64 << 20))
    assert s0[1] >= 13, f"per-leaf path: {s0}"
    assert sN[1] <= 4, f"fused path still ships {sN[1]} operands: {sN}"
    assert sN[2] >= 6 * (16 * 16 + 16), (
        f"no packed fusion buffer in HLO: {sN}")
    assert s0 != sN


def test_implicit_path_is_grouped_per_leaf(mesh8):
    # fusion_threshold=None keeps the implicit pmean-of-loss program: the
    # autodiff transpose reduces each leaf, and XLA groups them into (a)
    # variadic all-reduce op(s) with one operand per leaf — fusion at the
    # scheduling level without the packing copy.  Pin the shape so a
    # regression that fragments or repacks the default program is caught.
    ops, operands, largest = _all_reduce_stats(*_many_tensor_step(mesh8, None))
    assert ops <= 2, f"default path fragmented into {ops} all-reduce ops"
    assert operands >= 13  # 12 grad leaves + loss, individually visible


def test_fusion_golden_loss(mesh8):
    # All three reduction programs are the same math.
    def losses(threshold):
        step, state, batch = _many_tensor_step(mesh8, threshold)
        out = []
        for _ in range(3):
            state, m = step(state, batch)
            out.append(float(m["loss"]))
        return out

    ref = losses(None)
    np.testing.assert_allclose(losses(0), ref, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(losses(64 << 20), ref, rtol=1e-6, atol=1e-7)
    assert ref[-1] < ref[0]


def test_env_knob_reaches_step_threshold(monkeypatch):
    from tpuframe.parallel import tuning

    monkeypatch.setenv(tuning.ENV_KNOB, str(32 << 20))
    assert tuning.step_threshold() == 32 << 20
    monkeypatch.delenv(tuning.ENV_KNOB)
    assert tuning.step_threshold() is None


def test_hvd_average_gradients_honors_fusion_knob(mesh8, monkeypatch):
    """The hvd facade's DistributedOptimizer routes through
    collectives.average_gradients; with TPUFRAME_FUSION_THRESHOLD set the
    varying leaves must reduce through the packed buffers with identical
    values to the per-leaf path."""
    from tpuframe.parallel import collectives, tuning

    tree = {
        "a": jnp.arange(24, dtype=jnp.float32).reshape(2, 12),
        "b": jnp.full((5,), 3.0, jnp.float32),
        "c": jnp.full((3, 2), 2.0, jnp.float32),
    }

    def body(x):
        # pvary so leaves are genuinely per-replica (the hand-built-grads
        # case in average_gradients' contract).
        x = jax.tree.map(
            lambda l: lax.pcast(l, ("data",), to="varying"), x)
        return collectives.average_gradients(x, axis="data")

    monkeypatch.delenv(tuning.ENV_KNOB, raising=False)
    run = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P(),
                                out_specs=P()))
    ref = run(tree)  # knob unset: per-leaf pmean

    monkeypatch.setenv(tuning.ENV_KNOB, str(1 << 20))
    run2 = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P(),
                                 out_specs=P()))
    got = run2(tree)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(ref[k]))
