"""TFRecord + tf.Example codec (tpuframe/data/tfrecord.py) and the
prepare_imagenet TFRecord ingestion path.

No tensorflow in the image, so the oracle is the wire spec itself:
round-trips through the own encoder, hand-built proto bytes for the
unpacked encodings TF writers may emit, and CRC corruption detection.
The end-to-end test builds real JPEG TFRecord shards with PIL and runs
them through prepare_imagenet into the npy layout datasets.imagenet
consumes.
"""

import io
import struct

import numpy as np
import pytest

from tpuframe.data import tfrecord as tfr


class TestFraming:
    def test_roundtrip(self):
        recs = [b"hello", b"", b"x" * 1000]
        data = tfr.write_records(recs)
        assert list(tfr.iter_records(data)) == recs

    def test_data_crc_corruption_detected(self):
        data = bytearray(tfr.write_records([b"payload-bytes"]))
        data[14] ^= 0xFF  # flip a payload byte
        with pytest.raises(ValueError, match="data CRC"):
            list(tfr.iter_records(bytes(data)))

    def test_length_crc_corruption_detected(self):
        data = bytearray(tfr.write_records([b"payload"]))
        data[2] ^= 0x01  # corrupt the length field itself
        with pytest.raises(ValueError, match="CRC|truncated"):
            list(tfr.iter_records(bytes(data)))

    def test_truncation_detected(self):
        data = tfr.write_records([b"abcdef"])
        with pytest.raises(ValueError, match="truncated"):
            list(tfr.iter_records(data[:-2]))

    def test_known_masked_crc(self):
        # Framing must interoperate with TF's readers: the mask formula
        # is part of the spec. Check the mask transform itself.
        c = 0x12345678
        masked = (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF
        assert tfr._masked_crc(b"") != 0  # crc32c("")==0, mask shifts it
        assert masked == ((c >> 15 | c << 17) + 0xA282EAD8) % (1 << 32)


class TestExample:
    def test_roundtrip_all_types(self):
        ex = {
            "image/encoded": [b"\xff\xd8jpegbytes"],
            "image/class/label": np.asarray([42], np.int64),
            "scores": np.asarray([0.5, -1.25, 3e5], np.float32),
            "name": [b"n01440764_10026.JPEG"],
        }
        parsed = tfr.parse_example(tfr.build_example(ex))
        assert parsed["image/encoded"] == ex["image/encoded"]
        assert parsed["name"] == ex["name"]
        np.testing.assert_array_equal(parsed["image/class/label"],
                                      ex["image/class/label"])
        np.testing.assert_array_equal(parsed["scores"], ex["scores"])

    def test_negative_int64(self):
        ex = {"v": np.asarray([-1, -(2 ** 62)], np.int64)}
        parsed = tfr.parse_example(tfr.build_example(ex))
        np.testing.assert_array_equal(parsed["v"], ex["v"])

    def test_unpacked_numeric_encodings(self):
        # TF writers may emit unpacked repeated scalars; build by hand.
        # Feature{float_list{value: 1.5}} with UNPACKED fixed32 (field 1,
        # wire type 5):
        f32 = struct.pack("<I", struct.unpack("<I", struct.pack("<f", 1.5))[0])
        float_list = bytes([0o15]) + f32            # field 1, wt 5
        feature = tfr._ld(2, float_list)
        entry = tfr._ld(1, b"x") + tfr._ld(2, feature)
        example = tfr._ld(1, tfr._ld(1, entry))
        parsed = tfr.parse_example(example)
        np.testing.assert_allclose(parsed["x"], [1.5])
        # Int64List unpacked varint (field 1, wt 0):
        int_list = bytes([0o10]) + tfr._write_varint(7)
        feature = tfr._ld(3, int_list)
        entry = tfr._ld(1, b"y") + tfr._ld(2, feature)
        example = tfr._ld(1, tfr._ld(1, entry))
        np.testing.assert_array_equal(tfr.parse_example(example)["y"], [7])


def _jpeg_bytes(rng, size=40):
    from PIL import Image

    arr = rng.integers(0, 255, (size, size, 3), np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG")
    return buf.getvalue()


class TestPrepareFromTfrecords:
    def test_end_to_end_to_npy_and_loader(self, tmp_path):
        from tpuframe.data import prepare_imagenet as prep

        rng = np.random.default_rng(0)
        src = tmp_path / "tfr"
        src.mkdir()
        n = 10
        recs = [tfr.build_example({
            "image/encoded": [_jpeg_bytes(rng)],
            "image/class/label": np.asarray([i % 5], np.int64),
        }) for i in range(n)]
        (src / "train-00000-of-00002").write_bytes(
            tfr.write_records(recs[:6]))
        (src / "train-00001-of-00002").write_bytes(
            tfr.write_records(recs[6:]))

        out = tmp_path / "npy"
        shards = prep.prepare_tfrecords(str(src), str(out), image_size=32,
                                        shard_size=4)
        assert shards == 3  # 10 examples / 4 per shard
        imgs = np.load(out / "images_00000.npy")
        lbls = np.load(out / "labels_00000.npy")
        assert imgs.shape == (4, 32, 32, 3) and imgs.dtype == np.uint8
        np.testing.assert_array_equal(lbls, [0, 1, 2, 3])

        # the npy layout feeds datasets.imagenet unchanged
        from tpuframe.data import datasets

        train, test = datasets.imagenet(str(out), image_size=32,
                                        keep_u8=True)
        total = len(train.columns["label"]) + len(test.columns["label"])
        assert total == n
        assert train.columns["image"].dtype == np.uint8

    def test_missing_features_raise(self, tmp_path):
        from tpuframe.data import prepare_imagenet as prep

        src = tmp_path / "tfr"
        src.mkdir()
        (src / "bad.tfrecord").write_bytes(tfr.write_records(
            [tfr.build_example({"unrelated": [b"z"]})]))
        with pytest.raises(ValueError, match="image/encoded"):
            list(prep.iter_tfrecord_examples(str(src)))


def test_label_offset_maps_one_based_shards(tmp_path):
    from tpuframe.data import prepare_imagenet as prep

    rng = np.random.default_rng(1)
    src = tmp_path / "tfr"
    src.mkdir()
    recs = [tfr.build_example({
        "image/encoded": [_jpeg_bytes(rng)],
        "image/class/label": np.asarray([i + 1], np.int64),  # 1-based
    }) for i in range(4)]
    (src / "t.tfrecord").write_bytes(tfr.write_records(recs))
    got = [lbl for _, lbl in
           prep.iter_tfrecord_examples(str(src), label_offset=1)]
    assert got == [0, 1, 2, 3]
    # wrong offset on 0-based shards fails loudly
    recs0 = [tfr.build_example({
        "image/encoded": [_jpeg_bytes(rng)],
        "image/class/label": np.asarray([0], np.int64),
    })]
    (src / "t.tfrecord").write_bytes(tfr.write_records(recs0))
    with pytest.raises(ValueError, match="offset"):
        list(prep.iter_tfrecord_examples(str(src), label_offset=1))
