"""Fault injection + elastic resume + SPMD-divergence checks
(SURVEY.md §5.3: failure = job death + resume from checkpoint; §5.2)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from tpuframe.launch import LocalCluster
from tpuframe.obs import spmd_check


def _run_train(tmp_path, extra_env=None, total_steps=20):
    env = dict(os.environ)
    env.update({
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": env.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count=4",
    })
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", "tpuframe.train", "--config", "smoke",
         "--set", f"total_steps={total_steps}", "--set", "ckpt_every=5",
         "--set", "log_every=5", "--set", "eval_every=1000",
         "--set", "global_batch=16", "--ckpt-dir", str(tmp_path / "ck")],
        env=env, capture_output=True, text=True, timeout=500)


@pytest.mark.slow
def test_crash_and_resume(tmp_path):
    """Hard-kill (os._exit, no cleanup) at step 13; the restarted job must
    resume from the last committed checkpoint (step 10) and finish — the
    slice-restart recovery model (SURVEY.md §5.3)."""
    crashed = _run_train(tmp_path, {"TPUFRAME_FAULTS": "host:step=13:kind=crash"})
    assert crashed.returncode == 42, crashed.stderr[-1500:]
    assert "FAULT INJECTION" in crashed.stdout
    # checkpoints 5 and 10 committed; nothing at 13
    ck = tmp_path / "ck"
    committed = sorted(p.name for p in ck.iterdir() if p.is_dir())
    assert "step_00000010" in committed

    resumed = _run_train(tmp_path)
    assert resumed.returncode == 0, resumed.stderr[-1500:]
    assert "resumed from step 10" in resumed.stdout
    assert "[train 20]" in resumed.stdout


@pytest.mark.slow
def test_resumed_loss_matches_straight_run(tmp_path):
    straight = _run_train(tmp_path / "a")
    assert straight.returncode == 0, straight.stderr[-1500:]
    crashed = _run_train(tmp_path / "b", {"TPUFRAME_FAULTS": "host:step=13:kind=crash"})
    assert crashed.returncode == 42
    resumed = _run_train(tmp_path / "b")
    assert resumed.returncode == 0, resumed.stderr[-1500:]

    def final_loss(out):
        line = next(l for l in out.stdout.splitlines() if "[train 20]" in l)
        return float(line.split("loss=")[1].split()[0])

    np.testing.assert_allclose(final_loss(resumed), final_loss(straight),
                               rtol=1e-4)


def test_spmd_check_single_process_noop():
    spmd_check.assert_uniform_across_hosts("tag", b"anything")  # must not raise


def test_digest_stable():
    a = spmd_check.digest("payload")
    b = spmd_check.digest(b"payload")
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, spmd_check.digest("payload2"))


@pytest.mark.slow
def test_spmd_divergence_detected_across_hosts():
    """2-host cluster: uniform payload passes; a host-dependent payload is
    caught before any training collective would hang."""
    script = textwrap.dedent("""
        import jax
        from tpuframe.parallel import bootstrap
        from tpuframe.obs import spmd_check
        bootstrap.initialize()
        spmd_check.assert_uniform_across_hosts("ok", b"same-on-all-hosts")
        try:
            spmd_check.assert_uniform_across_hosts(
                "drift", f"host-{jax.process_index()}".encode())
        except RuntimeError as e:
            assert "divergence" in str(e)
            print("CAUGHT")
        else:
            raise SystemExit("divergence not detected")
    """)
    results = LocalCluster(2, 1, timeout=300).launch(
        [sys.executable, "-c", script])
    assert all("CAUGHT" in r.stdout for r in results)


@pytest.mark.slow
def test_stall_becomes_clean_abort(tmp_path):
    """Collective-timeout surfacing (SURVEY.md §5.3): wedge one rank mid-run;
    every rank's heartbeat watchdog must turn the resulting pod-wide stall
    into a clean exit-13 (not an indefinite hang), leaving the last committed
    checkpoint for auto-resume.  Rank 1 stalls in its host loop; rank 0
    stalls inside the collective waiting for it — both paths must abort."""
    with pytest.raises(RuntimeError) as excinfo:
        LocalCluster(
            2, 2, timeout=400,
            extra_env={
                "TPUFRAME_HANG_STEP": "3",        # only rank 1 hangs
                "TPUFRAME_HANG_RANK": "1",
                "TPUFRAME_STALL_TIMEOUT_S": "20",
            },
        ).launch([
            sys.executable, "-m", "tpuframe.train", "--config", "smoke",
            "--set", "total_steps=30", "--set", "log_every=5",
            "--set", "eval_every=1000", "--set", "global_batch=16",
            "--set", "ckpt_every=2", "--ckpt-dir", str(tmp_path / "ck"),
        ])
    msg = str(excinfo.value)
    assert "exit 13" in msg, msg
    assert "STALL" in msg, msg
    # a committed checkpoint exists for the restart to resume from
    committed = sorted(p.name for p in (tmp_path / "ck").iterdir()
                       if p.is_dir())
    assert any(n.startswith("step_") for n in committed), committed


@pytest.mark.slow
def test_spmd_check_enabled_in_harness():
    """TPUFRAME_CHECK_SPMD=1 through the real train.py on 2 hosts."""
    results = LocalCluster(
        2, 2, timeout=500,
        extra_env={"TPUFRAME_CHECK_SPMD": "1"},
    ).launch([
        sys.executable, "-m", "tpuframe.train", "--config", "smoke",
        "--set", "total_steps=4", "--set", "log_every=2",
        "--set", "eval_every=100", "--set", "global_batch=16",
    ])
    assert "done in" in results[0].stdout
