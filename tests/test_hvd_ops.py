"""Reduction-op surface parity: hvd.Min/Max/Product/Adasum + ProcessSet.

Horovod exposes a reduction-op enum on ``hvd.allreduce`` and subgroup
collectives via ``hvd.ProcessSet`` (SURVEY.md §3b op set; the Adasum op is
arXiv:2006.02924).  These tests pin the SPMD realizations on the 8-device
virtual CPU mesh:

  - Min/Max/Product against numpy reductions over the replica axis;
  - Adasum's butterfly against an independent numpy model of the same
    pairing tree, plus the op's two DEFINING properties — identical
    vectors -> identity (scale-insensitive), orthogonal vectors -> sum;
  - ProcessSet masked semantics: members get the subgroup result,
    non-members' tensors are untouched (Horovod's contract).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuframe.parallel import collectives, hvd


def _run8(body, x, mesh8, out_spec=P("data")):
    f = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                              out_specs=out_spec))
    return np.asarray(f(x))


def _adasum_pair(a, b):
    dot = float(np.dot(a.ravel(), b.ravel()))
    na = float(np.dot(a.ravel(), a.ravel()))
    nb = float(np.dot(b.ravel(), b.ravel()))
    ca = dot / (2 * na) if na > 0 else 0.0
    cb = dot / (2 * nb) if nb > 0 else 0.0
    return (1 - ca) * a + (1 - cb) * b


def _adasum_butterfly(rows):
    rows = [r.astype(np.float64) for r in rows]
    n = len(rows)
    k = 1
    while k < n:
        rows = [_adasum_pair(rows[i], rows[i ^ k]) for i in range(n)]
        k *= 2
    return rows[0]


class TestReduceOps:
    def test_min_max(self, mesh8):
        x = np.arange(16.0).reshape(8, 2)[np.random.default_rng(0).permutation(8)]

        def body(t):
            return (collectives.reduce_min(t, "data"),
                    collectives.reduce_max(t, "data"))

        f = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=(P(), P())))
        mn, mx = f(x)
        np.testing.assert_allclose(np.asarray(mn)[0], x.reshape(8, 1, 2).min(0)[0])
        np.testing.assert_allclose(np.asarray(mx)[0], x.reshape(8, 1, 2).max(0)[0])

    def test_product_with_zero_and_negative(self, mesh8):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((8, 3)).astype(np.float32)
        x[2, 1] = 0.0  # a log/exp formulation would break here
        x[5] *= -1.0
        out = _run8(lambda t: collectives.reduce_prod(t, "data"), x, mesh8, P())
        np.testing.assert_allclose(out[0], np.prod(x, axis=0), rtol=1e-5)

    def test_hvd_op_routing(self, mesh8):
        x = np.arange(8.0)

        def body(t):
            return (hvd.allreduce(t, op=hvd.Min), hvd.allreduce(t, op=hvd.Max),
                    hvd.allreduce(t, op=hvd.Sum), hvd.allreduce(t))

        f = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=(P(), P(), P(), P())))
        mn, mx, s, avg = (np.asarray(v) for v in f(x))
        assert mn[0] == 0.0 and mx[0] == 7.0 and s[0] == 28.0 and avg[0] == 3.5

    def test_average_and_op_conflict(self):
        with pytest.raises(ValueError, match="not both"):
            hvd.allreduce(jnp.ones(3), average=True, op=hvd.Sum)


class TestAdasum:
    def test_identical_vectors_are_identity(self, mesh8):
        # Scale-insensitivity: adasum(a, a) == a, so N identical replicas
        # reduce to the vector itself (NOT N*a) — the defining contrast
        # with Sum and the reason Adasum removes LR-by-size scaling.
        row = np.linspace(-2, 3, 6, dtype=np.float32)
        x = np.tile(row, (8, 1))
        out = _run8(lambda t: collectives.adasum(t, "data"), x, mesh8, P())
        np.testing.assert_allclose(out[0], row, rtol=1e-6)

    def test_orthogonal_vectors_sum(self, mesh8):
        # Each replica holds a distinct scaled basis vector: orthogonal at
        # every butterfly stage, so the result is the plain sum.
        scales = np.arange(1.0, 9.0, dtype=np.float32)
        x = np.diag(scales).astype(np.float32)
        out = _run8(lambda t: collectives.adasum(t, "data"), x, mesh8, P())
        np.testing.assert_allclose(out[0], x.sum(0), rtol=1e-6)

    def test_matches_numpy_butterfly(self, mesh8):
        rng = np.random.default_rng(7)
        x = rng.standard_normal((8, 4, 3)).astype(np.float32)
        out = _run8(lambda t: collectives.adasum(t, "data"), x, mesh8, P())
        ref = _adasum_butterfly([x[i] for i in range(8)])
        np.testing.assert_allclose(out[0], ref, rtol=1e-4, atol=1e-5)

    def test_zero_replica_contribution(self, mesh8):
        # One replica contributes a zero vector: the zero-norm guard must
        # not NaN, and adasum(0, b) == b at the pair level.
        rng = np.random.default_rng(8)
        x = rng.standard_normal((8, 5)).astype(np.float32)
        x[3] = 0.0
        out = _run8(lambda t: collectives.adasum(t, "data"), x, mesh8, P())
        assert np.isfinite(out).all()
        ref = _adasum_butterfly([x[i] for i in range(8)])
        np.testing.assert_allclose(out[0], ref, rtol=1e-4, atol=1e-5)

    def test_all_replicas_agree(self, mesh8):
        rng = np.random.default_rng(9)
        x = rng.standard_normal((8, 4)).astype(np.float32)
        out = _run8(lambda t: collectives.adasum(t, "data"), x, mesh8,
                    P("data"))
        for i in range(1, 8):
            np.testing.assert_allclose(out[i], out[0], rtol=1e-6)

    def test_distributed_optimizer_adasum(self, mesh8):
        # op=Adasum routes grads through the butterfly: with per-replica
        # orthogonal grads the applied update is the SUM of contributions.
        import optax

        x = np.diag(np.arange(1.0, 9.0)).astype(np.float32)
        tx = hvd.DistributedOptimizer(optax.sgd(1.0), op=hvd.Adasum,
                                      axis="data")

        def body(g):
            params = jnp.zeros((8,), jnp.float32)
            state = tx.init(params)
            updates, _ = tx.update(g, state, params)
            return updates

        out = _run8(body, x, mesh8, P())
        np.testing.assert_allclose(out[0], -x.sum(0), rtol=1e-5)

    def test_adasum_rejects_compression(self):
        import optax

        with pytest.raises(ValueError, match="compression"):
            hvd.DistributedOptimizer(optax.sgd(1.0), op=hvd.Adasum,
                                     compression="bf16")

    def test_presummed_leaves_degrade_to_sum(self, mesh8):
        # Grads of replicated params arrive already psum'd (vma-unvarying).
        # The documented contract: adasum passes them through unchanged
        # (i.e. the value IS the cross-replica sum) instead of crashing in
        # ppermute's vma check.
        x = np.arange(8.0, dtype=np.float32)

        def body(t):
            presummed = jax.lax.psum(t, "data")
            return collectives.adasum(presummed, "data")

        out = _run8(body, x, mesh8, P())
        assert out[0] == pytest.approx(28.0)


class TestAdasumStep:
    """grad_reduce='adasum' through the real step builder."""

    def _setup(self):
        import optax

        from tpuframe.parallel import step as step_lib

        def loss_fn(params, model_state, batch, rng):
            pred = batch["x"] @ params["w"]
            return jnp.mean((pred - batch["y"]) ** 2), (model_state, {})

        rng = np.random.default_rng(3)
        w = {"w": jnp.asarray(rng.standard_normal((6, 2)), jnp.float32)}
        tx = optax.sgd(0.1)
        state = step_lib.TrainState.create(w, tx)
        return step_lib, loss_fn, tx, state, rng

    def test_identical_shards_match_single_device(self, mesh8):
        # Adasum of identical per-replica grads is the IDENTITY, so feeding
        # every replica the same batch must reproduce the unmapped step
        # exactly — the end-to-end form of the scale-insensitivity property.
        step_lib, loss_fn, tx, state, rng = self._setup()
        xb = rng.standard_normal((4, 6)).astype(np.float32)
        yb = (xb @ np.ones((6, 2))).astype(np.float32)

        ada_step = step_lib.make_train_step(loss_fn, tx, mesh8, donate=False,
                                            grad_reduce="adasum")
        big = {"x": jnp.asarray(np.tile(xb, (8, 1))),
               "y": jnp.asarray(np.tile(yb, (8, 1)))}
        new_ada, m_ada = ada_step(state, big)

        solo_step = step_lib.make_train_step(loss_fn, tx, None, donate=False)
        new_solo, m_solo = solo_step(state, {"x": jnp.asarray(xb),
                                             "y": jnp.asarray(yb)})
        np.testing.assert_allclose(np.asarray(new_ada.params["w"]),
                                   np.asarray(new_solo.params["w"]),
                                   rtol=2e-6, atol=1e-7)
        assert float(m_ada["loss"]) == pytest.approx(float(m_solo["loss"]),
                                                     rel=1e-5)

    def test_composes_with_accum(self, mesh8):
        step_lib, loss_fn, tx, state, rng = self._setup()
        x = rng.standard_normal((32, 6)).astype(np.float32)
        y = rng.standard_normal((32, 2)).astype(np.float32)
        step = step_lib.make_train_step(loss_fn, tx, mesh8, donate=False,
                                        grad_reduce="adasum", accum_steps=2)
        new_state, metrics = step(state, {"x": jnp.asarray(x),
                                          "y": jnp.asarray(y)})
        assert np.isfinite(np.asarray(new_state.params["w"])).all()
        assert np.isfinite(float(metrics["loss"]))

    def test_rejects_fusion_threshold(self, mesh8):
        step_lib, loss_fn, tx, state, rng = self._setup()
        with pytest.raises(ValueError, match="adasum"):
            step_lib.make_train_step(loss_fn, tx, mesh8,
                                     grad_reduce="adasum",
                                     fusion_threshold=1 << 20)

    def test_rejects_unknown_reduce(self, mesh8):
        step_lib, loss_fn, tx, state, rng = self._setup()
        with pytest.raises(ValueError, match="grad_reduce"):
            step_lib.make_train_step(loss_fn, tx, mesh8, grad_reduce="nope")


class TestAsyncAndIntrospection:
    def test_async_handle_roundtrip(self, mesh8):
        # Port-compat pair: handle = allreduce_async_, synchronize(handle).
        x = np.arange(8.0, dtype=np.float32)

        def body(t):
            h = hvd.allreduce_async_(t, op=hvd.Sum)
            return hvd.synchronize(h)

        out = _run8(body, x, mesh8, P())
        assert out[0] == 28.0

    def test_synchronize_outside_jit_blocks(self):
        import jax.numpy as jnp

        v = hvd.synchronize(jnp.arange(4.0) * 2)
        np.testing.assert_allclose(np.asarray(v), [0, 2, 4, 6])

    def test_compression_namespace_maps_to_string_knob(self):
        # Horovod scripts pass hvd.Compression.fp16 — must be accepted
        # verbatim by DistributedOptimizer.
        import optax

        tx = hvd.DistributedOptimizer(optax.sgd(0.1),
                                      compression=hvd.Compression.fp16)
        assert tx is not None
        assert hvd.Compression.none is None

    def test_build_introspection_is_honest(self):
        # The reference genre queries these to pick env knobs; on TPU none
        # of the legacy transports exist.
        assert not hvd.mpi_built() and not hvd.nccl_built()
        assert not hvd.gloo_built() and not hvd.cuda_built()
        assert not hvd.rocm_built() and not hvd.mpi_enabled()


class TestUnitAxisMesh:
    """The single-device 'config 1' mode: a bound size-1 axis must come back
    vma-replicated from every op so out_specs=P() still compiles."""

    @pytest.fixture
    def mesh1(self):
        from jax.sharding import Mesh

        return Mesh(np.asarray(jax.devices()[:1]), ("data",))

    def test_ops_clear_unit_axis(self, mesh1):
        ps = hvd.ProcessSet([0])

        def body(t):
            return (collectives.allreduce(t, "data"),
                    collectives.broadcast(t, "data"),
                    collectives.reduce_min(t, "data"),
                    collectives.reduce_prod(t, "data"),
                    collectives.adasum(t, "data"),
                    hvd.allreduce(t, process_set=ps, axis="data"))

        f = jax.jit(jax.shard_map(body, mesh=mesh1, in_specs=P("data"),
                                  out_specs=tuple([P()] * 6)))
        outs = f(np.arange(4.0, dtype=np.float32))
        for o in outs:
            np.testing.assert_allclose(np.asarray(o), np.arange(4.0))


class TestProcessSet:
    def test_members_reduced_others_untouched(self, mesh8):
        ps = hvd.ProcessSet([1, 3, 5])
        x = np.arange(8.0, dtype=np.float32)
        out = _run8(lambda t: hvd.allreduce(t, process_set=ps), x, mesh8)
        want_mean = (1 + 3 + 5) / 3.0
        for r in range(8):
            expect = want_mean if r in (1, 3, 5) else float(r)
            assert out[r] == pytest.approx(expect), r

    def test_sum_op(self, mesh8):
        ps = hvd.ProcessSet([0, 7])
        x = np.arange(8.0, dtype=np.float32)
        out = _run8(lambda t: hvd.allreduce(t, op=hvd.Sum, process_set=ps),
                    x, mesh8)
        assert out[0] == 7.0 and out[7] == 7.0
        for r in range(1, 7):
            assert out[r] == float(r)

    def test_broadcast_to_subset(self, mesh8):
        ps = hvd.ProcessSet([2, 4, 6])
        x = np.arange(8.0, dtype=np.float32)
        out = _run8(
            lambda t: hvd.broadcast_parameters(t, root_rank=4, process_set=ps),
            x, mesh8)
        for r in range(8):
            expect = 4.0 if r in (2, 4, 6) else float(r)
            assert out[r] == pytest.approx(expect), r

    def test_broadcast_root_must_be_member(self, mesh8):
        ps = hvd.ProcessSet([2, 4])

        def body(t):
            return hvd.broadcast_parameters(t, root_rank=0, process_set=ps)

        with pytest.raises(ValueError, match="not a member"):
            jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=P("data")))(np.arange(8.0))

    def test_validation(self):
        with pytest.raises(ValueError):
            hvd.ProcessSet([])
        with pytest.raises(ValueError):
            hvd.ProcessSet([-1, 2])
        assert hvd.ProcessSet([3, 1, 3, 2]).ranks == (1, 2, 3)

    def test_negative_rank_raises_at_collectives_level(self, mesh8):
        # hvd.ProcessSet rejects negatives itself; the public collectives
        # API must too, else the mean divisor silently over-counts.
        def body(t):
            return collectives.masked_allreduce(t, "data", [-1, 0, 1])

        with pytest.raises(ValueError, match="out of range"):
            jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=P("data")))(np.arange(8.0))

    def test_broadcast_root_out_of_range_raises(self, mesh8):
        # An unmatched root would psum to zeros on every replica —
        # silent parameter corruption.
        def body(t):
            return collectives.broadcast(t, "data", root=8)

        with pytest.raises(ValueError, match="out of range"):
            jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=P()))(np.arange(8.0))

    def test_distributed_optimizer_average_op_conflict(self):
        import optax

        with pytest.raises(ValueError, match="not both"):
            hvd.DistributedOptimizer(optax.sgd(1.0), average=False,
                                     op=hvd.Average)

    def test_pp_rejects_adasum(self):
        from tpuframe import train as train_lib
        from tpuframe.utils import config as config_lib

        cfg = config_lib.get_config("lm_pp_smoke").with_overrides(
            grad_reduce="adasum")
        with pytest.raises(ValueError, match="grad_reduce"):
            train_lib.build_harness(cfg)

    def test_out_of_range_rank_raises(self, mesh8):
        # Rank 8 on an 8-replica axis never matches any index; without the
        # trace-time check the mean divisor would silently be wrong.
        ps = hvd.ProcessSet([0, 1, 8])

        def body(t):
            return hvd.allreduce(t, process_set=ps)

        with pytest.raises(ValueError, match="out of range"):
            jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=P("data")))(np.arange(8.0))
