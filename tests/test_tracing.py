"""The request-tracing plane (obs/tracing.py) and the SLO sentry
(obs/slo.py): span emission + the open-span registry, deterministic
sampling, cross-process trace reconstruction (router -> replica ->
scheduler), hedge/redispatch span semantics, the verify_traces
completeness contract with its seeded positives, the TF123 emission-seam
lint, and the multi-window burn-rate rc contract.  The subprocess chaos
tiers assert the same invariants at fleet scale in tests/test_chaos.py.
"""

import json
import threading
import time

import pytest

from tpuframe.obs import events as obs_events
from tpuframe.obs import goodput, slo, tracing
from tpuframe.resilience.policy import RetryPolicy
from tpuframe.serve.router import Router


def _no_sleep_policy(**kw):
    kw.setdefault("max_attempts", 2)
    kw.setdefault("base_delay_s", 0.001)
    kw.setdefault("max_delay_s", 0.001)
    kw.setdefault("attempt_timeout_s", 5.0)
    kw.setdefault("deadline_s", 10.0)
    return RetryPolicy(sleep=lambda s: None, **kw)


def _drive(router, *, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while router.has_work() and time.monotonic() < deadline:
        router.step()
        time.sleep(0.002)
    assert not router.has_work(), "router did not converge"


def _ok_reply(url, payload, timeout_s):
    if url.endswith("/generate"):
        return 200, {"rid": payload["rid"], "tokens": [1, 2],
                     "ttft_ms": 1.0}
    if url.endswith("/healthz"):
        return 200, "ok\n"
    return 200, "tpuframe_serve_queue_depth 0\n# EOF\n"


# ---------------------------------------------------------------------------
# Span API: emission, registry, sampling.
# ---------------------------------------------------------------------------

class TestSpanAPI:
    def test_span_events_schema_registered_and_valid(self, tmp_path):
        obs_events.init(str(tmp_path))
        try:
            tid = tracing.mint(0, force=True)
            sid = tracing.open_span(tid, "request", rid=0)
            tracing.note(tid, "requeue", span=sid, replica="r0")
            tracing.close_span(tid, sid, 12.5, status="ok")
            tracing.span(tid, "queue", parent=sid, ms=3.0)
        finally:
            obs_events.close()
        files = obs_events.event_files(str(tmp_path))
        assert obs_events.validate_files(files) == []  # schema-clean
        merged = obs_events.merge(str(tmp_path))
        types = [e["type"] for e in merged]
        assert types.count("span_open") == 2
        assert types.count("span_close") == 2
        assert types.count("span_note") == 1

    def test_open_span_registry_and_metrics_gauge(self):
        from tpuframe.obs import exporter

        base = tracing.open_span_count()
        tid = tracing.mint("gauge-test", force=True)
        sid = tracing.open_span(tid, "request")
        try:
            assert tracing.open_span_count() == base + 1
            assert (tid, sid, "request") in tracing.open_spans()
            text = exporter.MetricsExporter().render()
            assert f"tpuframe_open_spans {base + 1}\n" in text
        finally:
            tracing.close_span(tid, sid, 1.0)
        assert tracing.open_span_count() == base

    def test_atomic_span_pairs_bypass_the_registry(self):
        base = tracing.open_span_count()
        tracing.span("tx.0", "queue", ms=1.0)
        assert tracing.open_span_count() == base

    def test_sampling_knob_deterministic(self, monkeypatch):
        monkeypatch.setenv(tracing.ENV_SAMPLE, "0.5")
        assert tracing.resolve_sample() == 0.5
        picks = [tracing.sampled(rid) for rid in range(200)]
        assert picks == [tracing.sampled(rid) for rid in range(200)]
        assert 20 < sum(picks) < 180        # actually samples, not all/none
        monkeypatch.setenv(tracing.ENV_SAMPLE, "0")
        assert tracing.mint(7) is None                   # sampled out
        assert tracing.mint(7, force=True) is not None   # rollouts bypass
        monkeypatch.setenv(tracing.ENV_SAMPLE, "junk")
        assert tracing.resolve_sample() == 1.0
        monkeypatch.setenv(tracing.ENV_SAMPLE, "7")
        assert tracing.resolve_sample() == 1.0           # clamped

    def test_sampled_out_request_is_untraced_but_served(self, monkeypatch):
        monkeypatch.setenv(tracing.ENV_SAMPLE, "0")
        r = Router(["http://a"], transport=_ok_reply, hedge_ms=0,
                   scrape_interval_s=1e9)
        r.submit(0, [1])
        _drive(r)
        assert r.completed[0].trace is None
        assert r.counters["completed"] == 1


# ---------------------------------------------------------------------------
# Reconstruction + the verify_traces contract.
# ---------------------------------------------------------------------------

class TestReconstruction:
    def test_healthy_synthetic_roundtrip(self):
        evs = tracing._synthetic_trace()
        assert tracing.verify_traces(evs) == []
        traces = tracing.build_traces(evs)
        (tv,) = traces.values()
        (root,) = tv.complete_roots()
        assert root.name == "request" and root.ms == 62.0
        path = [sp.name for sp in tracing.critical_path(root)]
        assert path == ["request", "attempt", "serve", "decode"]
        rows = tracing.waterfall(root)
        assert [r["span"].name for r in rows] == [
            "request", "attempt", "serve", "queue", "prefill", "decode"]
        assert [r["depth"] for r in rows] == [0, 1, 2, 3, 3, 3]

    def test_seeded_leaked_span_is_loud(self):
        evs = [r for r in tracing._synthetic_trace()
               if not (r["type"] == "span_close"
                       and r.get("span") == "s1")]
        kinds = {p["kind"] for p in tracing.verify_traces(evs)}
        assert "leaked_span" in kinds
        # ...and through the anomaly sweep (obs anomalies integration).
        finds = goodput.find_anomalies(evs)
        assert any(f["kind"] == "leaked_span" for f in finds)

    def test_seeded_orphan_and_missing_root(self):
        healthy = tracing._synthetic_trace()
        orphaned = [dict(r, parent="zz")
                    if r["type"] == "span_open" and r.get("span") == "s1"
                    else r for r in healthy]
        kinds = {p["kind"] for p in tracing.verify_traces(orphaned)}
        assert "orphan_span" in kinds
        no_spans = [r for r in healthy
                    if r["type"] not in tracing.SPAN_EVENT_TYPES]
        kinds = {p["kind"] for p in tracing.verify_traces(no_spans)}
        assert "missing_root" in kinds
        unclosed = [r for r in healthy
                    if not (r["type"] == "span_close"
                            and r.get("span") == "r0")]
        kinds = {p["kind"] for p in tracing.verify_traces(unclosed)}
        assert "incomplete_root" in kinds

    def test_ttft_mismatch_tolerance(self):
        healthy = tracing._synthetic_trace()
        drifted = [dict(r, ttft_ms=67.0)
                   if r["type"] == "span_close" and r.get("span") == "r0"
                   else r for r in healthy]
        kinds = {p["kind"] for p in tracing.verify_traces(drifted)}
        assert "ttft_mismatch" in kinds
        # within tolerance: rounding drift is not an incident
        nudged = [dict(r, ttft_ms=19.0)
                  if r["type"] == "span_close" and r.get("span") == "r0"
                  else r for r in healthy]
        assert tracing.verify_traces(nudged, tol_ms=5.0) == []

    def test_training_only_logs_skip_span_sweep(self):
        # No span events: find_anomalies must not import/flag anything.
        evs = [{"schema": obs_events.SCHEMA_VERSION, "type": "train_step",
                "t": 1.0, "host": "h", "proc": 0, "attempt": 0,
                "step": 1, "loss": 2.0, "step_ms": 3.0}]
        assert all(f["kind"] not in ("leaked_span", "orphan_span")
                   for f in goodput.find_anomalies(evs))


# ---------------------------------------------------------------------------
# The cross-process join: router -> replica -> scheduler, in-process.
# ---------------------------------------------------------------------------

class TestRouterReplicaJoin:
    def test_trace_joins_router_to_scheduler(self, tmp_path):
        """The satellite-1 identity pin: one rid, one trace id, minted at
        Router.submit and visible verbatim on router_admit,
        router_request AND the replica's serve_request — with the span
        tree stitched across the /generate payload and every phase
        accounted (verify_traces clean, exactly one complete root per
        admitted rid)."""
        from tpuframe.serve.replica import FakeEngine, Replica

        obs_events.init(str(tmp_path))
        try:
            replica = Replica(FakeEngine(slots=2),
                              handler_timeout_s=10.0)
            pump = threading.Thread(
                target=replica.run, kwargs=dict(max_idle_s=30.0),
                daemon=True)
            pump.start()

            def transport(url, payload, timeout_s):
                if url.endswith("/generate"):
                    status, body = replica.handle_generate(
                        json.dumps(payload).encode())
                    return status, json.loads(body.decode())
                if url.endswith("/healthz"):
                    return 200, "ok\n"
                return 200, "tpuframe_serve_queue_depth 0\n# EOF\n"

            r = Router(["http://r0"], transport=transport, hedge_ms=0,
                       scrape_interval_s=1e9)
            for rid in range(4):
                assert r.submit(rid, [rid + 1, 2, 3], max_new_tokens=3)
            _drive(r)
            replica.drain()
            pump.join(10.0)
            assert not pump.is_alive()
        finally:
            obs_events.close()

        merged = obs_events.merge(str(tmp_path))
        admits = {e["id"]: e["trace"] for e in merged
                  if e["type"] == "router_admit"}
        served = {e["id"]: e["trace"] for e in merged
                  if e["type"] == "serve_request"}
        routed = {e["id"]: e["trace"] for e in merged
                  if e["type"] == "router_request"}
        assert set(admits) == set(served) == set(routed) == {0, 1, 2, 3}
        assert admits == served == routed       # ONE identity end to end

        assert tracing.verify_traces(merged) == []
        traces = tracing.build_traces(merged)
        for rid, tid in admits.items():
            roots = traces[tid].complete_roots()
            assert len(roots) == 1, f"rid {rid}: {len(roots)} roots"
            names = {sp.name for sp in traces[tid].spans.values()}
            assert {"request", "attempt", "serve", "queue", "prefill",
                    "decode"} <= names

        # Percentile exemplars resolve to reconstructed traces.
        fleet = goodput.fleet_stats(merged)
        ex = fleet["ttft_exemplars"]
        for q in ("p50", "p90", "p99"):
            assert ex[q]["trace"] in traces
        assert tracing.trace_of(merged, 0) == admits[0]


# ---------------------------------------------------------------------------
# Hedge-race and redispatch span semantics.
# ---------------------------------------------------------------------------

class TestAttemptSpans:
    def test_hedge_loser_closes_duplicate_under_same_trace(self, tmp_path):
        release = threading.Event()

        def transport(url, payload, timeout_s):
            if url.endswith("/generate") and "//a" in url:
                release.wait(5.0)
                return 200, {"rid": payload["rid"], "tokens": [9],
                             "ttft_ms": 99.0}
            return _ok_reply(url, payload, timeout_s)

        obs_events.init(str(tmp_path))
        try:
            r = Router(["http://a", "http://b"], transport=transport,
                       hedge_ms=30.0, scrape_interval_s=1e9)
            r.submit(0, [1])
            _drive(r)
            release.set()
            deadline = time.monotonic() + 5.0
            while (r.counters["duplicates"] < 1
                   and time.monotonic() < deadline):
                r.step()
                time.sleep(0.002)
            assert r.counters["duplicates"] == 1
        finally:
            obs_events.close()

        merged = obs_events.merge(str(tmp_path))
        traces = tracing.build_traces(merged)
        assert len(traces) == 1
        (tv,) = traces.values()
        (root,) = tv.complete_roots()
        attempts = [sp for sp in root.children if sp.name == "attempt"]
        assert len(attempts) == 2           # sibling subtrees, one root
        assert {a.opened["cause"] for a in attempts} == {"first", "hedge"}
        winner = [a for a in attempts
                  if not a.closed.get("duplicate")]
        loser = [a for a in attempts if a.closed.get("duplicate")]
        assert len(winner) == 1 and len(loser) == 1
        assert winner[0].closed["status"] == "ok"
        assert loser[0].opened["cause"] == "first"  # straggler lost
        assert tracing.verify_traces(merged) == []  # loser span closed

    def test_redispatch_after_drain_same_root(self, tmp_path):
        def transport(url, payload, timeout_s):
            if "//a" in url and url.endswith("/generate"):
                raise OSError("connection refused")
            return _ok_reply(url, payload, timeout_s)

        obs_events.init(str(tmp_path))
        try:
            r = Router(["http://a", "http://b"], transport=transport,
                       hedge_ms=0, scrape_interval_s=1e9,
                       dispatch_policy=_no_sleep_policy())
            r.submit(0, [1])
            _drive(r)
            assert r.summary()["redispatched"] == 1
        finally:
            obs_events.close()

        merged = obs_events.merge(str(tmp_path))
        traces = tracing.build_traces(merged)
        (tv,) = traces.values()
        (root,) = tv.complete_roots()
        attempts = {sp.opened["cause"]: sp for sp in root.children
                    if sp.name == "attempt"}
        assert set(attempts) == {"first", "redispatch"}
        assert attempts["first"].closed["status"] == "error"
        assert attempts["redispatch"].closed["status"] == "ok"
        notes = {n["note"] for n in tv.notes}
        assert notes & {"requeue", "drain_requeue"}
        assert tracing.verify_traces(merged) == []


# ---------------------------------------------------------------------------
# SLO sentry.
# ---------------------------------------------------------------------------

def _req(t, ttft):
    return {"schema": obs_events.SCHEMA_VERSION, "type": "router_request",
            "t": t, "host": "h-p90", "proc": 0, "attempt": 0,
            "id": 0, "replica": "r0", "ttft_ms": ttft}


class TestSLO:
    def test_spec_grammar_roundtrip(self):
        (s,) = slo.parse_slos("ttft<=800ms@99%")
        assert (s.metric, s.threshold_ms, s.objective) == \
            ("ttft", 800.0, 0.99)
        assert str(s) == "ttft<=800ms@99%"
        both = slo.parse_slos(slo.DEFAULT_SLO)
        assert [b.metric for b in both] == ["ttft", "tpot"]
        assert slo.parse_windows("60:14.4,300:6") == [(60.0, 14.4),
                                                      (300.0, 6.0)]

    @pytest.mark.parametrize("bad", [
        "ttft<800ms@99%", "latency<=1ms@99%", "ttft<=1ms@0%",
        "ttft<=1ms@100%", "", "ttft<=1ms"])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises(ValueError):
            slo.parse_slos(bad)

    def test_rc_contract(self):
        specs = slo.parse_slos("ttft<=100ms@90%")
        windows = [(60.0, 1.0)]
        clean = [_req(0.1 * i, 10.0) for i in range(30)]
        out = slo.evaluate(clean, specs, windows)
        assert out["rc"] == 0
        assert out["slos"][0]["breached"] is False
        assert out["slos"][0]["windows"][0]["n"] == 30  # full window shown
        slow = [_req(0.1 * i, 500.0) for i in range(30)]
        out = slo.evaluate(slow, specs, windows)
        assert out["rc"] == 1
        assert out["slos"][0]["windows"][0]["burn"] == pytest.approx(10.0)
        assert slo.evaluate([], specs, windows)["rc"] == 2

    def test_short_spike_long_window_policy(self):
        """The multi-window point: one spike trips a tight long-window
        factor while the tolerant short-window factor absorbs it."""
        specs = slo.parse_slos("ttft<=100ms@99%")
        evs = [_req(1.0 * i, 500.0 if i == 7 else 10.0)
               for i in range(100)]
        # short window, generous factor: the spike is 1/2 samples in a
        # 1s window -> burn 50 > 14.4 would breach; pick factor above it
        out = slo.evaluate(evs, specs, [(1.0, 60.0)])
        assert out["rc"] == 0
        out = slo.evaluate(evs, specs, [(99.0, 1.0)])
        assert out["rc"] == 1               # sustained view: budget blown

    def test_env_resolution(self, monkeypatch):
        monkeypatch.setenv(slo.ENV_SLO, "ttft<=5ms@50%")
        monkeypatch.setenv(slo.ENV_WINDOWS, "10:2")
        assert [str(s) for s in slo.resolve_slos()] == ["ttft<=5ms@50%"]
        assert slo.resolve_windows() == [(10.0, 2.0)]
        monkeypatch.delenv(slo.ENV_SLO)
        assert [str(s) for s in slo.resolve_slos()] == \
            [str(s) for s in slo.parse_slos(slo.DEFAULT_SLO)]


# ---------------------------------------------------------------------------
# Gate: the TF123 seam lint, the clock pin, check() itself.
# ---------------------------------------------------------------------------

class TestGate:
    def test_tf123_span_seam_lint(self):
        from tpuframe.analysis.source_lint import lint_source

        bad = ("from tpuframe.obs import events\n"
               "def f(tr):\n"
               "    events.emit('span_open', trace=tr, span='s1', "
               "name='x')\n")
        rules = [f.rule for f in
                 lint_source(bad, "tpuframe/serve/foo.py")]
        assert rules == ["TF123"]
        ok = bad.replace("name='x')",
                         "name='x')  # tf-lint: ok[TF123]")
        assert lint_source(ok, "tpuframe/serve/foo.py") == []
        # The seam itself is exempt; non-span types unaffected.
        assert lint_source(bad, "tpuframe/obs/tracing.py") == []
        other = bad.replace("'span_open'", "'router_admit'")
        assert all(f.rule != "TF123" for f in
                   lint_source(other, "tpuframe/serve/foo.py"))

    def test_scheduler_default_clock_is_monotonic(self):
        """Satellite 6: router wait_ms and scheduler queue/prefill spans
        subtract against the SAME clock family, so the phase sum can be
        asserted against the queue-inclusive TTFT."""
        from tpuframe.serve.replica import FakeEngine
        from tpuframe.serve.scheduler import Scheduler

        assert Scheduler(FakeEngine(slots=1))._clock is time.monotonic
        assert Router(["http://a"], transport=_ok_reply)._clock \
            is time.monotonic

    def test_trace_check_is_clean(self):
        assert tracing.check() == []

    def test_cli_trace_id_positional_paste_back(self, capsys):
        """The summary's exemplar rows print bare trace ids; `obs trace
        <dir> <tid>` must accept one pasted straight back (positional,
        not just --trace), and an unknown id is rc 2."""
        import pathlib

        from tpuframe.obs.__main__ import _load, main

        d = str(pathlib.Path(tracing.__file__).resolve()
                .parents[2] / "docs" / "samples" / "traced_fleet")
        tid = next(iter(tracing.build_traces(_load(d))))
        assert main(["trace", d, tid]) == 0
        out = capsys.readouterr().out
        assert f"trace {tid}:" in out and "critical path:" in out
        assert main(["trace", d, "tNOPE.0000"]) == 2
