"""The hierarchical-collective seam end to end: resolution precedence
(env > tuning DB > default, stale rows demote silently) for both the
hier mode and the per-fabric wire legs, the TF124 slice-axis seam lint,
fabric attribution of the compiled two-level lowering (in-slice groups
on ICI, cross-slice groups on DCN), byte-exact derived-budget pins of
the 1/n_inner DCN law, golden-loss parity of hier vs flat for both
weight-update modes, the compose-rejection matrix, the MegaScale
host-transfer DCN parser, and the compare differ's DCN regression rule.

Numerics use the legacy ``jax.experimental.shard_map`` idiom
(``check_rep=False``) so the suite runs on pre-vma jax too.
"""

import re

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpuframe.analysis import collective_graph as cg
from tpuframe.analysis import hlo_audit, shardflow, source_lint
from tpuframe.parallel import hier, quantwire, step as step_lib, zero1
from tpuframe.tune import db as tune_db


@pytest.fixture(scope="module")
def smesh():
    """4-way data x 2-slice mesh on the 8 virtual CPU devices — the
    smallest world where the two-level lowering has both fabrics."""
    from tpuframe.parallel import mesh as mesh_lib

    assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"
    return mesh_lib.make_mesh(mesh_lib.MeshSpec(data=4, slices=2))


# ---------------------------------------------------------------------------
# Resolution precedence: env > tune_db > default, per knob and per leg.
# ---------------------------------------------------------------------------


def _hier_rec(program="train_lm_b8", gen="v5e", mode="hier",
              fmt_dcn="int8-block"):
    return {"program": program, "family": "hier_collectives",
            "fingerprint": "fp0", "topology": "v5e:2x2",
            "generation": gen,
            "config": {"hier": mode, "wire_format_dcn": fmt_dcn,
                       "batch": 8, "weight_update": "replicated",
                       "slices": 2},
            "predicted": {"predicted_ms": 1.0, "bound": "hbm",
                          "fits": True, "vmem_bytes": 0,
                          "bytes_lower_bound": True}}


@pytest.fixture
def hier_db(tmp_path, monkeypatch):
    """A tuning DB with one swept hier/int8-dcn winner, wired into the
    env the way the resolution chain reads it; the generation gate is
    left CLOSED (no gen env) — tests open it explicitly."""
    path = str(tmp_path / "tune_db.json")
    db = tune_db.TuningDB(path)
    db.add(_hier_rec())
    db.save()
    monkeypatch.setenv("TPUFRAME_TUNE_DB", path)
    monkeypatch.delenv("TPUFRAME_HIER", raising=False)
    monkeypatch.delenv("TPUFRAME_WIRE_FORMAT", raising=False)
    monkeypatch.delenv("TPUFRAME_WIRE_FORMAT_DCN", raising=False)
    monkeypatch.delenv("TPUFRAME_TUNE_GEN", raising=False)
    monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
    return path


class TestResolution:
    def test_default_is_flat(self, hier_db):
        # DB exists but the generation gate is closed -> hard default.
        assert hier.resolve("train_lm_b8", "hier_collectives") \
            == ("flat", "default")

    def test_db_elected_when_generation_matches(self, hier_db,
                                                monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        assert hier.resolve("train_lm_b8", "hier_collectives") \
            == ("hier", "tune_db")
        # family fallback: unknown program, known family
        assert hier.resolve("train_other_b4", "hier_collectives") \
            == ("hier", "tune_db")

    def test_generation_gate(self, hier_db, monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v4")
        assert hier.resolve("train_lm_b8", "hier_collectives") \
            == ("flat", "default")

    def test_env_beats_db(self, hier_db, monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        monkeypatch.setenv(hier.ENV_VAR, "flat")
        assert hier.resolve("train_lm_b8", "hier_collectives") \
            == ("flat", "env")

    def test_env_invalid_raises(self, monkeypatch):
        monkeypatch.setenv(hier.ENV_VAR, "diagonal")
        with pytest.raises(ValueError, match="diagonal"):
            hier.resolve()

    def test_stale_db_row_demotes_silently(self, tmp_path, monkeypatch):
        # A DB written by a future/older tpuframe may elect a mode this
        # build doesn't know.  That must fall back to flat, not raise.
        path = str(tmp_path / "tune_db.json")
        db = tune_db.TuningDB(path)
        db.add(_hier_rec(mode="diagonal"))
        db.save()
        monkeypatch.setenv("TPUFRAME_TUNE_DB", path)
        monkeypatch.delenv("TPUFRAME_HIER", raising=False)
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
        assert hier.resolve("train_lm_b8", "hier_collectives") \
            == ("flat", "default")

    def test_dcn_leg_resolves_from_hier_family(self, hier_db,
                                               monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        ici, dcn = quantwire.resolve_legs(
            "train_lm_b8", family_dcn="hier_collectives")
        assert ici == ("fp", "default")
        assert dcn == ("int8-block", "tune_db")

    def test_dcn_env_beats_db(self, hier_db, monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        monkeypatch.setenv("TPUFRAME_WIRE_FORMAT_DCN", "fp")
        _ici, dcn = quantwire.resolve_legs(
            "train_lm_b8", family_dcn="hier_collectives")
        assert dcn == ("fp", "env")

    def test_self_check_clean(self, monkeypatch):
        monkeypatch.delenv(hier.ENV_VAR, raising=False)
        assert hier.check() == []


# ---------------------------------------------------------------------------
# TF124: collectives naming the slice (DCN) axis outside the seam.
# ---------------------------------------------------------------------------

_SEAM_PATH = "tpuframe/parallel/hier.py"
_RAW_SRC = ("from jax import lax\n"
            "\n"
            "def _mean(x):\n"
            "    return lax.pmean(x, ('slice', 'data'))\n")


class TestTF124:
    def test_flags_slice_collective_outside_seam(self):
        found = [f for f in source_lint.lint_source(
            _RAW_SRC, "tpuframe/parallel/zero1.py")
            if f.rule == "TF124"]
        assert found and "slice" in found[0].message

    def test_seam_module_is_exempt(self):
        findings = source_lint.lint_source(_RAW_SRC, _SEAM_PATH)
        assert not [f for f in findings if f.rule == "TF124"]

    def test_computed_axes_are_out_of_scope(self):
        # The seam's callers hand computed axis tuples down — only the
        # bare "slice" literal marks hand-routed DCN traffic.
        src = ("from jax import lax\n"
               "\n"
               "def _mean(x, axes):\n"
               "    return lax.pmean(x, axes)\n")
        findings = source_lint.lint_source(
            src, "tpuframe/parallel/step.py")
        assert not [f for f in findings if f.rule == "TF124"]

    def test_suppression_on_the_call_line(self):
        src = ("from jax import lax\n"
               "\n"
               "def _mean(x):\n"
               "    return lax.pmean(x, 'slice')"
               "  # tf-lint: ok[TF124] probe\n")
        findings = source_lint.lint_source(
            src, "tpuframe/parallel/step.py")
        assert not [f for f in findings if f.rule == "TF124"]

    def test_real_caller_files_are_clean(self):
        import tpuframe.parallel as pp
        root = pp.__path__[0]
        findings = source_lint.lint_paths(
            [f"{root}/step.py", f"{root}/zero1.py",
             f"{root}/collectives.py"])
        assert not [f for f in findings if f.rule == "TF124"], findings


# ---------------------------------------------------------------------------
# Derived budgets: the 1/n_inner DCN law, pinned byte-exact.
# ---------------------------------------------------------------------------


def test_derived_budget_hier_dcn_law():
    """The checked-in derived budgets must show the two-level shape
    exactly: the in-slice reduce-scatter and all-gather carry the full
    gradient payload, the cross-slice all-reduce carries payload /
    n_inner (n_inner = 4 on the 2-slice 8-device mesh), and the
    int8-block DCN leg carries payload / (4 * n_inner)."""
    flat = shardflow.derived_for("spec:dp=*;slices=2")
    h = shardflow.derived_for("spec:dp=*;slices=2+hier")
    if flat is None or h is None:
        pytest.skip("derived budgets not emitted for this jax")
    rs = h["above_floor"].get("reduce-scatter", 0)
    ag = h["above_floor"].get("all-gather", 0)
    ar = h["above_floor"].get("all-reduce", 0)
    assert rs > 0 and rs == ag, h["above_floor"]
    assert ar * 4 == rs, (ar, rs)  # the 1/n_inner law, byte-exact
    # ...and the cross-slice leg is under half the flat program's
    # whole gradient all-reduce (the DCN-ratio acceptance bound).
    flat_ar = flat["kinds"]["all-reduce"]["bytes"]
    assert 2 * ar <= flat_ar, (ar, flat_ar)

    h8 = shardflow.derived_for("spec:dp=*;slices=2+hier+dcn-int8")
    if h8 is not None:
        a2a = h8["above_floor"].get("all-to-all", 0)
        assert a2a > 0 and a2a * 16 == rs, (a2a, rs)


def test_derived_budget_zero1_hier_dcn_law():
    z = shardflow.derived_for("spec:dp=*;slices=2+zero1")
    z8 = shardflow.derived_for("spec:dp=*;slices=2+zero1+hier+dcn-int8")
    if z is None or z8 is None:
        pytest.skip("derived budgets not emitted for this jax")
    rs = z["above_floor"].get("reduce-scatter", 0)
    a2a = z8["above_floor"].get("all-to-all", 0)
    # zero1's scatter already pays the full payload once in-slice; the
    # quantized cross-slice exchange moves 1/16 of it.
    assert rs > 0 and a2a > 0 and a2a * 16 == rs, (a2a, rs)


# ---------------------------------------------------------------------------
# Compiled fabric attribution: two-level groups land on the right wires.
# ---------------------------------------------------------------------------


def _make_loss():
    def loss_fn(params, model_state, batch, rng_):
        x, y = batch
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        pred = h @ params["w2"] + params["b2"]
        return jnp.mean((pred - y) ** 2), (model_state, {})
    return loss_fn


def _init_params(key):
    # w1 is sized so its cross-slice shard (size / n_inner = 2048
    # elems) clears quantwire's MIN_QUANT_ELEMS floor — smaller leaves
    # ride the DCN leg in fp by design.
    k1, k2 = jax.random.split(key)
    return {"w1": jax.random.normal(k1, (64, 128)) * 0.1,
            "b1": jnp.zeros((128,)),
            "w2": jax.random.normal(k2, (128, 8)) * 0.1,
            "b2": jnp.zeros((8,))}


def _lower_hlo(mesh, hier_mode, fmt_dcn="fp", weight_update="replicated"):
    import optax

    tx = optax.sgd(0.05)
    params = _init_params(jax.random.key(1))
    if weight_update == "zero1":
        state = zero1.make_state(params, tx, mesh)
    else:
        state = step_lib.TrainState.create(params, tx)
        state = step_lib.replicate_state(state, mesh)
    train = step_lib.make_train_step(_make_loss(), tx, mesh,
                                     weight_update=weight_update,
                                     hier=hier_mode,
                                     wire_format_dcn=fmt_dcn,
                                     donate=False)
    x = jnp.zeros((64, 64))
    y = jnp.zeros((64, 8))
    return train.lower(state, (x, y)).compile().as_text()


def _split(hlo, floor=1024):
    coll = hlo_audit.parse_collectives(hlo)
    return shardflow.comm_split(cg.parse_graph(hlo), coll.filter(floor),
                                mesh_shape={"slice": 2, "data": 4},
                                n_devices=8)


class TestCompiledFabricSplit:
    def test_flat_crosses_slices_everywhere(self, smesh):
        split = _split(_lower_hlo(smesh, "flat"))
        assert split["dcn_bytes"] > 0
        assert split["ici_bytes"] == 0, split["ici"]

    def test_hier_moves_the_bulk_onto_ici(self, smesh):
        flat = _split(_lower_hlo(smesh, "flat"))
        h = _split(_lower_hlo(smesh, "hier"))
        assert h["ici_bytes"] > 0, h
        assert 2 * h["dcn_bytes"] <= flat["dcn_bytes"], (h, flat)

    def test_int8_dcn_leg_cuts_deeper(self, smesh):
        h = _split(_lower_hlo(smesh, "hier"))
        h8 = _split(_lower_hlo(smesh, "hier", fmt_dcn="int8-block"))
        assert h8["dcn_bytes"] < h["dcn_bytes"], (h8, h)

    def test_two_level_replica_groups_materialize(self, smesh):
        # slice-major device order: in-slice groups are the contiguous
        # quads, cross-slice groups the stride-4 pairs.
        hlo = _lower_hlo(smesh, "hier")
        assert re.search(r"replica_groups=\{\{0,1,2,3\},\{4,5,6,7\}\}",
                         hlo), "in-slice (ICI) groups missing"
        assert re.search(r"replica_groups=\{\{0,4\},\{1,5\},\{2,6\},"
                         r"\{3,7\}\}", hlo), \
            "cross-slice (DCN) groups missing"


# ---------------------------------------------------------------------------
# Golden loss: the two-level mean must track the flat mean exactly, and
# the int8 DCN leg within the quantized-wire acceptance bound.
# ---------------------------------------------------------------------------


def _run(mesh, hier_mode, fmt_dcn="fp", weight_update="replicated",
         steps=25):
    import optax

    tx = optax.sgd(0.05, momentum=0.9)
    params = _init_params(jax.random.key(1))
    if weight_update == "zero1":
        state = zero1.make_state(params, tx, mesh)
    else:
        state = step_lib.TrainState.create(params, tx)
        state = step_lib.replicate_state(state, mesh)
    train = step_lib.make_train_step(_make_loss(), tx, mesh,
                                     weight_update=weight_update,
                                     hier=hier_mode,
                                     wire_format_dcn=fmt_dcn,
                                     donate=False)
    key = jax.random.key(2)
    w_true = jax.random.normal(jax.random.key(7), (64, 8))
    losses = []
    for _ in range(steps):
        key, k1 = jax.random.split(key)
        x = jax.random.normal(k1, (64, 64))
        y = jnp.sin(x @ w_true)
        state, metrics = train(state, (x, y))
        losses.append(float(metrics["loss"]))
    return np.array(losses)


@pytest.mark.parametrize("weight_update", ["replicated", "zero1"])
def test_golden_loss_hier_matches_flat(smesh, weight_update):
    """The fp two-level mean is the flat mean re-associated — per-step
    loss parity to float-reassociation noise (observed ~1e-7)."""
    l_flat = _run(smesh, "flat", weight_update=weight_update)
    l_hier = _run(smesh, "hier", weight_update=weight_update)
    assert l_hier[-1] < l_flat[0], "hier run did not train"
    d = np.abs(l_hier - l_flat)
    assert d.max() <= 1e-4, (weight_update, d.max())


@pytest.mark.parametrize("weight_update", ["replicated", "zero1"])
def test_golden_loss_int8_dcn_tracks_flat(smesh, weight_update):
    """int8 on the DCN leg only: the documented quantized-wire bound
    (per-step |loss| delta <= 2e-3), same as the program-wide int8 wire
    it borrows its quantizer from."""
    l_flat = _run(smesh, "flat", weight_update=weight_update)
    l_q = _run(smesh, "hier", fmt_dcn="int8-block",
               weight_update=weight_update)
    assert l_q[-1] < l_flat[0], "int8-dcn run did not train"
    d = np.abs(l_q - l_flat)
    assert d.max() <= 2e-3, (weight_update, d.max())


# ---------------------------------------------------------------------------
# Compose rejections: the matrix is an API contract, not advice.
# ---------------------------------------------------------------------------


class TestComposeRejections:
    def test_hier_needs_shard_map(self, smesh):
        import optax

        with pytest.raises(ValueError, match="shard_map"):
            step_lib.make_train_step(_make_loss(), optax.sgd(0.1), smesh,
                                     mode="jit", hier="hier")

    def test_hier_rejects_adasum(self, smesh):
        import optax

        with pytest.raises(ValueError, match="adasum"):
            step_lib.make_train_step(_make_loss(), optax.sgd(0.1), smesh,
                                     grad_reduce="adasum", hier="hier")

    def test_hier_rejects_program_wide_int8(self, smesh):
        import optax

        with pytest.raises(ValueError, match="wire_format_dcn"):
            step_lib.make_train_step(_make_loss(), optax.sgd(0.1), smesh,
                                     wire_format="int8-block",
                                     hier="hier")

    def test_dcn_wire_needs_hier(self, smesh):
        import optax

        with pytest.raises(ValueError, match="hier"):
            step_lib.make_train_step(_make_loss(), optax.sgd(0.1), smesh,
                                     wire_format_dcn="int8-block")

    def test_dcn_wire_rejects_fusion(self, smesh):
        import optax

        with pytest.raises(ValueError, match="fusion_threshold"):
            step_lib.make_train_step(_make_loss(), optax.sgd(0.1), smesh,
                                     hier="hier",
                                     wire_format_dcn="int8-block",
                                     fusion_threshold=65536)


# ---------------------------------------------------------------------------
# MegaScale host-transfer parser: the DCN bytes HLO hides from the
# collective census on the compile-only multi-slice backend.
# ---------------------------------------------------------------------------

_MS_ATTRS = ('frontend_attributes={_xla_host_transfer_handler_name='
             '"xla_megascale_runtime",_xla_host_transfer_rendezvous='
             '"all-reduce.73_3"}')
_MS_SEND = ('  %send.1 = (f32[1025,8,128]{2,1,0}, u32[], token[]) '
            'send(%x, %tok), channel_id=5, is_host_transfer=true, '
            + _MS_ATTRS)
_MS_SEND_S8 = ('  %send.2 = (s8[4096]{0}, u32[], token[]) '
               'send(%q, %tok), channel_id=6, is_host_transfer=true, '
               + _MS_ATTRS)


class TestMegascaleSplit:
    def test_counts_payload_bytes_by_kind(self):
        out = shardflow.megascale_split("\n".join([_MS_SEND,
                                                   _MS_SEND_S8]))
        assert out == {"all-reduce": 1025 * 8 * 128 * 4 + 4096}

    def test_ignores_non_megascale_transfers(self):
        plain = ('  %send.3 = (f32[64]{0}, u32[], token[]) '
                 'send(%x, %tok), channel_id=7, is_host_transfer=true, '
                 'frontend_attributes={_xla_host_transfer_rendezvous='
                 '"infeed"}')
        assert shardflow.megascale_split(plain) == {}

    def test_ignores_recv_and_send_done(self):
        others = ('  %recv.1 = (f32[64]{0}, u32[], token[]) '
                  'recv(%tok), is_host_transfer=true, ' + _MS_ATTRS
                  + '\n  %send-done.1 = token[] send-done(%send.1), '
                    'is_host_transfer=true, ' + _MS_ATTRS)
        assert shardflow.megascale_split(others) == {}

    def test_empty_on_cpu_hlo(self, smesh):
        # Folding megascale bytes into the DCN column must be a no-op
        # where XLA emits real collectives.
        assert shardflow.megascale_split(_lower_hlo(smesh, "hier")) == {}


# ---------------------------------------------------------------------------
# The compare differ's DCN rule: growth flags, the crush direction never.
# ---------------------------------------------------------------------------


def _report(dcn_bytes=None):
    strat = {"name": "dp", "status": "ok", "violations": [],
             "derived": {"ignore_below": 1024, "kinds": {},
                         "above_floor": {}, "total_bytes": 0},
             "detectors": {}}
    if dcn_bytes is not None:
        strat["comm_split"] = {"slices": 2, "dcn_bytes": int(dcn_bytes),
                               "ici_bytes": 0}
    return {"strategies": [strat]}


class TestCompareDcnRule:
    def test_growth_is_a_regression(self):
        rc, lines = shardflow.compare_reports(_report(100000),
                                              _report(120001))
        assert rc == 1 and any("DCN bytes" in ln for ln in lines)

    def test_newly_crossing_slices_is_a_regression(self):
        rc, lines = shardflow.compare_reports(_report(0), _report(4096))
        assert rc == 1
        assert any("newly cross slices" in ln for ln in lines)

    def test_crush_direction_is_never_flagged(self):
        rc, lines = shardflow.compare_reports(_report(296196),
                                              _report(73728))
        assert rc == 0, lines

    def test_section_gated_on_both_reports(self):
        rc, _lines = shardflow.compare_reports(_report(None),
                                               _report(4096))
        assert rc == 0
