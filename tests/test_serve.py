"""tpuframe.serve: KV-cache engine, continuous batching, and its gates.

Covers the PR's contracts end to end on the 8-device virtual CPU mesh:

  - golden-logits parity: prefill-then-decode == the training forward,
    position by position, for every prompt bucket (full + ragged)
  - kv_cache shape-bucket invariants and env > DB > default resolution
  - scheduler admit/retire semantics over a fake engine (fast) and the
    loadgen loop over the real AOT engine
  - persistent compile-cache warm restarts for the serving executables
    (miss on first build, hits after jax.clear_caches())
  - TF109: no jit/.apply above the engine seam (positive + negative)
  - zero-collective HLO audit of plain-DP serving decode
  - decode roofline census: compiled cost_analysis bytes bracketed by
    the analytic model (the tune sweep's scoring basis)
  - obs: serve_* event schema + TTFT/TPOT/tokens-per-sec analytics
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuframe.models.transformer_lm import LMConfig, TransformerLM
from tpuframe.serve import kv_cache as kv
from tpuframe.serve.scheduler import Request, Scheduler

TINY = LMConfig.tiny()


def _decode_compiled(cfg, slots, capacity, donate=True):
    """AOT-compile the decode step the way the engine does (helper for
    the census tests — no full engine build needed)."""
    from tpuframe.serve import engine as engine_lib

    spec = kv.spec_for_model(cfg, slots=slots, capacity=capacity)
    decode_fn = engine_lib.make_decode_fn(TransformerLM(cfg))
    variables = jax.eval_shape(TransformerLM(cfg).init, jax.random.key(0),
                               jax.ShapeDtypeStruct((1, 8), jnp.int32))
    sds = jax.ShapeDtypeStruct
    p_sds = jax.tree.map(lambda s: sds(s.shape, s.dtype),
                         variables["params"])
    dtype = jnp.dtype(spec.dtype)
    cache_sds = tuple((sds(spec.layer_shape(), dtype),
                       sds(spec.layer_shape(), dtype))
                      for _ in range(cfg.num_layers))
    jitted = jax.jit(decode_fn, donate_argnums=(1, 2, 3) if donate else ())
    compiled = jitted.lower(p_sds, sds((slots, 1), jnp.int32),
                            sds((slots,), jnp.int32), cache_sds).compile()
    param_bytes = sum(
        int(np.prod(s.shape)) * jnp.dtype(s.dtype).itemsize
        for s in jax.tree_util.tree_leaves(variables["params"]))
    return compiled, spec, param_bytes


# ---------------------------------------------------------------------------
# kv_cache: shape buckets + spec invariants
# ---------------------------------------------------------------------------

class TestKVCache:
    def test_spec_shapes_and_bytes(self):
        spec = kv.spec_for_model(TINY, slots=4, capacity=64)
        assert spec.layer_shape() == (4, 64, TINY.num_heads, TINY.head_dim)
        # K + V, all layers, f32
        assert spec.bytes_per_token() == \
            2 * TINY.num_layers * TINY.num_heads * TINY.head_dim * 4
        assert spec.total_bytes() == 4 * 64 * spec.bytes_per_token()

    def test_spec_rejects_unaligned_capacity(self):
        with pytest.raises(ValueError, match="multiple of"):
            kv.spec_for_model(TINY, slots=4, capacity=65)

    def test_init_cache(self):
        spec = kv.spec_for_model(TINY, slots=2, capacity=16)
        layers, lengths = kv.init_cache(spec)
        assert len(layers) == TINY.num_layers
        assert layers[0][0].shape == spec.layer_shape()
        assert lengths.shape == (2,) and int(lengths.sum()) == 0

    def test_bucket_for(self):
        assert kv.bucket_for(1, (16, 32)) == 16
        assert kv.bucket_for(16, (16, 32)) == 16
        assert kv.bucket_for(17, (16, 32)) == 32
        with pytest.raises(ValueError, match="admission"):
            kv.bucket_for(33, (16, 32))

    def test_capacity_for_rounds_to_block(self):
        assert kv.capacity_for(1, 16) == 16
        assert kv.capacity_for(16, 16) == 16
        assert kv.capacity_for(17, 16) == 32

    def test_parse_buckets(self):
        assert kv.parse_buckets("64,128, 256") == (64, 128, 256)
        assert kv.parse_buckets("256;64") == (64, 256)
        with pytest.raises(ValueError):
            kv.parse_buckets("12")

    def test_check_buckets(self):
        assert kv.check_buckets((16, 32), 32) == []
        assert kv.check_buckets((32, 16), 32)      # unsorted
        assert kv.check_buckets((16, 64), 32)      # bucket > capacity

    def test_resolution_env_beats_db_and_default(self, monkeypatch):
        monkeypatch.delenv("TPUFRAME_TUNE_GEN", raising=False)
        monkeypatch.delenv("TPUFRAME_SERVE_BUCKETS", raising=False)
        monkeypatch.delenv("TPUFRAME_DECODE_BLOCK", raising=False)
        assert kv.resolve_buckets() == kv.DEFAULT_PROMPT_BUCKETS
        assert kv.resolve_decode_block() == kv.DEFAULT_DECODE_BLOCK
        monkeypatch.setenv("TPUFRAME_SERVE_BUCKETS", "32,96")
        monkeypatch.setenv("TPUFRAME_DECODE_BLOCK", "32")
        assert kv.resolve_buckets() == (32, 96)
        assert kv.resolve_decode_block() == 32

    def test_resolution_db_tier_under_generation(self, monkeypatch,
                                                 tmp_path):
        db_path = tmp_path / "db.json"
        db_path.write_text(json.dumps({
            "version": 1, "records": [{
                "program": "serve_decode_test", "family": "serve_lm",
                "fingerprint": "ab" * 16, "topology": "v5e:2x2",
                "generation": "v5e",
                "config": {"decode_block": 64,
                           "prompt_buckets": [64, 256], "slots": 8},
                "predicted": {"predicted_ms": 0.05}}]}))
        monkeypatch.setenv("TPUFRAME_TUNE_DB", str(db_path))
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        monkeypatch.delenv("TPUFRAME_SERVE_BUCKETS", raising=False)
        monkeypatch.delenv("TPUFRAME_DECODE_BLOCK", raising=False)
        assert kv.resolve_decode_block() == 64
        assert kv.resolve_buckets() == (64, 256)
        # plain run (no generation): DB must NOT engage
        monkeypatch.delenv("TPUFRAME_TUNE_GEN", raising=False)
        assert kv.resolve_decode_block() == kv.DEFAULT_DECODE_BLOCK


# ---------------------------------------------------------------------------
# Golden-logits parity — the tentpole's correctness contract.
# ---------------------------------------------------------------------------

class TestGoldenParity:
    def test_parity_every_bucket(self):
        from tpuframe.serve.engine import golden_parity_check

        buckets = (16, 32)
        capacity = kv.capacity_for(max(buckets) + 4, 16)
        problems = golden_parity_check(TINY, buckets=buckets,
                                       capacity=capacity, decode_tokens=4)
        assert problems == []

    def test_parity_detects_capacity_overrun(self):
        from tpuframe.serve.engine import golden_parity_check

        problems = golden_parity_check(TINY, buckets=(32,), capacity=32,
                                       decode_tokens=4)
        assert any("exceeds capacity" in p for p in problems)

    def test_ring_wraparound_is_sliding_window(self):
        """Past capacity the ring overwrites the oldest entries: lengths
        keep counting, valid clamps at capacity, and decode still runs
        (numerics = sliding-window attention, not a fault)."""
        cfg = TINY
        capacity = 8
        model = TransformerLM(cfg)
        ids = jax.random.randint(jax.random.key(0), (1, 14), 0,
                                 cfg.vocab_size)
        params = model.init(jax.random.key(1),
                            jnp.zeros((1, 8), jnp.int32))["params"]
        shape = (1, capacity, cfg.num_heads, cfg.head_dim)
        layers = tuple((jnp.zeros(shape), jnp.zeros(shape))
                       for _ in range(cfg.num_layers))
        _, layers = model.apply({"params": params}, ids[:, :8],
                                kv_cache=layers,
                                cache_length=jnp.zeros((1,), jnp.int32))
        length = jnp.asarray([8], jnp.int32)
        for t in range(8, 14):  # 6 decode steps, wrapping the ring
            logits, layers = model.apply(
                {"params": params}, ids[:, t:t + 1], kv_cache=layers,
                cache_length=length, decode=True)
            length = length + 1
        assert np.isfinite(np.asarray(logits)).all()


# ---------------------------------------------------------------------------
# Scheduler semantics over a fake engine (no compiles — fast tier).
# ---------------------------------------------------------------------------

class _FakeEngine:
    """Slot bookkeeping without jax: prefill echoes, decode counts up."""

    def __init__(self, slots=2, buckets=(8, 16), eos_id=None):
        self.slots = slots
        self.prompt_buckets = buckets
        self.eos_id = eos_id
        self._active = {}

    def prefill(self, prompt):
        return 100 + len(prompt), ("pcache", len(prompt)), len(prompt)

    def insert(self, slot, pcache, length, first_token):
        self._active[slot] = first_token

    def decode_step(self):
        out = np.zeros(self.slots, np.int32)
        for slot, tok in self._active.items():
            self._active[slot] = tok + 1
            out[slot] = tok + 1
        return out


class TestScheduler:
    def test_admission_rejects_oversized_prompt(self):
        sched = Scheduler(_FakeEngine(buckets=(8,)))
        with pytest.raises(ValueError, match="exceeds largest bucket"):
            sched.submit(Request(rid=0, prompt=list(range(9))))

    def test_continuous_batching_admits_and_retires(self):
        eng = _FakeEngine(slots=2)
        sched = Scheduler(eng)
        for rid in range(5):
            sched.submit(Request(rid=rid, prompt=[1, 2, 3],
                                 max_new_tokens=3))
        steps = 0
        while sched.has_work():
            sched.step()
            steps += 1
            assert steps < 50
        assert len(sched.completed) == 5
        assert [r.rid for r in sched.completed[:2]] == [0, 1]  # FIFO
        for r in sched.completed:
            assert len(r.tokens) == 3
            assert r.ttft_ms() is not None and r.ttft_ms() >= 0
            assert r.tpot_ms() is not None and r.tpot_ms() >= 0
        # a long generation never blocked a short one: more completions
        # than slot count proves slots were recycled mid-run
        assert len(sched.completed) > eng.slots

    def test_eos_retires_early(self):
        # fake decode emits first_token+1, +2, ...: eos = 104 stops rid 0
        # (prompt len 3 -> first token 103) after one decode step.
        eng = _FakeEngine(slots=1, eos_id=104)
        sched = Scheduler(eng)
        sched.submit(Request(rid=0, prompt=[1, 2, 3], max_new_tokens=50))
        while sched.has_work():
            sched.step()
        (req,) = sched.completed
        assert req.tokens[-1] == 104
        assert len(req.tokens) == 2

    def test_retire_then_admit_fills_freed_slot_same_step(self):
        """A slot freed by this step's retire is refilled by the trailing
        admit pass — the follower's prefill (and TTFT clock stop) lands
        this step instead of idling the slot until the next one."""
        sched = Scheduler(_FakeEngine(slots=1))
        sched.submit(Request(rid=0, prompt=[1, 2], max_new_tokens=2))
        sched.submit(Request(rid=1, prompt=[3, 4], max_new_tokens=2))
        sched.step()       # rid 0: admit + decode = done; rid 1 admitted
        assert [r.rid for r in sched.completed] == [0]
        follower = sched.active[0]
        assert follower is not None and follower.rid == 1
        assert len(follower.tokens) == 1          # prefill token landed
        assert follower.first_token_t is not None  # TTFT already stopped
        sched.step()                      # rid 1's one decode token
        assert [r.rid for r in sched.completed] == [0, 1]
        for r in sched.completed:
            assert len(r.tokens) == 2
            assert r.ttft_ms() is not None and r.ttft_ms() >= 0

    def test_instant_retire_reuses_slot_within_admit_pass(self):
        """max_new_tokens=1 requests finish at prefill: the admit pass
        retires them in place and reuses the slot, so a 1-slot scheduler
        drains any number of them in a single step."""
        sched = Scheduler(_FakeEngine(slots=1))
        for rid in range(3):
            sched.submit(Request(rid=rid, prompt=[rid], max_new_tokens=1))
        produced = sched.step()
        assert produced == 3               # all three admitted this step
        assert not sched.has_work()
        assert [r.rid for r in sched.completed] == [0, 1, 2]
        for r in sched.completed:
            assert len(r.tokens) == 1 and r.done_t is not None


# ---------------------------------------------------------------------------
# The real engine: loadgen, events, compile-cache warm restart.
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestEngineLoadgen:
    def test_loadgen_completes_and_emits_events(self, tmp_path):
        from tpuframe.obs import events as obs_events
        from tpuframe.obs import goodput
        from tpuframe.serve import loadgen
        from tpuframe.serve.engine import LMEngine

        events_dir = tmp_path / "events"
        obs_events.init(str(events_dir))
        try:
            engine = LMEngine(TINY, slots=2, prompt_buckets=(16, 32),
                              decode_block=16, max_context=40,
                              enable_persistent_cache=False)
            reqs = loadgen.synthetic_requests(
                6, buckets=(16, 32), vocab_size=TINY.vocab_size,
                max_new_tokens=4, seed=1)
            stats = loadgen.run_loadgen(engine, reqs)
        finally:
            obs_events.close()
        assert stats["requests"] == 6 and stats["unfinished"] == 0
        assert stats["total_tokens"] == 6 * 4

        merged = obs_events.merge(str(events_dir))
        assert obs_events.validate_files(
            obs_events.event_files(str(events_dir))) == []
        serve = goodput.serve_stats(merged)
        assert serve is not None
        assert serve["requests"] == 6
        assert serve["ttft_ms"] and serve["tpot_ms"]
        assert serve["tokens_per_s"] and serve["tokens_per_s"] > 0
        assert serve["tokens_per_s_per_chip"] == pytest.approx(
            serve["tokens_per_s"] / serve["n_devices"], abs=0.05)
        # training-only logs stay serving-free
        assert goodput.serve_stats(
            [r for r in merged if not r["type"].startswith("serve")]) \
            is None

    def test_persistent_cache_warm_restart(self, tmp_path, monkeypatch):
        """Second engine build after jax.clear_caches() must be served
        from the on-disk compile cache: hits > 0, no new misses beyond
        the first build's."""
        from tpuframe.obs import metrics
        from tpuframe.serve.engine import LMEngine
        from tpuframe.utils import compile_cache

        monkeypatch.setenv("TPUFRAME_COMPILE_CACHE", str(tmp_path / "cc"))
        # tiny programs compile in <1s; keep them all
        monkeypatch.setenv("TPUFRAME_COMPILE_CACHE_MIN_S", "0")
        compile_cache.enable()
        metrics.reset_counters()

        kw = dict(slots=2, prompt_buckets=(16,), decode_block=16,
                  max_context=24)
        LMEngine(TINY, **kw)
        first = metrics.counters("compile_cache")
        assert first.get("compile_cache.misses", 0) > 0

        jax.clear_caches()
        compile_cache.reset_cache()
        LMEngine(TINY, **kw)
        second = metrics.counters("compile_cache")
        # every program the first engine compiled is served from disk;
        # unrelated tiny ops recompiled by clear_caches() may still miss
        # (they predate enable()), so only the hit floor is asserted
        assert second.get("compile_cache.hits", 0) >= \
            first.get("compile_cache.misses", 0)

    def test_decode_outputs_cache_safe(self):
        from tpuframe.serve import engine as engine_lib
        from tpuframe.utils import compile_cache

        decode_fn = engine_lib.make_decode_fn(TransformerLM(TINY))
        spec = kv.spec_for_model(TINY, slots=2, capacity=16)
        sds = jax.ShapeDtypeStruct
        variables = jax.eval_shape(
            TransformerLM(TINY).init, jax.random.key(0),
            jax.ShapeDtypeStruct((1, 8), jnp.int32))
        p_sds = jax.tree.map(lambda s: sds(s.shape, s.dtype),
                             variables["params"])
        cache_sds = tuple(
            (sds(spec.layer_shape(), jnp.float32),
             sds(spec.layer_shape(), jnp.float32))
            for _ in range(TINY.num_layers))
        out = jax.eval_shape(decode_fn, p_sds, sds((2, 1), jnp.int32),
                             sds((2,), jnp.int32), cache_sds)
        assert compile_cache.outputs_cache_safe(out)
        # a typed PRNG key output is the unsafe case on jax < 0.6
        key_aval = jax.eval_shape(lambda: jax.random.key(0))
        if not compile_cache.safe_for_key_outputs():
            assert not compile_cache.outputs_cache_safe((out, key_aval))

    def test_bert_single_shot(self):
        from tpuframe.models.bert import BertConfig
        from tpuframe.serve.engine import BertClassifier

        clf = BertClassifier(BertConfig.tiny(num_classes=3),
                             buckets=(16, 32))
        label, probs = clf.classify(list(range(1, 11)))
        assert 0 <= label < 3
        assert probs.shape == (3,)
        assert float(probs.sum()) == pytest.approx(1.0, abs=1e-4)
        # identical request in the other bucket: same model, same answer
        label2, _ = clf.classify(list(range(1, 20)))
        assert 0 <= label2 < 3


# ---------------------------------------------------------------------------
# TF109 lint: the compile seam is enforced, not a convention.
# ---------------------------------------------------------------------------

class TestTF109:
    BAD = ("import jax\n\n"
           "def serve_one(model, params, ids, fn):\n"
           "    step = jax.jit(fn)\n"
           "    out = model.apply({'params': params}, ids)\n"
           "    return step, out\n")

    def test_fires_above_the_seam(self):
        from tpuframe.analysis import source_lint

        findings = source_lint.lint_source(
            self.BAD, "tpuframe/serve/scheduler.py")
        assert sum(f.rule == "TF109" for f in findings) == 2  # jit + apply

    def test_engine_is_the_sanctioned_seam(self):
        from tpuframe.analysis import source_lint

        findings = source_lint.lint_source(
            self.BAD, "tpuframe/serve/engine.py")
        assert not [f for f in findings if f.rule == "TF109"]

    def test_non_serve_paths_unaffected(self):
        from tpuframe.analysis import source_lint

        findings = source_lint.lint_source(
            self.BAD, "tpuframe/parallel/step.py")
        assert not [f for f in findings if f.rule == "TF109"]

    def test_shipped_serve_package_is_clean(self):
        import tpuframe.serve as serve_pkg
        from tpuframe.analysis import source_lint

        pkg_dir = os.path.dirname(serve_pkg.__file__)
        findings = source_lint.lint_paths([pkg_dir])
        assert not [str(f) for f in findings if f.rule == "TF109"]

    def test_serve_check_gate(self):
        from tpuframe import serve

        assert serve.check() == []


# ---------------------------------------------------------------------------
# Zero-collective serving decode (plain DP) — budget + HLO audit.
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestServeDecodeAudit:
    def test_budget_forbids_all_collectives(self):
        from tpuframe.analysis import budgets

        b = budgets.serve_decode_budget(12345)
        assert b.allowed == {}
        assert budgets.strategy_budget("serve-dp-decode",
                                       param_bytes=0).name \
            == "serve-dp-decode"

    def test_dp_decode_audit_passes(self):
        from tpuframe.analysis import strategies

        audit = strategies.audit_strategy("serve-dp-decode", 8)
        if audit.status == "unavailable":
            pytest.skip(audit.reason)
        assert audit.status == "ok", audit.violations
        # nothing above the scalar floor: every surviving op is tiny
        # index/length bookkeeping, not tensor traffic
        for op in audit.report.ops:
            assert op.bytes < audit.budget.ignore_below
        # the checked-in auto-derived budget is this program's exact
        # record — asserted instead of hand-copied byte constants
        import jax

        from tpuframe.analysis import shardflow

        derived_file = shardflow.load_derived()
        assert derived_file is not None
        if derived_file["jax"] == jax.__version__:
            assert shardflow.derive_budget(
                audit.report, audit.budget.ignore_below) == \
                shardflow.derived_for("serve-dp-decode")


# ---------------------------------------------------------------------------
# Decode roofline census: analytic model vs compiled cost_analysis.
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestDecodeRooflineCensus:
    def test_analytic_brackets_compiled_bytes(self):
        """The analytic decode model (params + KV read) must be a LOWER
        bound on the compiled program's byte count, and within 3x of it:
        the compiled count adds the donated cache write-back and the
        attention intermediates (observed ratio ~1.9x for the tiny
        config on this backend)."""
        from tpuframe.tune import roofline

        compiled, spec, param_bytes = _decode_compiled(TINY, 4, 64)
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        got = float((ca or {}).get("bytes accessed", 0.0))
        if got <= 0:
            pytest.skip("backend reports no cost analysis")
        analytic = roofline.decode_score(
            param_bytes=param_bytes,
            kv_bytes_per_token=spec.bytes_per_token(),
            slots=4, context=64)
        assert analytic.bytes_per_step <= got <= 3 * analytic.bytes_per_step
        assert analytic.bound == "hbm"

    def test_compiled_bytes_scale_with_kv_capacity(self):
        """Doubling KV capacity must grow compiled bytes by at least the
        extra cache read and at most ~5x it (write-back + attention
        intermediates; observed ~3.3x)."""
        c64, spec, _ = _decode_compiled(TINY, 4, 64)
        c128, _, _ = _decode_compiled(TINY, 4, 128)

        def _bytes(c):
            ca = c.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            return float((ca or {}).get("bytes accessed", 0.0))

        b64, b128 = _bytes(c64), _bytes(c128)
        if b64 <= 0 or b128 <= 0:
            pytest.skip("backend reports no cost analysis")
        kv_delta = 4 * 64 * spec.bytes_per_token()
        assert kv_delta <= (b128 - b64) <= 5 * kv_delta

    def test_decode_score_properties(self):
        from tpuframe.tune import roofline

        s = roofline.decode_score(param_bytes=50e6,
                                  kv_bytes_per_token=4096, slots=8,
                                  context=1024)
        # more slots amortize the weight read: higher per-chip throughput
        s2 = roofline.decode_score(param_bytes=50e6,
                                   kv_bytes_per_token=4096, slots=16,
                                   context=1024)
        assert s2.tokens_per_s_per_chip > s.tokens_per_s_per_chip
        # longer context adds KV traffic: lower throughput
        s3 = roofline.decode_score(param_bytes=50e6,
                                   kv_bytes_per_token=4096, slots=8,
                                   context=4096)
        assert s3.tokens_per_s_per_chip < s.tokens_per_s_per_chip
        with pytest.raises(ValueError):
            roofline.decode_score(param_bytes=1, kv_bytes_per_token=1,
                                  slots=0, context=1)


# ---------------------------------------------------------------------------
# Obs: event schema + analyzer stats.
# ---------------------------------------------------------------------------

class TestServeObs:
    def test_required_fields_registered(self):
        from tpuframe.obs import events

        for etype in ("serve_step", "serve_request", "serve_summary"):
            assert etype in events.REQUIRED_FIELDS

    def test_serve_stats_from_synthetic_events(self):
        from tpuframe.obs import goodput

        events = [
            {"type": "serve_request", "id": i, "prompt_tokens": 10,
             "output_tokens": 4, "ttft_ms": 10.0 + i, "tpot_ms": 2.0}
            for i in range(10)
        ] + [{"type": "serve_summary", "requests": 10, "tokens_per_s": 80.0,
              "n_devices": 4}]
        s = goodput.serve_stats(events)
        assert s["requests"] == 10
        assert s["ttft_ms"]["p50"] == pytest.approx(15.0, abs=1.01)
        assert s["tpot_ms"]["p99"] == 2.0
        assert s["tokens_per_s_per_chip"] == 20.0
        assert s["n_devices"] == 4

    def test_serve_stats_none_without_serving(self):
        from tpuframe.obs import goodput

        assert goodput.serve_stats(
            [{"type": "step", "step": 1, "wall_ms": 5.0}]) is None

    def test_serve_stats_reconstructs_without_summary(self):
        from tpuframe.obs import goodput

        events = [{"type": "serve_step", "step": i, "wall_ms": 10.0,
                   "active": 2, "admitted": 0, "produced": 2}
                  for i in range(5)]
        s = goodput.serve_stats(events)
        assert s["tokens_per_s"] == pytest.approx(200.0)


# ---------------------------------------------------------------------------
# Tune: serve_lm sweep plumbing (pure parts — the sweep itself is the
# offline CLI's job and its artifacts are committed).
# ---------------------------------------------------------------------------

class TestServeTune:
    def test_serve_bucket_sets(self):
        from tpuframe.tune import search

        buckets, capacity = search.serve_bucket_sets(64)
        assert capacity == 256
        assert buckets == (64, 128, 256)
        assert kv.check_buckets(buckets, capacity) == []

    def test_committed_db_has_serve_family(self):
        from tpuframe.tune import db as tune_db

        path = tune_db.default_db_path()
        if not os.path.exists(path):
            pytest.skip("no committed tuning DB")
        db = tune_db.TuningDB.open(path)
        recs = db.records(family="serve_lm")
        assert recs, "tune_db.json lost its serve_lm family"
        best = db.best(family="serve_lm", generation="v5e")
        assert "decode_block" in best.config
        assert best.config.get("prompt_buckets")
        env = best.env_overrides()
        assert "TPUFRAME_DECODE_BLOCK" in env
        assert "TPUFRAME_SERVE_BUCKETS" in env

    def test_committed_serve_report(self):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = os.path.join(root, "perf", "results",
                            "serve_report_v5e_22.json")
        if not os.path.exists(path):
            pytest.skip("no committed serve report")
        with open(path) as f:
            report = json.load(f)
        assert report["winner"] is not None
        rows = report["serve"]["rows"]
        assert rows == sorted(rows,
                              key=lambda r: r["predicted_ms_per_token"])


# ---------------------------------------------------------------------------
# Replica drain semantics (serve/replica.py over the fake engine).
# ---------------------------------------------------------------------------

class TestReplicaDrain:
    def test_drain_finishes_inflight_then_exits(self):
        """A replica that flips draining mid-generation still answers
        every request it already accepted (200 with the full token
        stream), rejects new work with 503, reads unhealthy for the
        router's scrape — and only then does its main loop exit."""
        import threading

        from tpuframe.serve.replica import FakeEngine, Replica

        replica = Replica(FakeEngine(slots=1), handler_timeout_s=10.0)
        results = []

        def call(rid):
            body = json.dumps({"rid": rid, "prompt": [1, 2, 3],
                               "max_new_tokens": 4}).encode()
            results.append(replica.handle_generate(body))

        t = threading.Thread(target=call, args=(0,), daemon=True)
        t.start()
        deadline = 200
        while not replica._inbox and deadline:  # accepted, not yet pumped
            deadline -= 1
            import time as _time
            _time.sleep(0.01)
        assert replica._inbox, "request never reached the inbox"

        replica.drain()                      # mid-generation drain signal
        assert replica.healthy() is False    # /healthz now reads 503
        status, body = replica.handle_generate(
            json.dumps({"rid": 1, "prompt": [4], "max_new_tokens": 2})
            .encode())
        assert status == 503                 # new work rejected
        assert json.loads(body.decode())["error"] == "draining"

        rc = replica.run()                   # drains, then exits
        assert rc == 0
        t.join(5.0)
        (accepted,) = results                # the accepted request: 200,
        status, body = accepted              # full stream, never dropped
        assert status == 200
        msg = json.loads(body.decode())
        assert msg["rid"] == 0 and len(msg["tokens"]) == 4
        assert msg["ttft_ms"] is not None
        assert not replica.scheduler.has_work()

    def test_fake_engine_streams_are_prompt_deterministic(self):
        """Re-prefilling the same prompt on a fresh replica reproduces
        the identical token stream — the idempotence the router's
        hedging and redispatch (first-winner-kept) rely on."""
        from tpuframe.serve.replica import FakeEngine

        def stream(prompt, n):
            sched = Scheduler(FakeEngine(slots=1))
            sched.submit(Request(rid=0, prompt=prompt, max_new_tokens=n))
            while sched.has_work():
                sched.step()
            return sched.completed[0].tokens

        assert stream([5, 6, 7], 6) == stream([5, 6, 7], 6)
        assert stream([5, 6, 7], 6) != stream([5, 6, 8], 6)

    def test_bad_request_and_oversized_prompt_get_400(self):
        from tpuframe.serve.replica import FakeEngine, Replica

        replica = Replica(FakeEngine(slots=1))
        status, _ = replica.handle_generate(b"not json")
        assert status == 400
        status, body = replica.handle_generate(
            json.dumps({"rid": 0, "prompt": list(range(100)),
                        "max_new_tokens": 2}).encode())
        assert status == 400                # outside buckets: rejected
        assert "outside buckets" in json.loads(body.decode())["error"]
