"""FSDP/ZeRO sharding: golden-loss vs replicated DP + placement checks
(SURVEY.md §7 golden-loss strategy; PAPERS.md:5 weight-update sharding)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpuframe import models
from tpuframe.models import losses
from tpuframe.parallel import fsdp as fsdp_lib
from tpuframe.parallel import mesh as mesh_lib
from tpuframe.parallel import step as step_lib


def _setup(mesh, use_fsdp):
    model = models.get_model("transformer-lm", tiny=True, vocab_size=64,
                             max_seq=32)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, size=(8, 33)).astype(np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    variables = model.init(jax.random.key(0),
                           jnp.asarray(batch["input_ids"][:1]))
    tx = optax.adamw(1e-3)

    def loss_fn(params, model_state, b, rng):
        logits = model.apply({"params": params}, b["input_ids"], train=True,
                             rngs={"dropout": rng})
        return losses.softmax_cross_entropy(logits, b["labels"]), ({}, {})

    state = step_lib.TrainState.create(variables["params"], tx)
    shardings = None
    if mesh is not None:
        from jax.sharding import NamedSharding
        data_mesh = mesh
        if use_fsdp:
            shardings = fsdp_lib.state_shardings(state, mesh)
            state = jax.tree.map(jax.device_put, state, shardings)
            data_mesh = fsdp_lib.auto_mesh(mesh)
        else:
            state = step_lib.replicate_state(state, mesh)
        batch = jax.tree.map(
            lambda x: jax.device_put(
                x, NamedSharding(data_mesh, mesh_lib.batch_spec())), batch)
    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False,
                                    state_shardings=shardings)
    return state, step, batch


def _losses(mesh, use_fsdp, n=3):
    state, step, batch = _setup(mesh, use_fsdp)
    out = []
    for _ in range(n):
        state, m = step(state, batch)
        out.append(float(m["loss"]))
    return out, state


@pytest.mark.slow
def test_fsdp_golden_loss_vs_replicated():
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=2, fsdp=4))
    ref, _ = _losses(None, False)
    got, _ = _losses(mesh, True)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)
    assert ref[-1] < ref[0]


def test_fsdp_state_actually_sharded():
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=2, fsdp=4))
    _, state = _losses(mesh, True, n=1)
    frac = fsdp_lib.param_fraction_sharded(state.params)
    assert frac > 0.9, f"only {frac:.1%} of param elements fsdp-sharded"
    # Optimizer moments mirror param sharding (the ZeRO memory win).
    frac_opt = fsdp_lib.param_fraction_sharded(state.opt_state)
    assert frac_opt > 0.5, f"only {frac_opt:.1%} of opt state sharded"
    # Per-device bytes: a sharded leaf stores 1/4 of its elements per chip.
    leaf = state.params["block_0"]["attn"]["query"]["kernel"]
    shard_shape = leaf.sharding.shard_shape(leaf.shape)
    assert int(np.prod(shard_shape)) == int(np.prod(leaf.shape)) // 4


def test_choose_spec_rules():
    assert fsdp_lib.choose_spec((4096, 512), 4) == P("fsdp", None)
    assert fsdp_lib.choose_spec((512, 4096), 4) == P(None, "fsdp")
    assert fsdp_lib.choose_spec((3, 5), 4) == P()        # tiny → replicated
    assert fsdp_lib.choose_spec((4098, 2), 4) == P()     # indivisible
    assert fsdp_lib.choose_spec((4096,), 1) == P()       # no fsdp axis


class TestTensorParallel:
    """TP over the model axis (tpuframe.parallel.tp) — golden loss +
    placement; composition with fsdp."""

    def _setup_tp(self, mesh_spec, model_kwargs=None):
        from tpuframe.parallel import tp as tp_lib

        mesh = mesh_lib.make_mesh(mesh_spec) if mesh_spec else None
        model = models.get_model("transformer-lm", tiny=True, vocab_size=64,
                                 max_seq=32, **(model_kwargs or {}))
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 64, size=(8, 33)).astype(np.int32)
        batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
        variables = model.init(jax.random.key(0),
                               jnp.asarray(batch["input_ids"][:1]))
        tx = optax.adamw(1e-3)

        def loss_fn(params, model_state, b, rng):
            logits = model.apply({"params": params}, b["input_ids"],
                                 train=True, rngs={"dropout": rng})
            return losses.softmax_cross_entropy(logits, b["labels"]), ({}, {})

        state = step_lib.TrainState.create(variables["params"], tx)
        shardings = None
        if mesh is not None:
            from jax.sharding import NamedSharding

            rules = tp_lib.rules_for_model("transformer-lm")
            shardings = fsdp_lib.state_shardings(state, mesh, tp_rules=rules)
            state = jax.tree.map(jax.device_put, state, shardings)
            dmesh = fsdp_lib.auto_mesh(mesh)
            batch = jax.tree.map(
                lambda x: jax.device_put(
                    x, NamedSharding(dmesh, mesh_lib.batch_spec())), batch)
        step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False,
                                        state_shardings=shardings)
        return state, step, batch

    def _losses(self, mesh_spec, n=3):
        state, step, batch = self._setup_tp(mesh_spec)
        out = []
        for _ in range(n):
            state, m = step(state, batch)
            out.append(float(m["loss"]))
        return out, state

    def test_tp_golden_loss_vs_single_device(self):
        ref, _ = self._losses(None)
        got, _ = self._losses(mesh_lib.MeshSpec(data=2, model=4))
        np.testing.assert_allclose(got, ref, rtol=3e-5, atol=3e-5)
        assert ref[-1] < ref[0]

    def test_tp_params_sharded_by_heads(self):
        _, state = self._losses(mesh_lib.MeshSpec(data=2, model=4), n=1)
        qk = state.params["block_0"]["attn"]["query"]["kernel"]
        # [hidden, heads, head_dim] with heads=4 split over model=4
        assert qk.sharding.shard_shape(qk.shape)[1] == qk.shape[1] // 4
        up = state.params["block_0"]["up"]["kernel"]
        assert up.sharding.shard_shape(up.shape)[1] == up.shape[1] // 4

    def test_tp_fsdp_compose(self):
        ref, _ = self._losses(None)
        got, state = self._losses(mesh_lib.MeshSpec(data=2, fsdp=2, model=2))
        np.testing.assert_allclose(got, ref, rtol=3e-5, atol=3e-5)
        qk = state.params["block_0"]["attn"]["query"]["kernel"]
        shard = qk.sharding.shard_shape(qk.shape)
        # model splits heads (dim 1), fsdp overlays the largest free dim
        assert int(np.prod(shard)) == int(np.prod(qk.shape)) // 4

    def test_match_spec_indivisible_falls_back(self):
        from jax.sharding import PartitionSpec as P

        from tpuframe.parallel import tp as tp_lib

        rules = tp_lib.rules_for_model("transformer-lm")
        # 3 heads not divisible by 4 -> replicate, never crash
        assert tp_lib.match_spec("block_0/attn/query/kernel", (64, 3, 16),
                                 4, rules) is None
        assert tp_lib.match_spec("block_0/attn/query/kernel", (64, 4, 16),
                                 4, rules) == P(None, "model", None)
        assert tp_lib.match_spec("block_0/mlp_ln/scale", (64,), 4,
                                 rules) is None
