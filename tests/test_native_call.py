"""The XLA-FFI custom-call path (SURVEY.md §3b native demonstrator):
C++ running inside a compiled XLA program on the CPU backend, bit-equal
to the jnp expression it replaces."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuframe.ops import native_call

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "cpu",
    reason="FFI custom calls are CPU-backend only (TPU kernels are pallas)")


def _inputs(shape=(4, 32, 32, 3), seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.integers(0, 256, size=shape), jnp.uint8)
    mean = jnp.asarray([0.485, 0.456, 0.406], jnp.float32)
    std = jnp.asarray([0.229, 0.224, 0.225], jnp.float32)
    return x, mean, std


def test_ffi_kernel_registers_and_matches_jnp():
    x, mean, std = _inputs()
    assert native_call._ffi_available(), "FFI kernel failed to build/register"
    got = jax.jit(native_call.normalize_u8)(x, mean, std)
    want = native_call._jnp_reference(x, mean, std)
    # Same fused multiply-add structure on both sides — the kernel
    # precomputes scale/shift exactly as the XLA fusion does; allow 1-ulp
    # class differences from operation-order freedom.
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_lowers_to_custom_call_in_jit():
    x, mean, std = _inputs((2, 8, 8, 3))
    assert native_call._ffi_available()
    txt = jax.jit(native_call.normalize_u8).lower(x, mean, std).as_text()
    assert "tf_normalize_u8" in txt


def test_rank2_and_odd_channels():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.integers(0, 256, size=(16, 5)), jnp.uint8)
    mean = jnp.asarray(rng.uniform(0.2, 0.8, size=5), jnp.float32)
    std = jnp.asarray(rng.uniform(0.1, 0.4, size=5), jnp.float32)
    got = native_call.normalize_u8(x, mean, std)
    np.testing.assert_allclose(
        np.asarray(got),
        np.asarray(native_call._jnp_reference(x, mean, std)),
        rtol=1e-6, atol=1e-6)


def test_non_u8_falls_back():
    x = jnp.zeros((2, 4, 3), jnp.float32)
    mean = jnp.zeros((3,), jnp.float32)
    std = jnp.ones((3,), jnp.float32)
    out = native_call.normalize_u8(x, mean, std)  # must not raise
    assert out.dtype == jnp.float32


def test_scalar_mean_std_falls_back():
    x = jnp.zeros((2, 4, 1), jnp.uint8)
    out = native_call.normalize_u8(x, 0.5, 0.5)  # grayscale-style call
    np.testing.assert_allclose(np.asarray(out), -1.0)
