"""tpuframe.tune fast tier (CPU, no TPU topology compile — the AOT sweep
itself is exercised by ``python -m tpuframe.tune sweep``):

  - roofline tables reproduce PERF.md §2's recorded ResNet-50 b=512
    anchors (1.252e13 flops / 1.435e11 bytes -> 63.6 ms MXU / 177 ms HBM,
    bandwidth-bound);
  - flash-attention block candidates exceeding the Mosaic VMEM
    double-buffer budget are pruned BEFORE any compile;
  - tuning-DB round-trip, predicted->measured upgrade, fingerprint
    mismatch fallback, env-beats-DB precedence;
  - a seeded compiler-option set changes the program fingerprint;
  - the shared compile-cache helper records persistent-cache hits in
    obs.metrics (the warm-restart path PR 2's relaunch loop exercises).
"""

import json
import os

import pytest

from tpuframe.tune import db as tune_db
from tpuframe.tune import roofline
from tpuframe.tune.search import (DEFAULT_VMEM_BUDGET, fa_block_candidates,
                                  fa_vmem_bytes, xla_opts_candidate_sets)


class TestRoofline:
    def test_resnet50_b512_anchors(self):
        # PERF.md §2: "t_mxu = 1.252e13 / 197e12 = 63.6 ms",
        # "t_hbm = 1.435e11 / 8.1e11 = 177.2 ms" — bandwidth-bound.
        s = roofline.score("v5e", flops=1.252e13, bytes_accessed=1.435e11)
        assert s["t_mxu_ms"] == pytest.approx(63.6, abs=0.1)
        assert s["t_hbm_ms"] == pytest.approx(177.2, abs=0.1)
        assert s["bound"] == "hbm"
        assert s["predicted_ms"] == s["t_hbm_ms"]

    def test_fits_verdict(self):
        s = roofline.score("v5e", flops=1e12, bytes_accessed=1e9,
                           peak_memory_bytes=20e9)
        assert s["fits"] is False  # v5e HBM is 15.75 GB
        s = roofline.score("v5e", flops=1e12, bytes_accessed=1e9,
                           peak_memory_bytes=10e9)
        assert s["fits"] is True
        s = roofline.score("v5e", flops=1e12, bytes_accessed=1e9)
        assert s["fits"] is None

    def test_scan_caveat_tags_lower_bound(self):
        # §8: scan bodies are counted once; byte scores of
        # scan-containing programs are lower bounds, and the tag must
        # survive into the score dict.
        s = roofline.score("v5e", flops=1e12, bytes_accessed=1e9,
                           contains_scan=True)
        assert s["bytes_lower_bound"] is True
        assert roofline.contains_scan("  %x = while(...)")
        assert not roofline.contains_scan("  %x = fusion(...)")

    def test_generation_table(self):
        # peak-flops column must agree with bench.py's BF16_PEAK_FLOPS
        assert roofline.get_hardware("v4").bf16_flops == 275e12
        assert roofline.get_hardware("v5e").bf16_flops == 197e12
        assert roofline.get_hardware("v5p").bf16_flops == 459e12
        assert roofline.get_hardware("v6e").bf16_flops == 918e12
        assert roofline.get_hardware("v5e:2x2").generation == "v5e"
        with pytest.raises(KeyError):
            roofline.get_hardware("v99")

    def test_check_tables_clean(self):
        assert roofline.check_tables() == []

    def test_score_compiled_list_shaped_cost_analysis(self):
        # older jax returns one cost dict PER DEVICE from cost_analysis()
        class FakeCompiled:
            def cost_analysis(self):
                return [{"flops": 1.252e13, "bytes accessed": 1.435e11}]

            def memory_analysis(self):
                raise RuntimeError("unavailable")

            def as_text(self):
                return "ENTRY main { fusion }"

        s = roofline.score_compiled(FakeCompiled(), "v5e")
        assert s["bound"] == "hbm"
        assert s["t_hbm_ms"] == pytest.approx(177.2, abs=0.1)
        assert s["fits"] is None

    def test_mxu_bound_verdict(self):
        # plenty of flops, almost no bytes -> compute-bound
        s = roofline.score("v5e", flops=1e14, bytes_accessed=1e6)
        assert s["bound"] == "mxu" and s["predicted_ms"] == s["t_mxu_ms"]


class TestVmemPruning:
    def test_default_grid_fits_at_d64(self):
        # the production grid (seq 2048, d 64, blocks {128,256,512}^2)
        # is entirely within budget — nothing to prune
        kept, pruned = fa_block_candidates(2048, 64)
        assert len(kept) == 9 and pruned == []

    def test_over_budget_pruned_before_compile(self):
        # (2048, 2048) at d=256 double-buffers to 20 MiB > 16 MiB: the
        # §11 class of tiling the real compiler rejects must die here,
        # not in a compile error
        assert fa_vmem_bytes(2048, 2048, 256) > DEFAULT_VMEM_BUDGET
        kept, pruned = fa_block_candidates(2048, 256, blocks=(128, 2048))
        reasons = {(p["fa_block_q"], p["fa_block_k"]): p["pruned"]
                   for p in pruned}
        assert reasons == {(2048, 2048): "vmem_over_budget"}
        assert {(c["fa_block_q"], c["fa_block_k"]) for c in kept} == {
            (128, 128), (128, 2048), (2048, 128)}

    def test_explicit_budget(self):
        kept, pruned = fa_block_candidates(2048, 64,
                                           budget=1024 * 1024)
        # 0.75 MiB (128x128) survives a 1 MiB budget; 256x256 (1.5 MiB)
        # and up do not
        assert {(c["fa_block_q"], c["fa_block_k"]) for c in kept} == {
            (128, 128)}
        assert all(p["pruned"] == "vmem_over_budget" for p in pruned)

    def test_indivisible_seq_pruned(self):
        _, pruned = fa_block_candidates(2048, 64, blocks=(128, 768))
        assert {(p["fa_block_q"], p["fa_block_k"]) for p in pruned} == {
            (128, 768), (768, 128), (768, 768)}
        assert all(p["pruned"] == "seq_not_divisible" for p in pruned)

    def test_vmem_model_monotone(self):
        # doubling either block dimension must not shrink the footprint
        assert fa_vmem_bytes(256, 128, 64) > fa_vmem_bytes(128, 128, 64)
        assert fa_vmem_bytes(128, 256, 64) > fa_vmem_bytes(128, 128, 64)
        assert fa_vmem_bytes(128, 128, 256) > fa_vmem_bytes(128, 128, 64)

    def test_lane_padding_floors_head_dim(self):
        # d=64 pads to 128 lanes: halving head_dim below 128 cannot
        # halve VMEM (the §11 padded-byte rule)
        assert fa_vmem_bytes(128, 128, 64) == fa_vmem_bytes(128, 128, 128)


def _rec(program="flash_mha_s2048_d64", family="flash_attention",
         gen="v5e", config=None, predicted_ms=10.0, vmem=0, fp="fp0"):
    return {"program": program, "family": family, "fingerprint": fp,
            "topology": "v5e:2x2", "generation": gen,
            "config": config or {"fa_block_q": 128, "fa_block_k": 128},
            "predicted": {"predicted_ms": predicted_ms, "bound": "hbm",
                          "fits": True, "vmem_bytes": vmem,
                          "bytes_lower_bound": True}}


class TestTuningDB:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "tune_db.json")
        db = tune_db.TuningDB(path)
        db.add(_rec(config={"fa_block_q": 128, "fa_block_k": 128}))
        db.add(_rec(config={"fa_block_q": 256, "fa_block_k": 256},
                    predicted_ms=8.0))
        db.save()
        db2 = tune_db.TuningDB.open(path)
        assert len(db2.records()) == 2
        assert tune_db.validate(db2.data) == []
        # predicted tier: lower roofline ms ranks first
        assert db2.best(family="flash_attention").config[
            "fa_block_q"] == 256

    def test_add_replaces_same_config(self, tmp_path):
        db = tune_db.TuningDB(str(tmp_path / "db.json"))
        db.add(_rec(predicted_ms=10.0))
        db.add(_rec(predicted_ms=7.0))  # re-sweep, same config key
        assert len(db.records()) == 1
        assert db.best().predicted["predicted_ms"] == 7.0

    def test_vmem_utilization_tiebreak(self, tmp_path):
        # cost_analysis can't see inside the pallas call (§8) so
        # roofline ms ties across block sizes — the fatter in-budget
        # tiling must rank first
        db = tune_db.TuningDB(str(tmp_path / "db.json"))
        db.add(_rec(config={"fa_block_q": 128, "fa_block_k": 128},
                    predicted_ms=10.0, vmem=786432))
        db.add(_rec(config={"fa_block_q": 512, "fa_block_k": 512},
                    predicted_ms=10.0, vmem=3145728))
        assert db.best().config["fa_block_q"] == 512

    def test_predicted_to_measured_upgrade(self, tmp_path):
        path = str(tmp_path / "db.json")
        db = tune_db.TuningDB(path)
        db.add(_rec(config={"fa_block_q": 128, "fa_block_k": 128},
                    predicted_ms=10.0))
        db.add(_rec(config={"fa_block_q": 512, "fa_block_k": 512},
                    predicted_ms=5.0))
        # offline ranking says 512 wins; the chip says 128 does
        loser = db.best()
        assert loser.config["fa_block_q"] == 512
        rec128 = [r for r in db.records()
                  if r.config["fa_block_q"] == 128][0]
        db.upgrade_measured(rec128, 1234.5, unit="img/s/chip")
        db.save()
        db2 = tune_db.TuningDB.open(path)
        best = db2.best(family="flash_attention")
        # measured tier beats every predicted entry
        assert best.config["fa_block_q"] == 128
        assert best.measured["value"] == 1234.5
        assert tune_db.validate(db2.data) == []

    def test_validate_rejects_malformed(self):
        assert tune_db.validate([]) != []
        assert tune_db.validate({"version": 99, "records": []}) != []
        bad = {"version": 1, "records": [{"program": "x"}]}
        assert any("missing" in p for p in tune_db.validate(bad))
        bad_gen = {"version": 1, "records": [_rec(gen="v99")]}
        assert any("generation" in p for p in tune_db.validate(bad_gen))

    def test_fingerprint_mismatch_falls_back(self, tmp_path):
        db = tune_db.TuningDB(str(tmp_path / "db.json"))
        db.add(_rec(fp=tune_db.fingerprint({"program": "p", "v": 1})))
        fp_now = tune_db.fingerprint({"program": "p", "v": 2})
        # the program changed since the sweep: stale tuning must not apply
        assert db.lookup("flash_mha_s2048_d64", fp_now) is None
        fp_same = tune_db.fingerprint({"program": "p", "v": 1})
        assert db.lookup("flash_mha_s2048_d64", fp_same) is not None

    def test_env_overrides_mapping(self):
        rec = tune_db.Record(_rec(config={
            "fa_block_q": 256, "fa_block_k": 512,
            "xla_opts": {"b": "2", "a": "1"}, "batch": 256}))
        assert rec.env_overrides() == {
            "TPUFRAME_FA_BLOCK_Q": "256", "TPUFRAME_FA_BLOCK_K": "512",
            "TPUFRAME_XLA_OPTS": "a=1,b=2",
            "TPUFRAME_BENCH_BATCH": "256"}


class TestResolution:
    """env override > measured > predicted > default — and no DB effect
    at all when the target generation is unknown (the tier-1 guarantee:
    CPU tests always see the hard defaults)."""

    @pytest.fixture
    def seeded_db(self, tmp_path, monkeypatch):
        path = str(tmp_path / "tune_db.json")
        db = tune_db.TuningDB(path)
        db.add(_rec(config={"fa_block_q": 512, "fa_block_k": 256},
                    predicted_ms=5.0))
        db.add(_rec(program="bench_resnet50_b256",
                    family="bench_resnet50",
                    config={"xla_opts": {"xla_opt_x": "1"},
                            "opts_name": "seeded", "batch": 256},
                    predicted_ms=100.0))
        db.save()
        monkeypatch.setenv("TPUFRAME_TUNE_DB", path)
        monkeypatch.delenv("TPUFRAME_TUNE_GEN", raising=False)
        monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
        monkeypatch.delenv("TPUFRAME_FA_BLOCK_Q", raising=False)
        monkeypatch.delenv("TPUFRAME_FA_BLOCK_K", raising=False)
        monkeypatch.delenv("TPUFRAME_XLA_OPTS", raising=False)
        return db

    def test_no_generation_means_defaults(self, seeded_db):
        assert tune_db.resolve_fa_blocks(128, 128) == (128, 128)
        assert tune_db.resolve_xla_opts("bench_resnet50_b256") is None

    def test_db_applies_when_generation_known(self, seeded_db,
                                              monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        assert tune_db.resolve_fa_blocks(128, 128) == (512, 256)
        assert tune_db.resolve_xla_opts("bench_resnet50_b256") == {
            "xla_opt_x": "1"}

    def test_env_override_beats_db(self, seeded_db, monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        monkeypatch.setenv("TPUFRAME_FA_BLOCK_Q", "1024")
        q, k = tune_db.resolve_fa_blocks(128, 128)
        assert (q, k) == (1024, 256)  # env wins per side; DB fills the rest
        monkeypatch.setenv("TPUFRAME_XLA_OPTS", "xla_opt_y=2")
        assert tune_db.resolve_xla_opts("bench_resnet50_b256") is None

    def test_relay_gen_hint_engages_db(self, seeded_db, monkeypatch):
        monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5e")
        assert tune_db.resolve_fa_blocks(128, 128) == (512, 256)

    def test_db_off_switch(self, seeded_db, monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        monkeypatch.setenv("TPUFRAME_TUNE_DB", "off")
        assert tune_db.resolve_fa_blocks(128, 128) == (128, 128)

    def test_corrupt_db_never_raises(self, tmp_path, monkeypatch):
        path = str(tmp_path / "bad.json")
        with open(path, "w") as f:
            f.write("{not json")
        monkeypatch.setenv("TPUFRAME_TUNE_DB", path)
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        assert tune_db.resolve_fa_blocks(128, 128) == (128, 128)

    def test_weight_update_unknown_family_falls_back(self, tmp_path,
                                                     monkeypatch,
                                                     recwarn):
        # A fresh DB that has never seen a ``weight_update_*`` sweep (or
        # one from an older schema missing the family entirely) must
        # resolve to None — and through zero1.resolve to the replicated
        # default — without a single warning.
        from tpuframe.parallel import zero1

        path = str(tmp_path / "tune_db.json")
        tune_db.TuningDB(path).save()
        monkeypatch.setenv("TPUFRAME_TUNE_DB", path)
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        monkeypatch.delenv("TPUFRAME_WEIGHT_UPDATE", raising=False)
        assert tune_db.resolve_weight_update(
            "train_resnet50_b512",
            family="weight_update_resnet50") is None
        assert zero1.resolve(program="train_resnet50_b512",
                             family="weight_update_resnet50") == \
            ("replicated", "default")
        assert len(recwarn) == 0

    def test_weight_update_env_set_means_db_abstains(self, seeded_db,
                                                     monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        monkeypatch.setenv("TPUFRAME_WEIGHT_UPDATE", "replicated")
        # env ownership is unambiguous: the DB layer returns None so
        # the caller's env parse is the only authority
        assert tune_db.resolve_weight_update("anything") is None


class TestFingerprint:
    def test_opts_change_fingerprint(self):
        desc = {"program": "bench_resnet50_b256", "n_chips": 4}
        base = tune_db.fingerprint(desc, {})
        seeded = tune_db.fingerprint(
            desc, {"xla_tpu_enable_latency_hiding_scheduler": "true"})
        assert base != seeded
        # order-insensitive within a set
        assert tune_db.fingerprint(desc, {"a": "1", "b": "2"}) == \
            tune_db.fingerprint(desc, {"b": "2", "a": "1"})

    def test_lowered_text_based_fingerprint_cpu(self):
        # the sweep fingerprints (program desc, opts); a seeded option
        # set must change the fingerprint even when the lowered module
        # text is identical — verified against a real CPU lowering
        import hashlib

        import jax
        import jax.numpy as jnp

        lowered = jax.jit(lambda x: x * 2 + 1).lower(
            jax.ShapeDtypeStruct((8,), jnp.float32))
        desc = {"hlo_sha": hashlib.sha256(
            lowered.as_text().encode()).hexdigest()}
        a = tune_db.fingerprint(desc)
        b = tune_db.fingerprint(
            desc, {"xla_tpu_enable_latency_hiding_scheduler": "true"})
        assert a != b
        assert tune_db.fingerprint(desc) == a  # deterministic


class TestXlaOptsHelper:
    def test_parse(self):
        from tpuframe.utils import xla_opts

        assert xla_opts.parse("a=1, b=2 ,") == {"a": "1", "b": "2"}
        with pytest.raises(ValueError):
            xla_opts.parse("a=1,noequals")
        with pytest.raises(ValueError):
            xla_opts.parse("=v")
        assert xla_opts.format_opts({"b": "2", "a": "1"}) == "a=1,b=2"

    def test_from_env(self, monkeypatch):
        from tpuframe.utils import xla_opts

        monkeypatch.delenv("TPUFRAME_XLA_OPTS", raising=False)
        assert xla_opts.from_env() is None
        monkeypatch.setenv("TPUFRAME_XLA_OPTS", "  ")
        assert xla_opts.from_env() is None
        monkeypatch.setenv("TPUFRAME_XLA_OPTS", "k=v")
        assert xla_opts.from_env() == {"k": "v"}

    def test_candidate_sets_cover_the_levers(self):
        sets = dict(xla_opts_candidate_sets())
        assert sets["baseline"] == {}
        assert "xla_tpu_enable_latency_hiding_scheduler" in \
            sets["latency_hiding"]
        assert "xla_tpu_scoped_vmem_limit_kib" in sets["scoped_vmem_64m"]
        # combiner set derives from parallel/tuning.py's flag template
        assert sets["combine_64m"] == {
            "xla_gpu_all_reduce_combine_threshold_bytes": "67108864"}


class TestReplayAdapter:
    def test_offline_topk_upgrade(self, tmp_path):
        from tpuframe.obs import autotune

        path = str(tmp_path / "db.json")
        db = tune_db.TuningDB(path)
        db.add(_rec(config={"fa_block_q": 128, "fa_block_k": 128},
                    predicted_ms=10.0))
        db.add(_rec(config={"fa_block_q": 256, "fa_block_k": 256},
                    predicted_ms=8.0))
        db.add(_rec(config={"fa_block_q": 512, "fa_block_k": 512},
                    predicted_ms=6.0))
        seen = []

        def measure(env):
            seen.append(env)
            # the chip disagrees with the roofline ranking: 128 wins
            return 1000.0 / int(env["TPUFRAME_FA_BLOCK_Q"])

        report = autotune.replay_offline_topk(
            measure, family="flash_attention", generation="v5e", k=2,
            db=db)
        # top-2 by predicted ms: 512 then 256 — both replayed via env
        assert [e["TPUFRAME_FA_BLOCK_Q"] for e in seen] == ["512", "256"]
        assert report.best_env["TPUFRAME_FA_BLOCK_Q"] == "256"
        db2 = tune_db.TuningDB.open(path)  # saved by the adapter
        measured = [r for r in db2.records() if r.measured]
        assert len(measured) == 2  # losers are upgraded too
        assert db2.best().config["fa_block_q"] == 256

    def test_failed_trial_keeps_predicted(self, tmp_path):
        from tpuframe.obs import autotune

        db = tune_db.TuningDB(str(tmp_path / "db.json"))
        db.add(_rec(predicted_ms=10.0))

        def measure(env):
            raise RuntimeError("relay down")

        report = autotune.replay_offline_topk(
            measure, family="flash_attention", db=db, save=False)
        assert report.trials[0]["value"] is None
        assert "relay down" in report.trials[0]["error"]
        assert db.records()[0].measured is None


class TestCompileCache:
    def test_second_compile_records_hit(self, tmp_path):
        """The acceptance-criteria path: a second compile of the same
        program is served by the persistent cache and shows up in the
        obs.metrics counters — the warm restart PR 2's relaunch loop
        gets for free."""
        import jax
        import jax.numpy as jnp

        from tpuframe.obs import metrics as obs_metrics
        from tpuframe.utils import compile_cache

        old_dir = jax.config.jax_compilation_cache_dir
        old_min_s = jax.config.jax_persistent_cache_min_compile_time_secs
        old_min_b = jax.config.jax_persistent_cache_min_entry_size_bytes
        obs_metrics.reset_counters("compile_cache.")
        try:
            got = compile_cache.enable(str(tmp_path / "cache"),
                                       min_compile_secs=0.0,
                                       min_entry_size_bytes=-1)
            assert got == str(tmp_path / "cache")

            def f(x):
                return jnp.sin(x) * jnp.cos(x) + x @ x.T

            x = jnp.ones((64, 64), jnp.float32)
            jax.jit(f)(x)  # cold: compiles, writes the cache
            c = obs_metrics.counters("compile_cache.")
            assert c.get("compile_cache.misses", 0) >= 1
            # clear the in-memory caches to simulate a relaunched
            # process, then recompile the same program: it must be
            # served by the persistent cache on disk
            jax.clear_caches()
            jax.jit(f)(x)
            c = obs_metrics.counters("compile_cache.")
            assert c.get("compile_cache.hits", 0) >= 1
        finally:
            jax.config.update("jax_compilation_cache_dir", old_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", old_min_s)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", old_min_b)
            obs_metrics.reset_counters("compile_cache.")

    def test_off_switch(self, monkeypatch):
        from tpuframe.utils import compile_cache

        monkeypatch.setenv("TPUFRAME_COMPILE_CACHE", "off")
        assert compile_cache.enable() is None

    def test_key_output_gate_matches_capability(self):
        # train.py only enables the cache when this holds: jax 0.4.x
        # hard-aborts serving typed-PRNG-key-output executables (the
        # train step returns state.rng) from the persistent cache
        import jax

        from tpuframe.utils import compile_cache

        assert compile_cache.safe_for_key_outputs() == \
            hasattr(jax, "typeof")

    def test_default_dir_is_repo_xla_cache(self):
        from tpuframe.utils import compile_cache

        assert compile_cache.default_cache_dir().endswith(".xla_cache")


class TestTuneCheck:
    def test_self_check_clean(self):
        import tpuframe.tune as tune

        assert tune.check() == []

    def test_self_check_flags_bad_db(self, tmp_path):
        import tpuframe.tune as tune

        bad = tmp_path / "db.json"
        bad.write_text(json.dumps({"version": 1,
                                   "records": [{"program": "x"}]}))
        problems = tune.check(db_path=str(bad))
        assert any("missing" in p for p in problems)


class TestShippedDB:
    def test_shipped_db_validates(self):
        """The committed tune_db.json (written by the sweep) must always
        pass schema validation — same check the analysis gate runs."""
        path = os.path.join(tune_db.repo_root(), "tune_db.json")
        if not os.path.exists(path):
            pytest.skip("no shipped tuning DB")
        with open(path) as f:
            data = json.load(f)
        assert tune_db.validate(data) == []
        db = tune_db.TuningDB(path, data)
        # acceptance floor: the FA block grid + >=2 opts sets
        fa = db.records(family="flash_attention")
        assert len(fa) >= 4
        bench = db.records(family="bench_resnet50")
        assert len({r.config.get("opts_name") for r in bench}) >= 2


# ---------------------------------------------------------------------------
# tune plan: the static auto-parallelism planner
# ---------------------------------------------------------------------------

from tpuframe.tune import plan  # noqa: E402


def _plan_row(name, spec, total, comm, **over):
    r = {"name": name, "spec": spec, "slices": 1, "n_devices": 4,
         "compile_topology": "v5e:2x2", "config": {}, "status": "ok",
         "detector_problems": [], "budget_findings": [],
         "predicted_step_ms": round(total - 0.001, 6), "t_ici_ms": 0.001,
         "t_dcn_ms": 0.0, "ici_bytes": comm, "dcn_bytes": 0,
         "comm_bytes": comm, "predicted_total_ms": total,
         "overlap_potential": 0.5, "bound": "hbm", "fits": True,
         "peak_memory_bytes": 1 << 20}
    r.update(over)
    return r


def _plan_report():
    """A synthetic report exercising ranking, admissibility and all three
    pinned verdicts — shaped exactly like a real `tune plan` emission."""
    rows = [
        _plan_row("spec:dp=*", "dp=*", 0.03, 300),
        _plan_row("spec:dp=*+zero1", "dp=*", 0.04, 600),
        _plan_row("spec:dp=*+int8-block", "dp=*", 0.05, 200),
        _plan_row("spec:dp=2,fsdp=2;slices=2", "dp=2,fsdp=2;slices=2",
                  0.06, 1000, slices=2, n_devices=8, t_ici_ms=0.004,
                  t_dcn_ms=0.025, ici_bytes=800, dcn_bytes=200),
        _plan_row("spec:dp=*,tp=2", "dp=*,tp=2", 0.01, 10,
                  status="inadmissible",
                  detector_problems=["seeded structural finding"]),
    ]
    ranking = plan.rank_rows(rows)
    return {"schema": plan.PLAN_SCHEMA, "jax": plan._jax_version(),
            "topology": "v5e:2x2", "generation": "v5e",
            "objective": "step + wire", "slice_counts": [1, 2],
            "candidates": rows, "skips": [], "ranking": ranking,
            "winner": rows[0], "verdicts": plan.compute_verdicts(rows)}


class TestPlanner:
    def test_scaled_topology(self):
        assert plan._scaled_topology("v5e:2x2", 1) == "v5e:2x2"
        assert plan._scaled_topology("v5e:2x2", 2) == "v5e:2x4"
        assert plan._scaled_topology("v4:2x2x2", 4) == "v4:2x2x8"

    def test_enumerate_candidates_includes_fused_variants(self):
        """The planner carries the bucketed-fusion modifiers (dp and
        dp+zero1) at the registry threshold, on every slice count — so
        overlap potential participates in predicted_total_ms ranking."""
        for n_slices in (1, 2):
            cands = plan.enumerate_candidates(8, n_slices)
            fused = [c for c in cands if "fusion_threshold" in c]
            assert len(fused) == 2
            assert all(c["fusion_threshold"] == 131072 for c in fused)
            assert {c.get("weight_update") for c in fused} == \
                {None, "zero1"}

    def test_rank_rows_excludes_inadmissible_and_is_total(self):
        rows = _plan_report()["candidates"]
        ranking = plan.rank_rows(rows)
        assert ranking[0] == "spec:dp=*"          # lowest admissible total
        assert "spec:dp=*,tp=2" not in ranking    # 0.01 ms but flagged
        assert ranking == plan.rank_rows(list(reversed(rows)))

    def test_verdicts_hold_on_synthetic_rows(self):
        v = plan.compute_verdicts(_plan_report()["candidates"])
        assert v["zero1_bytes"]["holds"] is True       # 300 < 600
        assert v["wire_bytes"]["holds"] is True        # 0.03 < 0.05 totals
        assert v["dcn_split"]["holds"] is True         # 0.025>0.004, 200<800
        # missing rows degrade to holds=None, never a crash
        assert plan.compute_verdicts([])["zero1_bytes"]["holds"] is None

    def test_check_clean_then_catches_tampering(self, tmp_path):
        import copy as copy_lib

        path = str(tmp_path / "plan_report.json")
        report = _plan_report()
        with open(path, "w") as f:
            json.dump(report, f)
        assert plan.check(path) == []

        tampered = copy_lib.deepcopy(report)
        tampered["ranking"] = list(reversed(tampered["ranking"]))
        with open(path, "w") as f:
            json.dump(tampered, f)
        assert any("ranking drift" in p for p in plan.check(path))

        tampered = copy_lib.deepcopy(report)
        tampered["verdicts"]["zero1_bytes"]["holds"] = False
        with open(path, "w") as f:
            json.dump(tampered, f)
        assert any("disagree" in p for p in plan.check(path))

    def test_check_flags_verdict_that_stopped_holding(self, tmp_path):
        """A verdict that re-derives to holds=False is a FINDING — the
        rows contradict the pinned PERF direction."""
        report = _plan_report()
        for r in report["candidates"]:
            if r["name"] == "spec:dp=*+zero1":
                r["comm_bytes"] = 100      # now dp moves MORE bytes
        report["verdicts"] = plan.compute_verdicts(report["candidates"])
        report["ranking"] = plan.rank_rows(report["candidates"])
        report["winner"] = next(r for r in report["candidates"]
                                if r["name"] == report["ranking"][0])
        path = str(tmp_path / "plan_report.json")
        with open(path, "w") as f:
            json.dump(report, f)
        assert any("does NOT hold" in p for p in plan.check(path))

    def test_seeded_ranking_positive(self):
        report = _plan_report()
        assert plan._seeded_ranking_positive(report) == []
        thin = dict(report, candidates=report["candidates"][:1],
                    ranking=report["ranking"][:1])
        assert any("cross-checked" in p
                   for p in plan._seeded_ranking_positive(thin))

    def test_version_skew_skips(self, tmp_path):
        report = _plan_report()
        report["jax"] = "0.0.0-some-other-jax"
        path = str(tmp_path / "plan_report.json")
        with open(path, "w") as f:
            json.dump(report, f)
        assert plan.check(path) == []

    def test_missing_report_is_a_finding(self, tmp_path):
        problems = plan.check(str(tmp_path / "nope.json"))
        assert any("tune plan" in p for p in problems)

    def test_shipped_report_passes_check(self):
        """The committed plan report must stay re-derivable — the same
        leg the analysis gate runs."""
        path = plan.default_report_path()
        if not os.path.exists(path):
            pytest.skip("no shipped plan report")
        assert plan.check(path) == []


class TestResolveSpec:
    """db.resolve_spec: env > DB > default, generation-gated like every
    other tuned knob — CPU tier-1 runs must never see a planned spec."""

    @pytest.fixture
    def seeded(self, tmp_path, monkeypatch):
        path = str(tmp_path / "tune_db.json")
        db = tune_db.TuningDB(path)
        db.add({"program": "train_lm_tiny", "family": "plan_spec",
                "fingerprint": "f" * 32, "topology": "v5e:2x2",
                "generation": "v5e", "config": {"spec": "dp=*,ep=2"},
                "predicted": {"predicted_ms": 0.03, "source": "planned"}})
        db.save()
        monkeypatch.setenv("TPUFRAME_TUNE_DB", path)
        for var in ("TPUFRAME_SPEC", "TPUFRAME_TUNE_GEN",
                    "PALLAS_AXON_TPU_GEN"):
            monkeypatch.delenv(var, raising=False)

    def test_no_generation_no_resolution(self, seeded):
        assert tune_db.resolve_spec("train_lm_tiny") is None

    def test_generation_gated_resolution(self, seeded, monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        assert tune_db.resolve_spec("train_lm_tiny") == "dp=*,ep=2"
        # unknown program falls back to the family winner
        assert tune_db.resolve_spec("other_prog") == "dp=*,ep=2"

    def test_env_spec_abstains(self, seeded, monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        monkeypatch.setenv("TPUFRAME_SPEC", "dp=4")
        assert tune_db.resolve_spec("train_lm_tiny") is None

    def test_env_overrides_carries_spec(self, seeded):
        db = tune_db.TuningDB.open()
        rec = db.best(family="plan_spec")
        assert rec.env_overrides()["TPUFRAME_SPEC"] == "dp=*,ep=2"
