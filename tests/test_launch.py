"""Launch layer (L5/L6): provisioning command builders + the local
multi-process fake cluster (SURVEY.md §7 test strategy: distributed tests via
multi-process CPU jax — N host processes, forced host devices, no TPU)."""

import os
import sys
import textwrap

import pytest

from tpuframe.launch import LocalCluster, SliceConfig, SliceLauncher, emit_scripts


def test_slice_commands():
    cfg = SliceConfig(name="pod", zone="us-central2-b", accelerator="v4-32",
                      project="proj", labels={"team": "ml"})
    create = cfg.create_cmd()
    assert create[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "create",
                          "pod"]
    assert "--accelerator-type=v4-32" in create
    assert "--project=proj" in create
    assert "--labels=team=ml" in create
    assert cfg.delete_cmd()[-1] == "--quiet"
    # v4-32: suffix counts TensorCores → 16 chips → 4 hosts (4 chips/host).
    assert cfg.num_chips == 16
    assert cfg.num_workers == 4

    ssh = cfg.ssh_cmd("python train.py", env={"A": "b c"})
    assert ssh[4] == "ssh" and "--worker=all" in ssh
    assert ssh[-1] == "A='b c' python train.py"

    scp = cfg.scp_cmd(".", "~/tpuframe")
    assert "scp" in scp and "pod:~/tpuframe" in scp


def test_worker_counts():
    # v2/v3/v4/v5p accelerator suffixes count TensorCores (2/chip, 8/host);
    # v5e/v6e suffixes count chips (8/host).  See SliceConfig comments.
    assert SliceConfig("a", accelerator="v4-8").num_chips == 4
    assert SliceConfig("a", accelerator="v4-8").num_workers == 1
    assert SliceConfig("a", accelerator="v3-8").num_workers == 1
    assert SliceConfig("a", accelerator="v3-32").num_workers == 4
    assert SliceConfig("a", accelerator="v5p-16").num_chips == 8
    assert SliceConfig("a", accelerator="v5p-16").num_workers == 2
    assert SliceConfig("a", accelerator="v5litepod-16").num_chips == 16
    assert SliceConfig("a", accelerator="v5litepod-16").num_workers == 2
    assert SliceConfig("a", accelerator="v6e-8").num_workers == 1


def test_emit_scripts(tmp_path):
    cfg = SliceConfig(name="pod")
    paths = emit_scripts(cfg, str(tmp_path))
    text = open(paths["provision.sh"]).read()
    assert "gcloud compute tpus tpu-vm create pod" in text
    assert "scp" in text
    teardown = open(paths["teardown.sh"]).read()
    assert "delete pod" in teardown


def test_slice_launcher_dry_run():
    cmd = SliceLauncher(SliceConfig("pod"), dry_run=True).launch(
        "python -m tpuframe.train --config imagenet_resnet50_pod")
    assert "--worker=all" in cmd
    assert "TPUFRAME_MULTIHOST=1" in cmd[-1]


@pytest.mark.slow
def test_local_cluster_spmd():
    """2 processes x 2 devices: rendezvous, global device view, cross-host
    collective — the hvd.init()+allreduce capability bar (SURVEY.md §4.3)."""
    script = textwrap.dedent("""
        import jax, numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P
        from tpuframe.parallel import bootstrap, mesh as mesh_lib
        bootstrap.initialize()
        assert jax.process_count() == 2
        assert jax.device_count() == 4
        mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=4))
        sharding = NamedSharding(mesh, P(("data", "fsdp")))
        local = np.full((2, 3), 1.0 + jax.process_index(), np.float32)
        arr = jax.make_array_from_process_local_data(sharding, local, (4, 3))
        total = jax.jit(lambda a: a.sum(),
                        out_shardings=NamedSharding(mesh, P()))(arr)
        # ranks contribute 1s and 2s: sum = 2*3*1 + 2*3*2 = 18
        assert float(total) == 18.0, float(total)
        # cross_replica_mean: genuinely per-process values -> global mean
        # (the hvd.allreduce(metric) eval path, SURVEY.md §4.5).
        from tpuframe.parallel import collectives
        m = collectives.cross_replica_mean(
            {"acc": 1.0 + jax.process_index()})
        assert abs(float(m["acc"]) - 1.5) < 1e-6, float(m["acc"])
        print("rank", jax.process_index(), "OK")
    """)
    results = LocalCluster(2, 2, timeout=300).launch(
        [sys.executable, "-c", script])
    assert all("OK" in r.stdout for r in results)


@pytest.mark.slow
def test_local_cluster_object_collectives():
    """hvd.broadcast_object / hvd.allgather_object across REAL processes:
    ragged picklable payloads (dict vs string of different sizes) —
    Horovod's metadata-sync verbs (sampler state, vocab tables)."""
    script = textwrap.dedent("""
        import jax
        from tpuframe.parallel import bootstrap, hvd
        bootstrap.initialize()
        r = jax.process_index()
        got = hvd.broadcast_object({"epoch": 7, "note": "x" * 100} if r == 0
                                   else None, root_rank=0)
        assert got == {"epoch": 7, "note": "x" * 100}, got
        rows = hvd.allgather_object(
            {"rank": r, "payload": "y" * (10 + 200 * r)})
        assert [x["rank"] for x in rows] == [0, 1], rows
        assert len(rows[1]["payload"]) == 210
        print("rank", r, "OBJ-OK")
    """)
    results = LocalCluster(2, 2, timeout=300).launch(
        [sys.executable, "-c", script])
    assert all("OBJ-OK" in r.stdout for r in results)


@pytest.mark.slow
def test_local_cluster_failure_surfaces():
    with pytest.raises(RuntimeError, match="rank 1"):
        LocalCluster(2, 1, timeout=300).launch([
            sys.executable, "-c",
            "import os, sys; sys.exit(int(os.environ['TPUFRAME_PROCESS_ID']))",
        ])


@pytest.mark.slow
def test_replicated_restore_reads_storage_only_on_primary(tmp_path):
    """Primary-read + interconnect-broadcast restore (SURVEY.md §4.4 parity
    with rank-0 torch.load + hvd.broadcast_parameters): for fully-replicated
    leaves only process 0 may touch the checkpoint files; every other
    process must receive the bytes via collectives.primary_device_put and
    still reconstruct identical values (incl. a PRNG key leaf)."""
    script = textwrap.dedent("""
        import jax, numpy as np
        from tpuframe.parallel import bootstrap, mesh as mesh_lib
        bootstrap.initialize()
        mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=4))
        from tpuframe.ckpt import checkpoint as ck
        repl = mesh_lib.replicated_sharding(mesh)
        w = np.arange(12, dtype=np.float32).reshape(3, 4)
        flags = np.array([True, False, True])
        state = {
            "w": mesh_lib.host_device_put(w, repl),
            "flags": mesh_lib.host_device_put(flags, repl),
            "rng": mesh_lib.host_device_put(jax.random.key(7), repl),
        }
        ck.save(%(d)r, 1, state)
        ck._barrier()  # COMMIT is written by process 0 after save's barrier

        calls = {"n": 0}
        orig = ck._load_shard
        def counting(*a, **k):
            calls["n"] += 1
            return orig(*a, **k)
        ck._load_shard = counting
        out = ck.restore(%(d)r, 1, mesh=mesh, target=state)
        np.testing.assert_array_equal(np.asarray(out["w"]), w)
        np.testing.assert_array_equal(np.asarray(out["flags"]), flags)
        assert np.asarray(jax.random.key_data(out["rng"])).tolist() == \\
            np.asarray(jax.random.key_data(jax.random.key(7))).tolist()
        if jax.process_index() == 0:
            assert calls["n"] > 0, "primary must read the checkpoint"
        else:
            assert calls["n"] == 0, \\
                f"non-primary hit storage {calls['n']} times"

        # Device-order robustness: real TPU meshes reorder devices to the
        # ICI torus, so the broadcast must work when the target mesh's
        # order differs from jax.devices() order.
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from tpuframe.parallel import collectives
        rev = Mesh(np.asarray(jax.devices()[::-1]), ("data",))
        payload = w if jax.process_index() == 0 else np.zeros_like(w)
        got = collectives.primary_device_put(
            payload, NamedSharding(rev, P()))
        np.testing.assert_array_equal(np.asarray(got), w)
        print("rank", jax.process_index(), "BCAST_OK")
    """) % {"d": str(tmp_path / "bck")}
    results = LocalCluster(2, 2, timeout=600).launch(
        [sys.executable, "-c", script])
    assert all("BCAST_OK" in r.stdout for r in results)


def test_run_with_relaunch_retries_then_succeeds():
    from tpuframe.launch.launcher import run_with_relaunch

    calls = {"n": 0}

    def run_once():
        calls["n"] += 1
        return 13 if calls["n"] < 3 else 0  # stall-abort rc twice, then ok

    msgs = []
    assert run_with_relaunch(run_once, 5, log=msgs.append,
                             sleep=lambda s: None) == 0
    assert calls["n"] == 3
    assert any("relaunch 2/5" in m for m in msgs)
    # budget exhausted: the last nonzero rc propagates
    calls["n"] = -10
    assert run_with_relaunch(run_once, 2, log=msgs.append,
                             sleep=lambda s: None) == 13


@pytest.mark.slow
def test_launch_cli_relaunch_resumes_crashed_job(tmp_path):
    """The supervisor loop end to end: a fault-injected job dies mid-run
    (exit 42) under `launch local --relaunch 1`; the relaunched job
    auto-resumes from the committed checkpoint and finishes."""
    import subprocess

    env = dict(os.environ)
    env.update({"TPUFRAME_FAULTS": "host:step=6:kind=crash:once=1"})
    proc = subprocess.run(
        [sys.executable, "-m", "tpuframe.launch", "local",
         "--nprocs", "2", "--devices", "2", "--relaunch", "1", "--",
         sys.executable, "-m", "tpuframe.train", "--config", "smoke",
         "--set", "total_steps=8", "--set", "ckpt_every=4",
         "--set", "log_every=4", "--set", "eval_every=1000",
         "--set", "global_batch=16",
         "--ckpt-dir", str(tmp_path / "ck")],
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout[-1500:] + proc.stderr[-800:]
    assert "relaunch 1/1" in proc.stdout
    assert "resumed from step 4" in proc.stdout


@pytest.mark.slow
def test_async_save_multihost_polling_finalize(tmp_path):
    """async_write on a 2-host cluster: no barrier anywhere in the save
    path — process 0's background worker finalizes by polling the other
    host's CRC sidecar; COMMIT appears, restore round-trips."""
    script = textwrap.dedent("""
        import jax, numpy as np
        from tpuframe.parallel import bootstrap, mesh as mesh_lib
        bootstrap.initialize()
        mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=4))
        from tpuframe.ckpt import checkpoint as ck
        repl = mesh_lib.replicated_sharding(mesh)
        w = np.arange(8, dtype=np.float32)
        state = {"w": mesh_lib.host_device_put(w, repl)}
        mgr = ck.CheckpointManager(%(d)r, every_steps=1, async_write=True)
        mgr.save(1, state)
        mgr.save(2, state)
        # wait_pending's contract: joins local workers AND (on non-primary
        # hosts) polls for process 0's COMMIT — durable on every host after.
        mgr.wait_pending()
        import os
        assert os.path.exists(%(d)r + "/step_00000002/COMMIT")
        step, out = mgr.restore_latest(mesh=mesh, target=state)
        assert step == 2, step
        np.testing.assert_array_equal(np.asarray(out["w"]), w)
        print("rank", jax.process_index(), "ASYNC_OK")
    """) % {"d": str(tmp_path / "ack")}
    results = LocalCluster(2, 2, timeout=420).launch(
        [sys.executable, "-c", script])
    assert all("ASYNC_OK" in r.stdout for r in results)


@pytest.mark.slow
def test_many_leaf_replicated_restore_no_deadlock(tmp_path):
    """Regression: per-leaf broadcast restore deadlocked once the tree had
    enough leaves for the placeholder ranks to race ~30 collective programs
    ahead of the file-reading primary (pod resume hung exactly this way).
    A 300-leaf replicated tree must restore through the ONE packed
    collective, bit-exact, within the cluster timeout."""
    script = textwrap.dedent("""
        import jax, numpy as np
        from tpuframe.parallel import bootstrap, mesh as mesh_lib
        bootstrap.initialize()
        mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=4))
        from tpuframe.ckpt import checkpoint as ck
        repl = mesh_lib.replicated_sharding(mesh)
        rng = np.random.default_rng(0)
        host = {f"layer_{i:03d}": {
                    "w": rng.normal(size=(4, 5)).astype(np.float32),
                    "step": np.int32(i)}
                for i in range(150)}
        state = jax.tree.map(
            lambda a: mesh_lib.host_device_put(a, repl), host)
        ck.save(%(d)r, 3, state)
        ck._barrier()
        out = ck.restore(%(d)r, 3, mesh=mesh, target=state)
        flat_out = jax.tree.leaves(out)
        flat_ref = jax.tree.leaves(host)
        assert len(flat_out) == 300
        for a, b in zip(flat_out, flat_ref):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        print("rank", jax.process_index(), "MANYLEAF_OK")
    """) % {"d": str(tmp_path / "bck")}
    results = LocalCluster(2, 2, timeout=420).launch(
        [sys.executable, "-c", script])
    assert all("MANYLEAF_OK" in r.stdout for r in results)


@pytest.mark.slow
def test_pod_config_multihost_kill_and_reshard_resume(tmp_path):
    """Config 5's actual shape, rehearsed multi-host (SURVEY.md §7 hard
    part 3): ``imagenet_resnet50_pod`` (scaled-down steps/shapes, synthetic
    data) on a 4-host x 2-device cluster, hard-killed mid-run, then resumed
    on a 2-host x 2-device cluster — a checkpoint written by 4 processes
    restored by 2 (cross-process reshard-on-restore), continuing to the
    exact final step."""
    overrides = [
        "--set", "total_steps=8", "--set", "ckpt_every=4",
        "--set", "global_batch=32", "--set", "log_every=4",
        "--set", "eval_every=1000", "--set", "warmup_steps=2",
        "--set", "compute_dtype='float32'",
        "--set", "dataset_kwargs={'image_size': 32, 'synthetic_size': 64, "
                 "'num_classes': 100}",
        "--set", "model_kwargs={'cifar_stem': True, 'num_classes': 100}",
        "--ckpt-dir", str(tmp_path / "ck"),
    ]
    argv = [sys.executable, "-m", "tpuframe.train",
            "--config", "imagenet_resnet50_pod"] + overrides

    # Phase 1: the whole 4-host pod dies as a unit at step 6 (after the
    # step-4 checkpoint committed).
    with pytest.raises(RuntimeError, match="exit 42"):
        LocalCluster(4, 2, timeout=600,
                     extra_env={"TPUFRAME_FAULTS": "host:step=6:kind=crash"}).launch(argv)
    committed = sorted(p.name for p in (tmp_path / "ck").iterdir()
                       if p.is_dir() and (p / "COMMIT").exists())
    assert "step_00000004" in committed, committed

    # Phase 2: restart on HALF the hosts — resume must reshard and finish.
    results = LocalCluster(2, 2, timeout=600).launch(argv)
    assert "resumed from step 4" in results[0].stdout, \
        results[0].stdout[-1500:]
    assert "[train 8]" in results[0].stdout


@pytest.mark.slow
def test_local_cluster_harness_end_to_end():
    """The full train.py on a 2-host x 2-device fake cluster — config 5's
    launch shape (SURVEY.md §4.2) without a pod."""
    results = LocalCluster(2, 2, timeout=500).launch([
        sys.executable, "-m", "tpuframe.train", "--config", "smoke",
        "--set", "total_steps=6", "--set", "log_every=3",
        "--set", "eval_every=6", "--set", "eval_batches=1",
        "--set", "global_batch=16",
    ])
    assert "done in" in results[0].stdout       # rank 0 logs
    assert "done in" not in results[1].stdout   # others gated
