"""Host-side units of bench.py: the watchdog's last-measured annotation
source and the relay probe (the driver-metric path must degrade
truthfully — a wrong 'best recorded' or a fabricated probe verdict would
poison BENCH_r* artifacts)."""

import importlib.util
import json
import socket
import threading

import pytest


@pytest.fixture()
def bench(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location(
        "bench", __file__.rsplit("/tests/", 1)[0] + "/bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write(results, name, rec):
    (results / name).write_text(json.dumps(rec) + "\n")


def test_best_recorded_skips_degraded_and_takes_max(bench, monkeypatch,
                                                    tmp_path):
    results = tmp_path / "perf" / "results"
    results.mkdir(parents=True)
    # _best_recorded roots its glob at dirname(bench.__file__): point the
    # module, not the global os.path, at the sandbox.
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    _write(results, "bench_a.out", {"value": 2000.0})
    _write(results, "bench_b.out", {"value": 2385.2})
    _write(results, "bench_c.out", {"value": 9999.0, "degraded": True})
    _write(results, "bench_d.out", {"no_value": 1})
    (results / "bench_junk.out").write_text("not json\n")
    assert bench._best_recorded() == 2385.2


def test_best_recorded_none_when_nothing_real(bench, monkeypatch, tmp_path):
    results = tmp_path / "perf" / "results"
    results.mkdir(parents=True)
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    _write(results, "bench_a.out", {"value": 0.0, "degraded": True})
    assert bench._best_recorded() is None


def test_relay_probe_none_outside_loopback_env(bench, monkeypatch):
    monkeypatch.delenv("AXON_LOOPBACK_RELAY", raising=False)
    assert bench._relay_probe() is None


def test_relay_probe_up_down(bench, monkeypatch):
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    def accept_quietly():
        try:
            srv.accept()
        except OSError:
            pass  # listener closed after the probe — expected

    t = threading.Thread(target=accept_quietly, daemon=True)
    t.start()
    try:
        assert bench._relay_probe(ports=(port,)) is True
    finally:
        srv.close()
    # Socket closed: the same port now refuses -> probe says down.
    assert bench._relay_probe(ports=(port,)) is False
