"""tpuframe.analysis — the offline static SPMD/collective lint.

Each layer is tested against a *seeded defect* plus its clean twin:

  Layer 1 (HLO):   a mis-sharded matmul whose contraction dim is sharded
                   materializes a full all-gather that the dp budget never
                   declared; the correctly sharded twin emits nothing.
  Layer 2 (jaxpr): a bf16 step with one hidden ``.astype(float32)`` off
                   the MXU path; a captured host constant; a donation
                   alias table diffed against its declaration.
  Layer 3 (AST):   one snippet per rule (TF101-TF104) that must fire,
                   a clean twin that must not, and the suppression
                   contract — plus the shipped ``tpuframe/`` tree, which
                   must self-lint clean (the CI gate's fast half).

Also here: the per-strategy budget audits over the REAL step programs
(skipping strategies this jax cannot express), the KNOWN_VMEM_EXCLUSIONS
registry cross-check, and the legacy-shard_map dp numerical parity run
referenced by tpuframe/parallel/step.py (check_rep=False disables the
psum-transpose rewrite; the explicit grad reduction must keep the dp
step bit-comparable to the single-device step).
"""

import pathlib
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuframe.analysis import (budgets, hlo_audit, jaxpr_checks,
                               source_lint, strategies)
from tpuframe.obs import spmd_check
from tpuframe.parallel import mesh as mesh_lib, step as step_lib


# ---------------------------------------------------------------------------
# Layer 1 mechanism: parsing HLO / StableHLO text.
# ---------------------------------------------------------------------------


def test_parse_collectives_kinds_and_bytes():
    txt = """
      %ar = f32[4,25]{1,0} all-reduce(%x), replica_groups={{0,1}}
      %ag = bf16[1024,1024]{1,0} all-gather(%y), dimensions={0}
      %cp = f32[128,128]{1,0} collective-permute(%z)
      %aa = f32[64,64]{1,0} all-to-all(%w)
    """
    rep = hlo_audit.parse_collectives(txt)
    by = rep.bytes_by_kind()
    assert by["all-reduce"] == 400
    assert by["all-gather"] == 1024 * 1024 * 2
    assert by["collective-permute"] == 128 * 128 * 4
    assert by["all-to-all"] == 64 * 64 * 4
    ar = [op for op in rep.ops if op.kind == "all-reduce"][0]
    assert ar.replica_groups == "{0,1}"


def test_parse_collectives_async_forms():
    # -start tuples alias the operand (halved); all-gather-start keeps the
    # gathered output; -done must not double count.
    txt = """
      %s = (f32[100]{0}, f32[100]{0}) all-reduce-start(%x)
      %d = f32[100]{0} all-reduce-done(%s)
      %g = (f32[8,16]{1,0}, f32[64,16]{1,0}) all-gather-start(%y)
      %gd = f32[64,16]{1,0} all-gather-done(%g)
    """
    rep = hlo_audit.parse_collectives(txt)
    assert rep.count_by_kind() == {"all-reduce": 1, "all-gather": 1}
    assert rep.bytes_by_kind()["all-reduce"] == 400
    assert rep.bytes_by_kind()["all-gather"] == 64 * 16 * 4


def test_parse_collectives_reduce_scatter_counts_operand():
    # The full operand crosses the wire even though the result is the
    # scattered shard.
    txt = "%rs = f32[16,128]{1,0} reduce-scatter(f32[128,128]{1,0} %x)"
    rep = hlo_audit.parse_collectives(txt)
    assert rep.bytes_by_kind()["reduce-scatter"] == 128 * 128 * 4


def test_parse_collectives_stablehlo_form():
    txt = ('%0 = "stablehlo.all_reduce"(%arg0) ({...}) '
           '{replica_groups = dense<[[0,1,2,3]]>} '
           ': (tensor<128x256xf32>) -> tensor<128x256xf32>')
    rep = hlo_audit.parse_collectives(txt)
    assert rep.bytes_by_kind() == {"all-reduce": 128 * 256 * 4}


def test_legacy_allreduce_payload_surface():
    # perf/_hlo_parse.py promotion: the legacy shape of the API survives.
    payload, ops = hlo_audit.allreduce_payload(
        "%r = (bf16[100]{0}, f32[10]{0}) all-reduce(%a, %b)")
    assert payload == {"bf16": 200, "f32": 40} and ops == 1


# ---------------------------------------------------------------------------
# Layer 1 policy: budgets.
# ---------------------------------------------------------------------------


def _report(txt):
    return hlo_audit.parse_collectives(textwrap.dedent(txt))


def test_budget_flags_undeclared_kind():
    rep = _report("%cp = f32[1024,1024]{1,0} collective-permute(%x)")
    v = budgets.check_budget(rep, budgets.dp_budget(1 << 20))
    assert len(v) == 1 and "undeclared collective kind" in v[0]
    assert "collective-permute" in v[0]


def test_budget_flags_cap_exceeded():
    rep = _report("%ar = f32[4096,4096]{1,0} all-reduce(%x)")  # 64 MB
    v = budgets.check_budget(rep, budgets.dp_budget(1 << 20))  # cap 2 MB
    assert len(v) == 1 and "budget exceeded" in v[0]


def test_budget_ignore_floor_and_clean_pass():
    rep = _report("""
      %m = f32[1]{0} all-reduce(%metric)
      %cp = f32[16]{0} collective-permute(%tiny)
      %g = f32[131072]{0} all-reduce(%grads)
    """)
    # Sub-floor metric scalars and stray tiny ops never violate; the
    # param-sized all-reduce fits its declaration.
    assert budgets.check_budget(rep, budgets.dp_budget(512 * 1024)) == []


def test_budget_total_cap():
    rep = _report("%ar = f32[1048576]{0} all-reduce(%x)")  # 4 MB
    b = budgets.CommBudget(name="t", allowed={"all-reduce": None},
                           max_total_bytes=1 << 20)
    v = budgets.check_budget(rep, b)
    assert len(v) == 1 and "total collective bytes" in v[0]


def test_budget_rejects_unknown_kind_declaration():
    with pytest.raises(ValueError, match="unknown collective kind"):
        budgets.CommBudget(name="t", allowed={"all-scatter": 1})


def test_strategy_budget_dispatch():
    b = budgets.strategy_budget("dp", param_bytes=100)
    assert b.allowed["all-reduce"] == 200
    with pytest.raises(ValueError, match="no declared budget"):
        budgets.strategy_budget("zmq-parallel")


# ---------------------------------------------------------------------------
# Layer 1 end to end: the seeded mis-sharding.
# ---------------------------------------------------------------------------


def _matmul_program(mesh, w_spec):
    xs = NamedSharding(mesh, P("data", None))
    ws = NamedSharding(mesh, w_spec)
    out = NamedSharding(mesh, P("data", None))
    x = jax.ShapeDtypeStruct((1024, 1024), jnp.float32, sharding=xs)
    w = jax.ShapeDtypeStruct((1024, 1024), jnp.float32, sharding=ws)
    return jax.jit(lambda x, w: x @ w, out_shardings=out), (x, w)


def test_missharded_matmul_breaks_dp_budget(mesh8):
    # w sharded along the CONTRACTING dim while x's columns are
    # replicated: GSPMD must materialize the full 4 MB weight all-gather
    # — the exact class of silent mistake the gate exists to catch.
    jitted, args = _matmul_program(mesh8, P("data", None))
    report, _ = hlo_audit.audit_jitted(jitted, *args)
    assert report.bytes_by_kind(1 << 20).get("all-gather", 0) \
        == 1024 * 1024 * 4
    v = budgets.check_budget(report, budgets.dp_budget(64 * 1024))
    assert v and "all-gather" in v[0]


def test_well_sharded_matmul_passes_dp_budget(mesh8):
    jitted, args = _matmul_program(mesh8, P())
    report, _ = hlo_audit.audit_jitted(jitted, *args)
    assert budgets.check_budget(report, budgets.dp_budget(64 * 1024)) == []


# ---------------------------------------------------------------------------
# Layer 2: jaxpr checks.
# ---------------------------------------------------------------------------


def test_find_f32_matmuls_catches_hidden_upcast():
    def bad_step(x, w1, w2):
        h = jnp.tanh(x @ w1)
        # The seeded defect: one matmul quietly runs in f32.
        return (h.astype(jnp.float32) @ w2.astype(jnp.float32)).sum()

    x = jnp.zeros((8, 16), jnp.bfloat16)
    w = jnp.zeros((16, 16), jnp.bfloat16)
    traced = jax.make_jaxpr(bad_step)(x, w, w)
    assert jaxpr_checks.has_bf16(traced)
    findings = jaxpr_checks.find_f32_matmuls(traced)
    assert len(findings) == 1
    assert findings[0].primitive == "dot_general"
    assert "float32" in findings[0].dtypes


def test_find_f32_matmuls_clean_bf16_step():
    def good_step(x, w1, w2):
        # f32 accumulation of the LOSS is legitimate — only MXU ops count.
        return (jnp.tanh(x @ w1) @ w2).astype(jnp.float32).sum()

    x = jnp.zeros((8, 16), jnp.bfloat16)
    w = jnp.zeros((16, 16), jnp.bfloat16)
    traced = jax.make_jaxpr(good_step)(x, w, w)
    assert jaxpr_checks.has_bf16(traced)
    assert jaxpr_checks.find_f32_matmuls(traced) == []


def test_find_large_constants():
    baked = np.ones((600, 600), np.float32)  # 1.44 MB closed over

    def leaky(x):
        return (x * jnp.asarray(baked)).sum()

    traced = jax.make_jaxpr(leaky)(jnp.zeros((600, 600), jnp.float32))
    findings = jaxpr_checks.find_large_constants(traced)
    assert findings and findings[0].nbytes == 600 * 600 * 4
    # Below-threshold constants are not hoarded.
    assert jaxpr_checks.find_large_constants(traced, min_bytes=2 << 20) == []


def test_parse_input_output_alias():
    hlo = ("HloModule jit_step, input_output_alias={ {0}: (0, {}, "
           "may-alias), {1}: (2, {1}, must-alias) }, "
           "entry_computation_layout={...}")
    assert jaxpr_checks.parse_input_output_alias(hlo) == {0, 2}
    assert jaxpr_checks.parse_input_output_alias("HloModule bare") == set()


def test_donation_report_leak_accounting():
    rep = jaxpr_checks.audit_donation(
        "HloModule m, input_output_alias={ {0}: (1, {}, may-alias) }",
        declared={1, 3}, platform="tpu")
    assert rep.aliased == {1}
    assert rep.leaked == {3}
    assert rep.platform_supports
    assert "leaked=1" in str(rep)


def test_donation_audit_cpu_backend_honesty(mesh8):
    # XLA:CPU ignores donation — the audit must say "can't tell here"
    # instead of reporting a mass leak (the TPU AOT path gives the real
    # answer; see tests/test_aot_tpu_compile.py).
    jitted = jax.jit(lambda s: jax.tree.map(lambda a: a + 1, s),
                     donate_argnums=(0,))
    compiled = jitted.lower({"w": jnp.zeros((128, 128))}).compile()
    rep = jaxpr_checks.audit_donation(compiled, declared={0},
                                      platform="cpu")
    assert rep.platform_supports or not rep.aliased


# ---------------------------------------------------------------------------
# Layer 3: source lint.
# ---------------------------------------------------------------------------


def _rules(src):
    return [f.rule for f in source_lint.lint_source(textwrap.dedent(src))]


def test_tf101_host_conversion_in_jitted_code():
    assert _rules("""
        import jax, numpy as np

        @jax.jit
        def f(x):
            y = float(x)
            z = np.asarray(x)
            return x
    """) == ["TF101", "TF101"]


def test_tf101_item_method_and_jit_by_name():
    # g is traced because it is PASSED to jax.jit, not decorated.
    assert _rules("""
        import jax

        def g(x):
            return x.item()

        step = jax.jit(g)
    """) == ["TF101"]


def test_tf101_host_code_is_allowed_to_convert():
    assert _rules("""
        def report(metrics):
            return float(metrics["loss"])
    """) == []


def test_tf102_python_branch_on_array():
    assert _rules("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            if jnp.any(x > 0):
                return x
            return -x
    """) == ["TF102"]


def test_tf102_static_config_branch_is_fine():
    assert _rules("""
        import jax

        @jax.jit
        def f(x, axes=()):
            if axes:
                return x
            return -x
    """) == []


def test_tf103_timing_without_sync():
    assert _rules("""
        import time

        def bench(step, batch):
            t0 = time.perf_counter()
            step(batch)
            t1 = time.perf_counter()
            return t1 - t0
    """) == ["TF103"]


def test_tf103_sync_in_scope_is_clean():
    assert _rules("""
        import time
        import jax

        def bench(step, batch):
            t0 = time.perf_counter()
            jax.block_until_ready(step(batch))
            t1 = time.perf_counter()
            return t1 - t0
    """) == []


def test_tf104_pallas_call_must_decide_interpret():
    assert _rules("""
        from jax.experimental import pallas as pl

        def kernel_call(x):
            return pl.pallas_call(my_kernel, out_shape=x)(x)
    """) == ["TF104"]
    assert _rules("""
        from jax.experimental import pallas as pl

        def kernel_call(x):
            return pl.pallas_call(my_kernel, out_shape=x,
                                  interpret=_auto_interpret())(x)
    """) == []


def test_lint_suppression_contract():
    # Targeted suppression silences exactly its rule...
    assert _rules("""
        import jax

        @jax.jit
        def f(x):
            return float(x)  # tf-lint: ok[TF101]
    """) == []
    # ...a mismatched tag does not...
    assert _rules("""
        import jax

        @jax.jit
        def f(x):
            return float(x)  # tf-lint: ok[TF104]
    """) == ["TF101"]
    # ...and a def-line suppression covers the whole function.
    assert _rules("""
        import jax

        @jax.jit
        def f(x):  # tf-lint: ok
            return float(x)
    """) == []


def test_lint_nested_def_inherits_tracedness():
    assert _rules("""
        import jax

        @jax.jit
        def outer(x):
            def inner(y):
                return float(y)
            return inner(x)
    """) == ["TF101"]


def test_tf105_raw_gcs_call_outside_gcs_layer():
    src = """
        def fetch(bucket, key):
            return bucket.blob(key).download_as_bytes()
    """
    findings = source_lint.lint_source(textwrap.dedent(src),
                                       "tpuframe/data/loader.py")
    assert [f.rule for f in findings] == ["TF105"]
    # ...and uploads / listings too
    src2 = """
        def push(bucket, key, data):
            bucket.blob(key).upload_from_string(data)
            return list(client.list_blobs(bucket))
    """
    findings2 = source_lint.lint_source(textwrap.dedent(src2),
                                        "tpuframe/ckpt/uploader.py")
    assert [f.rule for f in findings2] == ["TF105", "TF105"]


def test_tf105_gcs_layer_itself_is_exempt():
    src = """
        def _read_bytes_once(path):
            return _client().bucket(b).blob(k).download_as_bytes(timeout=60)
    """
    assert source_lint.lint_source(textwrap.dedent(src),
                                   "tpuframe/data/gcs.py") == []


def test_tf105_unbounded_sleep_retry_loop():
    assert _rules("""
        import time

        def poll(path):
            while True:
                if fetch(path):
                    break
                time.sleep(1.0)
    """) == ["TF105"]


def test_tf105_bounded_retry_loops_are_clean():
    # a comparison (attempt bound) in the loop body makes it bounded...
    assert _rules("""
        import time

        def poll(path):
            attempt = 0
            while True:
                attempt += 1
                if attempt >= 5:
                    return None
                time.sleep(1.0)
    """) == []
    # ...as does reading a clock (deadline pattern), or raising
    assert _rules("""
        import time

        def poll(deadline):
            while True:
                now = time.monotonic()
                time.sleep(1.0)
    """) == []
    # and a non-`while True` loop never matches at all
    assert _rules("""
        import time

        def poll(tries):
            while tries:
                tries -= 1
                time.sleep(1.0)
    """) == []


def test_tf105_suppression():
    assert _rules("""
        import time

        def forever():
            while True:  # tf-lint: ok[TF105]
                time.sleep(60.0)
    """) == []


def test_tf107_print_and_clock_in_hot_path():
    src = textwrap.dedent("""
        import time

        def make_batch(it):
            t0 = time.time()
            batch = next(it)
            print("batch in", time.time() - t0)
            return batch
    """)
    findings = source_lint.lint_source(src, "tpuframe/data/pipeline.py")
    assert [f.rule for f in findings] == ["TF107", "TF107", "TF107"]
    # The identical code outside a hot-path module is host code doing
    # host things — no finding.
    assert source_lint.lint_source(src, "tpuframe/launch/launcher.py") == []


def test_tf107_print_in_traced_code_fires_anywhere():
    assert _rules("""
        import jax

        @jax.jit
        def step(x):
            print("loss", x)
            return x * 2
    """) == ["TF107"]


def test_tf107_obs_routed_instrumentation_is_clean():
    src = textwrap.dedent("""
        from tpuframe.obs import events, metrics

        def make_batch(it):
            batch = next(it)
            metrics.bump("data.batches")
            events.emit("step", step=0, wall_ms=1.0)
            return batch
    """)
    assert source_lint.lint_source(src, "tpuframe/data/pipeline.py") == []
    # Module-level clock reads (import-time, not per-step) don't fire.
    mod = "import time\n_T0 = time.time()\n"
    assert source_lint.lint_source(mod, "tpuframe/parallel/step.py") == []


def test_tf107_suppression():
    src = textwrap.dedent("""
        def debug_batch(b):
            print("shape", b)  # tf-lint: ok[TF107]
    """)
    assert source_lint.lint_source(src, "tpuframe/data/pipeline.py") == []


def test_tf111_thread_outside_sanctioned_modules():
    # A stray thread calling into collectives deadlocks a pod, so thread
    # creation is reviewable policy: only the background-work homes may
    # construct one (docs/DESIGN.md "Async checkpointing").
    src = textwrap.dedent("""
        import threading

        def uploader(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
    """)
    findings = source_lint.lint_source(src, "tpuframe/train.py")
    assert [f.rule for f in findings] == ["TF111"]
    for sanctioned in ("tpuframe/ckpt/checkpoint.py",
                       "tpuframe/data/pipeline.py",
                       "tpuframe/obs/heartbeat.py",
                       "tpuframe/launch/launcher.py"):
        assert source_lint.lint_source(src, sanctioned) == [], sanctioned


def test_tf111_bare_thread_import_and_module_level():
    src = textwrap.dedent("""
        from threading import Thread

        worker = Thread(target=print)
    """)
    findings = source_lint.lint_source(src, "tpuframe/parallel/step.py")
    assert [f.rule for f in findings] == ["TF111"]


def test_tf111_suppression():
    src = textwrap.dedent("""
        import threading

        def sampler():
            t = threading.Thread(target=print)  # tf-lint: ok[TF111]
            t.start()
    """)
    assert source_lint.lint_source(src, "tpuframe/obs/devmem.py") == []


def test_tf114_unlocked_mutation_in_lock_owning_class():
    # A class that owns a lock has declared its state shared; mutating
    # another attribute without holding the lock is the statically
    # visible race (the contract the ckpt/obs worker threads rely on).
    src = textwrap.dedent("""
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def push(self, x):
                self.items.append(x)

            def reset(self):
                self.items = []
    """)
    findings = source_lint.lint_source(src, "tpuframe/ckpt/worker.py")
    assert [f.rule for f in findings] == ["TF114", "TF114"]
    assert "push" in findings[0].message
    assert "reset" in findings[1].message
    # same source outside the background-thread modules: out of scope
    assert source_lint.lint_source(src, "tpuframe/train.py") == []


def test_tf114_locked_and_ctor_mutations_are_clean():
    src = textwrap.dedent("""
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def push(self, x):
                with self._lock:
                    self.items.append(x)
                    self.count = len(self.items)
    """)
    assert source_lint.lint_source(src, "tpuframe/ckpt/worker.py") == []
    # a class with no lock never opted in — nothing to check against
    lockless = textwrap.dedent("""
        class Plain:
            def bump(self):
                self.n = 1
    """)
    assert source_lint.lint_source(lockless,
                                   "tpuframe/ckpt/worker.py") == []


def test_tf114_worker_closure_runs_unlocked():
    # A nested def's body executes when the WORKER calls it, not where
    # it is defined — a lock held at definition time proves nothing.
    src = textwrap.dedent("""
        import threading

        class Manager:
            def __init__(self):
                self._lock = threading.Lock()
                self.errors = []

            def start(self):
                with self._lock:
                    def work():
                        self.errors.append("boom")
                    return work
    """)
    findings = source_lint.lint_source(src, "tpuframe/ckpt/manager.py")
    assert [f.rule for f in findings] == ["TF114"]
    assert "errors" in findings[0].message


def test_tf114_module_level_lock_guards_globals():
    src = textwrap.dedent("""
        import threading

        _lock = threading.Lock()
        _active = None

        def stop():
            global _active
            _active = None

        def start(x):
            global _active
            with _lock:
                _active = x
    """)
    findings = source_lint.lint_source(src, "tpuframe/obs/exporter.py")
    assert [f.rule for f in findings] == ["TF114"]
    assert "stop" in findings[0].message and "_active" in findings[0].message


def test_tf114_suppression():
    src = textwrap.dedent("""
        import threading

        class Recorder:
            def __init__(self):
                self._lock = threading.Lock()
                self.path = None

            def dump(self, p):
                self.path = p  # tf-lint: ok[TF114]
    """)
    assert source_lint.lint_source(src, "tpuframe/obs/flight.py") == []


def test_tf117_sync_barrier_in_traced_hot_path():
    # A block_until_ready inside a traced function in parallel/ serializes
    # the very overlap the schedule auditor scores — fires on both the
    # module-level and method spellings.
    src = textwrap.dedent("""
        import jax

        @jax.jit
        def step(x):
            y = x * 2
            jax.block_until_ready(y)
            return y.block_until_ready()
    """)
    findings = source_lint.lint_source(src, "tpuframe/parallel/step.py")
    assert [f.rule for f in findings] == ["TF117", "TF117"]
    # serve/engine.py is the other declared hot path.
    findings = source_lint.lint_source(src, "tpuframe/serve/engine.py")
    assert [f.rule for f in findings] == ["TF117", "TF117"]


def test_tf117_device_get_in_traced_hot_path():
    src = textwrap.dedent("""
        import jax

        @jax.jit
        def decode(tok):
            return jax.device_get(tok)
    """)
    findings = source_lint.lint_source(src, "tpuframe/serve/engine.py")
    assert [f.rule for f in findings] == ["TF117"]


def test_tf117_untraced_and_out_of_scope_are_clean():
    # The same barriers in an UNtraced driver loop are the legitimate
    # spelling (that's where obs timing is supposed to sync)...
    untraced = textwrap.dedent("""
        import jax

        def drive(step, x):
            out = step(x)
            jax.block_until_ready(out)
            return jax.device_get(out)
    """)
    assert source_lint.lint_source(
        untraced, "tpuframe/parallel/step.py") == []
    # ...and traced code outside the declared hot paths is not this
    # rule's business (TF101/TF107 own the general cases).
    traced = textwrap.dedent("""
        import jax

        @jax.jit
        def bench(x):
            jax.block_until_ready(x)
            return x
    """)
    assert source_lint.lint_source(traced, "tpuframe/obs/bench.py") == []


def test_tf117_suppression():
    src = textwrap.dedent("""
        import jax

        @jax.jit
        def step(x):
            jax.block_until_ready(x)  # tf-lint: ok[TF117]
            return x
    """)
    assert source_lint.lint_source(src, "tpuframe/parallel/step.py") == []


def test_tf118_raw_network_call_outside_fleet_seams():
    # Fleet traffic without a RetryPolicy is the raw-GCS bypass class at
    # the serving boundary: no backoff, no deadline, no obs counters.
    src = textwrap.dedent("""
        import socket
        import urllib.request

        def probe(url):
            with urllib.request.urlopen(url, timeout=1.0) as r:
                return r.read()

        def dial(host):
            return socket.create_connection((host, 80))
    """)
    findings = source_lint.lint_source(src, "tpuframe/serve/scheduler.py")
    assert [f.rule for f in findings] == ["TF118", "TF118"]
    # The sanctioned seams: the router's transport and the exporter.
    assert source_lint.lint_source(src, "tpuframe/serve/router.py") == []
    assert source_lint.lint_source(src, "tpuframe/obs/exporter.py") == []


def test_tf118_bare_and_http_client_shapes():
    src = textwrap.dedent("""
        from urllib.request import urlopen
        import http.client

        def fetch(url):
            return urlopen(url).read()

        def connect(host):
            return http.client.HTTPConnection(host)
    """)
    findings = source_lint.lint_source(src, "tpuframe/resilience/policy.py")
    assert [f.rule for f in findings] == ["TF118", "TF118"]


def test_tf118_non_client_socket_use_is_clean():
    # gethostname/socketpair are not fleet traffic — no finding.
    src = textwrap.dedent("""
        import socket

        def host():
            return socket.gethostname()
    """)
    assert source_lint.lint_source(src, "tpuframe/obs/events.py") == []


def test_tf118_suppression():
    src = textwrap.dedent("""
        import socket

        def free_port():
            with socket.socket() as s:  # tf-lint: ok[TF118]
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]
    """)
    assert source_lint.lint_source(src, "tpuframe/launch/launcher.py") == []


def test_shipped_tree_self_lints_clean():
    import tpuframe

    pkg = pathlib.Path(tpuframe.__file__).parent
    findings = source_lint.lint_paths([pkg])
    assert findings == [], "\n".join(map(str, findings))


# ---------------------------------------------------------------------------
# Strategy audits over the real step programs + registration surface.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", sorted(strategies.STRATEGIES))
def test_strategy_step_program_fits_declared_budget(name):
    audit = strategies.audit_strategy(name)
    if audit.status == "unavailable":
        pytest.skip(audit.reason)
    assert audit.status == "ok", str(audit)
    assert audit.report is not None and audit.budget is not None


def test_dp_audit_sees_the_gradient_allreduce():
    # Wire-level guard for the legacy-shard_map grad fix (parallel/step.py
    # check_rep note): the dp program must carry a param-sized gradient
    # all-reduce — silently-local gradients would show (almost) none.
    audit = strategies.audit_strategy("dp")
    if audit.status == "unavailable":
        pytest.skip(audit.reason)
    # Per-leaf reductions may each sit under the budget floor — the TOTAL
    # gradient traffic is the invariant, so no min_bytes filter here.
    ar = audit.report.bytes_by_kind().get("all-reduce", 0)
    assert ar >= audit.param_bytes, audit.report.summary()


def test_check_step_program_budget_registration(mesh8):
    # The startup hash check and the budget audit run off one lowering.
    good, good_args = _matmul_program(mesh8, P())
    spmd_check.check_step_program(good, "good-matmul", *good_args,
                                  budget=budgets.dp_budget(64 * 1024))
    bad, bad_args = _matmul_program(mesh8, P("data", None))
    with pytest.raises(RuntimeError, match="budget violation"):
        spmd_check.audit_step_program(bad, "bad-matmul", *bad_args,
                                      budget=budgets.dp_budget(64 * 1024))


def test_known_exclusion_registry_matches_gate():
    from tpuframe.ops import fused_conv_bn

    assert budgets.check_known_exclusions() == []
    # The registered shape really is excluded by the VMEM gate...
    s = budgets.KNOWN_VMEM_EXCLUSIONS[0]["shape"]
    assert not fused_conv_bn.supported(s["h"], s["w"], s["n"], s["k"],
                                       s["c"])
    # ...while the neighbouring ResNet-50 1x1 shapes still fit.
    assert fused_conv_bn.supported(h=14, w=14, n=256, k=1024, c=512)


# ---------------------------------------------------------------------------
# Numerical parity: the legacy-shard_map dp step vs the single-device
# step (the verification promised in tpuframe/parallel/step.py).
# ---------------------------------------------------------------------------


def test_dp_step_matches_single_device_step(mesh8):
    def loss_fn(params, model_state, b, rng):
        pred = jnp.tanh(b["x"] @ params["w1"]) @ params["w2"]
        return jnp.mean((pred - b["y"]) ** 2), ({}, {})

    k1, k2, k3, k4 = jax.random.split(jax.random.key(7), 4)
    params = {"w1": 0.1 * jax.random.normal(k1, (16, 32)),
              "w2": 0.1 * jax.random.normal(k2, (32, 4))}
    batch = {"x": jax.random.normal(k3, (32, 16)),
             "y": jax.random.normal(k4, (32, 4))}
    tx = optax.adam(1e-2)

    dp_step = step_lib.make_train_step(loss_fn, tx, mesh8, donate=False)
    ref_step = step_lib.make_train_step(loss_fn, tx, mesh=None,
                                        donate=False)
    dp_state = step_lib.TrainState.create(params, tx)
    ref_state = step_lib.TrainState.create(params, tx)
    for _ in range(3):
        dp_state, dp_metrics = dp_step(dp_state, batch)
        ref_state, ref_metrics = ref_step(ref_state, batch)

    np.testing.assert_allclose(dp_metrics["loss"], ref_metrics["loss"],
                               rtol=1e-5, atol=1e-7)
    for key in params:
        np.testing.assert_allclose(
            np.asarray(dp_state.params[key]),
            np.asarray(ref_state.params[key]),
            rtol=1e-5, atol=1e-6, err_msg=key)
