"""Chaos harness: the CPU-mesh train loop under scheduled fault
sequences (docs/DESIGN.md "Async checkpointing & the flush contract").

Three properties of the async checkpoint pipeline, each proven under a
deterministic injected fault instead of asserted from code reading:

  * slow storage moves OFF the step path — ``goodput.productive`` of a
    slow-GCS run matches the no-fault run and the save's ``block_ms``
    stays tiny while its full span ``ms`` absorbs the injected delay
    (sync saves eat the same delay ON the step path, for contrast);
  * exact-continuation resume — SIGTERM with an upload in flight exits
    rc 14 only after ``flush()`` commits, and the resumed run's final
    loss equals an uninterrupted run's;
  * no acknowledged-but-unwritten checkpoint — a worker crash mid-upload
    leaves an uncommitted dir and NO ``ckpt_save`` event; stitched
    across attempts, every ``ckpt_save`` event maps to a
    committed-or-quarantined directory.

Fault schedules are seeded through ``TPUFRAME_FAULTS`` (times=/delay_s=
budgets, no wall-clock races), so every run here is reproducible.  The
process-killing faults (crash, double-SIGTERM) run under a subprocess
supervisor; the goodput comparison runs in-process on the shared
8-device CPU mesh.
"""

import os
import subprocess
import sys
import time

import numpy as np
import optax
import pytest

import jax.numpy as jnp

from tpuframe import ckpt
from tpuframe import train as train_mod
from tpuframe.ckpt.checkpoint import in_flight_step, latest_step
from tpuframe.launch import launcher as launcher_mod
from tpuframe.obs import events, goodput
from tpuframe.obs import metrics
from tpuframe.obs import tracing
from tpuframe.parallel import step as step_lib
from tpuframe.resilience import RC_PREEMPTED, faults
from tpuframe.utils import get_config


@pytest.fixture(autouse=True)
def _clean_chaos_state(monkeypatch):
    monkeypatch.delenv("TPUFRAME_FAULTS", raising=False)
    monkeypatch.delenv("TPUFRAME_ASYNC_CKPT", raising=False)
    monkeypatch.delenv(events.ENV_DIR, raising=False)
    monkeypatch.delenv(events.ENV_ATTEMPT, raising=False)
    faults.reset_from_env()
    metrics.reset_counters("retry.")
    events.close()
    yield
    faults.reset_from_env({})
    metrics.reset_counters("retry.")
    events.close()


def _smoke_cfg(tmp_path, **over):
    over.setdefault("distributed", False)
    over.setdefault("log_every", 1000)
    over.setdefault("eval_every", 1000)
    over.setdefault("global_batch", 16)
    over.setdefault("ckpt_dir", str(tmp_path / "ck"))
    return get_config("smoke").with_overrides(**over)


def _run_train(workdir, *, steps, ckpt_every, attempt=0, extra_env=None,
               devices=4, sets=None):
    """One supervised training attempt in a subprocess (``devices`` CPU
    devices — per attempt, so elastic legs can resize the world), with
    its event log and checkpoint dir under ``workdir`` so relaunch
    attempts stitch into one stream.  ``sets`` overrides/extends the
    default ``--set`` config pairs."""
    env = dict(os.environ)
    env.pop("TPUFRAME_FAULTS", None)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={devices}")
    env.update({
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": " ".join(flags).strip(),
        events.ENV_DIR: str(workdir / "events"),
        events.ENV_ATTEMPT: str(attempt),
    })
    env.update(extra_env or {})
    pairs = {"total_steps": steps, "ckpt_every": ckpt_every,
             "log_every": 2, "eval_every": 1000, "global_batch": 8,
             "distributed": False}
    pairs.update(sets or {})
    cmd = [sys.executable, "-m", "tpuframe.train", "--config", "smoke"]
    for k, v in pairs.items():
        cmd += ["--set", f"{k}={v}"]
    cmd += ["--ckpt-dir", str(workdir / "ck")]
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=240)


def _final_loss(proc, step):
    line = next(l for l in proc.stdout.splitlines() if f"[train {step}]" in l)
    return float(line.split("loss=")[1].split()[0])


def _assert_commit_or_quarantine(ck_dir, merged):
    """The cross-attempt stitcher invariant: every acknowledged save
    (a ``ckpt_save`` event) corresponds to a committed-or-quarantined
    directory — never an acknowledged-but-unwritten checkpoint."""
    saves = [r for r in merged if r.get("type") == "ckpt_save"]
    assert saves, "no ckpt_save events to check"
    for r in saves:
        name = f"step_{int(r['step']):08d}"
        committed = (ck_dir / name / "COMMIT").exists()
        quarantined = (ck_dir / f"{name}.corrupt").is_dir()
        assert committed or quarantined, (
            f"ckpt_save event for step {r['step']} but {name} is neither "
            f"committed nor quarantined")


# ---------------------------------------------------------------------------
# Goodput proof: slow GCS off the step path (summarize comparison).
# ---------------------------------------------------------------------------


class TestSlowGcsGoodput:
    # 4 delayed writes x 0.3s land on the step-10 save; 30 post-save
    # steps (~2.5s of compute) give the async worker room to overlap.
    _FAULT = "slow_gcs:delay_s=0.3:times=4"
    _STEPS, _EVERY = 40, 10

    def _run(self, tmp_path, monkeypatch, tag, *, fault, ckpt_async):
        evdir = str(tmp_path / f"ev_{tag}")
        monkeypatch.setenv(events.ENV_DIR, evdir)
        if fault:
            monkeypatch.setenv("TPUFRAME_FAULTS", fault)
        else:
            monkeypatch.delenv("TPUFRAME_FAULTS", raising=False)
        out = train_mod.train(_smoke_cfg(
            tmp_path / tag, total_steps=self._STEPS,
            ckpt_every=self._EVERY, ckpt_async=ckpt_async))
        assert out["step"] == self._STEPS
        return events.merge(evdir)

    def test_async_moves_ckpt_wall_off_step_path(self, tmp_path,
                                                 monkeypatch):
        base = self._run(tmp_path, monkeypatch, "base",
                         fault=None, ckpt_async=True)
        slow_async = self._run(tmp_path, monkeypatch, "slow_async",
                               fault=self._FAULT, ckpt_async=True)
        slow_sync = self._run(tmp_path, monkeypatch, "slow_sync",
                              fault=self._FAULT, ckpt_async=False)

        g_base = goodput.from_events(base)
        g_async = goodput.from_events(slow_async)
        g_sync = goodput.from_events(slow_sync)

        # The injected 1.2s hits the sync run's step path...
        assert g_sync["buckets"]["ckpt"] > 1.0, g_sync["buckets"]
        # ...and stays off the async run's (snapshot blocking only).
        assert g_async["buckets"]["ckpt"] < 0.8, g_async["buckets"]
        # Productive time is storage-independent: the slow-GCS async run
        # matches the no-fault run within CPU-timing noise.
        p_base = g_base["buckets"]["productive"]
        p_async = g_async["buckets"]["productive"]
        assert abs(p_async - p_base) < max(1.0, 0.5 * p_base), (
            p_base, p_async)

        # Event-level evidence on the slowed save: the full span absorbs
        # the delay, the step path never saw it.
        slowed = next(r for r in slow_async
                      if r.get("type") == "ckpt_save"
                      and r["step"] == self._EVERY)
        assert slowed["async_write"] is True
        assert slowed["ms"] > 1000.0, slowed
        assert slowed["block_ms"] < 500.0, slowed
        assert slowed["ms"] > 3 * slowed["block_ms"]

        # The blocked_ckpt detector agrees: the sync run is flagged, the
        # async run is not — and the live meter's sums-to-wall invariant
        # holds everywhere (no goodput_invariant findings).
        kinds_sync = {f["kind"] for f in goodput.find_anomalies(slow_sync)}
        kinds_async = {f["kind"] for f in goodput.find_anomalies(slow_async)}
        assert "blocked_ckpt" in kinds_sync
        assert "blocked_ckpt" not in kinds_async
        assert "goodput_invariant" not in (kinds_sync | kinds_async)

        # Both fault runs recorded the injections (fault_injected is
        # emitted before the fault acts — even from the worker thread).
        assert sum(1 for r in slow_async
                   if r.get("type") == "fault_injected") == 4


# ---------------------------------------------------------------------------
# Crash mid-upload: no acknowledged-but-unwritten checkpoint.
# ---------------------------------------------------------------------------


def test_crash_during_upload_never_acknowledges(tmp_path):
    work = tmp_path
    crashed = _run_train(work, steps=6, ckpt_every=3, attempt=0,
                         extra_env={"TPUFRAME_ASYNC_CKPT": "1",
                                    "TPUFRAME_FAULTS":
                                    "crash_during_upload:times=1"})
    assert crashed.returncode == 42, crashed.stderr[-1500:]
    assert "FAULT INJECTION" in crashed.stdout

    ck = work / "ck"
    # The step-3 save died after its shard files, before sidecar/COMMIT:
    # visible to the supervisor's in-flight probe, invisible to resume.
    assert (ck / "step_00000003").is_dir()
    assert not (ck / "step_00000003" / "COMMIT").exists()
    assert latest_step(str(ck)) is None
    assert in_flight_step(str(ck)) == 3

    # The ckpt_save event is emitted only after COMMIT, so the crashed
    # attempt acknowledged nothing.
    attempt0 = [r for r in events.merge(str(work / "events"))
                if r["attempt"] == 0]
    assert not any(r["type"] == "ckpt_save" for r in attempt0)
    assert any(r["type"] == "fault_injected" for r in attempt0)

    # Relaunch: nothing committed, so the attempt retrains from scratch
    # and overwrites the torn step-3 leftovers on its way through.
    resumed = _run_train(work, steps=6, ckpt_every=3, attempt=1,
                         extra_env={"TPUFRAME_ASYNC_CKPT": "1"})
    assert resumed.returncode == 0, resumed.stderr[-1500:]
    assert latest_step(str(ck)) == 6

    merged = events.merge(str(work / "events"))
    assert {r["attempt"] for r in merged} == {0, 1}
    _assert_commit_or_quarantine(ck, merged)


# ---------------------------------------------------------------------------
# SIGTERM with a pending upload: rc 14 only after flush() commits, then
# exact-continuation resume (golden-loss equality).
# ---------------------------------------------------------------------------


def test_sigterm_pending_upload_flushes_then_resumes_exactly(tmp_path):
    straight = _run_train(tmp_path / "a", steps=6, ckpt_every=3,
                          extra_env={"TPUFRAME_ASYNC_CKPT": "1"})
    assert straight.returncode == 0, straight.stderr[-1500:]

    work = tmp_path / "b"
    # SIGTERM lands the instant the step-3 snapshot starts uploading;
    # the slow_gcs budget guarantees the upload is genuinely in flight
    # when the flag is checked at the step boundary.
    preempted = _run_train(
        work, steps=6, ckpt_every=3, attempt=0,
        extra_env={"TPUFRAME_ASYNC_CKPT": "1",
                   "TPUFRAME_FAULTS": "sigterm_pending_upload:times=1,"
                                      "slow_gcs:delay_s=0.5:times=2"})
    assert preempted.returncode == RC_PREEMPTED, preempted.stderr[-1500:]
    assert "FAULT INJECTION: raising SIGTERM" in preempted.stdout
    # rc 14 was only reached through flush(): the pending save is
    # committed (not quarantined) and therefore acknowledged.
    ck = work / "ck"
    assert (ck / "step_00000003" / "COMMIT").exists()
    assert not (ck / "step_00000003.corrupt").exists()
    attempt0 = [r for r in events.merge(str(work / "events"))
                if r["attempt"] == 0]
    assert any(r["type"] == "ckpt_save" and r["step"] == 3
               for r in attempt0)
    assert any(r["type"] == "preempt" for r in attempt0)
    assert any(r["type"] == "run_end" for r in attempt0)

    resumed = _run_train(work, steps=6, ckpt_every=3, attempt=1,
                         extra_env={"TPUFRAME_ASYNC_CKPT": "1"})
    assert resumed.returncode == 0, resumed.stderr[-1500:]
    assert "resumed from step 3" in resumed.stdout
    np.testing.assert_allclose(_final_loss(resumed, 6),
                               _final_loss(straight, 6), rtol=1e-4)

    _assert_commit_or_quarantine(ck, events.merge(str(work / "events")))


# ---------------------------------------------------------------------------
# flush() unit contract: commit-or-quarantine at the deadline.
# ---------------------------------------------------------------------------


def _toy_state():
    return step_lib.TrainState.create(
        {"w": jnp.arange(12.0).reshape(3, 4), "b": jnp.ones(())},
        optax.adam(1e-3))


class TestFlush:
    def test_flush_commits_and_returns_true(self, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path), async_write=True)
        state = _toy_state()
        mgr.save(1, state)
        assert mgr.flush(deadline_s=30.0) is True
        assert (tmp_path / "step_00000001" / "COMMIT").exists()
        assert mgr._pending == []
        step, _ = mgr.restore_latest(target=state)
        assert step == 1

    def test_flush_sync_manager_is_trivial(self, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path), async_write=False)
        mgr.save(1, _toy_state())
        assert mgr.flush(deadline_s=0.0) is True
        assert (tmp_path / "step_00000001" / "COMMIT").exists()

    def test_flush_deadline_quarantines_stranded_upload(self, tmp_path,
                                                        monkeypatch,
                                                        capsys):
        # The worker wedges forever inside its first storage write (kind
        # hang on the slow_gcs seam); flush must not wait on it past the
        # deadline, and must leave nothing resume could mistake for a
        # durable checkpoint.  The hung daemon thread never wakes again,
        # so it cannot recreate the dir behind the test's back.
        monkeypatch.setenv("TPUFRAME_FAULTS", "slow_gcs:kind=hang:times=1")
        faults.reset_from_env()
        mgr = ckpt.CheckpointManager(str(tmp_path), async_write=True)
        mgr.save(1, _toy_state())
        t0 = time.perf_counter()
        assert mgr.flush(deadline_s=0.5) is False
        assert time.perf_counter() - t0 < 5.0  # bounded, not a join()
        assert (tmp_path / "step_00000001.corrupt").is_dir()
        assert not (tmp_path / "step_00000001").exists()
        assert latest_step(str(tmp_path)) is None
        assert in_flight_step(str(tmp_path)) is None
        assert mgr._pending == []
        assert "quarantined" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# The supervisor's probe understands in-flight saves.
# ---------------------------------------------------------------------------


class TestInFlightProbe:
    def test_in_flight_step_ignores_committed_and_corrupt(self, tmp_path):
        os.makedirs(tmp_path / "step_00000004")
        (tmp_path / "step_00000004" / "COMMIT").write_text("done")
        os.makedirs(tmp_path / "step_00000007")  # upload in flight
        os.makedirs(tmp_path / "step_00000005.corrupt")  # quarantined
        assert latest_step(str(tmp_path)) == 4
        assert in_flight_step(str(tmp_path)) == 7
        assert in_flight_step(str(tmp_path / "absent")) is None

    def test_progress_probe_counts_in_flight_saves(self, tmp_path):
        probe = launcher_mod._progress_probe(
            ["prog", "--ckpt-dir", str(tmp_path)])
        assert probe() is None  # empty dir: no progress yet
        os.makedirs(tmp_path / "step_00000010")
        (tmp_path / "step_00000010" / "COMMIT").write_text("done")
        assert probe() == 10
        # A preempted-mid-upload step counts as progress: the relaunch
        # either finishes the commit or retrains a few steps — it is not
        # a crash loop, and the budget must not be charged as one.
        os.makedirs(tmp_path / "step_00000020")
        assert probe() == 20
        # ...but a quarantined dir never does.
        os.rename(tmp_path / "step_00000020",
                  tmp_path / "step_00000020.corrupt")
        assert probe() == 10


# ---------------------------------------------------------------------------
# Elastic resize: 8 -> 4 -> 8 devices across relaunches, losing <=1 step
# per boundary, golden-loss-equivalent to the uninterrupted 8-device run.
# ---------------------------------------------------------------------------


class TestElasticResize:
    """The drain -> relaunch -> reshard -> rescale contract, end to end.

    Each leg is a subprocess at its own forced device count; the legs
    share the checkpoint dir and event dir, so the resize is detected by
    ``build_harness`` from the committed manifest's world record.  ZeRO-1
    weight update makes the reshard real: the smoke convnet's bias (size
    10) pads to 16 at n=8 and 12 at n=4, so both shrink and grow move a
    genuinely re-padded flat moment vector.  ``hold`` (the default
    policy) keeps batch/LR fixed, and the world-size-invariant loader
    order makes the continued run golden-loss-comparable to a straight
    8-device run (FP reduction order differs across n, hence rtol).
    Dropout is disabled: its per-replica streams are decorrelated by
    axis index, so masks are world-size dependent by design and would
    break golden equivalence for a reason unrelated to resharding."""

    _STEPS, _EVERY = 9, 3
    # ckpt_keep covers every save across the three legs (up to two extra
    # drain saves at the preemption boundaries) so the commit-or-
    # quarantine sweep can audit all of them.
    _SETS = {"distributed": True, "model_kwargs": {"dropout": 0.0},
             "ckpt_keep": 8}
    _ENV = {"TPUFRAME_ASYNC_CKPT": "1",
            "TPUFRAME_WEIGHT_UPDATE": "zero1"}

    def _leg(self, work, *, attempt, devices, fault=None):
        extra = dict(self._ENV)
        if fault:
            extra["TPUFRAME_FAULTS"] = fault
        return _run_train(work, steps=self._STEPS, ckpt_every=self._EVERY,
                          attempt=attempt, devices=devices, sets=self._SETS,
                          extra_env=extra)

    def test_shrink_then_grow_continues_within_one_step(self, tmp_path):
        straight = self._leg(tmp_path / "a", attempt=0, devices=8)
        assert straight.returncode == 0, straight.stderr[-1500:]

        work = tmp_path / "b"
        # Leg 0 (8 devices): partial SIGTERM (k=1 of 1 local host) at
        # step 4 — the membership-change model; the preemption path
        # drains the async save before exiting rc 14.
        leg0 = self._leg(work, attempt=0, devices=8,
                         fault="host:step=4:kind=partial_sigterm:times=1")
        assert leg0.returncode == RC_PREEMPTED, leg0.stderr[-1500:]
        assert "FAULT INJECTION" in leg0.stdout
        ck = work / "ck"
        committed0 = latest_step(str(ck))
        assert committed0 is not None and committed0 >= 3

        # Leg 1 (4 devices): restore reshards zero1 state 8->4 and the
        # run continues; a second reclaim ends the leg.
        leg1 = self._leg(work, attempt=1, devices=4,
                         fault="host:step=7:kind=partial_sigterm:times=1")
        assert leg1.returncode == RC_PREEMPTED, leg1.stderr[-1500:]
        assert "elastic resize: 8" in leg1.stdout, leg1.stdout[-2000:]
        assert "resumed from step" in leg1.stdout

        # Leg 2 (8 devices): capacity returns; reshard 4->8, run out.
        leg2 = self._leg(work, attempt=2, devices=8)
        assert leg2.returncode == 0, leg2.stderr[-1500:]
        assert "elastic resize: 4" in leg2.stdout, leg2.stdout[-2000:]
        assert "resumed from step" in leg2.stdout
        assert latest_step(str(ck)) == self._STEPS

        # Golden-loss-equivalent continuation under hold: same data
        # order (world-size-invariant loader), same batch/LR — only the
        # cross-n FP reduction order differs.
        np.testing.assert_allclose(_final_loss(leg2, self._STEPS),
                                   _final_loss(straight, self._STEPS),
                                   rtol=1e-3)

        merged = events.merge(str(work / "events"))
        assert {r["attempt"] for r in merged} == {0, 1, 2}
        _assert_commit_or_quarantine(ck, merged)

        # The typed boundary events carry full provenance.
        resizes = [r for r in merged if r["type"] == "elastic_resize"]
        assert [(r["n_from"], r["n_to"]) for r in resizes] == [(8, 4),
                                                              (4, 8)]
        for r in resizes:
            assert r["policy"] == "hold"
            assert r["global_batch_from"] == r["global_batch_to"] == 8
            assert r["base_lr_from"] == r["base_lr_to"]

        # The attempt stitcher prices the boundary: <=1 retrained step
        # per resize, and the stitcher surfaces the transitions.
        g = goodput.from_events(merged)
        assert g["attempts"] == 3
        assert g["retrained_steps"] <= 2, g
        assert g["elastic_resizes"] == 2
        assert g["elastic_transitions"] == ["8->4", "4->8"]

        # obs compare prices the boundary.  productive_frac is unchanged
        # in the amortized limit: its two factors are per-step productive
        # cost (asserted here — the resized legs' step path is not
        # slower, generous 3x bound because tiny CPU steps are noisy) and
        # boundary overhead (already bounded: retrained_steps <= 1 per
        # boundary plus a fixed init/compile cost per attempt, which at
        # this 9-step toy scale dominates wall but vanishes at real run
        # lengths — so the raw toy-scale fraction is NOT asserted).
        straight_ev = events.merge(str(tmp_path / "a" / "events"))
        cmp = goodput.compare_runs(straight_ev, merged)
        assert "productive_frac" in cmp["metrics"]
        g_straight = goodput.from_events(straight_ev)
        assert g["steps"] >= self._STEPS and g_straight["steps"] >= 1
        per_step = g["buckets"]["productive"] / g["steps"]
        per_step_straight = (g_straight["buckets"]["productive"]
                             / g_straight["steps"])
        assert per_step <= 3 * per_step_straight, (
            g["buckets"], g["steps"], g_straight["buckets"])


# ---------------------------------------------------------------------------
# Fleet chaos: kill 1 of 3 serving replicas mid-load, lose nothing.
# ---------------------------------------------------------------------------


class TestFleetChaos:
    """The serving half of the fault-tolerance story (DESIGN.md "Serving
    fleet & failure model"): a 3-replica fake-engine fleet under a
    seeded burst load, with ``replica_crash`` scheduled on one replica —
    deterministic via the fault registry's step pin, no wall-clock race.

    Proven against the same-seed no-fault run:
      * zero accepted-request loss — every admitted request retires
        exactly once (rid-level, through the event stitcher);
      * p99 TTFT of the faulted run stays <= 2x the no-fault run (burst
        load makes both queueing-dominated, so the bound tracks the 3->2
        capacity drop plus detection cost, not a noise floor);
      * the drain/redispatch story is visible as typed router_* events
        that validate_files, fleet_stats and obs compare all understand.
    """

    _N, _SEED = 36, 7
    _FLEET = dict(replicas=3, n_requests=_N, seed=_SEED, slots=2,
                  step_delay_ms=20.0, rate=1000.0,  # burst: all at t~0
                  max_new_tokens=8, queue_limit=256, hedge_ms=5000.0,
                  scrape_interval_s=0.05, timeout_s=90.0)

    def _events_ok(self, events_dir):
        files = events.event_files(str(events_dir))
        assert files, "fleet run wrote no event files"
        assert events.validate_files(files) == []
        return events.merge(str(events_dir))

    def test_replica_kill_loses_nothing_and_bounds_p99(self, tmp_path):
        from tpuframe.serve import router as router_lib

        base = router_lib.fleet_smoke(
            events_dir=str(tmp_path / "a"), **self._FLEET)
        kill = router_lib.fleet_smoke(
            events_dir=str(tmp_path / "b"), kill_rank=1, kill_step=3,
            **self._FLEET)

        # Clean fleet first: everything admitted, retired, exited 0.
        assert base["admitted"] == self._N and base["lost"] == 0
        assert base["shed"] == 0 and not base["timed_out"]
        assert base["exit_codes"] == [0, 0, 0]

        # The kill is real (os._exit(42) from the fault registry) ...
        assert kill["exit_codes"][1] == 42
        assert kill["exit_codes"][0] == 0 and kill["exit_codes"][2] == 0
        assert kill["drains"] >= 1
        # ... and still: zero accepted-request loss, shed counted (none
        # expected at this queue bound), nothing silently dropped.
        assert kill["admitted"] == self._N
        assert kill["lost"] == 0 and not kill["timed_out"]
        assert kill["shed"] == 0
        assert kill["requests"] + kill["shed"] == kill["admitted"]

        # p99 TTFT: faulted <= 2x no-fault, same seed.  _pct at p99 over
        # 36 samples is the max — this bounds the WORST request against
        # the capacity drop, not an average.
        p99_a = base["ttft_ms"]["p99"]
        p99_b = kill["ttft_ms"]["p99"]
        assert p99_a > 0
        assert p99_b <= 2.0 * p99_a, (
            f"p99 TTFT {p99_b:.1f}ms > 2x no-fault {p99_a:.1f}ms")

        # rid-exactness through the stitcher: every admitted rid retired
        # exactly once, across both the surviving replicas.
        merged = self._events_ok(tmp_path / "b")
        admits = [r["id"] for r in merged if r["type"] == "router_admit"]
        dones = [r["id"] for r in merged
                 if r["type"] == "router_request"]
        assert sorted(admits) == sorted(set(admits))
        assert sorted(dones) == sorted(admits)   # exactly once, all of them

        # The drain and re-dispatch are typed, attributed events.
        drains = [r for r in merged if r["type"] == "router_drain"]
        assert any(d["replica"] == "r1" for d in drains)
        assert all(d["reason"] for d in drains)
        redispatched = [r for r in merged
                        if r["type"] == "router_redispatch"]
        assert len(redispatched) == kill["redispatched"]
        # Dead replica's orphans landed on survivors.
        assert {r["replica"] for r in redispatched} <= {"r0", "r2"}

        # The offline analyzers see the same story.
        fleet = goodput.fleet_stats(merged)
        assert fleet["lost"] == 0 and fleet["requests"] == self._N
        assert any(d["replica"] == "r1" for d in fleet["drains"])
        assert set(fleet["by_replica"]) <= {"r0", "r2"}

        base_merged = self._events_ok(tmp_path / "a")
        cmp = goodput.compare_runs(base_merged, merged)
        assert "router_ttft_p90_ms" in cmp["metrics"]
        entry = cmp["metrics"]["router_ttft_p90_ms"]
        assert entry["a"] > 0 and entry["b"] > 0

        # Tracing through the kill: every admitted rid still
        # reconstructs to exactly ONE complete request root, every
        # completed root's wait+queue+prefill sum agrees with its
        # queue-inclusive TTFT (zero ttft_mismatch — the one-monotonic-
        # clock reconciliation), and the only anomalies are leaked
        # serve-side spans on the KILLED replica — the loud orphaned-
        # work signal the leak detector exists for.
        findings = tracing.verify_traces(merged)
        other = [f for f in findings if f["kind"] != "leaked_span"]
        assert other == [], other
        leaked = [f for f in findings if f["kind"] == "leaked_span"]
        assert leaked, "kill left no leaked span — the crash was clean?"
        assert all(str(f.get("host", "")).endswith("-p1")
                   for f in leaked), leaked
        traces = tracing.build_traces(merged)
        for rec in merged:
            if rec["type"] == "router_admit":
                roots = traces[rec["trace"]].complete_roots()
                assert len(roots) == 1, (rec["id"], len(roots))
        # The p99 exemplar names a trace the reconstruction can resolve.
        assert fleet["ttft_exemplars"]["p99"]["trace"] in traces
        # The no-fault run is anomaly-free end to end.
        assert tracing.verify_traces(base_merged) == []

    def test_replica_crash_seam_is_deterministic(self):
        """The seam grammar: replica_crash defaults to kind=crash and
        honors the step pin — the property the fleet test's kill_step
        scheduling rests on."""
        (f,) = faults.parse("replica_crash:step=3:rank=1")
        assert f.kind == "crash" and f.step == 3 and f.rank == 1
        for seam, kind in (("replica_hang", "hang"),
                           ("replica_slow", "slow")):
            (g,) = faults.parse(seam)
            assert g.kind == kind


class TestRollingUpdate:
    """PR 17's chaos tier: a live weight rollout across the 3-replica
    fleet under the same seeded burst load as TestFleetChaos, triggered
    the production way — the harness "commits" a checkpoint mid-run
    (manifest first, COMMIT last) and the controller's
    ``committed_world()`` poll picks it up.

    Proven, per ISSUE 17's acceptance bar:
      * zero accepted-request loss straight through the roll (rid-exact
        through the event stitcher);
      * p99 TTFT during the roll <= 2x the same-seed steady-state run;
      * every replica ends on the new version at ZERO compile-cache
        misses (hot swap, not restart), with the mixed-version window
        bounded and visible in fleet_stats;
      * a seeded-slow poisoned canary auto-rolls back — rollout_abort
        names the failing gate metric and the fleet returns to v0;
      * a replica killed mid-swap (rc 42) is drained, its work
        redispatched, and it relaunches on the NEW version — still
        zero loss.
    """

    _N, _SEED = 36, 7
    _ROLL = dict(replicas=3, n_requests=_N, seed=_SEED, slots=2,
                 step_delay_ms=20.0, rate=1000.0,  # burst: all at t~0
                 max_new_tokens=8, queue_limit=256, hedge_ms=5000.0,
                 scrape_interval_s=0.05, timeout_s=90.0,
                 canary_frac=0.34, bake_min_samples=4)

    def _steady(self, events_dir):
        from tpuframe.serve import router as router_lib

        keys = ("replicas", "n_requests", "seed", "slots",
                "step_delay_ms", "rate", "max_new_tokens", "queue_limit",
                "hedge_ms", "scrape_interval_s", "timeout_s")
        return router_lib.fleet_smoke(
            events_dir=str(events_dir),
            **{k: self._ROLL[k] for k in keys})

    def _events_ok(self, events_dir):
        files = events.event_files(str(events_dir))
        assert files, "rollout run wrote no event files"
        assert events.validate_files(files) == []
        return events.merge(str(events_dir))

    def _rid_exact(self, merged):
        admits = [r["id"] for r in merged if r["type"] == "router_admit"]
        dones = [r["id"] for r in merged if r["type"] == "router_request"]
        assert sorted(admits) == sorted(set(admits))
        assert sorted(dones) == sorted(admits)

    def test_rolling_update_zero_loss_bounded_p99(self, tmp_path):
        from tpuframe.serve import rollout as rollout_lib

        steady = self._steady(tmp_path / "steady")
        assert steady["lost"] == 0 and not steady["timed_out"]

        watch = tmp_path / "ck"
        watch.mkdir()
        # Mid-commit checkpoint on disk BEFORE the fleet starts: the
        # watcher must stay blind to it for the whole pre-trigger
        # window (the harness lands COMMIT mid-load).
        d = watch / "step_00000001"
        d.mkdir()
        (d / "manifest.json").write_text(
            '{"step": 1, "world": {"processes": 1, "devices": 1}}')

        out = rollout_lib.rolling_update_smoke(
            events_dir=str(tmp_path / "roll"), watch_dir=str(watch),
            gate_pct=50.0, **self._ROLL)
        ro = out["rollout"]

        # The roll completed the production way and nothing was lost.
        assert ro["state"] == "done" and ro["version"] == 1
        assert ro["world"]["step"] == 1
        assert out["admitted"] == self._N and out["lost"] == 0
        assert out["shed"] == 0 and not out["timed_out"]
        # Every replica ended on the new version — live off each
        # replica's own gauge, not the controller's belief.
        assert out["final_versions"] == {"r0": 1, "r1": 1, "r2": 1}
        # Hot swap, not restart: zero compile-cache misses, no relaunch.
        assert ro["swap_compile_misses"] == 0
        assert ro["relaunches"] == 0 and out["exit_codes"] == [0, 0, 0]
        # Bounded mixed-version window: one replica at a time.
        assert ro["window_s"] is not None and 0.0 < ro["window_s"] < 30.0

        # p99 TTFT during the roll <= 2x steady state, same seed.
        p99_a = steady["ttft_ms"]["p99"]
        p99_b = out["ttft_ms"]["p99"]
        assert p99_a > 0
        assert p99_b <= 2.0 * p99_a, (
            f"p99 TTFT {p99_b:.1f}ms during roll > 2x steady-state "
            f"{p99_a:.1f}ms")

        # rid-exactness and the typed rollout story in one stream.
        merged = self._events_ok(tmp_path / "roll")
        self._rid_exact(merged)
        ro_steps = [r for r in merged if r["type"] == "rollout_step"]
        assert [r for r in merged if r["type"] == "rollout_done"]
        swapped = [r["replica"] for r in ro_steps
                   if r["phase"] == "swapped"]
        assert sorted(swapped) == ["r0", "r1", "r2"]
        assert [r["replica"] for r in ro_steps
                if r["phase"] == "promoted"] == ["r0"]

        # The offline analyzers reconstruct the same bounded window.
        fs = goodput.fleet_stats(merged)
        assert fs["lost"] == 0
        v = fs["versions"]
        assert v["by_replica"] == {"r0": 1, "r1": 1, "r2": 1}
        assert v["target"] == 1 and not v["aborted"]
        assert 0.0 < v["mixed_window_s"] < 30.0

        # Tracing through the roll is fully clean: no leaks, no
        # orphans, every admitted rid exactly one complete root, every
        # phase sum within tolerance of its queue-inclusive TTFT —
        # drains and re-queues included.
        assert tracing.verify_traces(merged) == []
        traces = tracing.build_traces(merged)
        for rec in merged:
            if rec["type"] == "router_admit":
                assert len(traces[rec["trace"]].complete_roots()) == 1
        # The rollout itself is one force-sampled trace: a complete
        # root span whose notes carry the per-replica phases.
        ro_roots = [(tv, sp) for tv in traces.values()
                    for sp in tv.roots if sp.name == "rollout"]
        assert len(ro_roots) == 1
        rtv, ro_root = ro_roots[0]
        assert ro_root.complete
        assert ro_root.closed["status"] == "done"
        assert ro_root.closed["version"] == 1
        phases = {(n.get("replica"), n["note"]) for n in rtv.notes}
        assert {("r0", "swapped"), ("r1", "swapped"),
                ("r2", "swapped")} <= phases

    def test_poisoned_canary_auto_rolls_back(self, tmp_path):
        from tpuframe.serve import rollout as rollout_lib

        out = rollout_lib.rolling_update_smoke(
            events_dir=str(tmp_path / "ev"), gate_pct=50.0,
            faults_spec="slow_canary:times=1000:delay_s=0.05",
            **self._ROLL)
        ro = out["rollout"]

        # The gate caught the regression and named the metric.
        assert ro["state"] == "aborted" and ro["aborted"]
        assert ro["abort_metric"] in rollout_lib.GATE_METRICS
        # The fleet is back on the old version everywhere, and the
        # canary's last phase is the rollback.
        assert out["final_versions"] == {"r0": 0, "r1": 0, "r2": 0}
        assert ro["phases"][-1] == ["r0", "rolled_back"] or \
            tuple(ro["phases"][-1]) == ("r0", "rolled_back")
        # Still zero loss: a rollback is a drain, not an outage.
        assert out["admitted"] == self._N and out["lost"] == 0
        assert not out["timed_out"] and out["exit_codes"] == [0, 0, 0]

        merged = self._events_ok(tmp_path / "ev")
        self._rid_exact(merged)
        (abort,) = [r for r in merged if r["type"] == "rollout_abort"]
        assert abort["metric"] == ro["abort_metric"]
        assert abort["version"] == 1 and abort["reason"]
        v = goodput.fleet_stats(merged)["versions"]
        assert v["aborted"] and v["abort_metric"] == ro["abort_metric"]
        assert v["by_replica"]["r0"] == 0

    def test_mid_swap_kill_relaunches_on_new_version(self, tmp_path):
        from tpuframe.serve import rollout as rollout_lib

        out = rollout_lib.rolling_update_smoke(
            events_dir=str(tmp_path / "ev"), gate_pct=50.0,
            kill_during_swap_rank=1, **self._ROLL)
        ro = out["rollout"]

        # The kill was real (os._exit(42) inside swap application), the
        # supervisor relaunched rank 1 on the NEW version, and the roll
        # finished with every replica on it.
        assert out["relaunched_ranks"] == [1]
        assert ro["relaunches"] == 1
        assert ro["state"] == "done" and ro["version"] == 1
        assert out["final_versions"] == {"r0": 1, "r1": 1, "r2": 1}
        # Zero accepted-request loss through drain + kill + relaunch.
        assert out["admitted"] == self._N and out["lost"] == 0
        assert out["shed"] == 0 and not out["timed_out"]

        merged = self._events_ok(tmp_path / "ev")
        self._rid_exact(merged)
        ro_steps = [r for r in merged if r["type"] == "rollout_step"]
        assert [r["replica"] for r in ro_steps
                if r["phase"] == "swap_failed"] == ["r1"]
        assert [r["replica"] for r in ro_steps
                if r["phase"] == "relaunched"] == ["r1"]
        # The relaunch participates in the mixed-version window.
        v = goodput.fleet_stats(merged)["versions"]
        assert v["by_replica"] == {"r0": 1, "r1": 1, "r2": 1}
