"""The fleet router (serve/router.py): admission control, placement,
drain/redispatch, hedged retries — all over an injectable transport, so
the whole state machine runs without processes or sockets.  The
subprocess fleet (real replicas, real kills) lives in tests/test_chaos.py
and the real-HTTP 503-drain integration in tests/test_telemetry.py.
"""

import json
import threading
import time

import pytest

from tpuframe.resilience.policy import RetryPolicy
from tpuframe.serve import router as router_lib
from tpuframe.serve.router import Router, Shed


def _no_sleep_policy(**kw):
    kw.setdefault("max_attempts", 2)
    kw.setdefault("base_delay_s", 0.001)
    kw.setdefault("max_delay_s", 0.001)
    kw.setdefault("attempt_timeout_s", 5.0)
    kw.setdefault("deadline_s", 10.0)
    return RetryPolicy(sleep=lambda s: None, **kw)


def _drive(router, *, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while router.has_work() and time.monotonic() < deadline:
        router.step()
        time.sleep(0.002)
    assert not router.has_work(), "router did not converge"


def _ok_reply(url, payload, timeout_s):
    """Transport stub: every /generate answers 200 from the named
    replica; scrapes answer healthy with zero queue depth."""
    if url.endswith("/generate"):
        return 200, {"rid": payload["rid"], "tokens": [1, 2],
                     "ttft_ms": 1.0}
    if url.endswith("/healthz"):
        return 200, "ok\n"
    return 200, "tpuframe_serve_queue_depth 0\n# EOF\n"


class TestAdmission:
    def test_bounded_queue_sheds_at_limit(self):
        r = Router(["http://a"], queue_limit=2, transport=_ok_reply)
        assert r.submit(0, [1]) and r.submit(1, [1])
        assert not r.submit(2, [1])          # explicit shed, not buffering
        assert r.counters == {**r.counters, "admitted": 2, "shed": 1}
        assert len(r.pending) == 2           # the bound held

    def test_shed_can_raise(self):
        r = Router(["http://a"], queue_limit=1, transport=_ok_reply)
        assert r.submit(0, [1])
        with pytest.raises(Shed, match="queue full"):
            r.submit(1, [1], raise_on_shed=True)

    def test_inflight_counts_against_the_bound(self):
        """Dispatching must not free admission room: pending + in-flight
        is the queue the bound guards."""
        hold = threading.Event()

        def slow(url, payload, timeout_s):
            if url.endswith("/generate"):
                hold.wait(5.0)
            return _ok_reply(url, payload, timeout_s)

        r = Router(["http://a"], queue_limit=1, transport=slow,
                   hedge_ms=0)
        assert r.submit(0, [1])
        r.step()                              # 0 moves pending -> inflight
        assert not r.submit(1, [1])           # still full
        hold.set()
        _drive(r)

    def test_env_knob_resolution(self, monkeypatch):
        monkeypatch.setenv(router_lib.ENV_QUEUE, "7")
        monkeypatch.setenv(router_lib.ENV_HEDGE_MS, "250")
        monkeypatch.setenv(router_lib.ENV_REPLICAS, "5")
        assert router_lib.resolve_queue_limit() == 7
        assert router_lib.resolve_hedge_ms() == 250.0
        assert router_lib.resolve_replicas() == 5
        monkeypatch.setenv(router_lib.ENV_QUEUE, "junk")
        assert router_lib.resolve_queue_limit() == router_lib.DEFAULT_QUEUE


class TestPlacement:
    def test_least_loaded_dispatch_spreads_the_fleet(self):
        seen = []
        hold = threading.Event()

        def record(url, payload, timeout_s):
            if url.endswith("/generate"):
                seen.append(url.rsplit("/", 1)[0])
                hold.wait(5.0)
            return _ok_reply(url, payload, timeout_s)

        r = Router(["http://a", "http://b"], queue_limit=8,
                   transport=record, hedge_ms=0,
                   scrape_interval_s=1e9)  # placement by inflight only
        r.submit(0, [1])
        r.submit(1, [1])
        r.step()
        deadline = time.monotonic() + 2.0
        while len(seen) < 2 and time.monotonic() < deadline:
            time.sleep(0.002)
        assert sorted(seen) == ["http://a", "http://b"]
        hold.set()
        _drive(r)
        assert r.counters["completed"] == 2

    def test_scraped_queue_depth_breaks_ties(self):
        def transport(url, payload, timeout_s):
            if url.endswith("/metrics"):
                depth = 5 if "//a" in url else 0
                return 200, f"tpuframe_serve_queue_depth {depth}\n# EOF\n"
            return _ok_reply(url, payload, timeout_s)

        r = Router(["http://a", "http://b"], transport=transport,
                   scrape_interval_s=0.0)
        r._scrape_due(r._clock())
        assert r._replica("r0").queue_depth == 5.0
        assert r._pick().name == "r1"        # deeper queue loses the tie


class TestDrainRedispatch:
    def test_dead_replica_redispatches_exactly_once(self):
        """r0 refuses its dispatch (OSError through the RetryPolicy,
        scrapes still healthy); the router marks it draining and the
        request retires exactly once on r1 — the zero-loss contract at
        unit scale."""
        def transport(url, payload, timeout_s):
            if "//a" in url and url.endswith("/generate"):
                raise OSError("connection refused")
            return _ok_reply(url, payload, timeout_s)

        r = Router(["http://a", "http://b"], transport=transport,
                   hedge_ms=0, scrape_interval_s=1e9,
                   dispatch_policy=_no_sleep_policy())
        r.submit(0, [1])
        _drive(r)
        s = r.summary()
        assert s["requests"] == 1 and s["lost"] == 0
        assert s["drains"] == 1 and s["redispatched"] == 1
        assert s["dispatch_errors"] >= 1
        assert r._replica("r0").state == "draining"
        assert r.completed[0].replica == "r1"
        # retired exactly once: one rid, one completion record
        assert [q.rid for q in r.completed] == [0]

    def test_generate_503_drains_the_replica(self):
        """A draining replica answers /generate with 503 — an answer,
        not a transport failure: no retry burn, but the router must
        stop dispatching there and re-route."""
        def transport(url, payload, timeout_s):
            if "//a" in url and url.endswith("/generate"):
                return 503, {"error": "draining"}
            return _ok_reply(url, payload, timeout_s)

        r = Router(["http://a", "http://b"], transport=transport,
                   hedge_ms=0, scrape_interval_s=1e9)
        r.submit(0, [1])
        _drive(r)
        assert r._replica("r0").state == "draining"
        assert r.summary()["lost"] == 0
        assert r.completed[0].replica == "r1"

    def test_healthz_503_scrape_drains_without_traffic(self):
        def transport(url, payload, timeout_s):
            if "//a" in url and url.endswith("/healthz"):
                return 503, "unhealthy\n"
            return _ok_reply(url, payload, timeout_s)

        r = Router(["http://a", "http://b"], transport=transport,
                   scrape_interval_s=0.0)
        r._scrape_due(r._clock())
        assert r._replica("r0").state == "draining"
        assert r._replica("r1").state == "ok"
        assert r.counters["drains"] == 1

    def test_scrape_timeout_drains(self):
        def transport(url, payload, timeout_s):
            if "//a" in url:
                raise OSError("timed out")
            return _ok_reply(url, payload, timeout_s)

        r = Router(["http://a", "http://b"], transport=transport,
                   scrape_interval_s=0.0,
                   scrape_policy=_no_sleep_policy())
        r._scrape_due(r._clock())
        assert r._replica("r0").state == "draining"

    def test_all_replicas_down_keeps_request_queued(self):
        """No healthy replica: the admitted request stays pending (and
        counted as not-lost-yet) rather than being dropped."""
        def transport(url, payload, timeout_s):
            raise OSError("down")

        r = Router(["http://a"], transport=transport, hedge_ms=0,
                   scrape_interval_s=1e9,
                   dispatch_policy=_no_sleep_policy())
        r.submit(0, [1])
        deadline = time.monotonic() + 2.0
        while r.counters["drains"] < 1 and time.monotonic() < deadline:
            r.step()
            time.sleep(0.002)
        r.step()
        assert r.has_work()                 # still owed, not forgotten
        assert len(r.pending) == 1 and r.pending[0].rid == 0
        assert r.summary()["lost"] == 1     # honest accounting meanwhile


class TestHedging:
    def test_straggler_hedge_first_winner_kept(self):
        """r0 stalls past hedge_ms; the hedge lands on r1 and wins; r0's
        late answer is counted as a duplicate, not a second retirement."""
        release = threading.Event()

        def transport(url, payload, timeout_s):
            if url.endswith("/generate") and "//a" in url:
                release.wait(5.0)            # the straggler
                return 200, {"rid": payload["rid"], "tokens": [9],
                             "ttft_ms": 99.0}
            return _ok_reply(url, payload, timeout_s)

        r = Router(["http://a", "http://b"], transport=transport,
                   hedge_ms=30.0, scrape_interval_s=1e9)
        r.submit(0, [1])
        _drive(r)
        s = r.summary()
        assert s["requests"] == 1 and s["hedged"] == 1
        assert r.completed[0].replica == "r1"         # hedge won
        assert r.completed[0].result["tokens"] == [1, 2]
        release.set()                                 # straggler lands...
        deadline = time.monotonic() + 2.0
        while r.counters["duplicates"] < 1 and time.monotonic() < deadline:
            r.step()
            time.sleep(0.002)
        assert r.counters["duplicates"] == 1          # ...as a duplicate
        assert len(r.completed) == 1                  # exactly once

    def test_no_hedge_below_threshold_or_without_second_replica(self):
        hold = threading.Event()

        def transport(url, payload, timeout_s):
            if url.endswith("/generate"):
                hold.wait(0.2)
            return _ok_reply(url, payload, timeout_s)

        r = Router(["http://a"], transport=transport, hedge_ms=10.0)
        r.submit(0, [1])
        _drive(r)
        assert r.counters["hedged"] == 0  # nowhere else to race

    def test_hedge_disabled_with_nonpositive_threshold(self):
        r = Router(["http://a", "http://b"], transport=_ok_reply,
                   hedge_ms=0)
        r.submit(0, [1])
        _drive(r)
        assert r.counters["hedged"] == 0


class TestRouterObs:
    def test_events_emitted_and_typed(self, tmp_path):
        from tpuframe.obs import events as obs_events
        from tpuframe.obs import goodput

        obs_events.init(str(tmp_path))
        try:
            def transport(url, payload, timeout_s):
                if "//a" in url and url.endswith("/generate"):
                    raise OSError("down")
                return _ok_reply(url, payload, timeout_s)

            r = Router(["http://a", "http://b"], transport=transport,
                       queue_limit=1, hedge_ms=0, scrape_interval_s=1e9,
                       dispatch_policy=_no_sleep_policy())
            r.submit(0, [1])
            assert not r.submit(1, [1])      # shed -> router_shed
            _drive(r)
            r.summary()                      # -> router_summary
        finally:
            obs_events.close()
        files = obs_events.event_files(str(tmp_path))
        assert obs_events.validate_files(files) == []  # schema-clean
        merged = obs_events.merge(str(tmp_path))
        types = {e["type"] for e in merged}
        assert {"router_admit", "router_shed", "router_dispatch",
                "router_drain", "router_redispatch", "router_request",
                "router_summary"} <= types

        fleet = goodput.fleet_stats(merged)
        assert fleet is not None
        assert fleet["requests"] == 1 and fleet["admitted"] == 1
        assert fleet["shed"] == 1 and fleet["lost"] == 0
        assert fleet["redispatched"] == 1
        assert fleet["drains"] == [{"replica": "r0",
                                    "reason": "dispatch OSError"}]
        assert fleet["by_replica"] == {"r1": 1}
        assert fleet["ttft_ms"] is not None
        # training-only logs stay fleet-free
        assert goodput.fleet_stats(
            [e for e in merged
             if not e["type"].startswith("router")]) is None

    def test_router_ttft_includes_queue_wait(self):
        """Router TTFT = wait for dispatch + replica-reported TTFT; a
        request stuck behind a full fleet must show the queueing."""
        hold = threading.Event()

        def transport(url, payload, timeout_s):
            if url.endswith("/generate") and payload["rid"] == 0:
                hold.wait(5.0)
            return _ok_reply(url, payload, timeout_s)

        r = Router(["http://a"], transport=transport, hedge_ms=0,
                   max_inflight_per_replica=1, scrape_interval_s=1e9)
        r.submit(0, [1])
        r.submit(1, [1])
        r.step()
        time.sleep(0.1)                      # rid 1 queues behind rid 0
        hold.set()
        _drive(r)
        later = next(q for q in r.completed if q.rid == 1)
        assert later.ttft_ms >= 100.0        # the wait is in the number


class TestTransport:
    def test_parse_gauges(self):
        text = ("# TYPE tpuframe_serve_queue_depth gauge\n"
                "tpuframe_serve_queue_depth 3\n"
                "tpuframe_serve_active_slots 2\n"
                "other_metric 9\n# EOF\n")
        out = router_lib.parse_gauges(
            text, ("tpuframe_serve_queue_depth",
                   "tpuframe_serve_active_slots"))
        assert out == {"tpuframe_serve_queue_depth": 3.0,
                       "tpuframe_serve_active_slots": 2.0}

    def test_http_transport_returns_http_errors_as_answers(self):
        """A 503 body must come back as (503, body) — not raise into the
        RetryPolicy and burn its budget (exporter-backed round trip)."""
        from tpuframe.obs import exporter

        ex = exporter.MetricsExporter(port=0).start()
        try:
            ex.add_handler("/gen", lambda body: (
                200, json.dumps({"echo": json.loads(body)["x"]}).encode()))
            base = f"http://127.0.0.1:{ex.port}"
            status, body = router_lib.http_transport(
                f"{base}/gen", {"x": 5}, 2.0)
            assert (status, body) == (200, {"echo": 5})
            status, _ = router_lib.http_transport(f"{base}/missing",
                                                  {"x": 1}, 2.0)
            assert status == 404             # returned, not raised
            status, body = router_lib.http_transport(
                f"{base}/healthz", None, 2.0)   # GET when payload is None
            assert status == 200 and body == "ok\n"
        finally:
            ex.stop()

    def test_check_is_clean(self):
        assert router_lib.check() == []
