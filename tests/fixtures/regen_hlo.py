"""Regenerate the golden optimized-HLO fixtures for the shardflow tests.

Usage (from the repo root — the same scrubbed CPU child env the gate
uses):

    JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS= \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python tests/fixtures/regen_hlo.py

Writes, per *compilable* registered strategy:

    tests/fixtures/hlo/<name>.hlo.gz     optimized-HLO module text
    tests/fixtures/hlo/goldens.json      parsed-graph shape pins + meta

The fixtures let ``tests/test_shardflow.py`` exercise the whole parser +
detector stack without compiling anything (no jax import at test time),
and the goldens pin the graph *shape* (computation/node/parameter/
collective counts) so a parser regression that silently drops nodes
fails loudly.  Regenerate on a jax upgrade; the goldens record the jax
version so the pin test skips rather than lies when the compiler moved.
"""

import gzip
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
OUT = os.path.join(REPO, "tests", "fixtures", "hlo")


def main() -> int:
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        print("set JAX_PLATFORMS=cpu (and the 8-device XLA_FLAGS) first",
              file=sys.stderr)
        return 2
    sys.path.insert(0, REPO)
    import jax

    from tpuframe.analysis import shardflow, strategies
    from tpuframe.analysis.collective_graph import graph_of_compiled

    os.makedirs(OUT, exist_ok=True)
    goldens = {"jax": jax.__version__, "n_devices": 8, "strategies": {}}
    for audit in strategies.audit_all(8):
        if audit.compiled is None:
            print(f"skip {audit.name}: {audit.reason or audit.status}")
            continue
        txt = audit.compiled.as_text()
        graph = graph_of_compiled(audit.compiled)
        fname = f"{audit.name}.hlo.gz"
        with gzip.open(os.path.join(OUT, fname), "wt",
                       compresslevel=9) as f:
            f.write(txt)
        goldens["strategies"][audit.name] = {
            "file": fname,
            "summary": graph.summary(),
            "mesh_shape": list(list(p) for p in audit.meta.mesh_shape),
            "wire_dtype": audit.meta.wire_dtype,
            "n_declared_leaves": len(audit.meta.declared_leaves),
            # analysis v3: the integer schedule/liveness record — must
            # stay byte-identical to the strategy's derived_schedule.json
            # entry (tests cross-check the two files against each other).
            "schedule": shardflow.derive_schedule_entry(
                graph, ignore_below=audit.budget.ignore_below),
        }
        print(f"wrote {fname}: {graph.summary()}")
    with open(os.path.join(OUT, "goldens.json"), "w") as f:
        json.dump(goldens, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote goldens.json ({len(goldens['strategies'])} strategies)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
