"""Gradient accumulation (Horovod's ``backward_passes_per_step``):
tpuframe.parallel.step's ``accum_steps``.

Golden invariant: for a stateless model (no BN), mean-of-microbatch-grads
equals the full-batch grad (linearity), so accum_steps=K must reproduce the
accum_steps=1 losses step for step — single-device AND on the DP mesh —
with one cross-replica reduction per optimizer step either way."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpuframe.models import losses
from tpuframe.parallel import mesh as mesh_lib, step as step_lib

HID = 16


def _setup(mesh, accum_steps, fusion_threshold=None, batch=16):
    rng = np.random.default_rng(0)
    params = {f"l{i}": jnp.asarray(rng.normal(size=(HID, HID)) * 0.4,
                                   jnp.float32) for i in range(4)}
    x = rng.normal(size=(batch, HID)).astype(np.float32)
    t = rng.normal(size=(batch, HID)).astype(np.float32)
    tx = optax.adam(1e-2)

    def loss_fn(params, model_state, batch, rng):
        y = batch["x"]
        for i in range(4):
            y = jnp.tanh(y @ params[f"l{i}"])
        loss = jnp.mean((y - batch["t"]) ** 2)
        return loss, ({}, {"mse": loss})

    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False,
                                    accum_steps=accum_steps,
                                    fusion_threshold=fusion_threshold)
    state = step_lib.TrainState.create(params, tx)
    batch = {"x": x, "t": t}
    if mesh is not None:
        state = step_lib.replicate_state(state, mesh)
        batch = jax.tree.map(
            lambda a: jax.device_put(a, mesh_lib.batch_sharding(mesh)), batch)
    return step, state, batch


def _losses(mesh, accum_steps, n=3, fusion_threshold=None):
    step, state, batch = _setup(mesh, accum_steps, fusion_threshold)
    out = []
    for _ in range(n):
        state, m = step(state, batch)
        out.append(float(m["loss"]))
    return out


def test_accum_matches_single_pass_unsharded():
    ref = _losses(None, 1)
    np.testing.assert_allclose(_losses(None, 2), ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(_losses(None, 4), ref, rtol=1e-5, atol=1e-6)
    assert ref[-1] < ref[0]


def test_accum_matches_single_pass_on_mesh(mesh8):
    ref = _losses(mesh8, 1)
    np.testing.assert_allclose(_losses(mesh8, 2), ref, rtol=1e-5, atol=1e-6)
    # and the DP golden invariant holds across accumulation too
    np.testing.assert_allclose(_losses(None, 2), ref, rtol=1e-5, atol=1e-6)


def test_accum_composes_with_fusion(mesh8):
    ref = _losses(mesh8, 1)
    got = _losses(mesh8, 2, fusion_threshold=64 << 20)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_accum_single_reduction_per_step(mesh8):
    """Horovod's wire semantics: collectives per optimizer step must NOT
    scale with accum_steps — grads stay local through the scan and reduce
    once at the end."""
    def n_all_reduce_ops(accum):
        step, state, batch = _setup(mesh8, accum, batch=64)
        txt = step.lower(state, batch).compile().as_text()
        return sum(1 for line in txt.splitlines()
                   if re.search(r"=.*\ball-reduce(?:-start)?\(", line))

    assert n_all_reduce_ops(4) <= n_all_reduce_ops(1) + 1


def test_accum_metrics_and_grad_norm_present():
    step, state, batch = _setup(None, 2)
    _, m = step(state, batch)
    assert set(m) == {"mse", "loss", "grad_norm"}
    assert np.isfinite(float(m["grad_norm"]))


@pytest.mark.slow
def test_accum_bn_model_runs(mesh8):
    """Mutable model state (BN stats) threads through the scan: stats after
    one accum step differ from the initial stats and stay replicated."""
    from tpuframe import models

    model = models.ResNet18(num_classes=10)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(16, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=(16,)).astype(np.int32)
    variables = model.init(jax.random.key(0), jnp.asarray(x[:2]))
    tx = optax.sgd(0.1)

    def loss_fn(params, model_state, batch, rng):
        logits, mut = model.apply({"params": params, **model_state},
                                  batch["x"], train=True,
                                  mutable=["batch_stats"])
        return losses.softmax_cross_entropy(logits, batch["y"]), (
            dict(mut), {})

    state = step_lib.TrainState.create(
        variables["params"], tx,
        model_state={"batch_stats": variables["batch_stats"]})
    state = step_lib.replicate_state(state, mesh8)
    step = step_lib.make_train_step(loss_fn, tx, mesh8, donate=False,
                                    accum_steps=2)
    batch = jax.tree.map(
        lambda a: jax.device_put(a, mesh_lib.batch_sharding(mesh8)),
        {"x": x, "y": y})
    new_state, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))
    b0 = jax.tree.leaves(state.model_state["batch_stats"])
    b1 = jax.tree.leaves(new_state.model_state["batch_stats"])
    assert any(not np.allclose(np.asarray(u), np.asarray(v))
               for u, v in zip(b0, b1))


def test_accum_indivisible_batch_raises(mesh8):
    step, state, batch = _setup(mesh8, 3)  # local batch 2 per device, accum 3
    with pytest.raises(ValueError, match="accum_steps=3 does not divide"):
        step(state, batch)


def test_accum_zero_rejected():
    with pytest.raises(ValueError, match="accum_steps must be >= 1"):
        _setup(None, 0)
