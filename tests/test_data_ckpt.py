"""Data pipeline + checkpoint tests (SURVEY.md §7 test strategy: the fake
cluster exercises host-sharding; golden restore/reshard invariants)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuframe import ckpt
from tpuframe.data import ArrayDataset, ShardedLoader, cifar10, glue_sst2, mnist
from tpuframe.data import gcs
from tpuframe.parallel import mesh as mesh_lib, step as step_lib


class TestDatasets:
    def test_synthetic_mnist_shapes(self):
        train, test = mnist()
        assert train[0]["image"].shape == (28, 28, 1)
        assert train[:4]["image"].shape == (4, 28, 28, 1)
        assert train[:4]["label"].dtype == np.int32
        assert len(test) < len(train)

    def test_synthetic_cifar_and_glue(self):
        train, _ = cifar10()
        assert train[:2]["image"].shape == (2, 32, 32, 3)
        train, _ = glue_sst2(seq_len=64)
        b = train[:3]
        assert b["input_ids"].shape == (3, 64)
        assert set(b) == {"input_ids", "attention_mask", "token_type_ids", "label"}

    def test_lm_text_padded_docs(self):
        from tpuframe.data.datasets import lm_text

        train, _ = lm_text(seq_len=32, vocab_size=64, synthetic_size=16,
                           padded_docs=True, pad_id=0)
        b = train[:16]
        ids, labels = b["input_ids"], b["labels"]
        assert ids.shape == (16, 32) and labels.shape == (16, 32)
        for i in range(16):
            ignored = np.where(labels[i] == -100)[0]
            assert len(ignored) > 0  # every doc shorter than seq_len+1
            lo = ignored[0]
            # ignore region is a suffix; ids padded with pad_id after it
            assert np.all(labels[i, lo:] == -100)
            np.testing.assert_array_equal(ids[i, lo + 1:],
                                          np.zeros(31 - lo, np.int32))
            # valid region still the shifted next-token targets
            np.testing.assert_array_equal(labels[i, :lo], ids[i, 1:lo + 1])
        with pytest.raises(ValueError, match="synthetic"):
            lm_text("/tmp/x", padded_docs=True)

    def test_shard_disjoint_and_equal(self):
        ds = ArrayDataset({"x": np.arange(103)})
        shards = [ds.shard(4, i) for i in range(4)]
        assert all(len(s) == 25 for s in shards)  # drop remainder
        seen = np.concatenate([s.columns["x"] for s in shards])
        assert len(np.unique(seen)) == 100

    def test_ragged_columns_rejected(self):
        with pytest.raises(ValueError):
            ArrayDataset({"x": np.arange(4), "y": np.arange(5)})

    def test_mnist_idx_file_roundtrip(self, tmp_path):
        """Write real idx-format files and read them back — the on-disk
        format the reference's torchvision MNIST loader consumes."""
        import gzip as gz
        import struct

        imgs = (np.arange(2 * 28 * 28) % 255).astype(np.uint8).reshape(2, 28, 28)
        lbls = np.array([3, 7], np.uint8)

        def idx_bytes(arr):
            header = struct.pack(">I", (0x08 << 0) | (arr.ndim & 0xFF))
            header = struct.pack(">I", 0x00000800 | arr.ndim)
            dims = b"".join(struct.pack(">I", d) for d in arr.shape)
            return header + dims + arr.tobytes()

        for name, arr in [("train-images-idx3-ubyte.gz", imgs),
                          ("train-labels-idx1-ubyte.gz", lbls),
                          ("t10k-images-idx3-ubyte.gz", imgs),
                          ("t10k-labels-idx1-ubyte.gz", lbls)]:
            (tmp_path / name).write_bytes(gz.compress(idx_bytes(arr)))
        train, test = mnist(str(tmp_path))
        assert train[:2]["image"].shape == (2, 28, 28, 1)
        assert float(train[:2]["image"].max()) <= 1.0
        np.testing.assert_array_equal(train[:2]["label"], [3, 7])


class TestShardedLoader:
    def test_batches_sharded_on_mesh(self, mesh8):
        train, _ = mnist(synthetic_size=256)
        loader = ShardedLoader(train, global_batch=32, mesh=mesh8, seed=1)
        batch = next(iter(loader))
        assert batch["image"].shape == (32, 28, 28, 1)
        assert isinstance(batch["image"].sharding, NamedSharding)
        assert batch["image"].sharding.spec == mesh_lib.batch_spec()
        # per-device shard is 4 rows
        assert batch["image"].addressable_shards[0].data.shape[0] == 4

    def test_epoch_determinism_and_reshuffle(self):
        train, _ = mnist(synthetic_size=128)
        a = ShardedLoader(train, 16, seed=7)
        b = ShardedLoader(train, 16, seed=7)
        ba, bb = next(a.epoch(0)), next(b.epoch(0))
        np.testing.assert_array_equal(np.asarray(ba["label"]),
                                      np.asarray(bb["label"]))
        b1 = next(a.epoch(1))
        assert not np.array_equal(np.asarray(ba["label"]), np.asarray(b1["label"]))

    def test_steps_per_epoch_and_divisibility_error(self, mesh8):
        train, _ = mnist(synthetic_size=128)
        loader = ShardedLoader(train, 32, mesh=mesh8)
        assert loader.steps_per_epoch() == 4
        with pytest.raises(ValueError):
            ShardedLoader(train, 12, mesh=mesh8)  # 12 % 8 != 0

    def test_infinite_iter_crosses_epochs(self):
        train, _ = mnist(synthetic_size=64)
        loader = ShardedLoader(train, 32, shuffle=False)
        it = iter(loader)
        seen = [next(it) for _ in range(5)]  # 2 steps/epoch -> crosses twice
        assert len(seen) == 5

    def test_from_step_exact_continuation_across_epoch_boundary(self):
        """Resume positioning (SURVEY.md §5.4): a stream restarted at
        step N must replay the exact remaining batch sequence of an
        uninterrupted run — including the reshuffle at the epoch
        boundary it crosses."""
        train, _ = mnist(synthetic_size=64)
        straight = ShardedLoader(train, 32, seed=5)  # 2 steps/epoch
        it = iter(straight)
        want = [next(it) for _ in range(6)][3:]  # steps 3..5: epochs 1-2
        resumed = ShardedLoader(train, 32, seed=5)
        got_it = resumed.from_step(3)
        got = [next(got_it) for _ in range(3)]
        for w, g in zip(want, got):
            np.testing.assert_array_equal(np.asarray(w["label"]),
                                          np.asarray(g["label"]))
            np.testing.assert_array_equal(np.asarray(w["image"]),
                                          np.asarray(g["image"]))

    def test_prefetch_worker_exception_propagates(self):
        """A crash inside the prefetch thread (decoder bug, bad shard)
        must surface in the consumer as the original exception, after
        the batches assembled before it — never a silent hang on an
        empty queue."""
        train, _ = mnist(synthetic_size=64)
        calls = {"n": 0}

        class _FlakyDataset:
            def __len__(self):
                return len(train)

            def __getitem__(self, idx):
                calls["n"] += 1
                if calls["n"] >= 3:
                    raise RuntimeError("decoder blew up")
                return train[idx]

        loader = ShardedLoader(_FlakyDataset(), 16, shuffle=False)
        it = loader.epoch(0)
        next(it), next(it)  # assembled before the fault: still delivered
        with pytest.raises(RuntimeError, match="decoder blew up"):
            for _ in it:
                pass

    def test_cast_floats_halves_infeed_and_matches_device_cast(self):
        import jax.numpy as jnp

        train, _ = mnist(synthetic_size=64)
        plain = next(ShardedLoader(train, 16, shuffle=False).epoch(0))
        cast = next(ShardedLoader(train, 16, shuffle=False,
                                  cast_floats=jnp.bfloat16).epoch(0))
        assert cast["image"].dtype == jnp.bfloat16
        assert cast["label"].dtype == plain["label"].dtype  # ints untouched
        # Host-side numpy rounding == on-device XLA convert (both RNE), so
        # feeding the cast batch is bit-identical to casting after transfer.
        np.testing.assert_array_equal(
            np.asarray(plain["image"].astype(jnp.bfloat16)),
            np.asarray(cast["image"]))


class TestGcsAbstraction:
    def test_local_roundtrip_and_atomicity(self, tmp_path):
        p = str(tmp_path / "a" / "b.bin")
        gcs.write_bytes(p, b"hello")
        assert gcs.read_bytes(p) == b"hello"
        assert gcs.exists(p)
        assert gcs.listdir(str(tmp_path)) == ["a"]
        assert not gcs.exists(str(tmp_path / "nope"))

    def test_gs_scheme_requires_usable_client(self):
        # sandbox has the library but no credentials; either way the error
        # must be our actionable RuntimeError, not a raw client traceback
        with pytest.raises(RuntimeError, match="google-cloud-storage"):
            gcs.read_bytes("gs://bucket/key")

    def test_join(self):
        assert gcs.join("gs://b", "x", "y") == "gs://b/x/y"


def _toy_state(mesh=None):
    tx = optax.adam(1e-3)
    params = {"w": jnp.arange(12.0).reshape(3, 4), "b": jnp.ones(())}
    state = step_lib.TrainState.create(params, tx)
    if mesh is not None:
        state = step_lib.replicate_state(state, mesh)
    return state


class TestCheckpoint:
    def test_save_restore_exact(self, tmp_path, mesh8):
        state = _toy_state(mesh8)
        ckpt.save(str(tmp_path), 10, state)
        # restore into the exact TrainState structure
        restored = ckpt.restore(str(tmp_path), 10, mesh=mesh8, target=state)
        assert isinstance(restored, step_lib.TrainState)
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      np.asarray(state.params["w"]))
        chex_all_equal_structs(state, restored)

    def test_restore_without_target_gives_nested_dict(self, tmp_path, mesh8):
        state = _toy_state(mesh8)
        ckpt.save(str(tmp_path), 3, state)
        tree = ckpt.restore(str(tmp_path), 3)
        assert isinstance(tree, dict)
        np.testing.assert_array_equal(tree["params"]["w"],
                                      np.asarray(state.params["w"]))

    def test_reshard_on_restore(self, tmp_path, mesh8):
        """Save sharded over 8 devices, restore onto a 4-device mesh —
        SURVEY.md §7 hard part 3 (8-chip ckpt onto 32 chips, scaled down)."""
        big = jnp.arange(64.0).reshape(8, 8)
        sharded = jax.device_put(big, NamedSharding(mesh8, P("data")))
        ckpt.save(str(tmp_path), 1, {"x": sharded})
        assert len({s["file"] for s in json.loads(
            gcs.read_bytes(str(tmp_path / "step_00000001" / "manifest.json"))
        )["leaves"]["x"]["shards"]}) == 8

        mesh4 = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=4),
                                   devices=jax.devices()[:4])
        target = {"x": jax.device_put(jnp.zeros((8, 8)),
                                      NamedSharding(mesh4, P("data")))}
        restored = ckpt.restore(str(tmp_path), 1, target=target)
        np.testing.assert_array_equal(np.asarray(restored["x"]), np.asarray(big))
        assert restored["x"].sharding.mesh.shape["data"] == 4

    def test_bf16_leaf_roundtrip(self, tmp_path, mesh8):
        """np.save round-trips ml_dtypes bfloat16 as void records; restore
        must reinterpret via the manifest dtype (code-review finding)."""
        tree = {"p": jnp.arange(6.0, dtype=jnp.bfloat16).reshape(2, 3)}
        ckpt.save(str(tmp_path), 1, tree)
        out = ckpt.restore(str(tmp_path), 1)
        assert out["p"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(np.asarray(out["p"], np.float32),
                                      np.arange(6.0).reshape(2, 3))

    def test_sharded_restore_reads_only_overlapping_shards(self, tmp_path, mesh8):
        """Sharded-target restore goes through the region reader."""
        big = jnp.arange(64.0).reshape(8, 8)
        sharded = jax.device_put(big, NamedSharding(mesh8, P("data")))
        ckpt.save(str(tmp_path), 1, {"x": sharded})
        target = {"x": sharded}
        restored = ckpt.restore(str(tmp_path), 1, target=target)
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      np.asarray(big))
        assert not restored["x"].sharding.is_fully_replicated

    def test_crc_detects_corruption(self, tmp_path, mesh8):
        state = _toy_state(mesh8)
        path = ckpt.save(str(tmp_path), 5, state)
        # corrupt one shard file
        victim = next(f for f in (tmp_path / "step_00000005").iterdir()
                      if f.name.endswith(".npy"))
        raw = bytearray(victim.read_bytes())
        raw[-1] ^= 0xFF
        victim.write_bytes(bytes(raw))
        with pytest.raises(IOError, match="CRC"):
            ckpt.restore(str(tmp_path), 5, mesh=mesh8, target=state)

    def test_structure_mismatch_raises(self, tmp_path, mesh8):
        state = _toy_state(mesh8)
        ckpt.save(str(tmp_path), 2, state)
        bad_target = {"nope": jnp.zeros(())}
        with pytest.raises(ValueError, match="structure mismatch"):
            ckpt.restore(str(tmp_path), 2, target=bad_target)

    def test_manager_retention_resume_and_torn_ckpt(self, tmp_path, mesh8):
        state = _toy_state(mesh8)
        mgr = ckpt.CheckpointManager(str(tmp_path), every_steps=10, keep=2)
        assert not mgr.should_save(5)
        for step in (10, 20, 30):
            assert mgr.maybe_save(step, state) is not None
        names = sorted(p.name for p in tmp_path.iterdir())
        assert names == ["step_00000020", "step_00000030"]  # keep=2
        # torn checkpoint (no COMMIT) must be ignored by resume
        torn = tmp_path / "step_00000040"
        torn.mkdir()
        (torn / "manifest.json").write_text("{}")
        step, restored = mgr.restore_latest(mesh=mesh8, target=state)
        assert step == 30
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      np.asarray(state.params["w"]))

    def test_restore_latest_empty(self, tmp_path):
        mgr = ckpt.CheckpointManager(str(tmp_path))
        assert mgr.restore_latest() is None

    def test_save_best_keeps_single_record(self, tmp_path, mesh8):
        """save_best: only improvements are kept, exactly one best dir
        exists, restore_best returns the winning step's state."""
        mgr = ckpt.CheckpointManager(str(tmp_path), every_steps=10)
        s1 = _toy_state(mesh8)
        s2 = jax.tree_util.tree_map(
            lambda a: a * 2 if jnp.issubdtype(a.dtype, jnp.floating) else a,
            s1)
        assert mgr.save_best(10, s1, 1.5) is True
        assert mgr.save_best(20, s2, 2.0) is False   # worse: not saved
        assert mgr.save_best(30, s2, 0.5) is True    # better: replaces
        best_dirs = [p.name for p in (tmp_path / "best").iterdir()
                     if p.is_dir()]
        assert best_dirs == ["step_00000030"]
        step, restored = mgr.restore_best(mesh=mesh8, target=s1)
        assert step == 30
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      np.asarray(s2.params["w"]))
        # max mode: higher wins
        mgr2 = ckpt.CheckpointManager(str(tmp_path / "m2"))
        assert mgr2.save_best(1, s1, 0.7, mode="max") is True
        assert mgr2.save_best(2, s2, 0.6, mode="max") is False
        step2, _ = mgr2.restore_best(mesh=mesh8, target=s1)
        assert step2 == 1
        with pytest.raises(ValueError, match="contradicts"):
            mgr2.save_best(3, s1, 0.1, mode="min")  # opposite-order record
        with pytest.raises(ValueError, match="mode"):
            mgr2.save_best(3, s1, 0.1, mode="best")

    def test_async_save_commits_and_roundtrips(self, tmp_path, mesh8):
        """async_write: save() returns before COMMIT; wait_pending() makes
        every queued save durable, in order, with retention applied; the
        snapshot is immune to the live tree changing after save()."""
        state = _toy_state(mesh8)
        mgr = ckpt.CheckpointManager(str(tmp_path), every_steps=10, keep=2,
                                     async_write=True)
        saved_w = np.array(np.asarray(state.params["w"]), copy=True)
        for step in (10, 20, 30):
            mgr.maybe_save(step, state)
            # mutate the live tree right after the snapshot — the async
            # writer must not see this (copy-on-prepare contract)
            state = jax.tree_util.tree_map(
                lambda a: a + 1.0
                if jnp.issubdtype(a.dtype, jnp.floating) else a, state)
        mgr.wait_pending()
        names = sorted(p.name for p in tmp_path.iterdir())
        assert names == ["step_00000020", "step_00000030"]  # keep=2
        assert (tmp_path / "step_00000030" / "COMMIT").exists()
        step, restored = mgr.restore_latest(mesh=mesh8,
                                            target=_toy_state(mesh8))
        assert step == 30
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      saved_w + 2.0)  # state at save #3


def chex_all_equal_structs(a, b):
    ja = jax.tree_util.tree_structure(a)
    jb = jax.tree_util.tree_structure(b)
    assert ja == jb, (ja, jb)


class TestPrepareImagenet:
    def _make_tree(self, root, n_classes=2, per_class=3):
        from PIL import Image

        rng = np.random.default_rng(0)
        for c in range(n_classes):
            d = root / f"n{c:08d}"
            d.mkdir(parents=True)
            for i in range(per_class):
                arr = rng.integers(0, 255, size=(40 + 8 * c, 64, 3),
                                   dtype=np.uint8)
                Image.fromarray(arr).save(d / f"img_{i}.JPEG")

    def test_prepare_and_load_roundtrip(self, tmp_path):
        from tpuframe.data import prepare_imagenet
        from tpuframe.data.datasets import imagenet

        src, out = tmp_path / "raw", tmp_path / "out"
        self._make_tree(src)
        n = prepare_imagenet.prepare(str(src), str(out), image_size=32,
                                     shard_size=4, workers=1)
        assert n == 2  # 6 examples, shard_size 4 -> 2 shards
        names = sorted(p.name for p in out.iterdir())
        assert "images_00000.npy" in names and "labels_00001.npy" in names
        assert "classes.txt" in names

        train, test = imagenet(str(out), image_size=32)
        total = len(train) + len(test)
        assert total == 6
        img = train[:1]["image"]
        assert img.dtype == np.float32 and img.shape[1:] == (32, 32, 3)
        # normalized: values centered near 0, not 0..255
        assert abs(float(img.mean())) < 3.0

    def test_decode_geometry(self, tmp_path):
        from PIL import Image

        from tpuframe.data import prepare_imagenet

        p = tmp_path / "x.jpg"
        Image.fromarray(np.zeros((100, 300, 3), np.uint8)).save(p)
        arr = prepare_imagenet.decode_one((str(p), 64, 0))
        assert arr.shape == (64, 64, 3) and arr.dtype == np.uint8


class TestAugment:
    """On-device augmentation (tpuframe/data/augment.py)."""

    def test_flip_is_per_image_and_deterministic(self):
        import jax
        import jax.numpy as jnp
        from tpuframe.data import augment

        imgs = jnp.arange(4 * 2 * 3 * 1, dtype=jnp.uint8).reshape(4, 2, 3, 1)
        a = augment.random_flip(imgs, jax.random.key(0))
        b = augment.random_flip(imgs, jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        flipped = np.asarray(a) != np.asarray(imgs)
        per_img = flipped.reshape(4, -1).any(axis=1)
        assert per_img.any()          # some flip...
        assert not per_img.all() or True  # (p=0.5 over 4: both possible)
        # a flipped image is exactly the W-reverse
        for i in range(4):
            if per_img[i]:
                np.testing.assert_array_equal(
                    np.asarray(a)[i], np.asarray(imgs)[i, :, ::-1, :])

    def test_pad_crop_flip_preserves_shape_and_content_bounds(self):
        import jax
        import jax.numpy as jnp
        from tpuframe.data import augment

        imgs = jnp.ones((8, 32, 32, 3), jnp.uint8) * 7
        out = augment.apply("pad_crop_flip", imgs, jax.random.key(1))
        assert out.shape == imgs.shape and out.dtype == imgs.dtype
        vals = set(np.unique(np.asarray(out)).tolist())
        assert vals <= {0, 7}          # original pixels or zero padding

    def test_crop_flip_requires_margin(self):
        import jax
        import jax.numpy as jnp
        import pytest as _pytest
        from tpuframe.data import augment

        imgs = jnp.zeros((2, 32, 32, 3), jnp.uint8)
        with _pytest.raises(ValueError, match="smaller"):
            augment.apply("crop_flip", imgs, jax.random.key(0), crop=64)
        out = augment.apply("crop_flip",
                            jnp.zeros((2, 40, 40, 3), jnp.uint8),
                            jax.random.key(0), crop=32)
        assert out.shape == (2, 32, 32, 3)

    def test_unknown_mode_raises(self):
        import jax
        import jax.numpy as jnp
        import pytest as _pytest
        from tpuframe.data import augment

        with _pytest.raises(ValueError, match="unknown augment"):
            augment.apply("mixup", jnp.zeros((1, 8, 8, 3)),
                          jax.random.key(0))

    def test_center_crop_matches_geometry(self):
        import jax.numpy as jnp
        from tpuframe.data import augment

        imgs = jnp.arange(2 * 8 * 8 * 1, dtype=jnp.float32).reshape(2, 8, 8, 1)
        out = augment.center_crop(imgs, 4)
        assert out.shape == (2, 4, 4, 1)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(imgs[:, 2:6, 2:6, :]))
        # size-match is the identity
        same = augment.center_crop(imgs, 8)
        np.testing.assert_array_equal(np.asarray(same), np.asarray(imgs))
        import pytest as _pytest
        with _pytest.raises(ValueError, match="smaller"):
            augment.center_crop(imgs, 16)

    def test_crop_flip_end_to_end_harness(self):
        """Train 2 steps with larger synthetic storage + crop_flip: train
        crops to augment_crop, eval center-crops — both paths compile."""
        from tpuframe import train as train_mod
        from tpuframe.utils import get_config

        cfg = get_config("imagenet_resnet50").with_overrides(
            total_steps=2, eval_every=2, eval_batches=1, global_batch=16,
            warmup_steps=1, log_every=1, compute_dtype="float32",
            augment="crop_flip", augment_crop=24,
            dataset_kwargs={"image_size": 32, "synthetic_size": 32,
                            "num_classes": 10},
            model_kwargs={"cifar_stem": True, "num_classes": 10})
        metrics = train_mod.train(cfg)
        assert np.isfinite(metrics["loss"])
