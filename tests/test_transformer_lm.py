"""TransformerLM + sequence-parallel training: the golden-loss invariant
(SURVEY.md §7 test strategy) extended to the seq axis — the same model, data
and seed must produce the same losses whether the sequence is sharded over
8 virtual devices (ring or Ulysses attention) or run unsharded on one."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpuframe import models
from tpuframe.models import losses
from tpuframe.models.transformer_lm import LMConfig, TransformerLM
from tpuframe.parallel import mesh as mesh_lib
from tpuframe.parallel import step as step_lib
from tpuframe.utils.config import get_config


def _data(b=8, s=64, vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, vocab, size=(b, s + 1)).astype(np.int32)
    return {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}


def _make_step(model, mesh, shard_seq):
    from jax.sharding import PartitionSpec as P

    tx = optax.adam(1e-3)

    def loss_fn(params, model_state, batch, rng):
        logits = model.apply({"params": params}, batch["input_ids"],
                             train=True, rngs={"dropout": rng})
        loss = losses.softmax_cross_entropy(logits, batch["labels"])
        return loss, ({}, {"acc": losses.accuracy(logits, batch["labels"])})

    kwargs = {}
    if shard_seq:
        part = P(mesh_lib.BATCH_AXES, "seq")
        kwargs = dict(batch_partition=part,
                      reduce_axes=(*mesh_lib.BATCH_AXES, "seq"))
    return tx, step_lib.make_train_step(loss_fn, tx, mesh, donate=False,
                                        **kwargs)


def _train_steps(seq_mode, n_steps=3, mesh_spec=None):
    cfg = LMConfig.tiny(vocab_size=64, seq_mode=seq_mode, max_seq=64)
    model = TransformerLM(cfg)
    batch = _data()
    variables = model.init(jax.random.key(0),
                           jnp.asarray(batch["input_ids"][:1]))

    mesh = mesh_lib.make_mesh(mesh_spec) if mesh_spec else None
    tx, train_step = _make_step(model, mesh, shard_seq=(seq_mode != "none"))
    state = step_lib.TrainState.create(variables["params"], tx)
    if mesh is not None:
        state = step_lib.replicate_state(state, mesh)
        from jax.sharding import NamedSharding, PartitionSpec as P
        part = (P(mesh_lib.BATCH_AXES, "seq") if seq_mode != "none"
                else mesh_lib.batch_spec())
        batch = jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, part)), batch)

    lost = []
    for _ in range(n_steps):
        state, metrics = train_step(state, batch)
        lost.append(float(metrics["loss"]))
    return lost


def test_ring_golden_loss_vs_unsharded():
    ref = _train_steps("none")
    got = _train_steps("ring", mesh_spec=mesh_lib.MeshSpec(data=2, seq=4))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)
    assert ref[-1] < ref[0]  # actually learning


def test_ulysses_golden_loss_vs_unsharded():
    ref = _train_steps("none")
    got = _train_steps("ulysses", mesh_spec=mesh_lib.MeshSpec(data=2, seq=4))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_remat_matches_no_remat():
    batch = _data(b=2, s=32)
    outs = []
    for remat in (False, True):
        cfg = LMConfig.tiny(vocab_size=64, remat=remat, max_seq=32)
        model = TransformerLM(cfg)
        v = model.init(jax.random.key(0), jnp.asarray(batch["input_ids"]))

        def loss(params):
            logits = model.apply({"params": params},
                                 jnp.asarray(batch["input_ids"]), train=True,
                                 rngs={"dropout": jax.random.key(1)})
            return losses.softmax_cross_entropy(logits,
                                                jnp.asarray(batch["labels"]))

        l, g = jax.value_and_grad(loss)(v["params"])
        outs.append((l, g))
    np.testing.assert_allclose(outs[0][0], outs[1][0], rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
                 outs[0][1], outs[1][1])


def test_registry_and_config():
    model = models.get_model("transformer-lm", tiny=True)
    assert isinstance(model, TransformerLM)
    cfg = get_config("lm_smoke")
    assert cfg.shard_seq and cfg.mesh.seq == 4


def test_rope_position_offset_consistency():
    """RoPE with global offsets: a chunked forward with explicit positions
    equals the full-sequence forward — the property the seq-sharded model
    relies on (lax.axis_index offset)."""
    from tpuframe.models.transformer_lm import rope

    x = jax.random.normal(jax.random.key(0), (1, 16, 2, 8))
    full = rope(x, jnp.arange(16), 10000.0)
    lo = rope(x[:, :8], jnp.arange(8), 10000.0)
    hi = rope(x[:, 8:], 8 + jnp.arange(8), 10000.0)
    np.testing.assert_allclose(jnp.concatenate([lo, hi], axis=1), full,
                               rtol=1e-6, atol=1e-6)
