"""tpuframe.resilience: retry policies, structured fault injection, the
preemption contract, checkpoint quarantine/walk-back, and the hardened
supervisor (docs/DESIGN.md "Failure model & resilience").

Everything here is fast tier-1: recovery demos run the smoke workload
in-process on the virtual CPU mesh; timing behavior uses fake clocks.
"""

import json
import os
import random
import signal
import time

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpuframe import ckpt
from tpuframe import train as train_mod
from tpuframe.data import gcs
from tpuframe.launch.launcher import run_with_relaunch
from tpuframe.obs import metrics
from tpuframe.obs.heartbeat import Heartbeat
from tpuframe.parallel import step as step_lib
from tpuframe.resilience import RC_PREEMPTED, PreemptionGuard, RetryPolicy
from tpuframe.resilience import faults
from tpuframe.resilience.policy import is_retryable
from tpuframe.utils import get_config


@pytest.fixture(autouse=True)
def _clean_resilience_state(monkeypatch):
    """Every test starts with no armed faults and zeroed retry counters,
    and leaves none behind for the rest of the suite."""
    monkeypatch.delenv("TPUFRAME_FAULTS", raising=False)
    monkeypatch.delenv("TPUFRAME_FAULT_STEP", raising=False)
    monkeypatch.delenv("TPUFRAME_FAULT_ONCE", raising=False)
    faults.reset_from_env()
    metrics.reset_counters("retry.")
    yield
    faults.reset_from_env({})
    metrics.reset_counters("retry.")


# ---------------------------------------------------------------------------
# RetryPolicy: classification and timing (fake clock — no real sleeps)
# ---------------------------------------------------------------------------


class _FakeTime:
    def __init__(self):
        self.now = 0.0
        self.sleeps = []

    def clock(self):
        return self.now

    def sleep(self, s):
        self.sleeps.append(s)
        self.now += s


class _FixedRng:
    """uniform() returns the upper bound — makes jitter deterministic."""

    def uniform(self, a, b):
        return b


def _policy(ft, **kw):
    kw.setdefault("max_attempts", 4)
    kw.setdefault("base_delay_s", 0.1)
    kw.setdefault("max_delay_s", 10.0)
    kw.setdefault("deadline_s", 1000.0)
    return RetryPolicy(clock=ft.clock, sleep=ft.sleep, rng=_FixedRng(), **kw)


class TestRetryPolicy:
    def test_transient_failure_recovers(self):
        ft = _FakeTime()
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionResetError("peer reset")
            return "ok"

        metrics.reset_counters("retry.")
        assert _policy(ft).call(flaky, op="t") == "ok"
        assert len(calls) == 3
        got = metrics.counters("retry.")
        assert got["retry.t.retries"] == 2
        assert got["retry.t.recovered"] == 1

    def test_backoff_is_exponential_with_cap(self):
        ft = _FakeTime()

        def always():
            raise TimeoutError("slow")

        with pytest.raises(TimeoutError):
            _policy(ft, max_attempts=6, max_delay_s=1.0).call(always, op="t")
        # _FixedRng takes the top of [base, prev*3] each round, so delays
        # triple until the cap: 0.3, 0.9, 1.0, 1.0, 1.0 (5 sleeps, 6 tries).
        np.testing.assert_allclose(ft.sleeps, [0.3, 0.9, 1.0, 1.0, 1.0])

    def test_deadline_stops_retrying_early(self):
        ft = _FakeTime()
        calls = []

        def always():
            calls.append(1)
            ft.now += 30.0  # each attempt burns 30s of fake time
            raise TimeoutError("slow")

        with pytest.raises(TimeoutError):
            _policy(ft, max_attempts=100, deadline_s=60.0).call(always, op="t")
        assert len(calls) < 5  # nowhere near 100 attempts
        assert metrics.counters("retry.")["retry.t.exhausted"] == 1

    def test_non_retryable_raises_immediately(self):
        ft = _FakeTime()
        calls = []

        def missing():
            calls.append(1)
            raise FileNotFoundError("no such object")

        with pytest.raises(FileNotFoundError):
            _policy(ft).call(missing, op="t")
        assert len(calls) == 1 and ft.sleeps == []

    def test_classification(self):
        assert is_retryable(ConnectionResetError("x"))
        assert is_retryable(TimeoutError("x"))
        assert is_retryable(OSError("generic I/O"))
        assert is_retryable(faults.InjectedFault("x"))
        assert not is_retryable(FileNotFoundError("x"))
        assert not is_retryable(PermissionError("x"))
        assert not is_retryable(ValueError("x"))
        # google-cloud transients are classified by class name, so the
        # check works without the library installed.
        ServiceUnavailable = type("ServiceUnavailable", (Exception,), {})
        assert is_retryable(ServiceUnavailable("503"))


# ---------------------------------------------------------------------------
# Fault spec parsing + the legacy alias
# ---------------------------------------------------------------------------


class TestFaultSpec:
    def test_parse_full_grammar(self):
        fs = faults.parse("gcs_read:step=13:kind=ioerror,"
                          "ckpt_shard:kind=corrupt,"
                          "host:step=20:kind=sigterm:once=1:times=3")
        assert [f.seam for f in fs] == ["gcs_read", "ckpt_shard", "host"]
        assert fs[0].step == 13 and fs[0].kind == "ioerror"
        assert fs[2].once and fs[2].times == 3

    def test_parse_rejects_unknowns_loudly(self):
        with pytest.raises(ValueError, match="unknown fault seam"):
            faults.parse("tpu_melt:step=1")
        with pytest.raises(ValueError, match="unknown fault kind"):
            faults.parse("gcs_read:kind=explode")
        with pytest.raises(ValueError, match="unknown fault option"):
            faults.parse("gcs_read:when=later")

    def test_removed_legacy_env_raises_with_spelling(self):
        """The pre-grammar aliases are gone — setting one must raise with
        the exact TPUFRAME_FAULTS spelling, never be silently ignored (a
        fault the operator thinks is armed but never fires turns every
        resilience proof downstream into a false pass)."""
        with pytest.raises(RuntimeError,
                           match=r"host:step=7:kind=crash:once=1"):
            faults.reset_from_env(
                {"TPUFRAME_FAULT_STEP": "7", "TPUFRAME_FAULT_ONCE": "1"})
        with pytest.raises(RuntimeError, match="TPUFRAME_FAULT_ONCE"):
            faults.reset_from_env({"TPUFRAME_FAULT_ONCE": "1"})
        # the modern spelling of the same fault still arms and still
        # honours the once=1 resumed-run drop
        reg = faults.reset_from_env(
            {"TPUFRAME_FAULTS": "host:step=7:kind=crash:once=1"})
        f = reg.faults[-1]
        assert (f.seam, f.kind, f.step, f.once) == ("host", "crash", 7, True)
        reg.set_resumed(True)
        assert reg.faults == []

    def test_ioerror_fires_once_per_times(self):
        reg = faults.FaultRegistry(faults.parse("gcs_read:times=2"))
        for _ in range(2):
            with pytest.raises(faults.InjectedFault):
                reg.fire("gcs_read")
        reg.fire("gcs_read")  # armed count spent — no-op

    def test_mangle_corrupt_and_torn(self):
        reg = faults.FaultRegistry(
            faults.parse("ckpt_shard:kind=corrupt,ckpt_shard:kind=torn"))
        data = bytes(range(64))
        bad = reg.mangle("ckpt_shard", data)
        assert len(bad) == len(data) and bad != data
        torn = reg.mangle("ckpt_shard", data)
        assert len(torn) == len(data) // 2
        assert reg.mangle("ckpt_shard", data) == data  # spent


# ---------------------------------------------------------------------------
# gcs layer: injected faults are retried, counters surface
# ---------------------------------------------------------------------------


def test_gcs_read_retries_injected_ioerrors(tmp_path, monkeypatch):
    p = tmp_path / "obj.bin"
    p.write_bytes(b"payload")
    monkeypatch.setenv("TPUFRAME_FAULTS", "gcs_read:kind=ioerror:times=2")
    faults.reset_from_env()
    metrics.reset_counters("retry.")
    assert gcs.read_bytes(str(p)) == b"payload"
    got = metrics.counters("retry.")
    assert got["retry.gcs_read.retries"] == 2
    assert got["retry.gcs_read.recovered"] == 1


def test_gcs_missing_file_not_retried(tmp_path):
    metrics.reset_counters("retry.")
    with pytest.raises(FileNotFoundError):
        gcs.read_bytes(str(tmp_path / "absent"))
    assert metrics.counters("retry.") == {}


# ---------------------------------------------------------------------------
# Checkpoint quarantine + walk-back
# ---------------------------------------------------------------------------


def _toy_state():
    return step_lib.TrainState.create(
        {"w": jnp.arange(12.0).reshape(3, 4), "b": jnp.ones(())},
        optax.adam(1e-3))


def _save_two(tmp_path, state):
    ckpt.save(str(tmp_path), 1, state)
    ckpt.save(str(tmp_path), 2, state)


class TestQuarantineWalkBack:
    def test_corrupt_latest_shard_walks_back(self, tmp_path, capsys):
        state = _toy_state()
        _save_two(tmp_path, state)
        shard = next((tmp_path / "step_00000002").glob("*.npy"))
        raw = bytearray(shard.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        shard.write_bytes(bytes(raw))

        mgr = ckpt.CheckpointManager(str(tmp_path))
        step, tree = mgr.restore_latest(target=state)
        assert step == 1
        np.testing.assert_array_equal(np.asarray(tree.params["w"]),
                                      np.asarray(state.params["w"]))
        assert (tmp_path / "step_00000002.corrupt").is_dir()
        assert not (tmp_path / "step_00000002").exists()
        assert "quarantined" in capsys.readouterr().out
        # quarantined dirs are invisible to latest_step forever after
        assert ckpt.latest_step(str(tmp_path)) == 1

    def test_torn_manifest_walks_back(self, tmp_path):
        state = _toy_state()
        _save_two(tmp_path, state)
        (tmp_path / "step_00000002" / "manifest.json").write_bytes(
            b'{"leaves": {"trunc')
        step, _ = ckpt.CheckpointManager(str(tmp_path)).restore_latest(
            target=state)
        assert step == 1
        assert (tmp_path / "step_00000002.corrupt").is_dir()

    def test_all_checkpoints_bad_returns_none(self, tmp_path):
        state = _toy_state()
        ckpt.save(str(tmp_path), 1, state)
        for shard in (tmp_path / "step_00000001").glob("*.npy"):
            shard.unlink()
        assert ckpt.CheckpointManager(str(tmp_path)).restore_latest(
            target=state) is None
        assert (tmp_path / "step_00000001.corrupt").is_dir()

    def test_structure_mismatch_still_raises(self, tmp_path):
        """A target/treedef disagreement is a config error, not storage
        corruption — walking back would mask it on every misconfigured
        job, so it must raise."""
        state = _toy_state()
        ckpt.save(str(tmp_path), 1, state)
        wrong_target = {"completely": jnp.zeros(3), "different": jnp.ones(2)}
        with pytest.raises(ValueError):
            ckpt.CheckpointManager(str(tmp_path)).restore_latest(
                target=wrong_target)
        assert (tmp_path / "step_00000001").is_dir()  # NOT quarantined

    def test_shard_fault_at_save_is_caught_at_restore(self, tmp_path,
                                                      monkeypatch):
        """kind=corrupt mangles the bytes written while the manifest CRC
        covers the clean bytes — exactly a storage-side flip, which the
        restore CRC check must catch and quarantine."""
        state = _toy_state()
        ckpt.save(str(tmp_path), 1, state)
        monkeypatch.setenv("TPUFRAME_FAULTS", "ckpt_shard:kind=corrupt")
        faults.reset_from_env()
        ckpt.save(str(tmp_path), 2, state)
        step, _ = ckpt.CheckpointManager(str(tmp_path)).restore_latest(
            target=state)
        assert step == 1
        assert (tmp_path / "step_00000002.corrupt").is_dir()


# ---------------------------------------------------------------------------
# Preemption contract: SIGTERM → checkpoint at step boundary → rc 14 → resume
# ---------------------------------------------------------------------------


def _smoke_cfg(tmp_path, **over):
    over.setdefault("distributed", False)
    over.setdefault("total_steps", 6)
    over.setdefault("log_every", 2)
    over.setdefault("eval_every", 1000)
    over.setdefault("ckpt_every", 10)  # periodic saves out of the way
    over.setdefault("global_batch", 16)
    over.setdefault("ckpt_dir", str(tmp_path / "ck"))
    return get_config("smoke").with_overrides(**over)


class TestPreemption:
    def test_guard_turns_sigterm_into_flag(self):
        with PreemptionGuard() as guard:
            assert not guard.requested
            os.kill(os.getpid(), signal.SIGTERM)
            assert guard.requested
            assert guard.signal_name == "SIGTERM"

    def test_second_sigint_escalates(self):
        guard = PreemptionGuard().install()
        try:
            os.kill(os.getpid(), signal.SIGINT)
            assert guard.requested
            with pytest.raises(KeyboardInterrupt):
                os.kill(os.getpid(), signal.SIGINT)
        finally:
            guard.uninstall()

    def test_second_sigterm_escalates_to_kill(self, tmp_path):
        """A second SIGTERM (the supervisor's kill-after-grace) must
        actually terminate a wedged run — re-delivered with the guard
        uninstalled, so the default action fires.  Subprocess: the
        escalation kills the whole process by design."""
        import subprocess
        import sys
        import textwrap

        script = textwrap.dedent("""
            import os, signal
            from tpuframe.resilience.preempt import PreemptionGuard
            g = PreemptionGuard().install()
            os.kill(os.getpid(), signal.SIGTERM)
            assert g.requested and g.signal_name == "SIGTERM"
            os.kill(os.getpid(), signal.SIGTERM)  # escalation: no return
            print("SHIELDED")  # must be unreachable
        """)
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""})
        out = subprocess.run([sys.executable, "-c", script], env=env,
                             capture_output=True, text=True, timeout=120)
        assert out.returncode == -signal.SIGTERM, (out.returncode,
                                                   out.stderr[-800:])
        assert "SHIELDED" not in out.stdout

    def test_reassert_takes_signal_back(self):
        """jax.distributed's preemption notifier steals SIGTERM after the
        guard installs; reassert() must reclaim it (regression: preemption
        silently disabled under the local fake cluster)."""
        guard = PreemptionGuard().install()
        try:
            signal.signal(signal.SIGTERM, lambda s, f: None)  # the thief
            guard.reassert()
            os.kill(os.getpid(), signal.SIGTERM)
            assert guard.requested
        finally:
            guard.uninstall()

    def test_sigterm_mid_run_checkpoints_and_exits_14(self, tmp_path,
                                                      monkeypatch):
        monkeypatch.setenv("TPUFRAME_FAULTS", "host:step=3:kind=sigterm")
        with pytest.raises(SystemExit) as ei:
            train_mod.train(_smoke_cfg(tmp_path))
        assert ei.value.code == RC_PREEMPTED
        # the final checkpoint is COMMITTED at the preempted boundary
        assert (tmp_path / "ck" / "step_00000003" / "COMMIT").exists()
        assert ckpt.latest_step(str(tmp_path / "ck")) == 3

        # ...and a clean resume finishes the job from there
        monkeypatch.delenv("TPUFRAME_FAULTS")
        metrics_out = train_mod.train(_smoke_cfg(tmp_path))
        assert metrics_out["step"] == 6

    def test_supervisor_resumes_preempted_job_to_completion(self, tmp_path,
                                                            monkeypatch):
        """End-to-end contract: preemption costs the supervisor nothing —
        rc 14 relaunches immediately with zero relaunch budget."""
        monkeypatch.setenv("TPUFRAME_FAULTS", "host:step=3:kind=sigterm")
        out = {}

        def run_once():
            try:
                out.update(train_mod.train(_smoke_cfg(tmp_path)))
                return 0
            except SystemExit as e:
                return int(e.code)

        msgs = []
        rc = run_with_relaunch(run_once, 0, log=msgs.append,
                               sleep=lambda s: None)
        assert rc == 0
        assert out["step"] == 6
        assert any("preempted" in m for m in msgs)


# ---------------------------------------------------------------------------
# Supervisor hardening: backoff, crash loops, budget refresh
# ---------------------------------------------------------------------------


class TestSupervisor:
    def test_backoff_doubles_with_cap(self):
        sleeps = []

        def run_once():
            return 1

        rc = run_with_relaunch(
            run_once, 5, log=lambda m: None, sleep=sleeps.append,
            backoff_base_s=1.0, backoff_max_s=4.0,
            rng=_FixedRng())  # uniform() -> upper bound, i.e. delay itself
        assert rc == 1
        np.testing.assert_allclose(sleeps, [1.0, 2.0, 4.0, 4.0, 4.0])

    def test_preempted_rc_skips_backoff_and_budget(self):
        rcs = iter([RC_PREEMPTED, RC_PREEMPTED, 0])
        sleeps = []
        rc = run_with_relaunch(lambda: next(rcs), 0, log=lambda m: None,
                               sleep=sleeps.append)
        assert rc == 0
        assert sleeps == []  # no backoff, no budget consumed

    def test_crash_loop_without_progress_gives_up_early(self):
        calls = {"n": 0}

        def run_once():
            calls["n"] += 1
            return 42

        msgs = []
        rc = run_with_relaunch(run_once, 100, log=msgs.append,
                               sleep=lambda s: None, progress=lambda: 5,
                               max_stalled=2)
        assert rc == 42
        assert calls["n"] == 3  # initial + 2 stalled relaunches, not 101
        assert any("crash loop" in m for m in msgs)

    def test_checkpoint_progress_refreshes_budget(self):
        state = {"n": 0, "step": 0}

        def run_once():
            state["n"] += 1
            state["step"] += 10  # every attempt commits a new checkpoint
            return 13 if state["n"] < 6 else 0

        msgs = []
        rc = run_with_relaunch(run_once, 1, log=msgs.append,
                               sleep=lambda s: None,
                               progress=lambda: state["step"])
        # budget of ONE relaunch survives five failures because each one
        # made checkpoint progress
        assert rc == 0
        assert state["n"] == 6
        assert any("budget refreshed" in m for m in msgs)


# ---------------------------------------------------------------------------
# Satellites
# ---------------------------------------------------------------------------


def test_heartbeat_survives_broken_on_stall_callback(caplog):
    import logging

    def bad_callback(idle):
        raise RuntimeError("observer bug")

    hb = Heartbeat(timeout_s=0.05, poll_s=0.01, on_stall=bad_callback)
    with caplog.at_level(logging.ERROR, logger="tpuframe.obs.heartbeat"):
        hb.start()
        # `stalled` flips just before the callback runs, so poll for the
        # logged traceback itself, not the flag.
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and not any(
                "on_stall callback raised" in r.message
                for r in caplog.records):
            time.sleep(0.01)
    assert hb.stalled
    assert hb._thread.is_alive()  # the watchdog outlived the bad callback
    assert any("on_stall callback raised" in r.message
               for r in caplog.records)
    hb.stop()


def test_metrics_counters_roundtrip():
    metrics.reset_counters()
    metrics.bump("retry.x.retries")
    metrics.bump("retry.x.retries", 2)
    metrics.bump("other.thing")
    assert metrics.counters("retry.") == {"retry.x.retries": 3}
    assert metrics.counters()["other.thing"] == 1
    metrics.reset_counters("retry.")
    assert metrics.counters("retry.") == {}
    assert metrics.counters()["other.thing"] == 1
    metrics.reset_counters()


def test_retry_counters_reach_train_metrics(tmp_path, monkeypatch):
    """Acceptance demo (a): injected gcs_read IOErrors are retried and the
    run completes with retry counts in the returned metrics."""
    monkeypatch.setenv("TPUFRAME_FAULTS", "gcs_read:kind=ioerror:times=2")
    metrics.reset_counters("retry.")
    out = train_mod.train(_smoke_cfg(tmp_path, total_steps=4, ckpt_every=2))
    assert out["step"] == 4
    assert out.get("retry.gcs_read.retries", 0) == 2
    assert out.get("retry.gcs_read.recovered", 0) == 1
