"""uint8 end-to-end image pipeline (``keep_u8=True``): images stay u8 on
the host (4x less RAM than f32) and over the host→device link (1 byte/px
— half the bf16 infeed cast), with normalization moved on-device
(train._maybe_normalize → XLA fusion on TPU, the native FFI kernel on
CPU hosts).  The parity test pins that moving the normalize across the
link changes nothing but rounding order."""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuframe import train as train_mod
from tpuframe.data import ShardedLoader, datasets
from tpuframe.utils import get_config


def _tiny_cfg(**kw):
    # Synthetic imagenet carries 1000-class labels: the head must match
    # (the harness rejects a smaller head at build time).
    return get_config("imagenet_resnet50").with_overrides(
        total_steps=2, global_batch=8, warmup_steps=1, log_every=1,
        eval_every=2, eval_batches=1, compute_dtype="float32",
        model_kwargs={"cifar_stem": True},
        dataset_kwargs={"image_size": 32, "synthetic_size": 64, **kw})


def test_synthetic_u8_stays_u8_through_loader():
    train, _ = datasets.imagenet(None, image_size=32, synthetic_size=64,
                                 keep_u8=True)
    assert train.columns["image"].dtype == np.uint8
    batch = next(ShardedLoader(train, 16, shuffle=False,
                               cast_floats=jnp.bfloat16).epoch(0))
    # cast_floats must not touch integer inputs: u8 rides the link as u8.
    assert batch["image"].dtype == jnp.uint8


@pytest.mark.slow
def test_harness_runs_u8_end_to_end():
    metrics = train_mod.train(_tiny_cfg(keep_u8=True))
    assert metrics["step"] == 2
    assert np.isfinite(metrics["loss"])


@pytest.mark.slow
def test_real_shard_u8_vs_f32_parity(tmp_path):
    """The SAME u8 shard data through both paths — host-normalized f32
    (the default) vs u8-to-device + on-device normalize — must produce
    the same training losses up to rounding order."""
    rng = np.random.default_rng(0)
    # 1024 rows: the builder's 99/1 train/eval split must leave the eval
    # side at least one full batch.
    imgs = rng.integers(0, 256, size=(1024, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, size=(1024,)).astype(np.int64)
    np.save(tmp_path / "images_00000.npy", imgs)
    np.save(tmp_path / "labels_00000.npy", labels)

    losses = {}
    for keep_u8 in (False, True):
        cfg = _tiny_cfg(keep_u8=keep_u8).with_overrides(
            data_dir=str(tmp_path))
        losses[keep_u8] = train_mod.train(cfg)["loss"]
    assert abs(losses[True] - losses[False]) < 1e-4, losses


def test_maybe_normalize_real_vs_host_branch_match():
    """On-device normalize == the f32 builder branch's host normalize."""
    rng = np.random.default_rng(1)
    x = rng.integers(0, 256, size=(4, 8, 8, 3)).astype(np.uint8)
    host = ((x.astype(np.float32) / 255.0) - datasets.IMAGENET_MEAN) \
        / datasets.IMAGENET_STD
    cfg = _tiny_cfg().with_overrides(data_dir="/nonexistent-marker")
    dev = train_mod._maybe_normalize(cfg, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(dev), host, rtol=2e-6, atol=2e-6)


def test_maybe_normalize_passthrough_f32():
    x = jnp.zeros((2, 8, 8, 3), jnp.float32)
    assert train_mod._maybe_normalize(_tiny_cfg(), x) is x


def test_cifar_u8_end_to_end():
    cfg = get_config("cifar10_resnet18").with_overrides(
        total_steps=2, global_batch=8, warmup_steps=1, log_every=1,
        eval_every=2, eval_batches=1,
        dataset_kwargs={"synthetic_size": 64, "keep_u8": True})
    metrics = train_mod.train(cfg)
    assert metrics["step"] == 2
    assert np.isfinite(metrics["loss"]) and np.isfinite(metrics["eval_loss"])


def test_label_range_vs_head_mismatch_rejected():
    """A head smaller than the label range used to 'train' on all-zero
    one-hot rows (garbage loss, NaN eval); the harness now rejects it at
    build time with an actionable message."""
    import pytest

    cfg = _tiny_cfg().with_overrides(model_kwargs={"num_classes": 10})
    with pytest.raises(ValueError, match="num_classes=10"):
        train_mod.build_harness(cfg)
