"""MoE routing (tpuframe.ops.moe) + expert-parallel train step.

Covers the contract items of ``route_topk``: hand-computable dispatch and
combine tensors, capacity-overflow dropping with residual pass-through,
top-k combine renormalization, the Switch load-balance aux loss value on a
hand-checked case, and the golden invariants: MoEMLP with E=k=1 equals the
plain dense FFN computed from the same expert weights, and an
``moe_experts>0`` LM train step on a dp×expert mesh matches the unsharded
single-device run (SURVEY.md §7 golden-loss strategy extended to the
``expert`` axis)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpuframe.models import losses
from tpuframe.models.transformer_lm import LMConfig, MoEMLP, TransformerLM
from tpuframe.ops import moe
from tpuframe.parallel import mesh as mesh_lib
from tpuframe.parallel import step as step_lib


def _softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


class TestCapacityFor:
    def test_covers_even_load(self):
        # 100 tokens, 4 experts, k=1, factor 1.0 → ≥ 25 slots/expert.
        assert moe.capacity_for(100, 4, 1, 1.0) >= 25

    def test_multiple_of_four_and_min(self):
        for t, e, k, f in [(8, 8, 1, 0.1), (100, 4, 2, 1.25), (7, 3, 2, 1.0)]:
            c = moe.capacity_for(t, e, k, f)
            assert c % 4 == 0 and c >= 4

    def test_scales_with_k(self):
        assert moe.capacity_for(64, 4, 2, 1.0) >= 2 * moe.capacity_for(
            64, 4, 1, 1.0) - 4


class TestRouteTopK:
    def test_k1_dispatch_slots_in_order(self):
        # Tokens 0,1 prefer expert 0; tokens 2,3 prefer expert 1.
        logits = jnp.asarray([[4.0, 0.0], [4.0, 0.0],
                              [0.0, 4.0], [0.0, 4.0]], jnp.float32)
        dispatch, combine, _ = moe.route_topk(logits, k=1, capacity=4)
        d = np.asarray(dispatch)
        # (token, expert, slot): queue positions assigned in token order.
        assert d[0, 0, 0] == 1 and d[1, 0, 1] == 1
        assert d[2, 1, 0] == 1 and d[3, 1, 1] == 1
        assert d.sum() == 4  # exactly one slot per token
        # k=1 combine weight renormalizes to 1 on the dispatched slot.
        np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)),
                                   np.ones(4), atol=1e-6)

    def test_capacity_overflow_drops_with_residual_semantics(self):
        # All 6 tokens prefer expert 0; capacity 4 → tokens 4,5 dropped
        # (all-zero combine row — the residual connection carries them).
        logits = jnp.tile(jnp.asarray([[9.0, 0.0]], jnp.float32), (6, 1))
        dispatch, combine, _ = moe.route_topk(logits, k=1, capacity=4)
        d, c = np.asarray(dispatch), np.asarray(combine)
        assert d[:4, 0].sum() == 4          # first four tokens seated
        assert d[4:].sum() == 0             # overflow: no slot anywhere
        assert np.all(c[4:] == 0.0)         # zero combine → pass-through
        np.testing.assert_allclose(c[:4].sum(axis=(1, 2)), np.ones(4),
                                   atol=1e-6)

    def test_topk_combine_renormalization(self):
        rng = np.random.default_rng(3)
        logits = rng.normal(size=(16, 4)).astype(np.float32)
        dispatch, combine, _ = moe.route_topk(jnp.asarray(logits), k=2,
                                              capacity=16)
        gates = _softmax(logits)
        c = np.asarray(combine)
        for t in range(16):
            top2 = np.argsort(gates[t])[::-1][:2]
            g1, g2 = gates[t, top2[0]], gates[t, top2[1]]
            # Each token's two combine weights are its two gates
            # renormalized to sum to 1, placed on its chosen experts.
            np.testing.assert_allclose(c[t, top2[0]].sum(), g1 / (g1 + g2),
                                       atol=1e-5)
            np.testing.assert_allclose(c[t, top2[1]].sum(), g2 / (g1 + g2),
                                       atol=1e-5)
            np.testing.assert_allclose(c[t].sum(), 1.0, atol=1e-5)

    def test_switch_aux_loss_hand_value(self):
        # Hand case: 4 tokens, 2 experts. Three route to expert 0, one to
        # expert 1 (first choice, pre-capacity): ce = [0.75, 0.25].
        logits = np.asarray([[2.0, 0.0], [2.0, 0.0], [2.0, 0.0], [0.0, 2.0]],
                            np.float32)
        gates = _softmax(logits)
        me = gates.mean(axis=0)
        expected = 2.0 * (me[0] * 0.75 + me[1] * 0.25)
        _, _, aux = moe.route_topk(jnp.asarray(logits), k=1, capacity=4)
        np.testing.assert_allclose(float(aux), expected, atol=1e-6)

    def test_aux_loss_balanced_is_lower(self):
        balanced = jnp.asarray([[3.0, 0.0], [0.0, 3.0]] * 4, jnp.float32)
        skewed = jnp.tile(jnp.asarray([[3.0, 0.0]], jnp.float32), (8, 1))
        _, _, aux_b = moe.route_topk(balanced, k=1, capacity=8)
        _, _, aux_s = moe.route_topk(skewed, k=1, capacity=8)
        assert float(aux_b) < float(aux_s)


class TestMoEMLP:
    def test_e1_k1_equals_dense_ffn(self):
        # With one expert and k=1 the routed path must reduce exactly to
        # gelu(x @ up) @ down with combine weight 1 — the golden-vs-dense
        # invariant at the layer level.
        cfg = LMConfig.tiny(moe_experts=1, moe_k=1, hidden_size=16,
                            intermediate_size=32)
        layer = MoEMLP(cfg)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(2, 8, 16)), jnp.float32)
        variables = layer.init(jax.random.key(0), x)
        y, _ = layer.apply(variables, x, mutable=["aux_loss"])
        up = variables["params"]["up_experts"][0]
        down = variables["params"]["down_experts"][0]
        tokens = np.asarray(x).reshape(-1, 16)
        expected = jax.nn.gelu(tokens @ np.asarray(up)) @ np.asarray(down)
        np.testing.assert_allclose(np.asarray(y).reshape(-1, 16),
                                   np.asarray(expected), atol=1e-5)

    def test_aux_loss_sown(self):
        cfg = LMConfig.tiny(moe_experts=4, moe_k=2, hidden_size=16,
                            intermediate_size=32)
        layer = MoEMLP(cfg)
        x = jnp.ones((1, 8, 16), jnp.float32)
        variables = layer.init(jax.random.key(0), x)
        _, sown = layer.apply({"params": variables["params"]}, x,
                              mutable=["aux_loss"])
        aux = jax.tree.leaves(sown)
        assert len(aux) == 1 and np.asarray(aux[0]).shape == ()


def _moe_losses(mesh_spec, n_steps=3, aux_weight=0.0):
    """Train a tiny MoE LM for a few steps; ample capacity so no tokens are
    dropped (local-vs-global routing then agrees between shardings).

    ``aux_weight`` defaults to 0 for the golden comparison: the Switch aux
    loss is a product of per-routing-group means (me·ce), so its value under
    per-shard routing is mathematically different from the unsharded global
    value — expected behavior, not a defect; the aux metric itself is
    compared loosely in the test."""
    cfg = LMConfig.tiny(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, intermediate_size=64, max_seq=32,
                        moe_experts=4, moe_k=2, moe_every=2,
                        moe_capacity_factor=4.0)
    model = TransformerLM(cfg)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 64, size=(8, 33)).astype(np.int32)
    batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
    variables = model.init(jax.random.key(0),
                           jnp.asarray(batch["input_ids"][:1]))
    tx = optax.adam(1e-3)

    def loss_fn(params, model_state, batch, rng):
        logits, sown = model.apply({"params": params}, batch["input_ids"],
                                   train=True, rngs={"dropout": rng},
                                   mutable=["aux_loss"])
        loss = losses.softmax_cross_entropy(logits, batch["labels"])
        aux = sum(jax.tree.leaves(sown)) / max(len(jax.tree.leaves(sown)), 1)
        return loss + aux_weight * aux, ({}, {"moe_aux": aux})

    mesh = mesh_lib.make_mesh(mesh_spec) if mesh_spec else None
    train_step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False)
    state = step_lib.TrainState.create(variables["params"], tx)
    if mesh is not None:
        state = step_lib.replicate_state(state, mesh)
        batch = jax.tree.map(
            lambda x: jax.device_put(x, mesh_lib.batch_sharding(mesh)), batch)

    out = []
    for _ in range(n_steps):
        state, metrics = train_step(state, batch)
        out.append((float(metrics["loss"]), float(metrics["moe_aux"])))
    return out


@pytest.mark.slow
def test_moe_train_step_dp_expert_mesh_golden():
    ref = _moe_losses(None)
    got = _moe_losses(mesh_lib.MeshSpec(data=4, expert=2))
    np.testing.assert_allclose([l for l, _ in got], [l for l, _ in ref],
                               rtol=2e-5, atol=2e-5)
    assert ref[-1][0] < ref[0][0]  # learning
    assert all(a > 0 for _, a in ref)  # aux loss active
    # Aux is a per-routing-group statistic (see _moe_losses docstring):
    # pmean of per-shard values tracks the global value only approximately.
    for (_, a_got), (_, a_ref) in zip(got, ref):
        np.testing.assert_allclose(a_got, a_ref, rtol=0.2)


def test_moe_train_step_with_aux_weight_runs():
    # The full harness path (aux folded into the differentiated loss) on the
    # dp×expert mesh: must run and learn; exact golden equality is covered
    # by the aux_weight=0 test above.
    out = _moe_losses(mesh_lib.MeshSpec(data=4, expert=2), aux_weight=0.01)
    assert out[-1][0] < out[0][0]
