"""The wire-format seam end to end: resolution precedence (env > tuning
DB > default, stale rows demote silently), TF115 seam lint, shardflow
registration + seeded positive, derived-budget byte ratios for the int8
strategies, and golden-loss parity of the int8 wire against fp for both
weight-update modes.

Numerics use the legacy ``jax.experimental.shard_map`` idiom
(``check_rep=False``) so the suite runs on pre-vma jax too.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpuframe.analysis import shardflow, source_lint
from tpuframe.parallel import quantwire, step as step_lib, zero1
from tpuframe.tune import db as tune_db


# ---------------------------------------------------------------------------
# Resolution precedence: env > tune_db > default.
# ---------------------------------------------------------------------------


def _wire_rec(program="train_lm_b8", family="wire_format_lm",
              gen="v5e", fmt="int8-block"):
    return {"program": program, "family": family, "fingerprint": "fp0",
            "topology": "v5e:2x2", "generation": gen,
            "config": {"wire_format": fmt, "batch": 8},
            "predicted": {"predicted_ms": 1.0, "bound": "hbm",
                          "fits": True, "vmem_bytes": 0,
                          "bytes_lower_bound": True}}


@pytest.fixture
def wire_db(tmp_path, monkeypatch):
    """A tuning DB with one swept int8-block winner, wired into the env
    the way the resolution chain reads it; the generation gate is left
    CLOSED (no gen env) — tests open it explicitly."""
    path = str(tmp_path / "tune_db.json")
    db = tune_db.TuningDB(path)
    db.add(_wire_rec())
    db.save()
    monkeypatch.setenv("TPUFRAME_TUNE_DB", path)
    monkeypatch.delenv("TPUFRAME_WIRE_FORMAT", raising=False)
    monkeypatch.delenv("TPUFRAME_TUNE_GEN", raising=False)
    monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
    return path


class TestResolution:
    def test_default_is_fp(self, wire_db):
        # DB exists but the generation gate is closed -> hard default.
        assert quantwire.resolve("train_lm_b8", "wire_format_lm") \
            == ("fp", "default")

    def test_db_elected_when_generation_matches(self, wire_db, monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        assert quantwire.resolve("train_lm_b8", "wire_format_lm") \
            == ("int8-block", "tune_db")
        # family fallback: unknown program, known family
        assert quantwire.resolve("train_other_b4", "wire_format_lm") \
            == ("int8-block", "tune_db")

    def test_generation_gate(self, wire_db, monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v4")
        assert quantwire.resolve("train_lm_b8", "wire_format_lm") \
            == ("fp", "default")

    def test_env_beats_db(self, wire_db, monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        monkeypatch.setenv(quantwire.ENV_VAR, "fp")
        assert quantwire.resolve("train_lm_b8", "wire_format_lm") \
            == ("fp", "env")

    def test_env_invalid_raises(self, monkeypatch):
        # An explicit ask for something unknown is an error, never a
        # silent demotion — only DB rows demote silently.
        monkeypatch.setenv(quantwire.ENV_VAR, "int4-sparse")
        with pytest.raises(ValueError, match="int4-sparse"):
            quantwire.resolve()

    def test_stale_db_row_demotes_silently(self, tmp_path, monkeypatch):
        # A DB written by a future/older tpuframe may elect a format this
        # build doesn't know.  That must fall back to fp, not raise.
        path = str(tmp_path / "tune_db.json")
        db = tune_db.TuningDB(path)
        db.add(_wire_rec(fmt="int3-exotic"))
        db.save()
        monkeypatch.setenv("TPUFRAME_TUNE_DB", path)
        monkeypatch.delenv("TPUFRAME_WIRE_FORMAT", raising=False)
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
        assert quantwire.resolve("train_lm_b8", "wire_format_lm") \
            == ("fp", "default")

    def test_self_check_clean(self, monkeypatch):
        monkeypatch.delenv(quantwire.ENV_VAR, raising=False)
        assert quantwire.check() == []


# ---------------------------------------------------------------------------
# TF115: raw lax collectives in the wire-format seam.
# ---------------------------------------------------------------------------

_SEAM_PATH = "tpuframe/parallel/step.py"
_RAW_SRC = ("from jax import lax\n"
            "\n"
            "def _mean(x, ax):\n"
            "    return lax.psum(x, ax)\n")


class TestTF115:
    def test_flags_raw_collective_in_seam(self):
        found = [f for f in source_lint.lint_source(_RAW_SRC, _SEAM_PATH)
                 if f.rule == "TF115"]
        assert found and "wire" in found[0].message

    def test_other_modules_are_out_of_scope(self):
        findings = source_lint.lint_source(
            _RAW_SRC, "tpuframe/parallel/collectives.py")
        assert not [f for f in findings if f.rule == "TF115"]

    def test_pmean_is_the_fp_dispatch_target(self):
        # pmean IS what the resolved fp wire lowers to — flagging it
        # would make the seam unable to implement its own default.
        src = ("from jax import lax\n"
               "\n"
               "def _mean(x, ax):\n"
               "    return lax.pmean(x, ax)\n")
        findings = source_lint.lint_source(src, _SEAM_PATH)
        assert not [f for f in findings if f.rule == "TF115"]

    def test_suppression_on_the_call_line(self):
        src = ("from jax import lax\n"
               "\n"
               "def _norm(x, ax):\n"
               "    return lax.psum(x, ax)  # tf-lint: ok[TF115] scalar\n")
        findings = source_lint.lint_source(src, _SEAM_PATH)
        assert not [f for f in findings if f.rule == "TF115"]

    def test_real_seam_files_are_clean(self):
        import tpuframe.parallel as pp
        root = pp.__path__[0]
        findings = source_lint.lint_paths(
            [f"{root}/step.py", f"{root}/zero1.py"])
        assert not [f for f in findings if f.rule == "TF115"], findings


# ---------------------------------------------------------------------------
# shardflow: registration + the seeded positive.
# ---------------------------------------------------------------------------


class TestShardflowWire:
    def test_int8_block_registered(self):
        formats = shardflow.registered_wire_formats()
        assert formats.get("int8-block") == frozenset({"s8"})

    def test_seeded_positive_round_trip(self):
        # Clean registry: the seeded f32 all-reduce is exempted by no
        # narrow format, so the self-test passes...
        assert shardflow.seeded_wire_positive() == []
        # ...and a format registration claiming f32 is "narrow" must
        # trip it (a blinded wire_dtype detector fails loudly).
        shardflow.register_wire_format("f32-leak", {"s8", "f32"})
        try:
            assert shardflow.seeded_wire_positive() != []
        finally:
            del shardflow._WIRE_FORMATS["f32-leak"]
        assert shardflow.seeded_wire_positive() == []


# ---------------------------------------------------------------------------
# Derived budgets: the int8 strategies' wire bytes vs their fp twins.
# ---------------------------------------------------------------------------


def test_derived_budget_quantized_ratio():
    """The checked-in derived budgets must show the 4x per-leg drop: each
    quantized leg (s8 all-to-all for the reduce-scatter phase, s8
    all-gather back) carries 1/4 the bytes of the f32 gradient payload
    it replaced."""
    dp = shardflow.derived_for("dp")
    dpq = shardflow.derived_for("dp-int8")
    if dp is None or dpq is None:
        pytest.skip("derived budgets not emitted for this jax")
    a2a = dpq["above_floor"].get("all-to-all", 0)
    ag = dpq["above_floor"].get("all-gather", 0)
    assert a2a > 0 and a2a == ag, dpq["above_floor"]
    # dp's gradient all-reduce total (full census; the few non-gradient
    # scalar reduces add well under 2%).
    fp_bytes = dp["kinds"]["all-reduce"]["bytes"]
    assert abs(4 * a2a - fp_bytes) / fp_bytes < 0.02, (a2a, fp_bytes)

    dz = shardflow.derived_for("dp-zero1")
    dzq = shardflow.derived_for("dp-zero1-int8")
    if dz is None or dzq is None:
        pytest.skip("zero1 derived budgets not emitted for this jax")
    a2a_z = dzq["above_floor"].get("all-to-all", 0)
    ag_z = dzq["above_floor"].get("all-gather", 0)
    assert a2a_z > 0 and a2a_z == ag_z, dzq["above_floor"]
    rs_bytes = dz["kinds"]["reduce-scatter"]["bytes"]
    assert abs(4 * a2a_z - rs_bytes) / rs_bytes < 0.02, (a2a_z, rs_bytes)


# ---------------------------------------------------------------------------
# Golden loss: the int8 wire must track fp training, both update modes.
# ---------------------------------------------------------------------------


def _make_loss():
    def loss_fn(params, model_state, batch, rng_):
        x, y = batch
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        pred = h @ params["w2"] + params["b2"]
        return jnp.mean((pred - y) ** 2), (model_state, {})
    return loss_fn


def _init_params(key):
    k1, k2 = jax.random.split(key)
    return {"w1": jax.random.normal(k1, (32, 64)) * 0.1,
            "b1": jnp.zeros((64,)),
            "w2": jax.random.normal(k2, (64, 8)) * 0.1,
            "b2": jnp.zeros((8,))}


def _run(mesh, wire, weight_update="replicated", steps=25):
    import optax

    tx = optax.sgd(0.05, momentum=0.9)
    params = _init_params(jax.random.key(1))
    if weight_update == "zero1":
        state = zero1.make_state(params, tx, mesh)
    else:
        state = step_lib.TrainState.create(params, tx)
        state = step_lib.replicate_state(state, mesh)
    train = step_lib.make_train_step(_make_loss(), tx, mesh,
                                     weight_update=weight_update,
                                     wire_format=wire, donate=False)
    key = jax.random.key(2)
    w_true = jax.random.normal(jax.random.key(7), (32, 8))
    losses = []
    for _ in range(steps):
        key, k1 = jax.random.split(key)
        x = jax.random.normal(k1, (64, 32))
        y = jnp.sin(x @ w_true)
        state, metrics = train(state, (x, y))
        losses.append(float(metrics["loss"]))
    return np.array(losses)


@pytest.mark.parametrize("weight_update", ["replicated", "zero1"])
def test_golden_loss_int8_tracks_fp(mesh8, weight_update):
    """Loss-trajectory parity, the documented acceptance bound: per-step
    |loss_int8 - loss_fp| <= 2e-3 over the run (observed ~3e-5), and the
    int8 run itself trains."""
    l_fp = _run(mesh8, "fp", weight_update)
    l_q = _run(mesh8, "int8-block", weight_update)
    assert l_q[-1] < l_fp[0], "int8 run did not train"
    d = np.abs(l_q - l_fp)
    assert d.max() <= 2e-3, (weight_update, d.max())


def test_unknown_wire_format_rejected_at_build(mesh8):
    import optax

    with pytest.raises(ValueError, match="wire format"):
        step_lib.make_train_step(_make_loss(), optax.sgd(0.1), mesh8,
                                 wire_format="int5-wild")
