"""Core distributed tests: mesh construction, collectives, hvd facade, step.

Mirrors the reference's implicit invariants (SURVEY.md §7 test strategy):
the golden DP-correctness test — N-device gradients must equal 1-device
gradients on the same global batch — is the SPMD analog of Horovod's
allreduce-averaging contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from tpuframe.parallel import collectives, hvd, step as step_lib
from tpuframe.parallel import mesh as mesh_lib


class TestMesh:
    def test_default_mesh_is_pure_dp(self, mesh8):
        assert mesh8.shape["data"] == 8
        for ax in mesh_lib.AXES[1:]:
            assert mesh8.shape[ax] == 1
        assert mesh_lib.data_parallel_size(mesh8) == 8

    def test_wildcard_resolution(self):
        sizes = mesh_lib.MeshSpec(data=-1, model=2).sizes(8)
        assert sizes["data"] == 4 and sizes["model"] == 2

    def test_bad_divisibility_raises(self):
        with pytest.raises(ValueError):
            mesh_lib.MeshSpec(data=3).sizes(8)
        with pytest.raises(ValueError):
            mesh_lib.MeshSpec(data=-1, model=-1).sizes(8)

    def test_mesh42(self, mesh42):
        assert mesh42.shape["data"] == 4 and mesh42.shape["model"] == 2
        assert mesh_lib.data_parallel_size(mesh42) == 4

    def test_local_batch_size(self, mesh8):
        assert mesh_lib.local_batch_size(mesh8, 64) == 64  # single host
        with pytest.raises(ValueError):
            mesh_lib.local_batch_size(mesh8, 13)


class TestCollectives:
    def test_allreduce_mean_sum(self, mesh8):
        def body(x):
            return (collectives.allreduce(x, "data", average=True),
                    collectives.allreduce(x, "data", average=False))

        f = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=(P(), P())))
        x = np.arange(8.0)
        mean, total = f(x)
        assert mean[0] == pytest.approx(3.5)
        assert total[0] == pytest.approx(28.0)

    def test_allreduce_identity_unmapped(self):
        x = jnp.ones((3,))
        np.testing.assert_array_equal(collectives.allreduce(x), x)

    def test_broadcast_root(self, mesh8):
        def body(x):
            return collectives.broadcast(x, "data", root=3)

        f = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=P("data")))
        out = f(np.arange(8.0))
        np.testing.assert_array_equal(np.asarray(out), np.full(8, 3.0))

    def test_allgather(self, mesh8):
        def body(x):
            return collectives.allgather(x, "data")

        f = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=P("data")))
        out = np.asarray(f(np.arange(8.0))).reshape(8, 8)
        np.testing.assert_array_equal(out[0], np.arange(8.0))

    def test_ring_permute(self, mesh8):
        def body(x):
            return collectives.ring_permute(x, "data", shift=1)

        f = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=P("data")))
        out = np.asarray(f(np.arange(8.0)))
        np.testing.assert_array_equal(out, np.roll(np.arange(8.0), 1))

    def test_alltoall(self, mesh8):
        def body(x):
            return collectives.alltoall(x, "data", split_axis=0, concat_axis=0)

        f = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=P("data")))
        x = np.arange(64.0).reshape(64, 1)  # 8 rows/shard, split 8 ways
        out = np.asarray(f(x)).reshape(8, 8)
        # shard i row j == shard j row i of input blocks
        blocks = x.reshape(8, 8)
        np.testing.assert_array_equal(out, blocks.T)

    def test_reduce_scatter(self, mesh8):
        def body(x):
            return collectives.reduce_scatter(x, "data")

        f = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=P("data")))
        x = np.ones((64,))  # each shard holds 8 ones
        out = np.asarray(f(x))
        np.testing.assert_array_equal(out, np.full(8, 8.0))

    def test_global_norm_allreduced(self, mesh8):
        def body(x):
            return collectives.global_norm({"g": x}, axis="data")

        f = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=P()))
        x = np.ones((8,))
        assert float(f(x)) == pytest.approx(np.sqrt(8.0))

    def test_cross_replica_mean_host_level(self, mesh8):
        out = collectives.cross_replica_mean({"acc": 0.5}, mesh8)
        assert float(out["acc"]) == pytest.approx(0.5)

    def test_allreduce_partial_axis_binding(self):
        """Under pmap only 'data' is bound; allreduce over the default
        ('data','fsdp') must still reduce over the bound subset (code-review
        finding: the all-or-nothing check silently skipped the reduction)."""
        f = jax.pmap(lambda x: collectives.allreduce(x, axis=("data", "fsdp")),
                     axis_name="data")
        out = np.asarray(f(np.arange(8.0)))
        np.testing.assert_allclose(out, np.full(8, 3.5))

    def test_collectives_identity_unmapped(self):
        """allgather/alltoall/ring_permute/reduce_scatter must no-op outside a
        mapped context (single-process mode), like allreduce/broadcast."""
        x = jnp.arange(4.0)
        for fn in (collectives.allgather, collectives.alltoall,
                   collectives.ring_permute, collectives.reduce_scatter,
                   collectives.broadcast):
            np.testing.assert_array_equal(np.asarray(fn(x)), np.asarray(x))


class TestHvdFacade:
    def test_size_rank(self):
        hvd.init()
        assert hvd.size() == 8
        assert hvd.rank() == 0
        assert hvd.local_rank() == 0
        assert hvd.is_primary()

    def test_allgather_alltoall_grouped_verbs(self, mesh8):
        """The porting-surface extras: hvd.allgather / alltoall /
        grouped_allreduce inside a mapped step; barrier/join/shutdown are
        host-side and exercised single-process."""
        def body(x):
            gathered = hvd.allgather(x, axis=("data",))
            pair = hvd.grouped_allreduce([x, 2 * x], axis=("data",))
            # collective outputs are replica-identical but vma-varying;
            # pmean makes them provably unvarying for the P() out_specs
            return jax.tree.map(lambda t: jax.lax.pmean(t, "data"),
                                (gathered, pair[0], pair[1]))

        f = jax.jit(jax.shard_map(
            body, mesh=mesh8, in_specs=P("data"),
            out_specs=(jax.sharding.PartitionSpec(),) * 3))
        xs = np.arange(8.0, dtype=np.float32)
        gathered, a, b = f(xs)
        np.testing.assert_array_equal(np.asarray(gathered), xs)
        assert float(a[0]) == pytest.approx(3.5)     # mean over replicas
        assert float(b[0]) == pytest.approx(7.0)
        # uniform splits are the static-shape case and must pass through;
        # only genuinely ragged (unequal) splits are rejected
        np.testing.assert_array_equal(
            np.asarray(hvd.alltoall(jnp.arange(8.0), splits=[1] * 8)),
            np.arange(8.0))
        with pytest.raises(NotImplementedError, match="UNEQUAL"):
            hvd.alltoall(jnp.zeros((8,)), splits=[2, 6])
        assert hvd.join() == -1     # barrier-backed; single-process no-op
        hvd.barrier()
        hvd.shutdown()              # idempotent

    def test_distributed_optimizer_averages(self, mesh8):
        tx = hvd.DistributedOptimizer(optax.sgd(1.0), axis=("data",))

        def body(g):
            state = tx.init({"w": jnp.zeros(())})
            updates, _ = tx.update({"w": g}, state, {"w": jnp.zeros(())})
            return updates["w"]

        f = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=P()))
        upd = f(np.arange(8.0))
        # sgd(1.0) update = -avg(grad) = -3.5
        assert float(upd[0]) == pytest.approx(-3.5)

    def test_distributed_optimizer_identity_unmapped(self):
        tx = hvd.DistributedOptimizer(optax.sgd(0.1))
        params = {"w": jnp.ones(())}
        state = tx.init(params)
        updates, _ = tx.update({"w": jnp.ones(())}, state, params)
        assert float(updates["w"]) == pytest.approx(-0.1)

    def test_distributed_optimizer_with_autodiff_grads(self, mesh8):
        """Grads from jax.grad w.r.t. replicated params arrive pre-psum'd
        (vma-unvarying); DistributedOptimizer must still produce the average,
        matching hvd semantics exactly."""
        tx = hvd.DistributedOptimizer(optax.sgd(1.0), axis=("data",))

        def body(w, xs):
            g = jax.grad(lambda w: jnp.mean(w * xs))(w)  # pre-summed by vma
            state = tx.init(w)
            updates, _ = tx.update(g, state, w)
            return updates

        f = jax.jit(jax.shard_map(body, mesh=mesh8,
                                  in_specs=(P(), P("data")), out_specs=P()))
        xs = np.arange(32.0, dtype=np.float32)
        upd = f(jnp.zeros(()), xs)
        # average grad = mean(xs) = 15.5 → sgd(1.0) update = -15.5
        assert float(upd) == pytest.approx(-15.5)

    def test_distributed_optimizer_sum_not_double_counted(self, mesh8):
        """average=False with autodiff (pre-psum'd) grads must give the sum
        once, not world_size× (code-review finding)."""
        tx = hvd.DistributedOptimizer(optax.sgd(1.0), axis=("data",),
                                      average=False)

        def body(w, xs):
            g = jax.grad(lambda w: jnp.mean(w * xs))(w)  # pre-summed
            state = tx.init(w)
            updates, _ = tx.update(g, state, w)
            return updates

        f = jax.jit(jax.shard_map(body, mesh=mesh8,
                                  in_specs=(P(), P("data")), out_specs=P()))
        xs = np.arange(32.0, dtype=np.float32)
        upd = f(jnp.zeros(()), xs)
        # sum of per-shard grads = sum of local means = 8 * 15.5 = 124
        assert float(upd) == pytest.approx(-124.0)

    def test_bf16_compression_preserves_native_bf16(self, mesh8):
        """bf16-native grads must come back bf16, not upcast to f32
        (code-review finding: decompress keyed on dtype, not provenance)."""
        tx = hvd.DistributedOptimizer(optax.sgd(1.0), axis=("data",),
                                      compression="bf16")

        def body(g):
            params = {"w": jnp.zeros((), jnp.bfloat16)}
            state = tx.init(params)
            updates, _ = tx.update({"w": g}, state, params)
            return updates["w"]

        f = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=P()))
        out = f(np.full(8, 2.0, np.float32).astype(jnp.bfloat16))
        assert out.dtype == jnp.bfloat16

    def test_bf16_compression_roundtrip(self, mesh8):
        tx = hvd.DistributedOptimizer(optax.sgd(1.0), axis=("data",),
                                      compression="bf16")

        def body(g):
            state = tx.init({"w": jnp.zeros(())})
            updates, _ = tx.update({"w": g}, state, {"w": jnp.zeros(())})
            return updates["w"]

        f = jax.jit(jax.shard_map(body, mesh=mesh8, in_specs=P("data"),
                                  out_specs=P()))
        upd = f(np.full(8, 2.0))
        assert upd.dtype == jnp.float32
        assert float(upd[0]) == pytest.approx(-2.0)


def _toy_loss(params, model_state, batch, rng):
    del rng
    x, y = batch["x"], batch["y"]
    pred = x @ params["w"] + params["b"]
    loss = jnp.mean((pred - y) ** 2)
    return loss, (model_state, {"mse": loss})


def _toy_batch(n=32, d=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = np.arange(d, dtype=np.float32)
    y = x @ w + 0.1 * rng.normal(size=(n,)).astype(np.float32)
    return {"x": x, "y": y}


class TestTrainStep:
    def _init_state(self, tx, d=4):
        params = {"w": jnp.zeros((d,)), "b": jnp.zeros(())}
        return step_lib.TrainState.create(params, tx)

    def test_golden_dp_equals_single_device(self, mesh8):
        """THE DP-correctness invariant (SURVEY.md §7): same global batch,
        same seed ⇒ 8-way sharded step produces identical params to the
        unsharded step."""
        tx = optax.sgd(0.05)
        batch = _toy_batch()

        single = step_lib.make_train_step(_toy_loss, tx, None, donate=False)
        dist = step_lib.make_train_step(_toy_loss, tx, mesh8, donate=False)

        s1, m1 = single(self._init_state(tx), batch)
        s8, m8 = dist(self._init_state(tx), batch)

        np.testing.assert_allclose(np.asarray(s1.params["w"]),
                                   np.asarray(s8.params["w"]), rtol=1e-5)
        np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]), rtol=1e-5)
        assert int(s8.step) == 1

    def test_jit_mode_matches_shard_map(self, mesh8):
        tx = optax.sgd(0.05)
        batch = _toy_batch()
        a = step_lib.make_train_step(_toy_loss, tx, mesh8, mode="shard_map",
                                     donate=False)
        b = step_lib.make_train_step(_toy_loss, tx, mesh8, mode="jit",
                                     donate=False)
        sa, _ = a(self._init_state(tx), batch)
        sb, _ = b(self._init_state(tx), batch)
        np.testing.assert_allclose(np.asarray(sa.params["w"]),
                                   np.asarray(sb.params["w"]), rtol=1e-5)

    def test_loss_decreases(self, mesh8):
        tx = optax.sgd(0.1)
        train = step_lib.make_train_step(_toy_loss, tx, mesh8, donate=False)
        state = self._init_state(tx)
        batch = _toy_batch()
        losses = []
        for _ in range(20):
            state, m = train(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < 0.1 * losses[0]

    def test_eval_step_averages(self, mesh8):
        def metric_fn(params, model_state, batch):
            return {"mean_y": jnp.mean(batch["y"])}

        ev = step_lib.make_eval_step(metric_fn, mesh8)
        tx = optax.sgd(0.1)
        state = self._init_state(tx)
        batch = _toy_batch()
        out = ev(state, batch)
        assert float(out["mean_y"]) == pytest.approx(float(np.mean(batch["y"])),
                                                     rel=1e-5)

    def test_collectives_in_compiled_program(self, mesh8):
        """The compiled DP step must actually contain an all-reduce — the
        SPMD analog of asserting NCCL was invoked."""
        tx = optax.sgd(0.05)
        train = step_lib.make_train_step(_toy_loss, tx, mesh8, donate=False)
        state = self._init_state(tx)
        batch = _toy_batch()
        compiled = train.lower(state, batch).compile()
        hlo = compiled.as_text()
        assert "all-reduce" in hlo
