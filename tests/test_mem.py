"""tpuframe.mem — the rematerialization policy registry (ISSUE PR 5).

Golden invariant: every policy is a *schedule* decision, never a numeric
one — wrapping the loss in ``jax.checkpoint`` under any saveable
predicate must reproduce the ``none`` losses step for step (recompute
replays the identical forward ops).  The searched winner can then be
applied from the tuning DB without re-validating training math.

Also pinned here: env/DB resolution precedence (explicit env > legacy
alias > tune_db > default), the legacy ``TPUFRAME_BENCH_REMAT`` fold-in,
the donation audit over compiled HLO alias tables, the TF108 lint that
keeps bare remat out of model/step code, the bytes-MFU (HBM-roofline
utilization) math, and the ``(tag, policy)`` keying of the offline A/B
parser."""

import json
import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpuframe import mem
from tpuframe.mem import policy as mem_policy
from tpuframe.models import losses, resnet
from tpuframe.parallel import mesh as mesh_lib, step as step_lib


# ----------------------------------------------------------------------
# policy registry
# ----------------------------------------------------------------------

class TestPolicyRegistry:
    def test_presets_registered(self):
        pols = mem.available_policies()
        for p in ("none", "everything", "dots", "dots_no_batch",
                  "per_block", "full"):
            assert p in pols

    def test_validate_accepts_presets_and_save_named(self):
        for p in mem.available_policies():
            assert mem.validate_policy(p) == p
        assert (mem.validate_policy("save_named(block_out)")
                == "save_named(block_out)")

    def test_validate_rejects_unknown(self):
        with pytest.raises(ValueError, match="unknown remat policy"):
            mem.validate_policy("per_blok")

    def test_parse_save_named_round_trip(self):
        names = mem.parse_save_named("save_named(stem_out, block_out)")
        assert names == ("stem_out", "block_out")
        for n in names:
            assert n in mem.SEAM_NAMES

    def test_parse_save_named_rejects_unknown_seam(self):
        with pytest.raises(ValueError, match="unknown seam"):
            mem.parse_save_named("save_named(bogus_seam)")

    def test_parse_save_named_rejects_empty(self):
        with pytest.raises(ValueError):
            mem.parse_save_named("save_named()")

    def test_wrap_none_is_identity(self):
        def f(x):
            return x * 2
        assert mem.wrap(f, "none") is f
        assert mem.wrap(f, None) is f
        assert mem.wrap(f, "per_block") is not f

    def test_self_check_clean(self):
        # the registry's own gate (also run by the analysis CI gate):
        # every preset applies, parse round-trips, and the annotated
        # model/step files carry no bare remat.
        assert mem.check() == []


# ----------------------------------------------------------------------
# golden-loss equivalence: every policy reproduces the `none` training
# trajectory (8 virtual CPU devices, real ResNet blocks so the named
# seams exist)
# ----------------------------------------------------------------------

def _tiny_resnet_losses(mesh, remat_policy, n_steps=2):
    model = resnet.ResNet(stage_sizes=(1, 1), block_cls=resnet.BasicBlock,
                          num_classes=4, width=8, cifar_stem=True)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 8, 8, 3)).astype(np.float32)
    y = rng.integers(0, 4, size=(16,)).astype(np.int32)
    variables = model.init(jax.random.key(0), jnp.asarray(x[:2]))
    tx = optax.sgd(0.1, momentum=0.9)

    def loss_fn(params, model_state, batch, rng):
        logits, mut = model.apply({"params": params, **model_state},
                                  batch["x"], train=True,
                                  mutable=["batch_stats"])
        return losses.softmax_cross_entropy(logits, batch["y"]), (
            dict(mut), {})

    step = step_lib.make_train_step(
        loss_fn, tx, mesh, donate=False,
        remat_policy=None if remat_policy == "none" else remat_policy)
    state = step_lib.TrainState.create(
        variables["params"], tx,
        model_state={"batch_stats": variables["batch_stats"]})
    state = step_lib.replicate_state(state, mesh)
    batch = jax.tree.map(
        lambda a: jax.device_put(a, mesh_lib.batch_sharding(mesh)),
        {"x": x, "y": y})
    out = []
    for _ in range(n_steps):
        state, m = step(state, batch)
        out.append(float(m["loss"]))
    return out


@pytest.fixture(scope="module")
def golden_losses(mesh8):
    return _tiny_resnet_losses(mesh8, "none")


@pytest.mark.parametrize("policy", [
    "everything", "dots", "dots_no_batch", "per_block", "full",
    "save_named(block_out)",
])
def test_golden_loss_equivalence(mesh8, golden_losses, policy):
    got = _tiny_resnet_losses(mesh8, policy)
    np.testing.assert_allclose(got, golden_losses, rtol=1e-5, atol=1e-6)
    assert golden_losses[-1] < golden_losses[0]


# ----------------------------------------------------------------------
# env / tuning-DB resolution
# ----------------------------------------------------------------------

@pytest.fixture
def clean_env(monkeypatch):
    for var in ("TPUFRAME_REMAT_POLICY", "TPUFRAME_BENCH_REMAT",
                "TPUFRAME_TUNE_DB", "TPUFRAME_TUNE_GEN",
                "PALLAS_AXON_TPU_GEN"):
        monkeypatch.delenv(var, raising=False)
    return monkeypatch


class TestEnvResolution:
    def test_explicit_env_wins(self, clean_env):
        clean_env.setenv("TPUFRAME_REMAT_POLICY", "dots")
        clean_env.setenv("TPUFRAME_BENCH_REMAT", "1")
        assert mem.policy_from_env() == "dots"
        assert mem.resolve() == ("dots", "env")

    def test_explicit_env_validated(self, clean_env):
        clean_env.setenv("TPUFRAME_REMAT_POLICY", "nope")
        with pytest.raises(ValueError, match="unknown remat policy"):
            mem.policy_from_env()

    def test_legacy_alias_maps_to_per_block(self, clean_env, capsys):
        clean_env.setenv("TPUFRAME_BENCH_REMAT", "1")
        mem_policy._warned_legacy = False
        assert mem.policy_from_env() == "per_block"
        assert "deprecated" in capsys.readouterr().out
        # warn-once: the second read is silent
        assert mem.policy_from_env() == "per_block"
        assert "deprecated" not in capsys.readouterr().out
        assert mem.resolve() == ("per_block", "env_legacy")

    def test_legacy_zero_is_unset(self, clean_env):
        clean_env.setenv("TPUFRAME_BENCH_REMAT", "0")
        assert mem.policy_from_env() is None

    def test_default_without_env_or_db(self, clean_env):
        clean_env.setenv("TPUFRAME_TUNE_DB", "off")
        assert mem.resolve(program="train_resnet50_b512",
                           family="remat_resnet50") == ("none", "default")


def _seed_remat_db(path):
    from tpuframe.tune import db as tune_db
    db = tune_db.TuningDB(str(path))
    for pol, ms in (("none", 177.2), ("per_block", 150.0)):
        db.add({"program": "train_resnet50_b512",
                "family": "remat_resnet50",
                "fingerprint": "fp-test",
                "topology": "v5e:2x2",
                "generation": "v5e",
                "config": {"remat_policy": pol, "batch": 512},
                "predicted": {"predicted_ms": ms}})
    db.save()
    return db


class TestTuneDBResolution:
    def test_db_round_trip_and_best(self, tmp_path):
        from tpuframe.tune import db as tune_db
        path = tmp_path / "tune_db.json"
        _seed_remat_db(path)
        reloaded = tune_db.TuningDB.open(str(path))
        assert tune_db.validate(reloaded.data) == []
        best = reloaded.best(family="remat_resnet50", generation="v5e")
        assert best.config["remat_policy"] == "per_block"

    def test_resolve_consults_db(self, clean_env, tmp_path):
        path = tmp_path / "tune_db.json"
        _seed_remat_db(path)
        clean_env.setenv("TPUFRAME_TUNE_DB", str(path))
        clean_env.setenv("TPUFRAME_TUNE_GEN", "v5e")
        assert mem.resolve(program="train_resnet50_b512",
                           family="remat_resnet50") == ("per_block",
                                                        "tune_db")

    def test_db_gated_on_generation(self, clean_env, tmp_path):
        # no target generation (the CPU test-run case) -> hard default,
        # never a TPU-searched policy
        path = tmp_path / "tune_db.json"
        _seed_remat_db(path)
        clean_env.setenv("TPUFRAME_TUNE_DB", str(path))
        assert mem.resolve(program="train_resnet50_b512",
                           family="remat_resnet50") == ("none", "default")

    def test_env_preempts_db(self, clean_env, tmp_path):
        from tpuframe.tune import db as tune_db
        path = tmp_path / "tune_db.json"
        _seed_remat_db(path)
        clean_env.setenv("TPUFRAME_TUNE_DB", str(path))
        clean_env.setenv("TPUFRAME_TUNE_GEN", "v5e")
        clean_env.setenv("TPUFRAME_REMAT_POLICY", "dots")
        assert mem.resolve(program="train_resnet50_b512",
                           family="remat_resnet50") == ("dots", "env")
        # and the DB-side helper refuses to shadow an env override
        assert tune_db.resolve_remat_policy("train_resnet50_b512") is None

    def test_record_env_overrides_include_policy(self, tmp_path):
        from tpuframe.tune import db as tune_db
        path = tmp_path / "tune_db.json"
        db = _seed_remat_db(path)
        rec = db.best(family="remat_resnet50")
        env = rec.env_overrides()
        assert env["TPUFRAME_REMAT_POLICY"] == "per_block"


# ----------------------------------------------------------------------
# donation / aliasing audit
# ----------------------------------------------------------------------

class TestDonationAudit:
    def _compile(self, donate):
        def f(state, batch):
            return jax.tree.map(lambda a: a + jnp.sum(batch), state)
        state = {"w": jnp.zeros((64, 64)), "m": jnp.zeros((64, 64))}
        batch = jnp.ones((8,))
        fn = (jax.jit(f, donate_argnums=(0,)) if donate else jax.jit(f))
        return fn.lower(state, batch).compile()

    def test_donated_step_passes(self):
        compiled = self._compile(donate=True)
        rep = mem.donation_report(compiled)
        assert rep["donated"]
        assert rep["n_aliased"] >= 2           # both state leaves
        assert 0 in rep["aliased_params"]
        assert mem.audit_step_donation(compiled) == []

    def test_undonated_step_flagged(self):
        compiled = self._compile(donate=False)
        rep = mem.donation_report(compiled)
        assert not rep["donated"]
        problems = mem.audit_step_donation(compiled)
        assert problems and "no input_output_alias entries" in problems[0]


# ----------------------------------------------------------------------
# TF108: bare remat stays out of model/step code
# ----------------------------------------------------------------------

class TestTF108:
    def _rules(self, src, path):
        from tpuframe.analysis import source_lint
        return [f.rule for f in source_lint.lint_source(src, path)]

    BARE = ("import jax\n"
            "def f(x):\n"
            "    return jax.checkpoint(lambda y: y * 2)(x)\n")

    def test_flags_bare_checkpoint_in_models(self):
        assert "TF108" in self._rules(self.BARE, "tpuframe/models/net.py")
        assert "TF108" in self._rules(
            "import jax\ndef f(g, x):\n    return jax.remat(g)(x)\n",
            "tpuframe/parallel/step2.py")

    def test_registry_itself_exempt(self):
        assert "TF108" not in self._rules(self.BARE, "tpuframe/mem/policy.py")

    def test_out_of_scope_path_exempt(self):
        assert "TF108" not in self._rules(self.BARE, "tpuframe/obs/x.py")

    def test_suppression_comment(self):
        src = ("import jax\n"
               "def f(x):\n"
               "    return jax.checkpoint(lambda y: y * 2)(x)"
               "  # tf-lint: ok[TF108]\n")
        assert "TF108" not in self._rules(src, "tpuframe/models/net.py")

    def test_shipped_model_and_step_code_clean(self):
        # the actual annotated files route everything through mem.*
        from tpuframe.analysis import source_lint
        import tpuframe
        import os
        root = os.path.dirname(tpuframe.__file__)
        paths = [os.path.join(root, "models", "resnet.py"),
                 os.path.join(root, "models", "transformer_lm.py"),
                 os.path.join(root, "parallel", "step.py"),
                 os.path.join(root, "parallel", "pp_lm.py")]
        findings = [f for f in source_lint.lint_paths(paths)
                    if f.rule == "TF108"]
        assert findings == []


# ----------------------------------------------------------------------
# obs: bytes-MFU (HBM-roofline utilization) + remat_policy run event
# ----------------------------------------------------------------------

class TestHbmUtil:
    def test_math(self):
        from tpuframe.obs import goodput
        from tpuframe.tune import roofline
        hw = roofline.HARDWARE["v5e"]
        # one device streaming exactly its bandwidth for 1s -> 100%
        assert goodput.hbm_util(hw.hbm_bytes_per_s, 1.0,
                                generation="v5e") == pytest.approx(1.0)
        # PERF §2 anchor: 143.5 GB over the 177.2ms roofline step = 100%
        assert goodput.hbm_util(1.435e11, 0.1772,
                                generation="v5e") == pytest.approx(1.0,
                                                                   rel=1e-3)
        assert goodput.hbm_util(0.0, 1.0) == 0.0
        assert goodput.hbm_util(1.0, 0.0) == 0.0

    def test_from_events_recompute(self):
        from tpuframe.obs import goodput
        from tpuframe.tune import roofline
        hw = roofline.HARDWARE["v5e"]
        t0 = 1000.0
        events = [
            {"type": "run_start", "t": t0, "step": 0,
             "bytes_per_step": hw.hbm_bytes_per_s * 0.1},
            # first step is the compile and is excluded from the mean
            {"type": "step", "t": t0 + 1, "step": 1, "wall_ms": 9000.0},
            {"type": "step", "t": t0 + 2, "step": 2, "wall_ms": 100.0},
            {"type": "step", "t": t0 + 3, "step": 3, "wall_ms": 100.0},
        ]
        out = goodput.from_events(events, generation="v5e")
        assert out["hbm_util_productive"] == pytest.approx(1.0, rel=1e-6)

    def test_from_events_run_end_passthrough(self):
        from tpuframe.obs import goodput
        events = [
            {"type": "run_start", "t": 0.0, "step": 0},
            {"type": "run_end", "t": 10.0, "step": 5, "outcome": "ok",
             "hbm_util_productive": 0.81},
        ]
        out = goodput.from_events(events, generation="v5e")
        assert out["hbm_util_productive"] == pytest.approx(0.81)


class TestRematPolicyEvent:
    def test_schema_registered(self):
        from tpuframe.obs import events
        assert events.REQUIRED_FIELDS["remat_policy"] == ("policy",
                                                          "source")

    def test_validate_record(self):
        from tpuframe.obs import events
        good = {"schema": events.SCHEMA_VERSION, "type": "remat_policy",
                "t": 1.0, "host": "h", "proc": 0, "attempt": 0,
                "policy": "per_block", "source": "tune_db",
                "predicted_bytes_per_step": 1.7e11}
        assert events.validate_record(good) == []
        bad = dict(good)
        del bad["source"]
        assert any("source" in p for p in events.validate_record(bad))


# ----------------------------------------------------------------------
# offline A/B parser: (tag, policy) keying
# ----------------------------------------------------------------------

class TestAbRowsPolicyColumn:
    def test_policies_coexist_under_one_tag(self):
        from perf import _ab_rows
        lines = [
            json.dumps({"tag": "resnet50_remat_b512", "policy": "none",
                        "gb": 143.5}),
            json.dumps({"tag": "resnet50_remat_b512", "policy": "per_block",
                        "gb": 170.8}),
            json.dumps({"tag": "resnet50_b512", "gb": 143.5}),
        ]
        rows = _ab_rows.parse_rows(lines)
        assert len(rows) == 3
        assert _ab_rows.superseded_count(lines) == 0

    def test_same_policy_supersedes(self):
        from perf import _ab_rows
        lines = [
            json.dumps({"tag": "t", "policy": "dots", "gb": 1.0}),
            json.dumps({"tag": "t", "policy": "dots", "gb": 2.0}),
            json.dumps({"tag": "t", "gb": 9.0}),  # (t, None) is distinct
        ]
        rows = _ab_rows.parse_rows(lines)
        assert len(rows) == 2
        assert rows[0]["gb"] == 2.0
        assert _ab_rows.superseded_count(lines) == 1


# ----------------------------------------------------------------------
# sweep candidate list sanity (the TPU compile itself is tier-slow, in
# test_aot_tpu_compile.py)
# ----------------------------------------------------------------------

def test_remat_sweep_candidates_are_valid_policies():
    from tpuframe.tune import search
    cands = search.remat_policy_candidates()
    assert "none" in cands and "per_block" in cands
    for pol in cands:
        mem.validate_policy(pol)
    # `everything` is deliberately absent: byte-identical to `none`
    assert "everything" not in cands
