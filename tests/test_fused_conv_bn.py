"""Parity tests for the fused 1x1-conv+BN backward kernel
(tpuframe/ops/fused_conv_bn.py, PERF.md §6.3's byte-floor lever).

The kernel must be a NUMERICAL drop-in for the unfused composition: the
forward is the same folded math, and the backward's closed-form BN
gradient + fused matmuls must match XLA's autodiff of the reference
expression.  f32 runs pin tight tolerances; bf16 runs bound the rounding
introduced by keeping g in VMEM-f32 and casting once for the MXU dots.

CPU runs use the pallas interpreter (module/interpret=None auto-detects).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuframe.ops import fused_conv_bn as fcb


def _rand(rng, shape, dtype, scale=1.0, loc=0.0):
    return jnp.asarray(rng.normal(loc, scale, shape), dtype)


def _loss_parts(y, mean, var, t):
    # Touch every output (incl. the stats, with stop_gradient as the
    # module contract requires) so the vjp covers the full signature.
    return (jnp.sum(y.astype(jnp.float32) * t)
            + jnp.sum(jax.lax.stop_gradient(mean))
            + jnp.sum(jax.lax.stop_gradient(var)))


class TestCoreParity:
    @pytest.mark.parametrize("b,h,w,k,c", [(4, 8, 8, 12, 20),
                                           (8, 8, 8, 16, 48)])
    def test_f32_values_and_grads(self, b, h, w, k, c):
        rng = np.random.default_rng(0)
        a = _rand(rng, (b, h, w, k), jnp.float32, 2.0, 1.0)
        wk = _rand(rng, (k, c), jnp.float32, 0.2)
        gamma = jnp.asarray(rng.uniform(0.5, 2.0, c), jnp.float32)
        beta = _rand(rng, (c,), jnp.float32)
        t = _rand(rng, (b, h, w, c), jnp.float32)
        cfg = (1e-5, 64, True)  # small row budget -> multi-step grid

        def fused_loss(a, wk, g, bb):
            y, mean, var = fcb.conv1x1_bn_train(cfg, a, wk, g, bb)
            return _loss_parts(y, mean, var, t)

        def ref_loss(a, wk, g, bb):
            y, mean, var = fcb.conv1x1_bn_reference(a, wk, g, bb, eps=1e-5)
            return _loss_parts(y, mean, var, t)

        lf, gf = jax.value_and_grad(fused_loss, argnums=(0, 1, 2, 3))(
            a, wk, gamma, beta)
        lr, gr = jax.value_and_grad(ref_loss, argnums=(0, 1, 2, 3))(
            a, wk, gamma, beta)
        np.testing.assert_allclose(lf, lr, rtol=1e-5)
        for got, want, name in zip(gf, gr, ("da", "dw", "dgamma", "dbeta")):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4,
                err_msg=name)

    def test_bf16_values_and_grads(self):
        rng = np.random.default_rng(1)
        b, h, w, k, c = 4, 8, 8, 32, 64
        a = _rand(rng, (b, h, w, k), jnp.bfloat16, 1.0)
        wk = _rand(rng, (k, c), jnp.float32, 0.2)
        gamma = jnp.asarray(rng.uniform(0.5, 2.0, c), jnp.float32)
        beta = _rand(rng, (c,), jnp.float32)
        t = _rand(rng, (b, h, w, c), jnp.float32)
        cfg = (1e-5, 128, True)

        def fused_loss(a, wk, g, bb):
            y, mean, var = fcb.conv1x1_bn_train(cfg, a, wk, g, bb)
            return _loss_parts(y, mean, var, t)

        def ref_loss(a, wk, g, bb):
            y, mean, var = fcb.conv1x1_bn_reference(a, wk, g, bb, eps=1e-5)
            return _loss_parts(y, mean, var, t)

        lf, gf = jax.value_and_grad(fused_loss, argnums=(0, 1, 2, 3))(
            a, wk, gamma, beta)
        lr, gr = jax.value_and_grad(ref_loss, argnums=(0, 1, 2, 3))(
            a, wk, gamma, beta)
        # bf16 activations: both paths quantize at the same points except
        # g (ours rounds once to bf16 in VMEM); grads agree to bf16 eps.
        # atol scales with each tensor's magnitude — dW entries are sums
        # of M bf16-rounded products, so absolute error grows with the
        # sum's scale, not with unity.
        np.testing.assert_allclose(lf, lr, rtol=2e-2)
        for got, want, name in zip(gf, gr, ("da", "dw", "dgamma", "dbeta")):
            w32 = np.asarray(want, np.float32)
            np.testing.assert_allclose(
                np.asarray(got, np.float32), w32,
                rtol=3e-2, atol=3e-2 * max(np.abs(w32).max(), 1.0),
                err_msg=name)

    def test_dw_accumulates_across_grid_steps(self):
        # 8x8 spatial x batch 8 with a 16-row budget -> 32 sequential
        # steps; dW must equal the single-step answer exactly (f32
        # accumulation both ways).
        rng = np.random.default_rng(2)
        b, h, w, k, c = 8, 8, 8, 16, 24
        a = _rand(rng, (b, h, w, k), jnp.float32)
        wk = _rand(rng, (k, c), jnp.float32, 0.3)
        gamma = jnp.ones((c,), jnp.float32)
        beta = jnp.zeros((c,), jnp.float32)
        t = _rand(rng, (b, h, w, c), jnp.float32)

        def loss(cfg, a):
            y, mean, var = fcb.conv1x1_bn_train(cfg, a, wk, gamma, beta)
            return _loss_parts(y, mean, var, t)

        g_many = jax.grad(lambda a: loss((1e-5, 16, True), a))(a)
        g_one = jax.grad(lambda a: loss((1e-5, 4096, True), a))(a)
        np.testing.assert_allclose(np.asarray(g_many), np.asarray(g_one),
                                   rtol=1e-5, atol=1e-5)


class TestSupportGate:
    def test_resnet_shapes_supported(self):
        # (h, w, batch, k, c) for the flagship 1x1s at b=512
        assert fcb.supported(7, 7, 512, 2048, 512)     # layer4 conv1
        assert fcb.supported(7, 7, 512, 512, 2048)     # layer4 conv3
        assert fcb.supported(56, 56, 512, 64, 256)     # layer1 conv3
        assert fcb.supported(8, 8, 4, 12, 20)          # tiny test shape

    def test_vmem_budget_rejects_huge_channels(self):
        assert not fcb.supported(8, 8, 64, 4096, 4096)


def _unfused_pair(dtype, features, strides=1):
    conv = nn.Conv(features, (1, 1), (strides, strides), use_bias=False,
                   dtype=dtype, param_dtype=jnp.float32)
    bn = nn.BatchNorm(use_running_average=False, momentum=0.9, epsilon=1e-5,
                      dtype=dtype, param_dtype=jnp.float32)
    return conv, bn


class TestModuleParity:
    @pytest.mark.parametrize("strides", [1, 2])
    def test_f32_vs_conv_bn_pair(self, strides):
        rng = np.random.default_rng(3)
        k_in, c_out = 12, 20
        x = _rand(rng, (4, 8, 8, k_in), jnp.float32, 2.0, 0.5)
        fused = fcb.FusedConvBN(c_out, strides=strides, dtype=jnp.float32)
        fv = fused.init(jax.random.key(0), x)
        kernel = fv["params"]["kernel"]
        scale = jnp.asarray(rng.uniform(0.5, 2.0, c_out), jnp.float32)
        bias = _rand(rng, (c_out,), jnp.float32)
        fv = {"params": {"kernel": kernel, "scale": scale, "bias": bias},
              "batch_stats": fv["batch_stats"]}

        conv, bn = _unfused_pair(jnp.float32, c_out, strides)
        bv = {"params": {"scale": scale, "bias": bias},
              "batch_stats": {"mean": jnp.zeros((c_out,)),
                              "var": jnp.ones((c_out,))}}
        # Random target decorrelated from the activations: a loss like
        # sum(y^2) has an ~exactly-zero BN input grad (BN output stats are
        # invariant), which would make this test compare pure f32
        # cancellation noise between the two autodiff paths.
        h_sp = 8 // strides
        t = _rand(rng, (4, h_sp, h_sp, c_out), jnp.float32)

        def fused_loss(variables):
            y, mut = fused.apply(variables, x, mutable=["batch_stats"])
            return jnp.sum(y * t), (y, mut)

        def ref_loss(params):
            h = conv.apply({"params": params["conv"]}, x)
            y, mut = bn.apply(
                {"params": params["bn"], "batch_stats": bv["batch_stats"]},
                h, mutable=["batch_stats"])
            return jnp.sum(y * t), (y, mut)

        (lf, (yf, mutf)), gf = jax.value_and_grad(
            fused_loss, has_aux=True)(fv)
        (lr, (yr, mutr)), gr = jax.value_and_grad(ref_loss, has_aux=True)(
            {"conv": {"kernel": kernel},
             "bn": {"scale": scale, "bias": bias}})

        np.testing.assert_allclose(np.asarray(yf), np.asarray(yr),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(lf, lr, rtol=1e-5)
        for key in ("mean", "var"):
            np.testing.assert_allclose(
                np.asarray(mutf["batch_stats"][key]),
                np.asarray(mutr["batch_stats"][key]), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(gf["params"]["kernel"]),
            np.asarray(gr["conv"]["kernel"]), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(gf["params"]["scale"]),
            np.asarray(gr["bn"]["scale"]), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(gf["params"]["bias"]),
            np.asarray(gr["bn"]["bias"]), rtol=2e-4, atol=2e-4)

    def test_eval_mode_uses_running_stats(self):
        rng = np.random.default_rng(4)
        x = _rand(rng, (2, 4, 4, 8), jnp.float32)
        fused = fcb.FusedConvBN(16, use_running_average=True,
                                dtype=jnp.float32)
        v = fused.init(jax.random.key(1), x)
        v["batch_stats"]["mean"] = _rand(rng, (16,), jnp.float32)
        v["batch_stats"]["var"] = jnp.asarray(
            rng.uniform(0.5, 2.0, 16), jnp.float32)
        y = fused.apply(v, x)

        conv, _ = _unfused_pair(jnp.float32, 16)
        bn = nn.BatchNorm(use_running_average=True, epsilon=1e-5,
                          dtype=jnp.float32)
        h = conv.apply({"params": {"kernel": v["params"]["kernel"]}}, x)
        y_ref = bn.apply({"params": {"scale": v["params"]["scale"],
                                     "bias": v["params"]["bias"]},
                          "batch_stats": v["batch_stats"]}, h)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=2e-5, atol=2e-5)

    def test_untileable_shape_falls_back(self):
        # 1x3x3 input -> M=9 rows: kernel unsupported, reference path runs.
        rng = np.random.default_rng(5)
        x = _rand(rng, (1, 3, 3, 8), jnp.float32)
        fused = fcb.FusedConvBN(16, dtype=jnp.float32)
        v = fused.init(jax.random.key(2), x)
        y, mut = fused.apply(v, x, mutable=["batch_stats"])
        assert y.shape == (1, 3, 3, 16)
        assert np.isfinite(np.asarray(y)).all()


def _map_bottleneck_params(unf, has_ds):
    """Unfused Bottleneck param dict -> fused layout (see Bottleneck)."""
    out = {
        "FusedConvBN_0": {"kernel": unf["Conv_0"]["kernel"],
                          "scale": unf["BatchNorm_0"]["scale"],
                          "bias": unf["BatchNorm_0"]["bias"]},
        "Conv_0": unf["Conv_1"],
        "BatchNorm_0": unf["BatchNorm_1"],
        "FusedConvBN_1": {"kernel": unf["Conv_2"]["kernel"],
                          "scale": unf["BatchNorm_2"]["scale"],
                          "bias": unf["BatchNorm_2"]["bias"]},
    }
    if has_ds:
        out["downsample_fused"] = {
            "kernel": unf["downsample_conv"]["kernel"],
            "scale": unf["downsample_bn"]["scale"],
            "bias": unf["downsample_bn"]["bias"]}
    return out


def _map_bottleneck_stats(unf, has_ds):
    out = {"FusedConvBN_0": unf["BatchNorm_0"],
           "BatchNorm_0": unf["BatchNorm_1"],
           "FusedConvBN_1": unf["BatchNorm_2"]}
    if has_ds:
        out["downsample_fused"] = unf["downsample_bn"]
    return out


class TestResNetGolden:
    @pytest.mark.slow
    def test_tiny_resnet50_fused_equals_flax(self):
        """Full model golden equivalence: loss + param grads of a 2-block
        bottleneck ResNet under bn='fused' match bn='flax' with the same
        (mapped) parameters."""
        from tpuframe.models.resnet import Bottleneck, ResNet

        rng = np.random.default_rng(6)
        x = _rand(rng, (4, 16, 16, 3), jnp.float32, 1.0)
        labels = jnp.asarray(rng.integers(0, 4, (4,)), jnp.int32)

        def make(bn):
            return ResNet(stage_sizes=(1, 1), block_cls=Bottleneck,
                          num_classes=4, width=8, cifar_stem=True,
                          dtype=jnp.float32, bn=bn)

        flax_m, fused_m = make("flax"), make("fused")
        fv = flax_m.init(jax.random.key(3), x, train=True)

        params = dict(fv["params"])
        stats = dict(fv["batch_stats"])
        for name, has_ds in (("Bottleneck_0", True), ("Bottleneck_1", True)):
            params[name] = _map_bottleneck_params(params[name], has_ds)
            stats[name] = _map_bottleneck_stats(stats[name], has_ds)
        mapped = {"params": params, "batch_stats": stats}

        def loss(variables, model):
            logits, mut = model.apply(variables, x, train=True,
                                      mutable=["batch_stats"])
            one_hot = jax.nn.one_hot(labels, 4)
            l = -jnp.mean(jnp.sum(
                jax.nn.log_softmax(logits) * one_hot, axis=-1))
            return l, mut

        (lf, mutf), gf = jax.value_and_grad(
            lambda v: loss(v, flax_m), has_aux=True)(fv)
        (lz, mutz), gz = jax.value_and_grad(
            lambda v: loss(v, fused_m), has_aux=True)(mapped)

        np.testing.assert_allclose(lz, lf, rtol=1e-5)
        # Grad parity through BOTH blocks (incl. the fused downsample):
        # compare the stem conv grad (flows through everything) and each
        # mapped 1x1 kernel/scale/bias grad.
        np.testing.assert_allclose(
            np.asarray(gz["params"]["stem_conv"]["kernel"]),
            np.asarray(gf["params"]["stem_conv"]["kernel"]),
            rtol=5e-4, atol=5e-4)
        for blk in ("Bottleneck_0", "Bottleneck_1"):
            fz, ff = gz["params"][blk], gf["params"][blk]
            np.testing.assert_allclose(
                np.asarray(fz["FusedConvBN_0"]["kernel"]),
                np.asarray(ff["Conv_0"]["kernel"]), rtol=5e-4, atol=5e-4)
            np.testing.assert_allclose(
                np.asarray(fz["FusedConvBN_1"]["scale"]),
                np.asarray(ff["BatchNorm_2"]["scale"]),
                rtol=5e-4, atol=5e-4)
            np.testing.assert_allclose(
                np.asarray(fz["downsample_fused"]["bias"]),
                np.asarray(ff["downsample_bn"]["bias"]),
                rtol=5e-4, atol=5e-4)
            # batch_stats updates must match too
            np.testing.assert_allclose(
                np.asarray(mutz["batch_stats"][blk]
                           ["FusedConvBN_1"]["mean"]),
                np.asarray(mutf["batch_stats"][blk]
                           ["BatchNorm_2"]["mean"]), rtol=1e-5, atol=1e-6)
