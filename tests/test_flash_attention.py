"""Flash-attention kernel vs the XLA einsum reference (SURVEY.md §7 test
strategy: unit tests per module on CPU jax — the Pallas interpreter executes
the very kernel that compiles for TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuframe.ops import attention
from tpuframe.ops import flash_attention as fa


def _qkv(b=2, s=256, n=4, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, n, d)
    return tuple(jax.random.normal(k, shape, dtype) * 0.5 for k in ks)


def _padding_mask(b=2, s=256, seed=1):
    lengths = jax.random.randint(jax.random.key(seed), (b,), s // 4, s + 1)
    return (jnp.arange(s)[None, :] < lengths[:, None]).astype(jnp.int32)


def test_forward_matches_xla():
    q, k, v = _qkv()
    got = fa.flash_mha(q, k, v, interpret=True)
    want = attention._xla_attention(q, k, v, mask=None, dropout_rate=0.0,
                                    dropout_rng=None)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_forward_padding_mask():
    q, k, v = _qkv()
    mask = _padding_mask()
    got = fa.flash_mha(q, k, v, mask=mask, interpret=True)
    want = attention._xla_attention(q, k, v, mask=mask, dropout_rate=0.0,
                                    dropout_rng=None)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_forward_causal():
    q, k, v = _qkv(s=256)
    got = fa.flash_mha(q, k, v, causal=True, interpret=True)
    s = q.shape[1]
    causal = jnp.tril(jnp.ones((s, s), bool))[None, None]
    want = attention._xla_attention(q, k, v, mask=causal, dropout_rate=0.0,
                                    dropout_rng=None)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_multi_block_seq():
    # 2 q-blocks x 2 kv-blocks exercises the online-softmax accumulation.
    q, k, v = _qkv(s=256)
    got = fa.flash_mha(q, k, v, block_q=128, block_k=128, interpret=True)
    want = attention._xla_attention(q, k, v, mask=None, dropout_rate=0.0,
                                    dropout_rng=None)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_xla(causal):
    q, k, v = _qkv(b=1, s=256, n=2, d=64)
    mask = None if causal else _padding_mask(b=1, s=256)

    def loss_flash(q, k, v):
        o = fa.flash_mha(q, k, v, mask=mask, causal=causal, interpret=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def loss_xla(q, k, v):
        m = mask
        if causal:
            s = q.shape[1]
            m = jnp.tril(jnp.ones((s, s), bool))[None, None]
        o = attention._xla_attention(q, k, v, mask=m, dropout_rate=0.0,
                                     dropout_rng=None)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_xla = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for gf, gx, name in zip(g_flash, g_xla, "qkv"):
        np.testing.assert_allclose(gf, gx, atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_bf16_inputs():
    q, k, v = _qkv(dtype=jnp.bfloat16, s=128)
    got = fa.flash_mha(q, k, v, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = attention._xla_attention(q.astype(jnp.float32),
                                    k.astype(jnp.float32),
                                    v.astype(jnp.float32), mask=None,
                                    dropout_rate=0.0, dropout_rng=None)
    np.testing.assert_allclose(got.astype(jnp.float32), want,
                               atol=3e-2, rtol=3e-2)


def test_dispatch_selects_pallas(monkeypatch):
    q, k, v = _qkv(b=1, s=128, n=2, d=64)
    calls = []
    real = fa.flash_mha
    monkeypatch.setattr(fa, "flash_mha",
                        lambda *a, **kw: calls.append(1) or real(*a, **kw))
    out = attention.multihead_attention(q, k, v, impl="pallas")
    assert calls, "dispatch silently fell back to the XLA path"
    want = attention.multihead_attention(q, k, v, impl="xla")
    np.testing.assert_allclose(out, want, atol=2e-5, rtol=2e-5)


def test_unsupported_shape_falls_back():
    # seq 100 doesn't tile; dispatch must silently use the XLA path.
    q, k, v = _qkv(b=1, s=100, n=2, d=64)
    out = attention.multihead_attention(q, k, v, impl="pallas")
    want = attention.multihead_attention(q, k, v, impl="xla")
    np.testing.assert_allclose(out, want, atol=2e-5, rtol=2e-5)
    assert not fa.supported(q)


def test_cross_attention_kv_shape_guard():
    # s_kv=200 doesn't tile into 128-blocks: supported() must reject it and
    # flash_mha must refuse rather than silently truncating keys.
    q, _, _ = _qkv(b=1, s=128, n=2, d=64)
    k = jnp.ones((1, 200, 2, 64), jnp.float32)
    v = jnp.ones((1, 200, 2, 64), jnp.float32)
    assert not fa.supported(q, k)
    with pytest.raises(ValueError, match="do not tile"):
        fa.flash_mha(q, k, v, interpret=True)


def test_fully_masked_row_zero_grads():
    # A zero-length (all-padding) batch row: output and all grads must be
    # exactly zero for it — not s_kv-inflated garbage.
    q, k, v = _qkv(b=2, s=128, n=2, d=64)
    mask = jnp.stack([jnp.zeros(128, jnp.int32), jnp.ones(128, jnp.int32)])

    out = fa.flash_mha(q, k, v, mask=mask, interpret=True)
    np.testing.assert_array_equal(out[0], jnp.zeros_like(out[0]))

    def loss(q, k, v):
        o = fa.flash_mha(q, k, v, mask=mask, interpret=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g, name in ((dq, "dq"), (dk, "dk"), (dv, "dv")):
        np.testing.assert_array_equal(
            g[0], jnp.zeros_like(g[0]), err_msg=f"{name}[masked row]")
        assert float(jnp.max(jnp.abs(g[1]))) > 0  # live row still flows


def test_precision_argument_plumbs_through(monkeypatch):
    """precision reaches EVERY dot in fwd and bwd — asserted structurally
    by spying on lax.dot_general at trace time (the interpreter's numerics
    can't distinguish precisions, so allclose alone would pass even if the
    kwarg were dropped from the kernels)."""
    flash_mha = fa.flash_mha
    recorded = []
    orig_dot = jax.lax.dot_general

    def spy(*a, **k):
        recorded.append(k.get("precision"))
        return orig_dot(*a, **k)

    rng = np.random.default_rng(3)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.normal(0, 0.5, size=(1, 64, 2, 16)), jnp.float32)
    q, k, v = mk(), mk(), mk()

    def loss(f):
        def g(q, k, v):
            return jnp.sum(f(q, k, v) ** 2)
        return jax.grad(g, argnums=(0, 1, 2))(q, k, v)

    base = flash_mha(q, k, v, causal=True)
    g_base = loss(lambda q, k, v: flash_mha(q, k, v, causal=True))

    monkeypatch.setattr(jax.lax, "dot_general", spy)
    hi = flash_mha(q, k, v, causal=True, precision=jax.lax.Precision.HIGHEST)
    g_hi = loss(lambda q, k, v: flash_mha(
        q, k, v, causal=True, precision=jax.lax.Precision.HIGHEST))
    monkeypatch.undo()

    # structural: every kernel dot (fwd scores+accum, bwd recompute/dp/dq/
    # dkv) was traced with the requested precision
    assert len(recorded) >= 6, recorded
    assert all(p == jax.lax.Precision.HIGHEST for p in recorded), recorded
    # interpreter numerics are precision-invariant: values must match
    np.testing.assert_allclose(np.asarray(base), np.asarray(hi),
                               rtol=1e-6, atol=1e-6)
    for a, b in zip(g_base, g_hi):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# Generation-conditional lse/delta layout (PERF.md §12.2): lane-major
# residuals for every generation newer than v4; sublane-major for v4 and
# unknown targets (the layout every generation can compile).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("gen,lane", [
    (None, False),   # unknown target (CPU tier-1 runs) -> conservative
    ("v4", False),   # tpu.dynamic_gather unsupported -> sublane-major
    ("v5e", True),
    ("v5p", True),
    ("v6e", True),
])
def test_lse_layout_pinned_per_generation(monkeypatch, gen, lane):
    for var in ("TPUFRAME_TUNE_GEN", "PALLAS_AXON_TPU_GEN"):
        monkeypatch.delenv(var, raising=False)
    if gen is not None:
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", gen)
    assert fa._lse_lane_major() is lane


@pytest.mark.parametrize("gen", [None, "v5e"])
def test_lse_layout_residual_shape(monkeypatch, gen):
    # the layout decision is visible in the residual the fwd pass saves:
    # [bn, s] either way at the jax level, but built from a lane-major
    # [bn, 1, s] or sublane-major [bn, s, 1] HBM array.
    for var in ("TPUFRAME_TUNE_GEN", "PALLAS_AXON_TPU_GEN"):
        monkeypatch.delenv(var, raising=False)
    if gen is not None:
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", gen)
    q, k, v = _qkv(b=1, s=128, n=2, d=64)
    qf = q.reshape(2, 128, 64)
    out, lse = fa._flash_fwd(qf, k.reshape(2, 128, 64),
                             v.reshape(2, 128, 64), None, scale=64 ** -0.5,
                             causal=False, block_q=64, block_k=64,
                             interpret=True)
    assert lse.shape == (2, 128)
    assert out.shape == qf.shape


def test_lse_layouts_numerically_equivalent(monkeypatch):
    # the relayout is a pure storage decision: fwd outputs, the saved
    # lse, and all three input grads must be identical under both
    # layouts (same blocks, same accumulation order).
    rng = np.random.default_rng(7)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.normal(0, 0.5, size=(4, 128, 64)), jnp.float32)
    q, k, v = mk(), mk(), mk()

    def run(gen):
        for var in ("TPUFRAME_TUNE_GEN", "PALLAS_AXON_TPU_GEN"):
            monkeypatch.delenv(var, raising=False)
        if gen is not None:
            monkeypatch.setenv("TPUFRAME_TUNE_GEN", gen)
        out, lse = fa._flash_fwd(q, k, v, None, scale=64 ** -0.5,
                                 causal=True, block_q=64, block_k=64,
                                 interpret=True)
        dq, dk, dv = fa._flash_bwd(q, k, v, None, out, lse, 2 * out,
                                   scale=64 ** -0.5, causal=True,
                                   block_q=64, block_k=64, interpret=True)
        return out, lse, dq, dk, dv

    sub = run(None)      # sublane-major
    lan = run("v5e")     # lane-major
    for a, b, name in zip(sub, lan, ("out", "lse", "dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5, err_msg=name)
