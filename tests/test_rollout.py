"""tpuframe.serve.rollout: live weight rollout, canary gating, rollback.

Unit tier of PR 17 (the chaos-tier subprocess proofs live in
tests/test_chaos.py::TestRollingUpdate):

  - committed_world() hardening — the watch seam the controller polls:
    a mid-commit dir, a quarantined ``step_N.corrupt`` and a torn
    manifest are all invisible/None, so a partial upload can NEVER
    trigger a rollout; a committed checkpoint from a different world
    size is reported faithfully (serving params are world-invariant)
  - LMEngine.swap_params — the ONE sanctioned swap seam validates tree
    structure and leaf shapes/dtypes before rebinding
  - swap_parity_check — a hot-swapped engine matches a cold-started one
    token-for-token on every serve bucket, at zero new compile-cache
    misses (the recompile-free floor, asserted not assumed)
  - router version/canary plumbing — version gauge scraped into the
    handle, the seeded canary traffic split, drain_replica/readmit
  - gate_compare — the obs-compare rc contract (0 promote / 1 regress /
    2 no overlap), participate-only-when-both
  - the controller state machine on the in-process _SimFleet: phase
    ordering, bounded mixed-version window accounting, poisoned-canary
    auto-rollback naming the failing metric, starved-gate rollback
    (never promote blind)
  - fleet_stats rollout accounting from the typed events
"""

import json
import os

import pytest

from tpuframe.ckpt.checkpoint import committed_world
from tpuframe.obs import events, goodput
from tpuframe.serve import rollout as rollout_lib
from tpuframe.serve.rollout import (
    GATE_METRICS,
    RolloutController,
    _drive_sim_rollout,
    _SimFleet,
    gate_compare,
)
from tpuframe.serve.router import Router


@pytest.fixture(autouse=True)
def _clean_rollout_env(monkeypatch):
    for k in (rollout_lib.ENV_WATCH, rollout_lib.ENV_CANARY_FRAC,
              rollout_lib.ENV_GATE):
        monkeypatch.delenv(k, raising=False)
    events.close()
    yield
    events.close()


# ---------------------------------------------------------------------------
# The watch seam: committed_world() hardening.
# ---------------------------------------------------------------------------

def _write_step(root, step, *, manifest=True, commit=True, world=None,
                torn=False, suffix=""):
    d = root / f"step_{step:08d}{suffix}"
    d.mkdir(parents=True, exist_ok=True)
    if manifest:
        body = json.dumps({"step": step, "world": world or
                           {"processes": 1, "devices": 1}})
        if torn:
            body = body[: len(body) // 2]
        (d / "manifest.json").write_text(body)
    if commit:
        (d / "COMMIT").write_text("ok\n")
    return d


class TestCommittedWorldHardening:
    def test_mid_commit_dir_is_invisible(self, tmp_path):
        # Manifest present, COMMIT not yet written: an async save still
        # uploading.  The peek must see NOTHING.
        _write_step(tmp_path, 1, commit=False)
        assert committed_world(str(tmp_path)) is None

    def test_quarantined_corrupt_dir_is_invisible(self, tmp_path):
        _write_step(tmp_path, 1, suffix=".corrupt")
        assert committed_world(str(tmp_path)) is None
        # ... and never shadows a good older step.
        _write_step(tmp_path, 1)
        _write_step(tmp_path, 2, suffix=".corrupt")
        info = committed_world(str(tmp_path))
        assert info is not None and info["step"] == 1

    def test_torn_manifest_is_none_not_crash(self, tmp_path):
        _write_step(tmp_path, 1, torn=True)
        assert committed_world(str(tmp_path)) is None

    def test_different_world_size_reported_faithfully(self, tmp_path):
        # A checkpoint written by a 4-process/16-device trainer is a
        # fine rollout source — serving params are replicated and
        # reassemble world-size invariantly.  The peek reports it as-is.
        _write_step(tmp_path, 3, world={"processes": 4, "devices": 16})
        info = committed_world(str(tmp_path))
        assert info == {"step": 3, "processes": 4, "devices": 16}

    def test_watcher_never_triggers_on_partial_upload(self, tmp_path):
        """Regression: the controller's poll over a directory holding
        only a mid-commit dir / torn sidecar must never start a roll."""
        fleet = _SimFleet(2)
        router = Router(list(fleet.reps), transport=fleet.transport,
                        scrape_interval_s=1e9)
        ctl = RolloutController(router, transport=fleet.transport,
                                watch_dir=str(tmp_path),
                                watch_interval_s=0.0)
        _write_step(tmp_path, 1, commit=False)          # mid-commit
        _write_step(tmp_path, 2, torn=True)             # torn sidecar
        _write_step(tmp_path, 3, suffix=".corrupt")     # quarantined
        for _ in range(3):
            assert ctl.tick() is False
        assert ctl.state == "idle" and ctl.target is None
        # A NEWER good checkpoint commits (COMMIT written last, like the
        # real writer) -> triggers.  (Step 2's torn-but-committed
        # sidecar keeps shadowing step 1: newest-committed is the only
        # candidate, and unreadable-newest means "no rollout", never
        # "fall back to an older version".)
        _write_step(tmp_path, 4)
        assert ctl.tick() is True
        assert ctl.state == "rolling" and ctl.target == 4
        assert ctl.world == {"step": 4, "processes": 1, "devices": 1}

    def test_watcher_ignores_stale_and_current_versions(self, tmp_path):
        fleet = _SimFleet(2)
        router = Router(list(fleet.reps), transport=fleet.transport,
                        scrape_interval_s=1e9)
        ctl = RolloutController(router, transport=fleet.transport,
                                watch_dir=str(tmp_path),
                                watch_interval_s=0.0, current_version=5)
        _write_step(tmp_path, 5)   # == current: no-op
        _write_step(tmp_path, 4)   # older: no-op
        assert ctl.tick() is False and ctl.state == "idle"


# ---------------------------------------------------------------------------
# The swap seam + hot-vs-cold parity (real engine, CPU).
# ---------------------------------------------------------------------------

class TestSwapSeam:
    def _tiny_engine(self):
        from tpuframe.models.transformer_lm import LMConfig
        from tpuframe.serve.engine import LMEngine

        cfg = LMConfig.tiny()
        return cfg, LMEngine(cfg, slots=2, prompt_buckets=(16,),
                             decode_block=16, max_context=48, seed=0)

    def test_swap_params_rejects_wrong_tree(self):
        _cfg, eng = self._tiny_engine()
        with pytest.raises(ValueError, match="tree structure"):
            eng.swap_params({"not": "the same tree"})

    def test_swap_params_rejects_wrong_leaf_shape(self):
        import jax

        _cfg, eng = self._tiny_engine()
        bad = jax.tree.map(lambda a: a[..., :1] if a.ndim else a,
                           eng.params)
        with pytest.raises(ValueError, match="compiled for"):
            eng.swap_params(bad)

    def test_swap_params_rebinds_matching_weights(self):
        import jax
        import jax.numpy as jnp

        _cfg, eng = self._tiny_engine()
        new = jax.tree.map(lambda a: jnp.zeros_like(a), eng.params)
        eng.swap_params(new)
        leaf = jax.tree.leaves(eng.params)[0]
        assert float(jnp.abs(leaf).sum()) == 0.0

    def test_hot_swap_matches_cold_start_at_zero_misses(self):
        """Satellite 4: per serve bucket, a hot-swapped engine streams
        the same tokens as an engine cold-started on the new weights —
        and the swap itself costs zero compile-cache misses."""
        from tpuframe.models.transformer_lm import LMConfig
        from tpuframe.serve.engine import swap_parity_check

        problems = swap_parity_check(LMConfig.tiny(), buckets=(16, 32),
                                     decode_tokens=4, seed=0)
        assert problems == []


# ---------------------------------------------------------------------------
# Router plumbing: version scrape, canary split, drain/readmit.
# ---------------------------------------------------------------------------

class TestRouterVersionAndCanary:
    def _fleet_router(self, n=3, **kw):
        fleet = _SimFleet(n)
        kw.setdefault("scrape_interval_s", 0.0)
        kw.setdefault("hedge_ms", 0.0)
        router = Router(list(fleet.reps), transport=fleet.transport, **kw)
        return fleet, router

    def test_version_gauge_scraped_into_handle(self):
        fleet, router = self._fleet_router(2)
        router.step()
        assert [rep.version for rep in router.replicas] == [0, 0]
        # Replica 1 swaps; the next scrape sees it.
        list(fleet.reps.values())[1]["version"] = 7
        for rep in router.replicas:
            rep.last_scrape_t = -1e18
        router.step()
        assert [rep.version for rep in router.replicas] == [0, 7]
        assert router.summary()["versions"] == {"r0": 0, "r1": 7}

    def test_canary_split_is_seeded_and_proportional(self):
        _fleet, router = self._fleet_router(2,
                                            max_inflight_per_replica=10**6)
        router.set_canary("r0", 0.3, seed=123)
        picks = [router._pick().name for _ in range(400)]
        frac = picks.count("r0") / len(picks)
        assert 0.2 < frac < 0.4
        # Same seed -> identical sequence (deterministic traffic split).
        router.set_canary("r0", 0.3, seed=123)
        assert [router._pick().name for _ in range(400)] == picks

    def test_canary_split_yields_to_availability(self):
        # Canary armed but the non-canary pool has no capacity: traffic
        # still flows (the split is a preference, not an outage).
        _fleet, router = self._fleet_router(2)
        router.set_canary("r0", 0.0, seed=1)   # all traffic to "rest"
        router._replica("r1").state = "draining"
        assert router._pick().name == "r0"

    def test_drain_and_readmit_round_trip(self):
        _fleet, router = self._fleet_router(2)
        assert router.drain_replica("r0", reason="rollout:v1")
        assert router._replica("r0").state == "draining"
        assert router._pick().name == "r1"
        assert router.readmit("r0")
        assert router._replica("r0").state == "ok"
        assert not router.drain_replica("nope", reason="x")
        assert not router.readmit("nope")


# ---------------------------------------------------------------------------
# The promotion gate.
# ---------------------------------------------------------------------------

def _reqs(replica, ttft, tpot, n=8):
    return [{"type": "router_request", "id": i, "replica": replica,
             "ttft_ms": ttft} for i in range(n)] + \
           [{"type": "serve_request", "id": i, "ttft_ms": ttft,
             "tpot_ms": tpot, "output_tokens": 4} for i in range(n)]


class TestGateCompare:
    def test_rc0_on_parity(self):
        rc, res = gate_compare(_reqs("r1", 10.0, 2.0),
                               _reqs("r0", 10.5, 2.1), pct=25.0)
        assert rc == 0
        assert set(GATE_METRICS) <= set(res["metrics"])

    def test_rc1_names_the_failing_metric(self):
        rc, res = gate_compare(_reqs("r1", 10.0, 2.0),
                               _reqs("r0", 40.0, 2.0), pct=25.0)
        assert rc == 1
        bad = [r["metric"] for r in res["regressions"]]
        assert "serve_ttft_p90_ms" in bad and "router_ttft_p90_ms" in bad
        assert "serve_tpot_p90_ms" not in bad

    def test_rc2_when_either_side_is_blind(self):
        assert gate_compare(_reqs("r1", 10.0, 2.0), [], pct=25.0)[0] == 2
        assert gate_compare([], _reqs("r0", 10.0, 2.0), pct=25.0)[0] == 2

    def test_participates_only_when_both_carry_tpot(self):
        # Baseline without TPOT: a canary TPOT regression cannot fire —
        # but TTFT still participates (per-metric, not per-stream).
        base = [{"type": "router_request", "id": i, "replica": "r1",
                 "ttft_ms": 10.0} for i in range(8)]
        rc, res = gate_compare(base, _reqs("r0", 10.0, 99.0), pct=25.0)
        assert rc == 0
        assert "serve_tpot_p90_ms" not in res["metrics"]
        assert "router_ttft_p90_ms" in res["metrics"]


# ---------------------------------------------------------------------------
# Controller state machine on the simulated fleet.
# ---------------------------------------------------------------------------

class TestControllerStateMachine:
    def test_clean_roll_phase_order_and_versions(self):
        ctl, router, fleet = _drive_sim_rollout(gate_pct=50.0)
        assert ctl.state == "done"
        assert {rep["version"] for rep in fleet.reps.values()} == {1}
        assert ctl.swap_compile_misses == 0
        assert ctl.window_s is not None and ctl.window_s >= 0.0
        assert router.counters["admitted"] == router.counters["completed"]
        by_rep: dict = {}
        for _t, rep, phase in ctl.history:
            by_rep.setdefault(rep, []).append(phase)
        for rep, phases in by_rep.items():
            core = [p for p in phases
                    if p in ("drain", "swapped", "readmitted")]
            assert core == ["drain", "swapped", "readmitted"], (rep, phases)
        # Canary first, promoted exactly once, before the rest rolled.
        flat = [(rep, ph) for _t, rep, ph in ctl.history]
        assert flat[0][0] == "r0"
        assert [p for _r, p in flat].count("promoted") == 1

    def test_poisoned_canary_rolls_back_naming_metric(self):
        ctl, _router, fleet = _drive_sim_rollout(poisoned_ttft_ms=500.0,
                                                 gate_pct=50.0)
        assert ctl.state == "aborted"
        assert ctl.abort_metric in GATE_METRICS
        assert {rep["version"] for rep in fleet.reps.values()} == {0}
        # The canary was moved and moved BACK through the same seam.
        canary_swaps = [v for url, v in fleet.swaps
                        if url.endswith("/r0")]
        assert canary_swaps == [1, 0]
        phases = [p for _t, r, p in ctl.history if r == "r0"]
        assert phases[-1] == "rolled_back"

    def test_starved_gate_rolls_back_instead_of_promoting(self):
        """A bake that never collects both sides must NOT promote."""
        fleet = _SimFleet(2)
        router = Router(list(fleet.reps), transport=fleet.transport,
                        scrape_interval_s=0.0, hedge_ms=0.0)
        clock = [0.0]
        ctl = RolloutController(
            router, transport=fleet.transport, clock=lambda: clock[0],
            current_version=0, canary_frac=0.5, gate_pct=25.0,
            bake_min_samples=5, bake_timeout_s=1.0, drain_timeout_s=10.0,
            poll_interval_s=0.0)
        ctl.start(1)
        for _ in range(50):
            if ctl.state == "bake":
                break
            clock[0] += 0.01
            ctl.tick()
        assert ctl.state == "bake"
        clock[0] += 5.0          # deadline passes with zero traffic
        for _ in range(20):
            ctl.tick()
            if ctl.done():
                break
        assert ctl.state == "aborted"
        assert ctl.abort_metric == "insufficient_data"
        assert {rep["version"] for rep in fleet.reps.values()} == {0}

    def test_gate_disabled_promotes_without_bake(self):
        ctl, _router, fleet = _drive_sim_rollout(gate_pct=0.0)
        assert ctl.state == "done"
        assert {rep["version"] for rep in fleet.reps.values()} == {1}

    def test_single_replica_fleet_skips_canary(self):
        ctl, _router, fleet = _drive_sim_rollout(n=1, gate_pct=50.0)
        assert ctl.state == "done"
        assert {rep["version"] for rep in fleet.reps.values()} == {1}
        assert all(p != "promoted" for _t, _r, p in ctl.history)

    def test_env_knob_resolution(self, monkeypatch):
        monkeypatch.setenv(rollout_lib.ENV_CANARY_FRAC, "0.5")
        monkeypatch.setenv(rollout_lib.ENV_GATE, "10")
        monkeypatch.setenv(rollout_lib.ENV_WATCH, "/ck/dir")
        assert rollout_lib.resolve_canary_frac() == 0.5
        assert rollout_lib.resolve_gate_pct() == 10.0
        assert rollout_lib.resolve_watch_dir() == "/ck/dir"
        monkeypatch.setenv(rollout_lib.ENV_CANARY_FRAC, "junk")
        monkeypatch.setenv(rollout_lib.ENV_GATE, "-3")
        assert rollout_lib.resolve_canary_frac() == \
            rollout_lib.DEFAULT_CANARY_FRAC
        assert rollout_lib.resolve_gate_pct() == 0.0

    def test_check_is_clean(self):
        assert rollout_lib.check() == []


# ---------------------------------------------------------------------------
# Offline accounting: fleet_stats reads the rollout story back.
# ---------------------------------------------------------------------------

class TestFleetStatsRollout:
    def _base(self, t, typ, **kw):
        return {"t": t, "type": typ, **kw}

    def test_mixed_window_and_versions(self):
        evs = [
            self._base(1.0, "router_admit", id=0),
            self._base(1.1, "router_request", id=0, replica="r0",
                       ttft_ms=5.0),
            self._base(2.0, "rollout_step", replica="r0", version=1,
                       phase="swapped"),
            self._base(2.5, "rollout_step", replica="r1", version=1,
                       phase="relaunched"),
            self._base(3.0, "rollout_step", replica="r2", version=1,
                       phase="swapped"),
            self._base(3.1, "rollout_done", version=1, replicas=3),
        ]
        fs = goodput.fleet_stats(evs)
        v = fs["versions"]
        assert v["by_replica"] == {"r0": 1, "r1": 1, "r2": 1}
        assert v["target"] == 1 and not v["aborted"]
        assert v["mixed_window_s"] == 1.0

    def test_abort_and_rollback_accounting(self):
        evs = [
            self._base(1.0, "router_admit", id=0),
            self._base(1.1, "router_request", id=0, replica="r1",
                       ttft_ms=5.0),
            self._base(2.0, "rollout_step", replica="r0", version=1,
                       phase="swapped"),
            self._base(3.0, "rollout_abort", version=1,
                       metric="serve_ttft_p90_ms", reason="regressed"),
            self._base(3.5, "rollout_step", replica="r0", version=0,
                       phase="rolled_back"),
        ]
        v = goodput.fleet_stats(evs)["versions"]
        assert v["aborted"] and v["abort_metric"] == "serve_ttft_p90_ms"
        # rolled_back updates the replica's version but must NOT widen
        # the mixed window (only swapped/relaunched timestamps do).
        assert v["by_replica"] == {"r0": 0}
        assert v["mixed_window_s"] == 0.0

    def test_no_rollout_traffic_keeps_versions_none(self):
        evs = [self._base(1.0, "router_admit", id=0),
               self._base(1.1, "router_request", id=0, replica="r0",
                          ttft_ms=5.0)]
        assert goodput.fleet_stats(evs)["versions"] is None

    def test_rollout_events_schema_registered(self):
        for etype in rollout_lib.ROLLOUT_EVENT_TYPES:
            assert etype in events.REQUIRED_FIELDS


# ---------------------------------------------------------------------------
# Fault seams (satellite 1's grammar half).
# ---------------------------------------------------------------------------

def test_rollout_fault_seams_are_deterministic():
    from tpuframe.resilience import faults

    (f,) = faults.parse("slow_canary:times=1000:delay_s=0.05")
    assert f.kind == "slow" and f.times == 1000 and f.delay_s == 0.05
    (g,) = faults.parse("crash_during_swap:rank=1")
    assert g.kind == "crash" and g.rank == 1
    for seam, kind in (("slow_canary", "slow"),
                       ("crash_during_swap", "crash")):
        (h,) = faults.parse(seam)
        assert h.kind == kind


# ---------------------------------------------------------------------------
# TF121: the live weight-swap seam lint (satellite 6).
# ---------------------------------------------------------------------------

class TestTF121:
    RAW = "def apply(engine, p):\n    engine.params = p\n"

    def _lint(self, src, path):
        from tpuframe.analysis import source_lint

        return [f for f in source_lint.lint_source(src, path)
                if f.rule == "TF121"]

    def test_raw_params_write_flagged_in_rollout(self):
        assert len(self._lint(self.RAW,
                              "tpuframe/serve/rollout.py")) == 1

    def test_raw_params_write_flagged_in_replica(self):
        assert len(self._lint(self.RAW,
                              "tpuframe/serve/replica.py")) == 1

    def test_setattr_spelling_flagged(self):
        src = "def apply(e, p):\n    setattr(e, 'params', p)\n"
        assert len(self._lint(src, "tpuframe/serve/rollout.py")) == 1

    def test_augassign_flagged(self):
        src = "def nudge(e, d):\n    e.params += d\n"
        assert len(self._lint(src, "tpuframe/serve/replica.py")) == 1

    def test_sanctioned_swap_call_clean(self):
        src = "def apply(engine, p):\n    engine.swap_params(p)\n"
        assert self._lint(src, "tpuframe/serve/rollout.py") == []

    def test_engine_hosts_the_seam(self):
        # engine.py IS the seam — swap_params' own `self.params = ...`
        # must not be in scope (and nor is any other module).
        assert self._lint(self.RAW, "tpuframe/serve/engine.py") == []
        assert self._lint(self.RAW, "tpuframe/train.py") == []

    def test_reading_params_is_fine(self):
        src = ("def misses(engine):\n"
               "    leaves = engine.params\n"
               "    return leaves\n")
        assert self._lint(src, "tpuframe/serve/rollout.py") == []

    def test_suppression_honoured(self):
        src = ("def fixture(e, p):\n"
               "    e.params = p  # tf-lint: ok[TF121]\n")
        assert self._lint(src, "tpuframe/serve/rollout.py") == []

    def test_tree_is_clean(self):
        from pathlib import Path

        from tpuframe.analysis import source_lint

        findings = [f for f in source_lint.lint_paths(
            [Path("tpuframe")]) if f.rule == "TF121"]
        assert findings == [], "\n".join(map(str, findings))
