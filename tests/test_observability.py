"""Observability: step timeline (HOROVOD_TIMELINE parity) + fusion-threshold
knob (HOROVOD_FUSION_THRESHOLD parity) — SURVEY.md §5.1, §3b — plus the
obs v2 surface: structured run events, goodput/MFU accounting, devmem
telemetry, and the ``python -m tpuframe.obs`` analyzer."""

import json
import os
import pathlib
import subprocess
import sys
import time

import pytest

import tpuframe
from tpuframe.obs import devmem
from tpuframe.obs import events
from tpuframe.obs import goodput
from tpuframe.obs import metrics as obs_metrics
from tpuframe.obs.heartbeat import Heartbeat
from tpuframe.obs.timeline import StepTimeline
from tpuframe.parallel import tuning

_REPO = pathlib.Path(tpuframe.__file__).parent.parent
_SAMPLES = str(_REPO / "docs" / "samples")


def test_step_timeline_events(tmp_path):
    path = str(tmp_path / "trace.json")
    tl = StepTimeline(path)
    with tl.phase("train_step", step=1):
        pass
    tl.instant("fault", reason="test")
    tl.close()
    trace = json.load(open(path))
    names = [e["name"] for e in trace["traceEvents"]]
    assert names == ["train_step", "fault"]
    ev = trace["traceEvents"][0]
    assert ev["ph"] == "X" and ev["dur"] >= 0 and ev["args"] == {"step": 1}


def test_from_env_disabled_by_default(monkeypatch):
    monkeypatch.delenv("TPUFRAME_TIMELINE", raising=False)
    assert StepTimeline.from_env() is None


def test_tensorboard_events_stock_readable(tmp_path):
    """Our hand-encoded event files must parse with tensorboard's OWN loader
    (SURVEY.md §5.5 'event files a stock TensorBoard can read')."""
    from tpuframe.obs.tensorboard import SummaryWriter

    w = SummaryWriter(str(tmp_path))
    w.add_scalars(1, {"loss": 2.5, "skip_me": "str"}, prefix="train")
    w.add_scalars(2, {"loss": 1.25}, prefix="train")
    w.add_scalar("eval/acc", 0.75, 2)
    w.close()

    files = [f for f in os.listdir(tmp_path) if "tfevents" in f]
    assert len(files) == 1
    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader)

    events = list(EventFileLoader(str(tmp_path / files[0])).Load())
    assert events[0].file_version == "brain.Event:2"
    # TB's loader migrates simple_value -> rank-0 tensor (data_compat);
    # handle both, as a stock TB frontend does.
    scalars = [(v.tag, e.step,
                v.simple_value if v.WhichOneof("value") == "simple_value"
                else v.tensor.float_val[0])
               for e in events for v in e.summary.value]
    assert ("train/loss", 1, 2.5) in scalars
    assert ("train/loss", 2, 1.25) in scalars
    assert ("eval/acc", 2, 0.75) in scalars
    assert not any(t == "train/skip_me" for t, _, _ in scalars)


def test_metric_logger_tb_sink(tmp_path):
    from tpuframe.obs.metrics import MetricLogger

    logger = MetricLogger(None, stdout=False, tb_dir=str(tmp_path / "tb"))
    logger.log(3, {"loss": 0.5, "accuracy": 0.9})
    logger.log(3, {"accuracy": 0.8}, prefix="eval")
    logger.close()
    files = [f for f in os.listdir(tmp_path / "tb") if "tfevents" in f]
    assert len(files) == 1
    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader)

    tags = {v.tag for e in EventFileLoader(
        str(tmp_path / "tb" / files[0])).Load() for v in e.summary.value}
    assert {"train/loss", "train/accuracy", "eval/accuracy"} <= tags


def test_fusion_flags_shape():
    flags = tuning.fusion_flags(64 * 1024 * 1024)
    assert any("all_reduce_combine_threshold_bytes=67108864" in f
               for f in flags)


def test_apply_after_backend_init_refuses():
    # jax backend is live in the test process — apply must refuse, not lie.
    assert tuning.apply(1 << 20) is False


@pytest.mark.slow
def test_fusion_env_applies_in_fresh_process():
    code = (
        "import os; os.environ['TPUFRAME_FUSION_THRESHOLD'] = str(1 << 25)\n"
        "from tpuframe.parallel import bootstrap, tuning\n"
        "bootstrap.initialize()\n"
        "assert tuning.current() == 1 << 25, tuning.current()\n"
        "assert 'combine_threshold_bytes=33554432' in os.environ['XLA_FLAGS']\n"
        "import jax; jax.numpy.zeros(2).block_until_ready()\n"
        "print('FUSION_OK')\n"
    )
    env = dict(os.environ)
    env.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"})
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert "FUSION_OK" in out.stdout, out.stderr[-800:]


@pytest.mark.slow
def test_timeline_through_harness(tmp_path):
    path = str(tmp_path / "tl.json")
    env = dict(os.environ)
    env.update({
        "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": env.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count=4",
        "TPUFRAME_TIMELINE": path,
    })
    out = subprocess.run(
        [sys.executable, "-m", "tpuframe.train", "--config", "smoke",
         "--set", "total_steps=6", "--set", "log_every=3",
         "--set", "eval_every=6", "--set", "eval_batches=1",
         "--set", "global_batch=16"],
        env=env, capture_output=True, text=True, timeout=500)
    assert out.returncode == 0, out.stderr[-1500:]
    trace = json.load(open(path))
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"data_wait", "train_step", "eval"} <= names
    steps = [e for e in trace["traceEvents"] if e["name"] == "train_step"]
    assert len(steps) == 6


# ---------------------------------------------------------------------------
# obs v2: structured run events.
# ---------------------------------------------------------------------------

def _rec(t, etype, host="h0-p0", attempt=0, **kw):
    return {"schema": 1, "type": etype, "t": t, "host": host, "proc": 0,
            "attempt": attempt, **kw}


def _write_events(path, recs):
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_event_log_round_trip(tmp_path, monkeypatch):
    monkeypatch.setenv(events.ENV_ATTEMPT, "3")
    log = events.EventLog(str(tmp_path), host="h0-p0", proc=0)
    log.emit("step", step=7, wall_ms=12.5)
    log.emit("ckpt_save", step=7, ms=30.0, async_write=False)
    log.close()
    # Emission after close is a silent no-op, never a raise.
    assert log.emit("step", step=8, wall_ms=1.0) is None
    back = events.read_file(log.path, strict=True)
    assert [r["type"] for r in back] == ["step", "ckpt_save"]
    assert back[0]["step"] == 7 and back[0]["attempt"] == 3
    assert back[0]["schema"] == events.SCHEMA_VERSION
    assert all(events.validate_record(r) == [] for r in back)
    assert events.validate_files([log.path]) == []


def test_event_singleton_off_by_default(monkeypatch):
    monkeypatch.delenv(events.ENV_DIR, raising=False)
    events.close()
    assert events.init() is None
    assert not events.enabled()
    assert events.emit("step", step=1, wall_ms=1.0) is None


def test_event_log_append_across_attempts(tmp_path):
    # Relaunched attempts reopen the same per-host file in append mode —
    # one continuous, attempt-tagged stream.
    a = events.EventLog(str(tmp_path), host="h0-p0", proc=0)
    a.emit("step", step=1, wall_ms=5.0)
    a.close()
    b = events.EventLog(str(tmp_path), host="h0-p0", proc=0)
    b.emit("step", step=2, wall_ms=5.0)
    b.close()
    assert a.path == b.path
    assert [r["step"] for r in events.read_file(a.path)] == [1, 2]


def test_event_read_skips_torn_tail(tmp_path):
    p = tmp_path / "events.h0-p0.jsonl"
    _write_events(p, [_rec(1.0, "step", step=1, wall_ms=5.0)])
    with open(p, "a") as f:
        f.write('{"schema": 1, "type": "step", "t": 2.0, "ho')  # crash tear
    assert [r["step"] for r in events.read_file(str(p))] == [1]
    with pytest.raises(ValueError, match="unparseable"):
        events.read_file(str(p), strict=True)
    assert events.validate_files([str(p)])  # selfcheck is strict


def test_event_merge_orders_across_hosts(tmp_path):
    _write_events(tmp_path / "events.b-p1.jsonl",
                  [_rec(2.0, "step", host="b-p1", step=2, wall_ms=1.0),
                   _rec(4.0, "step", host="b-p1", step=3, wall_ms=1.0)])
    _write_events(tmp_path / "events.a-p0.jsonl",
                  [_rec(1.0, "step", host="a-p0", step=1, wall_ms=1.0),
                   _rec(2.0, "step", host="a-p0", step=2, wall_ms=1.0)])
    (tmp_path / "not-events.txt").write_text("ignored")
    merged = events.merge(str(tmp_path))
    assert [(r["t"], r["host"]) for r in merged] == [
        (1.0, "a-p0"), (2.0, "a-p0"), (2.0, "b-p1"), (4.0, "b-p1")]


def test_validate_record_catches_contract_breaks():
    good = _rec(1.0, "stall", last_step=4, idle_s=9.0)
    assert events.validate_record(good) == []
    # Both shipped schema generations read; an unknown future one fails.
    assert events.validate_record({**good, "schema": 2}) == []
    assert events.SCHEMA_VERSION in events.ACCEPTED_SCHEMAS
    assert events.validate_record({**good, "schema": 99})
    assert events.validate_record(_rec(1.0, "no_such_type"))
    missing = _rec(1.0, "run_end")  # no final_step/wall_s/goodput
    assert len(events.validate_record(missing)) == 3


# ---------------------------------------------------------------------------
# obs v2: goodput / MFU accounting.
# ---------------------------------------------------------------------------

def test_goodput_meter_buckets_sum_to_wall():
    now = [100.0]
    m = goodput.GoodputMeter(clock=lambda: now[0])
    m.step(10.0)              # first step = compile
    m.step(1.0)
    m.step(1.0)
    m.charge("ckpt", 2.0)
    m.charge("stall", 3.0)
    now[0] += 20.0
    s = m.summary()
    assert s["steps"] == 3 and s["productive_steps"] == 2
    assert s["buckets"]["compile"] == 10.0
    assert s["buckets"]["productive"] == 2.0
    assert s["buckets"]["other"] == pytest.approx(20.0 - 17.0)
    assert sum(s["buckets"].values()) == pytest.approx(s["wall_s"])
    with pytest.raises(ValueError):
        m.charge("nonsense", 1.0)


def test_mfu_arithmetic_and_guards():
    hw = pytest.importorskip("tpuframe.tune.roofline").get_hardware("v5e")
    # One device running at exactly half the bf16 peak for one second.
    assert goodput.mfu(hw.bf16_flops / 2, 1.0, generation="v5e",
                       n_devices=1) == pytest.approx(0.5)
    # Peak scales with slice size.
    assert goodput.mfu(hw.bf16_flops, 1.0, generation="v5e",
                       n_devices=4) == pytest.approx(0.25)
    assert goodput.mfu(0.0, 1.0) == 0.0
    assert goodput.mfu(1e12, 0.0) == 0.0
    assert goodput.flops_fallback(10, 4, 2) == 6.0 * 10 * 4 * 2


def test_from_events_crashed_attempt_reconstruction():
    # No run_end anywhere: buckets rebuilt from raw step/ckpt/stall
    # events, "other" absorbing the unattributed remainder of the span.
    stream = [
        _rec(0.0, "run_start", config="c", config_hash="h",
             jax_version="j", devices=2, flops_per_step=1e12,
             generation="v5e"),
        _rec(10.0, "step", step=1, wall_ms=9000.0),
        _rec(11.0, "step", step=2, wall_ms=500.0),
        _rec(12.0, "step", step=3, wall_ms=500.0),
        _rec(13.0, "ckpt_save", step=3, ms=1000.0),
        _rec(20.0, "stall", last_step=3, idle_s=5.0),
    ]
    s = goodput.from_events(stream)
    assert s["attempts"] == 1 and s["steps"] == 3 and s["final_step"] == 3
    b = s["buckets"]
    assert b["compile"] == 9.0 and b["productive"] == 1.0
    assert b["ckpt"] == 1.0 and b["stall"] == 5.0
    assert s["wall_s"] == 20.0
    assert sum(b.values()) == pytest.approx(s["wall_s"])
    # MFU recomputed offline from the run_start flops model.
    assert s["mfu_productive"] == pytest.approx(
        goodput.mfu(1e12, 0.5, generation="v5e", n_devices=2))


def test_from_events_stitches_restarts_on_samples():
    # The shipped docs/samples log: attempt 0 crashes at step 7, attempt
    # 1 resumes from the step-5 checkpoint and completes.
    merged = events.merge(_SAMPLES)
    assert merged, "docs/samples event files missing"
    s = goodput.from_events(merged)
    assert s["attempts"] == 2
    assert s["restart_lost_s"] > 0 and s["retrained_steps"] == 1
    assert s["final_step"] == 12
    assert sum(s["buckets"].values()) == pytest.approx(s["wall_s"],
                                                       abs=0.01)
    assert s["mfu_productive"] > 0
    assert s["peak_hbm_bytes"] == 6200000000


# ---------------------------------------------------------------------------
# obs v2: anomaly detection.
# ---------------------------------------------------------------------------

def test_anomaly_step_regression_rolling_median():
    steps = [_rec(float(i), "step", step=i, wall_ms=100.0)
             for i in range(1, 10)]
    steps[7]["wall_ms"] = 450.0  # 4.5x the rolling median
    found = goodput.find_anomalies(steps + [
        _rec(99.0, "run_end", final_step=9, wall_s=9.0, goodput={})])
    kinds = [f["kind"] for f in found]
    assert kinds == ["step_regression"]
    assert found[0]["step"] == 8
    # The compile step never trips the detector.
    first_slow = [_rec(0.0, "step", step=1, wall_ms=90000.0)] + steps[1:]
    found2 = goodput.find_anomalies(first_slow + [
        _rec(99.0, "run_end", final_step=9, wall_s=9.0, goodput={})])
    assert [f["kind"] for f in found2] == ["step_regression"]


def test_anomaly_stall_retry_storm_no_run_end():
    stream = ([_rec(float(i), "retry", op="gcs_read", outcome="retrying")
               for i in range(6)]
              + [_rec(30.0, "stall", last_step=4, idle_s=12.0),
                 _rec(31.0, "step", step=4, wall_ms=10.0)])
    found = goodput.find_anomalies(stream)
    kinds = sorted(f["kind"] for f in found)
    assert kinds == ["no_run_end", "retry_storm", "stall"]
    storm = next(f for f in found if f["kind"] == "retry_storm")
    # One report per stream, raised at the first threshold crossing.
    assert storm["count"] == 5


def test_anomaly_low_mfu_opt_in():
    stream = [
        _rec(0.0, "run_start", config="c", config_hash="h",
             jax_version="j", devices=1, flops_per_step=1.0,
             generation="v5e"),
        _rec(1.0, "step", step=1, wall_ms=100.0),
        _rec(2.0, "step", step=2, wall_ms=100.0),
        _rec(3.0, "run_end", final_step=2, wall_s=3.0, goodput={}),
    ]
    assert goodput.find_anomalies(stream) == []          # off by default
    found = goodput.find_anomalies(stream, mfu_min=0.5)  # 1 flop: ~0 MFU
    assert [f["kind"] for f in found] == ["low_mfu"]


def test_anomaly_blocked_input_and_blocked_ckpt():
    stream = [
        _rec(1.0, "step", step=1, wall_ms=400.0, input_wait_ms=2.0),
        _rec(2.0, "step", step=2, wall_ms=400.0, input_wait_ms=1800.0),
        # sync save: the whole write blocks the step path (v1: no
        # block_ms, ms is the blocking time)
        _rec(3.0, "ckpt_save", step=2, ms=2500.0),
        # async save: huge span, tiny blocking slice — NOT flagged
        _rec(4.0, "ckpt_save", step=4, ms=9000.0, block_ms=40.0,
             async_write=True),
        _rec(9.0, "run_end", final_step=4, wall_s=9.0, goodput={}),
    ]
    kinds = sorted(f["kind"] for f in goodput.find_anomalies(stream))
    assert kinds == ["blocked_ckpt", "blocked_input"]
    blocked = {f["kind"]: f for f in goodput.find_anomalies(stream)}
    assert blocked["blocked_input"]["step"] == 2
    assert blocked["blocked_ckpt"]["step"] == 2  # the sync one, not async
    # The threshold is policy: raising it past both clears the findings.
    assert goodput.find_anomalies(stream, blocked_ms=3000.0) == []


def test_anomaly_goodput_invariant_sums_to_wall():
    def run_end(buckets, wall):
        return _rec(10.0, "run_end", final_step=2, wall_s=wall,
                    goodput={"wall_s": wall, "buckets": buckets})

    ok = {"init": 1.0, "compile": 2.0, "productive": 3.0, "input": 0.5,
          "ckpt": 0.5, "eval": 0.0, "stall": 0.0, "other": 3.0}
    assert goodput.find_anomalies([run_end(ok, 10.0)]) == []
    # A lost slice (other dropped a second) violates the partition and
    # is flagged, never silently renormalized.
    bad = dict(ok, other=2.0)
    found = goodput.find_anomalies([run_end(bad, 10.0)])
    assert [f["kind"] for f in found] == ["goodput_invariant"]
    assert found[0]["bucket_sum_s"] == pytest.approx(9.0)
    # run_end with no buckets at all (crashed mid-write): not flagged
    # here — no_run_end and the reconstruction path own that case.
    assert goodput.find_anomalies(
        [_rec(1.0, "run_end", final_step=0, wall_s=5.0, goodput={})]) == []


def test_from_events_v2_input_and_async_block_reconstruction():
    # Crashed attempt (no run_end), schema-2 records: input_wait_ms
    # accumulates into the input bucket, and an async ckpt_save charges
    # only its block_ms — the upload tail overlapped training and must
    # not be billed to ckpt.
    stream = [
        _rec(0.0, "step", step=1, wall_ms=5000.0, input_wait_ms=1000.0),
        _rec(10.0, "step", step=2, wall_ms=500.0, input_wait_ms=250.0),
        _rec(11.0, "step", step=3, wall_ms=500.0, input_wait_ms=250.0),
        _rec(12.0, "ckpt_save", step=3, ms=6000.0, block_ms=100.0,
             async_write=True),
        _rec(20.0, "step", step=4, wall_ms=500.0),  # v1 record: no wait
    ]
    b = goodput.from_events(stream)["buckets"]
    assert b["input"] == pytest.approx(1.5)
    assert b["ckpt"] == pytest.approx(0.1)
    assert b["compile"] == pytest.approx(5.0)
    assert b["productive"] == pytest.approx(1.5)
    # v1 async save without block_ms: blocking unknown, charged as 0 —
    # a v1 sync save still charges its full ms.
    v1 = [_rec(0.0, "step", step=1, wall_ms=1000.0),
          _rec(5.0, "ckpt_save", step=1, ms=2000.0, async_write=True),
          _rec(9.0, "ckpt_save", step=1, ms=2000.0)]
    assert goodput.from_events(v1)["buckets"]["ckpt"] == pytest.approx(2.0)


def test_async_ckpt_sample_is_schema2_with_input_bucket():
    # The shipped async-checkpoint sample run: schema 2 end to end,
    # validating alongside the schema-1 main sample (ACCEPTED_SCHEMAS
    # spans both), with the input bucket populated and the async save's
    # block_ms << ms.
    sample = str(pathlib.Path(_SAMPLES) / "async_ckpt")
    files = events.event_files(sample)
    assert files and events.validate_files(files) == []
    merged = events.merge(sample)
    assert all(r["schema"] == 2 for r in merged)
    s = goodput.from_events(merged)
    assert s["buckets"]["input"] > 0
    assert sum(s["buckets"].values()) == pytest.approx(s["wall_s"],
                                                       abs=0.05)
    save = next(r for r in merged if r["type"] == "ckpt_save")
    assert save["async_write"] and save["ms"] > 10 * save["block_ms"]
    kinds = [f["kind"] for f in goodput.find_anomalies(merged)]
    assert kinds == ["blocked_input"]  # the deliberately starved step 6


# ---------------------------------------------------------------------------
# obs v2: devmem telemetry (no-op on CPU), heartbeat events, counters.
# ---------------------------------------------------------------------------

def test_devmem_noop_on_cpu():
    assert devmem.sample() is None  # CPU backend exposes no memory_stats
    emitted = []
    s = devmem.DevmemSampler(interval_s=0.01,
                             emit_fn=lambda **kw: emitted.append(kw))
    s.start()
    assert not s.active and s._thread is None  # stays inert: zero overhead
    s.stop()
    assert s.peak_summary() == {} and emitted == []


def test_devmem_sampler_peak_tracking():
    # Drive _record directly with synthetic stats — the TPU-side math.
    s = devmem.DevmemSampler(interval_s=60.0, emit_fn=lambda **kw: None)
    s._record([{"id": 0, "peak_bytes_in_use": 100, "bytes_in_use": 90},
               {"id": 1, "peak_bytes_in_use": 300}])
    s._record([{"id": 0, "peak_bytes_in_use": 200}])
    assert s.peak_summary() == {"peak_hbm_bytes": 300,
                                "per_device": {"0": 200, "1": 300}}


def test_heartbeat_structured_stall_event_and_rearm(tmp_path, monkeypatch):
    monkeypatch.setenv(events.ENV_DIR, str(tmp_path))
    log = events.init()
    h = Heartbeat(timeout_s=0.08, poll_s=0.02)
    h.start()
    try:
        deadline = time.monotonic() + 5.0
        while h.stall_count < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert h.stall_count == 1 and h.stalled
        h.beat(7)  # recovery re-arms the watchdog...
        assert not h.stalled
        while h.stall_count < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert h.stall_count == 2  # ...so a second stall reports again
    finally:
        h.stop()
        events.close()
    stalls = [r for r in events.read_file(log.path)
              if r["type"] == "stall"]
    assert len(stalls) == 2
    assert stalls[0]["last_step"] == 0 and stalls[1]["last_step"] == 7
    assert stalls[1]["stall_count"] == 2
    assert all(events.validate_record(r) == [] for r in stalls)


def test_counters_reset_and_bump_tolerance():
    obs_metrics.counters_reset()
    try:
        obs_metrics.bump("x.y")
        obs_metrics.bump("x.y", 2)
        obs_metrics.bump("x.y", "3")       # coerced
        obs_metrics.bump("x.y", object())  # swallowed, never raises
        obs_metrics.bump("z.w")
        assert obs_metrics.counters()["x.y"] == 6
        obs_metrics.counters_reset("x.")
        assert "x.y" not in obs_metrics.counters()
        assert obs_metrics.counters()["z.w"] == 1
    finally:
        obs_metrics.counters_reset()


# ---------------------------------------------------------------------------
# obs v2: the analyzer CLI.
# ---------------------------------------------------------------------------

def test_obs_cli_summarize_samples(capsys):
    from tpuframe.obs.__main__ import main as obs_main

    assert obs_main(["summarize", _SAMPLES]) == 0
    out = capsys.readouterr().out
    assert "goodput breakdown" in out
    assert "restart-lost" in out
    assert "mfu_productive" in out
    assert "peak HBM" in out
    assert "compile_cache.hits = 1" in out


def test_obs_cli_selfcheck_and_anomalies(tmp_path, capsys):
    from tpuframe.obs.__main__ import main as obs_main

    assert obs_main(["summarize", "--selfcheck"]) == 0
    # The sample log contains a stall + a crashed attempt: anomalies is
    # scriptable and exits 1.
    assert obs_main(["anomalies", _SAMPLES]) == 1
    out = capsys.readouterr().out
    assert "[stall]" in out and "[no_run_end]" in out
    # --blocked-ms is plumbed through: past the async sample's starved
    # step (1350 ms) the scan comes back clean.
    async_sample = str(pathlib.Path(_SAMPLES) / "async_ckpt")
    assert obs_main(["anomalies", async_sample]) == 1
    assert "[blocked_input]" in capsys.readouterr().out
    assert obs_main(["anomalies", async_sample, "--blocked-ms",
                     "2000"]) == 0
    merged = tmp_path / "merged.jsonl"
    assert obs_main(["merge", _SAMPLES, "-o", str(merged)]) == 0
    lines = [json.loads(l) for l in merged.read_text().splitlines()]
    assert lines == events.merge(_SAMPLES)


def test_obs_cli_empty_dir_exits_2(tmp_path):
    from tpuframe.obs.__main__ import main as obs_main

    with pytest.raises(SystemExit) as exc:
        obs_main(["summarize", str(tmp_path)])
    assert exc.value.code == 2


# ---------------------------------------------------------------------------
# obs v2: the event stream through the real harness (acceptance shape).
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_event_stream_through_harness(tmp_path):
    evdir = str(tmp_path / "events")
    env = dict(os.environ)
    env.update({
        "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": env.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count=4",
        "TPUFRAME_EVENTS_DIR": evdir,
    })
    out = subprocess.run(
        [sys.executable, "-m", "tpuframe.train", "--config", "smoke",
         "--set", "total_steps=6", "--set", "log_every=3",
         "--set", "eval_every=6", "--set", "eval_batches=1",
         "--set", "global_batch=16"],
        env=env, capture_output=True, text=True, timeout=500)
    assert out.returncode == 0, out.stderr[-1500:]

    files = events.event_files(evdir)
    assert len(files) == 1
    assert events.validate_files(files) == [], events.validate_files(files)
    merged = events.merge(evdir)
    types = {r["type"] for r in merged}
    assert {"run_start", "step", "run_end"} <= types
    start = next(r for r in merged if r["type"] == "run_start")
    assert start["flops_per_step"] > 0 and start["devices"] == 4
    assert len([r for r in merged if r["type"] == "step"]) == 6

    s = goodput.from_events(merged)
    assert s["steps"] == 6 and s["final_step"] == 6
    assert sum(s["buckets"].values()) == pytest.approx(s["wall_s"],
                                                       abs=0.02)
    assert s.get("mfu_productive", 0) > 0
    end = next(r for r in merged if r["type"] == "run_end")
    assert end["goodput"]["buckets"]["productive"] > 0
