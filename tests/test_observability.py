"""Observability: step timeline (HOROVOD_TIMELINE parity) + fusion-threshold
knob (HOROVOD_FUSION_THRESHOLD parity) — SURVEY.md §5.1, §3b."""

import json
import os
import subprocess
import sys

import pytest

from tpuframe.obs.timeline import StepTimeline
from tpuframe.parallel import tuning


def test_step_timeline_events(tmp_path):
    path = str(tmp_path / "trace.json")
    tl = StepTimeline(path)
    with tl.phase("train_step", step=1):
        pass
    tl.instant("fault", reason="test")
    tl.close()
    trace = json.load(open(path))
    names = [e["name"] for e in trace["traceEvents"]]
    assert names == ["train_step", "fault"]
    ev = trace["traceEvents"][0]
    assert ev["ph"] == "X" and ev["dur"] >= 0 and ev["args"] == {"step": 1}


def test_from_env_disabled_by_default(monkeypatch):
    monkeypatch.delenv("TPUFRAME_TIMELINE", raising=False)
    assert StepTimeline.from_env() is None


def test_tensorboard_events_stock_readable(tmp_path):
    """Our hand-encoded event files must parse with tensorboard's OWN loader
    (SURVEY.md §5.5 'event files a stock TensorBoard can read')."""
    from tpuframe.obs.tensorboard import SummaryWriter

    w = SummaryWriter(str(tmp_path))
    w.add_scalars(1, {"loss": 2.5, "skip_me": "str"}, prefix="train")
    w.add_scalars(2, {"loss": 1.25}, prefix="train")
    w.add_scalar("eval/acc", 0.75, 2)
    w.close()

    files = [f for f in os.listdir(tmp_path) if "tfevents" in f]
    assert len(files) == 1
    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader)

    events = list(EventFileLoader(str(tmp_path / files[0])).Load())
    assert events[0].file_version == "brain.Event:2"
    # TB's loader migrates simple_value -> rank-0 tensor (data_compat);
    # handle both, as a stock TB frontend does.
    scalars = [(v.tag, e.step,
                v.simple_value if v.WhichOneof("value") == "simple_value"
                else v.tensor.float_val[0])
               for e in events for v in e.summary.value]
    assert ("train/loss", 1, 2.5) in scalars
    assert ("train/loss", 2, 1.25) in scalars
    assert ("eval/acc", 2, 0.75) in scalars
    assert not any(t == "train/skip_me" for t, _, _ in scalars)


def test_metric_logger_tb_sink(tmp_path):
    from tpuframe.obs.metrics import MetricLogger

    logger = MetricLogger(None, stdout=False, tb_dir=str(tmp_path / "tb"))
    logger.log(3, {"loss": 0.5, "accuracy": 0.9})
    logger.log(3, {"accuracy": 0.8}, prefix="eval")
    logger.close()
    files = [f for f in os.listdir(tmp_path / "tb") if "tfevents" in f]
    assert len(files) == 1
    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader)

    tags = {v.tag for e in EventFileLoader(
        str(tmp_path / "tb" / files[0])).Load() for v in e.summary.value}
    assert {"train/loss", "train/accuracy", "eval/accuracy"} <= tags


def test_fusion_flags_shape():
    flags = tuning.fusion_flags(64 * 1024 * 1024)
    assert any("all_reduce_combine_threshold_bytes=67108864" in f
               for f in flags)


def test_apply_after_backend_init_refuses():
    # jax backend is live in the test process — apply must refuse, not lie.
    assert tuning.apply(1 << 20) is False


@pytest.mark.slow
def test_fusion_env_applies_in_fresh_process():
    code = (
        "import os; os.environ['TPUFRAME_FUSION_THRESHOLD'] = str(1 << 25)\n"
        "from tpuframe.parallel import bootstrap, tuning\n"
        "bootstrap.initialize()\n"
        "assert tuning.current() == 1 << 25, tuning.current()\n"
        "assert 'combine_threshold_bytes=33554432' in os.environ['XLA_FLAGS']\n"
        "import jax; jax.numpy.zeros(2).block_until_ready()\n"
        "print('FUSION_OK')\n"
    )
    env = dict(os.environ)
    env.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"})
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert "FUSION_OK" in out.stdout, out.stderr[-800:]


@pytest.mark.slow
def test_timeline_through_harness(tmp_path):
    path = str(tmp_path / "tl.json")
    env = dict(os.environ)
    env.update({
        "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": env.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count=4",
        "TPUFRAME_TIMELINE": path,
    })
    out = subprocess.run(
        [sys.executable, "-m", "tpuframe.train", "--config", "smoke",
         "--set", "total_steps=6", "--set", "log_every=3",
         "--set", "eval_every=6", "--set", "eval_batches=1",
         "--set", "global_batch=16"],
        env=env, capture_output=True, text=True, timeout=500)
    assert out.returncode == 0, out.stderr[-1500:]
    trace = json.load(open(path))
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"data_wait", "train_step", "eval"} <= names
    steps = [e for e in trace["traceEvents"] if e["name"] == "train_step"]
    assert len(steps) == 6
