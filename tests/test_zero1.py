"""tpuframe.parallel.zero1 — ZeRO-1 weight-update sharding (ISSUE PR 7).

Golden invariants pinned here:

* the sharded update is a *layout* decision, never a numeric one —
  ``weight_update="zero1"`` must reproduce the replicated trajectory step
  for step (reduce-scatter(mean) feeds the same global mean gradient to
  the same element-wise update math);
* the collective swap is proven at the wire level: the ``dp-zero1``
  strategy audit must show reduce-scatter + all-gather at EXACTLY the
  pad-to-multiple byte total and no gradient all-reduce above the scalar
  floor;
* the reduce-scatter / all-gather pair round-trips (including the
  gradient transpose, which is how the step's backward actually runs
  them), and non-divisible shards are rejected with a message naming the
  pad-to-multiple fix;
* resolution precedence (env > generation-gated tune DB > replicated
  default) and the fail-open contract: a stale or bogus DB row must
  never break a run;
* TF110 keeps stray optimizer updates out of the harness/parallel tree
  so nothing bypasses the weight-update seam.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpuframe.analysis import budgets as budgets_lib
from tpuframe.analysis import source_lint, strategies
from tpuframe.models import losses, resnet
from tpuframe.obs import events
from tpuframe.parallel import collectives
from tpuframe.parallel import mesh as mesh_lib
from tpuframe.parallel import step as step_lib
from tpuframe.parallel import zero1
from tpuframe.parallel.step import _shard_map
from tpuframe.tune import db as tune_db


# ----------------------------------------------------------------------
# pad-to-multiple layout arithmetic
# ----------------------------------------------------------------------

class TestPadLayout:
    def test_padded_rounds_up_to_multiple(self):
        assert zero1._padded(16, 8) == 16
        assert zero1._padded(17, 8) == 24
        assert zero1._padded(1, 8) == 8
        assert zero1._padded(0, 8) == 0

    def test_padded_bytes_counts_the_padding(self):
        probe = {"w": jax.ShapeDtypeStruct((3, 5), jnp.float32),
                 "b": jax.ShapeDtypeStruct((7,), jnp.float32)}
        # 15 -> 16, 7 -> 8 elements, 4 bytes each
        assert zero1.padded_bytes(probe, 8) == (16 + 8) * 4

    def test_padding_census_self_consistent(self):
        probe = {"w": jax.ShapeDtypeStruct((3, 5), jnp.float32),
                 "b": jax.ShapeDtypeStruct((7,), jnp.bfloat16)}
        census = zero1.padding_census(probe, 8)
        assert census["n_shards"] == 8
        assert len(census["leaves"]) == 2
        for row in census["leaves"]:
            assert row["padded"] % 8 == 0
            assert row["pad_waste"] == row["padded"] - row["size"]
        assert census["padded_elems"] >= census["total_elems"]
        assert census["padded_bytes"] == zero1.padded_bytes(probe, 8)
        assert census["waste_frac"] == pytest.approx(
            (census["padded_elems"] - census["total_elems"])
            / census["total_elems"])

    def test_self_check_clean(self):
        assert zero1.check() == []


# ----------------------------------------------------------------------
# reduce-scatter / all-gather round trip (the wire pattern itself)
# ----------------------------------------------------------------------

class TestCollectivesRoundTrip:
    def test_scatter_gather_identity(self, mesh8):
        x = jnp.arange(16, dtype=jnp.float32)

        def f(x):
            shard = collectives.reduce_scatter(x, "data", average=True)
            assert shard.shape == (2,)
            return collectives.allgather(shard, "data", tiled=True)

        out = jax.jit(_shard_map(f, mesh=mesh8, in_specs=P(),
                                 out_specs=P()))(x)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    def test_scatter_sums_without_average(self, mesh8):
        x = jnp.ones((8,), jnp.float32)

        def f(x):
            return collectives.allgather(
                collectives.reduce_scatter(x, "data", average=False),
                "data", tiled=True)

        out = jax.jit(_shard_map(f, mesh=mesh8, in_specs=P(),
                                 out_specs=P()))(x)
        np.testing.assert_array_equal(np.asarray(out), np.full((8,), 8.0))

    def test_non_divisible_rejected_with_padding_hint(self, mesh8):
        x = jnp.arange(10, dtype=jnp.float32)

        def f(x):
            return collectives.reduce_scatter(x, "data")

        with pytest.raises(ValueError, match="pad-to-multiple"):
            jax.jit(_shard_map(f, mesh=mesh8, in_specs=P(),
                               out_specs=P("data")))(x)

    def test_grad_transposes_through_the_pair(self, mesh8):
        # The step's backward differentiates THROUGH the scatter/gather
        # pair (psum_scatter transposes to all_gather and vice versa);
        # loss = sum(gather(scatter(x, mean))) == sum(x), so d/dx = 1.
        x = jnp.arange(16, dtype=jnp.float32)

        def loss(x):
            def f(x):
                shard = collectives.reduce_scatter(x, "data", average=True)
                full = collectives.allgather(shard, "data", tiled=True)
                return jnp.sum(full)

            per_replica = _shard_map(f, mesh=mesh8, in_specs=P(),
                                     out_specs=P())
            return per_replica(x)

        g = jax.grad(loss)(x)
        np.testing.assert_allclose(np.asarray(g), np.ones(16), rtol=1e-6)


# ----------------------------------------------------------------------
# sharded state construction
# ----------------------------------------------------------------------

def _toy_params():
    return {"w": jnp.ones((3, 5), jnp.float32),
            "b": jnp.zeros((7,), jnp.float32)}


class TestStateLayout:
    def test_init_opt_state_is_flat_padded(self):
        tx = optax.adamw(1e-3)
        opt = zero1.init_opt_state(tx, _toy_params(), 8)
        dims = {leaf.shape for leaf in jax.tree.leaves(opt)
                if getattr(leaf, "ndim", 0) >= 1}
        assert dims == {(16,), (8,)}  # 15 -> 16, 7 -> 8

    def test_make_state_passes_layout_check(self, mesh8):
        tx = optax.adamw(1e-3)
        state = zero1.make_state(_toy_params(), tx, mesh8)
        n = zero1.world_size(mesh8)
        assert n == 8
        assert zero1.check_state_layout(state, n) is state

    def test_make_state_shards_the_moments(self, mesh8):
        tx = optax.sgd(0.1, momentum=0.9)
        state = zero1.make_state(_toy_params(), tx, mesh8)
        for leaf in jax.tree.leaves(state.opt_state):
            if getattr(leaf, "ndim", 0) >= 1:
                shards = leaf.sharding.shard_shape(leaf.shape)
                assert shards[0] == leaf.shape[0] // 8

    def test_replicated_state_rejected(self, mesh8):
        tx = optax.adamw(1e-3)
        state = step_lib.TrainState.create(_toy_params(), tx)
        with pytest.raises(ValueError, match="zero1.make_state"):
            zero1.check_state_layout(state, 8)

    def test_world_of_one_degenerates_to_replicated_update(self):
        tx = optax.sgd(0.1, momentum=0.9)
        params = _toy_params()
        grads = jax.tree.map(lambda p: jnp.full_like(p, 0.5), params)
        opt = tx.init(params)
        new_p, _, norm = zero1.sharded_update(tx, (), params, opt, grads)
        updates, _ = tx.update(grads, tx.init(params), params)
        want = optax.apply_updates(params, updates)
        for a, b in zip(jax.tree.leaves(new_p), jax.tree.leaves(want)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(float(norm),
                                   float(optax.global_norm(grads)),
                                   rtol=1e-6)


# ----------------------------------------------------------------------
# golden-loss equivalence: zero1 reproduces the replicated trajectory
# ----------------------------------------------------------------------

N_GOLDEN_STEPS = 50


def _resnet_run(mesh, weight_update, n_steps=N_GOLDEN_STEPS):
    """test_mem's tiny-ResNet recipe (batch_stats exercise the
    model_state path) under either weight-update mode."""
    model = resnet.ResNet(stage_sizes=(1, 1), block_cls=resnet.BasicBlock,
                          num_classes=4, width=8, cifar_stem=True)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 8, 8, 3)).astype(np.float32)
    y = rng.integers(0, 4, size=(16,)).astype(np.int32)
    variables = model.init(jax.random.key(0), jnp.asarray(x[:2]))
    tx = optax.sgd(0.1, momentum=0.9)

    def loss_fn(params, model_state, batch, rng):
        logits, mut = model.apply({"params": params, **model_state},
                                  batch["x"], train=True,
                                  mutable=["batch_stats"])
        return losses.softmax_cross_entropy(logits, batch["y"]), (
            dict(mut), {})

    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False,
                                    weight_update=weight_update)
    if weight_update == "zero1":
        state = zero1.make_state(
            variables["params"], tx, mesh,
            model_state={"batch_stats": variables["batch_stats"]})
    else:
        state = step_lib.TrainState.create(
            variables["params"], tx,
            model_state={"batch_stats": variables["batch_stats"]})
        state = step_lib.replicate_state(state, mesh)
    batch = jax.tree.map(
        lambda a: jax.device_put(a, mesh_lib.batch_sharding(mesh)),
        {"x": x, "y": y})
    out = []
    for _ in range(n_steps):
        state, m = step(state, batch)
        out.append(float(m["loss"]))
    return out, state


def _lm_run(mesh, weight_update, n_steps=N_GOLDEN_STEPS):
    """Tiny TransformerLM under adamw — the second optimizer family
    (adam moments, not just sgd momentum) and the dict-batch LM path."""
    from tpuframe import models

    model = models.get_model("transformer-lm", tiny=True, vocab_size=64,
                             max_seq=32)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 64, size=(8, 32)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    variables = model.init(jax.random.key(0), jnp.asarray(ids[:2]))
    tx = optax.adamw(1e-3)

    def loss_fn(params, model_state, batch, rng):
        logits = model.apply({"params": params}, batch["input_ids"],
                             rngs={"dropout": rng})
        return losses.softmax_cross_entropy(logits, batch["labels"]), (
            model_state, {})

    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False,
                                    weight_update=weight_update)
    if weight_update == "zero1":
        state = zero1.make_state(variables["params"], tx, mesh)
    else:
        state = step_lib.TrainState.create(variables["params"], tx)
        state = step_lib.replicate_state(state, mesh)
    batch = jax.tree.map(
        lambda a: jax.device_put(a, mesh_lib.batch_sharding(mesh)),
        {"input_ids": ids, "labels": labels})
    out = []
    for _ in range(n_steps):
        state, m = step(state, batch)
        out.append(float(m["loss"]))
    return out, state


@pytest.mark.parametrize("runner", [_resnet_run, _lm_run],
                         ids=["resnet-sgd-momentum", "lm-adamw"])
def test_golden_loss_equivalence(mesh8, runner):
    golden, gstate = runner(mesh8, "replicated")
    got, zstate = runner(mesh8, "zero1")
    np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)
    assert golden[-1] < golden[0], "training should make progress"
    # final params match too — the trajectories are identical, not
    # merely loss-similar
    for a, b in zip(jax.tree.leaves(zstate.params),
                    jax.tree.leaves(gstate.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


# ----------------------------------------------------------------------
# the wire-level proof: dp-zero1 strategy audit
# ----------------------------------------------------------------------

class TestAudit:
    def test_dp_zero1_registered(self):
        assert "dp-zero1" in strategies.STRATEGIES
        b = budgets_lib.strategy_budget("dp-zero1",
                                        padded_param_bytes=4096)
        assert b.allowed == {"reduce-scatter": 4096, "all-gather": 4096}

    def test_collective_swap_is_exact(self):
        audit = strategies.audit_strategy("dp-zero1")
        if audit.status == "unavailable":
            pytest.skip(audit.reason)
        assert audit.status == "ok", str(audit.violations)
        kinds = audit.report.bytes_by_kind()
        budget = audit.budget
        # grads in / params out at EXACTLY the pad-to-multiple total
        assert kinds.get("reduce-scatter") == \
            budget.allowed["reduce-scatter"]
        assert kinds.get("all-gather") == budget.allowed["all-gather"]
        # the defect class itself: any gradient all-reduce above the
        # scalar floor means the swap did not happen
        assert audit.report.bytes_by_kind(
            min_bytes=budget.ignore_below).get("all-reduce", 0) == 0
        # and the checked-in auto-derived budget IS this program's
        # record — no hand-copied byte constants to fall out of date
        # (python -m tpuframe.analysis --emit-budgets regenerates it)
        from tpuframe.analysis import shardflow

        derived_file = shardflow.load_derived()
        assert derived_file is not None
        if derived_file["jax"] == jax.__version__:
            assert shardflow.derive_budget(
                audit.report, budget.ignore_below) == \
                shardflow.derived_for("dp-zero1")

    def test_budget_is_exact_padded_bytes(self):
        b = budgets_lib.zero1_budget(1000)
        assert b.allowed == {"reduce-scatter": 1000, "all-gather": 1000}
        assert b.ignore_below == 1024


# ----------------------------------------------------------------------
# resolution precedence: env > tune DB (generation-gated) > default
# ----------------------------------------------------------------------

class TestResolution:
    @pytest.fixture(autouse=True)
    def clean_env(self, monkeypatch):
        monkeypatch.delenv(zero1.ENV_VAR, raising=False)
        monkeypatch.delenv("TPUFRAME_TUNE_GEN", raising=False)
        monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
        monkeypatch.setenv("TPUFRAME_TUNE_DB", "off")

    @pytest.fixture
    def seeded_db(self, tmp_path, monkeypatch):
        path = str(tmp_path / "tune_db.json")
        db = tune_db.TuningDB(path)
        db.add({"program": "train_resnet50_b512",
                "family": "weight_update_resnet50",
                "fingerprint": "fp0", "topology": "v5e:2x2",
                "generation": "v5e",
                "config": {"weight_update": "zero1", "batch": 512},
                "predicted": {"predicted_ms": 5.0, "bound": "hbm",
                              "fits": True, "vmem_bytes": 0,
                              "bytes_lower_bound": True}})
        db.save()
        monkeypatch.setenv("TPUFRAME_TUNE_DB", path)
        return db

    def test_default_is_replicated(self):
        assert zero1.resolve() == ("replicated", "default")

    def test_env_override_wins(self, monkeypatch, seeded_db):
        monkeypatch.setenv(zero1.ENV_VAR, "zero1")
        assert zero1.resolve(program="anything") == ("zero1", "env")
        monkeypatch.setenv(zero1.ENV_VAR, "replicated")
        assert zero1.resolve(program="train_resnet50_b512") == \
            ("replicated", "env")

    def test_env_bogus_mode_raises(self, monkeypatch):
        monkeypatch.setenv(zero1.ENV_VAR, "zero2")
        with pytest.raises(ValueError, match="unknown weight-update mode"):
            zero1.resolve()

    def test_db_winner_engages_with_generation(self, seeded_db,
                                               monkeypatch):
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        assert zero1.resolve(program="train_resnet50_b512") == \
            ("zero1", "tune_db")
        # family fallback for a program the sweep never compiled verbatim
        assert zero1.resolve(program="train_resnet50_b1024",
                             family="weight_update_resnet50") == \
            ("zero1", "tune_db")

    def test_no_generation_means_default(self, seeded_db):
        # the tier-1 guarantee: CPU runs never see DB layout decisions
        assert zero1.resolve(program="train_resnet50_b512") == \
            ("replicated", "default")

    def test_stale_db_mode_falls_back(self, tmp_path, monkeypatch):
        path = str(tmp_path / "tune_db.json")
        db = tune_db.TuningDB(path)
        db.add({"program": "train_resnet50_b512",
                "family": "weight_update_resnet50",
                "fingerprint": "fp0", "topology": "v5e:2x2",
                "generation": "v5e",
                "config": {"weight_update": "zero9"},
                "predicted": {"predicted_ms": 5.0, "bound": "hbm",
                              "fits": True, "vmem_bytes": 0,
                              "bytes_lower_bound": True}})
        db.save()
        monkeypatch.setenv("TPUFRAME_TUNE_DB", path)
        monkeypatch.setenv("TPUFRAME_TUNE_GEN", "v5e")
        # a stale/bogus DB row must never break a run
        assert zero1.resolve(program="train_resnet50_b512") == \
            ("replicated", "default")

    def test_validate_mode(self):
        assert zero1.validate_mode("ZERO1") == "zero1"
        assert zero1.validate_mode("") == "replicated"
        with pytest.raises(ValueError, match="TPUFRAME_WEIGHT_UPDATE"):
            zero1.validate_mode("fsdp")


# ----------------------------------------------------------------------
# step-builder guard rails
# ----------------------------------------------------------------------

class TestStepGuards:
    def _loss(self, params, model_state, batch, rng):
        return jnp.sum(params["w"] * batch["x"]), (model_state, {})

    def test_zero1_requires_mesh(self):
        with pytest.raises(ValueError, match="needs a mesh"):
            step_lib.make_train_step(self._loss, optax.sgd(0.1), None,
                                     weight_update="zero1")

    def test_zero1_rejects_adasum(self, mesh8):
        with pytest.raises(ValueError, match="zero1"):
            step_lib.make_train_step(self._loss, optax.sgd(0.1), mesh8,
                                     grad_reduce="adasum",
                                     weight_update="zero1")

    def test_unknown_mode_rejected(self, mesh8):
        with pytest.raises(ValueError, match="unknown weight_update"):
            step_lib.make_train_step(self._loss, optax.sgd(0.1), mesh8,
                                     weight_update="zero3")


# ----------------------------------------------------------------------
# TF110: optimizer updates stay at the weight-update seam
# ----------------------------------------------------------------------

def _lint_file(tmp_path, rel, src):
    f = tmp_path / rel
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(src)
    return [x for x in source_lint.lint_paths([f]) if x.rule == "TF110"]


_STRAY_UPDATE = """
def step(tx, grads, opt_state, params):
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state
"""


class TestTF110:
    def test_fires_in_parallel_scope(self, tmp_path):
        found = _lint_file(tmp_path, "parallel/rogue.py", _STRAY_UPDATE)
        assert len(found) == 2
        assert all(f.rule == "TF110" for f in found)

    def test_fires_in_train_py(self, tmp_path):
        assert _lint_file(tmp_path, "train.py", _STRAY_UPDATE)

    def test_silent_outside_scope(self, tmp_path):
        assert _lint_file(tmp_path, "models/rogue.py", _STRAY_UPDATE) == []

    def test_seam_files_exempt(self, tmp_path):
        assert _lint_file(tmp_path, "parallel/step.py", _STRAY_UPDATE) == []
        assert _lint_file(tmp_path, "parallel/zero1.py",
                          _STRAY_UPDATE) == []

    def test_dict_update_not_flagged(self, tmp_path):
        src = "def f(d, cfg):\n    d.update(cfg, x=1)\n    return d\n"
        assert _lint_file(tmp_path, "parallel/cfgs.py", src) == []

    def test_suppression_honored(self, tmp_path):
        src = _STRAY_UPDATE.replace(
            "tx.update(grads, opt_state, params)",
            "tx.update(grads, opt_state, params)  # tf-lint: ok[TF110]"
        ).replace(
            "optax.apply_updates(params, updates)",
            "optax.apply_updates(params, updates)  # tf-lint: ok[TF110]")
        assert _lint_file(tmp_path, "parallel/rogue.py", src) == []

    def test_shipped_seam_files_clean(self):
        assert zero1.check() == []


# ----------------------------------------------------------------------
# observability: the weight_update run event
# ----------------------------------------------------------------------

class TestWeightUpdateEvent:
    def test_schema_registered(self):
        assert events.REQUIRED_FIELDS["weight_update"] == ("mode", "source")

    def test_emitted_record_validates(self, tmp_path):
        with events.EventLog(str(tmp_path)) as log:
            rec = log.emit("weight_update", mode="zero1", source="env",
                           n_shards=8)
        assert rec is not None
        assert events.validate_record(rec) == []
        (path,) = events.event_files(str(tmp_path))
        (read,) = events.read_file(path)
        assert read["mode"] == "zero1" and read["n_shards"] == 8
