"""Analysis v2+v3: collective-flow graph parser + structural detectors
+ the schedule/liveness plane.

Everything here runs without compiling anything: the golden fixtures
under ``tests/fixtures/hlo/`` are real optimized-HLO modules compiled
once on an 8-device CPU mesh (regenerate with
``tests/fixtures/regen_hlo.py``), and the seeded positives are
hand-written HLO snippets each detector must flag — every detector is
proven against both a known-bad program and every known-clean
strategy program.  The schedule plane (async start/done pairing,
overlap windows, liveness peaks) is additionally proven on seeded
*async* HLO, because CPU-compiled fixtures contain only sync
collectives.  The fused strategies sign ``declared_overlapped`` and so
run the exposed-comm detector as a LIVE gate, not report-only.
"""

import gzip
import json
import os
import types

import pytest

from tpuframe.analysis import hlo_audit, shardflow
from tpuframe.analysis import collective_graph as cg
from tpuframe.analysis.collective_graph import parse_graph

FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "fixtures", "hlo")

with open(os.path.join(FIXDIR, "goldens.json")) as _f:
    GOLDENS = json.load(_f)


def _fixture_text(name: str) -> str:
    entry = GOLDENS["strategies"][name]
    with gzip.open(os.path.join(FIXDIR, entry["file"]), "rt") as f:
        return f.read()


def _fake_audit(txt: str, *, name="seeded", ignore_below=0, meta=None):
    """The duck-typed slice of StrategyAudit the shardflow APIs read."""
    return types.SimpleNamespace(
        name=name, status="ok", reason="", violations=[],
        report=hlo_audit.parse_collectives(txt),
        budget=types.SimpleNamespace(ignore_below=ignore_below),
        compiled=types.SimpleNamespace(as_text=lambda: txt),
        meta=meta)


# ---------------------------------------------------------------------------
# Golden fixtures: parser shape pins + detectors clean on real programs.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", sorted(GOLDENS["strategies"]))
def test_golden_graph_shape(name):
    """Same fixture text => same parsed shape.  A parser change that
    drops computations/nodes/collectives fails here before it silently
    blinds the detectors."""
    graph = parse_graph(_fixture_text(name))
    assert graph.summary() == GOLDENS["strategies"][name]["summary"]
    assert graph.entry_computation is not None


@pytest.mark.parametrize("name", sorted(GOLDENS["strategies"]))
def test_golden_fixtures_pass_detectors(name):
    """Every registered strategy's real compiled program is clean under
    every structural detector (the acceptance criterion's clean half)."""
    entry = GOLDENS["strategies"][name]
    txt = _fixture_text(name)
    graph = parse_graph(txt)
    assert shardflow.detect_redundant_pairs(graph) == []
    assert shardflow.detect_wire_dtype(graph, entry["wire_dtype"]) == []
    assert shardflow.detect_replica_groups(
        graph, dict(tuple(p) for p in entry["mesh_shape"])) == []
    assert shardflow.census_cross_check(
        graph, hlo_audit.parse_collectives(txt)) == []


def test_goldens_match_checked_in_derived_budgets():
    """The fixtures, the derived-budget declarations, and the live gate
    all describe the same seven programs."""
    derived = shardflow.load_derived()
    assert derived is not None
    assert set(GOLDENS["strategies"]) == set(derived["strategies"])
    for name in GOLDENS["strategies"]:
        report = hlo_audit.parse_collectives(_fixture_text(name))
        decl = derived["strategies"][name]
        fresh = shardflow.derive_budget(report, decl["ignore_below"])
        assert fresh == decl, name


# ---------------------------------------------------------------------------
# Seeded positives: one known-bad program per detector.
# ---------------------------------------------------------------------------

_ADD = """\
%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}

%max (c: f32[], d: f32[]) -> f32[] {
  %c = f32[] parameter(0)
  %d = f32[] parameter(1)
  ROOT %m = f32[] maximum(%c, %d)
}
"""

_GROUPS8 = "replica_groups={{0,1,2,3,4,5,6,7}}"


def _module(entry_body: str) -> str:
    return (f"HloModule seeded\n\n{_ADD}\n"
            f"ENTRY %main (p0: f32[1024]) -> f32[1024] {{\n"
            f"{entry_body}\n}}\n")


def test_seeded_redundant_ag_rs_pair():
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  %ag = f32[8192] all-gather(%p0), {_GROUPS8}, dimensions={{0}}\n"
        f"  %cp = f32[8192] copy(%ag)\n"
        f"  ROOT %rs = f32[1024] reduce-scatter(%cp), {_GROUPS8}, "
        f"to_apply=%add")
    findings = shardflow.detect_redundant_pairs(parse_graph(txt))
    assert len(findings) == 1
    assert "redundant pair" in findings[0]
    # the def-use chase went through the copy to the all-gather
    assert "%ag" in findings[0] and "%rs" in findings[0]


def test_seeded_redundant_pair_needs_same_groups():
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  %ag = f32[8192] all-gather(%p0), {_GROUPS8}, dimensions={{0}}\n"
        f"  ROOT %rs = f32[1024] reduce-scatter(%ag), "
        f"replica_groups={{{{0,1,2,3}},{{4,5,6,7}}}}, to_apply=%add")
    assert shardflow.detect_redundant_pairs(parse_graph(txt)) == []


def test_seeded_duplicate_all_reduce():
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  %ar1 = f32[1024] all-reduce(%p0), {_GROUPS8}, to_apply=%add\n"
        f"  %ar2 = f32[1024] all-reduce(%p0), {_GROUPS8}, to_apply=%add\n"
        f"  ROOT %o = f32[1024] add(%ar1, %ar2)")
    findings = shardflow.detect_redundant_pairs(parse_graph(txt))
    assert len(findings) == 1
    assert "duplicate all-reduce" in findings[0]
    assert "%ar1" in findings[0] and "%ar2" in findings[0]


def test_seeded_duplicate_ar_distinct_reduce_fns_clean():
    """A sum- and a max-reduction of one def are NOT duplicates."""
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  %ar1 = f32[1024] all-reduce(%p0), {_GROUPS8}, to_apply=%add\n"
        f"  %ar2 = f32[1024] all-reduce(%p0), {_GROUPS8}, to_apply=%max\n"
        f"  ROOT %o = f32[1024] add(%ar1, %ar2)")
    assert shardflow.detect_redundant_pairs(parse_graph(txt)) == []


def test_seeded_wire_dtype_violation():
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  ROOT %ar = f32[1024] all-reduce(%p0), {_GROUPS8}, "
        f"to_apply=%add")
    findings = shardflow.detect_wire_dtype(parse_graph(txt), "bf16")
    assert len(findings) == 1
    assert "carries f32" in findings[0]
    # ...but an f32 wire declaration, or a byte floor above the payload,
    # accepts the same program.
    assert shardflow.detect_wire_dtype(parse_graph(txt), "f32") == []
    assert shardflow.detect_wire_dtype(parse_graph(txt), "bf16",
                                       ignore_below=1 << 20) == []


def test_wire_format_allowlist_seam():
    """A registered quantized wire format exempts its dtype set — the
    EQuARX registration point."""
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  ROOT %ar = f32[1024] all-reduce(%p0), {_GROUPS8}, "
        f"to_apply=%add")
    graph = parse_graph(txt)
    assert shardflow.detect_wire_dtype(graph, "bf16") != []
    shardflow.register_wire_format("test-blockwise", {"f32", "u8"})
    try:
        assert "test-blockwise" in shardflow.registered_wire_formats()
        assert shardflow.detect_wire_dtype(graph, "bf16") == []
    finally:
        shardflow._WIRE_FORMATS.pop("test-blockwise")


def test_int8_block_wire_format_registered_at_import():
    """quantwire's shipped format is registered by the module itself —
    the gate sees s8 collectives as the declared wire, not a violation,
    without any per-run setup."""
    assert shardflow.registered_wire_formats().get("int8-block") \
        == frozenset({"s8"})


def test_seeded_wire_positive_guards_the_gate():
    """check() must run the seeded wire-dtype positive first: a format
    registration broad enough to exempt f32 traffic blinds the detector,
    and the gate has to refuse to run blind."""
    assert shardflow.seeded_wire_positive() == []
    shardflow.register_wire_format("test-blind", {"s8", "f32"})
    try:
        probs = shardflow.seeded_wire_positive()
        assert probs and "exempting" in probs[0]
        # the gate entry point surfaces it even with no audits to run
        assert any("exempting" in p for p in shardflow.check([]))
    finally:
        shardflow._WIRE_FORMATS.pop("test-blind")
    assert shardflow.seeded_wire_positive() == []


def test_seeded_accidental_replication():
    txt = ("HloModule seeded\n\n"
           "ENTRY %main (p0: f32[1024,64]) -> f32[1024,64] {\n"
           "  %p0 = f32[1024,64] parameter(0)\n"
           "  ROOT %c = f32[1024,64] copy(%p0)\n}\n")
    declared = (("f32", (1024, 64), (128, 64)),)
    findings = shardflow.detect_replication(parse_graph(txt), declared)
    assert len(findings) == 1
    assert "accidental replication" in findings[0]
    # sharded as declared -> clean; tiny leaves stay under the floor
    sharded = ("HloModule ok\n\n"
               "ENTRY %main (p0: f32[128,64]) -> f32[128,64] {\n"
               "  %p0 = f32[128,64] parameter(0)\n"
               "  ROOT %c = f32[128,64] copy(%p0)\n}\n")
    assert shardflow.detect_replication(parse_graph(sharded),
                                        declared) == []
    assert shardflow.detect_replication(
        parse_graph(txt), declared, floor=1 << 30) == []


def test_seeded_replica_group_violations():
    mesh = {"data": 8}

    def groups_of(attr):
        txt = _module(
            f"  %p0 = f32[1024] parameter(0)\n"
            f"  ROOT %ar = f32[1024] all-reduce(%p0), "
            f"replica_groups={attr}, to_apply=%add")
        return shardflow.detect_replica_groups(parse_graph(txt), mesh)

    assert groups_of("{{0,1,2,3,4,5,6,7}}") == []
    unequal = groups_of("{{0,1,2},{3,4},{5,6,7}}")
    assert len(unequal) == 1 and "unequal group sizes" in unequal[0]
    overlap = groups_of("{{0,1},{1,2},{3,4},{5,6}}")
    assert len(overlap) == 1 and "overlap" in overlap[0]
    partial = groups_of("{{0,1},{2,3}}")
    assert len(partial) == 1 and "cover" in partial[0]


def test_seeded_replica_group_size_not_axis_product():
    # 12-device a×b mesh: size-2 groups partition the devices but no
    # combination of the declared axes (4, 3) explains a 2-wide group.
    mesh = {"a": 4, "b": 3}
    groups = "{" + ",".join(
        f"{{{2 * i},{2 * i + 1}}}" for i in range(6)) + "}"
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  ROOT %ar = f32[1024] all-reduce(%p0), "
        f"replica_groups={groups}, to_apply=%add")
    findings = shardflow.detect_replica_groups(parse_graph(txt), mesh)
    assert len(findings) == 1
    assert "not a product of declared mesh axes" in findings[0]


def test_seeded_replica_group_iota_forms():
    mesh = {"data": 8}

    def iota_of(count, size):
        txt = _module(
            f"  %p0 = f32[1024] parameter(0)\n"
            f"  ROOT %ar = f32[1024] all-reduce(%p0), "
            f"replica_groups=[{count},{size}]<=[8], to_apply=%add")
        return shardflow.detect_replica_groups(parse_graph(txt), mesh)

    assert iota_of(1, 8) == []
    short = iota_of(2, 2)                 # covers 4 of 8 devices
    assert len(short) == 1 and "do not cover" in short[0]
    odd = iota_of(4, 2)                   # covers, but 2 not in {1, 8}
    assert len(odd) == 1 and "not a product" in odd[0]


def test_seeded_collective_permute_pairs():
    mesh = {"data": 8}

    def permute_of(pairs):
        txt = _module(
            f"  %p0 = f32[1024] parameter(0)\n"
            f"  ROOT %cp = f32[1024] collective-permute(%p0), "
            f"source_target_pairs={pairs}")
        return shardflow.detect_replica_groups(parse_graph(txt), mesh)

    assert permute_of("{{0,1},{1,2},{2,3}}") == []
    dup = permute_of("{{0,1},{0,2}}")
    assert len(dup) == 1 and "duplicate" in dup[0]
    out = permute_of("{{0,9}}")
    assert len(out) == 1 and "outside the declared" in out[0]


def test_census_cross_check_mismatch():
    """Feed the census a report for a DIFFERENT program — the cross
    check must notice the two parsers disagree."""
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  ROOT %ar = f32[1024] all-reduce(%p0), {_GROUPS8}, "
        f"to_apply=%add")
    other = _module("  ROOT %p0 = f32[1024] parameter(0)")
    graph = parse_graph(txt)
    assert shardflow.census_cross_check(
        graph, hlo_audit.parse_collectives(txt)) == []
    findings = shardflow.census_cross_check(
        graph, hlo_audit.parse_collectives(other))
    assert len(findings) == 1 and "census mismatch" in findings[0]


# ---------------------------------------------------------------------------
# Derived budgets: drift in either direction fails; version skew skips.
# ---------------------------------------------------------------------------

_AR_TXT = None  # built once below


def _ar_audit():
    global _AR_TXT
    if _AR_TXT is None:
        _AR_TXT = _module(
            f"  %p0 = f32[1024] parameter(0)\n"
            f"  ROOT %ar = f32[1024] all-reduce(%p0), {_GROUPS8}, "
            f"to_apply=%add")
    return _fake_audit(_AR_TXT)


def _derived_file_for(audit) -> dict:
    return {
        "schema": shardflow.REPORT_SCHEMA,
        "jax": shardflow._jax_version(),
        "n_devices": 8,
        "strategies": {audit.name: shardflow.derive_budget(
            audit.report, audit.budget.ignore_below)},
    }


def test_budget_drift_clean_and_both_directions():
    audit = _ar_audit()
    derived = _derived_file_for(audit)
    assert shardflow.budget_drift(audit, derived) == []
    # declaration drifts above the program -> finding
    high = json.loads(json.dumps(derived))
    high["strategies"][audit.name]["kinds"]["all-reduce"]["bytes"] += 4
    assert any("drift on all-reduce" in p
               for p in shardflow.budget_drift(audit, high))
    # declaration misses a kind the program has -> finding too
    gone = json.loads(json.dumps(derived))
    del gone["strategies"][audit.name]["kinds"]["all-reduce"]
    assert any("drift on all-reduce" in p
               for p in shardflow.budget_drift(audit, gone))


def test_budget_drift_missing_entry_and_version_skew():
    audit = _ar_audit()
    derived = _derived_file_for(audit)
    nobody = json.loads(json.dumps(derived))
    nobody["strategies"] = {}
    assert any("no entry" in p
               for p in shardflow.budget_drift(audit, nobody))
    skew = json.loads(json.dumps(derived))
    skew["jax"] = "0.0.0-not-this-one"
    assert shardflow.budget_drift(audit, skew) == []
    assert shardflow.budget_drift(audit, None) != []


def test_derived_for_every_fixture_strategy():
    for name in GOLDENS["strategies"]:
        entry = shardflow.derived_for(name)
        assert entry is not None, name
        assert set(entry) == {"ignore_below", "kinds", "above_floor",
                              "total_bytes"}
        assert entry["total_bytes"] > 0


# ---------------------------------------------------------------------------
# The --json report schema + the compare contract (rc 0/1/2).
# ---------------------------------------------------------------------------

_TOP_KEYS = {"schema", "jax", "n_devices", "lint", "strategies"}
_STRATEGY_KEYS = {"name", "status", "reason", "violations", "collectives",
                  "total_bytes", "derived", "drift", "detectors", "graph",
                  "schedule", "schedule_drift", "overlap", "comm_split"}
_COMM_SPLIT_KEYS = {"slices", "ici", "dcn", "ici_bytes", "dcn_bytes",
                    "unattributed", "t_ici_ms", "t_dcn_ms", "generation"}
_DETECTOR_KEYS = {"redundant_pair", "wire_dtype", "replication",
                  "replica_groups", "census", "exposed_comm"}
_SCHEDULE_KEYS = {"ignore_below", "peak_live_bytes", "undonated_doubles",
                  "collectives", "async_pairs", "exposed_above_floor",
                  "interleavable_bytes"}
_OVERLAP_KEYS = {"generation", "comm_ms", "interleavable_ms",
                 "hideable_ms", "overlap_potential", "exposed",
                 "collectives_above_floor"}


def _schedule_file_for(audit) -> dict:
    graph = parse_graph(audit.compiled.as_text())
    return {
        "schema": shardflow.REPORT_SCHEMA,
        "jax": shardflow._jax_version(),
        "n_devices": 8,
        "strategies": {audit.name: shardflow.derive_schedule_entry(
            graph, ignore_below=audit.budget.ignore_below)},
    }


def _build_one_report(tmp_path, *, name="seeded"):
    audit = _fake_audit(_ar_audit().compiled.as_text(), name=name)
    derived_path = tmp_path / f"derived_{name}.json"
    derived_path.write_text(json.dumps(_derived_file_for(audit)))
    schedule_path = tmp_path / f"schedule_{name}.json"
    schedule_path.write_text(json.dumps(_schedule_file_for(audit)))
    finding = types.SimpleNamespace(rule="TF999", path="x.py", line=3,
                                    message="demo")
    return shardflow.build_report([audit], lint_findings=[finding],
                                  n_devices=8,
                                  derived_path=str(derived_path),
                                  schedule_path=str(schedule_path))


def test_report_schema_pinned(tmp_path):
    """The --json report shape is an API: obs-compare-style tooling
    parses it, so key changes must be deliberate (bump REPORT_SCHEMA)."""
    report = _build_one_report(tmp_path)
    assert set(report) == _TOP_KEYS
    assert report["schema"] == shardflow.REPORT_SCHEMA == 3
    assert report["lint"] == [{"rule": "TF999", "path": "x.py",
                               "line": 3, "message": "demo"}]
    (entry,) = report["strategies"]
    assert set(entry) == _STRATEGY_KEYS
    assert _STRATEGY_KEYS == set(shardflow.STRATEGY_REPORT_KEYS)
    assert set(entry["detectors"]) == _DETECTOR_KEYS
    assert set(entry["derived"]) == {"ignore_below", "kinds",
                                     "above_floor", "total_bytes"}
    assert set(entry["graph"]) == {"computations", "nodes",
                                   "entry_parameters",
                                   "collectives_by_kind"}
    assert set(entry["schedule"]) == _SCHEDULE_KEYS
    assert set(entry["overlap"]) == _OVERLAP_KEYS
    assert set(entry["comm_split"]) == _COMM_SPLIT_KEYS
    assert entry["drift"] == []
    assert entry["schedule_drift"] == []
    json.dumps(report)  # must be serializable as-is


def test_compare_reports_contract(tmp_path):
    base = _build_one_report(tmp_path)
    # identical reports: rc 0, one "ok" line per strategy
    rc, lines = shardflow.compare_reports(base, base)
    assert rc == 0 and any(ln.startswith("ok seeded") for ln in lines)
    # op-count change: rc 1 with a REGRESSION line
    worse = json.loads(json.dumps(base))
    worse["strategies"][0]["derived"]["kinds"]["all-reduce"]["count"] += 1
    rc, lines = shardflow.compare_reports(base, worse)
    assert rc == 1 and any("op count" in ln for ln in lines)
    # kind disappearing: rc 1
    gone = json.loads(json.dumps(base))
    del gone["strategies"][0]["derived"]["kinds"]["all-reduce"]
    rc, _ = shardflow.compare_reports(base, gone)
    assert rc == 1
    # byte move beyond tolerance: rc 1; within tolerance: rc 0
    fat = json.loads(json.dumps(base))
    kinds = fat["strategies"][0]["derived"]["kinds"]["all-reduce"]
    kinds["bytes"] = int(kinds["bytes"] * 1.5)
    rc, _ = shardflow.compare_reports(base, fat)
    assert rc == 1
    rc, _ = shardflow.compare_reports(base, fat, bytes_tol=0.6)
    assert rc == 0
    # a detector going from clean to firing: rc 1
    noisy = json.loads(json.dumps(base))
    noisy["strategies"][0]["detectors"]["wire_dtype"] = ["boom"]
    rc, lines = shardflow.compare_reports(base, noisy)
    assert rc == 1 and any("detector wire_dtype" in ln for ln in lines)
    # disjoint strategy sets: rc 2
    other = _build_one_report(tmp_path, name="different")
    rc, _ = shardflow.compare_reports(base, other)
    assert rc == 2


# ---------------------------------------------------------------------------
# Analysis v3: async pairing, overlap windows, liveness, schedule drift.
# ---------------------------------------------------------------------------

# A scheduled async module: the start->done pair is threaded through a
# copy AND a get-tuple-element (the chase the satellite fix targets),
# with an independent fusion scheduled inside the window.
_ASYNC_CHASED = """\
HloModule seeded_async, is_scheduled=true

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}

ENTRY %main (p0: f32[1024], p1: f32[1024]) -> (f32[1024], f32[1024]) {
  %p0 = f32[1024]{0} parameter(0)
  %p1 = f32[1024]{0} parameter(1)
  %ags = f32[8192]{0} all-gather-start(f32[1024]{0} %p0), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %fus = f32[1024]{0} fusion(f32[1024]{0} %p1), kind=kLoop, calls=%add
  %cp = f32[8192]{0} copy(f32[8192]{0} %ags)
  %gte = f32[8192]{0} get-tuple-element(f32[8192]{0} %cp), index=0
  %agd = f32[8192]{0} all-gather-done(f32[8192]{0} %gte)
  %sl = f32[1024]{0} bitcast(f32[8192]{0} %agd)
  ROOT %out = (f32[1024]{0}, f32[1024]{0}) tuple(%sl, %fus)
}
"""


def test_async_pairing_chases_through_plumbing():
    """A -done reached only through copy/get-tuple-element chains still
    pairs with its -start (today's real schedulers thread exactly such
    plumbing between the two)."""
    comp = parse_graph(_ASYNC_CHASED).entry_computation
    pairs, problems = comp.pair_async()
    assert pairs == {"ags": "agd"}
    assert problems == []


def test_unpaired_async_start_fails_loudly():
    """Deleting the -done must produce a pairing problem — surfaced by
    the exposed-comm detector regardless of the overlap declaration."""
    torn = _ASYNC_CHASED.replace(
        "  %agd = f32[8192]{0} all-gather-done(f32[8192]{0} %gte)\n", ""
    ).replace("%sl = f32[1024]{0} bitcast(f32[8192]{0} %agd)",
              "%sl = f32[1024]{0} bitcast(f32[8192]{0} %gte)")
    graph = parse_graph(torn)
    _, problems = graph.entry_computation.pair_async()
    assert len(problems) == 1 and "unpaired async start" in problems[0]
    # the detector surfaces it even on an undeclared strategy
    assert any("unpaired async start" in f
               for f in shardflow.detect_exposed_comm(graph, False))


def test_overlap_window_contents_and_interleavable_set():
    comp = parse_graph(_ASYNC_CHASED).entry_computation
    view = cg.schedule_view(comp)
    (w,) = view.windows
    assert w.is_async and w.kind == "all-gather"
    assert w.done_name == "agd" and w.window_len == 4
    # the fusion is scheduled inside the window -> actually overlapped
    assert w.overlapped_compute == 1 and not w.exposed
    # ...and it is also the only compute op independent of the collective
    assert w.interleavable_compute == 1
    assert w.interleavable_bytes == 4096


def test_seeded_zero_overlap_positive():
    """The acceptance criterion's seeded zero-overlap HLO: flagged under
    a declared-overlapped strategy, report-only otherwise, and the gate
    refuses to run blind (seeded_schedule_positive is wired into
    check())."""
    graph = parse_graph(shardflow._SEEDED_EXPOSED_HLO)
    found = shardflow.detect_exposed_comm(graph, True)
    assert len(found) == 1 and "back-to-back" in found[0]
    assert shardflow.detect_exposed_comm(graph, False) == []
    # above a floor bigger than the payload, the declaration passes too
    assert shardflow.detect_exposed_comm(graph, True,
                                         ignore_below=1 << 20) == []
    assert shardflow.seeded_schedule_positive() == []
    # check() runs the seeded positives even with no audits at all
    monkey = shardflow._SEEDED_PEAK_BYTES
    try:
        shardflow._SEEDED_PEAK_BYTES = monkey + 1
        assert any("sweep is mis-measuring" in p
                   for p in shardflow.check([]))
    finally:
        shardflow._SEEDED_PEAK_BYTES = monkey


def test_liveness_peak_and_aliasing():
    """Hand-computable liveness: the sweep must count the async start's
    in-flight buffer and the escaping root, and alias ops own nothing."""
    graph = parse_graph(shardflow._SEEDED_EXPOSED_HLO)
    lv = cg.liveness(graph.entry_computation, graph.aliased_params)
    assert lv.peak_bytes == shardflow._SEEDED_PEAK_BYTES
    assert lv.total_defined_bytes > 0
    assert lv.undonated == ()


def test_liveness_undonated_doubling_flag():
    """An un-donated entry parameter whose exact shape recurs in the
    root output is the doubled-residency smell; donating it (the module
    header alias table) clears the flag."""
    body = """\
ENTRY %main (p0: f32[65536], p1: f32[16]) -> (f32[65536], f32[16]) {
  %p0 = f32[65536]{0} parameter(0)
  %p1 = f32[16]{0} parameter(1)
  %cp = f32[65536]{0} copy(f32[65536]{0} %p0)
  %cq = f32[16]{0} copy(f32[16]{0} %p1)
  ROOT %out = (f32[65536]{0}, f32[16]{0}) tuple(%cp, %cq)
}
"""
    undonated = parse_graph("HloModule m, is_scheduled=true\n\n" + body)
    lv = cg.liveness(undonated.entry_computation,
                     undonated.aliased_params, undonated_floor=1024)
    # p0 (256 KiB, shape-matches output 0) flags; p1 is under the floor
    assert lv.undonated == ("p0",)
    donated = parse_graph(
        "HloModule m, is_scheduled=true, input_output_alias={ {0}: (0, {},"
        " may-alias) }\n\n" + body)
    assert donated.aliased_params == frozenset({0})
    lv2 = cg.liveness(donated.entry_computation, donated.aliased_params,
                      undonated_floor=1024)
    assert lv2.undonated == ()


def test_seeded_liveness_drift_positive():
    """The acceptance criterion's seeded liveness drift: a tampered
    peak_live_bytes declaration must fail, version skew must skip, a
    missing entry/file must fail."""
    audit = _fake_audit(shardflow._SEEDED_EXPOSED_HLO, ignore_below=1024)
    sched = _schedule_file_for(audit)
    assert shardflow.schedule_drift(audit, sched) == []
    drifted = json.loads(json.dumps(sched))
    drifted["strategies"][audit.name]["peak_live_bytes"] += 4096
    probs = shardflow.schedule_drift(audit, drifted)
    assert len(probs) == 1 and "drift on peak_live_bytes" in probs[0]
    # drift the other direction fails identically
    lower = json.loads(json.dumps(sched))
    lower["strategies"][audit.name]["peak_live_bytes"] -= 4096
    assert shardflow.schedule_drift(audit, lower) != []
    # version skew: skip, not lie
    skew = json.loads(json.dumps(sched))
    skew["jax"] = "0.0.0-not-this-one"
    assert shardflow.schedule_drift(audit, skew) == []
    # missing entry / missing file: loud
    nobody = json.loads(json.dumps(sched))
    nobody["strategies"] = {}
    assert any("no entry" in p
               for p in shardflow.schedule_drift(audit, nobody))
    assert shardflow.schedule_drift(audit, None) != []


@pytest.mark.parametrize("name", sorted(GOLDENS["strategies"]))
def test_golden_fixtures_schedule_clean(name):
    """Every fixture passes the exposed-comm detector in report-only
    mode, and its async pairing has no problems."""
    graph = parse_graph(_fixture_text(name))
    assert shardflow.detect_exposed_comm(graph, False) == []
    for comp in graph.computations.values():
        _, problems = comp.pair_async()
        assert problems == []


_FUSED_FIXTURES = sorted(n for n in GOLDENS["strategies"] if "fused" in n)


@pytest.mark.parametrize("name", _FUSED_FIXTURES)
def test_fused_fixtures_pass_live_gate_with_interior_windows(name):
    """The fused strategies sign ``declared_overlapped=True``, which
    turns exposed-comm into a LIVE gate for them.  On the all-sync CPU
    fixture the declaration survives only because every gated window
    has legally interleavable interior compute — so assert both halves:
    the gate is clean AND the windows are provably non-empty.  A fusion
    regression that packs everything into one end-of-step bucket (no
    interior compute left) fails here."""
    entry = GOLDENS["strategies"][name]
    floor = entry["schedule"]["ignore_below"]
    graph = parse_graph(_fixture_text(name))
    assert shardflow.detect_exposed_comm(graph, True,
                                         ignore_below=floor) == []
    # nonzero-interior-window: the pinned schedule record agrees with a
    # fresh derivation, and both show real interleavable work.
    sched = entry["schedule"]
    assert sched["interleavable_bytes"] > 0, name
    assert sched["exposed_above_floor"] > 0, name  # sync CPU: exposed, hidden-able
    fresh = shardflow.derive_schedule_entry(graph, ignore_below=floor)
    assert fresh["interleavable_bytes"] == sched["interleavable_bytes"]
    # and at least one gated window individually carries interior compute
    windows = [w for comp in graph.computations.values()
               for w in cg.schedule_view(comp).windows
               if w.bytes >= floor]
    assert windows and all(w.interleavable_compute > 0 for w in windows)


def test_fused_fixture_set_is_complete():
    """Both signed strategies (dp and dp-zero1) regenerated into the
    goldens — a regen that silently drops one fails loudly here, not as
    a skipped parametrization."""
    assert _FUSED_FIXTURES == ["spec:dp=*+fused131072",
                               "spec:dp=*+zero1+fused131072"]


def test_fixtures_match_checked_in_derived_schedule():
    """The goldens' schedule records and derived_schedule.json are two
    spellings of one derivation — byte-equal, per strategy (the
    acceptance criterion's byte check)."""
    sched = shardflow.load_derived_schedule()
    assert sched is not None
    assert set(GOLDENS["strategies"]) == set(sched["strategies"])
    assert GOLDENS["jax"] == sched["jax"]
    for name, entry in GOLDENS["strategies"].items():
        assert entry["schedule"] == sched["strategies"][name], name
        # and both regenerate from the fixture text
        fresh = shardflow.derive_schedule_entry(
            parse_graph(_fixture_text(name)),
            ignore_below=entry["schedule"]["ignore_below"])
        assert fresh == entry["schedule"], name
        assert shardflow.schedule_for(name) == entry["schedule"]


def test_overlap_score_shape_and_bounds():
    for name in sorted(GOLDENS["strategies"]):
        graph = parse_graph(_fixture_text(name))
        report = hlo_audit.parse_collectives(_fixture_text(name))
        score = shardflow.overlap_score(
            graph, report, n_devices=8,
            ignore_below=GOLDENS["strategies"][name]["schedule"]
            ["ignore_below"])
        assert set(score) == _OVERLAP_KEYS
        assert 0.0 <= score["overlap_potential"] <= 1.0
        assert score["hideable_ms"] <= score["comm_ms"] + 1e-9
        # sync-only CPU programs: every above-floor collective exposed
        assert score["exposed"] == score["collectives_above_floor"]


def test_compare_schedule_section(tmp_path):
    """The 0/1/2 contract extended to the schedule plane: each metric
    regresses individually, and the section participates only when both
    reports carry it."""
    base = _build_one_report(tmp_path)
    # more exposed above-floor collectives: rc 1
    worse = json.loads(json.dumps(base))
    worse["strategies"][0]["schedule"]["exposed_above_floor"] += 1
    rc, lines = shardflow.compare_reports(base, worse)
    assert rc == 1 and any("exposed above-floor" in ln for ln in lines)
    # peak-live move beyond tolerance, either direction: rc 1
    for factor in (1.5, 0.5):
        fat = json.loads(json.dumps(base))
        sched = fat["strategies"][0]["schedule"]
        sched["peak_live_bytes"] = int(sched["peak_live_bytes"] * factor)
        rc, lines = shardflow.compare_reports(base, fat)
        assert rc == 1 and any("peak live bytes" in ln for ln in lines)
    # overlap-potential drop > 0.10: rc 1; a gain never regresses
    slow = json.loads(json.dumps(base))
    slow["strategies"][0]["overlap"]["overlap_potential"] -= 0.5
    rc, lines = shardflow.compare_reports(base, slow)
    assert rc == 1 and any("overlap potential" in ln for ln in lines)
    # schema-1 baseline without the schedule section still compares
    # clean on the structural metrics (participate-only-when-both)
    old = json.loads(json.dumps(base))
    for s in old["strategies"]:
        s.pop("schedule"), s.pop("overlap"), s.pop("schedule_drift")
    rc, _ = shardflow.compare_reports(old, worse)
    assert rc == 0
    rc, _ = shardflow.compare_reports(worse, old)
    assert rc == 0


def test_selfcheck_validates_golden_pair():
    """The checked-in docs/samples pair must keep the whole --compare
    contract alive, and the selfcheck must notice a broken pair."""
    assert shardflow.selfcheck() == []
    assert shardflow.selfcheck("/nonexistent-samples-dir") != []


def test_schedule_entry_is_integer_exact():
    """Every derived_schedule value is an int — the precondition for the
    byte-exact emit/regenerate contract."""
    sched = shardflow.load_derived_schedule()
    for name, entry in sched["strategies"].items():
        for key, value in entry.items():
            assert isinstance(value, int), (name, key, value)
