"""Analysis v2: collective-flow graph parser + structural detectors.

Everything here runs without compiling anything: the golden fixtures
under ``tests/fixtures/hlo/`` are real optimized-HLO modules compiled
once on an 8-device CPU mesh (regenerate with
``tests/fixtures/regen_hlo.py``), and the seeded positives are
hand-written HLO snippets each detector must flag — every detector is
proven against both a known-bad program and the seven known-clean
strategy programs.
"""

import gzip
import json
import os
import types

import pytest

from tpuframe.analysis import hlo_audit, shardflow
from tpuframe.analysis.collective_graph import parse_graph

FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "fixtures", "hlo")

with open(os.path.join(FIXDIR, "goldens.json")) as _f:
    GOLDENS = json.load(_f)


def _fixture_text(name: str) -> str:
    entry = GOLDENS["strategies"][name]
    with gzip.open(os.path.join(FIXDIR, entry["file"]), "rt") as f:
        return f.read()


def _fake_audit(txt: str, *, name="seeded", ignore_below=0, meta=None):
    """The duck-typed slice of StrategyAudit the shardflow APIs read."""
    return types.SimpleNamespace(
        name=name, status="ok", reason="", violations=[],
        report=hlo_audit.parse_collectives(txt),
        budget=types.SimpleNamespace(ignore_below=ignore_below),
        compiled=types.SimpleNamespace(as_text=lambda: txt),
        meta=meta)


# ---------------------------------------------------------------------------
# Golden fixtures: parser shape pins + detectors clean on real programs.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", sorted(GOLDENS["strategies"]))
def test_golden_graph_shape(name):
    """Same fixture text => same parsed shape.  A parser change that
    drops computations/nodes/collectives fails here before it silently
    blinds the detectors."""
    graph = parse_graph(_fixture_text(name))
    assert graph.summary() == GOLDENS["strategies"][name]["summary"]
    assert graph.entry_computation is not None


@pytest.mark.parametrize("name", sorted(GOLDENS["strategies"]))
def test_golden_fixtures_pass_detectors(name):
    """Every registered strategy's real compiled program is clean under
    every structural detector (the acceptance criterion's clean half)."""
    entry = GOLDENS["strategies"][name]
    txt = _fixture_text(name)
    graph = parse_graph(txt)
    assert shardflow.detect_redundant_pairs(graph) == []
    assert shardflow.detect_wire_dtype(graph, entry["wire_dtype"]) == []
    assert shardflow.detect_replica_groups(
        graph, dict(tuple(p) for p in entry["mesh_shape"])) == []
    assert shardflow.census_cross_check(
        graph, hlo_audit.parse_collectives(txt)) == []


def test_goldens_match_checked_in_derived_budgets():
    """The fixtures, the derived-budget declarations, and the live gate
    all describe the same seven programs."""
    derived = shardflow.load_derived()
    assert derived is not None
    assert set(GOLDENS["strategies"]) == set(derived["strategies"])
    for name in GOLDENS["strategies"]:
        report = hlo_audit.parse_collectives(_fixture_text(name))
        decl = derived["strategies"][name]
        fresh = shardflow.derive_budget(report, decl["ignore_below"])
        assert fresh == decl, name


# ---------------------------------------------------------------------------
# Seeded positives: one known-bad program per detector.
# ---------------------------------------------------------------------------

_ADD = """\
%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(%a, %b)
}

%max (c: f32[], d: f32[]) -> f32[] {
  %c = f32[] parameter(0)
  %d = f32[] parameter(1)
  ROOT %m = f32[] maximum(%c, %d)
}
"""

_GROUPS8 = "replica_groups={{0,1,2,3,4,5,6,7}}"


def _module(entry_body: str) -> str:
    return (f"HloModule seeded\n\n{_ADD}\n"
            f"ENTRY %main (p0: f32[1024]) -> f32[1024] {{\n"
            f"{entry_body}\n}}\n")


def test_seeded_redundant_ag_rs_pair():
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  %ag = f32[8192] all-gather(%p0), {_GROUPS8}, dimensions={{0}}\n"
        f"  %cp = f32[8192] copy(%ag)\n"
        f"  ROOT %rs = f32[1024] reduce-scatter(%cp), {_GROUPS8}, "
        f"to_apply=%add")
    findings = shardflow.detect_redundant_pairs(parse_graph(txt))
    assert len(findings) == 1
    assert "redundant pair" in findings[0]
    # the def-use chase went through the copy to the all-gather
    assert "%ag" in findings[0] and "%rs" in findings[0]


def test_seeded_redundant_pair_needs_same_groups():
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  %ag = f32[8192] all-gather(%p0), {_GROUPS8}, dimensions={{0}}\n"
        f"  ROOT %rs = f32[1024] reduce-scatter(%ag), "
        f"replica_groups={{{{0,1,2,3}},{{4,5,6,7}}}}, to_apply=%add")
    assert shardflow.detect_redundant_pairs(parse_graph(txt)) == []


def test_seeded_duplicate_all_reduce():
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  %ar1 = f32[1024] all-reduce(%p0), {_GROUPS8}, to_apply=%add\n"
        f"  %ar2 = f32[1024] all-reduce(%p0), {_GROUPS8}, to_apply=%add\n"
        f"  ROOT %o = f32[1024] add(%ar1, %ar2)")
    findings = shardflow.detect_redundant_pairs(parse_graph(txt))
    assert len(findings) == 1
    assert "duplicate all-reduce" in findings[0]
    assert "%ar1" in findings[0] and "%ar2" in findings[0]


def test_seeded_duplicate_ar_distinct_reduce_fns_clean():
    """A sum- and a max-reduction of one def are NOT duplicates."""
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  %ar1 = f32[1024] all-reduce(%p0), {_GROUPS8}, to_apply=%add\n"
        f"  %ar2 = f32[1024] all-reduce(%p0), {_GROUPS8}, to_apply=%max\n"
        f"  ROOT %o = f32[1024] add(%ar1, %ar2)")
    assert shardflow.detect_redundant_pairs(parse_graph(txt)) == []


def test_seeded_wire_dtype_violation():
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  ROOT %ar = f32[1024] all-reduce(%p0), {_GROUPS8}, "
        f"to_apply=%add")
    findings = shardflow.detect_wire_dtype(parse_graph(txt), "bf16")
    assert len(findings) == 1
    assert "carries f32" in findings[0]
    # ...but an f32 wire declaration, or a byte floor above the payload,
    # accepts the same program.
    assert shardflow.detect_wire_dtype(parse_graph(txt), "f32") == []
    assert shardflow.detect_wire_dtype(parse_graph(txt), "bf16",
                                       ignore_below=1 << 20) == []


def test_wire_format_allowlist_seam():
    """A registered quantized wire format exempts its dtype set — the
    EQuARX registration point."""
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  ROOT %ar = f32[1024] all-reduce(%p0), {_GROUPS8}, "
        f"to_apply=%add")
    graph = parse_graph(txt)
    assert shardflow.detect_wire_dtype(graph, "bf16") != []
    shardflow.register_wire_format("test-blockwise", {"f32", "u8"})
    try:
        assert "test-blockwise" in shardflow.registered_wire_formats()
        assert shardflow.detect_wire_dtype(graph, "bf16") == []
    finally:
        shardflow._WIRE_FORMATS.pop("test-blockwise")


def test_int8_block_wire_format_registered_at_import():
    """quantwire's shipped format is registered by the module itself —
    the gate sees s8 collectives as the declared wire, not a violation,
    without any per-run setup."""
    assert shardflow.registered_wire_formats().get("int8-block") \
        == frozenset({"s8"})


def test_seeded_wire_positive_guards_the_gate():
    """check() must run the seeded wire-dtype positive first: a format
    registration broad enough to exempt f32 traffic blinds the detector,
    and the gate has to refuse to run blind."""
    assert shardflow.seeded_wire_positive() == []
    shardflow.register_wire_format("test-blind", {"s8", "f32"})
    try:
        probs = shardflow.seeded_wire_positive()
        assert probs and "exempting" in probs[0]
        # the gate entry point surfaces it even with no audits to run
        assert any("exempting" in p for p in shardflow.check([]))
    finally:
        shardflow._WIRE_FORMATS.pop("test-blind")
    assert shardflow.seeded_wire_positive() == []


def test_seeded_accidental_replication():
    txt = ("HloModule seeded\n\n"
           "ENTRY %main (p0: f32[1024,64]) -> f32[1024,64] {\n"
           "  %p0 = f32[1024,64] parameter(0)\n"
           "  ROOT %c = f32[1024,64] copy(%p0)\n}\n")
    declared = (("f32", (1024, 64), (128, 64)),)
    findings = shardflow.detect_replication(parse_graph(txt), declared)
    assert len(findings) == 1
    assert "accidental replication" in findings[0]
    # sharded as declared -> clean; tiny leaves stay under the floor
    sharded = ("HloModule ok\n\n"
               "ENTRY %main (p0: f32[128,64]) -> f32[128,64] {\n"
               "  %p0 = f32[128,64] parameter(0)\n"
               "  ROOT %c = f32[128,64] copy(%p0)\n}\n")
    assert shardflow.detect_replication(parse_graph(sharded),
                                        declared) == []
    assert shardflow.detect_replication(
        parse_graph(txt), declared, floor=1 << 30) == []


def test_seeded_replica_group_violations():
    mesh = {"data": 8}

    def groups_of(attr):
        txt = _module(
            f"  %p0 = f32[1024] parameter(0)\n"
            f"  ROOT %ar = f32[1024] all-reduce(%p0), "
            f"replica_groups={attr}, to_apply=%add")
        return shardflow.detect_replica_groups(parse_graph(txt), mesh)

    assert groups_of("{{0,1,2,3,4,5,6,7}}") == []
    unequal = groups_of("{{0,1,2},{3,4},{5,6,7}}")
    assert len(unequal) == 1 and "unequal group sizes" in unequal[0]
    overlap = groups_of("{{0,1},{1,2},{3,4},{5,6}}")
    assert len(overlap) == 1 and "overlap" in overlap[0]
    partial = groups_of("{{0,1},{2,3}}")
    assert len(partial) == 1 and "cover" in partial[0]


def test_seeded_replica_group_size_not_axis_product():
    # 12-device a×b mesh: size-2 groups partition the devices but no
    # combination of the declared axes (4, 3) explains a 2-wide group.
    mesh = {"a": 4, "b": 3}
    groups = "{" + ",".join(
        f"{{{2 * i},{2 * i + 1}}}" for i in range(6)) + "}"
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  ROOT %ar = f32[1024] all-reduce(%p0), "
        f"replica_groups={groups}, to_apply=%add")
    findings = shardflow.detect_replica_groups(parse_graph(txt), mesh)
    assert len(findings) == 1
    assert "not a product of declared mesh axes" in findings[0]


def test_seeded_replica_group_iota_forms():
    mesh = {"data": 8}

    def iota_of(count, size):
        txt = _module(
            f"  %p0 = f32[1024] parameter(0)\n"
            f"  ROOT %ar = f32[1024] all-reduce(%p0), "
            f"replica_groups=[{count},{size}]<=[8], to_apply=%add")
        return shardflow.detect_replica_groups(parse_graph(txt), mesh)

    assert iota_of(1, 8) == []
    short = iota_of(2, 2)                 # covers 4 of 8 devices
    assert len(short) == 1 and "do not cover" in short[0]
    odd = iota_of(4, 2)                   # covers, but 2 not in {1, 8}
    assert len(odd) == 1 and "not a product" in odd[0]


def test_seeded_collective_permute_pairs():
    mesh = {"data": 8}

    def permute_of(pairs):
        txt = _module(
            f"  %p0 = f32[1024] parameter(0)\n"
            f"  ROOT %cp = f32[1024] collective-permute(%p0), "
            f"source_target_pairs={pairs}")
        return shardflow.detect_replica_groups(parse_graph(txt), mesh)

    assert permute_of("{{0,1},{1,2},{2,3}}") == []
    dup = permute_of("{{0,1},{0,2}}")
    assert len(dup) == 1 and "duplicate" in dup[0]
    out = permute_of("{{0,9}}")
    assert len(out) == 1 and "outside the declared" in out[0]


def test_census_cross_check_mismatch():
    """Feed the census a report for a DIFFERENT program — the cross
    check must notice the two parsers disagree."""
    txt = _module(
        f"  %p0 = f32[1024] parameter(0)\n"
        f"  ROOT %ar = f32[1024] all-reduce(%p0), {_GROUPS8}, "
        f"to_apply=%add")
    other = _module("  ROOT %p0 = f32[1024] parameter(0)")
    graph = parse_graph(txt)
    assert shardflow.census_cross_check(
        graph, hlo_audit.parse_collectives(txt)) == []
    findings = shardflow.census_cross_check(
        graph, hlo_audit.parse_collectives(other))
    assert len(findings) == 1 and "census mismatch" in findings[0]


# ---------------------------------------------------------------------------
# Derived budgets: drift in either direction fails; version skew skips.
# ---------------------------------------------------------------------------

_AR_TXT = None  # built once below


def _ar_audit():
    global _AR_TXT
    if _AR_TXT is None:
        _AR_TXT = _module(
            f"  %p0 = f32[1024] parameter(0)\n"
            f"  ROOT %ar = f32[1024] all-reduce(%p0), {_GROUPS8}, "
            f"to_apply=%add")
    return _fake_audit(_AR_TXT)


def _derived_file_for(audit) -> dict:
    return {
        "schema": shardflow.REPORT_SCHEMA,
        "jax": shardflow._jax_version(),
        "n_devices": 8,
        "strategies": {audit.name: shardflow.derive_budget(
            audit.report, audit.budget.ignore_below)},
    }


def test_budget_drift_clean_and_both_directions():
    audit = _ar_audit()
    derived = _derived_file_for(audit)
    assert shardflow.budget_drift(audit, derived) == []
    # declaration drifts above the program -> finding
    high = json.loads(json.dumps(derived))
    high["strategies"][audit.name]["kinds"]["all-reduce"]["bytes"] += 4
    assert any("drift on all-reduce" in p
               for p in shardflow.budget_drift(audit, high))
    # declaration misses a kind the program has -> finding too
    gone = json.loads(json.dumps(derived))
    del gone["strategies"][audit.name]["kinds"]["all-reduce"]
    assert any("drift on all-reduce" in p
               for p in shardflow.budget_drift(audit, gone))


def test_budget_drift_missing_entry_and_version_skew():
    audit = _ar_audit()
    derived = _derived_file_for(audit)
    nobody = json.loads(json.dumps(derived))
    nobody["strategies"] = {}
    assert any("no entry" in p
               for p in shardflow.budget_drift(audit, nobody))
    skew = json.loads(json.dumps(derived))
    skew["jax"] = "0.0.0-not-this-one"
    assert shardflow.budget_drift(audit, skew) == []
    assert shardflow.budget_drift(audit, None) != []


def test_derived_for_every_fixture_strategy():
    for name in GOLDENS["strategies"]:
        entry = shardflow.derived_for(name)
        assert entry is not None, name
        assert set(entry) == {"ignore_below", "kinds", "above_floor",
                              "total_bytes"}
        assert entry["total_bytes"] > 0


# ---------------------------------------------------------------------------
# The --json report schema + the compare contract (rc 0/1/2).
# ---------------------------------------------------------------------------

_TOP_KEYS = {"schema", "jax", "n_devices", "lint", "strategies"}
_STRATEGY_KEYS = {"name", "status", "reason", "violations", "collectives",
                  "total_bytes", "derived", "drift", "detectors", "graph"}
_DETECTOR_KEYS = {"redundant_pair", "wire_dtype", "replication",
                  "replica_groups", "census"}


def _build_one_report(tmp_path, *, name="seeded"):
    audit = _fake_audit(_ar_audit().compiled.as_text(), name=name)
    derived_path = tmp_path / f"derived_{name}.json"
    derived_path.write_text(json.dumps(_derived_file_for(audit)))
    finding = types.SimpleNamespace(rule="TF999", path="x.py", line=3,
                                    message="demo")
    return shardflow.build_report([audit], lint_findings=[finding],
                                  n_devices=8,
                                  derived_path=str(derived_path))


def test_report_schema_pinned(tmp_path):
    """The --json report shape is an API: obs-compare-style tooling
    parses it, so key changes must be deliberate (bump REPORT_SCHEMA)."""
    report = _build_one_report(tmp_path)
    assert set(report) == _TOP_KEYS
    assert report["schema"] == shardflow.REPORT_SCHEMA == 1
    assert report["lint"] == [{"rule": "TF999", "path": "x.py",
                               "line": 3, "message": "demo"}]
    (entry,) = report["strategies"]
    assert set(entry) == _STRATEGY_KEYS
    assert set(entry["detectors"]) == _DETECTOR_KEYS
    assert set(entry["derived"]) == {"ignore_below", "kinds",
                                     "above_floor", "total_bytes"}
    assert set(entry["graph"]) == {"computations", "nodes",
                                   "entry_parameters",
                                   "collectives_by_kind"}
    assert entry["drift"] == []
    json.dumps(report)  # must be serializable as-is


def test_compare_reports_contract(tmp_path):
    base = _build_one_report(tmp_path)
    # identical reports: rc 0, one "ok" line per strategy
    rc, lines = shardflow.compare_reports(base, base)
    assert rc == 0 and any(ln.startswith("ok seeded") for ln in lines)
    # op-count change: rc 1 with a REGRESSION line
    worse = json.loads(json.dumps(base))
    worse["strategies"][0]["derived"]["kinds"]["all-reduce"]["count"] += 1
    rc, lines = shardflow.compare_reports(base, worse)
    assert rc == 1 and any("op count" in ln for ln in lines)
    # kind disappearing: rc 1
    gone = json.loads(json.dumps(base))
    del gone["strategies"][0]["derived"]["kinds"]["all-reduce"]
    rc, _ = shardflow.compare_reports(base, gone)
    assert rc == 1
    # byte move beyond tolerance: rc 1; within tolerance: rc 0
    fat = json.loads(json.dumps(base))
    kinds = fat["strategies"][0]["derived"]["kinds"]["all-reduce"]
    kinds["bytes"] = int(kinds["bytes"] * 1.5)
    rc, _ = shardflow.compare_reports(base, fat)
    assert rc == 1
    rc, _ = shardflow.compare_reports(base, fat, bytes_tol=0.6)
    assert rc == 0
    # a detector going from clean to firing: rc 1
    noisy = json.loads(json.dumps(base))
    noisy["strategies"][0]["detectors"]["wire_dtype"] = ["boom"]
    rc, lines = shardflow.compare_reports(base, noisy)
    assert rc == 1 and any("detector wire_dtype" in ln for ln in lines)
    # disjoint strategy sets: rc 2
    other = _build_one_report(tmp_path, name="different")
    rc, _ = shardflow.compare_reports(base, other)
    assert rc == 2
