"""Pipeline parallelism (tpuframe.parallel.pp): GPipe over the ``pipe``
mesh axis.

Golden invariants (SURVEY.md §7 strategy, extended to the pipe axis):
  * pipeline_apply over S stages == sequentially applying the S stage
    functions, exactly;
  * a train step whose forward runs through the pipeline produces the same
    losses as the unsharded stacked-layer model, on a data×pipe mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuframe.parallel import mesh as mesh_lib, pp, step as step_lib

HID = 16


def _stage_fn(params, x):
    # params: [1, HID, HID] slice (leading stage dim from P('pipe')).
    return jnp.tanh(x @ params[0])


def _stacked_params(n_stages, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(n_stages, HID, HID)) * 0.5,
                       jnp.float32)


def _sequential(params, x):
    for i in range(params.shape[0]):
        x = jnp.tanh(x @ params[i])
    return x


class TestMicrobatch:
    def test_shape(self):
        x = jnp.arange(24.0).reshape(12, 2)
        assert pp.microbatch(x, 4).shape == (4, 3, 2)

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            pp.microbatch(jnp.zeros((10, 2)), 4)


@pytest.mark.parametrize("n_micro", [4, 8])
def test_pipeline_matches_sequential(n_micro):
    n_stages = 4
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=2, pipe=n_stages))
    params = _stacked_params(n_stages)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(16, HID)), jnp.float32)

    def body(params, xb):
        micro = pp.microbatch(xb, n_micro)
        out = pp.pipeline_apply(_stage_fn, params, micro)
        out = pp.last_stage_value(out)
        return out.reshape(xb.shape)

    got = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P("pipe"), P("data")),
        out_specs=P("data")))(params, x)
    ref = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_pipeline_grads_match_sequential():
    """jax.grad through the pipeline == grad of the sequential model — the
    backward pipeline comes from transposing scan+ppermute, no schedule."""
    n_stages, n_micro = 4, 4
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(pipe=n_stages))
    params = _stacked_params(n_stages)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(8, HID)), jnp.float32)
    t = jnp.asarray(rng.normal(size=(8, HID)), jnp.float32)

    def pipe_loss(params, x, t):
        micro = pp.microbatch(x, n_micro)
        out = pp.last_stage_value(pp.pipeline_apply(_stage_fn, params, micro))
        return jnp.mean((out.reshape(x.shape) - t) ** 2)

    def grad_body(params, x, t):
        g = jax.grad(pipe_loss)(params, x, t)
        # params are pipe-sharded: each stage's grad slice is already its
        # own; collect the full stack for comparison.
        return g

    g_pipe = jax.jit(jax.shard_map(
        grad_body, mesh=mesh,
        in_specs=(P("pipe"), P(), P()),
        out_specs=P("pipe")))(params, x, t)

    def seq_loss(params, x, t):
        return jnp.mean((_sequential(params, x) - t) ** 2)

    g_ref = jax.grad(seq_loss)(params, x, t)
    np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_ref),
                               atol=1e-6, rtol=1e-5)


def test_pp_train_step_golden_vs_unsharded():
    """Full train loop: losses on a data=2 x pipe=4 mesh match the
    unsharded stacked-layer model step for step."""
    n_stages, n_micro = 4, 4
    rng = np.random.default_rng(3)
    x = np.asarray(rng.normal(size=(16, HID)), np.float32)
    t = np.asarray(rng.normal(size=(16, HID)), np.float32)
    params0 = _stacked_params(n_stages, seed=4)
    tx = optax.sgd(0.05)

    # --- reference: plain single-device training on the stacked params ---
    def seq_loss(params, batch):
        return jnp.mean((_sequential(params, batch["x"]) - batch["t"]) ** 2)

    ref_losses = []
    p = params0
    opt = tx.init(p)
    for _ in range(3):
        l, g = jax.value_and_grad(seq_loss)(p, {"x": x, "t": t})
        up, opt = tx.update(g, opt, p)
        p = optax.apply_updates(p, up)
        ref_losses.append(float(l))

    # --- pipeline: shard_map train step over data x pipe ---
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=2, pipe=n_stages))

    def pipe_step(p, opt, batch):
        def loss_fn(p):
            micro = pp.microbatch(batch["x"], n_micro)
            out = pp.last_stage_value(pp.pipeline_apply(_stage_fn, p, micro))
            loss = jnp.mean((out.reshape(batch["x"].shape) - batch["t"]) ** 2)
            return lax.pmean(loss, "data")

        # Grads arrive already data-averaged (p unvarying over data; the
        # pmean-of-loss transpose emits the reduction) — no explicit pmean.
        loss, g = jax.value_and_grad(loss_fn)(p)
        up, opt = tx.update(g, opt, p)
        return optax.apply_updates(p, up), opt, loss

    step = jax.jit(jax.shard_map(
        pipe_step, mesh=mesh,
        in_specs=(P("pipe"), P("pipe"), P("data")),
        out_specs=(P("pipe"), P("pipe"), P())))

    shard_x = NamedSharding(mesh, P("data"))
    batch = {"x": jax.device_put(jnp.asarray(x), shard_x),
             "t": jax.device_put(jnp.asarray(t), shard_x)}
    p_pipe = jax.device_put(params0, NamedSharding(mesh, P("pipe")))
    opt_pipe = jax.jit(lambda p: tx.init(p),
                      out_shardings=NamedSharding(mesh, P("pipe")))(p_pipe)

    pipe_losses = []
    for _ in range(3):
        p_pipe, opt_pipe, loss = step(p_pipe, opt_pipe, batch)
        pipe_losses.append(float(loss))

    np.testing.assert_allclose(pipe_losses, ref_losses, rtol=1e-5, atol=1e-6)
    assert ref_losses[-1] < ref_losses[0]
