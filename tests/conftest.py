"""Test harness: an 8-device virtual CPU mesh — the "fake cluster".

SURVEY.md §7 test strategy: distributed behavior is tested with forced host
devices so no TPU is needed in CI.  The sandbox's sitecustomize imports jax
and pins the TPU backend before pytest starts, so redirecting via env vars
alone is too late — we also flip ``jax.config`` here, which is honored because
no backend has been initialized yet at collection time.
"""

import os

# TPUFRAME_TPU_TESTS=1 keeps the real backend so the TPU-gated tests
# (tests/test_flash_attention_tpu.py) can run on the bench chip:
#   TPUFRAME_TPU_TESTS=1 python -m pytest tests/test_flash_attention_tpu.py
_USE_TPU = os.environ.get("TPUFRAME_TPU_TESTS") == "1"

if not _USE_TPU:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

if not _USE_TPU:
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running tests")


def pytest_collection_modifyitems(config, items):
    if not _USE_TPU:
        return
    # TPU mode targets the single relay chip (one client at a time; see
    # PERF.md): run ONLY the TPU-gated tests and skip everything that
    # expects the 8-device virtual CPU cluster.
    skip = pytest.mark.skip(
        reason="TPUFRAME_TPU_TESTS=1 runs only the *_tpu test modules")
    for item in items:
        if not item.fspath.basename.endswith("_tpu.py"):
            item.add_marker(skip)


@pytest.fixture(scope="session")
def mesh8():
    from tpuframe.parallel import mesh as mesh_lib

    assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"
    return mesh_lib.make_mesh(mesh_lib.MeshSpec(data=8))


@pytest.fixture(scope="session")
def mesh42():
    """2-D mesh: 4-way data x 2-way model — exercises non-trivial axes."""
    from tpuframe.parallel import mesh as mesh_lib

    return mesh_lib.make_mesh(mesh_lib.MeshSpec(data=4, model=2))
