"""Native C++ host runtime: gather, crc32c, build caching, pipeline + ckpt
integration (SURVEY.md §3b native-component parity)."""

import numpy as np
import pytest

from tpuframe import native
from tpuframe.data.datasets import ArrayDataset


def test_library_builds():
    assert native.available(), "g++ toolchain present but native build failed"


@pytest.mark.parametrize("dtype", [np.float32, np.int32, np.uint8,
                                   np.float64])
def test_gather_matches_numpy(dtype):
    rng = np.random.default_rng(0)
    src = (rng.normal(0, 100, size=(257, 7, 3))).astype(dtype)
    idx = rng.integers(0, 257, size=91)
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])


def test_gather_large_multithreaded():
    rng = np.random.default_rng(1)
    src = rng.normal(size=(2048, 3000)).astype(np.float32)  # > 1 MB: threads
    idx = rng.integers(0, 2048, size=512)
    np.testing.assert_array_equal(native.gather_rows(src, idx, n_threads=8),
                                  src[idx])


def test_gather_bounds_check():
    src = np.zeros((4, 2), np.float32)
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([0, 4]))
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([-1]))


def test_gather_1d_rows():
    src = np.arange(100, dtype=np.int64)
    idx = np.array([5, 0, 99])
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])


def test_crc32c_vectors():
    # RFC 3720 test vector + seed chaining + fallback agreement.
    assert native.crc32c(b"123456789") == 0xE3069283
    assert native.crc32c(b"") == 0
    data = np.random.default_rng(2).integers(0, 256, 10000).astype(np.uint8)
    assert native.crc32c(data) == native._crc32c_py(data.tobytes(), 0)
    assert native.crc32c(b"hello") != native.crc32c(b"hellp")


def test_build_is_cached():
    from tpuframe.native.build import build

    p1 = build()
    p2 = build()
    assert p1 == p2


def test_dataset_gather_path():
    ds = ArrayDataset({"x": np.arange(40, dtype=np.float32).reshape(10, 4),
                       "y": np.arange(10, dtype=np.int32)})
    idx = np.array([3, 1, 7])
    batch = ds[idx]
    np.testing.assert_array_equal(batch["x"], ds.columns["x"][idx])
    np.testing.assert_array_equal(batch["y"], np.array([3, 1, 7], np.int32))
    # slices keep the plain path
    assert ds[:2]["x"].shape == (2, 4)


def test_loader_background_prefetch_equivalence():
    """Batches from the threaded prefetch path match direct indexing in
    content and order (determinism is the DP-correctness substrate)."""
    import jax

    from tpuframe.data import ShardedLoader

    ds = ArrayDataset({"x": np.arange(128, dtype=np.float32).reshape(64, 2),
                       "label": np.arange(64, dtype=np.int32)})
    loader = ShardedLoader(ds, global_batch=8, mesh=None, seed=7)
    got = [jax.device_get(b) for b in loader.epoch(0)]
    order = loader._epoch_order(0)
    assert len(got) == 8
    for i, b in enumerate(got):
        idx = order[i * 8:(i + 1) * 8]
        np.testing.assert_array_equal(b["label"], ds.columns["label"][idx])


def test_loader_early_abandon_no_deadlock():
    from tpuframe.data import ShardedLoader

    ds = ArrayDataset({"x": np.zeros((64, 2), np.float32),
                       "label": np.zeros(64, np.int32)})
    loader = ShardedLoader(ds, global_batch=8, mesh=None)
    it = loader.epoch(0)
    next(it)
    it.close()  # train loops abandon mid-epoch at total_steps


def test_ckpt_crc_detects_corruption(tmp_path):
    import jax.numpy as jnp

    from tpuframe.ckpt import checkpoint as ckpt

    tree = {"w": jnp.arange(16.0).reshape(4, 4)}
    path = ckpt.save(str(tmp_path), 1, tree)
    # flip a byte in the shard payload (past the .npy header)
    import os

    shard = next(p for p in os.listdir(path) if p.endswith(".npy"))
    fpath = os.path.join(path, shard)
    raw = bytearray(open(fpath, "rb").read())
    raw[-1] ^= 0xFF
    open(fpath, "wb").write(bytes(raw))
    with pytest.raises(IOError, match="CRC mismatch"):
        ckpt.restore(str(tmp_path), 1)
    restored = ckpt.restore(str(tmp_path), 1, verify_crc=False)
    assert restored["w"].shape == (4, 4)
