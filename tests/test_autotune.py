"""Autotune sweep driver (tpuframe.obs.autotune) — greedy coordinate
descent over env knobs, budget handling, failed-trial tolerance, and the
subprocess measure's JSON-line contract."""

import json
import sys

from tpuframe.obs.autotune import (Axis, autotune, main, subprocess_measure)


def test_greedy_finds_separable_optimum():
    # value = f(batch) + g(thresh): separable, so greedy is exact.
    scores_b = {"128": 1.0, "256": 3.0, "512": 2.0}
    scores_t = {"": 0.5, "0": 0.1, "8": 0.9}

    calls = []

    def measure(env):
        calls.append(dict(env))
        return scores_b[env["B"]] + scores_t[env["T"]]

    report = autotune(measure, [Axis("B", ["128", "256", "512"]),
                                Axis("T", ["", "0", "8"])])
    assert report.best_env == {"B": "256", "T": "8"}
    assert report.best_value == 3.9
    # baseline + 2 extra per axis = 5 trials, no duplicates wasted
    assert len(report.trials) == 5
    # second axis swept at the first axis's winner
    assert all(c["B"] == "256" for c in calls[3:])


def test_budget_caps_trials():
    report = autotune(lambda env: float(env["X"]),
                      [Axis("X", [str(i) for i in range(10)])], budget=4)
    assert len(report.trials) == 4
    assert report.best_value == 3.0  # best among the 4 tried


def test_failed_trials_recorded_not_fatal():
    def measure(env):
        if env["X"] == "boom":
            raise RuntimeError("kaboom")
        return float(env["X"])

    report = autotune(measure, [Axis("X", ["1", "boom", "5"])])
    assert report.best_env == {"X": "5"}
    errs = [t for t in report.trials if "error" in t]
    assert len(errs) == 1 and "kaboom" in errs[0]["error"]


def test_subprocess_measure_parses_json_line(tmp_path):
    script = tmp_path / "fake_bench.py"
    script.write_text(
        "import json, os\n"
        "print('noise line')\n"
        "print(json.dumps({'metric': 'x', "
        "'value': float(os.environ.get('KNOB', '1')) * 2}))\n")
    m = subprocess_measure([sys.executable, str(script)])
    assert m({"KNOB": "21"}) == 42.0
    assert m({"KNOB": ""}) == 2.0  # '' removes the var -> default 1


def test_cli_end_to_end(tmp_path):
    script = tmp_path / "fake_bench.py"
    script.write_text(
        "import json, os\n"
        "v = {'a': 1.0, 'b': 9.0, 'c': 4.0}[os.environ['KNOB']]\n"
        "print(json.dumps({'value': v}))\n")
    out = tmp_path / "report.json"
    rc = main(["--axis", "KNOB=a,b,c", "--out", str(out), "--",
               sys.executable, str(script)])
    assert rc == 0
    report = json.loads(out.read_text())
    assert report["best_env"] == {"KNOB": "b"}
    assert report["best_value"] == 9.0
    assert len(report["trials"]) == 3
