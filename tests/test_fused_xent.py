"""Chunked fused softmax cross-entropy (tpuframe.ops.fused_xent) vs the
naive materialized-logits path: forward equality, gradient equality (both
h and W), tail-chunk vocab padding, bf16 inputs, and the argmax helper."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpuframe.ops.fused_xent import chunked_argmax, fused_softmax_xent


def _naive(h, w, labels):
    logits = (h.astype(jnp.float32) @ w.astype(jnp.float32))
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def _data(t=48, hdim=16, v=100, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.normal(0, 1, size=(t, hdim)), dtype)
    w = jnp.asarray(rng.normal(0, 0.5, size=(hdim, v)), dtype)
    labels = jnp.asarray(rng.integers(0, v, size=(t,)), jnp.int32)
    return h, w, labels


@pytest.mark.parametrize("chunk", [16, 32, 100, 128])
def test_fwd_matches_naive(chunk):
    # 100 % 16 != 0: exercises the padded tail chunk; 128 > V: single chunk.
    h, w, labels = _data()
    got = fused_softmax_xent(h, w, labels, chunk=chunk)
    ref = _naive(h, w, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("chunk", [16, 100])
def test_grads_match_naive(chunk):
    h, w, labels = _data()

    def loss_fused(h, w):
        return jnp.mean(fused_softmax_xent(h, w, labels, chunk=chunk))

    def loss_naive(h, w):
        return jnp.mean(_naive(h, w, labels))

    (gh_f, gw_f) = jax.grad(loss_fused, argnums=(0, 1))(h, w)
    (gh_n, gw_n) = jax.grad(loss_naive, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gh_f), np.asarray(gh_n),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_n),
                               rtol=2e-5, atol=2e-5)


def test_batched_shape_and_jit():
    h, w, labels = _data(t=24)
    hb = h.reshape(2, 12, -1)
    lb = labels.reshape(2, 12)
    got = jax.jit(lambda a, b, c: fused_softmax_xent(a, b, c, chunk=32))(
        hb, w, lb)
    assert got.shape == (2, 12)
    ref = _naive(h, w, labels).reshape(2, 12)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_bf16_inputs():
    h, w, labels = _data(dtype=jnp.bfloat16)
    got = fused_softmax_xent(h, w, labels, chunk=32)
    ref = _naive(h, w, labels)  # f32 reference on the same (bf16) values
    assert got.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    g = jax.grad(lambda h, w: jnp.mean(
        fused_softmax_xent(h, w, labels, chunk=32)), argnums=(0, 1))(h, w)
    assert g[0].dtype == jnp.bfloat16 and g[1].dtype == jnp.bfloat16


def test_training_decreases_loss():
    # End-to-end sanity: SGD on (h, w) through the fused op learns.
    h, w, labels = _data(t=32, v=64)
    loss_fn = lambda h, w: jnp.mean(  # noqa: E731
        fused_softmax_xent(h, w, labels, chunk=16))
    l0 = float(loss_fn(h, w))
    for _ in range(20):
        gh, gw = jax.grad(loss_fn, argnums=(0, 1))(h, w)
        h, w = h - 0.5 * gh, w - 0.5 * gw
    assert float(loss_fn(h, w)) < l0 * 0.5


def test_ignore_index_masks_loss_and_grads():
    """torch ignore_index parity: masked tokens contribute zero loss and
    zero gradient; the dense losses helper divides by the valid count."""
    torch = pytest.importorskip("torch")  # reference semantics, cpu
    F = torch.nn.functional

    from tpuframe.models.losses import softmax_cross_entropy

    h, w, labels = _data(t=32, v=50)
    labels = labels.at[::4].set(-100)  # every 4th token ignored

    # fused: per-token zeros at masked slots, grads unaffected by them
    per_tok = fused_softmax_xent(h, w, labels, chunk=16, ignore_index=-100)
    assert np.all(np.asarray(per_tok)[::4] == 0.0)

    def loss_fused(h, w):
        pt = fused_softmax_xent(h, w, labels, chunk=16, ignore_index=-100)
        return jnp.sum(pt) / jnp.sum(labels != -100)

    gh, gw = jax.grad(loss_fused, argnums=(0, 1))(h, w)

    # torch reference on identical values
    ht = torch.tensor(np.asarray(h), requires_grad=True)
    wt = torch.tensor(np.asarray(w), requires_grad=True)
    loss_t = F.cross_entropy(ht @ wt, torch.tensor(np.asarray(labels),
                                                   dtype=torch.long),
                             ignore_index=-100)
    loss_t.backward()
    np.testing.assert_allclose(float(loss_fused(h, w)), float(loss_t),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gh), ht.grad.numpy(),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw), wt.grad.numpy(),
                               rtol=2e-5, atol=2e-5)

    # dense helper: same value as torch's mean reduction
    dense = softmax_cross_entropy(h @ w, labels, ignore_index=-100)
    np.testing.assert_allclose(float(dense), float(loss_t),
                               rtol=1e-5, atol=1e-5)


def test_chunked_argmax_matches_naive():
    h, w, _ = _data()
    got = chunked_argmax(h, w, chunk=16)
    ref = jnp.argmax(h.astype(jnp.float32) @ w.astype(jnp.float32), axis=-1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_harness_fused_xent_matches_dense_path():
    """Golden at harness level: the fused_xent=True LM run must track the
    materialized-logits run step for step (same seeds, f32, no dropout
    difference — both paths run the identical model trunk)."""
    from tpuframe import train as train_mod
    from tpuframe.utils import get_config

    base = get_config("lm_smoke").with_overrides(
        total_steps=8, log_every=4, eval_every=100,
        model_kwargs={"seq_mode": None}, shard_seq=False,
        mesh={"data": 8})
    m_dense = train_mod.train(base)
    m_fused = train_mod.train(base.with_overrides(fused_xent=True))
    assert m_fused["step"] == 8
    np.testing.assert_allclose(m_fused["loss"], m_dense["loss"],
                               rtol=5e-4, atol=5e-4)
    assert abs(m_fused["accuracy"] - m_dense["accuracy"]) < 0.05


def test_harness_padded_docs_trains_dense_and_fused():
    """The fine-tune data shape end to end: variable-length padded docs
    with -100 labels through the harness — dense and fused loss paths
    agree (both honor ignore_index=-100) and the run learns."""
    from tpuframe import train as train_mod
    from tpuframe.utils import get_config

    base = get_config("lm_smoke").with_overrides(
        total_steps=8, log_every=4, eval_every=100,
        model_kwargs={"seq_mode": None}, shard_seq=False, mesh={"data": 8},
        dataset_kwargs={"padded_docs": True})
    m_dense = train_mod.train(base)
    m_fused = train_mod.train(base.with_overrides(fused_xent=True))
    assert np.isfinite(m_dense["loss"])
    np.testing.assert_allclose(m_fused["loss"], m_dense["loss"],
                               rtol=5e-4, atol=5e-4)


def test_harness_padded_docs_seq_sharded_unbiased():
    """Bias regression (code-review finding): suffix padding makes seq
    shards systematically unequal in valid tokens, so a per-shard masked
    mean pmean-ed uniformly deflates the loss.  The global sum/count
    reduction must make the dp2 x sp4 layout match the flat dp8 layout on
    identical data."""
    from tpuframe import train as train_mod
    from tpuframe.utils import get_config

    flat = get_config("lm_smoke").with_overrides(
        total_steps=4, log_every=2, eval_every=100,
        model_kwargs={"seq_mode": None}, shard_seq=False, mesh={"data": 8},
        dataset_kwargs={"padded_docs": True})
    seqp = get_config("lm_smoke").with_overrides(
        total_steps=4, log_every=2, eval_every=100,
        dataset_kwargs={"padded_docs": True})  # default: ring, dp2 x sp4
    m_flat = train_mod.train(flat)
    m_seqp = train_mod.train(seqp)
    np.testing.assert_allclose(m_seqp["loss"], m_flat["loss"],
                               rtol=2e-3, atol=2e-3)


def test_harness_fused_xent_with_seq_parallel():
    """fused_xent composes with ring-attention sequence parallelism (the
    lm_long flagship layout): hidden states arrive seq-sharded, the dw
    cotangent psums over data AND seq axes.  Dense-vs-fused golden on the
    default lm_smoke dp2 x sp4 mesh."""
    from tpuframe import train as train_mod
    from tpuframe.utils import get_config

    base = get_config("lm_smoke").with_overrides(
        total_steps=6, log_every=3, eval_every=100)
    m_dense = train_mod.train(base)
    m_fused = train_mod.train(base.with_overrides(fused_xent=True))
    assert m_fused["step"] == 6
    np.testing.assert_allclose(m_fused["loss"], m_dense["loss"],
                               rtol=5e-4, atol=5e-4)
