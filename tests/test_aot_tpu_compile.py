"""Full TPU (Mosaic + XLA) AOT compile guard — no chip needed.

One stage deeper than tests/test_fa_tpu_lowering.py: the sandbox bundles
``libtpu.so``, and a compile-only topology
(``jax.experimental.topologies.get_topology_desc("v5e:2x2", "tpu")``)
runs the ENTIRE TPU compiler — Mosaic kernel codegen, XLA fusion/layout,
SPMD partitioning — on the CPU host (the PERF.md §7 discovery).  These
tests pin that the flagship programs actually COMPILE for v5e:

  - flash-attention fwd + bwd (Mosaic codegen, the round-2/3 risk class);
  - the ResNet-50 DP train step partitioned over 4 devices (collectives
    present in the lowering).

This is the strongest no-hardware guard available; only execution-time
behavior (numerics on the MXU, timing) still needs the bench chip.
"""

import os
import pathlib
import subprocess
import sys
import textwrap

import pytest

_PERF_DIR = str(pathlib.Path(__file__).resolve().parents[1] / "perf")
if _PERF_DIR not in sys.path:
    sys.path.insert(0, _PERF_DIR)

pytestmark = pytest.mark.slow

# The compile-only topology initializes libtpu in-process, which does not
# coexist with the axon TPU plugin or a CPU-pinned jax config — each test
# runs in a scrubbed subprocess (same pattern as __graft_entry__'s dryrun).
# The scrub must happen in the PARENT env: the sandbox's sitecustomize
# registers the axon plugin at interpreter start, before any -c script
# line runs (see tests/conftest.py) — in-child os.environ edits are too
# late and the compile would route to the relay.
_PRELUDE = """
import sys
sys.path.insert(0, {repo!r})
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import topologies
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
topo = topologies.get_topology_desc("v5e:2x2", platform="tpu")
"""


def _run(body, timeout=900, extra_env=None):
    from _common import aot_lock

    repo = pathlib.Path(__file__).resolve().parents[1]
    script = _PRELUDE.format(repo=str(repo)) + textwrap.dedent(body)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    if extra_env:
        env.update(extra_env)
    # Serialize against every other compile-only libtpu user (the perf
    # scripts hold the same lock via hold_aot_lock): a second concurrent
    # process ABORTS on libtpu's /tmp lockfile — seen as flaky suite
    # failures when an offline census overlapped these tests.  Bounded
    # wait so a stuck holder fails the test loudly instead of hanging.
    with aot_lock(timeout_s=1800):
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, text=True,
                              timeout=timeout)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return proc.stdout


def test_flash_attention_fwd_bwd_compiles_for_v5e():
    out = _run("""
        from tpuframe.ops.flash_attention import flash_mha
        dev = topo.devices[0]
        mesh = Mesh(np.array([dev]), ("d",))
        sh = NamedSharding(mesh, P())
        q = jax.ShapeDtypeStruct((2, 1024, 4, 64), jnp.bfloat16, sharding=sh)

        def fwd(q, k, v):
            return flash_mha(q, k, v, causal=True, interpret=False).sum()

        c = jax.jit(jax.grad(fwd, argnums=(0, 1, 2))).lower(q, q, q).compile()
        txt = c.as_text()
        assert "tpu_custom_call" in txt or "custom-call" in txt, txt[:2000]
        print("FA fwd+bwd Mosaic compile OK,",
              int((c.cost_analysis() or {}).get("bytes accessed", 0)), "bytes")
    """)
    assert "Mosaic compile OK" in out


def test_resnet50_dp4_step_compiles_for_v5e():
    out = _run("""
        import optax
        from tpuframe import models
        from tpuframe.models import losses
        from tpuframe.parallel import mesh as mesh_lib
        from tpuframe.parallel import step as step_lib

        mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=4),
                                  devices=list(topo.devices))
        repl = NamedSharding(mesh, P())
        dsh = NamedSharding(mesh, mesh_lib.batch_spec())
        model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16)
        variables = jax.eval_shape(
            lambda k: model.init(k, jnp.zeros((2, 224, 224, 3), jnp.bfloat16)),
            jax.random.key(0))
        tx = optax.sgd(0.1, momentum=0.9)

        def loss_fn(params, model_state, b, rng):
            logits, mut = model.apply({"params": params, **model_state},
                                      b["image"], train=True,
                                      mutable=["batch_stats"])
            return losses.softmax_cross_entropy(logits, b["label"]), (
                dict(mut), {})

        state = jax.eval_shape(
            lambda v: step_lib.TrainState.create(
                v["params"], tx,
                model_state={"batch_stats": v["batch_stats"]}), variables)
        to_s = lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=repl)
        state = jax.tree.map(
            lambda s: to_s(s) if hasattr(s, "shape") else s, state,
            is_leaf=lambda l: isinstance(l, jax.ShapeDtypeStruct))
        batch = {"image": jax.ShapeDtypeStruct((16, 224, 224, 3),
                                               jnp.bfloat16, sharding=dsh),
                 "label": jax.ShapeDtypeStruct((16,), jnp.int32,
                                               sharding=dsh)}
        step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False)
        c = jax.jit(step).lower(state, batch).compile()
        txt = c.as_text()
        assert "all-reduce" in txt, "expected cross-replica collectives"
        print("DP4 v5e compile OK")
    """, timeout=2700)
    assert "DP4 v5e compile OK" in out


_FUSION_BODY = """
    import optax
    from tpuframe import models
    from tpuframe.models import losses
    from tpuframe.parallel import mesh as mesh_lib
    from tpuframe.parallel import step as step_lib
    from tpuframe.parallel import tuning

    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=4),
                              devices=list(topo.devices))
    repl = NamedSharding(mesh, P())
    dsh = NamedSharding(mesh, mesh_lib.batch_spec())
    model = models.ResNet18(num_classes=10, cifar_stem=True,
                            dtype=jnp.bfloat16)
    variables = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((2, 32, 32, 3), jnp.bfloat16)),
        jax.random.key(0))
    tx = optax.sgd(0.1)

    def loss_fn(params, model_state, b, rng):
        logits, mut = model.apply({"params": params, **model_state},
                                  b["x"], train=True,
                                  mutable=["batch_stats"])
        return losses.softmax_cross_entropy(logits, b["y"]), (dict(mut), {})

    state = jax.eval_shape(
        lambda v: step_lib.TrainState.create(
            v["params"], tx,
            model_state={"batch_stats": v["batch_stats"]}), variables)
    to_s = lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=repl)
    state = jax.tree.map(
        lambda s: to_s(s) if hasattr(s, "shape") else s, state,
        is_leaf=lambda l: isinstance(l, jax.ShapeDtypeStruct))
    batch = {"x": jax.ShapeDtypeStruct((16, 32, 32, 3), jnp.bfloat16,
                                       sharding=dsh),
             "y": jax.ShapeDtypeStruct((16,), jnp.int32, sharding=dsh)}
    step = step_lib.make_train_step(
        loss_fn, tx, mesh, donate=False,
        fusion_threshold=tuning.step_threshold())
    c = jax.jit(step).lower(state, batch).compile()
    txt = c.as_text()
    import re as _re
    ops = 0
    tensors = 0
    for ln in txt.splitlines():
        s = ln.strip()
        m = _re.match(r"%?[\\w.-]+ = (.*?) all-reduce(-start)?\\(", s)
        if not m:
            continue
        ops += 1
        tensors += len(_re.findall(r"(?:bf16|f32)\\[", m.group(1)))
    print("ALLREDUCE", ops, tensors)
"""


def test_fusion_threshold_on_v5e_combiner_owns_fusion():
    """HOROVOD_FUSION_THRESHOLD on the REAL TPU compiler: the v5e
    combiner merges gradient reductions into ONE variadic all-reduce
    with or without the explicit program-level fusion buffers — i.e. on
    TPU the backend delivers Horovod's full fusion regardless of the
    knob (SURVEY.md §3b's L1 mapping, now compiler-verified).  The knob
    still changes the traced program: per-leaf mode ships many tensors
    through the single op, packed mode ships few buckets."""
    def counts(threshold):
        out = _run(_FUSION_BODY,
                   extra_env={"TPUFRAME_FUSION_THRESHOLD": threshold})
        parts = out.split("ALLREDUCE")[1].split()
        return int(parts[0]), int(parts[1])

    ops_leaf, tensors_leaf = counts("0")
    ops_packed, tensors_packed = counts("67108864")
    # Backend fusion: one combined all-reduce either way.
    assert ops_leaf == ops_packed == 1, (ops_leaf, ops_packed)
    # The program-level knob is still visible as the operand structure.
    assert tensors_leaf > tensors_packed >= 1, (tensors_leaf,
                                                tensors_packed)


def test_flash_mha_lse_fwd_bwd_compiles_for_v5e():
    """The ring-stage variant (round 5): lse output + its cotangent fold.
    Guards the Mosaic lowering of the lse path the capacity audit's
    flash-ring rows depend on."""
    out = _run("""
        from tpuframe.ops.flash_attention import flash_mha_lse
        dev = topo.devices[0]
        mesh = Mesh(np.array([dev]), ("d",))
        sh = NamedSharding(mesh, P())
        q = jax.ShapeDtypeStruct((2, 512, 4, 64), jnp.bfloat16, sharding=sh)

        def loss(q, k, v):
            out, lse = flash_mha_lse(q, k, v, causal=True, interpret=False)
            # lse participates so its cotangent path compiles too.
            return out.astype(jnp.float32).sum() + (lse * 0.5).sum()

        c = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(q, q, q).compile()
        txt = c.as_text()
        assert "tpu_custom_call" in txt or "custom-call" in txt, txt[:2000]
        print("FA-lse fwd+bwd Mosaic compile OK")
    """)
    assert "Mosaic compile OK" in out


def test_flash_attention_compiles_for_v4_target():
    """v4-generation Mosaic guard (PERF.md §12.1): the lse/delta rows must
    stay sublane-major — a lane-major layout lowers as tpu.dynamic_gather,
    which v4 rejects ('Sublane gather not supported').  This compile
    catches any regression without v4 hardware."""
    out = _run("""
        from tpuframe.ops.flash_attention import flash_mha, flash_mha_lse
        topo4 = topologies.get_topology_desc("v4:2x2x1", platform="tpu")
        dev = topo4.devices[0]
        mesh = Mesh(np.array([dev]), ("d",))
        sh = NamedSharding(mesh, P())
        q = jax.ShapeDtypeStruct((2, 512, 4, 64), jnp.bfloat16, sharding=sh)

        def loss(q, k, v):
            out, lse = flash_mha_lse(q, k, v, causal=True, interpret=False)
            return out.astype(jnp.float32).sum() + (lse * 0.5).sum()

        c = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(q, q, q).compile()
        assert "custom-call" in c.as_text()
        print("FA v4 Mosaic compile OK")
    """)
    assert "v4 Mosaic compile OK" in out


def test_flash_attention_dp4_budget_audit_v5e():
    """tpuframe.analysis over the REAL TPU compiler output: a dp4
    flash-attention train step is AOT-compiled for v5e and its
    collectives must fit the declared dp budget — the Mosaic kernel must
    not perturb the step's wire pattern, and the gradient all-reduce
    must be present and param-sized (the CI gate's deep half; the fast
    half audits CPU lowerings in tests/test_analysis.py)."""
    import jax as _jax
    if not hasattr(_jax, "typeof"):
        pytest.skip("jax.typeof unavailable (flash_mha's shard_map-aware "
                    "out_shape needs the varying-axes API, jax>=0.6) — "
                    "same SKIP-not-PASS contract as tpuframe.analysis "
                    "strategies")
    out = _run("""
        import optax
        from tpuframe.ops.flash_attention import flash_mha
        from tpuframe.analysis import budgets, hlo_audit
        from tpuframe.parallel import mesh as mesh_lib
        from tpuframe.parallel import step as step_lib

        mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=4),
                                  devices=list(topo.devices))
        repl = NamedSharding(mesh, P())
        dsh = NamedSharding(mesh, mesh_lib.batch_spec())
        tx = optax.sgd(0.1)

        def loss_fn(params, model_state, b, rng):
            q = b["q"]
            o = flash_mha(q, q, q, causal=True, interpret=False)
            h = o.reshape(q.shape[0], q.shape[1], -1).astype(jnp.float32)
            return ((h @ params["w"]) ** 2).mean(), ({}, {})

        state = jax.eval_shape(lambda: step_lib.TrainState.create(
            {"w": jnp.zeros((256, 1024), jnp.float32)}, tx))
        to_s = lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=repl)
        state = jax.tree.map(
            lambda s: to_s(s) if hasattr(s, "shape") else s, state,
            is_leaf=lambda l: isinstance(l, jax.ShapeDtypeStruct))
        batch = {"q": jax.ShapeDtypeStruct((8, 512, 4, 64), jnp.bfloat16,
                                           sharding=dsh)}
        step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False)
        report, c = hlo_audit.audit_jitted(step, state, batch)
        txt = c.as_text()
        assert "tpu_custom_call" in txt or "custom-call" in txt, txt[:2000]
        pb = 256 * 1024 * 4
        violations = budgets.check_budget(report, budgets.dp_budget(pb))
        assert not violations, violations
        ar = report.bytes_by_kind().get("all-reduce", 0)
        assert pb <= ar <= 2 * pb, (ar, pb, report.summary())
        print("FA dp4 budget audit OK:", report.summary())
    """, timeout=2700)
    assert "budget audit OK" in out


def test_remat_sweep_cli_smoke_v5e(tmp_path):
    """``python -m tpuframe.tune sweep --remat`` end to end on the real
    v5e compiler (2 policies, small batch to keep the compiles short):
    both policies compile, the report ranks by cost_analysis bytes, the
    winner lands in the tuning DB with a ``remat_policy`` config, and
    the mechanism PERF.md §16 documents holds — per_block CUTS temp
    (live-activation) memory vs none.  Bytes-accessed is recorded but
    deliberately not ordered here: on this conv net recompute
    re-materializes through HBM, so remat is a capacity lever, not a
    bandwidth one (§16's honest finding)."""
    from _common import aot_lock  # noqa: F401 — lock held by the sweep

    repo = pathlib.Path(__file__).resolve().parents[1]
    db = tmp_path / "tune_db.json"
    report = tmp_path / "remat_report.json"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    # remat_sweep takes the AOT lock itself (hold_aot_lock) — do NOT
    # wrap in _run's aot_lock or the child would wait on the parent.
    proc = subprocess.run(
        [sys.executable, "-m", "tpuframe.tune", "sweep", "--remat",
         "--topology", "v5e:2x2", "--remat-batch", "64",
         "--remat-policies", "none", "per_block",
         "--db", str(db), "--report", str(report)],
        env=env, cwd=str(repo), capture_output=True, text=True,
        timeout=2700)
    assert proc.returncode == 0, proc.stderr[-3000:]

    import json as _json
    rep = _json.loads(report.read_text())
    assert rep["remat"]["compile_errors"] == []
    rows = {r["policy"]: r for r in rep["remat"]["rows"]}
    assert set(rows) == {"none", "per_block"}, rep["remat"]["rows"]
    for r in rows.values():
        assert r["gb"] > 0 and r["temp_gb"] > 0
        assert r["drop_vs_none_pct"] is not None
    # The capacity mechanism: per-block remat halves-ish live residency.
    assert rows["per_block"]["temp_gb"] < rows["none"]["temp_gb"]
    assert rep["winner"]["policy"] in rows

    from tpuframe.tune import db as tune_db
    tdb = tune_db.TuningDB.open(str(db))
    recs = tdb.records(family="remat_resnet50", generation="v5e")
    assert {r.config["remat_policy"] for r in recs} == {"none",
                                                        "per_block"}
    best = tdb.best(family="remat_resnet50", generation="v5e")
    assert best.config["remat_policy"] == rep["winner"]["policy"]


def test_fused_conv_bn_bwd_compiles_for_v5e_at_oom_shape():
    """Round-5 kernel (ops/fused_conv_bn.py): Mosaic lowering of the
    fused backward at the shape whose first tiling overflowed the real
    v5e VMEM (layer4-conv1 @ b=256: K=1024, C=512, 14x14 — the
    double-buffer budget regression guard, PERF.md §11)."""
    out = _run("""
        from tpuframe.ops.fused_conv_bn import conv1x1_bn_train
        dev = topo.devices[0]
        mesh = Mesh(np.array([dev]), ("d",))
        sh = NamedSharding(mesh, P())
        a = jax.ShapeDtypeStruct((256, 14, 14, 1024), jnp.bfloat16,
                                 sharding=sh)
        w = jax.ShapeDtypeStruct((1024, 512), jnp.float32, sharding=sh)
        g = jax.ShapeDtypeStruct((512,), jnp.float32, sharding=sh)

        cfg = (1e-5, 2048, False)   # interpret=False -> Mosaic

        def loss(a, w, gamma, beta):
            y, mean, var = conv1x1_bn_train(cfg, a, w, gamma, beta)
            return y.astype(jnp.float32).sum()

        c = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3))).lower(
            a, w, g, g).compile()
        assert "tpu_custom_call" in c.as_text()
        print("fused conv+BN bwd Mosaic compile OK")
    """)
    assert "Mosaic compile OK" in out
