"""Model-level pipeline parallelism: ScanBlockLM through
tpuframe.parallel.pp_lm on a data×pipe mesh.

Golden invariant: the pipelined train losses equal the same model trained
unsharded (same init, same data), step for step — the pipeline decomposition
and its transposed backward change nothing about the math."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpuframe.models.transformer_lm import LMConfig, ScanBlockLM
from tpuframe.parallel import mesh as mesh_lib, pp_lm, step as step_lib


def _cfg():
    return LMConfig.tiny(vocab_size=64, hidden_size=32, num_layers=4,
                         num_heads=2, intermediate_size=64, max_seq=16)


def _data(b=8, s=16):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, size=(b, s + 1)).astype(np.int32)
    return {"input_ids": jnp.asarray(ids[:, :-1]),
            "labels": jnp.asarray(ids[:, 1:])}


def _init_state(model, batch, tx):
    variables = model.init(jax.random.key(0), batch["input_ids"][:1])
    return step_lib.TrainState.create(variables["params"], tx)


@pytest.mark.slow
def test_scanblock_lm_full_forward_matches_staged():
    model = ScanBlockLM(_cfg())
    batch = _data()
    v = model.init(jax.random.key(0), batch["input_ids"][:1])
    full = model.apply(v, batch["input_ids"])
    x = model.apply(v, batch["input_ids"], embed_only=True)
    bl = v["params"]["blocks"]
    for lo in range(0, 4, 2):
        sl = jax.tree.map(lambda a: a[lo:lo + 2], bl)
        x = model.apply({"params": {"blocks": sl}}, x, stage=True,
                        stage_layers=2)
    out = model.apply(v, x, head_only=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full), atol=1e-6)


@pytest.mark.slow
def test_pp_lm_golden_losses_vs_unsharded():
    model = ScanBlockLM(_cfg())
    batch = _data()
    tx = optax.adamw(1e-3)

    # --- unsharded reference on the SAME init ---
    state = _init_state(model, batch, tx)

    def loss_fn(params, model_state, b, rng):
        logits = model.apply({"params": params}, b["input_ids"])
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(
                logits, b["labels"]))
        return loss, ({}, {})

    ref_step = step_lib.make_train_step(loss_fn, tx, None, donate=False)
    ref_losses = []
    s = state
    for _ in range(4):
        s, m = ref_step(s, batch)
        ref_losses.append(float(m["loss"]))

    # --- pipelined on data=2 x pipe=4 ---
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=2, pipe=4))
    factory, place_state, place_batch = pp_lm.make_pp_lm_step(
        model, tx, mesh, n_micro=4)
    ps = place_state(_init_state(model, batch, tx))
    pb = place_batch(batch)
    step = factory(ps)
    pp_losses = []
    for _ in range(4):
        ps, m = step(ps, pb)
        pp_losses.append(float(m["loss"]))

    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-5, atol=2e-5)
    assert ref_losses[-1] < ref_losses[0]


@pytest.mark.slow
def test_pp_lm_global_norm_clip_matches_unsharded():
    """pp_clip_by_global_norm: the cross-stage clip must reproduce the
    unsharded optax.clip_by_global_norm trajectory exactly — per-stage
    local norms would diverge (the reason grad_clip_norm used to be
    refused with pp).  Tight max_norm so the clip actually engages."""
    model = ScanBlockLM(_cfg())
    batch = _data()
    max_norm = 0.05  # well below the typical initial grad norm

    # --- unsharded reference with optax's own clip ---
    tx_ref = optax.chain(optax.clip_by_global_norm(max_norm),
                         optax.adamw(1e-3))
    state = _init_state(model, batch, tx_ref)

    def loss_fn(params, model_state, b, rng):
        logits = model.apply({"params": params}, b["input_ids"])
        loss = jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
            logits, b["labels"]))
        return loss, ({}, {})

    ref_step = step_lib.make_train_step(loss_fn, tx_ref, None, donate=False)
    ref_losses, s = [], state
    for _ in range(4):
        s, m = ref_step(s, batch)
        ref_losses.append(float(m["loss"]))

    # --- pipelined with the cross-stage clip ---
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=2, pipe=4))
    tx_pp = optax.chain(pp_lm.pp_clip_by_global_norm(max_norm),
                        optax.adamw(1e-3))
    factory, place_state, place_batch = pp_lm.make_pp_lm_step(
        model, tx_pp, mesh, n_micro=4)
    ps = place_state(_init_state(model, batch, tx_pp))
    step = factory(ps)
    pp_losses = []
    pb = place_batch(batch)
    for _ in range(4):
        ps, m = step(ps, pb)
        pp_losses.append(float(m["loss"]))

    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_pp_lm_fused_xent_matches_dense():
    """fused_xent=True through the pipeline: the chunked head+loss must
    reproduce the dense pipeline losses step for step (same init/data)."""
    model = ScanBlockLM(_cfg())
    batch = _data()
    tx = optax.adamw(1e-3)
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=2, pipe=4))

    def run(fused):
        factory, place_state, place_batch = pp_lm.make_pp_lm_step(
            model, tx, mesh, n_micro=4, fused_xent=fused)
        ps = place_state(_init_state(model, batch, tx))
        step = factory(ps)
        out = []
        pb = place_batch(batch)
        for _ in range(3):
            ps, m = step(ps, pb)
            out.append((float(m["loss"]), float(m["accuracy"])))
        return out

    dense, fused = run(False), run(True)
    np.testing.assert_allclose([l for l, _ in fused], [l for l, _ in dense],
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose([a for _, a in fused], [a for _, a in dense],
                               atol=1e-6)


def test_pp_lm_block_state_is_sharded():
    model = ScanBlockLM(_cfg())
    batch = _data()
    tx = optax.adamw(1e-3)
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=2, pipe=4))
    factory, place_state, _ = pp_lm.make_pp_lm_step(model, tx, mesh,
                                                    n_micro=4)
    ps = place_state(_init_state(model, batch, tx))
    # blocks leaves sharded over pipe (4 layers / 4 stages = 1 per shard)
    leaf = ps.params["blocks"]["block"]["attn_ln"]["scale"]
    shards = {tuple(s.index) for s in leaf.addressable_shards}
    assert len(shards) == 4, shards
    # embed replicated
    emb = ps.params["embed"]["embedding"]
    assert len({tuple(s.index) for s in emb.addressable_shards}) == 1
    # optimizer state mirrors the params partition
    mu = ps.opt_state[0].mu["blocks"]["block"]["attn_ln"]["scale"]
    assert len({tuple(s.index) for s in mu.addressable_shards}) == 4


def test_pp_lm_indivisible_layers_raises():
    model = ScanBlockLM(LMConfig.tiny(vocab_size=64, hidden_size=32,
                                      num_layers=5, num_heads=2,
                                      intermediate_size=64, max_seq=16))
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(pipe=4, data=2))
    with pytest.raises(ValueError, match="not divisible"):
        pp_lm.make_pp_lm_step(model, optax.sgd(0.1), mesh, n_micro=2)


@pytest.mark.slow
def test_pp_harness_end_to_end_with_resume(tmp_path):
    """lm_pp_smoke through the full harness: trains, evals, checkpoints the
    pipe-sharded state, and a restarted run resumes to the same final loss
    as a straight run."""
    from tpuframe import train as train_mod
    from tpuframe.utils import get_config

    ck = str(tmp_path / "ck")
    base = get_config("lm_pp_smoke").with_overrides(
        total_steps=20, ckpt_every=10, log_every=10, eval_every=100,
        ckpt_dir=ck, grad_clip_norm=1.0)  # exercises the pp-safe clip wiring
    straight = train_mod.train(base)
    assert straight["step"] == 20
    assert straight["loss"] < 3.0

    part1 = train_mod.train(base.with_overrides(total_steps=10,
                                                ckpt_dir=ck + "2"))
    part2 = train_mod.train(base.with_overrides(ckpt_dir=ck + "2"))
    assert part2["step"] == 20
    np.testing.assert_allclose(straight["loss"], part2["loss"], rtol=1e-4)
