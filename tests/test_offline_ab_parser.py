"""Pin perf/exp_offline_ab.py's all-reduce payload parser.

The parser feeds PERF.md §8 finding 4 (32-device wire bytes); it has to
handle XLA's variadic tuple all-reduces, skip non-collective lines, and
halve async start/done pairs (whose result tuple aliases the operand) —
the exact shape the latency-hiding scheduler emits.
"""

import importlib
import pathlib
import re
import sys


def _load():
    perf = pathlib.Path(__file__).resolve().parents[1] / "perf"
    if str(perf) not in sys.path:
        sys.path.insert(0, str(perf))
    # Importing exp_offline_ab would trigger its CPU re-exec guard inside
    # pytest; extract the parser by running it on text instead.
    return perf / "exp_offline_ab.py"


def _parse(txt: str):
    payload = {"bf16": 0.0, "f32": 0.0}
    ops = 0
    for line in txt.splitlines():
        stripped = line.strip()
        m_ = re.match(r"%?[\w.-]+ = (.*?) all-reduce(-start)?\(", stripped)
        if not m_:
            continue
        factor = 0.5 if m_.group(2) else 1.0
        for dt, dims in re.findall(r"(bf16|f32)\[([0-9,]*)\]", m_.group(1)):
            sz = {"bf16": 2, "f32": 4}[dt]
            k = 1
            for d in dims.split(","):
                if d:
                    k *= int(d)
            payload[dt] += k * sz * factor
        ops += 1
    return payload, ops


def test_parser_source_matches_this_copy():
    # The test re-implements the parser to run it without the module's
    # re-exec side effects; fail loudly if the source drifts from what is
    # being pinned here.
    src = _load().read_text()
    assert r"all-reduce(-start)?\(" in src
    assert "factor = 0.5 if m_.group(2) else 1.0" in src


def test_sync_variadic_tuple():
    txt = """
  %all-reduce = (bf16[100]{0:T(128)(2,1)}, f32[10]{0:T(128)S(1)}) all-reduce(%a, %b), replica_groups={{0,1}}
"""
    payload, ops = _parse(txt)
    assert ops == 1
    assert payload["bf16"] == 200 and payload["f32"] == 40


def test_async_start_halved():
    # start's result tuple aliases the operand: shapes appear twice.
    txt = """
  %all-reduce-start = (bf16[100]{0}, bf16[100]{0}) all-reduce-start(%a), replica_groups={{0,1}}
  %all-reduce-done = bf16[100]{0} all-reduce-done(%all-reduce-start)
"""
    payload, ops = _parse(txt)
    assert ops == 1  # -done has no '(-start)?(' match shape... see below
    assert payload["bf16"] == 200  # (100*2 + 100*2) * 0.5


def test_non_collective_lines_ignored():
    txt = """
  %fusion.1 = bf16[512,56,56,256]{3,0,2,1:T(8,128)(2,1)} fusion(%p0), kind=kOutput
  %convert.5 = f32[64]{0} convert(%c)
  ROOT %tuple = (bf16[8]{0}) tuple(%x)
"""
    payload, ops = _parse(txt)
    assert ops == 0 and payload["bf16"] == 0 and payload["f32"] == 0
