"""Pin perf/_hlo_parse.allreduce_payload (used by perf/exp_offline_ab.py).

The parser feeds PERF.md §8 finding 4 (32-device wire bytes); it has to
handle XLA's variadic tuple all-reduces, skip non-collective lines, and
halve async start/done pairs (whose result tuple aliases the operand) —
the exact shape the latency-hiding scheduler emits.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "perf"))

from _hlo_parse import allreduce_payload  # noqa: E402


def test_sync_variadic_tuple():
    txt = """
  %all-reduce = (bf16[100]{0:T(128)(2,1)}, f32[10]{0:T(128)S(1)}) all-reduce(%a, %b), replica_groups={{0,1}}
"""
    payload, ops = allreduce_payload(txt)
    assert ops == 1
    assert payload["bf16"] == 200 and payload["f32"] == 40


def test_async_start_halved():
    # start's result tuple aliases the operand: shapes appear twice; the
    # -done line carries the result shape but is not an extra payload.
    txt = """
  %all-reduce-start = (bf16[100]{0}, bf16[100]{0}) all-reduce-start(%a), replica_groups={{0,1}}
  %all-reduce-done = bf16[100]{0} all-reduce-done(%all-reduce-start)
"""
    payload, ops = allreduce_payload(txt)
    assert ops == 1
    assert payload["bf16"] == 200  # (100*2 + 100*2) * 0.5


def test_multidim_product():
    txt = "  %all-reduce.1 = f32[4,25]{1,0} all-reduce(%g), replica_groups={}\n"
    payload, ops = allreduce_payload(txt)
    assert ops == 1 and payload["f32"] == 400


def test_non_collective_lines_ignored():
    txt = """
  %fusion.1 = bf16[512,56,56,256]{3,0,2,1:T(8,128)(2,1)} fusion(%p0), kind=kOutput
  %convert.5 = f32[64]{0} convert(%c)
  ROOT %tuple = (bf16[8]{0}) tuple(%x)
"""
    payload, ops = allreduce_payload(txt)
    assert ops == 0 and payload["bf16"] == 0 and payload["f32"] == 0
