"""Pin perf/_hlo_parse.allreduce_payload (used by perf/exp_offline_ab.py).

The parser feeds PERF.md §8 finding 4 (32-device wire bytes); it has to
handle XLA's variadic tuple all-reduces, skip non-collective lines, and
halve async start/done pairs (whose result tuple aliases the operand) —
the exact shape the latency-hiding scheduler emits.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "perf"))

from _hlo_parse import allreduce_payload  # noqa: E402


def test_sync_variadic_tuple():
    txt = """
  %all-reduce = (bf16[100]{0:T(128)(2,1)}, f32[10]{0:T(128)S(1)}) all-reduce(%a, %b), replica_groups={{0,1}}
"""
    payload, ops = allreduce_payload(txt)
    assert ops == 1
    assert payload["bf16"] == 200 and payload["f32"] == 40


def test_async_start_halved():
    # start's result tuple aliases the operand: shapes appear twice; the
    # -done line carries the result shape but is not an extra payload.
    txt = """
  %all-reduce-start = (bf16[100]{0}, bf16[100]{0}) all-reduce-start(%a), replica_groups={{0,1}}
  %all-reduce-done = bf16[100]{0} all-reduce-done(%all-reduce-start)
"""
    payload, ops = allreduce_payload(txt)
    assert ops == 1
    assert payload["bf16"] == 200  # (100*2 + 100*2) * 0.5


def test_multidim_product():
    txt = "  %all-reduce.1 = f32[4,25]{1,0} all-reduce(%g), replica_groups={}\n"
    payload, ops = allreduce_payload(txt)
    assert ops == 1 and payload["f32"] == 400


def test_non_collective_lines_ignored():
    txt = """
  %fusion.1 = bf16[512,56,56,256]{3,0,2,1:T(8,128)(2,1)} fusion(%p0), kind=kOutput
  %convert.5 = f32[64]{0} convert(%c)
  ROOT %tuple = (bf16[8]{0}) tuple(%x)
"""
    payload, ops = allreduce_payload(txt)
    assert ops == 0 and payload["bf16"] == 0 and payload["f32"] == 0


# ---------------------------------------------------------------------------
# offline_ab.jsonl supersession (perf/_ab_rows): PERF.md §11 regenerated the
# round-4 offline pallas rows in place — regenerations APPEND with the same
# tag, so the parser must keep only the latest line per tag.  _ab_rows is
# deliberately import-side-effect-free (exp_offline_ab grabs the AOT lock
# at import; tests must never).
# ---------------------------------------------------------------------------

import json  # noqa: E402

from _ab_rows import load_rows, parse_rows, superseded_count  # noqa: E402


def _lines(*rows):
    return [json.dumps(r) for r in rows]


def test_latest_row_per_tag_wins():
    rows = parse_rows(_lines(
        {"tag": "lm_2k_pallas_fusedxent", "gb": 999.0, "round": 4},
        {"tag": "resnet50_dp32", "gb": 6.84},
        {"tag": "lm_2k_pallas_fusedxent", "gb": 99.83, "round": 5},
    ))
    assert len(rows) == 2
    by_tag = {r["tag"]: r for r in rows}
    # the round-5 regeneration supersedes the round-4 interpret-mode row
    assert by_tag["lm_2k_pallas_fusedxent"]["gb"] == 99.83
    assert by_tag["resnet50_dp32"]["gb"] == 6.84


def test_suffixed_tags_are_distinct_keys():
    # a v4-topology regeneration must never hide the v5e row
    rows = parse_rows(_lines(
        {"tag": "resnet50_dp32", "gb": 6.84},
        {"tag": "resnet50_dp32_v4_221", "gb": 7.5},
        {"tag": "resnet50_dp32_r5", "gb": 6.9},
    ))
    assert [r["tag"] for r in rows] == [
        "resnet50_dp32", "resnet50_dp32_v4_221", "resnet50_dp32_r5"]


def test_compile_error_rows_supersedeable_both_ways():
    # error -> success: the fix wins; success -> error: the latest
    # compiler verdict wins (a regression must not hide behind old data)
    rows = parse_rows(_lines(
        {"tag": "a", "compile_error": "RESOURCE_EXHAUSTED"},
        {"tag": "a", "gb": 1.0},
        {"tag": "b", "gb": 2.0},
        {"tag": "b", "compile_error": "vmem"},
    ))
    by_tag = {r["tag"]: r for r in rows}
    assert "compile_error" not in by_tag["a"] and by_tag["a"]["gb"] == 1.0
    assert by_tag["b"]["compile_error"] == "vmem"


def test_garbage_and_blank_lines_skipped():
    rows = parse_rows(["", "not json {", json.dumps({"tag": "x", "gb": 1}),
                       "[1,2,3]"])
    assert len(rows) == 1 and rows[0]["tag"] == "x"


def test_superseded_count():
    lines = _lines({"tag": "a", "v": 1}, {"tag": "a", "v": 2},
                   {"tag": "b", "v": 1})
    assert superseded_count(lines) == 1
    assert superseded_count(_lines({"tag": "a", "v": 1})) == 0


def test_real_results_file_round_trips(tmp_path):
    p = tmp_path / "offline_ab.jsonl"
    p.write_text("\n".join(_lines({"tag": "a", "v": 1},
                                  {"tag": "a", "v": 2})) + "\n")
    rows = load_rows(str(p))
    assert rows == [{"tag": "a", "v": 2}]
