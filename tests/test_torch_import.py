"""torchvision ResNet checkpoint import (tpuframe/models/torch_import.py).

torchvision itself is not in the image, so the oracle is structural: the
export/import pair must be a bijection on the full variable tree, the
exported key set must be exactly torchvision's naming scheme, and a
synthetic state_dict built with torch tensors must round-trip through
the importer with the conv/fc layout transforms applied.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuframe import models
from tpuframe.models import torch_import as ti


def _init(model, size=32):
    return model.init(jax.random.key(0), jnp.zeros((1, size, size, 3)))


@pytest.mark.parametrize("name", ["resnet18", "resnet50"])
def test_roundtrip_bijection(name):
    model = models.get_model(name, num_classes=10, cifar_stem=False)
    v = _init(model)
    sd = ti.export_torchvision_resnet(v)
    v2 = ti.load_torchvision_resnet(v, sd)
    flat1 = ti._flat(v["params"]) | {
        "s/" + k: x for k, x in ti._flat(v["batch_stats"]).items()}
    flat2 = ti._flat(v2["params"]) | {
        "s/" + k: x for k, x in ti._flat(v2["batch_stats"]).items()}
    assert set(flat1) == set(flat2)
    for k in flat1:
        np.testing.assert_array_equal(np.asarray(flat1[k]),
                                      np.asarray(flat2[k]), err_msg=k)


def test_key_names_match_torchvision_scheme():
    model = models.get_model("resnet50", num_classes=1000, cifar_stem=False)
    sd = ti.export_torchvision_resnet(_init(model))
    # Spot-pin canonical torchvision keys incl. stage boundaries and the
    # downsample entries only stage-opening blocks have.
    for key in ("conv1.weight", "bn1.running_var",
                "layer1.0.conv3.weight", "layer1.0.downsample.0.weight",
                "layer1.0.downsample.1.running_mean",
                "layer1.2.bn3.bias",
                "layer2.0.downsample.0.weight", "layer2.3.conv2.weight",
                "layer3.5.bn1.weight", "layer4.2.conv3.weight",
                "fc.weight", "fc.bias"):
        assert key in sd, key
    assert "layer1.1.downsample.0.weight" not in sd  # non-opening block
    # torchvision resnet50: 1 stem + 48 block convs + 4 downsamples = 53.
    assert sum(1 for k in sd if k.endswith("conv1.weight")
               or k.endswith("conv2.weight") or k.endswith("conv3.weight")
               or k == "conv1.weight") == 49
    assert sum(1 for k in sd if k.endswith("downsample.0.weight")) == 4


def test_torch_tensor_state_dict_with_layout_transforms():
    torch = pytest.importorskip("torch")
    model = models.get_model("resnet18", num_classes=4, cifar_stem=False)
    v = _init(model)
    sd_np = ti.export_torchvision_resnet(v)
    sd_t = {k: torch.from_numpy(np.ascontiguousarray(x))
            for k, x in sd_np.items()}
    # Perturb one conv deterministically in TORCH layout (OIHW); the
    # importer must land it transposed in the flax kernel (HWIO).
    w = sd_t["layer1.0.conv1.weight"]
    sd_t["layer1.0.conv1.weight"] = torch.arange(
        w.numel(), dtype=torch.float32).reshape(w.shape)
    v2 = ti.load_torchvision_resnet(v, sd_t)
    got = np.asarray(v2["params"]["BasicBlock_0"]["Conv_0"]["kernel"])
    want = np.arange(w.numel(), dtype=np.float32).reshape(
        tuple(w.shape)).transpose(2, 3, 1, 0)
    np.testing.assert_array_equal(got, want)


def test_missing_and_mismatched_keys_raise():
    model = models.get_model("resnet18", num_classes=4, cifar_stem=False)
    v = _init(model)
    sd = ti.export_torchvision_resnet(v)
    broken = dict(sd)
    del broken["layer2.0.downsample.0.weight"]
    with pytest.raises(KeyError, match="downsample"):
        ti.load_torchvision_resnet(v, broken)
    wrong = dict(sd)
    wrong["fc.weight"] = np.zeros((7, 3), np.float32)
    with pytest.raises(ValueError, match="fc.weight"):
        ti.load_torchvision_resnet(v, wrong)


def test_fused_bn_tree_rejected_with_clear_error():
    # bn='fused' re-keys the Bottleneck 1x1 conv+BN pairs (FusedConvBN_N,
    # downsample_fused) — the importer must refuse up front with guidance
    # instead of dying on a raw KeyError mid-import.
    variables = {
        "params": {
            "stem_conv": {"kernel": jnp.zeros((7, 7, 3, 4))},
            "Bottleneck_0": {
                "FusedConvBN_0": {"kernel": jnp.zeros((1, 1, 4, 8)),
                                  "scale": jnp.ones((8,)),
                                  "bias": jnp.zeros((8,))},
                "Conv_1": {"kernel": jnp.zeros((3, 3, 8, 8))},
                "downsample_fused": {"kernel": jnp.zeros((1, 1, 4, 8))},
            },
        },
        "batch_stats": {"Bottleneck_0": {"FusedConvBN_0": {
            "mean": jnp.zeros((8,)), "var": jnp.ones((8,))}}},
    }
    with pytest.raises(ValueError, match="bn='fused'"):
        ti.load_torchvision_resnet(variables, {})
