"""tpuframe.parallel.pspec — declarative parallelism specs lowered onto
hierarchical ICI×DCN meshes (ISSUE PR 15).

Golden invariants pinned here:

* the spec grammar round-trips (parse -> canonical -> parse) and rejects
  malformed or overcommitted strings with messages naming the defect —
  never a silent fallback;
* the hierarchical mesh puts the DCN ``slice`` axis OUTERMOST, and the
  slice-aware batch helpers (``batch_axes``/``data_parallel_size``/
  ``batch_spec``) range over it;
* spec lowering is a *naming* decision, never a numeric one: the
  spec-lowered dp / dp-zero1 / fsdp steps reproduce the hand-wired
  trajectories step for step (same rtol pin as test_zero1's golden);
* the composed ``dp=2,fsdp=2;slices=2`` strategy audits clean through
  all four shardflow detectors, its auto-derived budget matches the
  checked-in ``derived_budgets.json`` pin byte for byte, and the
  ICI/DCN comm split attributes nonzero bytes to the cross-slice axis;
* TF119 keeps raw ``jax.sharding.Mesh``/``jax.make_mesh`` construction
  out of everything but the mesh seam (parallel/mesh.py, pspec.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpuframe.analysis import collective_graph as cg
from tpuframe.analysis import shardflow, source_lint, strategies
from tpuframe.models import losses
from tpuframe.parallel import mesh as mesh_lib
from tpuframe.parallel import pspec
from tpuframe.parallel import step as step_lib
from tpuframe.parallel import zero1
from tpuframe.tune import roofline

COMPOSED = "dp=2,fsdp=2;slices=2"
COMPOSED_NAME = f"spec:{COMPOSED}"


# ----------------------------------------------------------------------
# grammar: round-trip, malformed, overcommitted
# ----------------------------------------------------------------------

class TestGrammar:
    @pytest.mark.parametrize("text,want", pspec._ROUNDTRIP_CASES)
    def test_round_trip(self, text, want):
        spec = pspec.parse_spec(text)
        assert spec.canonical() == want
        assert pspec.parse_spec(spec.canonical()) == spec

    def test_whitespace_is_insignificant(self):
        assert (pspec.parse_spec(" dp=4, fsdp=2 ; slices=2 ")
                == pspec.parse_spec("dp=4,fsdp=2;slices=2"))

    @pytest.mark.parametrize("text", pspec._MALFORMED_CASES)
    def test_malformed_rejected(self, text):
        with pytest.raises(pspec.SpecError):
            pspec.parse_spec(text)

    @pytest.mark.parametrize("text,n", pspec._OVERCOMMITTED_CASES)
    def test_overcommitted_rejected(self, text, n):
        with pytest.raises(pspec.SpecError,
                           match="overcommit|divide|does not fit"):
            pspec.parse_spec(text).sizes(n)

    def test_wildcard_dp_absorbs_remainder(self):
        sizes = pspec.parse_spec("dp=*,fsdp=2").sizes(8)
        assert sizes["data"] == 4 and sizes["fsdp"] == 2

    def test_composed_sizes_include_slice(self):
        sizes = pspec.parse_spec(COMPOSED).sizes(8)
        assert sizes[mesh_lib.SLICE_AXIS] == 2
        assert sizes["data"] == 2 and sizes["fsdp"] == 2

    def test_self_check_clean(self):
        assert pspec.check() == []


class TestResolve:
    @pytest.fixture(autouse=True)
    def clean_env(self, monkeypatch):
        monkeypatch.delenv(pspec.SPEC_ENV, raising=False)

    def test_default_is_none(self):
        assert pspec.resolve() == (None, "default")

    def test_env_wins(self, monkeypatch):
        monkeypatch.setenv(pspec.SPEC_ENV, "dp=2,tp=4")
        spec, source = pspec.resolve()
        assert source == "env" and spec.tp == 4

    def test_explicit_beats_env(self, monkeypatch):
        monkeypatch.setenv(pspec.SPEC_ENV, "dp=2")
        spec, source = pspec.resolve("dp=4;slices=2")
        assert source == "arg" and spec.slices == 2

    def test_explicit_parse_error_raises(self):
        with pytest.raises(pspec.SpecError):
            pspec.resolve("dp=banana")

    def test_env_parse_error_raises(self, monkeypatch):
        # A *declared* spec that cannot parse must be loud — silent
        # fallback would train on the wrong layout.
        monkeypatch.setenv(pspec.SPEC_ENV, "dp=0")
        with pytest.raises(pspec.SpecError):
            pspec.resolve()


# ----------------------------------------------------------------------
# hierarchical mesh: slice axis outermost, slice-aware batch helpers
# ----------------------------------------------------------------------

class TestHierarchicalMesh:
    def test_slice_axis_is_outermost(self):
        mesh = pspec.parse_spec(COMPOSED).make_mesh()
        assert mesh.axis_names[0] == mesh_lib.SLICE_AXIS
        assert dict(mesh.shape)[mesh_lib.SLICE_AXIS] == 2

    def test_single_slice_mesh_unchanged(self):
        # slices=1 must be byte-identical to the pre-pspec layout: no
        # slice axis at all, so every existing program re-lowers the
        # same HLO (the tier-1 safety property).
        mesh = pspec.parse_spec("dp=8").make_mesh()
        assert mesh_lib.SLICE_AXIS not in mesh.shape
        assert mesh.axis_names == mesh_lib.AXES

    def test_batch_axes_slice_aware(self):
        flat = pspec.parse_spec("dp=8").make_mesh()
        hier = pspec.parse_spec(COMPOSED).make_mesh()
        assert mesh_lib.batch_axes(flat) == mesh_lib.BATCH_AXES
        assert mesh_lib.batch_axes(hier) == (mesh_lib.SLICE_AXIS,
                                             *mesh_lib.BATCH_AXES)

    def test_data_parallel_size_counts_slices(self):
        flat = pspec.parse_spec("dp=4,fsdp=2").make_mesh()
        hier = pspec.parse_spec(COMPOSED).make_mesh()
        # batch shards over (slice, data, fsdp) — BATCH_AXES includes
        # fsdp (batch rides the weight shards), slice multiplies it
        assert mesh_lib.data_parallel_size(flat) == 8
        assert mesh_lib.data_parallel_size(hier) == 8

    def test_mesh_spec_slices_roundtrip(self):
        ms = pspec.parse_spec(COMPOSED).mesh_spec()
        assert ms.slices == 2
        assert ms.sizes(8)[mesh_lib.SLICE_AXIS] == 2


# ----------------------------------------------------------------------
# lowering onto the step seams
# ----------------------------------------------------------------------

class TestLower:
    def test_dp_lowering_is_shard_map_kwargs(self):
        spec = pspec.parse_spec("dp=8")
        mesh = spec.make_mesh()
        kw = pspec.lower(spec, mesh, weight_update="zero1",
                         wire_format="int8-block")
        assert kw["weight_update"] == "zero1"
        assert kw["wire_format"] == "int8-block"
        assert kw["reduce_axes"] == mesh_lib.BATCH_AXES

    def test_hierarchical_dp_reduces_over_slice(self):
        spec = pspec.parse_spec("dp=4;slices=2")
        mesh = spec.make_mesh()
        kw = pspec.lower(spec, mesh)
        assert kw["reduce_axes"][0] == mesh_lib.SLICE_AXIS
        assert kw["batch_partition"] == P(mesh_lib.batch_axes(mesh))

    def test_weight_sharded_lowering_builds_shardings(self, mesh8):
        spec = pspec.parse_spec("dp=4,fsdp=2")
        mesh = spec.make_mesh()
        state = _tiny_lm_state(optax.adamw(1e-3))
        kw = pspec.lower(spec, mesh, state)
        assert "state_shardings" in kw

    def test_modifiers_refused_on_weight_sharded(self):
        spec = pspec.parse_spec("dp=4,fsdp=2")
        mesh = spec.make_mesh()
        with pytest.raises(pspec.SpecError, match="do not compose"):
            pspec.lower(spec, mesh, _tiny_lm_state(optax.adamw(1e-3)),
                        weight_update="zero1")

    def test_weight_sharded_needs_state(self):
        spec = pspec.parse_spec("dp=4,fsdp=2")
        mesh = spec.make_mesh()
        with pytest.raises(pspec.SpecError, match="TrainState"):
            pspec.lower(spec, mesh, None)

    def test_pp_refused(self):
        spec = pspec.parse_spec("dp=4,pp=2")
        mesh = spec.make_mesh()
        with pytest.raises(pspec.SpecError, match="pp_lm|harness"):
            pspec.lower(spec, mesh)

    def test_wrong_mesh_refused(self, mesh8):
        spec = pspec.parse_spec("dp=4,fsdp=2")
        with pytest.raises(pspec.SpecError, match="spec.make_mesh"):
            pspec.lower(spec, mesh8)  # mesh8 is data=8, fsdp=1

    def test_sp_lowering_widens_reduction_over_seq(self):
        spec = pspec.parse_spec("dp=4,sp=2")
        mesh = spec.make_mesh()
        kw = pspec.lower(spec, mesh)
        assert kw["reduce_axes"] == (*mesh_lib.batch_axes(mesh), "seq")
        assert kw["batch_partition"] == P(mesh_lib.batch_axes(mesh),
                                          "seq")

    def test_sp_refuses_shard_map_modifiers(self):
        spec = pspec.parse_spec("dp=4,sp=2")
        mesh = spec.make_mesh()
        for kw in ({"weight_update": "zero1"},
                   {"wire_format": "int8-block"},
                   {"fusion_threshold": 1 << 20},
                   {"grad_reduce": "adasum"}):
            with pytest.raises(pspec.SpecError, match="do not compose"):
                pspec.lower(spec, mesh, **kw)

    def test_tp_requires_rules(self):
        spec = pspec.parse_spec("dp=2,tp=4")
        mesh = spec.make_mesh()
        with pytest.raises(pspec.SpecError, match="tp_rules"):
            pspec.lower(spec, mesh, _tiny_lm_state(optax.adamw(1e-3)))

    def test_adasum_is_exclusive_but_lowers_alone(self):
        spec = pspec.parse_spec("dp=8")
        mesh = spec.make_mesh()
        with pytest.raises(pspec.SpecError, match="adasum"):
            pspec.lower(spec, mesh, weight_update="zero1",
                        grad_reduce="adasum")
        kw = pspec.lower(spec, mesh, grad_reduce="adasum")
        assert kw["grad_reduce"] == "adasum"

    def test_lower_pp_validates_before_delegating(self):
        nopp = pspec.parse_spec("dp=8")
        with pytest.raises(pspec.SpecError, match="pp > 1"):
            pspec.lower_pp(nopp, nopp.make_mesh(), None, None)
        comp = pspec.parse_spec("dp=2,tp=2,pp=2")
        with pytest.raises(pspec.SpecError, match="dp only"):
            pspec.lower_pp(comp, comp.make_mesh(), None, None)


# ----------------------------------------------------------------------
# golden-loss equivalence: spec-lowered vs hand-wired, 3 strategies
# ----------------------------------------------------------------------

N_GOLDEN_STEPS = 50


def _tiny_lm_pieces():
    from tpuframe import models

    model = models.get_model("transformer-lm", tiny=True, vocab_size=64,
                             max_seq=32)
    rng = np.random.default_rng(2)
    ids = rng.integers(0, 64, size=(8, 32)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    variables = model.init(jax.random.key(0), jnp.asarray(ids[:2]))
    tx = optax.adamw(1e-3)

    def loss_fn(params, model_state, batch, rng):
        logits = model.apply({"params": params}, batch["input_ids"],
                             rngs={"dropout": rng})
        return losses.softmax_cross_entropy(logits, batch["labels"]), (
            model_state, {})

    return variables, loss_fn, tx, {"input_ids": ids, "labels": labels}


def _tiny_lm_state(tx):
    variables, _, _, _ = _tiny_lm_pieces()
    return step_lib.TrainState.create(variables["params"], tx)


def _run_steps(step, state, batch, mesh, n_steps=N_GOLDEN_STEPS):
    batch = jax.tree.map(
        lambda a: jax.device_put(a, mesh_lib.batch_sharding(mesh)), batch)
    out = []
    for _ in range(n_steps):
        state, m = step(state, batch)
        out.append(float(m["loss"]))
    return out, state


def _legacy_run(mesh, mode):
    variables, loss_fn, tx, batch = _tiny_lm_pieces()
    if mode == "fsdp":
        from tpuframe.parallel import fsdp as fsdp_lib

        state = step_lib.TrainState.create(variables["params"], tx)
        shardings = fsdp_lib.state_shardings(state, mesh)
        step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False,
                                        state_shardings=shardings)
        state = jax.tree.map(mesh_lib.host_device_put, state, shardings)
    elif mode == "zero1":
        state = zero1.make_state(variables["params"], tx, mesh)
        step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False,
                                        weight_update="zero1")
    else:
        state = step_lib.TrainState.create(variables["params"], tx)
        state = step_lib.replicate_state(state, mesh)
        step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False)
    return _run_steps(step, state, batch, mesh)


def _spec_run(spec_text, mode):
    variables, loss_fn, tx, batch = _tiny_lm_pieces()
    spec = pspec.parse_spec(spec_text)
    mesh = spec.make_mesh()
    state = step_lib.TrainState.create(variables["params"], tx)
    if mode == "zero1":
        state = zero1.make_state(variables["params"], tx, mesh)
        kw = pspec.lower(spec, mesh, weight_update="zero1")
    elif mode == "fsdp":
        kw = pspec.lower(spec, mesh, state)
        state = jax.tree.map(mesh_lib.host_device_put, state,
                             kw["state_shardings"])
    else:
        kw = pspec.lower(spec, mesh)
        state = step_lib.replicate_state(state, mesh)
    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False, **kw)
    return _run_steps(step, state, batch, mesh)


@pytest.mark.slow
@pytest.mark.parametrize("spec_text,legacy_mesh_spec,mode", [
    ("dp=8", mesh_lib.MeshSpec(data=8), "replicated"),
    ("dp=8", mesh_lib.MeshSpec(data=8), "zero1"),
    ("dp=4,fsdp=2", mesh_lib.MeshSpec(data=4, fsdp=2), "fsdp"),
], ids=["dp", "dp-zero1", "fsdp"])
def test_golden_loss_spec_vs_legacy(spec_text, legacy_mesh_spec, mode):
    legacy_mesh = mesh_lib.make_mesh(legacy_mesh_spec)
    golden, gstate = _legacy_run(legacy_mesh, mode)
    got, sstate = _spec_run(spec_text, mode)
    np.testing.assert_allclose(got, golden, rtol=1e-5, atol=1e-6)
    assert golden[-1] < golden[0], "training should make progress"
    for a, b in zip(jax.tree.leaves(sstate.params),
                    jax.tree.leaves(gstate.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


# ----------------------------------------------------------------------
# the composed multi-slice strategy: detectors, pinned budget, DCN split
# ----------------------------------------------------------------------

class TestComposedStrategy:
    def test_registered(self):
        assert COMPOSED_NAME in strategies.STRATEGIES

    def test_register_spec_strategy_naming(self):
        name = strategies.register_spec_strategy(
            "dp=*", weight_update="zero1", wire_format="int8-block")
        try:
            assert name == "spec:dp=*+zero1+int8-block"
            assert name in strategies.STRATEGIES
        finally:
            strategies.STRATEGIES.pop(name, None)

    def test_wrong_world_size_is_unavailable(self):
        audit = strategies.audit_strategy(COMPOSED_NAME, n_devices=2)
        assert audit.status == "unavailable"

    @pytest.fixture(scope="class")
    def composed_audit(self):
        audit = strategies.audit_strategy(COMPOSED_NAME)
        if audit.status == "unavailable":
            pytest.skip(audit.reason)
        return audit

    def test_audit_ok(self, composed_audit):
        assert composed_audit.status == "ok", str(composed_audit.violations)
        assert dict(composed_audit.meta.mesh_shape)[
            mesh_lib.SLICE_AXIS] == 2

    def test_all_four_detectors_clean(self, composed_audit):
        flow = shardflow.audit_flow(composed_audit, n_devices=8)
        for det in ("redundant_pair", "wire_dtype", "replication",
                    "replica_groups"):
            assert flow["detectors"][det] == [], det

    def test_replica_groups_validate_against_slice_product(
            self, composed_audit):
        # The detector's valid sizes come from the declared hierarchical
        # mesh INCLUDING the slice axis: 2 (slice|data|fsdp), 4
        # (pairwise products), 8 (full product) all pass; corrupting the
        # declared slice size must produce findings.
        graph = cg.parse_graph(composed_audit.compiled.as_text())
        good = shardflow.detect_replica_groups(
            graph, composed_audit.meta.mesh_dict)
        assert good == []
        bad_mesh = dict(composed_audit.meta.mesh_dict)
        bad_mesh[mesh_lib.SLICE_AXIS] = 3
        assert shardflow.detect_replica_groups(graph, bad_mesh) != []

    def test_derived_budget_pinned_byte_exact(self, composed_audit):
        derived_file = shardflow.load_derived()
        assert derived_file is not None
        if derived_file["jax"] != jax.__version__:
            pytest.skip("derived_budgets.json pinned at another jax")
        pinned = shardflow.derived_for(COMPOSED_NAME)
        assert pinned is not None, (
            f"{COMPOSED_NAME} missing from derived_budgets.json — "
            f"run python -m tpuframe.analysis --emit-budgets")
        assert shardflow.derive_budget(
            composed_audit.report,
            composed_audit.budget.ignore_below) == pinned

    def test_dcn_split_nonzero_on_cross_slice_axis(self, composed_audit):
        flow = shardflow.audit_flow(composed_audit, n_devices=8)
        split = flow["comm_split"]
        assert split["slices"] == 2
        assert split["dcn_bytes"] > 0, "cross-slice traffic must price DCN"
        assert split["ici_bytes"] > 0, "in-slice traffic must price ICI"
        assert split["unattributed"] == 0
        assert split["ici_bytes"] + split["dcn_bytes"] == sum(
            split["ici"].values()) + sum(split["dcn"].values())

    def test_single_slice_strategy_has_no_dcn_bytes(self):
        audit = strategies.audit_strategy("dp")
        if audit.status == "unavailable":
            pytest.skip(audit.reason)
        split = shardflow.audit_flow(audit, n_devices=8)["comm_split"]
        assert split["slices"] == 1 and split["dcn_bytes"] == 0


# ----------------------------------------------------------------------
# iota replica-group materialization (the strided T(perm) forms the
# real fixtures contain — a contiguous-only reading would misattribute)
# ----------------------------------------------------------------------

class TestMaterializedGroups:
    def _node(self, text):
        graph = cg.parse_graph(f"""\
HloModule m

%add (a: f32[], b: f32[]) -> f32[] {{
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %r = f32[] add(f32[] %a, f32[] %b)
}}

ENTRY %main (p0: f32[8]) -> f32[8] {{
  %p0 = f32[8]{{0}} parameter(0)
  ROOT %ar = f32[8]{{0}} all-reduce(f32[8]{{0}} %p0), {text}, to_apply=%add
}}
""")
        (_, node), = graph.collectives()
        return node

    @staticmethod
    def _as_lists(groups):
        return [list(g) for g in groups]

    def test_transposed_iota_is_strided(self):
        node = self._node("replica_groups=[2,4]<=[4,2]T(1,0)")
        groups = cg.materialized_groups(node, 8)
        assert self._as_lists(groups) == [[0, 2, 4, 6], [1, 3, 5, 7]]

    def test_plain_iota_is_contiguous(self):
        node = self._node("replica_groups=[2,4]<=[8]")
        groups = cg.materialized_groups(node, 8)
        assert self._as_lists(groups) == [[0, 1, 2, 3], [4, 5, 6, 7]]

    def test_explicit_groups_pass_through(self):
        node = self._node("replica_groups={{0,4},{1,5},{2,6},{3,7}}")
        groups = cg.materialized_groups(node, 8)
        assert self._as_lists(groups) == [[0, 4], [1, 5], [2, 6], [3, 7]]

    def test_inconsistent_spec_returns_none(self):
        node = self._node("replica_groups=[2,3]<=[8]")
        assert cg.materialized_groups(node, 8) is None


# ----------------------------------------------------------------------
# DCN roofline plane
# ----------------------------------------------------------------------

class TestDcnRoofline:
    def test_tables_clean(self):
        assert roofline.check_tables() == []

    def test_dcn_slower_than_ici_everywhere(self):
        for gen, hw in roofline.HARDWARE.items():
            assert 0 < hw.dcn_bytes_per_s < hw.ici_bytes_per_s, gen

    def test_dcn_ms_linear_in_bytes(self):
        a = roofline.dcn_ms("v5e", "all-reduce", 1 << 20, 2)
        b = roofline.dcn_ms("v5e", "all-reduce", 1 << 22, 2)
        assert b == pytest.approx(4 * a)

    def test_single_slice_is_free(self):
        assert roofline.dcn_ms("v5e", "all-reduce", 1 << 20, 1) == 0.0

    def test_comm_split_score_prices_both_fabrics(self):
        split = {"slices": 2, "ici": {"all-gather": 1 << 20},
                 "dcn": {"all-reduce": 1 << 20}}
        score = roofline.comm_split_score("v5e", split, n_devices=8,
                                          n_slices=2)
        fabrics = {r["fabric"] for r in score["rows"]}
        assert fabrics == {"ici", "dcn"}
        assert score["t_dcn_ms"] > score["t_ici_ms"]


# ----------------------------------------------------------------------
# TF119: the mesh-seam lint
# ----------------------------------------------------------------------

class TestTF119:
    RAW = ("from jax.sharding import Mesh\n"
           "m = Mesh(devs, ('data',))\n")

    def _lint(self, src, path):
        return [f for f in source_lint.lint_source(src, path)
                if f.rule == "TF119"]

    def test_raw_mesh_flagged(self):
        assert len(self._lint(self.RAW, "tpuframe/train.py")) == 1

    def test_dotted_spelling_flagged(self):
        src = "import jax\nm = jax.sharding.Mesh(devs, ('data',))\n"
        assert len(self._lint(src, "tpuframe/serve/engine.py")) == 1

    def test_jax_make_mesh_flagged(self):
        src = "import jax\nm = jax.make_mesh((8,), ('data',))\n"
        assert len(self._lint(src, "tpuframe/train.py")) == 1

    def test_seam_make_mesh_allowed(self):
        src = ("from tpuframe.parallel import mesh as mesh_lib\n"
               "m = mesh_lib.make_mesh(spec)\n")
        assert self._lint(src, "tpuframe/train.py") == []

    def test_mesh_seam_exempt(self):
        assert self._lint(self.RAW, "tpuframe/parallel/mesh.py") == []
        assert self._lint(self.RAW, "tpuframe/parallel/pspec.py") == []

    def test_suppression_honoured(self):
        src = ("from jax.sharding import Mesh\n"
               "m = Mesh(d, ('x',))  # tf-lint: ok[TF119]\n")
        assert self._lint(src, "tpuframe/train.py") == []

    def test_tree_is_clean(self):
        from pathlib import Path

        findings = [f for f in source_lint.lint_paths(
            [Path("tpuframe")]) if f.rule == "TF119"]
        assert findings == [], "\n".join(map(str, findings))


# ----------------------------------------------------------------------
# TF120: the strategy-registration seam lint
# ----------------------------------------------------------------------

class TestTF120:
    META = ("from tpuframe.analysis.strategies import StrategyMeta\n"
            "m = StrategyMeta(name='mine')\n")

    def _lint(self, src, path):
        return [f for f in source_lint.lint_source(src, path)
                if f.rule == "TF120"]

    def test_hand_built_meta_flagged(self):
        assert len(self._lint(self.META, "tpuframe/train.py")) == 1

    def test_registry_subscript_write_flagged(self):
        src = ("from tpuframe.analysis import strategies\n"
               "strategies.STRATEGIES['mine'] = build\n")
        assert len(self._lint(src, "tpuframe/bench.py")) == 1

    def test_registry_update_flagged(self):
        for call in ("STRATEGIES.update({'mine': build})",
                     "strategies.STRATEGIES.setdefault('mine', build)"):
            assert len(self._lint(call + "\n", "tpuframe/bench.py")) == 1

    def test_strategy_seam_exempt(self):
        assert self._lint(self.META,
                          "tpuframe/analysis/strategies.py") == []

    def test_reading_the_registry_is_fine(self):
        src = ("from tpuframe.analysis import strategies\n"
               "b = strategies.STRATEGIES['dp']\n"
               "names = list(strategies.STRATEGIES)\n")
        assert self._lint(src, "tpuframe/bench.py") == []

    def test_suppression_honoured(self):
        src = "m = StrategyMeta(name='x')  # tf-lint: ok[TF120]\n"
        assert self._lint(src, "tpuframe/train.py") == []

    def test_tree_is_clean(self):
        from pathlib import Path

        findings = [f for f in source_lint.lint_paths(
            [Path("tpuframe")]) if f.rule == "TF120"]
        assert findings == [], "\n".join(map(str, findings))


# ----------------------------------------------------------------------
# spec-lowered registration surface: aliases warn once, event registered
# ----------------------------------------------------------------------

class TestRegistration:
    def test_legacy_alias_warns_once(self):
        import warnings

        strategies._warned_legacy.discard("_build_zero1")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            try:
                strategies._build_zero1(8)
                strategies._build_zero1(8)
            except strategies.Unavailable:
                pass
        deps = [x for x in w if issubclass(x.category, DeprecationWarning)]
        assert len(deps) == 1
        assert "spec-lowered" in str(deps[0].message)

    def test_dp_family_is_spec_lowered(self):
        import functools

        for name in ("dp", "dp-int8", "dp-zero1", "dp-zero1-int8"):
            builder = strategies.STRATEGIES[name]
            assert isinstance(builder, functools.partial)
            assert builder.func is strategies._build_from_spec

    def test_pspec_event_registered(self):
        from tpuframe.obs import events

        assert events.REQUIRED_FIELDS["pspec"] == ("spec", "source")

    def test_every_training_strategy_is_spec_lowered(self):
        """Tentpole acceptance: zero hand-wired training builders.  Every
        training entry in the registry is a partial over
        _build_from_spec with a spec string; serve-dp-decode is the one
        decode program (not a training parallelism, documented in the
        registry)."""
        import functools

        for name, builder in strategies.STRATEGIES.items():
            if name == "serve-dp-decode":
                continue
            assert isinstance(builder, functools.partial), name
            assert builder.func is strategies._build_from_spec, name
            assert builder.args and isinstance(builder.args[0], str), name
