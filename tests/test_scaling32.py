"""The 8->32 scaling projection's measured input, verified at BOTH mesh
endpoints (round-3 verdict missing #6 / SURVEY.md §6, §7 hard part 5).

perf/scaling_projection.py models ring all-reduce cost as
``2*(N-1)/N * B / BW`` with B taken from the compiled 8-device HLO.  The
load-bearing assumption is that B — the per-step cross-replica payload —
does not grow with N (only the ring factor does).  Nothing before this
test verified the compiled 32-device program actually ships those bytes.

Each endpoint compiles in its own subprocess because the forced host
device count is fixed at backend init (the test session is pinned to 8).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "perf", "scaling_projection.py")


def _bytes_at(n_devices: int) -> int:
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    out = subprocess.run(
        [sys.executable, SCRIPT, "--bytes-only", str(n_devices)],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["n_devices"] == n_devices
    return rec["ar_bytes"]


@pytest.mark.slow
def test_allreduce_bytes_match_projection_model_at_8_and_32():
    b8 = _bytes_at(8)
    b32 = _bytes_at(32)

    # The projection's B: the fp32 gradient tree of ResNet-50 (~25.5M
    # params -> ~102 MB) plus nothing else.  Check against the analytic
    # param count rather than a magic constant.
    from tpuframe import models
    import jax
    import jax.numpy as jnp

    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.zeros((2, 64, 64, 3),
                                                        jnp.bfloat16)))
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(variables["params"]))
    grad_bytes = 4 * n_params

    # B is N-independent: the 32-way program ships the same payload the
    # 8-way HLO measured (the ring factor 2*(N-1)/N is cost model, not
    # payload).  Allow 2% slack for N-dependent scalar reductions (loss,
    # batch-stats counters).
    assert abs(b32 - b8) <= 0.02 * b8, (b8, b32)
    # And B is what the projection says it is: the fp32 grad tree (batch
    # stats ride the same fused all-reduce, hence the upper margin).
    assert 0.95 * grad_bytes <= b8 <= 1.15 * grad_bytes, (
        b8, grad_bytes, n_params)
