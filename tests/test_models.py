"""Model zoo tests: shapes, param counts vs the reference architectures,
train/eval mode behavior, and a DP train-step integration check per family."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpuframe.models import (BertConfig, BertForSequenceClassification,
                             ConvNet, ResNet18, ResNet50, get_model, losses)
from tpuframe.parallel import step as step_lib


def _param_count(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


class TestConvNet:
    def test_shapes_and_params(self):
        model = ConvNet()
        x = jnp.zeros((2, 28, 28, 1))
        variables = model.init(jax.random.key(0), x)
        logits = model.apply(variables, x)
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32

    def test_dropout_train_mode(self):
        model = ConvNet()
        x = jnp.ones((2, 28, 28, 1))
        variables = model.init(jax.random.key(0), x)
        a = model.apply(variables, x, train=True,
                        rngs={"dropout": jax.random.key(1)})
        b = model.apply(variables, x, train=True,
                        rngs={"dropout": jax.random.key(2)})
        assert not np.allclose(np.asarray(a), np.asarray(b))
        # eval is deterministic
        c = model.apply(variables, x)
        d = model.apply(variables, x)
        np.testing.assert_array_equal(np.asarray(c), np.asarray(d))


class TestResNet:
    def test_resnet18_cifar(self):
        model = ResNet18(num_classes=10)
        x = jnp.zeros((2, 32, 32, 3))
        variables = model.init(jax.random.key(0), x)
        logits = model.apply(variables, x)
        assert logits.shape == (2, 10)
        # torchvision resnet18 (ImageNet head 1000) has 11.69M; CIFAR head
        # (10 classes) trims the fc: ~11.18M params + BN stats excluded.
        n = _param_count(variables["params"])
        assert 10.5e6 < n < 11.8e6, n

    def test_resnet50_imagenet_param_count(self):
        model = ResNet50(num_classes=1000)
        x = jnp.zeros((1, 64, 64, 3))  # small spatial for test speed
        variables = model.init(jax.random.key(0), x)
        n = _param_count(variables["params"])
        # torchvision resnet50: 25.557M params
        assert abs(n - 25.557e6) < 0.2e6, n

    def test_batch_stats_update(self):
        model = ResNet18(num_classes=10)
        x = jax.random.normal(jax.random.key(1), (4, 32, 32, 3))
        variables = model.init(jax.random.key(0), x)
        _, mutated = model.apply(variables, x, train=True,
                                 mutable=["batch_stats"])
        before = jax.tree.leaves(variables["batch_stats"])
        after = jax.tree.leaves(mutated["batch_stats"])
        assert any(not np.allclose(np.asarray(a), np.asarray(b))
                   for a, b in zip(before, after))

    def test_bf16_compute_f32_params(self):
        model = ResNet18(num_classes=10, dtype=jnp.bfloat16)
        x = jnp.zeros((2, 32, 32, 3))
        variables = model.init(jax.random.key(0), x)
        for p in jax.tree.leaves(variables["params"]):
            assert p.dtype == jnp.float32
        logits = model.apply(variables, x)
        assert logits.dtype == jnp.float32

    @pytest.mark.slow
    def test_remat_same_function_same_grads(self):
        """Per-block rematerialization is a schedule change, not a math
        change: outputs, batch-stats updates, and gradients must match the
        plain model exactly (same params, same param structure)."""
        import optax

        # ResNet18/BasicBlock: the wrapping/naming loop under test is
        # shared with Bottleneck, and this variant keeps the test ~10x
        # cheaper on the CPU suite.
        plain = ResNet18(num_classes=10, remat=False)
        remat = ResNet18(num_classes=10, remat=True)
        x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
        y = jnp.asarray([3, 7])
        variables = plain.init(jax.random.key(0), x)
        assert (jax.tree.structure(variables["params"])
                == jax.tree.structure(remat.init(jax.random.key(0),
                                                 x)["params"]))

        def loss_fn(model, params):
            logits, mut = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                x, train=True, mutable=["batch_stats"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean(), mut

        (l_a, mut_a), g_a = jax.value_and_grad(
            lambda p: loss_fn(plain, p), has_aux=True)(variables["params"])
        (l_b, mut_b), g_b = jax.value_and_grad(
            lambda p: loss_fn(remat, p), has_aux=True)(variables["params"])
        assert float(l_a) == float(l_b)
        for a, b in zip(jax.tree.leaves((g_a, mut_a)),
                        jax.tree.leaves((g_b, mut_b))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestBert:
    def test_tiny_forward(self):
        cfg = BertConfig.tiny()
        model = BertForSequenceClassification(cfg)
        ids = jnp.zeros((2, 16), jnp.int32)
        variables = model.init(jax.random.key(0), ids)
        logits = model.apply(variables, ids)
        assert logits.shape == (2, cfg.num_classes)

    def test_base_param_count(self):
        cfg = BertConfig.base()
        model = BertForSequenceClassification(cfg)
        ids = jnp.zeros((1, 8), jnp.int32)
        variables = jax.eval_shape(
            lambda: model.init(jax.random.key(0), ids))
        n = _param_count(variables["params"])
        # HF bert-base-uncased encoder+embeddings+pooler: 109.48M (+2-class head)
        assert abs(n - 109.48e6) < 1.0e6, n

    def test_padding_mask_effect(self):
        cfg = BertConfig.tiny()
        model = BertForSequenceClassification(cfg)
        ids = jnp.ones((1, 8), jnp.int32)
        variables = model.init(jax.random.key(0), ids)
        full = model.apply(variables, ids, jnp.ones((1, 8), jnp.int32))
        masked = model.apply(variables, ids,
                             jnp.array([[1, 1, 1, 1, 0, 0, 0, 0]], jnp.int32))
        assert not np.allclose(np.asarray(full), np.asarray(masked))

    def test_hf_weight_import_shapes(self):
        """Round-trip: a fake HF state_dict with correct shapes must map onto
        the flax tree with every leaf shape preserved."""
        from tpuframe.models.bert import load_hf_weights

        cfg = BertConfig.tiny()
        model = BertForSequenceClassification(cfg)
        ids = jnp.zeros((1, 8), jnp.int32)
        variables = model.init(jax.random.key(0), ids)
        params = jax.tree.map(np.asarray, dict(variables["params"]))

        H, I = cfg.hidden_size, cfg.intermediate_size
        rng = np.random.default_rng(0)
        sd = {
            "bert.embeddings.word_embeddings.weight": rng.normal(size=(cfg.vocab_size, H)),
            "bert.embeddings.position_embeddings.weight": rng.normal(size=(cfg.max_position, H)),
            "bert.embeddings.token_type_embeddings.weight": rng.normal(size=(cfg.type_vocab_size, H)),
            "bert.embeddings.LayerNorm.weight": np.ones(H),
            "bert.embeddings.LayerNorm.bias": np.zeros(H),
            "bert.pooler.dense.weight": rng.normal(size=(H, H)),
            "bert.pooler.dense.bias": np.zeros(H),
        }
        for i in range(cfg.num_layers):
            p = f"bert.encoder.layer.{i}."
            for proj in ("attention.self.query", "attention.self.key",
                         "attention.self.value", "attention.output.dense"):
                sd[p + proj + ".weight"] = rng.normal(size=(H, H))
                sd[p + proj + ".bias"] = np.zeros(H)
            sd[p + "attention.output.LayerNorm.weight"] = np.ones(H)
            sd[p + "attention.output.LayerNorm.bias"] = np.zeros(H)
            sd[p + "intermediate.dense.weight"] = rng.normal(size=(I, H))
            sd[p + "intermediate.dense.bias"] = np.zeros(I)
            sd[p + "output.dense.weight"] = rng.normal(size=(H, I))
            sd[p + "output.dense.bias"] = np.zeros(H)
            sd[p + "output.LayerNorm.weight"] = np.ones(H)
            sd[p + "output.LayerNorm.bias"] = np.zeros(H)

        loaded = load_hf_weights(params, sd, cfg)
        orig_shapes = jax.tree.map(lambda x: x.shape, params)
        new_shapes = jax.tree.map(lambda x: tuple(np.asarray(x).shape), loaded)
        assert orig_shapes == new_shapes
        # and the word embedding actually changed
        assert not np.allclose(loaded["embeddings"]["word"]["embedding"],
                               params["embeddings"]["word"]["embedding"])


class TestRegistry:
    def test_get_model(self):
        assert isinstance(get_model("convnet"), ConvNet)
        with pytest.raises(ValueError):
            get_model("vgg")


class TestLosses:
    def test_cross_entropy_and_accuracy(self):
        logits = jnp.array([[10.0, 0.0], [0.0, 10.0]])
        labels = jnp.array([0, 1])
        assert float(losses.softmax_cross_entropy(logits, labels)) < 1e-3
        assert float(losses.accuracy(logits, labels)) == 1.0
        smooth = losses.softmax_cross_entropy(logits, labels, 0.1)
        assert float(smooth) > float(losses.softmax_cross_entropy(logits, labels))

    def test_topk(self):
        logits = jnp.array([[3.0, 2.0, 1.0, 0.0]])
        assert float(losses.topk_accuracy(logits, jnp.array([2]), k=3)) == 1.0
        assert float(losses.topk_accuracy(logits, jnp.array([3]), k=3)) == 0.0


class TestModelTrainIntegration:
    def test_resnet18_dp_step(self, mesh8):
        """ResNet-18 with BatchNorm through the full DP train step — the
        mutable-state path (model_state pmean) must compile and run."""
        model = ResNet18(num_classes=10)
        x = jax.random.normal(jax.random.key(0), (16, 32, 32, 3))
        y = jnp.zeros((16,), jnp.int32)
        variables = model.init(jax.random.key(1), x[:2])
        tx = optax.sgd(0.1)

        def loss_fn(params, model_state, batch, rng):
            logits, mutated = model.apply(
                {"params": params, **model_state}, batch["x"], train=True,
                mutable=["batch_stats"])
            loss = losses.softmax_cross_entropy(logits, batch["y"])
            return loss, (dict(mutated), {"acc": losses.accuracy(logits, batch["y"])})

        state = step_lib.TrainState.create(
            variables["params"], tx,
            model_state={"batch_stats": variables["batch_stats"]})
        train = step_lib.make_train_step(loss_fn, tx, mesh8, donate=False)
        state2, metrics = train(state, {"x": x, "y": y})
        assert np.isfinite(float(metrics["loss"]))
        assert int(state2.step) == 1
        # batch_stats must have been updated and stayed replicated
        b0 = jax.tree.leaves(state.model_state["batch_stats"])
        b1 = jax.tree.leaves(state2.model_state["batch_stats"])
        assert any(not np.allclose(np.asarray(u), np.asarray(v))
                   for u, v in zip(b0, b1))


class TestSpaceToDepthStem:
    def test_exact_equivalence_to_conv_stem(self):
        """The s2d stem computes the SAME function as the 7x7/stride-2 stem
        when its kernel is the s2d_stem_kernel rearrangement — the
        function-preserving claim in models/resnet.py."""
        from jax import lax

        from tpuframe.models.resnet import s2d_stem_kernel, space_to_depth

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(2, 224, 224, 3)), jnp.float32)
        w7 = jnp.asarray(rng.normal(size=(7, 7, 3, 16)) * 0.1, jnp.float32)

        ref = lax.conv_general_dilated(
            x, w7, window_strides=(2, 2), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        got = lax.conv_general_dilated(
            space_to_depth(x, 2), s2d_stem_kernel(w7), window_strides=(1, 1),
            padding=((1, 2), (1, 2)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        assert got.shape == ref.shape == (2, 112, 112, 16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_resnet50_s2d_forward_shape_and_params(self):
        m_std = ResNet50(num_classes=10)
        m_s2d = ResNet50(num_classes=10, stem="space_to_depth")
        x = jnp.zeros((1, 224, 224, 3), jnp.float32)
        v_std = m_std.init(jax.random.key(0), x)
        v_s2d = m_s2d.init(jax.random.key(0), x)
        assert m_s2d.apply(v_s2d, x).shape == (1, 10)
        # Only the stem kernel differs: 4*4*12 taps (8x8 receptive field,
        # a superset of the padded 7x7) vs 7*7*3.
        n = lambda v: sum(a.size for a in jax.tree.leaves(v["params"]))  # noqa: E731
        assert n(v_s2d) - n(v_std) == (4 * 4 * 12 - 7 * 7 * 3) * 64


class TestDepthVariants:
    """torchvision-parity depth family: param counts must match the
    canonical torchvision models exactly (the same oracle style as the
    ResNet-50 count pin)."""

    @pytest.mark.parametrize("name,expected", [
        ("resnet34", 21_797_672),
        ("resnet101", 44_549_160),
        ("resnet152", 60_192_808),
    ])
    def test_param_counts_match_torchvision(self, name, expected):
        from tpuframe import models

        model = models.get_model(name, num_classes=1000)
        variables = jax.eval_shape(
            lambda k: model.init(k, jnp.zeros((1, 224, 224, 3))),
            jax.random.key(0))
        n = sum(int(np.prod(p.shape))
                for p in jax.tree.leaves(variables["params"]))
        # torchvision counts include the BN affine params; batch_stats are
        # buffers there, params nowhere — count them separately like the
        # ResNet-50 pin does.
        assert n == expected
