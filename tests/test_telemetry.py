"""The live telemetry plane (PR 9): the OpenMetrics exporter
(``obs/exporter.py``), the crash flight recorder (``obs/flight.py``),
the profiler trace window (``TPUFRAME_TRACE_STEPS``), the ``obs
compare`` regression sentry, and the TF112/TF113 lint rules — plus the
satellite hardening (metrics thread-safety hammer, tensorboard
incremental flush, StepTimeline contract)."""

import json
import os
import pathlib
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

import tpuframe
from tpuframe.obs import events
from tpuframe.obs import exporter
from tpuframe.obs import flight
from tpuframe.obs import goodput
from tpuframe.obs import metrics as obs_metrics
from tpuframe.obs.timeline import StepTimeline, parse_trace_steps

_REPO = pathlib.Path(tpuframe.__file__).parent.parent
_SAMPLES = _REPO / "docs" / "samples"

_TRAIN_CMD = [sys.executable, "-m", "tpuframe.train", "--config", "smoke",
              "--set", "total_steps=6", "--set", "log_every=3",
              "--set", "eval_every=6", "--set", "eval_batches=1",
              "--set", "global_batch=16"]


def _train_env(**extra):
    env = dict(os.environ)
    env.update({
        "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": env.get("XLA_FLAGS", "") +
        " --xla_force_host_platform_device_count=4",
    })
    env.update(extra)
    return env


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(url: str, timeout=2.0):
    """(status, body) — urllib raises on non-2xx, the exporter's 503 is
    an expected state here."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# Exporter unit surface
# ---------------------------------------------------------------------------

def test_exporter_render_openmetrics_contract():
    obs_metrics.reset_counters()
    obs_metrics.bump("retry.gcs_read.retries", 3)
    try:
        ex = exporter.MetricsExporter()
        ex.set_gauge("tpuframe_step", 7)
        ex.set_gauge("tpuframe_goodput_bucket_seconds", 1.5,
                     bucket="productive")
        ex.add_collector(lambda: [("tpuframe_live", {"k": "v"}, 2.0)])
        text = ex.render()
    finally:
        obs_metrics.reset_counters()
    lines = text.splitlines()
    # Counters: the _total suffix with the TYPE line naming the family
    # WITHOUT it (the OpenMetrics counter contract).
    assert "# TYPE tpuframe_events counter" in lines
    assert ('tpuframe_events_total{name="retry.gcs_read.retries"} 3'
            in lines)
    assert "# TYPE tpuframe_step gauge" in lines
    assert "tpuframe_step 7" in lines
    assert ('tpuframe_goodput_bucket_seconds{bucket="productive"} 1.5'
            in lines)
    assert 'tpuframe_live{k="v"} 2' in lines
    # Exposition terminator: last line is # EOF, trailing newline.
    assert lines[-1] == "# EOF" and text.endswith("\n")


def test_exporter_broken_collector_and_label_escaping():
    ex = exporter.MetricsExporter()

    def broken():
        raise RuntimeError("boom")

    ex.add_collector(broken)
    ex.set_gauge("g", 1.0, path='a"b\nc\\d')
    text = ex.render()
    # The broken collector is skipped, not fatal; labels escape per spec.
    assert 'g{path="a\\"b\\nc\\\\d"} 1' in text


def test_exporter_http_endpoints_and_health_flip():
    state = {"ok": True}
    ex = exporter.MetricsExporter(port=0, health=lambda: state["ok"])
    ex.start()
    assert ex.port and ex.port > 0
    try:
        base = f"http://127.0.0.1:{ex.port}"
        status, body = _get(f"{base}/metrics")
        assert status == 200 and body.rstrip().endswith("# EOF")
        status, body = _get(f"{base}/healthz")
        assert status == 200 and body == "ok\n"
        state["ok"] = False
        status, body = _get(f"{base}/healthz")
        assert status == 503 and body == "unhealthy\n"
        status, _ = _get(f"{base}/nope")
        assert status == 404
    finally:
        ex.stop()


def test_exporter_broken_health_probe_reads_unhealthy():
    def probe():
        raise RuntimeError("probe died")

    assert exporter.MetricsExporter(health=probe).healthy() is False


def test_exporter_textfile_flush(tmp_path):
    path = str(tmp_path / "sub" / "metrics.prom")
    ex = exporter.MetricsExporter(textfile=path)
    ex.set_gauge("tpuframe_step", 3)
    ex.flush()
    first = open(path).read()
    assert "tpuframe_step 3" in first and first.rstrip().endswith("# EOF")
    ex.set_gauge("tpuframe_step", 4)
    ex.stop()  # stop() re-flushes
    assert "tpuframe_step 4" in open(path).read()
    # Atomic rewrite: no tmp litter left behind.
    assert os.listdir(tmp_path / "sub") == ["metrics.prom"]


def test_start_from_env_gating(monkeypatch, tmp_path):
    monkeypatch.delenv(exporter.ENV_PORT, raising=False)
    monkeypatch.delenv(exporter.ENV_TEXTFILE, raising=False)
    exporter.stop()
    assert exporter.start_from_env() is None  # off unless asked
    monkeypatch.setenv(exporter.ENV_TEXTFILE, str(tmp_path / "m.prom"))
    ex = exporter.start_from_env()
    try:
        assert ex is not None and ex.port is None  # textfile-only mode
        assert exporter.start_from_env() is ex     # idempotent singleton
    finally:
        exporter.stop()
    assert exporter.get() is None


def test_exporter_stop_is_idempotent():
    ex = exporter.MetricsExporter(port=0).start()
    assert ex.port and ex.port > 0
    ex.stop()
    ex.stop()  # second stop must be a no-op, not a crash
    # and the module-level stop() with no exporter alive is too
    exporter.stop()
    exporter.stop()


def test_exporter_occupied_port_falls_back_to_ephemeral(capsys):
    """A fleet launching N replicas on one host with the same port knob
    must not lose N-1 scrape planes: the loser of the bind race serves
    from an ephemeral port (on ``.port``) instead of crashing or going
    silently scrape-less."""
    first = exporter.MetricsExporter(port=0).start()
    try:
        second = exporter.MetricsExporter(port=first.port).start()
        try:
            assert second.port and second.port != first.port
            status, body = _get(f"http://127.0.0.1:{second.port}/metrics")
            assert status == 200 and body.rstrip().endswith("# EOF")
        finally:
            second.stop()
        assert "fell back to ephemeral port" in capsys.readouterr().err
    finally:
        first.stop()


def test_exporter_post_handler_round_trip():
    ex = exporter.MetricsExporter(port=0).start()
    try:
        ex.add_handler("/echo", lambda body: (200, body.upper()))

        def boom(body):
            raise RuntimeError("handler boom")

        ex.add_handler("/boom", boom)
        base = f"http://127.0.0.1:{ex.port}"

        def post(path, data):
            req = urllib.request.Request(f"{base}{path}", data=data)
            try:
                with urllib.request.urlopen(req, timeout=2.0) as r:
                    return r.status, r.read()
            except urllib.error.HTTPError as e:
                return e.code, e.read()

        status, body = post("/echo", b"fleet")
        assert (status, body) == (200, b"FLEET")
        status, _ = post("/nowhere", b"x")
        assert status == 404
        status, body = post("/boom", b"x")  # 500, server stays up
        assert status == 500 and b"RuntimeError" in body
        status, body = post("/echo", b"still alive")
        assert (status, body) == (200, b"STILL ALIVE")
    finally:
        ex.stop()


def test_router_scrape_503_redispatches_with_zero_loss():
    """End-to-end over real HTTP: replica A accepts a request then its
    /healthz flips 503 mid-generation; the router's scrape marks it
    draining (``router_drain``), re-dispatches the in-flight request to
    replica B (``router_redispatch``), and the admitted request retires
    exactly once — zero loss, first winner kept."""
    from tpuframe.serve.router import Router

    a_state = {"ok": True}
    a_release = threading.Event()

    def a_generate(body):
        msg = json.loads(body.decode())
        a_state["ok"] = False          # health flips mid-generation
        a_release.wait(10.0)           # ...and A stalls on the answer
        return 200, json.dumps({"rid": msg["rid"], "tokens": [1],
                                "ttft_ms": 1.0}).encode()

    def b_generate(body):
        msg = json.loads(body.decode())
        return 200, json.dumps({"rid": msg["rid"], "tokens": [1, 2],
                                "ttft_ms": 2.0}).encode()

    ex_a = exporter.MetricsExporter(port=0,
                                    health=lambda: a_state["ok"]).start()
    ex_b = exporter.MetricsExporter(port=0).start()
    try:
        ex_a.add_handler("/generate", a_generate)
        ex_b.add_handler("/generate", b_generate)
        router = Router(
            [f"http://127.0.0.1:{ex_a.port}",
             f"http://127.0.0.1:{ex_b.port}"],
            queue_limit=8, hedge_ms=0,  # no hedging: drain does the work
            scrape_interval_s=0.01, scrape_timeout_s=1.0,
            dispatch_timeout_s=15.0)
        assert router.submit(7, [1, 2, 3], 4)
        deadline = time.monotonic() + 15.0
        while router.has_work() and time.monotonic() < deadline:
            router.step()
            time.sleep(0.005)
        summary = router.summary()
    finally:
        a_release.set()
        ex_a.stop()
        ex_b.stop()
    assert summary["admitted"] == 1 and summary["requests"] == 1
    assert summary["lost"] == 0
    assert summary["drains"] == 1 and summary["redispatched"] == 1
    (req,) = router.completed
    assert req.replica == "r1" and req.result["tokens"] == [1, 2]


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

def test_flight_ring_is_bounded_and_dump_payload(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUFRAME_ATTEMPT", "2")
    rec = flight.FlightRecorder(str(tmp_path), maxlen=4)
    for i in range(10):
        rec.record({"type": "step", "step": i})
    assert [r["step"] for r in rec.snapshot()] == [6, 7, 8, 9]
    path = rec.dump("unit_test")
    assert path and os.path.basename(path) == "flight_2.json"
    payload = json.load(open(path))
    assert payload["reason"] == "unit_test"
    assert payload["attempt"] == 2
    assert [r["step"] for r in payload["events"]] == [6, 7, 8, 9]
    assert isinstance(payload["counters"], dict)


def test_flight_listener_tees_even_when_write_fails(tmp_path):
    """The ring must hold the record even when the JSONL write is torn —
    that's the whole point of dumping from memory, not from the file."""
    log = events.init(str(tmp_path))
    rec = flight.install(str(tmp_path), maxlen=8)
    try:
        log.emit("step", step=1, wall_ms=10.0)
        log._fh.close()  # simulate a torn/closed file descriptor
        log.emit("step", step=2, wall_ms=11.0)  # write fails, no raise
        steps = [r["step"] for r in rec.snapshot() if r["type"] == "step"]
        assert steps == [1, 2]
    finally:
        flight.uninstall()
        events.close()


def test_flight_dump_noop_when_uninstalled():
    flight.uninstall()
    assert flight.get() is None
    assert flight.dump("nothing") is None


def test_flight_install_env_gating(tmp_path, monkeypatch):
    monkeypatch.delenv(events.ENV_DIR, raising=False)
    assert flight.install() is None  # no directory anywhere: off
    monkeypatch.setenv(events.ENV_DIR, str(tmp_path))
    monkeypatch.setenv(flight.ENV_EVENTS, "3")
    rec = flight.install()
    try:
        assert rec is not None and rec._ring.maxlen == 3
    finally:
        flight.uninstall()


# ---------------------------------------------------------------------------
# Satellites: counter thread-safety, tensorboard incremental flush,
# StepTimeline contract, parse_trace_steps
# ---------------------------------------------------------------------------

def test_metrics_bump_hammer_threads_exact_total():
    obs_metrics.reset_counters()
    n_threads, n_bumps = 8, 2000

    def hammer():
        for _ in range(n_bumps):
            obs_metrics.bump("hammer.total")
            obs_metrics.bump("hammer.weighted", 2)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    got = obs_metrics.counters()
    obs_metrics.reset_counters()
    assert got["hammer.total"] == n_threads * n_bumps
    assert got["hammer.weighted"] == 2 * n_threads * n_bumps


def test_tensorboard_local_flush_is_incremental(tmp_path):
    from tpuframe.obs.tensorboard import SummaryWriter

    w = SummaryWriter(str(tmp_path), flush_every=1000)
    w.add_scalar("loss", 2.0, 1)
    w.flush()
    size1 = os.path.getsize(w.path)
    # The in-memory buffer drains on local flush — flushed history lives
    # on disk, not in RAM (the O(n^2) rewrite this satellite removed).
    assert len(w._buf) == 0
    w.add_scalar("loss", 1.0, 2)
    w.flush()
    size2 = os.path.getsize(w.path)
    assert size2 > size1
    w.close()
    # Appended increments must still parse as one well-formed stream.
    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader)

    loaded = list(EventFileLoader(w.path).Load())
    tags = [v.tag for e in loaded for v in e.summary.value]
    assert tags.count("loss") == 2


def test_step_timeline_chrome_trace_fields(tmp_path):
    tl = StepTimeline(str(tmp_path / "t.json"))
    with tl.phase("data_wait", step=3):
        pass
    with tl.phase("train_step", step=3):
        pass
    tl.instant("preempted", step=3)
    tl.close()
    doc = json.load(open(tl.path))
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    assert [e["name"] for e in evs] == ["data_wait", "train_step",
                                       "preempted"]
    for e in evs:
        assert {"ph", "ts", "pid", "tid"} <= set(e)
    assert evs[0]["ph"] == "X" and evs[0]["dur"] >= 0
    assert evs[2]["ph"] == "i"


def test_step_timeline_multihost_proc_suffix(tmp_path, monkeypatch):
    import tpuframe.obs.timeline as timeline_mod

    monkeypatch.setattr(timeline_mod.jax, "process_count", lambda: 2)
    monkeypatch.setattr(timeline_mod.jax, "process_index", lambda: 1)
    tl = StepTimeline(str(tmp_path / "t.json"))
    assert tl.path.endswith("t.proc1.json")
    tl.instant("x")
    tl.close()
    assert json.load(open(tl.path))["traceEvents"][0]["pid"] == 1


def test_parse_trace_steps():
    assert parse_trace_steps("100:5") == (100, 5)
    assert parse_trace_steps(" 0:1 ") == (0, 1)
    for bad in (None, "", "  ", "5", "a:b", "1:2:3", "-1:5", "3:0",
                "3:-2", "1.5:2"):
        assert parse_trace_steps(bad) is None, bad


# ---------------------------------------------------------------------------
# events listener seam + new schema types
# ---------------------------------------------------------------------------

def test_events_listener_tee_and_removal(tmp_path):
    seen = []
    events.add_listener(seen.append)
    try:
        log = events.EventLog(str(tmp_path))
        log.emit("trace_start", step=5, path="/tmp/trace")
        log.emit("trace_end", step=8, path="/tmp/trace")
        log.close()
    finally:
        events.remove_listener(seen.append)
    assert [r["type"] for r in seen] == ["trace_start", "trace_end"]
    # The new types are registered schema types, not validation leaks.
    for r in seen:
        assert events.validate_record(r) == []
    # After removal the tee is dead.
    log2 = events.EventLog(str(tmp_path))
    log2.emit("step", step=1, wall_ms=1.0)
    log2.close()
    assert len(seen) == 2


def test_events_broken_listener_does_not_break_emit(tmp_path):
    def broken(rec):
        raise RuntimeError("listener bug")

    events.add_listener(broken)
    try:
        log = events.EventLog(str(tmp_path))
        assert log.emit("step", step=1, wall_ms=1.0) is not None
        log.close()
    finally:
        events.remove_listener(broken)


# ---------------------------------------------------------------------------
# compare — the regression sentry
# ---------------------------------------------------------------------------

def test_compare_runs_flags_golden_pair():
    a = events.merge(str(_SAMPLES / "compare_fast"))
    b = events.merge(str(_SAMPLES / "compare_slow"))
    result = goodput.compare_runs(a, b)
    flagged = {r["metric"] for r in result["regressions"]}
    assert {"step_p50_ms", "mfu_productive",
            "serve_ttft_p90_ms"} <= flagged
    # Identity is clean in BOTH directions of the threshold.
    assert goodput.compare_runs(a, a)["regressions"] == []
    # The fast run against the slow baseline is an improvement, not a
    # regression.
    back = goodput.compare_runs(b, a)
    assert back["regressions"] == [] and back["improvements"]


def test_compare_runs_skips_one_sided_metrics():
    """A metric only participates when both runs carry it — a baseline
    without serving traffic must not 'regress' on TTFT."""
    a = events.merge(str(_SAMPLES / "compare_fast"))
    training_only = [r for r in a if not r["type"].startswith("serve")]
    result = goodput.compare_runs(training_only, a)
    assert "serve_ttft_p90_ms" not in result["metrics"]


def test_compare_thresholds_overridable():
    a = events.merge(str(_SAMPLES / "compare_fast"))
    b = events.merge(str(_SAMPLES / "compare_slow"))
    # Thresholds wide enough that nothing regresses.
    loose = goodput.compare_runs(a, b, thresholds={
        "step_pct": 1e6, "productive_drop": 1.0, "mfu_drop": 1.0,
        "serve_pct": 1e6})
    assert loose["regressions"] == []


def test_obs_cli_compare_exit_codes(capsys):
    from tpuframe.obs.__main__ import main

    fast, slow = str(_SAMPLES / "compare_fast"), str(_SAMPLES
                                                     / "compare_slow")
    assert main(["compare", fast, slow]) == 1
    out = capsys.readouterr().out
    assert "COMPARE-REGRESSION [step_p50_ms]" in out
    assert main(["compare", fast, fast]) == 0
    # Threshold flags reach the checks.
    assert main(["compare", fast, slow, "--step-pct", "1e6",
                 "--mfu-drop", "1", "--serve-pct", "1e6",
                 "--prod-drop", "1"]) == 0


def test_obs_selfcheck_includes_compare_golden(capsys):
    from tpuframe.obs.__main__ import main

    assert main(["summarize", "--selfcheck"]) == 0
    out = capsys.readouterr().out
    assert "0 problem(s)" in out


def test_selfcheck_catches_blind_sentry(tmp_path, monkeypatch):
    """If the golden pair ever stops flagging (threshold drift), the
    selfcheck must fail CI — prove it by pointing the sample root at a
    copy where fast == slow."""
    import tpuframe.obs.__main__ as obs_main

    root = tmp_path / "samples"
    for name in ("compare_fast", "compare_slow"):
        d = root / name
        d.mkdir(parents=True)
        src = _SAMPLES / "compare_fast" / "events.compare-0-p0.jsonl"
        (d / "events.compare-0-p0.jsonl").write_text(src.read_text())
    monkeypatch.setattr(obs_main, "_samples_root", lambda: str(root))
    problems = obs_main._selfcheck_compare()
    assert problems and "blind" in problems[0]


# ---------------------------------------------------------------------------
# TF112 / TF113 lint rules
# ---------------------------------------------------------------------------

def test_tf112_unregistered_event_type():
    from tpuframe.analysis.source_lint import lint_source

    src = ("from tpuframe.obs import events as events_lib\n"
           "def f():\n"
           "    events_lib.emit('not_a_type', x=1)\n"
           "    events_lib.emit('step', step=1, wall_ms=2.0)\n"
           "    obs_events.emit('also_bogus')\n"
           "    events_lib.emit(computed_name, x=1)\n")
    findings = [f for f in lint_source(src, "tpuframe/x.py")
                if f.rule == "TF112"]
    assert len(findings) == 2  # both literals flagged, computed skipped
    assert "not_a_type" in findings[0].message


def test_tf112_registry_matches_import():
    """The AST-extracted registry and the real REQUIRED_FIELDS can never
    drift — same source of truth, two readers."""
    from tpuframe.analysis.source_lint import _event_type_registry

    assert _event_type_registry() == frozenset(events.REQUIRED_FIELDS)


def test_tf113_http_server_fenced():
    from tpuframe.analysis.source_lint import lint_source

    src = "import http.server\nfrom http.server import HTTPServer\n"
    assert len([f for f in lint_source(src, "tpuframe/serve/api.py")
                if f.rule == "TF113"]) == 2
    # The exporter is the sanctioned endpoint.
    assert [f for f in lint_source(src, "tpuframe/obs/exporter.py")
            if f.rule == "TF113"] == []


def test_lint_gate_clean_on_tree():
    """The repo's own tree must pass the new rules (the analysis CI
    gate runs them over tpuframe/)."""
    from tpuframe.analysis.source_lint import lint_paths

    findings = [f for f in lint_paths([str(_REPO / "tpuframe")])
                if f.rule in ("TF112", "TF113")]
    assert findings == [], [str(f) for f in findings]


# ---------------------------------------------------------------------------
# End-to-end through the harness (CPU mesh, subprocess)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_exporter_scrape_through_harness(tmp_path):
    """A live scrape during training serves goodput buckets, and the
    final exposition's bucket-seconds sum matches the offline summarize
    recompute (same books, two readers)."""
    evdir = str(tmp_path / "events")
    textfile = str(tmp_path / "metrics.prom")
    port = _free_port()
    proc = subprocess.Popen(
        _TRAIN_CMD, env=_train_env(
            TPUFRAME_EVENTS_DIR=evdir,
            TPUFRAME_METRICS_PORT=str(port),
            TPUFRAME_METRICS_TEXTFILE=textfile),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    live_scrapes = []
    try:
        deadline = time.time() + 500
        while proc.poll() is None and time.time() < deadline:
            try:
                status, body = _get(
                    f"http://127.0.0.1:{port}/metrics", timeout=1.0)
                if status == 200:
                    live_scrapes.append(body)
                hstatus, hbody = _get(
                    f"http://127.0.0.1:{port}/healthz", timeout=1.0)
                if hstatus == 200:
                    assert hbody == "ok\n"  # healthy while stepping
            except Exception:  # noqa: BLE001 — not up yet / mid-shutdown
                pass
            time.sleep(0.3)
        rc = proc.wait(timeout=60)
    finally:
        proc.kill()
        out, err = proc.communicate()
    assert rc == 0, err[-1500:]
    assert live_scrapes, "no successful live scrape during the run"
    assert any("tpuframe_goodput_bucket_seconds" in s
               for s in live_scrapes)

    # Final exposition (stop()'s flush) vs the offline recompute.
    final = open(textfile).read()
    bucket_sum = sum(
        float(line.rsplit(" ", 1)[1]) for line in final.splitlines()
        if line.startswith("tpuframe_goodput_bucket_seconds{"))
    summary = goodput.from_events(events.merge(evdir))
    assert bucket_sum == pytest.approx(sum(summary["buckets"].values()),
                                       rel=0.02, abs=0.25)
    assert bucket_sum == pytest.approx(summary["wall_s"],
                                       rel=0.02, abs=0.25)


@pytest.mark.slow
def test_healthz_flips_on_injected_stall(tmp_path):
    """An injected hang flips /healthz to 503 (the heartbeat watchdog is
    the health probe).  Stall-abort is disabled so the unhealthy window
    is observable instead of ~ms wide."""
    port = _free_port()
    proc = subprocess.Popen(
        _TRAIN_CMD, env=_train_env(
            TPUFRAME_EVENTS_DIR=str(tmp_path / "events"),
            TPUFRAME_METRICS_PORT=str(port),
            TPUFRAME_STALL_TIMEOUT_S="3", TPUFRAME_STALL_POLL_S="0.5",
            TPUFRAME_STALL_ABORT="0", TPUFRAME_HANG_STEP="3"),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        flipped = False
        deadline = time.time() + 500
        while time.time() < deadline and proc.poll() is None:
            try:
                status, _ = _get(f"http://127.0.0.1:{port}/healthz",
                                 timeout=1.0)
                if status == 503:
                    flipped = True
                    break
            except Exception:  # noqa: BLE001 — not up yet / mid-shutdown
                pass
            time.sleep(0.3)
        assert flipped, "healthz never flipped to 503 during the hang"
    finally:
        proc.kill()
        proc.communicate()


@pytest.mark.slow
def test_crash_fault_leaves_flight_dump(tmp_path):
    """A kind=crash fault (os._exit(42), no handler can run) still
    leaves a flight dump whose tail matches the JSONL log."""
    evdir = str(tmp_path / "events")
    out = subprocess.run(
        _TRAIN_CMD, env=_train_env(
            TPUFRAME_EVENTS_DIR=evdir,
            TPUFRAME_FAULTS="host:step=3:kind=crash"),
        capture_output=True, text=True, timeout=500)
    assert out.returncode == 42, out.stderr[-1500:]
    dump_path = os.path.join(evdir, "flight_0.json")
    assert os.path.exists(dump_path), os.listdir(evdir)
    payload = json.load(open(dump_path))
    assert payload["reason"] == "crash_injected"
    ring = payload["events"]
    assert ring and ring[-1]["type"] == "fault_injected"
    # The ring's tail IS the log's tail (same records, memory copy).
    # Compare (type, t) pairs: values that json.dumps(default=str)
    # stringified round-trip differently, the identity keys don't.
    logged = events.read_file(events.event_files(evdir)[0])
    ring_tail = [(r["type"], r["t"]) for r in ring]
    log_tail = [(r["type"], r["t"]) for r in logged]
    n = min(len(ring_tail), len(log_tail))
    assert n >= 3
    assert ring_tail[-n:] == log_tail[-n:]


@pytest.mark.slow
def test_trace_steps_window_through_harness(tmp_path):
    """TPUFRAME_TRACE_STEPS captures a profiler window and announces it
    as typed trace_start/trace_end events carrying the artifact path."""
    evdir = str(tmp_path / "events")
    out = subprocess.run(
        _TRAIN_CMD, env=_train_env(
            TPUFRAME_EVENTS_DIR=evdir, TPUFRAME_TRACE_STEPS="3:2"),
        capture_output=True, text=True, timeout=500)
    assert out.returncode == 0, out.stderr[-1500:]
    merged = events.merge(evdir)
    starts = [r for r in merged if r["type"] == "trace_start"]
    ends = [r for r in merged if r["type"] == "trace_end"]
    assert len(starts) == 1 and len(ends) == 1
    assert starts[0]["step"] == 3 and ends[0]["step"] == 5
    trace_path = starts[0]["path"]
    assert trace_path == ends[0]["path"]
    assert os.path.isdir(trace_path)  # the artifact actually landed
    assert events.validate_files(events.event_files(evdir)) == []
