"""Sequence-parallel attention (ring + Ulysses) vs full attention.

The DP-correctness invariant extended to the seq axis: sharding the sequence
over the mesh must not change the math (SURVEY.md §7 golden-loss strategy).
Runs on the 8-device virtual CPU mesh from conftest.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuframe.ops import attention, seq_parallel
from tpuframe.parallel import mesh as mesh_lib


@pytest.fixture(scope="module")
def seq_mesh():
    # 2-way data x 4-way seq: both batch and sequence sharded.
    return mesh_lib.make_mesh(mesh_lib.MeshSpec(data=2, seq=4))


def _qkv(b=4, s=64, n=4, d=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (b, s, n, d), jnp.float32) * 0.5
                 for k in ks)


def _padding_mask(b=4, s=64, seed=1):
    lengths = jax.random.randint(jax.random.key(seed), (b,), s // 4, s + 1)
    return (jnp.arange(s)[None, :] < lengths[:, None]).astype(jnp.int32)


def _reference(q, k, v, mask=None, causal=False):
    return attention.multihead_attention(q, k, v, mask=mask, causal=causal,
                                         impl="xla")


def _run_sharded(fn, mesh, q, k, v, mask):
    """shard_map fn over (data, seq) with activations sharded [data, seq]."""
    act = P("data", "seq")
    specs = (act, act, act, P("data", "seq") if mask is not None else P())
    mapped = jax.shard_map(fn, mesh=mesh, in_specs=specs, out_specs=act)
    args = [jax.device_put(x, NamedSharding(mesh, s))
            for x, s in zip((q, k, v), (act,) * 3)]
    m = (jax.device_put(mask, NamedSharding(mesh, P("data", "seq")))
         if mask is not None else None)
    return jax.jit(mapped)(*args, m)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(seq_mesh, causal):
    q, k, v = _qkv()
    mask = None if causal else _padding_mask()

    def fn(q, k, v, m):
        return seq_parallel.ring_attention(q, k, v, axis="seq", mask=m,
                                           causal=causal)

    got = _run_sharded(fn, seq_mesh, q, k, v, mask)
    want = _reference(q, k, v, mask=mask, causal=causal)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_q_chunked_matches_full(seq_mesh, causal):
    # q_chunk smaller than the local chunk (16 < 64/4): exercises the
    # lax.map sub-chunking that bounds the per-stage score block at long
    # context (the 32k OOM fix, PERF.md §9) — must be exact.
    q, k, v = _qkv()
    mask = None if causal else _padding_mask()

    def fn(q, k, v, m):
        return seq_parallel.ring_attention(q, k, v, axis="seq", mask=m,
                                           causal=causal, q_chunk=8)

    got = _run_sharded(fn, seq_mesh, q, k, v, mask)
    want = _reference(q, k, v, mask=mask, causal=causal)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_ring_attention_q_chunk_indivisible_falls_back(seq_mesh):
    # Local chunk 16 with q_chunk=10: indivisible -> whole-chunk path.
    q, k, v = _qkv()

    def fn(q, k, v, m):
        return seq_parallel.ring_attention(q, k, v, axis="seq",
                                           causal=True, q_chunk=10)

    got = _run_sharded(fn, seq_mesh, q, k, v, None)
    want = _reference(q, k, v, causal=True)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(seq_mesh, causal):
    q, k, v = _qkv()
    mask = None if causal else _padding_mask()

    def fn(q, k, v, m):
        return seq_parallel.ulysses_attention(q, k, v, axis="seq", mask=m,
                                              causal=causal)

    got = _run_sharded(fn, seq_mesh, q, k, v, mask)
    want = _reference(q, k, v, mask=mask, causal=causal)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_ring_attention_gradients(seq_mesh):
    """Gradients flow through the ppermute rotation and match full attention."""
    q, k, v = _qkv(b=2, s=32, n=2, d=8)

    def loss_ring(q, k, v):
        def fn(q, k, v, m):
            return seq_parallel.ring_attention(q, k, v, axis="seq",
                                               causal=True)
        act = P("data", "seq")
        mapped = jax.shard_map(fn, mesh=seq_mesh,
                               in_specs=(act, act, act, P()),
                               out_specs=act)
        return jnp.sum(mapped(q, k, v, None) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(gr, gf, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("q_chunk", [4, 3])  # 3: ragged tail (8 = 2*3 + 2)
def test_ring_attention_q_chunked_gradients(seq_mesh, q_chunk):
    """Gradients through the lax.map + double-checkpoint sub-chunk path —
    the 32k memory fix's backward (PERF.md §9) — must match full attention
    exactly, including with a ragged tail sub-chunk."""
    q, k, v = _qkv(b=2, s=32, n=2, d=8)  # local chunk 32/4 = 8 > q_chunk
    mask = _padding_mask(b=2, s=32, seed=3)

    def loss_ring(q, k, v):
        def fn(q, k, v, m):
            return seq_parallel.ring_attention(q, k, v, axis="seq", mask=m,
                                               causal=False,
                                               q_chunk=q_chunk)
        act = P("data", "seq")
        mapped = jax.shard_map(fn, mesh=seq_mesh,
                               in_specs=(act, act, act, P("data", "seq")),
                               out_specs=act)
        return jnp.sum(mapped(q, k, v, mask) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(_reference(q, k, v, mask=mask) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(gr, gf, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name}")


def test_ring_fully_masked_rows(seq_mesh):
    """A batch entry that is entirely padding yields exactly zero output."""
    q, k, v = _qkv(b=4, s=64)
    mask = jnp.concatenate([jnp.zeros((2, 64), jnp.int32),
                            jnp.ones((2, 64), jnp.int32)])

    def fn(q, k, v, m):
        return seq_parallel.ring_attention(q, k, v, axis="seq", mask=m)

    got = np.asarray(jax.device_get(_run_sharded(fn, seq_mesh, q, k, v, mask)))
    np.testing.assert_array_equal(got[:2], np.zeros_like(got[:2]))
    assert float(np.max(np.abs(got[2:]))) > 0


@pytest.fixture(autouse=True)
def _force_ring_flash_interpreter(monkeypatch):
    """The flash-ring tests exercise the kernel path UNDER the
    interpreter (that is the point of the CPU suite); the production
    guard in ring_attention would otherwise silently fall back to the
    XLA stages off-TPU."""
    monkeypatch.setenv("TPUFRAME_RING_FLASH_INTERPRET", "1")


def _run_sharded_novma(fn, mesh, q, k, v, mask):
    """_run_sharded with shard_map's vma check off: the pallas HLO
    interpreter's internal slicing mixes varying operands with its own
    unvarying loop indices, which the check rejects (the error message
    itself prescribes check_vma=False as the workaround).  CPU-test-only
    concession — the real-TPU Mosaic lowering doesn't interpret and
    carries vma via flash_attention._sds."""
    act = P("data", "seq")
    specs = (act, act, act, P("data", "seq") if mask is not None else P())
    mapped = jax.shard_map(fn, mesh=mesh, in_specs=specs, out_specs=act,
                           check_vma=False)
    args = [jax.device_put(x, NamedSharding(mesh, s))
            for x, s in zip((q, k, v), (act,) * 3)]
    m = (jax.device_put(mask, NamedSharding(mesh, P("data", "seq")))
         if mask is not None else None)
    return jax.jit(mapped)(*args, m)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_full(seq_mesh, causal):
    """impl='pallas': flash-kernel stages + logsumexp merge vs the full
    reference — the VERDICT round-4 #3 path (ring is the sp fallback when
    heads don't divide the axis, so it must not be byte-penalized)."""
    q, k, v = _qkv()
    mask = None if causal else _padding_mask()

    def fn(q, k, v, m):
        return seq_parallel.ring_attention(q, k, v, axis="seq", mask=m,
                                           causal=causal, impl="pallas")

    got = _run_sharded_novma(fn, seq_mesh, q, k, v, mask)
    want = _reference(q, k, v, mask=mask, causal=causal)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_ring_flash_causal_with_padding_mask(seq_mesh):
    """Causal + key padding composed: the diagonal stage uses the kernel's
    tri mask AND the rotated padding mask simultaneously."""
    q, k, v = _qkv()
    mask = _padding_mask()

    def fn(q, k, v, m):
        return seq_parallel.ring_attention(q, k, v, axis="seq", mask=m,
                                           causal=True, impl="pallas")

    got = _run_sharded_novma(fn, seq_mesh, q, k, v, mask)
    want = _reference(q, k, v, mask=mask, causal=True)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_gradients(seq_mesh, causal):
    """dq/dk/dv through the flash stages, the lse-cotangent fold
    (flash_attention._flash_lse_vjp_bwd's delta correction) and the
    stage-merge autodiff must match full attention."""
    q, k, v = _qkv(b=2, s=32, n=2, d=8)
    mask = None if causal else _padding_mask(b=2, s=32, seed=3)

    def loss_ring(q, k, v):
        def fn(q, k, v, m):
            return seq_parallel.ring_attention(q, k, v, axis="seq", mask=m,
                                               causal=causal, impl="pallas")
        act = P("data", "seq")
        mspec = P("data", "seq") if mask is not None else P()
        mapped = jax.shard_map(fn, mesh=seq_mesh,
                               in_specs=(act, act, act, mspec),
                               out_specs=act, check_vma=False)
        return jnp.sum(mapped(q, k, v, mask) ** 2)

    def loss_full(q, k, v):
        return jnp.sum(_reference(q, k, v, mask=mask, causal=causal) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(gr, gf, atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name}")


def test_ring_flash_fully_masked_rows(seq_mesh):
    """All-padding batch entries: lse stays NEG_INF through every merge
    and the output is exactly zero (same contract as the XLA path)."""
    q, k, v = _qkv(b=4, s=64)
    mask = jnp.concatenate([jnp.zeros((2, 64), jnp.int32),
                            jnp.ones((2, 64), jnp.int32)])

    def fn(q, k, v, m):
        return seq_parallel.ring_attention(q, k, v, axis="seq", mask=m,
                                           impl="pallas")

    got = np.asarray(jax.device_get(
        _run_sharded_novma(fn, seq_mesh, q, k, v, mask)))
    np.testing.assert_array_equal(got[:2], np.zeros_like(got[:2]))
    assert float(np.max(np.abs(got[2:]))) > 0


def test_ring_flash_unsupported_shape_falls_back(seq_mesh):
    """Local chunk not sublane-aligned (s=40 over 4 devices -> c=10): the
    pallas request silently uses the XLA stages, still exact."""
    q, k, v = _qkv(s=40)

    def fn(q, k, v, m):
        return seq_parallel.ring_attention(q, k, v, axis="seq",
                                           causal=True, impl="pallas")

    got = _run_sharded(fn, seq_mesh, q, k, v, None)
    want = _reference(q, k, v, causal=True)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_ulysses_head_divisibility(seq_mesh):
    q, k, v = _qkv(n=3)  # 3 heads not divisible by seq=4

    def fn(q, k, v, m):
        return seq_parallel.ulysses_attention(q, k, v, axis="seq")

    with pytest.raises(ValueError, match="heads"):
        _run_sharded(fn, seq_mesh, q, k, v, None)
