"""End-to-end harness tests: each workload config's graph runs a few steps on
the fake cluster; smoke config converges; checkpoint resume continues exactly."""

import jax
import numpy as np
import pytest

from tpuframe import train as train_mod
from tpuframe.utils import get_config
from tpuframe.utils.config import WORKLOADS


class TestConfigs:
    def test_all_workloads_defined(self):
        # the five reference configs [B:6-12] + smoke
        assert {"mnist_single", "cifar10_resnet18", "imagenet_resnet50",
                "glue_bert", "imagenet_resnet50_pod"} <= set(WORKLOADS)

    def test_overrides(self):
        cfg = get_config("smoke").with_overrides(total_steps=5)
        assert cfg.total_steps == 5
        with pytest.raises(ValueError):
            cfg.with_overrides(nonsense=1)

    def test_kwargs_overrides_merge_not_replace(self):
        # `--set model_kwargs={"moe_experts": 4}` on a tiny config must keep
        # the config's own kwargs (dropping them silently rebuilds the model
        # at full default size — a 219M-param lm_smoke).
        cfg = get_config("lm_smoke").with_overrides(
            model_kwargs={"moe_experts": 4})
        assert cfg.model_kwargs["moe_experts"] == 4
        assert cfg.model_kwargs["tiny"] is True  # preserved
        assert cfg.dataset_kwargs["seq_len"] == 64  # untouched field
        # per-key override still wins
        cfg2 = cfg.with_overrides(model_kwargs={"tiny": False})
        assert cfg2.model_kwargs["tiny"] is False
        assert cfg2.model_kwargs["moe_experts"] == 4
        # None deletes a key — the replace escape hatch
        cfg3 = cfg2.with_overrides(model_kwargs={"seq_mode": None})
        assert "seq_mode" not in cfg3.model_kwargs


class TestEndToEnd:
    def test_smoke_converges_single_process(self, tmp_path):
        cfg = get_config("smoke").with_overrides(
            distributed=False, total_steps=60, log_every=20, eval_every=30)
        metrics = train_mod.train(cfg)
        assert metrics["step"] == 60
        assert metrics["loss"] < 1.0  # synthetic MNIST is very learnable
        assert "eval_accuracy" in metrics

    def test_smoke_distributed_matches_single(self):
        """Golden invariant at harness level: same config, same seeds —
        distributed (8-chip) and single-process loss match closely."""
        cfg1 = get_config("smoke").with_overrides(distributed=False,
                                                  total_steps=20, log_every=20)
        cfg8 = get_config("smoke").with_overrides(total_steps=20, log_every=20)
        m1 = train_mod.train(cfg1)
        m8 = train_mod.train(cfg8)
        # dropout rngs differ (per-replica decorrelation), so allow slack
        assert abs(m1["loss"] - m8["loss"]) < 0.35, (m1["loss"], m8["loss"])

    @pytest.mark.parametrize("ckpt_async", [False, True])
    def test_resume_continues_exactly(self, tmp_path, ckpt_async):
        """Resume == straight run, for sync and async checkpointing (the
        async case proves the background write/restore round-trip, not
        mid-run commit timing — train() drains pending saves on exit)."""
        ck = str(tmp_path / "ck")
        base = get_config("smoke").with_overrides(
            ckpt_dir=ck, ckpt_every=10, total_steps=20, log_every=10,
            ckpt_async=ckpt_async)
        # run 20 steps straight through
        straight = train_mod.train(base)
        # run 10, stop, then "restart the job" and run to 20
        train_mod.train(base.with_overrides(total_steps=10,
                                            ckpt_dir=ck + "2"))
        part2 = train_mod.train(base.with_overrides(ckpt_dir=ck + "2"))
        assert part2["step"] == 20
        np.testing.assert_allclose(straight["loss"], part2["loss"],
                                   rtol=1e-4)

    def test_smoke_track_best_saves_best_eval(self, tmp_path):
        """track_best: a best/ checkpoint exists after training and holds
        the step with the lowest eval loss seen."""
        import json

        ck = tmp_path / "ck"
        cfg = get_config("smoke").with_overrides(
            distributed=False, total_steps=30, log_every=10, eval_every=10,
            ckpt_dir=str(ck), ckpt_every=100, track_best=True)
        train_mod.train(cfg)
        record = json.loads((ck / "best" / "metric.json").read_text())
        assert record["mode"] == "min" and record["step"] in (10, 20, 30)
        best_dirs = [p.name for p in (ck / "best").iterdir() if p.is_dir()]
        assert len(best_dirs) == 1

    def test_smoke_lars_optimizer_learns(self):
        """LARS (the large-batch ImageNet scaling recipe): layerwise
        trust-ratio optimizer runs through the harness and decreases
        loss; BN/bias leaves excluded from decay+adaptation."""
        cfg = get_config("smoke").with_overrides(
            distributed=False, optimizer="lars", base_lr=1.0,
            weight_decay=1e-4, total_steps=40, log_every=20, eval_every=100)
        metrics = train_mod.train(cfg)
        assert metrics["step"] == 40
        assert np.isfinite(metrics["loss"]) and metrics["loss"] < 2.0

    def test_cifar_resnet18_steps(self):
        cfg = get_config("cifar10_resnet18").with_overrides(
            total_steps=3, global_batch=16, warmup_steps=1, log_every=1,
            eval_every=3, eval_batches=1,
            dataset_kwargs={"synthetic_size": 64})
        metrics = train_mod.train(cfg)
        assert metrics["step"] == 3
        assert np.isfinite(metrics["loss"])

    def test_glue_bert_tiny_steps(self):
        """BERT path end-to-end — same graph as config 4, tiny dimensions
        (model_kwargs flow straight into BertConfig)."""
        cfg = get_config("glue_bert").with_overrides(
            total_steps=2, global_batch=8, warmup_steps=1, log_every=1,
            eval_every=2, eval_batches=1,
            dataset_kwargs={"synthetic_size": 32, "seq_len": 32,
                            "vocab_size": 512},
            model_kwargs={"vocab_size": 512, "hidden_size": 64,
                          "num_layers": 2, "num_heads": 2,
                          "intermediate_size": 128, "max_position": 32})
        metrics = train_mod.train(cfg)
        assert metrics["step"] == 2
        assert np.isfinite(metrics["loss"])

    def test_mnist_single_config_runs(self):
        cfg = get_config("mnist_single").with_overrides(
            total_steps=4, log_every=2, eval_every=4, eval_batches=1,
            dataset_kwargs={"synthetic_size": 256})
        metrics = train_mod.train(cfg)
        assert metrics["step"] == 4

    def test_cli_main(self, capsys):
        metrics = train_mod.main([
            "--config", "smoke", "--set", "total_steps=4",
            "--set", "log_every=2", "--set", "eval_every=4",
            "--set", "eval_batches=1"])
        assert metrics["step"] == 4
        out = capsys.readouterr().out
        assert "[tpuframe] done" in out
