"""int8-quantized gradient reduction (quantwire.all_reduce_mean +
hvd.DistributedOptimizer(compression="int8"); the removed
collectives.quantized_mean alias must raise) — the EQuARX-style wire format
(SURVEY.md §3b ring-allreduce row; PAPERS.md:7; arXiv:2506.17615).

Uses the legacy ``jax.experimental.shard_map`` idiom with
``check_rep=False`` so the suite runs on pre-vma jax too: inputs are
closed over and varied per replica via ``lax.axis_index``.
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from tpuframe.parallel import collectives, hvd, mesh as mesh_lib
from tpuframe.parallel import quantwire


def _per_replica(mesh, fn, tree, axes=("data",)):
    """Run ``fn`` per replica on ``tree`` scaled by (1 + linear replica
    index) — every leaf genuinely varies across the mesh."""
    def body():
        i = collectives._linear_index(axes).astype(jnp.float32)
        return fn(jax.tree.map(lambda l: l * (1.0 + i), tree))

    m = shard_map(body, mesh=mesh, in_specs=(), out_specs=P(),
                  check_rep=False)
    return jax.jit(m)()


def test_all_reduce_mean_error_bound(mesh8):
    rng = np.random.default_rng(0)
    # 2048/4096 elems: above MIN_QUANT_ELEMS, so the quantized path runs.
    tree = {"w": jnp.asarray(rng.normal(size=(64, 64)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(2048,)), jnp.float32)}

    exact = _per_replica(
        mesh8, lambda t: jax.tree.map(
            lambda l: lax.pmean(l, ("data",)), t), tree)
    quant = _per_replica(
        mesh8, lambda t: quantwire.all_reduce_mean(t, ("data",)), tree)

    for k in tree:
        # Replica r contributes g*(1+r), worst magnitude 8|g|.  Two
        # quantizations touch each value (reduce-scatter contribution +
        # the all-gather of the reduced shard), each with per-block
        # scale <= blockmax/127 and error <= scale/2, so
        # |mean err| <= 2 * 8*max|g| / 254 — a hard ABSOLUTE bound
        # (scale-proportional, so no rtol check).
        bound = 16 * float(jnp.max(jnp.abs(tree[k]))) / 254 + 1e-6
        err = np.max(np.abs(np.asarray(quant[k]) - np.asarray(exact[k])))
        assert err <= bound, (k, err, bound)
        # direction preserved: gradients still point the same way
        e, q = np.asarray(exact[k]).ravel(), np.asarray(quant[k]).ravel()
        cos = float(e @ q / (np.linalg.norm(e) * np.linalg.norm(q)))
        assert cos > 0.999, (k, cos)


def test_small_leaves_fall_back_to_exact_fp(mesh8):
    """Leaves under MIN_QUANT_ELEMS take the fp pmean path — bitwise
    exact, no quantization noise on biases and norm scales."""
    rng = np.random.default_rng(3)
    tree = {"b": jnp.asarray(rng.normal(size=(16,)), jnp.float32)}
    exact = _per_replica(
        mesh8, lambda t: jax.tree.map(
            lambda l: lax.pmean(l, ("data",)), t), tree)
    out = _per_replica(
        mesh8, lambda t: quantwire.all_reduce_mean(t, ("data",)), tree)
    np.testing.assert_array_equal(np.asarray(out["b"]),
                                  np.asarray(exact["b"]))


def test_quantize_roundtrip_error_bound_per_block():
    """Local quantize/dequantize round trip: error <= blockmax/254 for
    every block size, zeros exact."""
    rng = np.random.default_rng(7)
    flat = jnp.asarray(rng.normal(size=(4096,)) * 3.0, jnp.float32)
    for block in (64, 128, 256, 512):
        q, scales = quantwire.quantize_blocks(flat, block)
        assert q.dtype == jnp.int8
        back = quantwire.dequantize_blocks(q, scales).reshape(-1)
        err = np.abs(np.asarray(back) - np.asarray(flat))
        blockmax = np.max(
            np.abs(np.asarray(flat)).reshape(-1, block), axis=1)
        bound = np.repeat(blockmax / 254 * 1.001, block) + 1e-7
        assert np.all(err <= bound), (block, err.max())
    zq, zs = quantwire.quantize_blocks(jnp.zeros((256,), jnp.float32), 256)
    np.testing.assert_array_equal(
        np.asarray(quantwire.dequantize_blocks(zq, zs)), 0.0)


def test_quantized_mean_zero_and_sign(mesh8):
    tree = {"z": jnp.zeros((2048,), jnp.float32),
            "s": jnp.asarray(
                np.tile([-1.0, 1.0, -0.5, 0.5], 512), jnp.float32)}
    out = _per_replica(
        mesh8,
        lambda t: quantwire.all_reduce_mean(t, ("data",), min_elems=0),
        tree)
    np.testing.assert_array_equal(np.asarray(out["z"]), np.zeros(2048))
    assert np.all(np.sign(np.asarray(out["s"]))
                  == np.sign(np.asarray(tree["s"])))


def test_quantized_narrow_int_on_the_wire(mesh8):
    """The compiled program must actually move int8 — the wire
    compression claim, asserted in HLO: an s8 all-to-all (reduce-scatter
    phase) plus an s8 all-gather, and NO f32 all-reduce of the payload
    shape."""
    x = jnp.ones((64, 64), jnp.float32)

    def body():
        i = lax.axis_index("data").astype(jnp.float32)
        return quantwire.all_reduce_mean({"g": x * (1.0 + i)}, ("data",))

    txt = jax.jit(shard_map(body, mesh=mesh8, in_specs=(),
                            out_specs=P(), check_rep=False)
                  ).lower().compile().as_text()
    lines = txt.splitlines()
    assert any("all-to-all" in l and "s8[" in l for l in lines), \
        "no s8 all-to-all in HLO"
    assert any("all-gather" in l and "s8[" in l for l in lines), \
        "no s8 all-gather in HLO"
    assert not any("all-reduce" in l and "f32[4096]" in l for l in lines), \
        "payload-sized f32 all-reduce still present"


def test_removed_alias_raises_with_replacement():
    """collectives.quantized_mean is gone — the error must name the one
    remaining quantization seam so a stale call site self-documents its
    own migration."""
    tree = {"g": jnp.zeros((8,), jnp.float32)}
    with pytest.raises(RuntimeError, match="quantwire.all_reduce_mean"):
        collectives.quantized_mean(tree, axis="data")
    with pytest.raises(RuntimeError, match="TPUFRAME_WIRE_FORMAT"):
        collectives.quantized_mean(tree, axis="data")


def test_distributed_optimizer_int8_trains(mesh8):
    import optax

    rng = np.random.default_rng(1)
    params = {"w": jnp.asarray(rng.normal(size=(16, 16)) * 0.3, jnp.float32)}
    x = rng.normal(size=(16, 16)).astype(np.float32)
    t = np.tanh(rng.normal(size=(16, 16))).astype(np.float32)
    tx = hvd.DistributedOptimizer(optax.sgd(0.2), compression="int8")

    # hvd-style manual step: per-replica local grads (the batch shard
    # differs per replica), DistributedOptimizer's quantized mean is the
    # only reduction.
    def body(p, opt, b):
        def local_loss(p):
            return jnp.mean((jnp.tanh(b["x"] @ p["w"]) - b["t"]) ** 2)

        g = jax.grad(local_loss)(p)
        up, opt = tx.update(g, opt, p)
        return jax.tree.map(lambda a, u: a + u, p, up), opt

    mapped = jax.jit(shard_map(
        body, mesh=mesh8,
        in_specs=(P(), P(), P(("data", "fsdp"))),
        out_specs=(P(), P()), check_rep=False))
    batch = jax.tree.map(
        lambda a: jax.device_put(a, mesh_lib.batch_sharding(mesh8)),
        {"x": x, "t": t})
    opt = tx.init(params)
    losses = []
    p = params
    for _ in range(40):
        loss = float(jnp.mean(
            (jnp.tanh(jnp.asarray(x) @ p["w"]) - jnp.asarray(t)) ** 2))
        losses.append(loss)
        p, opt = mapped(p, opt, batch)
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    # near-monotone: block-quantization noise may wiggle a step slightly
    assert all(b <= a + 1e-3 for a, b in zip(losses, losses[1:]))


def test_int8_requires_average():
    import optax

    tx = hvd.DistributedOptimizer(optax.sgd(0.1), compression="int8",
                                  average=False)
    with pytest.raises(ValueError, match="int8"):
        tx.update({"w": jnp.ones(3)}, tx.init({"w": jnp.ones(3)}))


def test_quantized_mean_multi_axis(mesh42):
    """Reduction over a 2-D mesh (data=4 x model=2): the quantized mean
    must divide by the full 8-replica world, matching pmean over both
    axes within the quantizer's bound."""
    rng = np.random.default_rng(9)
    tree = {"g": jnp.asarray(rng.normal(size=(2048,)), jnp.float32)}
    axes = ("data", "model")
    exact = _per_replica(
        mesh42, lambda t: jax.tree.map(
            lambda l: lax.pmean(l, axes), t), tree, axes=axes)
    quant = _per_replica(
        mesh42, lambda t: quantwire.all_reduce_mean(t, axes), tree,
        axes=axes)
    bound = 16 * float(jnp.max(jnp.abs(tree["g"]))) / 254 + 1e-6
    err = np.max(np.abs(np.asarray(quant["g"]) - np.asarray(exact["g"])))
    assert err <= bound, (err, bound)
