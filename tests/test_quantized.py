"""int8-quantized gradient reduction (collectives.quantized_mean +
hvd.DistributedOptimizer(compression="int8")) — the EQuARX-style wire
option (SURVEY.md §3b ring-allreduce row; PAPERS.md:7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from tpuframe.parallel import collectives, hvd, mesh as mesh_lib


def _per_replica(mesh, fn, tree):
    def body(t):
        t = jax.tree.map(
            lambda l: l * (1.0 + lax.axis_index("data").astype(jnp.float32)),
            jax.tree.map(lambda l: lax.pcast(l, ("data",), to="varying"), t))
        return fn(t)

    return jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P(),
                                 out_specs=P()))(tree)


def test_quantized_mean_error_bound(mesh8):
    rng = np.random.default_rng(0)
    tree = {"w": jnp.asarray(rng.normal(size=(32, 16)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(16,)), jnp.float32)}

    exact = _per_replica(
        mesh8, lambda t: collectives.average_gradients(t, axis="data"), tree)
    quant = _per_replica(
        mesh8, lambda t: collectives.quantized_mean(t, axis="data"), tree)

    for k in tree:
        # replica r contributes g*(1+r); worst contribution magnitude 8|g|;
        # shared scale s = max|contribution|/127, per-contribution error
        # <= s/2, so |mean err| <= 8*max|g|/254 — the quantizer's hard
        # bound (error is ABSOLUTE / scale-proportional, so no rtol check).
        bound = 8 * float(jnp.max(jnp.abs(tree[k]))) / 254 + 1e-6
        err = np.max(np.abs(np.asarray(quant[k]) - np.asarray(exact[k])))
        assert err <= bound, (k, err, bound)
        # direction preserved: gradients still point the same way
        e, q = np.asarray(exact[k]).ravel(), np.asarray(quant[k]).ravel()
        cos = float(e @ q / (np.linalg.norm(e) * np.linalg.norm(q)))
        assert cos > 0.999, (k, cos)


def test_quantized_mean_zero_and_sign(mesh8):
    tree = {"z": jnp.zeros((8,), jnp.float32),
            "s": jnp.asarray([-1.0, 1.0, -0.5, 0.5], jnp.float32)}
    out = _per_replica(
        mesh8, lambda t: collectives.quantized_mean(t, axis="data"), tree)
    np.testing.assert_array_equal(np.asarray(out["z"]), np.zeros(8))
    assert np.all(np.sign(np.asarray(out["s"]))
                  == np.sign(np.asarray(tree["s"])))


def test_quantized_mean_narrow_int_on_the_wire(mesh8):
    """The compiled program must actually all-reduce int16 — the wire
    compression claim, asserted in HLO."""
    x = {"g": jnp.ones((64, 64), jnp.float32)}

    def body(t):
        t = jax.tree.map(
            lambda l: lax.pcast(l, ("data",), to="varying"), t)
        return collectives.quantized_mean(t, axis="data")

    txt = jax.jit(jax.shard_map(
        body, mesh=mesh8, in_specs=P(), out_specs=P())).lower(x).compile(
        ).as_text()
    assert any("all-reduce" in line and "s16[64,64]" in line
               for line in txt.splitlines()), "no int16 all-reduce in HLO"


def test_distributed_optimizer_int8_trains(mesh8):
    import optax

    from tpuframe.parallel import step as step_lib

    rng = np.random.default_rng(1)
    params = {"w": jnp.asarray(rng.normal(size=(16, 16)) * 0.3, jnp.float32)}
    x = rng.normal(size=(16, 16)).astype(np.float32)
    t = np.tanh(rng.normal(size=(16, 16))).astype(np.float32)
    tx = hvd.DistributedOptimizer(optax.sgd(0.2), compression="int8")

    def loss_fn(p, ms, b, r):
        return jnp.mean((jnp.tanh(b["x"] @ p["w"]) - b["t"]) ** 2), ({}, {})

    # hvd-style manual step: per-replica local grads (pcast-varying params),
    # DistributedOptimizer's quantized mean is the only reduction.
    def body(p, opt, b):
        g = jax.grad(lambda p: loss_fn(
            jax.tree.map(lambda a: lax.pcast(a, ("data",), to="varying"), p),
            {}, b, None)[0])(p)
        up, opt = tx.update(g, opt, p)
        return jax.tree.map(lambda a, u: a + u, p, up), opt

    mapped = jax.jit(jax.shard_map(
        body, mesh=mesh8,
        in_specs=(P(), P(), P(("data", "fsdp"))),
        out_specs=(P(), P())))
    batch = jax.tree.map(
        lambda a: jax.device_put(a, mesh_lib.batch_sharding(mesh8)),
        {"x": x, "t": t})
    opt = tx.init(params)
    losses = []
    p = params
    for _ in range(40):
        loss = float(jnp.mean(
            (jnp.tanh(jnp.asarray(x) @ p["w"]) - jnp.asarray(t)) ** 2))
        losses.append(loss)
        p, opt = mapped(p, opt, batch)
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    assert all(b <= a + 1e-4 for a, b in zip(losses, losses[1:]))  # monotone


def test_int8_requires_average():
    import optax

    tx = hvd.DistributedOptimizer(optax.sgd(0.1), compression="int8",
                                  average=False)
    with pytest.raises(ValueError, match="int8"):
        tx.update({"w": jnp.ones(3)}, tx.init({"w": jnp.ones(3)}))


def test_quantized_mean_mixed_vma_divides_presummed_axes():
    """A leaf varying on 'data' but presummed over 'fsdp' must be divided
    by BOTH axis sizes (average_gradients semantics) — switching
    compression=None to "int8" must not change effective LR."""
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=4, fsdp=2))
    g = jnp.full((8,), 4.0, jnp.float32)

    def body(t):
        t = jax.tree.map(
            lambda l: lax.pcast(l, ("data",), to="varying"), t)
        exact = collectives.average_gradients(t, axis=("data", "fsdp"))
        quant = collectives.quantized_mean(t, axis=("data", "fsdp"))
        return exact, quant

    exact, quant = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=P(), out_specs=P()))({"g": g})
    np.testing.assert_allclose(np.asarray(quant["g"]),
                               np.asarray(exact["g"]), atol=0.05)
    # value check: identical contributions of 4.0, mean over data=4 then
    # /fsdp=2 presummed divisor -> 2.0
    np.testing.assert_allclose(np.asarray(exact["g"]), 2.0)
