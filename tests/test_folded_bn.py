"""FoldedBatchNorm parity vs nn.BatchNorm (tpuframe/models/folded_bn.py).

The census-driven BN must be a numerical drop-in: identical statistics,
identical running-stat updates, and f32 outputs matching flax's to float
tolerance.  In bf16 the activation-sized math deliberately rounds the
per-channel affine before the FMA — bounded by bf16 eps — which is the
entire point (no f32 activation-sized values in the compiled step).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuframe.models.folded_bn import FoldedBatchNorm


def _pair(dtype):
    ref = nn.BatchNorm(use_running_average=False, momentum=0.9,
                       epsilon=1e-5, dtype=dtype, param_dtype=jnp.float32)
    fold = FoldedBatchNorm(use_running_average=False, momentum=0.9,
                           epsilon=1e-5, dtype=dtype,
                           param_dtype=jnp.float32)
    return ref, fold


def _random_variables(rng, c):
    # Non-trivial scale/bias/running stats so the affine actually matters.
    return {
        "params": {"scale": jnp.asarray(rng.uniform(0.5, 2.0, c), jnp.float32),
                   "bias": jnp.asarray(rng.normal(0, 1, c), jnp.float32)},
        "batch_stats": {"mean": jnp.asarray(rng.normal(0, 1, c), jnp.float32),
                        "var": jnp.asarray(rng.uniform(0.5, 2, c), jnp.float32)},
    }


class TestParity:
    def test_f32_train_output_and_stats(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(3.0, 2.0, (8, 6, 6, 16)), jnp.float32)
        ref, fold = _pair(jnp.float32)
        v = _random_variables(rng, 16)
        y_ref, m_ref = ref.apply(v, x, mutable=["batch_stats"])
        y_fold, m_fold = fold.apply(v, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y_fold), np.asarray(y_ref),
                                   rtol=2e-5, atol=2e-5)
        for k in ("mean", "var"):
            np.testing.assert_allclose(
                np.asarray(m_fold["batch_stats"][k]),
                np.asarray(m_ref["batch_stats"][k]), rtol=1e-5, atol=1e-6)

    def test_f32_eval_output(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(0, 1, (4, 5, 5, 8)), jnp.float32)
        v = _random_variables(rng, 8)
        ref = nn.BatchNorm(use_running_average=True, epsilon=1e-5,
                           dtype=jnp.float32)
        fold = FoldedBatchNorm(use_running_average=True, epsilon=1e-5,
                               dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(fold.apply(v, x)),
                                   np.asarray(ref.apply(v, x)),
                                   rtol=2e-5, atol=2e-5)

    def test_bf16_close_to_f32_reference(self):
        # The bf16 path rounds the per-channel affine once; the output must
        # stay within bf16-eps-class distance of the exact f32 result.
        rng = np.random.default_rng(2)
        x32 = jnp.asarray(rng.normal(1.0, 2.0, (8, 4, 4, 32)), jnp.float32)
        v = _random_variables(rng, 32)
        ref = nn.BatchNorm(use_running_average=False, epsilon=1e-5,
                           dtype=jnp.float32)
        y_exact, _ = ref.apply(v, x32, mutable=["batch_stats"])
        fold = FoldedBatchNorm(use_running_average=False, epsilon=1e-5,
                               dtype=jnp.bfloat16)
        y_b, _ = fold.apply(v, x32.astype(jnp.bfloat16),
                            mutable=["batch_stats"])
        err = np.abs(np.asarray(y_b, np.float32) - np.asarray(y_exact))
        scale = np.abs(np.asarray(y_exact)).max()
        assert err.max() <= 0.03 * max(scale, 1.0), err.max()

    def test_large_mean_small_std_channel(self):
        # The cancellation regime: |mean| >> std.  The statistics must be
        # computed from the f32-CONVERTED input: squaring in bf16 first
        # makes E[x^2]-E[x]^2 quantization noise (x~50 has bf16 step
        # ~0.2 >> std 0.05), collapsing the variance toward the eps clamp.
        # The exact property: folded's batch variance equals the f64
        # variance OF THE bf16-QUANTIZED INPUT (input rounding is
        # unavoidable; destroying the remaining signal in the square is
        # the bug this pins).
        rng = np.random.default_rng(5)
        x32 = rng.normal(50.0, 0.05, (64, 4, 4, 8)).astype(np.float32)
        xb = jnp.asarray(x32, jnp.bfloat16)
        fold = FoldedBatchNorm(use_running_average=False, epsilon=1e-5,
                               momentum=0.9, dtype=jnp.bfloat16)
        v = fold.init(jax.random.key(0), xb)
        _, m = fold.apply(v, xb, mutable=["batch_stats"])
        # init stats are mean=0/var=1; one update mixes with momentum 0.9.
        var = (np.asarray(m["batch_stats"]["var"], np.float64) - 0.9) / 0.1
        # Parity target is FLAX on the same input: f32 E[x^2]-E[x]^2 at
        # |mean|~50 carries ~f32-eps*mean^2 noise for both modules alike;
        # the bf16-squaring bug this pins loses the signal entirely.
        ref = nn.BatchNorm(use_running_average=False, epsilon=1e-5,
                           momentum=0.9, dtype=jnp.bfloat16)
        _, mr = ref.apply(v, xb, mutable=["batch_stats"])
        want = (np.asarray(mr["batch_stats"]["var"], np.float64) - 0.9) / 0.1
        np.testing.assert_allclose(var, want, rtol=1e-3, atol=1e-6)
        assert (var > 1e-4).all()  # not collapsed to the eps clamp

    def test_init_variable_layout_matches_flax(self):
        x = jnp.zeros((2, 4, 4, 8), jnp.float32)
        ref, fold = _pair(jnp.float32)
        vr = ref.init(jax.random.key(0), x)
        vf = fold.init(jax.random.key(0), x)
        assert jax.tree.map(jnp.shape, vf) == jax.tree.map(jnp.shape, vr)

    def test_f32_activation_values_reduced_in_bf16_graph(self):
        # The module's reason to exist: the bf16 apply's only
        # activation-shaped f32 values are the two stats-reduction converts
        # (which XLA fuses into the reduces — no HBM materialization; the
        # offline AOT census is the byte-level proof), while nn.BatchNorm
        # runs the whole normalize chain in f32.
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(0, 1, (16, 8, 8, 32)), jnp.bfloat16)

        def f32_activation_eqns(mod):
            v = mod.init(jax.random.key(0), x)
            jaxpr = jax.make_jaxpr(
                lambda vv, xx: mod.apply(vv, xx, mutable=["batch_stats"]))(v, x)
            out = []
            for eqn in jaxpr.jaxpr.eqns:
                for var in eqn.outvars:
                    aval = var.aval
                    if (getattr(aval, "dtype", None) == jnp.float32
                            and getattr(aval, "ndim", 0) == 4
                            and aval.shape[0] == 16):
                        out.append(eqn.primitive.name)
            return out

        fold = f32_activation_eqns(
            FoldedBatchNorm(use_running_average=False, dtype=jnp.bfloat16))
        ref = f32_activation_eqns(
            nn.BatchNorm(use_running_average=False, dtype=jnp.bfloat16))
        # Only the stats-chain values (convert + square, both feeding the
        # reduces) — no f32 normalize arithmetic.
        assert set(fold) <= {"convert_element_type", "square",
                             "integer_pow"}, fold
        assert len(fold) <= 3
        assert len(ref) > len(fold)  # the census finding


class TestInResNet:
    @pytest.mark.slow
    def test_resnet18_forward_backward_folded(self):
        from tpuframe import models
        from tpuframe.models import losses

        model = models.ResNet18(num_classes=10, bn="folded",
                                dtype=jnp.bfloat16)
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.normal(0, 1, (4, 32, 32, 3)), jnp.bfloat16)
        y = jnp.asarray(rng.integers(0, 10, 4), jnp.int32)
        v = model.init(jax.random.key(0), x)

        def loss_fn(params):
            logits, mut = model.apply({"params": params,
                                       "batch_stats": v["batch_stats"]},
                                      x, train=True, mutable=["batch_stats"])
            return losses.softmax_cross_entropy(logits, y)

        loss, grads = jax.value_and_grad(loss_fn)(v["params"])
        assert np.isfinite(float(loss))
        gn = sum(float(jnp.sum(jnp.abs(g))) for g in jax.tree.leaves(grads))
        assert np.isfinite(gn) and gn > 0

    def test_bad_bn_name_raises(self):
        from tpuframe import models

        with pytest.raises(ValueError, match="unknown bn"):
            models.ResNet18(bn="nope").init(
                jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
