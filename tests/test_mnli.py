"""MNLI — the second GLUE task (SURVEY.md §1 config 4 [B:10]): 3-way
sentence-PAIR classification.  What's new vs SST-2, and therefore what
these tests pin: header-located tsv parsing with '-' label drops, the
``[CLS] a [SEP] b [SEP]`` pair encoding with 0/1 ``token_type_ids``
(WordPiece parity vs HF for pairs), and the 3-class BERT head flowing
through the harness.
"""

import numpy as np
import pytest

from tpuframe.data import datasets
from tpuframe.utils import get_config

from tpuframe import train as train_mod


MNLI_TSV = "\t".join([
    "index", "promptID", "pairID", "genre", "sentence1_binary_parse",
    "sentence2_binary_parse", "sentence1_parse", "sentence2_parse",
    "sentence1", "sentence2", "label1", "gold_label"]) + "\n" + "\n".join([
    "\t".join(["0", "1", "1e", "fiction", "(p)", "(h)", "(p)", "(h)",
               "the cat sat on the mat", "a cat is sitting",
               "entailment", "entailment"]),
    "\t".join(["1", "2", "2c", "travel", "(p)", "(h)", "(p)", "(h)",
               "the train left at noon", "the train never ran",
               "contradiction", "contradiction"]),
    "\t".join(["2", "3", "3n", "letters", "(p)", "(h)", "(p)", "(h)",
               "she wrote a letter", "she wrote to her brother",
               "neutral", "neutral"]),
    # No annotator consensus — must be dropped, not trained on.
    "\t".join(["3", "4", "4x", "fiction", "(p)", "(h)", "(p)", "(h)",
               "ambiguous premise", "ambiguous hypothesis",
               "neutral", "-"]),
])


class TestMnliTsv:
    @pytest.fixture()
    def mnli_dir(self, tmp_path):
        (tmp_path / "train.tsv").write_text(MNLI_TSV)
        (tmp_path / "dev_matched.tsv").write_text(MNLI_TSV)
        return str(tmp_path)

    def test_parse_columns_by_header_and_drop_dash(self, mnli_dir):
        train, dev = datasets.glue_mnli(mnli_dir, seq_len=32)
        assert len(train) == 3  # the '-' row is gone
        np.testing.assert_array_equal(train.columns["label"], [0, 2, 1])

    def test_hash_fallback_pair_encoding(self, mnli_dir):
        train, _ = datasets.glue_mnli(mnli_dir, seq_len=32)
        ids = train.columns["input_ids"]
        types = train.columns["token_type_ids"]
        mask = train.columns["attention_mask"]
        assert (ids[:, 0] == 101).all()
        for i in range(3):
            seps = np.flatnonzero(ids[i] == 102)
            assert len(seps) == 2  # [CLS] a [SEP] b [SEP]
            # Segment ids: 0 through the first [SEP], 1 from there to the
            # second [SEP], 0 again in the padding.
            assert types[i, :seps[0] + 1].max() == 0
            assert types[i, seps[0] + 1:seps[1] + 1].min() == 1
            assert types[i, seps[1] + 1:].max() == 0
            assert mask[i, :seps[1] + 1].all() and not mask[i, seps[1] + 1:].any()


class TestMnliSynthetic:
    def test_shapes_and_learnable_signal(self):
        train, ev = datasets.glue_mnli(None, seq_len=64, synthetic_size=128)
        assert len(train) == 128
        assert set(np.unique(train.columns["label"])) <= {0, 1, 2}
        # Signal token encodes the label (the learnability hook).
        np.testing.assert_array_equal(
            train.columns["input_ids"][:, 1], 200 + train.columns["label"])
        # Pair structure: token_type_ids 1-segment sits inside the mask.
        types, mask = train.columns["token_type_ids"], train.columns["attention_mask"]
        assert (types <= mask).all()
        assert types.any(axis=1).all()  # every row HAS a B segment


class TestWordPiecePairParity:
    def test_pair_encoding_matches_hf(self, tmp_path):
        transformers = pytest.importorskip("transformers")
        from tpuframe.data.wordpiece import WordPieceTokenizer

        vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "the", "cat",
                 "sat", "on", "mat", "a", "is", "sitting", "##s", "dog"]
        vpath = tmp_path / "vocab.txt"
        vpath.write_text("\n".join(vocab) + "\n")
        ours = WordPieceTokenizer(str(vpath))
        theirs = transformers.BertTokenizer(str(vpath), do_lower_case=True)

        pairs = [("the cat sat on the mat", "a cat is sitting"),
                 ("the cats sat", "a dog is sitting on the mat"),
                 ("the " * 30 + "cat", "dog " * 30)]  # forces pair truncation
        enc_a = ours(pairs, max_length=24)
        enc_b = theirs([p[0] for p in pairs], [p[1] for p in pairs],
                       padding="max_length", truncation=True, max_length=24,
                       return_tensors="np")
        for key in ("input_ids", "attention_mask", "token_type_ids"):
            np.testing.assert_array_equal(enc_a[key], enc_b[key], err_msg=key)


STSB_TSV = "\t".join([
    "index", "genre", "filename", "year", "old_index", "source1", "source2",
    "sentence1", "sentence2", "score"]) + "\n" + "\n".join([
    "\t".join(["0", "main-captions", "f", "2012", "1", "n", "n",
               "a plane is taking off", "an air plane is taking off", "5.0"]),
    "\t".join(["1", "main-captions", "f", "2012", "2", "n", "n",
               "a man is playing a flute", "a man is eating food", "0.8"]),
    # Unscored row (test-set shape) — must be dropped.
    "\t".join(["2", "main-captions", "f", "2012", "3", "n", "n",
               "x", "y", ""]),
])


class TestStsb:
    def test_tsv_scores_parsed_and_unscored_dropped(self, tmp_path):
        (tmp_path / "train.tsv").write_text(STSB_TSV)
        (tmp_path / "dev.tsv").write_text(STSB_TSV)
        train, _ = datasets.glue_stsb(str(tmp_path), seq_len=32)
        assert len(train) == 2
        assert train.columns["label"].dtype == np.float32
        np.testing.assert_allclose(train.columns["label"], [5.0, 0.8])

    def test_crlf_tsv(self, tmp_path):
        """CRLF GLUE files: header names must not carry \\r (the last
        column's lookup broke before splitlines) and labels must parse."""
        (tmp_path / "train.tsv").write_text(STSB_TSV.replace("\n", "\r\n"))
        (tmp_path / "dev.tsv").write_text(STSB_TSV.replace("\n", "\r\n"))
        train, _ = datasets.glue_stsb(str(tmp_path), seq_len=32)
        np.testing.assert_allclose(train.columns["label"], [5.0, 0.8])

    def test_synthetic_score_signal(self):
        train, _ = datasets.glue_stsb(None, seq_len=64, synthetic_size=128)
        labels = train.columns["label"]
        assert labels.dtype == np.float32
        assert 0.0 <= labels.min() and labels.max() <= 5.0
        # Score is decodable from the signal token — the learnability hook.
        np.testing.assert_allclose(
            labels, (train.columns["input_ids"][:, 1] - 200) / 2.0)

    def test_float_labels_survive_bf16_infeed_cast(self):
        """The cast_keys contract end-to-end: under a bf16 config the
        loader may cast float INPUTS, but float TARGETS must stay f32."""
        import jax.numpy as jnp

        from tpuframe.data import ShardedLoader

        train, _ = datasets.glue_stsb(None, seq_len=32, synthetic_size=64)
        batch = next(ShardedLoader(train, 16, shuffle=False,
                                   cast_floats=jnp.bfloat16).epoch(0))
        assert batch["label"].dtype == jnp.float32

    def test_bert_stsb_regression_tiny_steps(self):
        cfg = get_config("glue_bert_stsb").with_overrides(
            total_steps=2, global_batch=8, warmup_steps=1, log_every=1,
            eval_every=2, eval_batches=1,
            dataset_kwargs={"synthetic_size": 32, "seq_len": 32,
                            "vocab_size": 512},
            model_kwargs={"vocab_size": 512, "hidden_size": 64,
                          "num_layers": 2, "num_heads": 2,
                          "intermediate_size": 128, "max_position": 32})
        assert cfg.model_kwargs["num_classes"] == 1
        metrics = train_mod.train(cfg)
        assert metrics["step"] == 2
        assert np.isfinite(metrics["loss"])
        assert "mse" in metrics and "eval_mse" in metrics
        assert -1.0 <= metrics["eval_pearson"] <= 1.0
        assert not any(k.startswith("eval__m_") for k in metrics)

    def test_finalize_eval_pearson_matches_numpy(self):
        rng = np.random.default_rng(0)
        pred = rng.normal(2.0, 1.5, size=256)
        y = 0.7 * pred + rng.normal(0, 0.5, size=256)
        avg = {"_m_pred": pred.mean(), "_m_y": y.mean(),
               "_m_pred2": (pred ** 2).mean(), "_m_y2": (y ** 2).mean(),
               "_m_py": (pred * y).mean(), "mse": 1.0}
        out = train_mod._finalize_eval(avg)
        np.testing.assert_allclose(out["pearson"], np.corrcoef(pred, y)[0, 1],
                                   rtol=1e-12)
        assert set(out) == {"pearson", "mse"}


class TestMnliHarness:
    def test_bert_mnli_tiny_steps(self):
        """The 3-class pair task end-to-end through the harness — same
        graph as config glue_bert_mnli, tiny dimensions."""
        cfg = get_config("glue_bert_mnli").with_overrides(
            total_steps=2, global_batch=8, warmup_steps=1, log_every=1,
            eval_every=2, eval_batches=1,
            dataset_kwargs={"synthetic_size": 32, "seq_len": 32,
                            "vocab_size": 512},
            model_kwargs={"vocab_size": 512, "hidden_size": 64,
                          "num_layers": 2, "num_heads": 2,
                          "intermediate_size": 128, "max_position": 32})
        assert cfg.model_kwargs["num_classes"] == 3  # merge kept the head
        metrics = train_mod.train(cfg)
        assert metrics["step"] == 2
        assert np.isfinite(metrics["loss"])


class TestCola:
    """CoLA: 4-column headerless TSV, binary labels, MCC eval metric."""

    def test_tsv_parse(self, tmp_path):
        tsv = ("gj04\t1\t\tThe sailors rode the breeze clear of the rocks.\n"
               "gj04\t0\t*\tThe car honked down the road.\n"
               "ab12\t1\t\tShort one.\n")
        for name in ("train.tsv", "dev.tsv"):
            (tmp_path / name).write_text(tsv)
        train, dev = datasets.glue_cola(str(tmp_path), seq_len=16)
        np.testing.assert_array_equal(train.columns["label"], [1, 0, 1])
        assert train.columns["input_ids"].shape == (3, 16)

    def test_mcc_finalize_matches_definition(self):
        from tpuframe.train import _finalize_eval

        # Rates from a known confusion matrix: tp=40 fp=10 tn=45 fn=5 /100.
        avg = {"_m_tp": 0.40, "_m_fp": 0.10, "_m_tn": 0.45, "_m_fn": 0.05,
               "accuracy": 0.85}
        out = _finalize_eval(avg)
        tp, fp, tn, fn = 40, 10, 45, 5
        want = (tp * tn - fp * fn) / (
            (tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)) ** 0.5
        assert abs(out["mcc"] - want) < 1e-12
        assert "_m_tp" not in out

    def test_degenerate_single_class_has_no_mcc(self):
        from tpuframe.train import _finalize_eval

        out = _finalize_eval({"_m_tp": 0.0, "_m_fp": 0.0, "_m_tn": 1.0,
                              "_m_fn": 0.0})
        assert "mcc" not in out

    @pytest.mark.slow
    def test_bert_cola_tiny_steps_reports_mcc(self):
        from tpuframe import train as train_mod
        from tpuframe.utils import get_config

        cfg = get_config("glue_bert_cola").with_overrides(
            total_steps=2, eval_every=2, eval_batches=2, global_batch=8,
            warmup_steps=1, log_every=1,
            model_kwargs={"vocab_size": 512, "hidden_size": 64,
                          "num_layers": 2, "num_heads": 2,
                          "intermediate_size": 128, "max_position": 32},
            dataset_kwargs={"synthetic_size": 64, "seq_len": 16,
                            "vocab_size": 512})
        metrics = train_mod.train(cfg)
        assert "eval_mcc" in metrics or "eval_accuracy" in metrics
