"""Benchmark: ResNet-50 training throughput, images/sec/chip.

The driver-defined metric (BASELINE.json:2): ResNet-50 images/sec/chip.
This runs the flagship model's full training step (fwd+bwd+update, bf16
compute) on the available chip(s) with synthetic ImageNet shapes, which
isolates accelerator throughput from input-pipeline effects.

``vs_baseline``: the reference's own numbers are unpublished (BASELINE.md —
`"published": {}` and the source mount was empty), so the anchor is the
Horovod-GPU era per-chip figure for this exact workload: ~360 images/sec on a
V100 with standard fp16/32 ResNet-50 training (MLPerf v0.6-era single-GPU
throughput; the Horovod paper's hardware class, PAPERS.md:8).
vs_baseline = value / 360.0.

Robustness (round-1 lesson — BENCH_r01.json was rc=124/parsed=null): progress
goes to stderr at every stage, batch/steps are env-tunable
(TPUFRAME_BENCH_BATCH / _STEPS / _WARMUP / _BUDGET_S), the persistent XLA
compile cache is enabled, a watchdog emits the JSON line even if the remote
TPU relay hangs, and any mid-run failure still prints a (degraded) JSON line.

Output: one JSON line on stdout
  {"metric": "resnet50_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": N, ...}
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

V100_HOROVOD_ANCHOR = 360.0  # images/sec/chip, see module docstring

# Batch 256 measured fastest under honest chained-async timing (sweep
# 2026-07-30 on the v5e chip: 256->2385, 512->2332, 768->2225, 1024->2033
# images/sec/chip; round 2's 512 optimum was an artifact of the
# serializing per-step-fetch timer).  Consistent with the step being
# HBM-bound (PERF.md §2): bytes/img are ~flat with batch and the smaller
# working set wins.
BATCH_PER_CHIP = int(os.environ.get("TPUFRAME_BENCH_BATCH", "256"))
IMAGE_SIZE = 224
WARMUP_STEPS = int(os.environ.get("TPUFRAME_BENCH_WARMUP", "3"))
MEASURE_STEPS = int(os.environ.get("TPUFRAME_BENCH_STEPS", "16"))
BUDGET_S = float(os.environ.get("TPUFRAME_BENCH_BUDGET_S", "1500"))

# XLA-counted (FMA = 2 flops, matching how the peak specs count):
# 1.252e13 flops / 512 images from the compiled full step's cost_analysis
# (perf/exp_breakdown.py; fwd alone is 4.08e12/512 = ~8.0e9, bwd+update the
# rest).  The literature's "4.1 GFLOPs" for ResNet-50 is GMACs; using it
# against an FMA=2 peak understated MFU by 2x (rounds 1-2 reported 11-15%
# for a truly ~29%, HBM-bound step — t_hbm 177ms vs 218ms measured, 81% of
# the bandwidth roofline).
RESNET50_FLOPS_PER_IMAGE = 1.252e13 / 512
BF16_PEAK_FLOPS = {  # per chip, from public TPU spec sheets
    "v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12,
}

_T0 = time.time()
_RESULT: dict = {}  # mutated in place so the watchdog sees partial progress
_DONE = threading.Event()  # set before the final emit; silences the watchdog


def _log(msg: str) -> None:
    print(f"[bench +{time.time() - _T0:6.1f}s] {msg}", file=sys.stderr,
          flush=True)


def _emit(value: float, n_chips: int, **extra) -> None:
    _DONE.set()
    line = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / V100_HOROVOD_ANCHOR, 4),
    }
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    peak = BF16_PEAK_FLOPS.get(gen)
    if peak and value > 0 and _RESULT.get("backend") != "cpu":
        line["mfu"] = round(value * RESNET50_FLOPS_PER_IMAGE / peak, 4)
        line["chip"] = gen
    if n_chips:
        line["n_chips"] = n_chips
    if _RESULT.get("remat_policy"):
        line["policy"] = _RESULT["remat_policy"]
    if _RESULT.get("weight_update", "replicated") != "replicated":
        line["weight_update"] = _RESULT["weight_update"]
    if _RESULT.get("wire_format", "fp") != "fp":
        line["wire_format"] = _RESULT["wire_format"]
    line.update(extra)
    print(json.dumps(line), flush=True)


def _best_recorded() -> float | None:
    """Best images/sec/chip among recorded on-chip runs (perf/results/
    bench_*.out) — one source of truth for the 'last measured' annotation;
    queued sweeps that find a new optimum update it automatically."""
    import glob

    best = None
    here = os.path.dirname(os.path.abspath(__file__))
    for f in glob.glob(os.path.join(here, "perf", "results", "bench_*.out")):
        try:
            with open(f) as fh:
                lines = fh.read().strip().splitlines()
            obj = json.loads(lines[-1]) if lines else {}
        except (OSError, json.JSONDecodeError):
            continue
        v = obj.get("value")
        if (isinstance(v, (int, float)) and not obj.get("degraded")
                and (best is None or v > best)):
            best = float(v)
    return best


def _relay_ports() -> tuple[int, ...]:
    """Relay tunnel ports to probe — ``TPUFRAME_RELAY_PORTS`` (comma-sep)
    overrides the defaults.  The axon client package exposes no port
    constant (the :8081-:8083 set appears only in its docstrings), so the
    defaults are pinned here but operator-overridable rather than
    silently rotting if the relay layout changes."""
    raw = os.environ.get("TPUFRAME_RELAY_PORTS", "8083,8082,8081")
    try:
        ports = tuple(int(p) for p in raw.split(",") if p.strip())
    except ValueError:
        ports = ()
    return ports or (8083, 8082, 8081)


def _relay_probe(ports=None) -> bool | None:
    """Fast health probe of the loopback TPU relay BEFORE importing jax.

    The relay tunnel serves on localhost ports (see ``_relay_ports``);
    during an outage every one refuses instantly, while a wedged-but-
    listening relay still accepts TCP.  Returns True (some port accepts),
    False (all refused), or None (not the loopback-relay environment —
    nothing to probe).  Advisory only: a False shrinks the import-stage
    deadline (the tunnel could in principle come up lazily), it never
    skips the real claim attempt.
    """
    import socket

    if os.environ.get("AXON_LOOPBACK_RELAY") != "1":
        return None
    host = (os.environ.get("PALLAS_AXON_POOL_IPS") or "127.0.0.1").split(",")[0]
    for port in (ports if ports is not None else _relay_ports()):
        s = socket.socket()
        s.settimeout(2.0)
        try:
            s.connect((host, port))
            return True
        except OSError:
            continue
        finally:
            s.close()
    return False


# With the relay tunnel down (ports refusing), a healthy init is impossible;
# 150s is ~20x the measured healthy claim time (8.4s) yet degrades ~10x
# faster than the full watchdog budget did in BENCH_r03 (1500s at
# import-jax).
RELAY_DOWN_IMPORT_DEADLINE_S = 150.0


def _watchdog() -> None:
    """Emit a (degraded) JSON line and hard-exit if the run overruns its
    budget — a hung TPU relay must not turn into a silent driver timeout.
    A hang at import/claim stage is the relay-outage signature (PERF.md
    §0); the degraded line then points at the last recorded on-chip
    measurement (BASELINE.md) WITHOUT reporting it as this run's value."""
    deadline = BUDGET_S
    if _RESULT.get("relay_probe") is False:
        # Tunnel ports refused pre-import: if still stuck at import-jax
        # after the short deadline, degrade immediately instead of
        # burning the full budget (BENCH_r03 spent 1500s here).
        if not _DONE.wait(RELAY_DOWN_IMPORT_DEADLINE_S):
            if _RESULT.get("stage") == "import-jax":
                deadline = 0.0  # fall through to the degraded emit now
            else:
                deadline = BUDGET_S - RELAY_DOWN_IMPORT_DEADLINE_S
        else:
            return
    if deadline > 0 and _DONE.wait(deadline):
        return  # main thread emitted the real result
    if _DONE.is_set():
        return  # real result emitted in the wait/emit race window
    stage = _RESULT.get("stage", "unknown")
    _log(f"WATCHDOG: exceeded the {stage!r}-stage deadline; "
         f"emitting degraded result")
    extra = {}
    if _RESULT.get("relay_probe") is not None:
        extra["relay_probe"] = _RESULT["relay_probe"]
    if stage == "import-jax":
        extra["relay_outage_suspected"] = True
        best = _best_recorded()
        if best is not None:
            extra["last_measured_on_chip"] = best
            extra["last_measured_source"] = "perf/results (see BASELINE.md)"
    _emit(_RESULT.get("best_value", 0.0), _RESULT.get("n_chips", 0),
          degraded=True, stage=stage, **extra)
    os._exit(0)


def run(batch_per_chip: int, warmup: int, measure: int) -> float:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tpuframe import models
    from tpuframe.models import losses
    from tpuframe.parallel import mesh as mesh_lib
    from tpuframe.parallel import step as step_lib

    # World resolution through the elastic resolver — the single source
    # of truth shared with train.build_harness, read at call time (never
    # cached at module level; TF116 enforces the discipline).
    from tpuframe import elastic

    world = elastic.current_world()
    n_chips = world.n_devices
    mesh = world.mesh
    _RESULT["n_chips"] = n_chips
    _RESULT["backend"] = jax.default_backend()
    _RESULT["stage"] = "build"
    _log(f"devices: {n_chips} x {jax.devices()[0].device_kind} "
         f"(backend={jax.default_backend()})")

    global_batch = batch_per_chip * n_chips

    # TPUFRAME_BENCH_STEM=space_to_depth A/Bs the MXU-friendly stem
    # reformulation (models/resnet.py; exact-function-preserving).
    # TPUFRAME_REMAT_POLICY=<tpuframe.mem name> A/Bs rematerialization
    # policies (trades recompute flops for HBM bytes on the bandwidth-
    # bound step); unset, the tuning DB's offline remat-sweep winner
    # applies.  The legacy TPUFRAME_BENCH_REMAT=1 still maps to
    # per_block (deprecated alias, mem.policy_from_env).
    # TPUFRAME_BENCH_BN=folded A/Bs the census-driven BN whose
    # activation-sized math stays bf16 (models/folded_bn.py; PERF.md §7).
    from tpuframe import mem

    stem = os.environ.get("TPUFRAME_BENCH_STEM", "conv")
    bn = os.environ.get("TPUFRAME_BENCH_BN", "flax")
    remat_policy, remat_source = mem.resolve(
        program=f"train_resnet50_b{global_batch}", family="remat_resnet50")
    if remat_policy != "none":
        _log(f"remat policy: {remat_policy} (source: {remat_source})")
    _RESULT["remat_policy"] = remat_policy
    # TPUFRAME_WEIGHT_UPDATE=zero1 A/Bs ZeRO-1 weight-update sharding
    # (reduce-scatter → sharded update → all-gather); unset, the tuning
    # DB's offline weight_update_* sweep winner applies.
    from tpuframe.parallel import zero1 as zero1_lib

    weight_update, wu_source = zero1_lib.resolve(
        program=f"train_resnet50_b{global_batch}",
        family="weight_update_resnet50")
    if weight_update == "zero1":
        _log(f"weight update: {weight_update} (source: {wu_source})")
    _RESULT["weight_update"] = weight_update
    # TPUFRAME_WIRE_FORMAT=int8-block A/Bs block-quantized gradient
    # collectives (quantized all-to-all + all-gather instead of the f32
    # all-reduce); unset, the DB's offline wire_format_* winner applies.
    from tpuframe.parallel import quantwire

    wire_format, wf_source = quantwire.resolve(
        program=f"train_resnet50_b{global_batch}",
        family="wire_format_resnet50")
    if wire_format != "fp":
        _log(f"wire format: {wire_format} (source: {wf_source})")
    _RESULT["wire_format"] = wire_format
    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16, stem=stem,
                            bn=bn)
    rng = np.random.default_rng(0)
    # bf16 on the host: halves infeed bytes and skips the on-device cast.
    x = rng.normal(0.5, 0.25, size=(global_batch, IMAGE_SIZE, IMAGE_SIZE, 3)
                   ).astype(jnp.bfloat16)
    y = rng.integers(0, 1000, size=(global_batch,)).astype(np.int32)
    variables = model.init(jax.random.key(0), jnp.asarray(x[:2]))

    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)

    def loss_fn(params, model_state, batch, step_rng):
        logits, mutated = model.apply(
            {"params": params, **model_state}, batch["image"], train=True,
            mutable=["batch_stats"])
        loss = losses.softmax_cross_entropy(logits, batch["label"],
                                            label_smoothing=0.1)
        return loss, (dict(mutated), {})

    state = step_lib.TrainState.create(
        variables["params"], tx,
        model_state={"batch_stats": variables["batch_stats"]})
    # TPUFRAME_XLA_OPTS="k=v,k=v" -> per-compile XLA options (e.g.
    # xla_tpu_enable_latency_hiding_scheduler=true).  compiler_options
    # travels inside the compile request, so it survives the relay's
    # remote-compile hop where env vars (XLA_FLAGS / LIBTPU_INIT_ARGS)
    # either crash the local flag parser or never reach the compiler.
    from tpuframe.tune import db as tune_db
    from tpuframe.utils import xla_opts as xla_opts_lib

    try:
        xla_opts = xla_opts_lib.from_env()
    except ValueError as e:
        raise SystemExit(str(e))
    if xla_opts is None:
        # No env override: consult the offline tuning DB (only applies
        # when the target TPU generation is known; tpuframe.tune).
        xla_opts = tune_db.resolve_xla_opts(
            f"bench_resnet50_b{batch_per_chip}", family="bench_resnet50")
        if xla_opts:
            _log(f"compiler_options from tuning DB: {xla_opts}")
    else:
        _log(f"compiler_options: {xla_opts}")
    if weight_update == "zero1" and mesh is None:
        # single-chip run: nothing to shard the update over — honor the
        # resolution idiom (a DB row must never break a run) unless the
        # user asked by env, in which case make_train_step's error is due.
        if wu_source != "env":
            weight_update = "replicated"
            _RESULT["weight_update"] = weight_update
    if wire_format != "fp" and mesh is None:
        # single-chip run: no cross-chip wire to quantize — same idiom.
        if wf_source != "env":
            wire_format = "fp"
            _RESULT["wire_format"] = wire_format
    train_step = step_lib.make_train_step(
        loss_fn, tx, mesh, donate=True, compiler_options=xla_opts,
        remat_policy=None if remat_policy == "none" else remat_policy,
        weight_update=weight_update,
        wire_format=wire_format)

    if mesh is not None:
        if weight_update == "zero1":
            state = zero1_lib.make_state(
                variables["params"], tx, mesh,
                model_state={"batch_stats": variables["batch_stats"]})
        else:
            state = step_lib.replicate_state(state, mesh)
        put = lambda a: jax.device_put(a, mesh_lib.batch_sharding(mesh))  # noqa: E731
    else:
        put = jax.device_put
    batch = {"image": put(x), "label": put(y)}

    _RESULT["stage"] = "compile+warmup"
    _log(f"compiling + warmup ({warmup} steps, batch {batch_per_chip}/chip, "
         f"global {global_batch})...")
    for i in range(warmup):
        state, metrics = train_step(state, batch)
        float(metrics["loss"])  # per-step sync is fine for warmup
        _log(f"warmup step {i + 1}/{warmup} done")

    # Timing: async chained dispatch with a scalar fetch every SYNC_EVERY
    # steps.  Each step consumes the previous state, so fetching step k's
    # loss is a full barrier for steps 1..k — honest wall-clock — while the
    # host runs ahead and dispatch overlaps device compute (the production
    # loop's behavior; per-step scalar fetches serialized host and device
    # and cost ~22% on the bench chip, perf/exp_async_timing.py).
    # block_until_ready was re-validated against scalar fetches on this
    # relay platform (round-3; the round-2 early-return anomaly is gone).
    sync_every = 8
    _RESULT["stage"] = "measure"
    _log(f"measuring {measure} steps (sync every {sync_every})...")
    t0 = time.perf_counter()
    done = 0
    while done < measure:
        chunk = min(sync_every, measure - done)
        for _ in range(chunk):
            state, metrics = train_step(state, batch)
        float(metrics["loss"])  # barrier for the whole chunk
        done += chunk
        dt_so_far = time.perf_counter() - t0
        # Live partial estimate for the watchdog.
        _RESULT["best_value"] = done * global_batch / dt_so_far / n_chips
    dt = time.perf_counter() - t0

    per_chip = measure * global_batch / dt / n_chips
    _log(f"measured {per_chip:.1f} images/sec/chip "
         f"({dt / measure * 1e3:.1f} ms/step)")
    return per_chip


def main() -> None:
    probe = _relay_probe()
    _RESULT["relay_probe"] = probe
    if probe is False:
        _log(f"relay probe: tunnel ports refused — import deadline "
             f"shortened to {RELAY_DOWN_IMPORT_DEADLINE_S:.0f}s")
    threading.Thread(target=_watchdog, daemon=True).start()
    _RESULT["stage"] = "import-jax"
    _log("importing jax (remote TPU relay init can be slow)...")
    import jax  # noqa: F401 — backend init is the slow part being timed

    from tpuframe.utils import compile_cache

    # Shared persistent-cache helper (tpuframe.utils.compile_cache): same
    # <repo>/.xla_cache dir + 1.0s threshold as before, now with
    # compile_cache.hits/misses counters in obs.metrics.
    compile_cache.enable()

    n_chips = 0
    try:
        per_chip = run(BATCH_PER_CHIP, WARMUP_STEPS, MEASURE_STEPS)
        n_chips = _RESULT["n_chips"]
    except Exception as e:  # degraded path: smaller batch, fewer steps
        _log(f"primary config failed ({type(e).__name__}: {e}); "
             f"retrying degraded (batch 128, 2+4 steps)")
        try:
            per_chip = run(128, 2, 4)
            n_chips = _RESULT["n_chips"]
            _emit(per_chip, n_chips, degraded=True)
            return
        except Exception as e2:
            _log(f"degraded config also failed ({type(e2).__name__}: {e2})")
            _emit(_RESULT.get("best_value", 0.0), _RESULT.get("n_chips", 0),
                  degraded=True, error=f"{type(e2).__name__}: {e2}"[:200])
            return
    _emit(per_chip, n_chips)


if __name__ == "__main__":
    main()
