"""Benchmark: ResNet-50 training throughput, images/sec/chip.

The driver-defined metric (BASELINE.json:2): ResNet-50 images/sec/chip.
This runs the flagship model's full training step (fwd+bwd+update, bf16
compute, batch 128/chip) on the available chip(s) with synthetic ImageNet
shapes, which isolates accelerator throughput from input-pipeline effects.

``vs_baseline``: the reference's own numbers are unpublished (BASELINE.md —
`"published": {}` and the source mount was empty), so the anchor is the
Horovod-GPU era per-chip figure for this exact workload: ~360 images/sec on a
V100 with standard fp16/32 ResNet-50 training (MLPerf v0.6-era single-GPU
throughput; the Horovod paper's hardware class, PAPERS.md:8).
vs_baseline = value / 360.0.

Output: one JSON line
  {"metric": "resnet50_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": N}
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

V100_HOROVOD_ANCHOR = 360.0  # images/sec/chip, see module docstring

# Batch 512/chip measured fastest on the v5e bench chip (sweep 2026-07-29:
# 128->1083, 256->1454, 512->1824, 1024->1797 images/sec/chip); large batches
# keep the MXU fed through the small-spatial late stages.
BATCH_PER_CHIP = 512
IMAGE_SIZE = 224
WARMUP_STEPS = 3
MEASURE_STEPS = 8


def main() -> None:
    from tpuframe import models
    from tpuframe.models import losses
    from tpuframe.parallel import mesh as mesh_lib
    from tpuframe.parallel import step as step_lib

    n_chips = jax.device_count()
    mesh = mesh_lib.make_mesh() if n_chips > 1 else None
    global_batch = BATCH_PER_CHIP * n_chips

    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    x = rng.normal(0.5, 0.25, size=(global_batch, IMAGE_SIZE, IMAGE_SIZE, 3)
                   ).astype(np.float32)
    y = rng.integers(0, 1000, size=(global_batch,)).astype(np.int32)
    variables = model.init(jax.random.key(0), jnp.asarray(x[:2]))

    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)

    def loss_fn(params, model_state, batch, step_rng):
        logits, mutated = model.apply(
            {"params": params, **model_state}, batch["image"], train=True,
            mutable=["batch_stats"])
        loss = losses.softmax_cross_entropy(logits, batch["label"],
                                            label_smoothing=0.1)
        return loss, (dict(mutated), {})

    state = step_lib.TrainState.create(
        variables["params"], tx,
        model_state={"batch_stats": variables["batch_stats"]})
    train_step = step_lib.make_train_step(loss_fn, tx, mesh, donate=True)

    if mesh is not None:
        state = step_lib.replicate_state(state, mesh)
        put = lambda a: jax.device_put(a, mesh_lib.batch_sharding(mesh))  # noqa: E731
    else:
        put = jax.device_put
    batch = {"image": put(x), "label": put(y)}

    def synced_step(state):
        state, metrics = train_step(state, batch)
        # Hard sync via scalar fetch: on the sandbox's axon relay platform,
        # block_until_ready over a chain of donated buffers can return before
        # execution finishes, inflating async-loop timings ~80x; fetching the
        # loss forces completion of the whole step.
        float(metrics["loss"])
        return state

    for _ in range(WARMUP_STEPS):
        state = synced_step(state)

    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state = synced_step(state)
    dt = time.perf_counter() - t0

    images_per_sec = MEASURE_STEPS * global_batch / dt
    per_chip = images_per_sec / n_chips
    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / V100_HOROVOD_ANCHOR, 4),
    }))


if __name__ == "__main__":
    main()
