"""Elastic world-size training: resize the mesh mid-run, lose ≤1 step.

On a preemptible fleet the world size is a *variable*, not a constant: a
spot reclaim takes k of n hosts and the economically sane response is to
continue at n′ = n−k now, then grow back when capacity returns — not to
idle until an identical slice reappears.  Horovod's elastic mode
(arXiv:1802.05799, PAPERS.md) and the goodput accounting of
arXiv:2011.03641 both frame membership change as a *bounded-cost event*;
this package supplies the bound.

Every ingredient already exists in-repo; elastic composes them:

  - the supervisor relaunch loop (``launch/launcher.py``, PR 2) replays
    the run command after a crash — here it additionally consults the
    :data:`ENV_SCHEDULE` membership plan and rebuilds the local cluster
    at the new world size per attempt;
  - the commit-or-quarantine async checkpoint (``ckpt/checkpoint.py``,
    PR 8) drains in-flight saves via ``flush(deadline)`` on SIGTERM, so
    the surviving hosts always leave a committed step behind;
  - ZeRO-1's flat pad-to-multiple layout (``parallel/zero1.py``, PR 7)
    makes the n→n′ optimizer-state reshard *trivially deterministic* —
    see :func:`resharding.reshard_flat` for why truncate-or-zero-pad is
    exact, not approximate;
  - the obs attempt stitcher (``obs/goodput.py``, PR 4/9) prices the
    boundary: ``retrained_steps`` across a resize must stay ≤1.

The contract, in order: **drain → relaunch → reshard → rescale.**
Global batch and LR react to n→n′ by a declared policy
(:data:`POLICIES`: ``hold``/``linear``/``sqrt``) resolved from
:data:`ENV_RESCALE`, and the whole transition is emitted as a typed
``elastic_resize`` run event with full provenance (n_from/n_to, policy,
old/new batch and LR, policy source).

Like every other wire in the repo, the resharding map is budgeted:
``analysis/shardflow.py`` derives the exact shard-movement bytes for an
n→n′ transition (from :func:`resharding.moved_elems` interval
arithmetic over the flagship param census) and pins them in
``derived_budgets.json`` — drift fails the gate.

This module is import-light on purpose (no jax at import time): the
supervisor consumes the membership schedule before any backend exists.
"""

from __future__ import annotations

from tpuframe.elastic.membership import (  # noqa: F401
    ENV_RESCALE,
    ENV_SCHEDULE,
    POLICIES,
    World,
    current_world,
    parse_schedule,
    rescale,
    resolve_rescale,
    schedule_from_env,
    world_for_attempt,
)
from tpuframe.elastic.resharding import (  # noqa: F401
    moved_elems,
    reshard_flat,
    resize_movement,
)


# ---------------------------------------------------------------------------
# Analysis-gate self-check.
# ---------------------------------------------------------------------------

# Files that consume world size at runtime and must NOT cache it at
# module import (TF116's scope) — a stale module-level capture is the
# classic elastic-training bug: the value survives the relaunch and the
# run silently computes at the dead world size.
_TF116_SELF_LINT = (
    "tpuframe/train.py",
    "tpuframe/data",
    "tpuframe/ckpt",
    "tpuframe/obs",
    "bench.py",
)


def check() -> list:
    """Self-check for the ``python -m tpuframe.analysis`` CI gate.
    Returns problem strings; [] means healthy."""
    import os

    problems: list[str] = []
    # 1. schedule grammar round-trips and clamps
    try:
        sched = parse_schedule("8,4,8")
        if sched != (8, 4, 8):
            problems.append(f"parse_schedule('8,4,8') -> {sched!r}")
        if world_for_attempt(0, sched) != 8 or world_for_attempt(1, sched) != 4:
            problems.append("world_for_attempt indexes the wrong leg")
        if world_for_attempt(99, sched) != 8:
            problems.append("world_for_attempt does not clamp to the last leg")
    except Exception as e:  # noqa: BLE001 — report, don't crash CI
        problems.append(f"schedule grammar: {e}")
    try:
        schedule_from_env()
    except ValueError as e:
        problems.append(f"{ENV_SCHEDULE} is set to an invalid schedule: {e}")
    # 2. rescale policies: hold is identity, linear/sqrt scale as declared
    b, lr = rescale(32, 0.1, 8, 4, "hold")
    if (b, lr) != (32, 0.1):
        problems.append(f"hold rescale is not identity: {(b, lr)}")
    b, lr = rescale(32, 0.1, 8, 4, "linear")
    if b != 16 or abs(lr - 0.05) > 1e-12:
        problems.append(f"linear rescale wrong: {(b, lr)}")
    try:
        resolve_rescale()
    except ValueError as e:
        problems.append(f"{ENV_RESCALE} is set to an invalid policy: {e}")
    # 3. reshard arithmetic: conservation + identity properties, and the
    #    local padded_len mirror must agree with zero1's layout
    if moved_elems(100, 8, 8) != 0:
        problems.append("moved_elems(n==n') must be 0")
    if not (0 <= moved_elems(100, 8, 4) <= 100):
        problems.append("moved_elems out of [0, size]")
    from tpuframe.elastic.resharding import padded_len
    from tpuframe.parallel import zero1

    for size in (0, 1, 7, 8, 100, 144, 4097):
        for n in (1, 2, 4, 8):
            if padded_len(size, n) != zero1.padded_len(size, n):
                problems.append(
                    f"padded_len({size}, {n}) diverged from zero1's layout")
    # 4. TF116 self-lint: no module-level world-size captures outside the
    #    sanctioned elastic/launch/parallel seams
    from tpuframe.analysis.source_lint import lint_paths

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_root = os.path.dirname(pkg_root)
    paths = [os.path.join(repo_root, p) for p in _TF116_SELF_LINT]
    for f in lint_paths([p for p in paths if os.path.exists(p)]):
        if f.rule == "TF116":
            problems.append(f"self-lint: {f}")
    return problems
