"""Membership policy: who is in the world, and how the run reacts.

Three concerns live here, all deliberately jax-free at import time so
the supervisor (which runs before any backend exists) can consume them:

**The membership schedule** (:data:`ENV_SCHEDULE`).  Real elastic
training gets its membership changes from the resource manager; the
in-repo simulation declares them up front as a comma-separated list of
total device counts, one per supervisor attempt::

    TPUFRAME_ELASTIC="8,4,8"   # attempt 0 at 8, attempt 1 at 4, then 8

:func:`world_for_attempt` clamps past the end (the last leg is the
steady state), so a schedule shorter than the relaunch budget is fine.

**The rescale policy** (:data:`ENV_RESCALE`).  When the world resizes
n→n′ the run must decide what happens to global batch and LR.  The
policy is *declared*, not inferred — it lands in the ``elastic_resize``
run event so every resize carries its provenance:

  - ``hold``   — keep batch and LR (default).  Data order is world-size
    independent (``ShardedLoader``'s permutation is seeded globally), so
    ``hold`` gives golden-loss-equivalent continuation — the property
    the chaos tier pins.
  - ``linear`` — batch and LR scale by n′/n (the classic linear-scaling
    rule, arXiv:1706.02677 regime).
  - ``sqrt``   — batch scales linearly, LR by sqrt(n′/n) (the
    conservative rule for adaptive optimizers).

**The world resolver** (:func:`current_world`).  train.py and bench.py
used to derive mesh shape + device counts independently; the resize
path needs a single source of truth, so both now route here.  The
resolver reads the world *at call time* — never cache its result at
module level (TF116 enforces this outside the elastic/launch/parallel
seams).
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Any

ENV_SCHEDULE = "TPUFRAME_ELASTIC"
ENV_RESCALE = "TPUFRAME_ELASTIC_RESCALE"

POLICIES = ("hold", "linear", "sqrt")


# ---------------------------------------------------------------------------
# Membership schedule.
# ---------------------------------------------------------------------------


def parse_schedule(text: str) -> tuple[int, ...]:
    """``"8,4,8"`` → ``(8, 4, 8)``.  Empty/blank → ``()`` (not elastic)."""
    out = []
    for tok in (text or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        try:
            n = int(tok)
        except ValueError:
            raise ValueError(
                f"{ENV_SCHEDULE} entries must be integers, got {tok!r}")
        if n <= 0:
            raise ValueError(
                f"{ENV_SCHEDULE} entries must be positive, got {n}")
        out.append(n)
    return tuple(out)


def schedule_from_env(env=os.environ) -> tuple[int, ...]:
    """The declared membership plan, or ``()`` when the run is rigid."""
    return parse_schedule(env.get(ENV_SCHEDULE, ""))


def world_for_attempt(attempt: int, schedule: tuple[int, ...]) -> int:
    """Total device count for supervisor attempt ``attempt`` (0-based).
    Clamps to the last leg — the schedule's tail is the steady state."""
    if not schedule:
        raise ValueError("world_for_attempt called with an empty schedule")
    return schedule[min(max(int(attempt), 0), len(schedule) - 1)]


# ---------------------------------------------------------------------------
# Rescale policy.
# ---------------------------------------------------------------------------


def validate_policy(policy: str) -> str:
    policy = (policy or "hold").strip().lower()
    if policy not in POLICIES:
        raise ValueError(f"unknown elastic rescale policy {policy!r}; "
                         f"expected one of {POLICIES} ({ENV_RESCALE})")
    return policy


def resolve_rescale(env=os.environ) -> tuple[str, str]:
    """``(policy, source)`` — env override > ``hold`` default.  ``source``
    is ``env``/``default``, emitted in the ``elastic_resize`` event."""
    raw = env.get(ENV_RESCALE, "").strip()
    if raw:
        return validate_policy(raw), "env"
    return "hold", "default"


def rescale(global_batch: int, base_lr: float, n_from: int, n_to: int,
            policy: str) -> tuple[int, float]:
    """Apply ``policy`` to ``(global_batch, base_lr)`` for an n→n′
    resize.  The returned batch is kept a positive multiple of ``n_to``
    so per-replica and per-host divisibility survive the transition."""
    policy = validate_policy(policy)
    if policy == "hold" or n_from == n_to or n_from <= 0 or n_to <= 0:
        return int(global_batch), float(base_lr)
    ratio = n_to / n_from
    batch = int(round(global_batch * ratio))
    batch = max(n_to, (batch // n_to) * n_to)
    if policy == "linear":
        lr = float(base_lr) * ratio
    else:  # sqrt
        lr = float(base_lr) * math.sqrt(ratio)
    return batch, lr


# ---------------------------------------------------------------------------
# The world resolver (single source of truth for train.py AND bench.py).
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class World:
    """A point-in-time snapshot of the visible world.  Snapshots are for
    *immediate* use — hold one across a relaunch boundary and it lies."""

    n_devices: int
    n_processes: int
    process_index: int
    mesh: Any  # jax.sharding.Mesh | None


def current_world(spec=None, *, distributed: bool | None = None) -> World:
    """Resolve device/process counts and (optionally) build the mesh.

    ``distributed=True`` always builds the mesh from ``spec`` (train.py's
    contract), ``False`` never does, and ``None`` builds one only when
    more than one device is visible (bench.py's contract).  Reads jax at
    call time — the post-relaunch world, never a cached one.
    """
    import jax

    from tpuframe.parallel import mesh as mesh_lib

    n_devices = jax.device_count()
    want_mesh = distributed if distributed is not None else n_devices > 1
    mesh = mesh_lib.make_mesh(spec) if want_mesh else None
    return World(
        n_devices=n_devices,
        n_processes=jax.process_count(),
        process_index=jax.process_index(),
        mesh=mesh,
    )
