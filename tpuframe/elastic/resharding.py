"""The deterministic n→n′ resharding map for ZeRO-1 flat state.

Why truncate-or-zero-pad is EXACT, not approximate
--------------------------------------------------
zero1 stores every optimizer moment as a flat 1-D vector zero-padded to
``padded_len(size, n)`` and sharded over the n weight-update replicas.
The pad region is zero *forever*, by construction:

  - ``init_opt_state`` runs ``tx.init`` over zero templates — element-
    wise optimizers initialize moments to zeros;
  - every step pads gradients with zeros (``flat_pad``), and the
    reduce-scatter mean of zeros is zero;
  - element-wise transforms (sgd/momentum/adam(w)) keep a zero moment
    zero under a zero gradient, so the pad rows never drift.

Therefore resharding a saved ``[padded_len(size, n)]`` vector to the
target ``[padded_len(size, n′)]`` layout needs no metadata at all:

  - shrink (target shorter): ``vec[:target]`` — target ≥ true size, so
    only provably-zero pad rows are dropped;
  - grow (target longer): zero-pad — exactly what a fresh layout at n′
    would contain in those rows.

Params are replicated in the ZeRO-1 layout (only the *update* is
sharded), so they restore through the ordinary full-reassembly path
unchanged; the resharding map touches optimizer moments only.  Both
directions compose to the identity, which is why the 8→4→8 chaos run
can demand golden-loss-equivalent continuation rather than "close".

Movement accounting
-------------------
:func:`moved_elems` prices the transition the same way shardflow prices
a collective: walk the element index space in O(n+n′) segments and sum
the elements whose owning shard changes between the n- and n′-layouts.
``analysis/shardflow.py`` rolls this up over the flagship param census
into ``derived_budgets.json`` — the resharding map is a wire like any
other, and drift fails the gate.
"""

from __future__ import annotations

import numpy as np


def padded_len(size: int, n: int) -> int:
    """zero1's pad-to-multiple layout length (mirrors ``zero1._padded``;
    elastic.check() cross-checks the two stay identical)."""
    return -(-int(size) // int(n)) * int(n)


def reshard_flat(vec, target_len: int):
    """Truncate or zero-pad a flat 1-D moment vector to ``target_len``.

    ``vec`` is any 1-D array-like (the restore path hands in the fully
    reassembled host array).  See the module docstring for why this is
    the *exact* n→n′ map for ZeRO-1 state, shrink and grow alike.
    """
    vec = np.asarray(vec)
    if vec.ndim != 1:
        raise ValueError(f"reshard_flat wants a flat 1-D vector, "
                         f"got shape {vec.shape}")
    target_len = int(target_len)
    if vec.shape[0] == target_len:
        return vec
    if vec.shape[0] > target_len:
        return vec[:target_len]
    out = np.zeros((target_len,), dtype=vec.dtype)
    out[: vec.shape[0]] = vec
    return out


def moved_elems(size: int, n_from: int, n_to: int) -> int:
    """Elements of a true-size-``size`` vector whose owning shard index
    changes when the flat layout re-pads from ``n_from`` to ``n_to``
    shards.  Exact, O(n_from + n_to): owner is constant on the overlap
    segments of the two chunk grids, so walk segment boundaries instead
    of elements.  Pad rows are excluded — they carry no state."""
    size, n_from, n_to = int(size), int(n_from), int(n_to)
    if size <= 0 or n_from == n_to:
        return 0
    chunk_f = padded_len(size, n_from) // n_from
    chunk_t = padded_len(size, n_to) // n_to
    moved = 0
    i = 0
    while i < size:
        owner_f = i // chunk_f
        owner_t = i // chunk_t
        nxt = min((owner_f + 1) * chunk_f, (owner_t + 1) * chunk_t, size)
        if owner_f != owner_t:
            moved += nxt - i
        i = nxt
    return moved


def resize_movement(leaves, n_from: int, n_to: int, *,
                    moment_vectors: int = 2) -> dict:
    """Roll :func:`moved_elems` up over a param census.

    ``leaves`` is an iterable of ``(name, size, itemsize)`` rows (one per
    param leaf); ``moment_vectors`` is how many flat state vectors the
    optimizer keeps per leaf (2 for adam(w): mu and nu).  Returns the
    audit dict shardflow pins in ``derived_budgets.json``:
    ``moved_bytes`` (state bytes that change owner), ``state_bytes``
    (total sharded-state bytes in the n′ layout) and ``moved_frac``.
    """
    rows = []
    moved_b = 0
    state_b = 0
    for name, size, itemsize in leaves:
        me = moved_elems(size, n_from, n_to)
        mb = int(me) * int(itemsize) * int(moment_vectors)
        tb = padded_len(size, n_to) * int(itemsize) * int(moment_vectors)
        rows.append({
            "name": str(name),
            "size": int(size),
            "padded_from": padded_len(size, n_from),
            "padded_to": padded_len(size, n_to),
            "moved_elems": int(me),
            "moved_bytes": mb,
        })
        moved_b += mb
        state_b += tb
    return {
        "n_from": int(n_from),
        "n_to": int(n_to),
        "moment_vectors": int(moment_vectors),
        "n_leaves": len(rows),
        "moved_bytes": moved_b,
        "state_bytes": state_b,
        "moved_frac": moved_b / max(state_b, 1),
        "leaves": rows,
    }
