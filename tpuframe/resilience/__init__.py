"""Failure-model implementation (SURVEY.md §5.3, docs/DESIGN.md "Failure
model & resilience").

The reference system's value proposition was surviving real GCP failure
modes — preemptible hosts, flaky GCS, stalled ranks — via Horovod's
elastic/stall machinery.  tpuframe's equivalent is job-restart recovery
(TPU pods fail as a unit): this package hardens every seam of that model.

  * :mod:`tpuframe.resilience.policy` — retry policies for transient I/O:
    exponential backoff with decorrelated jitter, per-attempt timeout,
    overall deadline, retryable-exception classification.  Applied to
    every ``data/gcs.py`` operation and checkpoint shard I/O; retry
    counts surface through ``obs/metrics.py`` counters.
  * :mod:`tpuframe.resilience.faults` — structured fault injection
    (``TPUFRAME_FAULTS``): I/O errors, slow reads, torn/corrupt shards,
    crashes and signals at named seams, so every recovery path is
    deterministically testable on CPU.
  * :mod:`tpuframe.resilience.preempt` — the GCP preemption contract:
    SIGTERM/SIGINT set a flag, the harness checkpoints at the next step
    boundary and exits rc 14 so the supervisor resumes instead of
    counting a crash.

Exit-code table (the supervisor's vocabulary, see launch/launcher.py):

  ====  =====================================================
  rc    meaning
  ====  =====================================================
  0     clean completion
  13    stall watchdog abort (obs/heartbeat via train.py)
  14    preemption: final checkpoint committed, resume me
  42    injected crash (fault kind ``crash``)
  ====  =====================================================

This package must stay importable without jax (the launcher and the gcs
layer import it before any backend exists).
"""

from tpuframe.resilience.policy import (  # noqa: F401
    RetryPolicy,
    is_retryable,
    retrying,
)
from tpuframe.resilience import faults  # noqa: F401
from tpuframe.resilience.preempt import (  # noqa: F401
    RC_PREEMPTED,
    PreemptionGuard,
)
