"""Structured fault injection — every recovery path deterministically
testable on CPU (SURVEY.md §5.3's "test-only hook", grown into a registry).

Spec grammar (``TPUFRAME_FAULTS``, comma-separated entries)::

    TPUFRAME_FAULTS="gcs_read:step=13:kind=ioerror,ckpt_shard:kind=corrupt,
                     host:step=20:kind=sigterm"

    <seam>[:step=N][:kind=K][:times=T][:rank=R][:k=K][:once=1][:delay_s=X]

Seams are named injection points the framework calls into:

  ============  ======================================================
  seam          where it fires
  ============  ======================================================
  gcs_read      ``data/gcs.py`` read_bytes (every manifest/shard read)
  gcs_write     ``data/gcs.py`` write_bytes
  gcs_list      ``data/gcs.py`` listdir
  ckpt_shard    checkpoint shard serialization (``mangle`` on the bytes
                actually written — kinds ``corrupt``/``torn``)
  host          the training loop, once per step (crash/signal kinds)
  slow_gcs      ``data/gcs.py`` write_bytes, *before* the retry wrapper's
                attempt (default kind ``slow``: models degraded storage
                without consuming retry budget — the async-vs-sync
                goodput comparison seam)
  crash_during_upload
                the async checkpoint worker, after shard files are
                written but before the sidecar/COMMIT (default kind
                ``crash`` — proves no acknowledged-but-unwritten ckpt)
  sigterm_pending_upload
                right after an async save is enqueued, while its upload
                is in flight (default kind ``sigterm`` — drives the
                flush-before-rc-14 path)
  replica_crash
                the serving replica's main loop, once per scheduler
                step (default kind ``crash`` — the replica-kill model:
                the router must redispatch its in-flight requests)
  replica_hang  same seam, default kind ``hang`` — the replica's step
                loop stops beating while its exporter thread keeps
                serving, so ``/healthz`` flips 503 (the stall model)
  replica_slow  same seam, default kind ``slow`` — a straggler replica
                (sleeps ``delay_s``; the hedging model)
  slow_canary   the replica main loop, once per iteration, but ONLY
                while the replica is serving a weights version it was
                not launched with (default kind ``slow`` — the poisoned-
                canary model: the new version is slower than the old,
                and the rollout gate must catch it and roll back)
  crash_during_swap
                inside the replica's weight-swap application, after the
                swap was accepted but before the new version is live
                (default kind ``crash`` — proves a replica killed
                mid-swap is drained, redispatched and relaunched on the
                NEW version with zero admitted-request loss)
  ============  ======================================================

Kinds: ``ioerror`` (raise a retryable :class:`InjectedFault`), ``slow``
(sleep ``delay_s``), ``corrupt`` (flip bytes), ``torn`` (truncate),
``crash`` (``os._exit(42)``, no cleanup — the hard-kill model),
``sigterm``/``sigint`` (deliver the real signal to this process — drives
the preemption contract), ``hang`` (sleep forever — the stall class),
``partial_sigterm`` (deliver SIGTERM only on the first ``k`` of n
simulated hosts — the membership-change model: a spot reclaim takes k
hosts, the survivors drain and the supervisor relaunches at n−k; the
elastic resize chaos tier drives on this kind).

Matching: ``step=N`` gates on the training step (the harness calls
:func:`set_step`); ``times=T`` caps firings (default 1); ``rank=R``
restricts to one process; ``k=K`` (``partial_sigterm`` only, default 1)
selects how many of the n hosts take the signal; ``once=1`` drops the
fault on a *resumed* run (start_step > 0) so relaunch tests survive the
step that killed them — the old ``TPUFRAME_FAULT_ONCE`` semantics.

The pre-grammar ``TPUFRAME_FAULT_STEP``/``TPUFRAME_FAULT_ONCE`` aliases
are REMOVED: setting either raises at registry build with the
``TPUFRAME_FAULTS`` spelling to use instead — a fault the operator
thinks is armed but the registry silently ignores is the worst failure
mode a chaos harness can have.

No jax import: gcs and the launcher pull this in before any backend.
"""

from __future__ import annotations

import os
import signal
import time
from dataclasses import dataclass

_KINDS = ("ioerror", "slow", "corrupt", "torn", "crash", "sigterm",
          "sigint", "hang", "partial_sigterm")
_SEAMS = ("gcs_read", "gcs_write", "gcs_list", "gcs_stat", "gcs_delete",
          "ckpt_shard", "host", "slow_gcs", "crash_during_upload",
          "sigterm_pending_upload", "replica_crash", "replica_hang",
          "replica_slow", "slow_canary", "crash_during_swap")
# The checkpoint-pipeline seams read more naturally with their purpose as
# the default kind — ``slow_gcs`` without ``:kind=`` means slow, not a
# spelled-the-seam-name-but-raises-ioerror surprise.  Same for the
# serving-replica seams: the name IS the failure mode.
_SEAM_DEFAULT_KIND = {"slow_gcs": "slow", "crash_during_upload": "crash",
                      "sigterm_pending_upload": "sigterm",
                      "replica_crash": "crash", "replica_hang": "hang",
                      "replica_slow": "slow", "slow_canary": "slow",
                      "crash_during_swap": "crash"}
_CRASH_RC = 42


class InjectedFault(IOError):
    """Raised by ``kind=ioerror`` — an OSError subclass, so the default
    retry classification treats it as transient (that is the point)."""


@dataclass
class Fault:
    seam: str
    kind: str = "ioerror"
    step: int | None = None
    times: int = 1
    rank: int | None = None
    once: bool = False
    delay_s: float = 1.0
    # partial_sigterm only: how many of the n simulated hosts take the
    # signal (processes with index < k).
    k: int = 1


def parse(spec: str) -> list[Fault]:
    """Parse a ``TPUFRAME_FAULTS`` value; raises ValueError loudly on
    unknown seams/kinds/keys (a silently-ignored fault spec would make a
    recovery test pass vacuously)."""
    faults: list[Fault] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        seam, *opts = entry.split(":")
        if seam not in _SEAMS:
            raise ValueError(f"unknown fault seam {seam!r} in {entry!r}; "
                             f"have {_SEAMS}")
        f = Fault(seam=seam, kind=_SEAM_DEFAULT_KIND.get(seam, "ioerror"))
        for opt in opts:
            key, sep, val = opt.partition("=")
            if not sep:
                raise ValueError(f"fault option {opt!r} needs key=value "
                                 f"(in {entry!r})")
            if key == "kind":
                if val not in _KINDS:
                    raise ValueError(f"unknown fault kind {val!r} in "
                                     f"{entry!r}; have {_KINDS}")
                f.kind = val
            elif key == "step":
                f.step = int(val)
            elif key == "times":
                f.times = int(val)
            elif key == "rank":
                f.rank = int(val)
            elif key == "k":
                f.k = int(val)
                if f.k < 1:
                    raise ValueError(f"fault option k must be >= 1 "
                                     f"(in {entry!r})")
            elif key == "once":
                f.once = val not in ("0", "false", "")
            elif key == "delay_s":
                f.delay_s = float(val)
            else:
                raise ValueError(f"unknown fault option {key!r} in "
                                 f"{entry!r}")
        faults.append(f)
    return faults


def _process_index() -> int:
    """This process's rank without forcing a jax import: the launcher env
    var is authoritative in the fake cluster; fall back to jax only when
    it is already imported (TPU metadata autodetection)."""
    env = os.environ.get("TPUFRAME_PROCESS_ID")
    if env:
        return int(env)
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return jax.process_index()
        except Exception:  # noqa: BLE001 — backend not initialized yet
            return 0
    return 0


def _emit_fault(f: Fault, step: int) -> None:
    """Best-effort ``fault_injected`` event — emitted *before* the fault
    acts, so even a ``crash``/``hang`` leaves its record in the log.
    Only when the event layer is already imported (no-jax guarantee)."""
    try:
        import sys

        events = sys.modules.get("tpuframe.obs.events")
        if events is not None:
            events.emit("fault_injected", seam=f.seam, kind=f.kind,
                        step=step)
    except Exception:  # noqa: BLE001 — injection must act even if
        pass  # observability is broken; the test asserts the fault, not the log


class FaultRegistry:
    def __init__(self, faults: list[Fault] | None = None):
        self.faults = list(faults or [])
        self.step = 0

    def set_step(self, step: int) -> None:
        self.step = step

    def set_resumed(self, resumed: bool) -> None:
        """Drop ``once`` faults on a resumed run (start_step > 0)."""
        if resumed:
            self.faults = [f for f in self.faults if not f.once]

    def _take(self, seam: str, kinds: tuple[str, ...]) -> Fault | None:
        for f in self.faults:
            if (f.seam == seam and f.kind in kinds and f.times > 0
                    and (f.step is None or f.step == self.step)
                    and (f.rank is None or f.rank == _process_index())):
                f.times -= 1
                return f
        return None

    def fire(self, seam: str) -> None:
        """Run any control-flow fault armed at ``seam`` (everything except
        the data-mangling kinds, which go through :meth:`mangle`)."""
        f = self._take(seam, ("ioerror", "slow", "crash", "sigterm",
                              "sigint", "hang", "partial_sigterm"))
        if f is None:
            return
        _emit_fault(f, self.step)
        if f.kind == "ioerror":
            raise InjectedFault(f"injected ioerror at seam {seam} "
                                f"(step {self.step})")
        if f.kind == "slow":
            print(f"[tpuframe] FAULT INJECTION: slow {seam} "
                  f"({f.delay_s:.1f}s) at step {self.step}", flush=True)
            time.sleep(f.delay_s)
            return
        if f.kind == "crash":
            print(f"[tpuframe] FAULT INJECTION: dying at step {self.step}",
                  flush=True)
            try:
                # ``os._exit`` bypasses every handler and atexit hook, so
                # the flight recorder (obs/flight.py) must dump HERE —
                # via sys.modules, keeping this module's no-jax/no-obs
                # import guarantee.
                import sys

                flight = sys.modules.get("tpuframe.obs.flight")
                if flight is not None:
                    flight.dump("crash_injected")
            except Exception:  # noqa: BLE001 — dying anyway
                pass
            os._exit(_CRASH_RC)
        if f.kind == "partial_sigterm":
            # Membership change: only the first k of n simulated hosts are
            # reclaimed.  The registry is per-process, so each process
            # decides from its OWN rank; survivors print and continue —
            # they learn about the shrink from the coordinator dying, not
            # from the signal.
            if _process_index() < f.k:
                print(f"[tpuframe] FAULT INJECTION: raising SIGTERM on "
                      f"host {_process_index()} (partial, k={f.k}) at "
                      f"step {self.step}", flush=True)
                os.kill(os.getpid(), signal.SIGTERM)
            else:
                print(f"[tpuframe] FAULT INJECTION: partial_sigterm "
                      f"spared host {_process_index()} (k={f.k}) at "
                      f"step {self.step}", flush=True)
            return
        if f.kind in ("sigterm", "sigint"):
            sig = signal.SIGTERM if f.kind == "sigterm" else signal.SIGINT
            print(f"[tpuframe] FAULT INJECTION: raising {f.kind.upper()} "
                  f"at step {self.step}", flush=True)
            os.kill(os.getpid(), sig)
            return
        if f.kind == "hang":
            print(f"[tpuframe] FAULT INJECTION: hanging at step "
                  f"{self.step}", flush=True)
            time.sleep(10 ** 6)

    def mangle(self, seam: str, data: bytes) -> bytes:
        """Return ``data`` corrupted/truncated when a data fault is armed
        at ``seam`` (simulates storage-side corruption: the writer's CRC
        is computed over the CLEAN bytes, so restore sees a mismatch)."""
        f = self._take(seam, ("corrupt", "torn"))
        if f is None:
            return data
        _emit_fault(f, self.step)
        print(f"[tpuframe] FAULT INJECTION: {f.kind} bytes at seam {seam} "
              f"(step {self.step})", flush=True)
        if f.kind == "torn":
            return data[: max(1, len(data) // 2)]
        mangled = bytearray(data)
        for i in (0, len(mangled) // 2, len(mangled) - 1):
            mangled[i] ^= 0xFF
        return bytes(mangled)


# ---------------------------------------------------------------------------
# Module-level default registry (the one the framework's seams consult).
# ---------------------------------------------------------------------------

_registry: FaultRegistry | None = None


def reset_from_env(env=os.environ) -> FaultRegistry:
    """(Re)build the active registry from ``TPUFRAME_FAULTS``.

    The removed ``TPUFRAME_FAULT_STEP``/``TPUFRAME_FAULT_ONCE`` aliases
    raise loudly instead of being ignored: an operator who sets them
    believes a fault is armed, and a chaos fault that silently never
    fires turns every downstream resilience proof into a false pass."""
    global _registry
    for var in ("TPUFRAME_FAULT_STEP", "TPUFRAME_FAULT_ONCE"):
        if env.get(var, "").strip():
            step = env.get("TPUFRAME_FAULT_STEP", "N").strip() or "N"
            once = ":once=1" if env.get("TPUFRAME_FAULT_ONCE") else ""
            raise RuntimeError(
                f"{var} was removed — spell the fault as "
                f"TPUFRAME_FAULTS='host:step={step}:kind=crash{once}' "
                f"(see tpuframe.resilience.faults for the grammar)")
    _registry = FaultRegistry(parse(env.get("TPUFRAME_FAULTS", "")))
    return _registry


def registry() -> FaultRegistry:
    global _registry
    if _registry is None:
        _registry = reset_from_env()
    return _registry


def fire(seam: str) -> None:
    registry().fire(seam)


def mangle(seam: str, data: bytes) -> bytes:
    return registry().mangle(seam, data)


def set_step(step: int) -> None:
    registry().set_step(step)


def set_resumed(resumed: bool) -> None:
    registry().set_resumed(resumed)
