"""The GCP preemption contract (SURVEY.md §5.3; docs/DESIGN.md).

Preemptible/spot TPU-VMs get SIGTERM with a short grace window before
the host disappears.  The old behavior — die mid-step, lose everything
since the last periodic checkpoint — wastes up to ``ckpt_every`` steps
of pod time per preemption.  The contract implemented here:

  1. :class:`PreemptionGuard` turns SIGTERM/SIGINT into a *flag*, never
     an exception: a signal mid-collective must not unwind the runtime.
  2. The training loop checks the flag at each step boundary, commits a
     final checkpoint, and exits with :data:`RC_PREEMPTED` (14).
  3. The supervisor (``launch/launcher.py:run_with_relaunch``) treats
     rc 14 as "resume me" — it relaunches immediately without consuming
     the crash budget or backing off.

A second signal escalates past the flag: ^C ^C raises
KeyboardInterrupt inline (an interactive user means it), and a second
SIGTERM re-delivers the signal with the guard uninstalled — a
supervisor's kill-after-grace must actually kill a wedged run, not be
shielded into another ignored flag flip.

No jax import; the guard must be installable before any backend.
"""

from __future__ import annotations

import signal
import sys
import threading

RC_PREEMPTED = 14


class PreemptionGuard:
    """Flag-setting SIGTERM/SIGINT handler with install/uninstall.

    Signal handlers only work in the main thread; ``install()`` in any
    other thread is a visible no-op (``active`` stays False) rather than
    an error, so library code can call it unconditionally.
    """

    def __init__(self, signals: tuple[int, ...] = (signal.SIGTERM,
                                                   signal.SIGINT)):
        self.signals = signals
        self.active = False
        self._requested = False
        self.signal_name: str | None = None
        self._saved: dict[int, object] = {}

    @property
    def requested(self) -> bool:
        return self._requested

    def _handle(self, signum, frame) -> None:
        if self._requested and signum in (signal.SIGINT, signal.SIGTERM):
            # Second signal: the sender means it — stop shielding.  ^C ^C
            # raises inline; a repeated SIGTERM (the supervisor's
            # kill-after-grace) is re-delivered with the pre-guard
            # handler restored, so the default action terminates the
            # process instead of flipping the flag it already flipped.
            self.uninstall()
            if signum == signal.SIGINT:
                raise KeyboardInterrupt
            signal.raise_signal(signum)
            return
        self._requested = True
        self.signal_name = signal.Signals(signum).name
        try:
            # Structured record of the preemption moment (obs/events.py),
            # only when the event layer is already loaded — this module
            # keeps its no-jax guarantee, and a signal handler must never
            # raise.
            events = sys.modules.get("tpuframe.obs.events")
            if events is not None:
                events.emit("preempt", signal=self.signal_name)
            # Flight dump at the signal, not at the rc-14 exit: if the
            # grace window expires mid-checkpoint the postmortem still
            # has the ring as of the SIGTERM.
            flight = sys.modules.get("tpuframe.obs.flight")
            if flight is not None:
                flight.dump(f"preempt_{self.signal_name}")
        except Exception:  # noqa: BLE001 — observability is optional here
            pass
        print(f"[tpuframe] received {self.signal_name} — will checkpoint "
              f"at the next step boundary and exit rc {RC_PREEMPTED} "
              f"(supervisor resumes)", file=sys.stderr, flush=True)

    def install(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            return self
        for sig in self.signals:
            self._saved[sig] = signal.getsignal(sig)
            signal.signal(sig, self._handle)
        self.active = True
        return self

    def reassert(self) -> None:
        """Re-register after something else replaced the handler.

        ``jax.distributed.initialize`` starts XLA's preemption notifier,
        which installs its own SIGTERM handler that only logs the signal —
        silently disabling the rc-14 contract.  Callers that initialize a
        distributed backend after :meth:`install` must call this to take
        the signal back.  ``_saved`` is left untouched so ``uninstall()``
        still restores the pre-guard handlers.
        """
        if not self.active:
            return
        for sig in self.signals:
            if signal.getsignal(sig) is not self._handle:
                signal.signal(sig, self._handle)

    def uninstall(self) -> None:
        for sig, old in self._saved.items():
            try:
                signal.signal(sig, old)
            except (ValueError, TypeError):  # not main thread / exotic old
                pass
        self._saved.clear()
        self.active = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
