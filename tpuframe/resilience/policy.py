"""Retry policies for transient I/O failures.

Every storage operation in the framework (``data/gcs.py``, checkpoint
shard reads/writes) runs under a :class:`RetryPolicy`: exponential
backoff with *decorrelated jitter* (each delay is drawn uniformly from
``[base, prev * 3]``, capped — avoids retry synchronization across a
pod's hosts, which all lose the same GCS endpoint at the same moment),
a per-attempt timeout plumbed into the client call where the client
supports one, and an overall deadline so a retry loop can never stall a
job longer than the heartbeat watchdog's window.

Classification is explicit: only *transient* errors retry.  A
``FileNotFoundError`` is a fact about the bucket, not the network, and
retrying it just turns a crisp error into a slow one.

Retry activity is surfaced through ``tpuframe.obs.metrics`` counters
(``retry.<op>.retries`` / ``.recovered`` / ``.exhausted``) so a flaky
storage backend is visible in the training log, not just in latency.

The module must import without jax (gcs/launch import it first); the
metrics bump is lazy and best-effort.
"""

from __future__ import annotations

import functools
import random
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable

# Exception types that are facts about the request, not the transport —
# retrying them cannot help.
_NON_RETRYABLE_OS = (
    FileNotFoundError,
    FileExistsError,
    IsADirectoryError,
    NotADirectoryError,
    PermissionError,
)

# Transient google-cloud / requests / urllib3 error classes, matched by
# name so the classification works without those packages importable
# (the sandbox has no GCS client; production TPU-VMs do).
_RETRYABLE_NAMES = frozenset({
    "ServiceUnavailable",       # 503
    "TooManyRequests",          # 429
    "InternalServerError",      # 500
    "BadGateway",               # 502
    "GatewayTimeout",           # 504
    "DeadlineExceeded",
    "RetryError",
    "TransportError",
    "ChunkedEncodingError",
    "ProtocolError",
    "IncompleteRead",
})


def is_retryable(exc: BaseException) -> bool:
    """Default transient-vs-permanent classification."""
    if isinstance(exc, _NON_RETRYABLE_OS):
        return False
    # ConnectionError/TimeoutError are OSError subclasses; generic OSError
    # (reset, EIO, transient NFS/FUSE failures) is treated as transient —
    # the permanent shapes are excluded above.
    if isinstance(exc, OSError):
        return True
    return any(c.__name__ in _RETRYABLE_NAMES for c in type(exc).__mro__)


def _bump(name: str) -> None:
    """Best-effort counter increment — a broken metrics import must never
    break a retry loop mid-recovery."""
    try:
        from tpuframe.obs import metrics

        metrics.bump(name)
    except Exception:  # noqa: BLE001 — observability is strictly optional here
        pass


def _emit_retry(op: str, outcome: str, attempt: int) -> None:
    """Best-effort structured ``retry`` event — same contract as
    ``_bump``: never raises back into the retry loop.  Uses the event
    module only when something else (train.py) already imported it, so
    this module keeps its no-jax import guarantee (the obs package pulls
    jax in)."""
    try:
        events = sys.modules.get("tpuframe.obs.events")
        if events is not None:
            # attempt_n, not attempt: the envelope's ``attempt`` is the
            # supervisor relaunch counter, and emit's **fields override it.
            events.emit("retry", op=op, outcome=outcome, attempt_n=attempt)
    except Exception:  # noqa: BLE001 — observability is strictly optional here
        pass


@dataclass
class RetryPolicy:
    """Bounded retry with decorrelated jitter.

    ``clock``/``sleep``/``rng`` are injectable so the timing behavior is
    unit-testable with a fake clock (tests/test_resilience.py).
    """

    max_attempts: int = 5
    base_delay_s: float = 0.05
    max_delay_s: float = 5.0
    # Plumbed into client calls that accept a timeout (the GCS blob API
    # does); enforcement of a hung attempt that ignores it is the stall
    # watchdog's job (obs/heartbeat).
    attempt_timeout_s: float | None = 60.0
    deadline_s: float | None = 120.0
    retryable: Callable[[BaseException], bool] = field(default=is_retryable)
    clock: Callable[[], float] = field(default=time.monotonic)
    sleep: Callable[[float], None] = field(default=time.sleep)
    rng: random.Random = field(default_factory=random.Random)

    def call(self, fn: Callable[..., Any], *args: Any, op: str = "io",
             **kwargs: Any) -> Any:
        """Run ``fn(*args, **kwargs)`` under this policy; re-raises the
        last error when attempts or the deadline run out, immediately for
        non-retryable errors."""
        start = self.clock()
        delay = self.base_delay_s
        attempt = 0
        while True:
            attempt += 1
            try:
                out = fn(*args, **kwargs)
                if attempt > 1:
                    _bump(f"retry.{op}.recovered")
                    _emit_retry(op, "recovered", attempt)
                return out
            except Exception as e:  # noqa: BLE001 — classified right below
                if not self.retryable(e):
                    raise
                if attempt >= self.max_attempts:
                    _bump(f"retry.{op}.exhausted")
                    _emit_retry(op, "exhausted", attempt)
                    raise
                # Decorrelated jitter: uniform over [base, prev*3], capped.
                delay = min(self.max_delay_s,
                            self.rng.uniform(self.base_delay_s,
                                             max(self.base_delay_s,
                                                 delay * 3.0)))
                if self.deadline_s is not None:
                    remaining = self.deadline_s - (self.clock() - start)
                    if remaining <= 0.0:
                        _bump(f"retry.{op}.exhausted")
                        _emit_retry(op, "exhausted", attempt)
                        raise
                    delay = min(delay, remaining)
                _bump(f"retry.{op}.retries")
                _emit_retry(op, "retrying", attempt)
                print(f"[resilience] {op} failed "
                      f"(attempt {attempt}/{self.max_attempts}): "
                      f"{type(e).__name__}: {e} — retrying in {delay:.2f}s",
                      file=sys.stderr, flush=True)
                self.sleep(delay)

    def wrap(self, fn: Callable[..., Any], *, op: str | None = None
             ) -> Callable[..., Any]:
        """``fn`` bound to this policy (``op`` defaults to the fn name)."""
        name = op or getattr(fn, "__name__", "io")

        @functools.wraps(fn)
        def wrapped(*args: Any, **kwargs: Any) -> Any:
            return self.call(fn, *args, op=name, **kwargs)

        return wrapped


def retrying(policy: RetryPolicy, *, op: str | None = None):
    """Decorator form: ``@retrying(policy, op="gcs_read")``."""

    def deco(fn: Callable[..., Any]) -> Callable[..., Any]:
        return policy.wrap(fn, op=op)

    return deco
