"""AOT-compiled prefill/decode engine — the one sanctioned compile seam
of ``tpuframe.serve``.

Every jitted program in the serving path lives HERE, compiled ahead of
time against the closed set of bucketed shapes from ``serve.kv_cache``:

  prefill[b]  (params, ids[1, b], length[1])        -> (tok[1], cache)
              one per prompt bucket ``b`` — causal attention over the
              left-aligned padded prompt (identical math to the training
              forward, so golden-logits parity is by construction) plus
              the KV write, sampling the first output token at
              ``length - 1``.
  decode      (params, toks[S, 1], lengths[S], cache) -> updated triple
              one program total — the query-length-1 step over all
              ``S`` slots at once, ring-writing each slot's KV at its
              own index (ops.attention.decode_attention).  Cache,
              lengths and token buffers are DONATED: the executable
              updates HBM in place, so a decode step's traffic is
              exactly params + touched KV — the quantity the roofline
              bound (tune/roofline.decode_score) models.
  insert      (cache, lengths, toks, pcache, slot, len, tok) -> updated
              one program total — copies a finished prefill's
              single-slot cache into the shared decode cache at a
              traced slot index (continuous batching's admission op).

The scheduler/loadgen layers above call these executables and are
forbidden (lint TF109) from calling ``jit``/``.apply`` themselves — a
novel shape reaching the compiler mid-serving is a silent multi-second
stall, the serving analogue of the TF106 dead-env-write footgun.

Greedy argmax sampling keeps the engine deterministic (and its compiled
programs free of typed PRNG-key outputs, so they are persistent-cache
safe on every jax — ``utils.compile_cache.outputs_cache_safe``).
"""

from __future__ import annotations

import time

import numpy as np

from tpuframe.serve import kv_cache as kv


def make_prefill_fn(model, spec: kv.CacheSpec):
    """The prefill step program (shared with the analysis-gate strategy
    audit so the audited program IS the served program).  Batch 1: one
    request prefills at a time; the capacity is the full decode ring so
    insertion is a single batch-dim slice copy."""
    import jax.numpy as jnp

    shape = (1, spec.capacity, spec.num_heads, spec.head_dim)
    dtype = jnp.dtype(spec.dtype)

    def prefill_fn(params, ids, length):
        layers = tuple((jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                       for _ in range(spec.num_layers))
        logits, layers = model.apply(
            {"params": params}, ids, kv_cache=layers,
            cache_length=jnp.zeros((1,), jnp.int32), decode=False)
        last = jnp.take_along_axis(logits, (length - 1)[:, None, None],
                                   axis=1)  # [1, 1, V] at the true end
        tok = jnp.argmax(last[:, 0, :], axis=-1).astype(jnp.int32)
        return tok, layers

    return prefill_fn


def make_decode_fn(model):
    """The decode step program: one token for every slot, ring KV write,
    greedy argmax.  ``lengths`` advances for every slot (inactive slots
    decode garbage the scheduler ignores — branchless beats a per-slot
    cond on TPU, and the ring write keeps wraparound safe)."""
    import jax.numpy as jnp

    def decode_fn(params, tokens, lengths, layers):
        logits, layers = model.apply(
            {"params": params}, tokens, kv_cache=layers,
            cache_length=lengths, decode=True)
        nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
        return nxt[:, None], lengths + 1, layers

    return decode_fn


def make_insert_fn(num_layers: int):
    """Admission: copy a prefilled single-slot cache into the shared
    decode cache at a *traced* slot index — one compiled program serves
    every slot."""
    from jax import lax

    def insert_fn(layers, lengths, tokens, p_layers, slot, length, tok):
        out = []
        for (k, v), (pk, pv) in zip(layers, p_layers):
            out.append((lax.dynamic_update_slice(k, pk, (slot, 0, 0, 0)),
                        lax.dynamic_update_slice(v, pv, (slot, 0, 0, 0))))
        lengths = lax.dynamic_update_slice(lengths, length[None], (slot,))
        tokens = lax.dynamic_update_slice(tokens, tok[None, None],
                                          (slot, 0))
        return tuple(out), lengths, tokens

    if num_layers < 1:
        raise ValueError("need at least one layer")
    return insert_fn


class LMEngine:
    """Bucketed AOT serving engine for :class:`TransformerLM`.

    Owns the decode cache (``slots`` concurrent sequences) and the AOT
    executable table.  All compilation happens in ``__init__`` — by the
    time ``prefill``/``decode_step`` run, every shape the engine will
    ever execute is already compiled, and with the persistent compile
    cache (PR 3) enabled, already on disk for the next restart.
    """

    def __init__(self, cfg, params=None, *, slots: int = 4,
                 max_context: int | None = None, prompt_buckets=None,
                 decode_block: int | None = None, eos_id: int | None = None,
                 seed: int = 0, enable_persistent_cache: bool = True):
        import jax
        import jax.numpy as jnp

        from tpuframe.models.transformer_lm import TransformerLM
        from tpuframe.utils import compile_cache

        if enable_persistent_cache:
            compile_cache.enable()
        self.cfg = cfg
        self.model = TransformerLM(cfg)
        self.eos_id = eos_id
        self.last_prefill_ms = 0.0
        self.decode_block = (decode_block if decode_block is not None
                             else kv.resolve_decode_block())
        buckets = (tuple(prompt_buckets) if prompt_buckets is not None
                   else kv.resolve_buckets())
        self.prompt_buckets = tuple(sorted(set(buckets)))
        max_context = max_context or max(self.prompt_buckets)
        capacity = kv.capacity_for(max_context, self.decode_block)
        problems = kv.check_buckets(self.prompt_buckets, capacity)
        if problems:
            raise ValueError("; ".join(problems))
        self.spec = kv.spec_for_model(cfg, slots=slots, capacity=capacity)

        if params is None:
            params = self.model.init(
                jax.random.key(seed),
                jnp.zeros((1, min(self.prompt_buckets)), jnp.int32)
            )["params"]
        self.params = params

        # --- the AOT table -------------------------------------------------
        sds = jax.ShapeDtypeStruct
        p_sds = jax.tree.map(lambda a: sds(a.shape, a.dtype), params)
        cache_sds = tuple(
            (sds(self.spec.layer_shape(), jnp.dtype(self.spec.dtype)),
             sds(self.spec.layer_shape(), jnp.dtype(self.spec.dtype)))
            for _ in range(cfg.num_layers))
        pcache_sds = jax.tree.map(
            lambda s: sds((1,) + s.shape[1:], s.dtype), cache_sds)
        i32 = jnp.int32

        self._prefill = {}
        for b in self.prompt_buckets:
            fn = make_prefill_fn(self.model, self.spec)
            self._prefill[b] = jax.jit(fn).lower(
                p_sds, sds((1, b), i32), sds((1,), i32)).compile()

        decode_fn = make_decode_fn(self.model)
        self._decode = jax.jit(decode_fn, donate_argnums=(1, 2, 3)).lower(
            p_sds, sds((slots, 1), i32), sds((slots,), i32),
            cache_sds).compile()

        insert_fn = make_insert_fn(cfg.num_layers)
        self._insert = jax.jit(insert_fn, donate_argnums=(0, 1, 2)).lower(
            cache_sds, sds((slots,), i32), sds((slots, 1), i32),
            pcache_sds, sds((), i32), sds((), i32), sds((), i32)).compile()

        # Cache-safety contract (ISSUE 6 satellite): none of the serving
        # programs may output typed PRNG keys, so the persistent cache is
        # safe for them even on jax < 0.6 (safe_for_key_outputs() False).
        out = jax.eval_shape(decode_fn, p_sds,
                             sds((slots, 1), i32), sds((slots,), i32),
                             cache_sds)
        if not compile_cache.outputs_cache_safe(out):
            raise RuntimeError(
                "decode step outputs an extended dtype — persistent-cache "
                "unsafe on this jax; keep PRNG keys out of serve programs")
        self.reset()

    # --- state -------------------------------------------------------------

    def reset(self) -> None:
        """Fresh (zeroed) decode cache; every slot becomes free."""
        import jax.numpy as jnp

        self._layers, self._lengths = kv.init_cache(self.spec)
        self._tokens = jnp.zeros((self.spec.slots, 1), jnp.int32)

    @property
    def slots(self) -> int:
        return self.spec.slots

    def compiled_programs(self) -> dict:
        """The AOT table, for census/tests: name -> compiled."""
        table = {f"prefill_{b}": c for b, c in self._prefill.items()}
        table["decode"] = self._decode
        table["insert"] = self._insert
        return table

    def swap_params(self, new_params) -> None:
        """The ONE sanctioned live weight-swap seam (lint TF121).

        Hot-swaps the served weights without touching the AOT table:
        every executable takes ``params`` as a call argument, so
        rebinding the attribute is the whole swap — zero recompiles by
        construction, which is exactly the compile-cache hit floor the
        rollout controller asserts.  The new tree must match the old one
        leaf-for-leaf in shape and dtype (a serving fleet's params are
        replicated, so a checkpoint written at a different world size
        reassembles to this same replicated tree — the world-size
        invariance the elastic restore path guarantees; only the flat
        ZeRO-1 *optimizer* moments ever reshard, and serving never
        loads those).  A mismatched tree means the checkpoint is for a
        different model: refuse loudly rather than serve garbage."""
        import jax

        old_leaves, old_def = jax.tree.flatten(self.params)
        new_leaves, new_def = jax.tree.flatten(new_params)
        if old_def != new_def:
            raise ValueError(
                "swap_params: new weights have a different tree "
                "structure — this checkpoint is not for this model")
        for i, (a, b) in enumerate(zip(old_leaves, new_leaves)):
            if a.shape != b.shape or a.dtype != b.dtype:
                raise ValueError(
                    f"swap_params: leaf {i} is {b.shape}/{b.dtype}, "
                    f"engine compiled for {a.shape}/{a.dtype} — a "
                    f"shape-changing update needs a new engine, not a "
                    f"hot swap")
        self.params = new_params

    # --- serving ops -------------------------------------------------------

    def prefill(self, token_ids) -> tuple:
        """Run one prompt through its bucket's prefill executable.
        Returns ``(first_token: int, prefill_cache, length: int)``."""
        import jax.numpy as jnp

        ids = list(int(t) for t in token_ids)
        if not ids:
            raise ValueError("empty prompt")
        bucket = kv.bucket_for(len(ids), self.prompt_buckets)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(ids)] = ids
        t0 = time.monotonic()
        tok, pcache = self._prefill[bucket](
            self.params, jnp.asarray(padded),
            jnp.asarray([len(ids)], jnp.int32))
        first = int(tok[0])   # host sync: the first token materializes
        # Host-observed executable time (through the sync above) — the
        # scheduler's prefill trace span reports it as ``engine_ms`` so
        # waterfalls split bucket-dispatch overhead from device work.
        self.last_prefill_ms = 1e3 * (time.monotonic() - t0)
        return first, pcache, len(ids)

    def insert(self, slot: int, pcache, length: int,
               first_token: int) -> None:
        """Admit a prefilled request into ``slot`` of the decode batch."""
        import jax.numpy as jnp

        if not 0 <= slot < self.spec.slots:
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.spec.slots})")
        self._layers, self._lengths, self._tokens = self._insert(
            self._layers, self._lengths, self._tokens, pcache,
            jnp.asarray(slot, jnp.int32), jnp.asarray(length, jnp.int32),
            jnp.asarray(first_token, jnp.int32))

    def decode_step(self) -> np.ndarray:
        """One decode step over every slot.  Returns the new token per
        slot (host numpy [slots]; inactive slots carry garbage the
        scheduler ignores)."""
        self._tokens, self._lengths, self._layers = self._decode(
            self.params, self._tokens, self._lengths, self._layers)
        return np.asarray(self._tokens[:, 0])


# ---------------------------------------------------------------------------
# Single-shot BERT classification — the non-autoregressive serving path.
# ---------------------------------------------------------------------------

class BertClassifier:
    """Bucketed AOT single-shot classifier: no cache, one executable per
    sequence bucket, batch 1 — the GLUE-style request/response shape."""

    def __init__(self, cfg, params=None, *, buckets=(64, 128),
                 seed: int = 0):
        import jax
        import jax.numpy as jnp

        from tpuframe.models.bert import BertForSequenceClassification

        self.cfg = cfg
        self.model = BertForSequenceClassification(cfg)
        self.buckets = tuple(sorted(set(buckets)))
        if max(self.buckets) > cfg.max_position:
            raise ValueError(f"bucket {max(self.buckets)} exceeds "
                             f"max_position {cfg.max_position}")
        if params is None:
            b0 = min(self.buckets)
            params = self.model.init(
                jax.random.key(seed), jnp.zeros((1, b0), jnp.int32)
            )["params"]
        self.params = params

        def classify_fn(params, ids, mask):
            logits = self.model.apply({"params": params}, ids,
                                      attention_mask=mask)
            return jax.nn.softmax(logits, axis=-1)

        sds = jax.ShapeDtypeStruct
        p_sds = jax.tree.map(lambda a: sds(a.shape, a.dtype), params)
        self._classify = {
            b: jax.jit(classify_fn).lower(
                p_sds, sds((1, b), jnp.int32),
                sds((1, b), jnp.int32)).compile()
            for b in self.buckets}

    def classify(self, token_ids) -> tuple:
        """-> ``(label: int, probs: np.ndarray[num_classes])``."""
        import jax.numpy as jnp

        ids = list(int(t) for t in token_ids)
        bucket = kv.bucket_for(len(ids), self.buckets)
        padded = np.zeros((1, bucket), np.int32)
        mask = np.zeros((1, bucket), np.int32)
        padded[0, :len(ids)] = ids
        mask[0, :len(ids)] = 1
        probs = np.asarray(self._classify[bucket](
            self.params, jnp.asarray(padded), jnp.asarray(mask))[0])
        return int(probs.argmax()), probs


def swap_parity_check(cfg, *, buckets, decode_tokens: int = 4,
                      seed: int = 0, decode_block: int = 16) -> list:
    """The hot-swap analogue of :func:`golden_parity_check`: an engine
    swapped onto new weights must produce, for every serve bucket (full
    and ragged prompt), exactly the token streams of an engine
    cold-started on those weights — AND the swap itself must cost zero
    compile-cache misses (the AOT table is untouched; params are call
    arguments).  Returns problem strings; [] means the swap is
    transparent."""
    import jax
    import jax.numpy as jnp

    from tpuframe.obs import metrics

    buckets = tuple(sorted(buckets))
    max_context = max(buckets) + decode_tokens + decode_block
    hot = LMEngine(cfg, slots=2, prompt_buckets=buckets,
                   decode_block=decode_block, max_context=max_context,
                   seed=seed)
    new_params = hot.model.init(
        jax.random.key(seed + 1),
        jnp.zeros((1, min(buckets)), jnp.int32))["params"]
    cold = LMEngine(cfg, new_params, slots=2, prompt_buckets=buckets,
                    decode_block=decode_block, max_context=max_context)

    misses_before = metrics.counters().get("compile_cache.misses", 0)
    hot.swap_params(new_params)

    problems = []

    def stream(engine, ids):
        engine.reset()
        first, pcache, length = engine.prefill(ids)
        engine.insert(0, pcache, length, first)
        toks = [first]
        for _ in range(decode_tokens):
            toks.append(int(engine.decode_step()[0]))
        return toks

    for bucket in buckets:
        for prompt_len in sorted({bucket, max(2, bucket - 3)}):
            ids = [int(t) for t in jax.random.randint(
                jax.random.key(seed + bucket + prompt_len),
                (prompt_len,), 0, cfg.vocab_size)]
            got, want = stream(hot, ids), stream(cold, ids)
            if got != want:
                problems.append(
                    f"bucket {bucket} prompt_len {prompt_len}: "
                    f"hot-swapped stream {got} != cold-start {want}")

    misses_after = metrics.counters().get("compile_cache.misses", 0)
    if misses_after != misses_before:
        problems.append(
            f"swap cost {misses_after - misses_before} compile-cache "
            f"miss(es) — the hot-swap path must never recompile")
    return problems


# ---------------------------------------------------------------------------
# Golden-logits parity — the correctness contract of the whole cache path.
# ---------------------------------------------------------------------------

def golden_parity_check(cfg, *, buckets, capacity: int,
                        decode_tokens: int = 4, seed: int = 0,
                        atol: float = 2e-5) -> list:
    """Prefill-then-decode must reproduce the training forward's logits
    position-by-position, for every prompt bucket (both a full bucket
    and a ragged prompt that exercises the length mask).  Returns
    problem strings; [] means parity holds.

    Uses raw ``model.apply`` on purpose — this file is the sanctioned
    compile seam, and the reference side must be the *training* path,
    not another serving program.
    """
    import jax
    import jax.numpy as jnp

    from tpuframe.models.transformer_lm import TransformerLM

    model = TransformerLM(cfg)
    problems = []
    params = None
    for bucket in buckets:
        for prompt_len in {bucket, max(2, bucket - 3)}:
            total = prompt_len + decode_tokens
            if total > capacity:
                problems.append(f"bucket {bucket}: prompt+decode {total} "
                                f"exceeds capacity {capacity}")
                continue
            ids = jax.random.randint(jax.random.key(seed + bucket),
                                     (1, total), 0, cfg.vocab_size)
            if params is None:
                params = model.init(jax.random.key(seed),
                                    jnp.zeros((1, 8), jnp.int32))["params"]
            ref = model.apply({"params": params}, ids)

            shape = (1, capacity, cfg.num_heads, cfg.head_dim)
            layers = tuple(
                (jnp.zeros(shape, cfg.jnp_dtype),
                 jnp.zeros(shape, cfg.jnp_dtype))
                for _ in range(cfg.num_layers))
            got_p, layers = model.apply(
                {"params": params}, ids[:, :prompt_len], kv_cache=layers,
                cache_length=jnp.zeros((1,), jnp.int32), decode=False)
            outs = [got_p]
            length = jnp.asarray([prompt_len], jnp.int32)
            for t in range(prompt_len, total):
                lg, layers = model.apply(
                    {"params": params}, ids[:, t:t + 1], kv_cache=layers,
                    cache_length=length, decode=True)
                outs.append(lg)
                length = length + 1
            got = jnp.concatenate(outs, axis=1)
            diff = float(jnp.max(jnp.abs(ref - got)))
            if diff > atol:
                problems.append(
                    f"bucket {bucket} prompt_len {prompt_len}: max "
                    f"|logit diff| {diff:.2e} > {atol:.0e}")
    return problems
