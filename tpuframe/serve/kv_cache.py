"""Paged/ring KV-cache for the decode path (tpuframe.serve).

The cache is the serving counterpart of a training batch: per layer one
``(k, v)`` pair of ``[slots, capacity, num_heads, head_dim]`` arrays plus
a ``lengths [slots]`` vector counting tokens already cached per slot.
It is deliberately a *plain pytree of arrays*, not an object the model
mutates: the engine threads it functionally through the AOT-compiled
prefill/decode executables (arrays in, updated arrays out), which is
what makes buffer donation — and therefore in-place HBM updates — legal.

Ring semantics: the model writes token ``t`` at index ``t % capacity``
and masks attention to ``min(t + 1, capacity)`` valid entries, so a
sequence that outlives its bucket degrades to sliding-window attention
over the last ``capacity`` tokens instead of faulting.  Keys are stored
post-RoPE, so a wrapped slot keeps the absolute position it was written
with (see ``models/transformer_lm.py:CausalSelfAttention``).

Shape bucketing lives here too: every compiled shape (prompt buckets,
KV capacity) is a multiple of the decode block, so the engine's AOT
table is a small closed set and the persistent compile cache (PR 3) can
amortize warmup across restarts.  Bucket sets resolve env > tune-DB >
default (``TPUFRAME_SERVE_BUCKETS`` / ``TPUFRAME_DECODE_BLOCK``, the
PR 3/5 precedence idiom via ``tune.db``).
"""

from __future__ import annotations

from dataclasses import dataclass

# Hard defaults — what a plain CPU run (no env, no tune DB) sees.  128
# matches the flash-attention default block edge and the (8, 128) TPU
# tile; prompt buckets are powers of two over it so padding waste is
# bounded at 2x worst-case.
DEFAULT_DECODE_BLOCK = 128
DEFAULT_PROMPT_BUCKETS = (128, 256, 512)


@dataclass(frozen=True)
class CacheSpec:
    """Static shape contract of one engine's cache — everything the AOT
    table is keyed on."""

    slots: int           # decode batch size (concurrent sequences)
    capacity: int        # KV entries per slot (ring length)
    num_layers: int
    num_heads: int
    head_dim: int
    dtype: str = "float32"

    def __post_init__(self):
        if self.capacity % 8:
            raise ValueError(f"capacity {self.capacity} not a multiple of "
                             f"8 (TPU sublane alignment)")
        if self.slots < 1:
            raise ValueError(f"need at least one slot, got {self.slots}")

    def layer_shape(self) -> tuple:
        return (self.slots, self.capacity, self.num_heads, self.head_dim)

    def bytes_per_token(self) -> int:
        """HBM bytes one cached token costs across all layers (K + V) —
        the ``kv_bytes_per_token`` input of the decode roofline
        (tune/roofline.decode_score)."""
        import numpy as np

        itemsize = np.dtype(self.dtype).itemsize
        return 2 * self.num_layers * self.num_heads * self.head_dim \
            * itemsize

    def total_bytes(self) -> int:
        return self.slots * self.capacity * self.bytes_per_token()


def init_cache(spec: CacheSpec):
    """Zeroed per-layer ``(k, v)`` pairs + zero lengths — the engine's
    reset state.  Returns ``(layers, lengths)``."""
    import jax.numpy as jnp

    shape = spec.layer_shape()
    dtype = jnp.dtype(spec.dtype)
    layers = tuple((jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
                   for _ in range(spec.num_layers))
    lengths = jnp.zeros((spec.slots,), jnp.int32)
    return layers, lengths


def spec_for_model(cfg, *, slots: int, capacity: int) -> CacheSpec:
    """CacheSpec derived from an ``LMConfig`` (single source for the
    layer geometry — the spec can never disagree with the model)."""
    return CacheSpec(slots=slots, capacity=capacity,
                     num_layers=cfg.num_layers, num_heads=cfg.num_heads,
                     head_dim=cfg.head_dim, dtype=cfg.dtype)


# ---------------------------------------------------------------------------
# Shape buckets — the closed set of compiled shapes.
# ---------------------------------------------------------------------------

def parse_buckets(text: str) -> tuple:
    """``"64,128,256"`` -> ``(64, 128, 256)`` (sorted, deduplicated).
    The TPUFRAME_SERVE_BUCKETS wire format."""
    vals = sorted({int(v) for v in text.replace(";", ",").split(",")
                   if v.strip()})
    if not vals:
        raise ValueError(f"no buckets in {text!r}")
    if any(v < 8 or v % 8 for v in vals):
        raise ValueError(f"buckets must be multiples of 8, got {vals}")
    return tuple(vals)


def resolve_buckets(default=DEFAULT_PROMPT_BUCKETS) -> tuple:
    """Prompt-length buckets: env > tune-DB > default (tune.db owns the
    precedence chain so serving and training resolve identically)."""
    from tpuframe.tune import db as tune_db

    return tune_db.resolve_serve_buckets(tuple(default))


def resolve_decode_block(default: int = DEFAULT_DECODE_BLOCK) -> int:
    """KV-capacity granularity: env > tune-DB > default."""
    from tpuframe.tune import db as tune_db

    return tune_db.resolve_decode_block(default)


def bucket_for(length: int, buckets) -> int:
    """Smallest bucket that fits ``length``.  Raises when the request
    exceeds every bucket — admission control's job is to reject it
    BEFORE any compile-shape decision, never to pick a silent new
    shape (that is exactly the recompile-per-request failure mode the
    TF109 lint guards)."""
    for b in buckets:
        if length <= b:
            return b
    raise ValueError(f"prompt length {length} exceeds largest bucket "
                     f"{max(buckets)} — reject at admission")


def capacity_for(max_context: int, decode_block: int) -> int:
    """KV capacity for a target context: round up to the decode block so
    every compiled capacity is block-quantized."""
    if max_context < 1:
        raise ValueError(f"max_context must be positive, got {max_context}")
    blocks = (max_context + decode_block - 1) // decode_block
    return blocks * decode_block


def check_buckets(buckets, capacity: int) -> list:
    """Invariants the analysis-gate self-check enforces.  Returns
    problem strings; [] means healthy."""
    problems = []
    bl = tuple(buckets)
    if bl != tuple(sorted(set(bl))):
        problems.append(f"buckets not sorted/unique: {bl}")
    if any(b < 8 or b % 8 for b in bl):
        problems.append(f"buckets not multiples of 8: {bl}")
    if bl and max(bl) > capacity:
        problems.append(f"largest bucket {max(bl)} exceeds KV capacity "
                        f"{capacity} — prefill would overrun the ring")
    if capacity % 8:
        problems.append(f"capacity {capacity} not a multiple of 8")
    return problems
