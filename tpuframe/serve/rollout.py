"""Live weight rollout — zero-loss rolling updates, canary gating and
auto-rollback across the serving fleet.

The one production loop the earlier layers left unwired: training emits
commit-last checkpoints (``ckpt/checkpoint.py``), the fleet serves
behind the health-driven router (``serve/router.py``), and a hot swap
at unchanged shapes never recompiles (``serve/engine.py:swap_params`` —
params are call arguments to the AOT table, the same shapes-are-known
property that lets the elastic layer reshard training state n→n′
without recompiling).  This controller composes them so a new
checkpoint reaches every replica without restarting the fleet or
dropping a single admitted request.

The state machine (DESIGN.md "Live rollout & canary")::

    idle ──watch sees a committed step──▶ canary: drain → swap →
    readmit (+ seeded traffic fraction) ──▶ bake: old-vs-new TTFT/TPOT
    through ``obs compare``'s thresholds ──rc 0──▶ roll the rest, one
    replica at a time (drain → swap → readmit) ──▶ done
                                          └─rc 1 (or starved gate)──▶
    abort: drain the canary, swap the OLD version back, readmit ──▶
    aborted

Invariants the chaos tier proves:

  zero loss      every phase rides the router's existing drain/
                 redispatch contract — a draining replica finishes its
                 accepted work while the router re-places the rest, so
                 ``sorted(router_request ids) == sorted(router_admit
                 ids)`` holds straight through a roll.
  bounded mix    at most ONE replica is mid-transition at a time; the
                 mixed-version window (first replica on the new version
                 → last one) is surfaced as ``window_s`` and recomputed
                 offline by ``goodput.fleet_stats`` from the typed
                 ``rollout_step`` events.
  hit floor      a swap at unchanged shapes costs zero compile-cache
                 misses — asserted per swap from the replica's counter
                 delta (``swap_compile_misses`` in the summary).
  honest gate    promotion needs BOTH sides of the comparison: the gate
                 metrics participate only-when-both (the ``obs
                 compare`` contract), and a bake that never collects
                 enough canary samples rolls BACK rather than promote
                 blind.

Watching: ``ckpt.committed_world()`` is the read-only peek — a dir
mid-commit (no COMMIT), a quarantined ``step_N.corrupt`` or a torn
manifest is invisible/None by construction, so a partial upload can
never trigger a rollout.  A checkpoint from a different world size is
fine: serving params are replicated and reassemble world-size
invariantly (only flat ZeRO-1 *moments* ever reshard, and serving never
loads those).

Env knobs: ``TPUFRAME_ROLLOUT_WATCH`` (checkpoint dir to poll),
``TPUFRAME_CANARY_FRAC`` (seeded traffic fraction to the canary,
default 0.25; 0 disables the canary), ``TPUFRAME_ROLLOUT_GATE``
(TTFT/TPOT p90 regression threshold in %, default 25; 0 disables the
gate).

No jax import at module scope: the controller drives a fleet over HTTP
and must stay as light as the router; the checkpoint peek is imported
lazily on first watch poll.
"""

from __future__ import annotations

import os
import time

from tpuframe.obs import events as obs_events
from tpuframe.obs import tracing
from tpuframe.resilience.policy import RetryPolicy
from tpuframe.serve.router import Router, parse_gauges

ENV_WATCH = "TPUFRAME_ROLLOUT_WATCH"
ENV_CANARY_FRAC = "TPUFRAME_CANARY_FRAC"
ENV_GATE = "TPUFRAME_ROLLOUT_GATE"

DEFAULT_CANARY_FRAC = 0.25
DEFAULT_GATE_PCT = 25.0

ROLLOUT_EVENT_TYPES = ("rollout_step", "rollout_done", "rollout_abort")

# The promotion gate's metric universe: end-to-end TTFT at the router
# plus replica-reported TTFT/TPOT.  Everything else compare_runs knows
# (step times, MFU) is training-side and never participates here.
GATE_METRICS = ("router_ttft_p90_ms", "serve_ttft_p90_ms",
                "serve_tpot_p90_ms")

_SCRAPE_GAUGES = ("tpuframe_serve_queue_depth",
                  "tpuframe_serve_active_slots",
                  "tpuframe_weights_version")


def resolve_watch_dir() -> str | None:
    raw = os.environ.get(ENV_WATCH, "").strip()
    return raw or None


def resolve_canary_frac() -> float:
    raw = os.environ.get(ENV_CANARY_FRAC, "").strip()
    if not raw:
        return DEFAULT_CANARY_FRAC
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return DEFAULT_CANARY_FRAC


def resolve_gate_pct() -> float:
    raw = os.environ.get(ENV_GATE, "").strip()
    if not raw:
        return DEFAULT_GATE_PCT
    try:
        return max(0.0, float(raw))
    except ValueError:
        return DEFAULT_GATE_PCT


def gate_compare(baseline_events: list, canary_events: list, *,
                 pct: float) -> tuple[int, dict]:
    """The promotion gate: diff canary traffic against old-version
    traffic with ``goodput.compare_runs`` and return the ``obs
    compare`` rc contract restricted to the gate metrics — 0 promote,
    1 regression (roll back), 2 no overlapping gate metric (keep
    baking; NEVER promote on 2).  A metric participates only when both
    sides carry it, like every other compare metric."""
    from tpuframe.obs import goodput

    res = goodput.compare_runs(baseline_events, canary_events,
                               thresholds={"serve_pct": pct})
    present = [m for m in GATE_METRICS if m in res["metrics"]]
    if not present:
        return 2, res
    if any(r["metric"] in GATE_METRICS for r in res["regressions"]):
        return 1, res
    return 0, res


class RolloutController:
    """Drives one rolling weight update across a :class:`Router`'s fleet.

    Cooperative, not threaded: ``tick()`` is called once per router
    loop iteration (``Router.run(on_tick=...)``) and advances a
    non-blocking state machine, so request traffic keeps flowing — and
    keeps being measured — all the way through the roll.  The only
    blocking call is the swap POST itself, bounded by its RetryPolicy.
    """

    def __init__(self, router: Router, *, transport=None,
                 clock=time.monotonic, watch_dir: str | None = None,
                 watch_interval_s: float = 0.25,
                 current_version: int = 0,
                 canary_frac: float | None = None,
                 gate_pct: float | None = None,
                 bake_min_samples: int = 5, bake_timeout_s: float = 20.0,
                 drain_timeout_s: float = 15.0,
                 swap_timeout_s: float = 10.0,
                 relaunch_timeout_s: float = 30.0,
                 poll_interval_s: float = 0.05,
                 swap_seed: int | None = None, seed: int = 0, log=None):
        self.router = router
        self._transport = transport or router._transport
        self._clock = clock
        self.watch_dir = (resolve_watch_dir() if watch_dir is None
                          else watch_dir)
        self.watch_interval_s = watch_interval_s
        self.current_version = int(current_version)
        self.canary_frac = (resolve_canary_frac() if canary_frac is None
                            else min(1.0, max(0.0, float(canary_frac))))
        self.gate_pct = (resolve_gate_pct() if gate_pct is None
                         else max(0.0, float(gate_pct)))
        self.bake_min_samples = max(1, int(bake_min_samples))
        self.bake_timeout_s = bake_timeout_s
        self.drain_timeout_s = drain_timeout_s
        self.swap_timeout_s = swap_timeout_s
        self.relaunch_timeout_s = relaunch_timeout_s
        self.poll_interval_s = poll_interval_s
        # ``seed`` seeds the router's canary traffic split; ``swap_seed``
        # (real-engine fleets) tells the replica which weights to
        # regenerate — None means a metadata-only swap (FakeEngine).
        self.seed = seed
        self.swap_seed = swap_seed
        self._log = log or (lambda *_a: None)
        self._swap_policy = RetryPolicy(
            max_attempts=2, base_delay_s=0.02, max_delay_s=0.25,
            attempt_timeout_s=swap_timeout_s,
            deadline_s=2.0 * swap_timeout_s)

        self.state = "idle"        # idle|rolling|bake|done|aborted
        self.target: int | None = None
        self.world: dict | None = None      # committed_world() peek
        self.history: list = []    # (t, replica, phase)
        self.gate_result: dict | None = None
        self.abort_metric: str | None = None
        self.abort_reason: str | None = None
        self.swap_compile_misses = 0
        self.relaunches = 0
        self.window_s: float | None = None
        self._plan: list[str] = []
        self._cursor = 0
        self._phase: str | None = None
        self._phase_t = 0.0
        self._last_poll_t = -1e18
        self._last_watch_t = -1e18
        self._rollback = False
        self._swap_to: int | None = None
        self._first_swap_t: float | None = None
        self._last_swap_t: float | None = None
        self._bake_start_idx = 0
        self._bake_start_t = 0.0
        self._canary_name: str | None = None
        # Fleet-operation trace (always sampled — one per roll, never
        # volume): a "rollout" root span open for the whole roll, with
        # every per-replica phase as a note under it, so a request
        # waterfall and the swap that delayed it land in one event
        # stream with the same vocabulary.
        self.trace: str | None = None
        self._root_span: str | None = None
        self._trace_t0 = 0.0

    # -- observability ------------------------------------------------------

    def _emit(self, replica: str, phase: str, version: int) -> None:
        self.history.append((self._clock(), replica, phase))
        obs_events.emit("rollout_step", replica=replica, version=version,
                        phase=phase)
        if self.trace is not None:
            tracing.note(self.trace, phase, span=self._root_span,
                         replica=replica, version=version)
        self._log(f"rollout: {replica} {phase} (v{version})")

    def _close_trace(self, status: str) -> None:
        if self.trace is not None and self._root_span is not None:
            tracing.close_span(
                self.trace, self._root_span,
                1e3 * max(0.0, self._clock() - self._trace_t0),
                status=status, version=self.target)
            self._root_span = None

    def summary(self) -> dict:
        return {
            "state": self.state,
            "version": self.current_version,
            "target": self.target,
            "window_s": self.window_s,
            "swap_compile_misses": self.swap_compile_misses,
            "relaunches": self.relaunches,
            "aborted": self.state == "aborted",
            "abort_metric": self.abort_metric,
            "abort_reason": self.abort_reason,
            "phases": [(rep, phase) for _t, rep, phase in self.history],
            "world": self.world,
        }

    # -- the watch seam -----------------------------------------------------

    def poll_watch(self, now: float) -> int | None:
        """Peek the checkpoint directory through ``committed_world()``:
        only a COMMITTED step with a readable manifest is visible — a
        dir mid-commit, a quarantined ``.corrupt`` or a torn sidecar
        yields None and never triggers a rollout."""
        if self.watch_dir is None:
            return None
        if now - self._last_watch_t < self.watch_interval_s:
            return None
        self._last_watch_t = now
        from tpuframe.ckpt.checkpoint import committed_world

        info = committed_world(self.watch_dir)
        if info is None:
            return None
        step = int(info["step"])
        if step <= self.current_version:
            return None
        self.world = info
        return step

    # -- control ------------------------------------------------------------

    def start(self, target_version: int) -> bool:
        """Begin a roll to ``target_version``.  One roll at a time; the
        canary (when enabled) is the FIRST replica in the plan."""
        if self.state not in ("idle", "done", "aborted"):
            return False
        names = [rep.name for rep in self.router.replicas]
        if not names:
            return False
        self.target = int(target_version)
        self._plan = names
        self._cursor = 0
        self._rollback = False
        self._swap_to = self.target
        self._canary_name = (names[0] if self.canary_frac > 0
                             and len(names) > 1 else None)
        self.trace = tracing.mint(f"rollout-v{self.target}", force=True)
        self._trace_t0 = self._clock()
        self._root_span = tracing.open_span(
            self.trace, "rollout", version=self.target,
            replicas=len(names))
        self.state = "rolling"
        self._enter_phase("drain")
        self._log(f"rollout: v{self.current_version} -> v{self.target} "
                  f"over {names} (canary={self._canary_name})")
        return True

    def done(self) -> bool:
        return self.state in ("done", "aborted")

    def tick(self, now: float | None = None) -> bool:
        """Advance the state machine one notch.  Returns True while the
        rollout still has work (the ``Router.run(on_tick=...)`` keep-
        running signal)."""
        now = self._clock() if now is None else now
        if self.state == "idle":
            target = self.poll_watch(now)
            if target is not None:
                self.start(target)
            return self.state == "rolling"
        if self.state == "rolling":
            self._tick_rolling(now)
        elif self.state == "bake":
            self._tick_bake(now)
        return not self.done()

    # -- the per-replica submachine -----------------------------------------

    def _enter_phase(self, phase: str) -> None:
        self._phase = phase
        self._phase_t = self._clock()
        self._last_poll_t = -1e18

    def _rep_name(self) -> str:
        return self._plan[self._cursor]

    def _probe(self, name: str) -> dict | None:
        """Best-effort one-shot /metrics scrape of one replica (the
        router stops scraping a draining replica; the controller must
        keep watching it through the swap)."""
        rep = self.router._replica(name)
        if rep is None:
            return None
        try:
            status, text = self._transport(rep.url + "/metrics", None,
                                           self.swap_timeout_s)
        except Exception:  # noqa: BLE001 — dead/restarting replica is a
            return None    # normal state here, the caller keeps polling
        if status != 200:
            return None
        return parse_gauges(text if isinstance(text, str) else "",
                            _SCRAPE_GAUGES)

    def _tick_rolling(self, now: float) -> None:
        name = self._rep_name()
        if self._phase == "drain":
            self.router.drain_replica(
                name, reason=f"rollout:v{self._swap_to}")
            if not self._rollback:
                self._emit(name, "drain", self._swap_to)
            self._enter_phase("wait_drain")
            return
        if self._phase == "wait_drain":
            if now - self._last_poll_t < self.poll_interval_s:
                return
            self._last_poll_t = now
            gauges = self._probe(name)
            idle = (gauges is not None
                    and gauges.get("tpuframe_serve_active_slots", 1) == 0
                    and gauges.get("tpuframe_serve_queue_depth", 1) == 0)
            if idle:
                self._enter_phase("swap")
            elif now - self._phase_t > self.drain_timeout_s:
                # Proceed anyway: the router already redispatched the
                # replica's in-flight work, and a swap between scheduler
                # steps is safe — loud, not silent.
                self._log(f"rollout: {name} drain timed out after "
                          f"{self.drain_timeout_s}s; swapping anyway")
                self._enter_phase("swap")
            return
        if self._phase == "swap":
            self._do_swap(name)
            return
        if self._phase == "wait_relaunch":
            if now - self._phase_t > self.relaunch_timeout_s:
                self._abort("swap", f"replica {name} did not come back "
                                    f"on v{self._swap_to} within "
                                    f"{self.relaunch_timeout_s}s")
                return
            if now - self._last_poll_t < self.poll_interval_s:
                return
            self._last_poll_t = now
            gauges = self._probe(name)
            if (gauges is not None
                    and int(gauges.get("tpuframe_weights_version", -1))
                    == self._swap_to):
                self.relaunches += 1
                self._note_on_target(name, "relaunched")
                self._readmit(name)
            return

    def _do_swap(self, name: str) -> None:
        rep = self.router._replica(name)
        payload = {"version": self._swap_to}
        if self.swap_seed is not None and not self._rollback:
            payload["seed"] = self.swap_seed
        try:
            status, body = self._swap_policy.call(
                self._transport, rep.url + "/swap_weights", payload,
                self.swap_timeout_s, op="rollout_swap")
        except Exception as e:  # noqa: BLE001 — the replica died mid-
            # swap (crash_during_swap): wait for the supervisor to
            # relaunch it on the NEW version
            self._emit(name, "swap_failed", self._swap_to)
            self._log(f"rollout: swap on {name} failed "
                      f"({type(e).__name__}) — waiting for relaunch")
            self._enter_phase("wait_relaunch")
            return
        if status != 200 or not isinstance(body, dict):
            err = body.get("error") if isinstance(body, dict) else body
            self._abort("swap", f"replica {name} refused the swap "
                                f"({status}): {err}")
            return
        self.swap_compile_misses += int(
            body.get("compile_cache_misses") or 0)
        if not self._rollback:
            self._note_on_target(name, "swapped")
        self._readmit(name)

    def _note_on_target(self, name: str, phase: str) -> None:
        t = self._clock()
        if self._first_swap_t is None:
            self._first_swap_t = t
        self._last_swap_t = t
        self._emit(name, phase, self._swap_to)

    def _readmit(self, name: str) -> None:
        self.router.readmit(name)
        if self._rollback:
            self._emit(name, "rolled_back", self._swap_to)
        else:
            self._emit(name, "readmitted", self._swap_to)
        self._advance()

    def _advance(self) -> None:
        """Next replica — or the bake (after the canary), the finish
        line, or the end of a rollback."""
        if self._rollback:
            self.state = "aborted"
            self.router.clear_canary()
            self._close_trace("aborted")
            return
        name = self._rep_name()
        self._cursor += 1
        if name == self._canary_name:
            # Canary is live: steer the seeded fraction at it and bake.
            self.router.set_canary(name, self.canary_frac,
                                   seed=self.seed)
            if self.gate_pct > 0:
                self.state = "bake"
                self._bake_start_idx = len(self.router.completed)
                self._bake_start_t = self._clock()
                return
            # Gate disabled: promote immediately (explicitly asked for).
            self._promote()
            return
        if self._cursor >= len(self._plan):
            self._finish()
            return
        self._enter_phase("drain")

    def _promote(self) -> None:
        self.router.clear_canary()
        self._emit(self._canary_name or self._plan[0], "promoted",
                   self.target)
        if self._cursor >= len(self._plan):
            self._finish()
            return
        self.state = "rolling"
        self._enter_phase("drain")

    def _finish(self) -> None:
        self.router.clear_canary()
        self.window_s = (round(self._last_swap_t - self._first_swap_t, 6)
                         if self._first_swap_t is not None else 0.0)
        self.current_version = self.target
        self.state = "done"
        obs_events.emit("rollout_done", version=self.target,
                        replicas=len(self._plan),
                        window_s=self.window_s)
        self._close_trace("done")
        self._log(f"rollout: done — fleet on v{self.target}, "
                  f"mixed-version window {self.window_s}s")

    # -- the canary gate ----------------------------------------------------

    def _gate_events(self, reqs: list) -> list:
        """Synthesize the minimal typed event stream ``compare_runs``
        reads from one side's completed requests — the same shapes the
        real log carries, so the gate IS the ``obs compare`` contract."""
        evs = []
        for req in reqs:
            body = req.result or {}
            evs.append({"type": "router_request", "id": req.rid,
                        "replica": req.replica,
                        "ttft_ms": req.ttft_ms})
            evs.append({"type": "serve_request", "id": req.rid,
                        "prompt_tokens": len(req.prompt),
                        "output_tokens": len(body.get("tokens") or []),
                        "ttft_ms": body.get("ttft_ms"),
                        "tpot_ms": body.get("tpot_ms")})
        return evs

    def _tick_bake(self, now: float) -> None:
        baked = self.router.completed[self._bake_start_idx:]
        new_side = [r for r in baked if r.replica == self._canary_name]
        old_side = [r for r in baked if r.replica != self._canary_name]
        enough = (len(new_side) >= self.bake_min_samples
                  and len(old_side) >= self.bake_min_samples)
        starved = now - self._bake_start_t > self.bake_timeout_s
        if not enough and not starved:
            return
        rc, res = gate_compare(self._gate_events(old_side),
                               self._gate_events(new_side),
                               pct=self.gate_pct)
        self.gate_result = res
        if rc == 0 and enough:
            self._log(f"rollout: gate clean over {len(old_side)} old / "
                      f"{len(new_side)} canary samples — promoting")
            self._promote()
            return
        if rc == 1:
            bad = next(r for r in res["regressions"]
                       if r["metric"] in GATE_METRICS)
            self._abort(bad["metric"],
                        bad.get("detail") or f"{bad['metric']} regressed")
            return
        if starved:
            # rc 2 (or too few samples) at the deadline: the gate never
            # saw both sides — roll back rather than promote blind.
            self._abort("insufficient_data",
                        f"gate starved after {self.bake_timeout_s}s "
                        f"({len(old_side)} old / {len(new_side)} canary "
                        f"samples, need {self.bake_min_samples})")

    def _abort(self, metric: str, reason: str) -> None:
        """Regression (or a blind/unrecoverable roll): emit the abort
        with the failing metric, then roll the canary BACK to the old
        version through the same drain→swap→readmit machinery."""
        self.abort_metric = metric
        self.abort_reason = reason
        obs_events.emit("rollout_abort", version=self.target,
                        metric=metric, reason=reason)
        self._log(f"rollout: ABORT v{self.target} — {metric}: {reason}")
        self.router.clear_canary()
        swapped = [rep for _t, rep, phase in self.history
                   if phase in ("swapped", "relaunched")]
        if swapped and self.state in ("rolling", "bake"):
            # Restore every replica already moved (normally just the
            # canary — the bake gates before the rest roll).
            self._rollback = True
            self._swap_to = self.current_version
            self._plan = list(dict.fromkeys(swapped))
            self._cursor = 0
            self.state = "rolling"
            self._enter_phase("drain")
        else:
            self.state = "aborted"
            self._close_trace("aborted")


# ---------------------------------------------------------------------------
# Rolling-update fleet harness — subprocess replicas + router + controller,
# shared by the chaos tier (zero-loss, canary-rollback and mid-swap-kill
# proofs) and reusable from ``python -m tpuframe.serve`` drivers.
# ---------------------------------------------------------------------------

def rolling_update_smoke(*, replicas: int = 3, n_requests: int = 36,
                         seed: int = 0, slots: int = 2,
                         step_delay_ms: float = 20.0, rate: float = 1000.0,
                         max_new_tokens: int = 8,
                         queue_limit: int | None = 256,
                         hedge_ms: float | None = 5000.0,
                         scrape_interval_s: float = 0.05,
                         target_version: int = 1,
                         start_after_completed: int | None = None,
                         canary_frac: float = 0.34,
                         gate_pct: float | None = None,
                         bake_min_samples: int = 4,
                         bake_timeout_s: float = 20.0,
                         faults_spec: str | None = None,
                         kill_during_swap_rank: int | None = None,
                         watch_dir: str | None = None,
                         events_dir: str | None = None,
                         timeout_s: float = 90.0,
                         ready_timeout_s: float = 30.0,
                         log=None) -> dict:
    """Spawn a CPU fleet, drive the seeded loadgen through the router,
    and run one live rollout mid-load — returning the router summary,
    the controller summary, replica exit codes and the final scraped
    per-replica versions.

    ``faults_spec`` is armed on EVERY replica (the ``slow_canary`` seam
    self-scopes to whichever replica is serving new weights);
    ``kill_during_swap_rank`` arms ``crash_during_swap`` on one rank and
    the harness plays supervisor: a replica that dies mid-swap is
    relaunched on the SAME port with ``--weights-version`` set to the
    NEW version, which the controller detects and readmits.

    With ``watch_dir`` set, the rollout is triggered the production way:
    the harness "commits" checkpoint ``step_<target_version>`` (manifest
    already on disk, COMMIT written last) once ``start_after_completed``
    requests have retired, and the controller's ``committed_world()``
    poll picks it up.  Without it, ``start()`` is called directly at the
    same trigger point."""
    import shutil
    import subprocess
    import tempfile

    from tpuframe.serve import loadgen
    from tpuframe.serve import router as router_lib

    start_after = (n_requests // 4 if start_after_completed is None
                   else start_after_completed)
    tmpdir = tempfile.mkdtemp(prefix="tpuframe-rollout-")
    procs: list = []
    ports: list = []
    relaunched_ranks: set = set()
    old_proc_id = os.environ.get("TPUFRAME_PROCESS_ID")

    def spawn(rank: int, *, version: int, port: int = 0):
        spec_parts = [s for s in (faults_spec,) if s]
        if (kill_during_swap_rank is not None
                and rank == kill_during_swap_rank
                and rank not in relaunched_ranks):
            spec_parts.append(f"crash_during_swap:rank={rank}")
        ready = os.path.join(tmpdir, f"ready.{rank}")
        if os.path.exists(ready):
            os.remove(ready)
        return router_lib._spawn_replica(
            rank, tmpdir=tmpdir, events_dir=events_dir, engine="fake",
            slots=slots, step_delay_ms=step_delay_ms, stall_timeout_s=2.0,
            faults_spec=",".join(spec_parts) or None,
            weights_version=version, port=port)

    try:
        for rank in range(replicas):
            procs.append(spawn(rank, version=0))
        ports = [router_lib._wait_ready(p, ready,
                                        timeout_s=ready_timeout_s)
                 for p, ready, _log in procs]
        urls = [f"http://127.0.0.1:{port}" for port in ports]
        if events_dir:
            os.environ["TPUFRAME_PROCESS_ID"] = str(replicas + 90)
            obs_events.init(events_dir)
        reqs = loadgen.synthetic_requests(
            n_requests, buckets=(16, 32), rate=rate,
            max_new_tokens=max_new_tokens, vocab_size=256, seed=seed)
        router = Router(urls, queue_limit=queue_limit, hedge_ms=hedge_ms,
                        scrape_interval_s=scrape_interval_s,
                        scrape_timeout_s=0.5, dispatch_timeout_s=30.0,
                        max_inflight_per_replica=max(2, slots))
        ctl = RolloutController(
            router, watch_dir=watch_dir, watch_interval_s=0.05,
            current_version=0, canary_frac=canary_frac,
            gate_pct=gate_pct, bake_min_samples=bake_min_samples,
            bake_timeout_s=bake_timeout_s, drain_timeout_s=10.0,
            swap_timeout_s=5.0, relaunch_timeout_s=ready_timeout_s,
            seed=seed, log=log)
        triggered = False

        def on_tick():
            nonlocal triggered
            if (not triggered
                    and router.counters["completed"] >= start_after):
                triggered = True
                if watch_dir:
                    _commit_fake_checkpoint(watch_dir, target_version)
                else:
                    ctl.start(target_version)
            # Supervisor half of the mid-swap-kill contract: a replica
            # that died rc 42 during the roll comes back on the SAME
            # port serving the NEW version.
            for rank, (proc, _ready, _lg) in enumerate(procs):
                if (proc.poll() is not None and proc.returncode != 0
                        and rank not in relaunched_ranks and triggered
                        and not ctl.done()):
                    relaunched_ranks.add(rank)
                    procs[rank] = spawn(rank, version=ctl.target or
                                        target_version, port=ports[rank])
                    try:
                        router_lib._wait_ready(
                            procs[rank][0], procs[rank][1],
                            timeout_s=ready_timeout_s)
                    except RuntimeError:
                        pass  # controller's relaunch timeout will abort
            if triggered or ctl.watch_dir:
                return ctl.tick()
            return not triggered
        out = router.run(reqs, timeout_s=timeout_s, on_tick=on_tick,
                         log=log)
        out["rollout"] = ctl.summary()
        # Final ground truth straight off each replica's gauge.
        final_versions = {}
        for rank, url in enumerate(urls):
            gauges = None
            try:
                status, text = router._transport(
                    url + "/metrics", None, 2.0)
                if status == 200:
                    gauges = parse_gauges(
                        text if isinstance(text, str) else "",
                        ("tpuframe_weights_version",))
            except Exception:  # noqa: BLE001 — a dead replica reports None
                pass
            final_versions[f"r{rank}"] = (
                int(gauges["tpuframe_weights_version"])
                if gauges and "tpuframe_weights_version" in gauges
                else None)
        out["final_versions"] = final_versions
        out["relaunched_ranks"] = sorted(relaunched_ranks)
        if events_dir:
            obs_events.close()
        for proc, _ready, _lg in procs:
            if proc.poll() is None:
                proc.terminate()
        exit_codes = []
        for proc, _ready, _lg in procs:
            try:
                exit_codes.append(proc.wait(timeout=10))
            except subprocess.TimeoutExpired:
                proc.kill()
                exit_codes.append(proc.wait(timeout=10))
        out["exit_codes"] = exit_codes
        return out
    finally:
        if old_proc_id is None:
            os.environ.pop("TPUFRAME_PROCESS_ID", None)
        else:
            os.environ["TPUFRAME_PROCESS_ID"] = old_proc_id
        for proc, _ready, _lg in procs:
            if proc.poll() is None:
                proc.kill()
        shutil.rmtree(tmpdir, ignore_errors=True)


def _commit_fake_checkpoint(directory: str, step: int, *,
                            processes: int = 1, devices: int = 1) -> None:
    """Make ``step_<step>`` visible to ``committed_world()`` the way the
    checkpoint writer does: manifest first, COMMIT last.  (The chaos
    tier pre-creates the manifest-only dir so the watcher demonstrably
    ignores a mid-commit checkpoint, then this lands the COMMIT.)"""
    import json

    d = os.path.join(directory, f"step_{step:08d}")
    os.makedirs(d, exist_ok=True)
    manifest = os.path.join(d, "manifest.json")
    if not os.path.exists(manifest):
        with open(manifest, "w") as f:
            json.dump({"step": step,
                       "world": {"processes": processes,
                                 "devices": devices}}, f)
    with open(os.path.join(d, "COMMIT"), "w") as f:
        f.write("ok\n")


# ---------------------------------------------------------------------------
# Analysis-gate self-check (``python -m tpuframe.analysis``).
# ---------------------------------------------------------------------------

class _SimFleet:
    """In-process fleet stub for ``check()`` and unit tests: N replicas
    answering /healthz, /metrics (with the version gauge), /generate
    (deterministic TTFT, slower on the new version when poisoned) and
    /swap_weights — the whole controller state machine without a
    process or a socket."""

    def __init__(self, n: int, *, poisoned_ttft_ms: float | None = None):
        self.reps = {f"http://sim/r{i}": {"version": 0}
                     for i in range(n)}
        self.poisoned_ttft_ms = poisoned_ttft_ms
        self.swaps: list = []

    def transport(self, url: str, payload, timeout_s):
        base, _, path = url.rpartition("/")
        rep = self.reps[base]
        if path == "healthz":
            return 200, "ok\n"
        if path == "metrics":
            return 200, ("tpuframe_serve_queue_depth 0\n"
                         "tpuframe_serve_active_slots 0\n"
                         f"tpuframe_weights_version {rep['version']}\n")
        if path == "swap_weights":
            rep["version"] = int(payload["version"])
            self.swaps.append((base, rep["version"]))
            return 200, {"version": rep["version"],
                         "compile_cache_misses": 0}
        if path == "generate":
            ttft = 1.0
            if rep["version"] > 0 and self.poisoned_ttft_ms is not None:
                ttft = self.poisoned_ttft_ms
            return 200, {"rid": payload["rid"], "tokens": [1, 2, 3],
                         "ttft_ms": ttft, "tpot_ms": ttft / 4.0}
        return 404, {"error": f"no handler for {path}"}


def _drive_sim_rollout(*, n: int = 3, poisoned_ttft_ms=None,
                       gate_pct: float = 50.0, canary_frac: float = 0.5,
                       max_iters: int = 20000) -> tuple:
    """Run one complete rollout against the in-process stub fleet;
    returns (controller, router, fleet)."""
    fleet = _SimFleet(n, poisoned_ttft_ms=poisoned_ttft_ms)
    router = Router(list(fleet.reps), transport=fleet.transport,
                    queue_limit=10_000, hedge_ms=0.0,
                    scrape_interval_s=0.0, scrape_timeout_s=0.2,
                    dispatch_timeout_s=2.0, max_inflight_per_replica=4)
    ctl = RolloutController(
        router, transport=fleet.transport, current_version=0,
        canary_frac=canary_frac, gate_pct=gate_pct, bake_min_samples=4,
        bake_timeout_s=5.0, drain_timeout_s=2.0, swap_timeout_s=1.0,
        relaunch_timeout_s=2.0, poll_interval_s=0.0, seed=0)
    ctl.start(1)
    rid = 0
    for _ in range(max_iters):
        if rid < 4000 and len(router.pending) < 8:
            router.submit(rid, [1, 2, 3])
            rid += 1
        router.step()
        ctl.tick()
        if ctl.done() and not router.has_work():
            break
        time.sleep(0.0005)
    return ctl, router, fleet


def check() -> list:
    """Host-only rollout checks for the CI gate: event registration,
    the TF121 swap-seam lint, env-knob resolution, the state-machine
    invariants on a simulated fleet, and the seeded poisoned-canary
    positive — a gate that fails to roll back a 100x-slower canary is
    blind, and this check refuses to let it run."""
    import pathlib

    problems: list = []

    from tpuframe.obs import events as events_lib

    for etype in ROLLOUT_EVENT_TYPES:
        if etype not in events_lib.REQUIRED_FIELDS:
            problems.append(
                f"rollout event type {etype!r} not registered in "
                f"obs.events.REQUIRED_FIELDS (TF112 contract)")

    from tpuframe.analysis import source_lint

    pkg = pathlib.Path(__file__).resolve().parent.parent
    try:
        findings = source_lint.lint_paths([pkg])
    except Exception as exc:  # noqa: BLE001
        problems.append(f"rollout lint crashed: {exc!r}")
        findings = []
    problems += [f"rollout lint: {f}" for f in findings
                 if f.rule == "TF121"]

    if not 0.0 <= resolve_canary_frac() <= 1.0:
        problems.append("TPUFRAME_CANARY_FRAC resolved outside [0, 1]")
    if resolve_gate_pct() < 0:
        problems.append("TPUFRAME_ROLLOUT_GATE resolved below 0")

    # Gate arithmetic: participate-only-when-both, and the rc contract.
    fast = [{"type": "router_request", "id": i, "replica": "r0",
             "ttft_ms": 10.0} for i in range(8)]
    slow = [{"type": "router_request", "id": i, "replica": "r1",
             "ttft_ms": 100.0} for i in range(8)]
    rc, _res = gate_compare(fast, slow, pct=25.0)
    if rc != 1:
        problems.append(f"gate_compare missed a 10x TTFT regression "
                        f"(rc {rc}, want 1)")
    rc, _res = gate_compare(fast, [], pct=25.0)
    if rc != 2:
        problems.append(f"gate_compare promoted with one side empty "
                        f"(rc {rc}, want 2) — the gate must never run "
                        f"blind")

    # State-machine invariants on the clean simulated fleet: every
    # replica drains before it swaps and swaps before it readmits, at
    # most one replica is mid-transition at a time, and the fleet ends
    # on the new version with a zero compile-miss floor.
    ctl, router, fleet = _drive_sim_rollout(gate_pct=50.0)
    if ctl.state != "done":
        problems.append(f"sim rollout did not complete: state "
                        f"{ctl.state} ({ctl.abort_reason})")
    else:
        versions = {rep["version"] for rep in fleet.reps.values()}
        if versions != {1}:
            problems.append(f"sim rollout left mixed versions {versions}")
        if ctl.swap_compile_misses != 0:
            problems.append(f"sim rollout cost "
                            f"{ctl.swap_compile_misses} compile misses")
        order: dict = {}
        for i, (_t, rep, phase) in enumerate(ctl.history):
            order.setdefault(rep, []).append(phase)
        for rep, phases in order.items():
            want_prefix = ["drain", "swapped", "readmitted"]
            got = [p for p in phases if p in want_prefix]
            if got != want_prefix:
                problems.append(f"sim rollout phase order on {rep}: "
                                f"{phases}")
        if router.counters["admitted"] != router.counters["completed"]:
            problems.append(
                f"sim rollout lost requests: "
                f"{router.counters['admitted']} admitted vs "
                f"{router.counters['completed']} completed")

    # Seeded poisoned-canary positive: the gate MUST roll back.
    ctl, _router, fleet = _drive_sim_rollout(poisoned_ttft_ms=500.0,
                                             gate_pct=50.0)
    if ctl.state != "aborted":
        problems.append(
            f"poisoned canary was NOT rolled back (state {ctl.state}) "
            f"— the promotion gate is blind and may not run")
    else:
        if ctl.abort_metric not in GATE_METRICS:
            problems.append(f"rollback named metric "
                            f"{ctl.abort_metric!r}, want one of "
                            f"{GATE_METRICS}")
        versions = {rep["version"] for rep in fleet.reps.values()}
        if versions != {0}:
            problems.append(f"rollback left versions {versions}, "
                            f"want all back on 0")

    from tpuframe.resilience import faults as faults_lib

    for seam, kind in (("slow_canary", "slow"),
                       ("crash_during_swap", "crash")):
        try:
            parsed = faults_lib.parse(seam)
        except ValueError as exc:
            problems.append(f"fault seam {seam} unparseable: {exc}")
            continue
        if not parsed or parsed[0].kind != kind:
            problems.append(f"fault seam {seam}: default kind "
                            f"{parsed[0].kind if parsed else '?'} "
                            f"(want {kind})")

    return problems
