"""One serving replica of the fleet — engine + scheduler behind the exporter.

A replica is the unit the router (``serve/router.py``) load-balances
over: the existing continuous-batching :class:`~tpuframe.serve.scheduler.
Scheduler` wrapped in a process whose *entire* HTTP surface rides the PR 9
telemetry exporter (``obs/exporter.py`` — the one sanctioned endpoint,
TF113):

  ``/metrics``   live queue depth / active slots / TTFT percentiles (the
                 router's load + shed signal)
  ``/healthz``   200 while the step loop beats and the replica is not
                 draining; 503 otherwise — the router's drain signal
  ``/generate``  POST ``{"rid", "prompt", "max_new_tokens"}`` → blocks
                 until the scheduler retires the request, returns
                 ``{"rid", "tokens", "ttft_ms", "tpot_ms", "proc"}``
  ``/swap_weights``
                 POST ``{"version"[, "seed"]}`` → blocks until the main
                 loop applies the hot swap through the engine's
                 sanctioned ``swap_params`` seam (TF121), returns
                 ``{"version", "compile_cache_misses"}``.  The replica
                 also publishes the label-free
                 ``tpuframe_weights_version`` gauge on ``/metrics`` —
                 the router scrapes it, which is how the rollout
                 controller proves the mixed-version window is bounded.

Threading contract: the exporter's HTTP worker threads only parse,
enqueue into the inbox and wait on an event — the *main* thread is the
only one that touches the engine (prefill/insert/decode are jax on the
real engine; a worker thread driving them would be the TF111 collective-
ordering hazard).  No thread is created in this module.

Drain semantics (the zero-loss half of the fleet contract): SIGTERM — or
a 503-flipping health probe — marks the replica draining.  ``/generate``
rejects *new* work with 503, ``/healthz`` goes 503 so the router stops
dispatching and re-dispatches as it sees fit, and the main loop keeps
stepping until every request it already accepted has retired and been
answered; only then does it exit 0.  A request is therefore never
acknowledged-and-dropped: it either completes here or was never accepted.

Chaos seams (``resilience/faults.py``): the step loop fires
``replica_slow`` / ``replica_hang`` / ``replica_crash`` once per
iteration with the fault step pinned to the scheduler step count, so
``TPUFRAME_FAULTS="replica_crash:step=3:rank=1"`` deterministically
kills replica 1 after its third scheduler step.  Two rollout seams ride
the same loop: ``slow_canary`` fires per iteration but ONLY while the
replica serves a weights version it was not launched with (the
poisoned-canary model — armed fleet-wide, it slows exactly the canary),
and ``crash_during_swap`` fires inside the swap application, after the
swap was accepted but before the new version is live (the mid-swap
kill the supervisor must relaunch on the NEW version).

The :class:`FakeEngine` is the pure-host stand-in for fleet tests and
the selfcheck smoke: deterministic token streams that are a function of
the prompt alone, so re-prefill on any replica reproduces them — the
idempotence the router's hedging (first-winner-kept) relies on, same as
the real engine's greedy argmax decode.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

from tpuframe.obs import events as obs_events
from tpuframe.obs import exporter as obs_exporter
from tpuframe.obs import tracing
from tpuframe.resilience import faults
from tpuframe.serve.scheduler import Request, Scheduler

READY_PREFIX = "TPUFRAME_REPLICA_READY"

# Fired once per main-loop iteration, cheap no-ops unless armed.
_FAULT_SEAMS = ("replica_slow", "replica_hang", "replica_crash")


def _compile_misses() -> int:
    """The compile-cache miss counter without forcing a jax import (the
    FakeEngine replica stays jax-free): the counter only exists once
    ``tpuframe.obs.metrics`` is loaded, which any real engine pulls in."""
    mod = sys.modules.get("tpuframe.obs.metrics")
    if mod is None:
        return 0
    return int(mod.counters().get("compile_cache.misses", 0))


class FakeEngine:
    """Deterministic pure-host engine with the LMEngine seam contract.

    Token streams are a pure function of the prompt (first token from a
    prompt hash, each decode token from the previous one), so any
    replica re-prefilling the same request produces the same stream —
    the property that makes the router's redispatch/hedging idempotent.
    ``step_delay_s`` models decode cost so fleet runs have real
    queueing behavior without a jax compile.
    """

    def __init__(self, *, slots: int = 2, prompt_buckets=(16, 32),
                 eos_id: int | None = None, step_delay_s: float = 0.0,
                 vocab_size: int = 256):
        self.slots = slots
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        self.eos_id = eos_id
        self.step_delay_s = step_delay_s
        self.vocab_size = vocab_size
        self._last = [0] * slots
        self.last_prefill_ms = 0.0

    def prefill(self, token_ids):
        t0 = time.monotonic()
        first = (sum(int(t) for t in token_ids)
                 + 31 * len(token_ids)) % self.vocab_size
        self.last_prefill_ms = 1e3 * (time.monotonic() - t0)
        return first, ("pcache", len(token_ids)), len(token_ids)

    def insert(self, slot, pcache, length, first_token) -> None:
        self._last[slot] = int(first_token)

    def decode_step(self):
        if self.step_delay_s > 0:
            time.sleep(self.step_delay_s)
        out = []
        for s in range(self.slots):
            self._last[s] = (self._last[s] * 31 + 7) % self.vocab_size
            out.append(self._last[s])
        return out

    def reset(self) -> None:
        self._last = [0] * self.slots


class Replica:
    """The serving fleet's worker: scheduler main loop + exporter surface."""

    def __init__(self, engine, *, stall_timeout_s: float = 2.0,
                 handler_timeout_s: float = 120.0, clock=time.monotonic,
                 weights_version: int = 0):
        self.engine = engine
        self._clock = clock
        self.stall_timeout_s = stall_timeout_s
        self.handler_timeout_s = handler_timeout_s
        self.scheduler = Scheduler(engine)
        self._inbox: list = []               # (Request, threading.Event)
        self._inbox_lock = threading.Lock()
        self._waiters: dict = {}             # rid -> threading.Event
        self._resolved = 0                   # prefix of scheduler.completed
        self._draining = False
        self._last_beat = clock()
        # The served weights version (checkpoint step for real weights).
        # ``_launch_version`` is what this process booted with — the
        # slow_canary seam keys on the difference, so a fault armed
        # fleet-wide slows exactly the replicas serving NEW weights.
        self.weights_version = int(weights_version)
        self._launch_version = int(weights_version)
        self._swap_inbox: list = []          # swap jobs (dicts)
        self.exporter = obs_exporter.start_from_env(health=self.healthy)
        if self.exporter is not None:
            self.exporter.add_handler("/generate", self.handle_generate)
            self.exporter.add_handler("/swap_weights", self.handle_swap)
            self.exporter.add_collector(self._version_sample)

    def _version_sample(self):
        # Label-free on purpose: the router's parse_gauges reads only
        # label-free lines off the scrape.
        return [("tpuframe_weights_version", {},
                 float(self.weights_version))]

    # -- health / drain ---------------------------------------------------

    def healthy(self) -> bool:
        """503 the moment we drain OR the step loop stops beating — the
        router must see a hung replica (main loop stuck, exporter thread
        alive) as unhealthy before any request deadline trips."""
        if self._draining:
            return False
        return (self._clock() - self._last_beat) < self.stall_timeout_s

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, signum=None, frame=None) -> None:
        """Graceful drain (the SIGTERM handler): stop accepting, finish
        and answer everything already accepted, then let ``run`` exit."""
        self._draining = True

    # -- the exporter-thread side -----------------------------------------

    def handle_generate(self, body: bytes):
        """POST /generate — runs on an exporter HTTP worker thread.
        Only parses, enqueues and waits; the main loop owns the engine."""
        try:
            msg = json.loads(body.decode() or "{}")
            rid = int(msg["rid"])
            prompt = [int(t) for t in msg["prompt"]]
            max_new = int(msg.get("max_new_tokens", 8))
        except (KeyError, ValueError, TypeError) as e:
            return 400, json.dumps({"error": f"bad request: {e}"}).encode()
        if len(prompt) > max(self.engine.prompt_buckets) or not prompt:
            return 400, json.dumps(
                {"error": f"prompt length {len(prompt)} outside buckets "
                          f"{self.engine.prompt_buckets}"}).encode()
        if self._draining:
            return 503, json.dumps({"error": "draining"}).encode()
        # arrival_t on the SCHEDULER's clock — queue/prefill spans and
        # the serve_request TTFT are deltas against it, so every
        # replica-side duration comes from one monotonic clock source.
        req = Request(rid=rid, prompt=prompt, max_new_tokens=max_new,
                      arrival_t=self.scheduler._clock())
        trace = msg.get("trace")
        if trace is not None:
            # The router's attempt span id arrives as "span": parenting
            # the serve span under it stitches the cross-process tree.
            req.trace = str(trace)
            req.span = tracing.open_span(req.trace, "serve",
                                         parent=msg.get("span"), rid=rid)
        done = threading.Event()
        with self._inbox_lock:
            self._inbox.append((req, done))
        if not done.wait(self.handler_timeout_s):
            # The serve span stays OPEN on purpose: a request the
            # scheduler never answered is exactly what the leaked-span
            # anomaly exists to make loud.
            return 504, json.dumps(
                {"error": "timed out waiting for the scheduler"}).encode()
        if req.trace is not None and req.span is not None:
            tracing.close_span(
                req.trace, req.span,
                1e3 * max(0.0, self.scheduler._clock() - req.arrival_t),
                ttft_ms=round(req.ttft_ms() or 0.0, 3),
                tpot_ms=round(req.tpot_ms(), 3)
                if req.tpot_ms() is not None else None)
        return 200, json.dumps({
            "rid": rid,
            "tokens": [int(t) for t in req.tokens],
            "ttft_ms": req.ttft_ms(),
            "tpot_ms": req.tpot_ms(),
            "proc": os.environ.get("TPUFRAME_PROCESS_ID", "0"),
        }).encode()

    def handle_swap(self, body: bytes):
        """POST /swap_weights — runs on an exporter HTTP worker thread.
        Like /generate it only parses, enqueues and waits: the MAIN loop
        owns the engine, so the swap is applied between scheduler steps
        (never mid-decode) and the only-main-thread-touches-the-engine
        contract holds."""
        try:
            msg = json.loads(body.decode() or "{}")
            version = int(msg["version"])
            seed = msg.get("seed")
            seed = None if seed is None else int(seed)
        except (KeyError, ValueError, TypeError) as e:
            return 400, json.dumps({"error": f"bad swap: {e}"}).encode()
        job = {"version": version, "seed": seed, "result": None,
               "done": threading.Event()}
        with self._inbox_lock:
            self._swap_inbox.append(job)
        if not job["done"].wait(self.handler_timeout_s):
            return 504, json.dumps(
                {"error": "timed out waiting for the swap"}).encode()
        if "error" in (job["result"] or {}):
            return 500, json.dumps(job["result"]).encode()
        return 200, json.dumps(job["result"]).encode()

    # -- the main-loop side ------------------------------------------------

    def _apply_swaps(self) -> None:
        """Apply queued weight swaps on the MAIN loop, between scheduler
        steps.  The ``crash_during_swap`` seam fires after the swap was
        accepted but before the version flips — the window where a kill
        must leave the supervisor relaunching on the NEW version."""
        with self._inbox_lock:
            jobs, self._swap_inbox = self._swap_inbox, []
        for job in jobs:
            try:
                faults.fire("crash_during_swap")
                misses0 = _compile_misses()
                if job["seed"] is not None:
                    # Real-weights path: regenerate params (stand-in for
                    # a checkpoint restore; replicated params reassemble
                    # world-size invariantly) and hot-swap them through
                    # the engine's one sanctioned seam.
                    import jax
                    import jax.numpy as jnp

                    new_params = self.engine.model.init(
                        jax.random.key(job["seed"]),
                        jnp.zeros((1, min(self.engine.prompt_buckets)),
                                  jnp.int32))["params"]
                    self.engine.swap_params(new_params)
                self.weights_version = job["version"]
                job["result"] = {
                    "version": self.weights_version,
                    "compile_cache_misses": _compile_misses() - misses0,
                }
            except Exception as e:  # noqa: BLE001 — a refused swap (bad
                # tree/shape) must answer 500, not kill the serving loop
                job["result"] = {"error": f"{type(e).__name__}: {e}"}
            job["done"].set()

    def _pump_inbox(self) -> int:
        with self._inbox_lock:
            batch, self._inbox = self._inbox, []
        for req, done in batch:
            self._waiters[req.rid] = done
            self.scheduler.submit(req)
        return len(batch)

    def _resolve_completed(self) -> None:
        completed = self.scheduler.completed
        while self._resolved < len(completed):
            req = completed[self._resolved]
            self._resolved += 1
            done = self._waiters.pop(req.rid, None)
            if done is not None:
                done.set()

    def run(self, *, max_steps: int | None = None,
            idle_sleep_s: float = 0.002,
            max_idle_s: float | None = None) -> int:
        """The replica main loop: beat, fire chaos seams, pump the inbox,
        step the scheduler, answer retired requests.  Returns 0 when a
        drain completed with nothing left in flight."""
        sched = self.scheduler
        idle_since = self._clock()
        while True:
            self._last_beat = self._clock()
            faults.set_step(sched.step_count)
            for seam in _FAULT_SEAMS:
                faults.fire(seam)
            if self.weights_version != self._launch_version:
                # Scoped to the NEW version by construction: arm the
                # fault fleet-wide and only the swapped canary slows.
                faults.fire("slow_canary")
            self._apply_swaps()
            self._pump_inbox()
            if sched.has_work():
                sched.step()
                self._resolve_completed()
                idle_since = self._clock()
            elif self._draining:
                break  # drained: every accepted request has been answered
            else:
                if (max_idle_s is not None
                        and self._clock() - idle_since > max_idle_s):
                    break
                time.sleep(idle_sleep_s)
            if max_steps is not None and sched.step_count >= max_steps:
                break
        self._resolve_completed()
        return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpuframe.serve.replica",
        description="one serving-fleet replica (engine+scheduler behind "
                    "the telemetry exporter)")
    ap.add_argument("--engine", default="fake", choices=("fake", "tiny-lm"))
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--step-delay-ms", type=float, default=0.0,
                    help="fake-engine decode cost per step")
    ap.add_argument("--stall-timeout-s", type=float, default=2.0)
    ap.add_argument("--max-steps", type=int, default=None)
    ap.add_argument("--max-idle-s", type=float, default=None,
                    help="exit after this much idle time (orphan guard)")
    ap.add_argument("--ready-file", default=None,
                    help="write the READY line (bound port) here")
    ap.add_argument("--weights-version", type=int, default=0,
                    help="version this replica boots on (a relaunch "
                         "after a mid-swap kill passes the NEW one)")
    args = ap.parse_args(argv)

    faults.reset_from_env()
    obs_events.init()
    if args.engine == "fake":
        engine = FakeEngine(slots=args.slots,
                            step_delay_s=args.step_delay_ms / 1e3)
    else:
        from tpuframe.models.transformer_lm import LMConfig
        from tpuframe.serve.engine import LMEngine

        buckets = (16, 32)
        engine = LMEngine(LMConfig.tiny(), slots=args.slots,
                          prompt_buckets=buckets, decode_block=16,
                          max_context=max(buckets) + 32)

    replica = Replica(engine, stall_timeout_s=args.stall_timeout_s,
                      weights_version=args.weights_version)
    signal.signal(signal.SIGTERM, replica.drain)
    if replica.exporter is None or replica.exporter.port is None:
        print("[replica] no scrape endpoint — set TPUFRAME_METRICS_PORT "
              "(0 = ephemeral) before launching a fleet replica",
              file=sys.stderr)
        return 2
    ready = f"{READY_PREFIX} port={replica.exporter.port} pid={os.getpid()}"
    if args.ready_file:
        tmp = f"{args.ready_file}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(ready + "\n")
        os.replace(tmp, args.ready_file)
    print(ready, flush=True)

    rc = replica.run(max_steps=args.max_steps, max_idle_s=args.max_idle_s)
    obs_events.close()
    obs_exporter.stop()
    return rc


if __name__ == "__main__":
    sys.exit(main())
