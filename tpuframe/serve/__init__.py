"""tpuframe.serve — AOT-compiled inference with KV-cache + continuous batching.

The serving counterpart of the training stack: a paged/ring KV-cache
(``kv_cache``), an explicit prefill/decode split compiled ahead-of-time
against a closed set of bucketed shapes (``engine``), continuous
batching over fixed decode slots (``scheduler``), and an open-loop
load generator (``loadgen``).  Decode block sizes and bucket sets
resolve env > tune-DB > default, same precedence as every other tuned
knob (PR 3/5).

Imports stay lazy — ``check()`` runs inside the analysis gate where jax
may be pinned to CPU, and nothing here should drag in flax at import
time.
"""

from __future__ import annotations

__all__ = ["check"]


def check() -> list:
    """Self-check for the analysis gate (``python -m tpuframe.analysis``).

    Pure-host checks only (no model compiles — the gate stays fast):
    resolved bucket/block invariants, the TF109 lint over the serve
    package itself, and a sanity pass on the decode roofline.  Returns
    problem strings; [] means healthy.
    """
    import pathlib

    problems: list = []

    # 1. Resolved shape-bucket invariants.
    from tpuframe.serve import kv_cache as kv

    try:
        block = kv.resolve_decode_block()
        buckets = kv.resolve_buckets()
        capacity = kv.capacity_for(max(buckets), block)
        problems += [f"serve buckets: {p}"
                     for p in kv.check_buckets(buckets, capacity)]
        if block < 8 or block % 8:
            problems.append(f"decode block {block} not a multiple of 8")
    except Exception as exc:  # noqa: BLE001 — resolution itself broke
        problems.append(f"serve bucket resolution failed: {exc!r}")

    # 2. TF109 over our own files: no un-bucketed jit/apply above the
    #    engine seam.
    from tpuframe.analysis import source_lint

    pkg = pathlib.Path(__file__).parent
    try:
        findings = source_lint.lint_paths([pkg])
    except Exception as exc:  # noqa: BLE001
        problems.append(f"serve lint crashed: {exc!r}")
        findings = []
    problems += [f"serve lint: {f}" for f in findings
                 if f.rule == "TF109"]

    # 3. Decode roofline is monotone in the cached-context size (more KV
    #    traffic can only slow a memory-bound decode down).
    from tpuframe.tune import roofline

    try:
        short = roofline.decode_score(
            param_bytes=int(50e6), kv_bytes_per_token=4096,
            slots=8, context=256)
        long_ = roofline.decode_score(
            param_bytes=int(50e6), kv_bytes_per_token=4096,
            slots=8, context=4096)
        if not short.tokens_per_s_per_chip > long_.tokens_per_s_per_chip:
            problems.append(
                "decode roofline not monotone in context length: "
                f"{short.tokens_per_s_per_chip} <= "
                f"{long_.tokens_per_s_per_chip}")
    except Exception as exc:  # noqa: BLE001
        problems.append(f"decode roofline sanity failed: {exc!r}")

    return problems
