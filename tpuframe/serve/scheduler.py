"""Continuous batching over the engine's fixed decode slots.

The decode executable always runs all ``slots`` sequences (its shape is
compiled once); *continuous batching* means requests are admitted into
and retired from those slots at step boundaries, so a long generation
never blocks a short one behind it — the batching lesson of the TPU-pod
scaling papers (arXiv:1909.09756 / 2011.03641) applied to a decode loop:
keep the chip-filling shape constant and move the *work* in and out.

Per step, in order:

  1. admit   — for every free slot, pop the oldest pending request,
               prefill it (its bucket's executable), insert into the
               slot.  TTFT is measured here: arrival -> first token.
  2. decode  — ONE decode step over all slots (active or not; inactive
               lanes compute garbage, which costs less than a recompile
               or a per-slot branch).
  3. retire  — requests that hit ``max_new_tokens`` or the EOS id leave
               their slot free for the next admit.

Observability rides obs v2: a typed ``serve_step`` event per step and a
``serve_request`` event per retirement (TTFT/TPOT, token counts) — the
offline analyzer (``python -m tpuframe.obs summarize``) computes the
percentiles and tokens/sec/chip from these, beside the training MFU.

This file is above the compile seam: it calls only the engine's AOT
executables (lint TF109 keeps ``jit``/``.apply`` out of here).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from tpuframe.obs import events as obs_events
from tpuframe.obs import exporter as obs_exporter
from tpuframe.obs import tracing
from tpuframe.obs.goodput import _pct


@dataclass
class Request:
    """One generation request and its lifecycle record."""

    rid: int
    prompt: list
    max_new_tokens: int = 16
    arrival_t: float = 0.0            # scheduler clock, seconds
    # -- filled in by the scheduler --
    first_token_t: float | None = None
    done_t: float | None = None
    tokens: list = field(default_factory=list)   # generated tokens
    # Tracing context: trace id propagated in the /generate payload and
    # the replica-side "serve" span the scheduler's queue/prefill/decode
    # phase spans parent under.  None when the request is untraced.
    trace: str | None = None
    span: str | None = None

    @property
    def done(self) -> bool:
        return self.done_t is not None

    def ttft_ms(self) -> float | None:
        if self.first_token_t is None:
            return None
        return 1e3 * (self.first_token_t - self.arrival_t)

    def tpot_ms(self) -> float | None:
        """Time per output token AFTER the first (the decode cadence)."""
        if self.done_t is None or self.first_token_t is None \
                or len(self.tokens) < 2:
            return None
        return 1e3 * (self.done_t - self.first_token_t) \
            / (len(self.tokens) - 1)


class Scheduler:
    """Continuous-batching request loop over one :class:`LMEngine`.

    ``clock`` is injectable (fake-clock tests, the GoodputMeter idiom);
    the default is the host monotonic clock.
    """

    def __init__(self, engine, *, clock=time.monotonic):
        self.engine = engine
        self._clock = clock
        self.pending: list = []                 # FIFO of Request
        self.active: list = [None] * engine.slots
        self.completed: list = []
        self.step_count = 0
        self.tokens_generated = 0
        # Live telemetry (obs/exporter.py, env-gated no-op otherwise):
        # queue/slot/token gauges and TTFT/TPOT percentiles served
        # through pull collectors — a scrape between steps must see the
        # *current* pending depth (the router's admission signal), not
        # the last step's snapshot.
        self._exporter = obs_exporter.start_from_env()
        if self._exporter is not None:
            self._exporter.add_collector(self._latency_samples)
            self._exporter.add_collector(self._load_samples)

    def _load_samples(self):
        return [
            ("tpuframe_serve_queue_depth", {}, float(len(self.pending))),
            ("tpuframe_serve_active_slots", {},
             float(sum(r is not None for r in self.active))),
            ("tpuframe_serve_tokens_generated", {},
             float(self.tokens_generated)),
        ]

    def _latency_samples(self):
        ttft = sorted(v for v in (r.ttft_ms() for r in self.completed)
                      if v is not None)
        tpot = sorted(v for v in (r.tpot_ms() for r in self.completed)
                      if v is not None)
        out = []
        for name, vals in (("tpuframe_serve_ttft_ms", ttft),
                           ("tpuframe_serve_tpot_ms", tpot)):
            if vals:
                for q, frac in (("p50", 0.5), ("p90", 0.9), ("p99", 0.99)):
                    out.append((name, {"quantile": q}, _pct(vals, frac)))
        return out

    def submit(self, request: Request) -> None:
        if len(request.prompt) > max(self.engine.prompt_buckets):
            # Admission control: reject ahead of any shape decision —
            # never invent a new compile shape for an oversized prompt.
            raise ValueError(
                f"request {request.rid}: prompt {len(request.prompt)} "
                f"exceeds largest bucket "
                f"{max(self.engine.prompt_buckets)}")
        self.pending.append(request)

    def has_work(self) -> bool:
        return bool(self.pending) or any(r is not None
                                         for r in self.active)

    def step(self) -> int:
        """One scheduler step (admit + decode + retire + admit).
        Returns the number of live tokens produced this step.

        The trailing admit pass fills slots freed by *this step's*
        retires — their prefill (and first token, so TTFT) lands this
        step and their first decode token next step.  Without it a
        freed slot idles until the next step's leading admit."""
        t0 = self._clock()
        admitted = self._admit()

        produced = 0
        if any(r is not None for r in self.active):
            toks = self.engine.decode_step()
            now = self._clock()
            for slot, req in enumerate(self.active):
                if req is None:
                    continue
                tok = int(toks[slot])
                req.tokens.append(tok)
                produced += 1
                if self._finished(req, tok):
                    req.done_t = now
                    self._retire(slot)
        admitted += self._admit()
        self.step_count += 1
        self.tokens_generated += produced + admitted
        obs_events.emit(
            "serve_step", step=self.step_count,
            wall_ms=round(1e3 * (self._clock() - t0), 3),
            active=sum(r is not None for r in self.active),
            admitted=admitted, produced=produced,
            queued=len(self.pending))
        return produced + admitted

    # -- internals ----------------------------------------------------------

    def _admit(self) -> int:
        """Fill free slots from the pending FIFO.  A request that
        finishes at prefill (max_new_tokens=1 or instant EOS) retires in
        place and its slot is reused without advancing — one admit pass
        never leaves a free slot behind while requests wait."""
        admitted = 0
        slot = 0
        while self.pending and slot < self.engine.slots:
            if self.active[slot] is not None:
                slot += 1
                continue
            req = self.pending.pop(0)
            t_adm = self._clock()
            first_tok, pcache, length = self.engine.prefill(req.prompt)
            self.engine.insert(slot, pcache, length, first_tok)
            req.first_token_t = self._clock()
            req.tokens.append(first_tok)
            if req.trace is not None:
                # Phase spans share clock reads with the TTFT record:
                # arrival -> admit is queue, admit -> first token is
                # prefill, so queue.ms + prefill.ms == ttft_ms exactly
                # (modulo rounding) — the verify_traces invariant.
                tracing.span(req.trace, "queue", parent=req.span,
                             ms=1e3 * (t_adm - req.arrival_t))
                tracing.span(req.trace, "prefill", parent=req.span,
                             ms=1e3 * (req.first_token_t - t_adm),
                             engine_ms=getattr(self.engine,
                                               "last_prefill_ms", None))
            self.active[slot] = req
            admitted += 1
            if self._finished(req, first_tok):
                self._retire(slot)
            else:
                slot += 1
        return admitted

    def _finished(self, req: Request, tok: int) -> bool:
        return (len(req.tokens) >= req.max_new_tokens
                or (self.engine.eos_id is not None
                    and tok == self.engine.eos_id))

    def _retire(self, slot: int) -> None:
        req = self.active[slot]
        self.active[slot] = None
        if req.done_t is None:
            req.done_t = self._clock()
        self.completed.append(req)
        if req.trace is not None and req.first_token_t is not None:
            tracing.span(req.trace, "decode", parent=req.span,
                         ms=1e3 * (req.done_t - req.first_token_t),
                         tokens=len(req.tokens))
        obs_events.emit(
            "serve_request", id=req.rid, trace=req.trace,
            prompt_tokens=len(req.prompt),
            output_tokens=len(req.tokens),
            ttft_ms=round(req.ttft_ms() or 0.0, 3),
            tpot_ms=round(req.tpot_ms(), 3)
            if req.tpot_ms() is not None else None)
