"""Health-driven fleet router — admission control, drain, hedged retries.

The consumer the PR 9 telemetry plane was built for: a router that
load-balances generation requests over N replica processes
(``serve/replica.py``) and keeps the fleet available under partial
failure — the goodput-at-fleet-scale discipline of arXiv:2011.03641 and
the Horovod supervisor lineage (arXiv:1802.05799) applied to serving.

Contract (see DESIGN.md "Serving fleet & failure model"):

  admission   ``submit()`` either accepts into a *bounded* pending queue
              or sheds explicitly (429-style, ``router_shed`` event +
              counter) — never unbounded buffering.  Acknowledgment at
              the router means exactly this: an admitted request retires
              exactly once or the run is wrong; a shed request was never
              acknowledged.
  placement   least-loaded healthy replica: local in-flight count first,
              then the live ``tpuframe_serve_queue_depth`` gauge scraped
              off ``/metrics``.
  drain       a 503 from ``/healthz``, a scrape timeout, or a failed
              dispatch marks the replica draining (sticky): no new
              dispatches, and its in-flight requests are re-queued for
              re-dispatch (``router_drain`` / ``router_redispatch``).
              Original attempts keep racing — a gracefully draining
              replica finishes its accepted work and may still win.
  hedging     an in-flight request older than ``hedge_ms`` with no
              racing attempt gets one hedge on another replica
              (``router_hedge``).  First winner kept; losers counted as
              duplicates.  Safe because decode is deterministic
              (greedy argmax / FakeEngine's pure token function):
              re-prefill reproduces the same stream on any replica.
  transport   every scrape and dispatch goes through
              :class:`~tpuframe.resilience.policy.RetryPolicy`
              (decorrelated jitter, attempt timeout, deadline) — the
              TF118 lint keeps raw urllib/socket use out of the rest of
              the tree so this is the *only* client seam.

Threading: dispatch attempts run on daemon threads that only do stdlib
HTTP and a queue put (never jax — the TF111 hazard does not apply); all
router state is owned by the single-threaded ``step()`` loop, which
consumes attempt outcomes from the done queue.

Env knobs: ``TPUFRAME_ROUTER_QUEUE`` (pending bound, default 64),
``TPUFRAME_HEDGE_MS`` (hedge threshold, default 1000),
``TPUFRAME_ROUTER_REPLICAS`` (fleet size for the CLI ``--fleet`` mode).
"""

from __future__ import annotations

import json
import os
import queue
import random
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field

from tpuframe.obs import events as obs_events
from tpuframe.obs import tracing
from tpuframe.obs.goodput import _pct
from tpuframe.resilience.policy import RetryPolicy

ENV_REPLICAS = "TPUFRAME_ROUTER_REPLICAS"
ENV_QUEUE = "TPUFRAME_ROUTER_QUEUE"
ENV_HEDGE_MS = "TPUFRAME_HEDGE_MS"

DEFAULT_QUEUE = 64
DEFAULT_HEDGE_MS = 1000.0
DEFAULT_REPLICAS = 2

ROUTER_EVENT_TYPES = (
    "router_admit", "router_shed", "router_dispatch", "router_hedge",
    "router_redispatch", "router_drain", "router_request",
    "router_summary",
)


def _env_num(name: str, default, cast):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return cast(raw)
    except ValueError:
        return default


def resolve_queue_limit() -> int:
    return max(1, _env_num(ENV_QUEUE, DEFAULT_QUEUE, int))


def resolve_hedge_ms() -> float:
    return _env_num(ENV_HEDGE_MS, DEFAULT_HEDGE_MS, float)


def resolve_replicas() -> int:
    return max(1, _env_num(ENV_REPLICAS, DEFAULT_REPLICAS, int))


def http_transport(url: str, payload: dict | None, timeout_s: float):
    """The one raw-HTTP seam (TF118): POST ``payload`` as JSON, GET when
    ``payload`` is None.  Returns ``(status, parsed body)`` — an HTTP
    error status is an *answer* (503 from a draining replica must not
    burn retry budget); only transport failures raise, as OSError
    subclasses the RetryPolicy's default classification retries."""
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            status, body = r.status, r.read()
    except urllib.error.HTTPError as e:
        status, body = e.code, e.read()
    text = body.decode("utf-8", "replace")
    try:
        return status, json.loads(text)
    except ValueError:
        return status, text


def parse_gauges(text: str, names) -> dict:
    """Label-free gauge samples out of an OpenMetrics page — enough to
    read the queue-depth/active-slots signals off a replica scrape."""
    out: dict = {}
    wanted = set(names)
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) == 2 and parts[0] in wanted:
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                pass
    return out


class Shed(RuntimeError):
    """Raised by ``submit(..., raise_on_shed=True)`` — the 429 analogue."""


@dataclass
class ReplicaHandle:
    """The router's view of one replica."""

    url: str
    name: str
    state: str = "ok"                  # "ok" -> "draining" (sticky)
    queue_depth: float = 0.0
    active_slots: float = 0.0
    last_scrape_t: float = -1e18
    inflight: set = field(default_factory=set)   # rids dispatched here
    # Served weights version, scraped off the replica's label-free
    # ``tpuframe_weights_version`` gauge; None until first seen.  The
    # rollout controller reads this to prove the mixed-version window
    # is bounded (and the canary constraint routes on it).
    version: int | None = None


@dataclass
class RoutedRequest:
    """One request's lifecycle at the router."""

    rid: int
    prompt: list
    max_new_tokens: int
    submit_t: float
    attempts: int = 0                  # dispatches launched (all causes)
    live: int = 0                      # attempt threads still running
    hedged: bool = False
    requeued: bool = False             # next dispatch is a re-dispatch
    last_launch_t: float | None = None
    done_t: float | None = None
    ttft_ms: float | None = None       # router wait + winning replica TTFT
    replica: str | None = None         # winning replica
    result: dict | None = None
    # Tracing context (None when sampled out): the trace id minted at
    # admission and the root "request" span every attempt/serve span
    # parents under.  Rides the dispatch payload into the replica.
    trace: str | None = None
    root_span: str | None = None

    @property
    def done(self) -> bool:
        return self.done_t is not None


class Router:
    """Single-threaded routing loop over a fleet of replica endpoints.

    ``transport`` is injectable (``fn(url, payload|None, timeout_s) ->
    (status, body)``) so the whole drain/hedge/shed state machine is
    unit-testable without processes; the default is
    :func:`http_transport` under the dispatch/scrape RetryPolicies.
    """

    def __init__(self, replica_urls, *, queue_limit: int | None = None,
                 hedge_ms: float | None = None,
                 scrape_interval_s: float = 0.25,
                 scrape_timeout_s: float = 1.0,
                 dispatch_timeout_s: float = 60.0,
                 max_inflight_per_replica: int = 4,
                 transport=None, dispatch_policy: RetryPolicy | None = None,
                 scrape_policy: RetryPolicy | None = None,
                 clock=time.monotonic):
        self.replicas = [ReplicaHandle(url=str(u).rstrip("/"), name=f"r{i}")
                         for i, u in enumerate(replica_urls)]
        self.queue_limit = (resolve_queue_limit() if queue_limit is None
                            else max(1, int(queue_limit)))
        self.hedge_ms = (resolve_hedge_ms() if hedge_ms is None
                         else float(hedge_ms))
        self.scrape_interval_s = scrape_interval_s
        self.scrape_timeout_s = scrape_timeout_s
        self.dispatch_timeout_s = dispatch_timeout_s
        self.max_inflight_per_replica = max_inflight_per_replica
        self._clock = clock
        self._transport = transport or http_transport
        # Both policies bounded on every axis: attempts, per-attempt
        # timeout AND deadline — a router retry loop must never outlive
        # the request it is retrying for.
        self.dispatch_policy = dispatch_policy or RetryPolicy(
            max_attempts=2, base_delay_s=0.02, max_delay_s=0.25,
            attempt_timeout_s=dispatch_timeout_s,
            deadline_s=2.0 * dispatch_timeout_s)
        self.scrape_policy = scrape_policy or RetryPolicy(
            max_attempts=2, base_delay_s=0.02, max_delay_s=0.25,
            attempt_timeout_s=scrape_timeout_s,
            deadline_s=4.0 * scrape_timeout_s)
        self.pending: list[RoutedRequest] = []
        self.inflight: dict[int, RoutedRequest] = {}
        self.completed: list[RoutedRequest] = []
        self.counters = {"admitted": 0, "shed": 0, "completed": 0,
                         "hedged": 0, "redispatched": 0, "duplicates": 0,
                         "dispatch_errors": 0, "drains": 0}
        self._done_q: queue.SimpleQueue = queue.SimpleQueue()
        # Attempt spans launched but not yet reaped — lets run() grant a
        # bounded grace window so late hedge losers close their spans
        # instead of leaking them into the offline anomaly sweep.
        self._open_attempts: set[tuple[str, str]] = set()
        # Canary constraint (rollout controller): while set, a seeded
        # fraction of fresh placements is steered onto the canary
        # replica and the rest onto the old-version pool.
        self._canary_name: str | None = None
        self._canary_frac = 0.0
        self._canary_rng = random.Random(0)

    # -- admission ---------------------------------------------------------

    def submit(self, rid: int, prompt, max_new_tokens: int = 8, *,
               raise_on_shed: bool = False) -> bool:
        """Admit into the bounded queue or shed explicitly.  Admission is
        the router's acknowledgment: an admitted request retires exactly
        once; a shed one was never accepted (and is counted, never
        silently dropped)."""
        depth = len(self.pending) + len(self.inflight)
        if depth >= self.queue_limit:
            self.counters["shed"] += 1
            obs_events.emit("router_shed", id=rid, queued=depth)
            if raise_on_shed:
                raise Shed(f"request {rid}: router queue full "
                           f"({depth}/{self.queue_limit})")
            return False
        req = RoutedRequest(
            rid=rid, prompt=list(prompt),
            max_new_tokens=int(max_new_tokens), submit_t=self._clock(),
            trace=tracing.mint(rid))
        if req.trace is not None:
            req.root_span = tracing.open_span(req.trace, "request",
                                              rid=rid)
        self.pending.append(req)
        self.counters["admitted"] += 1
        obs_events.emit("router_admit", id=rid, trace=req.trace)
        return True

    # -- the routing loop --------------------------------------------------

    def step(self) -> None:
        """One router tick: reap finished attempts, scrape due health,
        hedge stragglers, dispatch what the fleet has capacity for."""
        now = self._clock()
        self._reap()
        self._scrape_due(now)
        self._hedge_due(now)
        self._dispatch_pending()

    def has_work(self) -> bool:
        return bool(self.pending) or bool(self.inflight)

    def _replica(self, name: str) -> ReplicaHandle | None:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        return None

    def set_canary(self, name: str, frac: float, *, seed: int = 0) -> None:
        """Arm the canary placement constraint: a seeded ``frac`` of
        fresh placements lands on replica ``name`` (the new version),
        the rest on the old-version pool — the version constraint the
        rollout gate's old-vs-new comparison needs."""
        self._canary_name = name
        self._canary_frac = min(1.0, max(0.0, float(frac)))
        self._canary_rng = random.Random(seed)

    def clear_canary(self) -> None:
        self._canary_name = None
        self._canary_frac = 0.0

    def _pick(self, exclude_rid: int | None = None
              ) -> ReplicaHandle | None:
        """Least-loaded healthy replica with dispatch capacity, never one
        already holding this rid (a hedge/redispatch must race a
        *different* replica).  Under an armed canary constraint the
        eligible pool is first split canary/rest and one seeded draw
        selects the side — so the traffic fraction is deterministic
        given the seed and the dispatch order."""
        eligible = []
        for rep in self.replicas:
            if rep.state != "ok":
                continue
            if exclude_rid is not None and exclude_rid in rep.inflight:
                continue
            if len(rep.inflight) >= self.max_inflight_per_replica:
                continue
            eligible.append(rep)
        if self._canary_name is not None:
            canary = [r for r in eligible if r.name == self._canary_name]
            rest = [r for r in eligible if r.name != self._canary_name]
            if canary and rest:
                draw = self._canary_rng.random()
                eligible = canary if draw < self._canary_frac else rest
            # One side empty: fall through on whatever has capacity —
            # availability beats the traffic split.
        best = None
        for rep in eligible:
            load = (len(rep.inflight), rep.queue_depth)
            if best is None or load < best[0]:
                best = (load, rep)
        return None if best is None else best[1]

    def drain_replica(self, name: str, *, reason: str) -> bool:
        """Operator/rollout-initiated drain: same sticky state and
        redispatch contract as a health-detected one — no new
        dispatches, in-flight work requeued, originals keep racing."""
        rep = self._replica(name)
        if rep is None:
            return False
        self._mark_draining(rep, reason=reason)
        return True

    def readmit(self, name: str) -> bool:
        """Undo a sticky drain after the rollout controller swapped and
        re-verified the replica: back to "ok", with the scrape clock
        reset so the next step() re-reads its health and version gauge
        immediately."""
        rep = self._replica(name)
        if rep is None:
            return False
        rep.state = "ok"
        rep.last_scrape_t = -1e18
        return True

    def _launch(self, req: RoutedRequest, rep: ReplicaHandle, *,
                cause: str) -> None:
        req.attempts += 1
        req.live += 1
        req.last_launch_t = self._clock()
        rep.inflight.add(req.rid)
        self.inflight[req.rid] = req
        start_t = req.last_launch_t
        url = rep.url + "/generate"
        payload = {"rid": req.rid, "prompt": req.prompt,
                   "max_new_tokens": req.max_new_tokens}
        span = None
        if req.trace is not None:
            span = tracing.open_span(req.trace, "attempt",
                                     parent=req.root_span,
                                     replica=rep.name, cause=cause)
            self._open_attempts.add((req.trace, span))
            # Context propagation: the replica parents its serve span
            # under this attempt, so a hedge race reconstructs as two
            # sibling attempt subtrees of one root.
            payload["trace"] = req.trace
            payload["span"] = span
        trace = req.trace

        def attempt():
            try:
                status, body = self.dispatch_policy.call(
                    self._transport, url, payload,
                    self.dispatch_timeout_s, op="router_dispatch")
                self._done_q.put((req.rid, rep.name, start_t, status,
                                  body, trace, span))
            except Exception as e:  # noqa: BLE001 — retries exhausted or
                # non-retryable: the loop requeues/marks draining
                self._done_q.put((req.rid, rep.name, start_t, None, e,
                                  trace, span))

        # This thread only does stdlib HTTP + a queue put — it never
        # touches jax or a collective, so the TF111 ordering hazard does
        # not apply; all shared state is owned by the step() loop, which
        # consumes outcomes from the done queue.
        threading.Thread(  # tf-lint: ok[TF111]
            target=attempt, daemon=True,
            name=f"router-dispatch-{req.rid}-{rep.name}").start()
        etype = {"hedge": "router_hedge",
                 "redispatch": "router_redispatch"}.get(
            cause, "router_dispatch")
        obs_events.emit(etype, id=req.rid, replica=rep.name)

    def _close_attempt(self, trace, span, start_t: float, *,
                       status: str, **fields) -> None:
        if trace is None or span is None:
            return
        self._open_attempts.discard((trace, span))
        tracing.close_span(trace, span,
                           1e3 * max(0.0, self._clock() - start_t),
                           status=status, **fields)

    def _reap(self) -> None:
        while True:
            try:
                rid, rep_name, start_t, status, body, trace, span = \
                    self._done_q.get_nowait()
            except queue.Empty:
                return
            rep = self._replica(rep_name)
            if rep is not None:
                rep.inflight.discard(rid)
            req = self.inflight.get(rid)
            if req is None or req.done:
                # Hedge/redispatch loser finishing late: first winner
                # was kept, this one is only counted — and its span
                # closes ``duplicate=true`` under the same trace.
                if status == 200:
                    self.counters["duplicates"] += 1
                    self._close_attempt(trace, span, start_t,
                                        status="ok", duplicate=True)
                else:
                    self._close_attempt(trace, span, start_t,
                                        status="error", duplicate=True)
                continue
            req.live -= 1
            if status == 200 and isinstance(body, dict):
                self._close_attempt(trace, span, start_t, status="ok")
                self._complete(req, rep_name, start_t, body)
                continue
            self._close_attempt(
                trace, span, start_t, status="error",
                detail=(type(body).__name__ if status is None
                        else int(status)))
            self.counters["dispatch_errors"] += 1
            if rep is not None and rep.state == "ok":
                why = (f"dispatch {type(body).__name__}"
                       if status is None else f"generate {status}")
                self._mark_draining(rep, reason=why)
            if req.live <= 0 and req not in self.pending:
                # No racing attempt left: back to the queue front.
                req.requeued = True
                self.pending.insert(0, req)
                if req.trace is not None:
                    tracing.note(req.trace, "requeue",
                                 span=req.root_span, replica=rep_name)

    def _complete(self, req: RoutedRequest, rep_name: str, start_t: float,
                  body: dict) -> None:
        req.done_t = self._clock()
        req.replica = rep_name
        req.result = body
        wait_ms = 1e3 * max(0.0, start_t - req.submit_t)
        req.ttft_ms = wait_ms + float(body.get("ttft_ms") or 0.0)
        self.inflight.pop(req.rid, None)
        if req in self.pending:
            self.pending.remove(req)
        self.completed.append(req)
        self.counters["completed"] += 1
        if req.trace is not None and req.root_span is not None:
            # wait_ms + the replica's queue + prefill spans must sum to
            # this ttft_ms — the invariant verify_traces enforces.
            tracing.close_span(
                req.trace, req.root_span,
                1e3 * max(0.0, req.done_t - req.submit_t),
                replica=rep_name, ttft_ms=round(req.ttft_ms, 3),
                wait_ms=round(wait_ms, 3),
                tokens=len(body.get("tokens") or []))
        obs_events.emit(
            "router_request", id=req.rid, replica=rep_name,
            ttft_ms=round(req.ttft_ms, 3),
            wait_ms=round(wait_ms, 3), trace=req.trace,
            output_tokens=len(body.get("tokens") or []),
            attempts=req.attempts)

    def _mark_draining(self, rep: ReplicaHandle, *, reason: str) -> None:
        """503 / scrape timeout / dispatch failure: stop dispatching to
        this replica and requeue its in-flight work for re-dispatch.
        Original attempts keep racing (a graceful drain finishes its
        accepted requests and may still win — first winner kept)."""
        if rep.state == "draining":
            return
        rep.state = "draining"
        self.counters["drains"] += 1
        obs_events.emit("router_drain", replica=rep.name, reason=reason)
        for rid in sorted(rep.inflight):
            req = self.inflight.get(rid)
            if req is None or req.done or req in self.pending:
                continue
            req.requeued = True
            self.pending.insert(0, req)
            if req.trace is not None:
                tracing.note(req.trace, "drain_requeue",
                             span=req.root_span, replica=rep.name,
                             reason=reason)

    def _scrape_due(self, now: float) -> None:
        for rep in self.replicas:
            if (rep.state != "ok"
                    or now - rep.last_scrape_t < self.scrape_interval_s):
                continue
            rep.last_scrape_t = now
            try:
                status, _body = self.scrape_policy.call(
                    self._transport, rep.url + "/healthz", None,
                    self.scrape_timeout_s, op="router_scrape")
            except Exception as e:  # noqa: BLE001 — unreachable after
                # retries: that IS the drain signal
                self._mark_draining(rep,
                                    reason=f"scrape {type(e).__name__}")
                continue
            if status != 200:
                self._mark_draining(rep, reason=f"healthz {status}")
                continue
            try:
                _s, text = self.scrape_policy.call(
                    self._transport, rep.url + "/metrics", None,
                    self.scrape_timeout_s, op="router_scrape")
                gauges = parse_gauges(
                    text if isinstance(text, str) else "",
                    ("tpuframe_serve_queue_depth",
                     "tpuframe_serve_active_slots",
                     "tpuframe_weights_version"))
                rep.queue_depth = gauges.get("tpuframe_serve_queue_depth",
                                             rep.queue_depth)
                rep.active_slots = gauges.get(
                    "tpuframe_serve_active_slots", rep.active_slots)
                if "tpuframe_weights_version" in gauges:
                    rep.version = int(gauges["tpuframe_weights_version"])
            except Exception:  # noqa: BLE001 — the load signal is
                pass  # best-effort; /healthz above is authoritative

    def _hedge_due(self, now: float) -> None:
        if self.hedge_ms <= 0:
            return
        for req in list(self.inflight.values()):
            if (req.done or req.hedged or req.live != 1
                    or req in self.pending
                    or req.last_launch_t is None):
                continue
            if 1e3 * (now - req.last_launch_t) < self.hedge_ms:
                continue
            rep = self._pick(exclude_rid=req.rid)
            if rep is None:
                continue
            req.hedged = True
            self.counters["hedged"] += 1
            self._launch(req, rep, cause="hedge")

    def _dispatch_pending(self) -> None:
        while self.pending:
            req = self.pending[0]
            if req.done:
                self.pending.pop(0)
                continue
            rep = self._pick(exclude_rid=req.rid)
            if rep is None:
                return
            self.pending.pop(0)
            if req.requeued:
                self.counters["redispatched"] += 1
                self._launch(req, rep, cause="redispatch")
                req.requeued = False
            else:
                self._launch(req, rep, cause="first")

    # -- open-loop drive ---------------------------------------------------

    def run(self, requests, *, timeout_s: float = 60.0,
            arrival_speedup: float = 1.0, poll_s: float = 0.002,
            on_tick=None, log=None) -> dict:
        """Drive the loadgen's seeded schedule through the fleet: submit
        each request once the wall clock passes its ``arrival_t`` (virtual
        seconds scaled by ``arrival_speedup``), tick the router until
        everything admitted has retired (or ``timeout_s`` trips — counted
        as lost, never silently).  ``on_tick()`` (if given) runs once per
        loop after ``step()`` — the rollout controller's drive seam; when
        it returns a truthy "keep running" the loop also waits for it,
        not just for the request backlog."""
        todo = sorted(requests, key=lambda r: r.arrival_t)
        t0 = self._clock()
        i = 0
        timed_out = False
        while True:
            now = self._clock() - t0
            while (i < len(todo)
                   and todo[i].arrival_t / arrival_speedup <= now):
                r = todo[i]
                i += 1
                self.submit(r.rid, r.prompt, r.max_new_tokens)
            self.step()
            tick_busy = bool(on_tick()) if on_tick is not None else False
            if i >= len(todo) and not self.has_work() and not tick_busy:
                break
            if now > timeout_s:
                timed_out = True
                break
            time.sleep(poll_s)
        # Bounded grace for late hedge/redispatch losers: their attempt
        # threads may still be in flight after every request retired;
        # reap them so their spans close as duplicates instead of
        # leaking.  Wall clock on purpose — tests inject fake _clocks
        # that do not advance while we sleep.
        grace_end = time.monotonic() + 2.0
        while self._open_attempts and time.monotonic() < grace_end:
            self._reap()
            time.sleep(poll_s)
        out = self.summary()
        out["submitted"] = i
        out["timed_out"] = timed_out
        if log:
            log(f"fleet: {out['requests']}/{out['admitted']} admitted "
                f"requests completed, {out['shed']} shed, "
                f"{out['redispatched']} redispatched, "
                f"{out['hedged']} hedged, {out['drains']} drain(s)")
        return out

    def summary(self) -> dict:
        """Fleet rollup (also emitted as the typed ``router_summary``)."""
        ttft = sorted(r.ttft_ms for r in self.completed
                      if r.ttft_ms is not None)
        out = {
            "requests": self.counters["completed"],
            "admitted": self.counters["admitted"],
            "shed": self.counters["shed"],
            "lost": self.counters["admitted"] - self.counters["completed"],
            "hedged": self.counters["hedged"],
            "redispatched": self.counters["redispatched"],
            "duplicates": self.counters["duplicates"],
            "dispatch_errors": self.counters["dispatch_errors"],
            "drains": self.counters["drains"],
            "replicas": len(self.replicas),
            "versions": {rep.name: rep.version for rep in self.replicas},
            "ttft_ms": {q: round(_pct(ttft, v), 3) for q, v in
                        (("p50", 0.5), ("p90", 0.9), ("p99", 0.99))}
            if ttft else None,
        }
        flat = {k: v for k, v in out.items() if not isinstance(v, dict)}
        if out["ttft_ms"]:
            flat.update({f"ttft_{q}_ms": v
                         for q, v in out["ttft_ms"].items()})
        obs_events.emit("router_summary", **flat)
        return out


# ---------------------------------------------------------------------------
# Fleet harness — subprocess replicas + router, shared by the chaos tier
# and ``python -m tpuframe.serve --selfcheck`` (the offline CPU proof).
# ---------------------------------------------------------------------------

def _spawn_replica(rank: int, *, tmpdir: str, events_dir: str | None,
                   engine: str, slots: int, step_delay_ms: float,
                   stall_timeout_s: float, faults_spec: str | None,
                   weights_version: int = 0, port: int = 0):
    ready = os.path.join(tmpdir, f"ready.{rank}")
    log_path = os.path.join(tmpdir, f"replica.{rank}.log")
    env = dict(os.environ)
    env.update({
        # 0 = ephemeral (port read back via READY); a relaunch after a
        # mid-swap kill passes the dead replica's port so the router's
        # URL stays valid.
        "TPUFRAME_METRICS_PORT": str(port),
        "TPUFRAME_PROCESS_ID": str(rank),
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "TPUFRAME_ATTEMPT": env.get("TPUFRAME_ATTEMPT", "0"),
    })
    env.pop("TPUFRAME_FAULTS", None)
    # the removed legacy aliases now RAISE at registry build — scrub
    # them so an operator shell that still exports one cannot take down
    # a replica that never asked for a fault
    env.pop("TPUFRAME_FAULT_STEP", None)
    env.pop("TPUFRAME_FAULT_ONCE", None)
    if events_dir:
        env["TPUFRAME_EVENTS_DIR"] = events_dir
    if faults_spec:
        env["TPUFRAME_FAULTS"] = faults_spec
    cmd = [sys.executable, "-m", "tpuframe.serve.replica",
           "--engine", engine, "--slots", str(slots),
           "--step-delay-ms", str(step_delay_ms),
           "--stall-timeout-s", str(stall_timeout_s),
           "--weights-version", str(weights_version),
           "--max-idle-s", "60", "--ready-file", ready]
    log_fh = open(log_path, "wb")
    proc = subprocess.Popen(cmd, env=env, stdout=log_fh, stderr=log_fh)
    log_fh.close()
    return proc, ready, log_path


def _wait_ready(proc, ready_path: str, *, timeout_s: float) -> int:
    """Poll the replica's ready file for its bound port."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(ready_path):
            text = open(ready_path).read()
            for part in text.split():
                if part.startswith("port="):
                    return int(part.split("=", 1)[1])
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica exited rc={proc.returncode} before READY")
        time.sleep(0.01)
    raise RuntimeError(f"replica not ready after {timeout_s}s")


def fleet_smoke(*, replicas: int = 2, n_requests: int = 12,
                kill_rank: int | None = None, kill_step: int = 3,
                seed: int = 0, events_dir: str | None = None,
                engine: str = "fake", slots: int = 2,
                step_delay_ms: float = 5.0, rate: float = 50.0,
                max_new_tokens: int = 8, queue_limit: int | None = None,
                hedge_ms: float | None = None,
                scrape_interval_s: float = 0.05,
                timeout_s: float = 60.0, ready_timeout_s: float = 30.0,
                log=None) -> dict:
    """Spawn a CPU fleet of replica subprocesses, drive the seeded
    Poisson loadgen through the router, optionally ``replica_crash`` one
    replica mid-run, tear the fleet down, and return the router summary
    plus replica exit codes — the chaos tier's and the selfcheck's
    shared offline proof harness."""
    import shutil
    import tempfile

    from tpuframe.serve import loadgen

    tmpdir = tempfile.mkdtemp(prefix="tpuframe-fleet-")
    procs = []
    old_proc_id = os.environ.get("TPUFRAME_PROCESS_ID")
    try:
        for rank in range(replicas):
            spec = None
            if kill_rank is not None and rank == kill_rank:
                spec = (f"replica_crash:step={kill_step}"
                        f":rank={kill_rank}")
            procs.append(_spawn_replica(
                rank, tmpdir=tmpdir, events_dir=events_dir, engine=engine,
                slots=slots, step_delay_ms=step_delay_ms,
                stall_timeout_s=2.0, faults_spec=spec))
        urls = [f"http://127.0.0.1:"
                f"{_wait_ready(p, ready, timeout_s=ready_timeout_s)}"
                for p, ready, _log in procs]
        if events_dir:
            # The router's own events get their own per-process file
            # (the replicas own ranks 0..N-1).
            os.environ["TPUFRAME_PROCESS_ID"] = str(replicas + 90)
            obs_events.init(events_dir)
        reqs = loadgen.synthetic_requests(
            n_requests, buckets=(16, 32), rate=rate,
            max_new_tokens=max_new_tokens, vocab_size=256, seed=seed)
        router = Router(urls, queue_limit=queue_limit, hedge_ms=hedge_ms,
                        scrape_interval_s=scrape_interval_s,
                        scrape_timeout_s=0.5, dispatch_timeout_s=30.0,
                        max_inflight_per_replica=max(2, slots))
        out = router.run(reqs, timeout_s=timeout_s, log=log)
        if events_dir:
            obs_events.close()
        for proc, _ready, _log in procs:
            if proc.poll() is None:
                proc.terminate()  # graceful drain path (SIGTERM)
        exit_codes = []
        for proc, _ready, _log in procs:
            try:
                exit_codes.append(proc.wait(timeout=10))
            except subprocess.TimeoutExpired:
                proc.kill()
                exit_codes.append(proc.wait(timeout=10))
        out["exit_codes"] = exit_codes
        return out
    finally:
        if old_proc_id is None:
            os.environ.pop("TPUFRAME_PROCESS_ID", None)
        else:
            os.environ["TPUFRAME_PROCESS_ID"] = old_proc_id
        for proc, _ready, _log in procs:
            if proc.poll() is None:
                proc.kill()
        shutil.rmtree(tmpdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Analysis-gate self-check (``python -m tpuframe.analysis``).
# ---------------------------------------------------------------------------

def check() -> list:
    """Host-only router checks for the CI gate: event registration, the
    TF118 client seam over the whole tree, admission arithmetic, bounded
    retry policies, and the replica fault seams.  Returns problem
    strings; [] means healthy."""
    import pathlib

    problems: list = []

    from tpuframe.obs import events as events_lib

    for etype in ROUTER_EVENT_TYPES:
        if etype not in events_lib.REQUIRED_FIELDS:
            problems.append(
                f"router event type {etype!r} not registered in "
                f"obs.events.REQUIRED_FIELDS (TF112 contract)")

    from tpuframe.analysis import source_lint

    pkg = pathlib.Path(__file__).resolve().parent.parent
    try:
        findings = source_lint.lint_paths([pkg])
    except Exception as exc:  # noqa: BLE001
        problems.append(f"router lint crashed: {exc!r}")
        findings = []
    problems += [f"router lint: {f}" for f in findings
                 if f.rule == "TF118"]

    # Admission control: the bounded queue sheds at the limit and counts
    # it — never unbounded buffering.
    r = Router(["http://127.0.0.1:9"], queue_limit=2,
               transport=lambda *_a, **_k: (503, "check() never dispatches"))
    if not (r.submit(0, [1, 2]) and r.submit(1, [1, 2])):
        problems.append("admission control: queue rejected below limit")
    if r.submit(2, [1, 2]):
        problems.append("admission control: queue did not shed at limit")
    if r.counters["shed"] != 1 or r.counters["admitted"] != 2:
        problems.append(
            f"admission counters wrong: {r.counters['admitted']} admitted,"
            f" {r.counters['shed']} shed (want 2, 1)")

    for pol, what in ((r.dispatch_policy, "dispatch"),
                      (r.scrape_policy, "scrape")):
        if pol.max_attempts < 1 or pol.deadline_s is None \
                or pol.attempt_timeout_s is None:
            problems.append(f"{what} RetryPolicy unbounded "
                            f"(attempts/timeout/deadline must all be set)")

    from tpuframe.resilience import faults as faults_lib

    for seam, kind in (("replica_crash", "crash"),
                       ("replica_hang", "hang"),
                       ("replica_slow", "slow")):
        try:
            parsed = faults_lib.parse(seam)
        except ValueError as exc:
            problems.append(f"fault seam {seam} unparseable: {exc}")
            continue
        if not parsed or parsed[0].kind != kind:
            problems.append(f"fault seam {seam}: default kind "
                            f"{parsed[0].kind if parsed else '?'} "
                            f"(want {kind})")

    if resolve_queue_limit() < 1:
        problems.append("TPUFRAME_ROUTER_QUEUE resolved below 1")
    if resolve_replicas() < 1:
        problems.append("TPUFRAME_ROUTER_REPLICAS resolved below 1")

    return problems
