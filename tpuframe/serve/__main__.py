"""``python -m tpuframe.serve`` — serving loadgen CLI + CPU selfcheck.

Default mode runs the open-loop load generator over a named model with
continuous batching and prints the summary stats (writing obs v2 events
when ``TPUFRAME_EVENTS_DIR``/``--events-dir`` is set)::

    python -m tpuframe.serve --model tiny-lm --steps 100

``--selfcheck`` is the CI/acceptance entry: golden-logits parity on
every bucket, a full loadgen run with events, an ``obs summarize``
subprocess proving the TTFT/TPOT/tokens-per-sec reporting path, a BERT
single-shot classification smoke, and the persistent-cache safety
assertion — all on CPU, no accelerator required.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile


def _build_engine(model: str, *, slots: int, buckets, decode_block,
                  max_context):
    from tpuframe.models.transformer_lm import LMConfig
    from tpuframe.serve.engine import LMEngine

    if model != "tiny-lm":
        raise SystemExit(f"unknown --model {model!r} (have: tiny-lm)")
    cfg = LMConfig.tiny()
    return LMEngine(cfg, slots=slots, prompt_buckets=buckets,
                    decode_block=decode_block, max_context=max_context)


def cmd_run(args) -> int:
    from tpuframe.obs import events as obs_events
    from tpuframe.serve import loadgen

    if args.events_dir:
        os.environ["TPUFRAME_EVENTS_DIR"] = args.events_dir
    obs_events.init()

    print(f"[serve] building engine for {args.model} "
          f"(slots={args.slots}) ...", flush=True)
    engine = _build_engine(args.model, slots=args.slots, buckets=None,
                           decode_block=None, max_context=None)
    n_requests = max(1, args.steps // 4)
    reqs = loadgen.synthetic_requests(
        n_requests, buckets=engine.prompt_buckets,
        vocab_size=engine.cfg.vocab_size, seed=args.seed,
        max_new_tokens=args.max_new_tokens)
    stats = loadgen.run_loadgen(engine, reqs, max_steps=args.steps,
                                log=lambda m: print(f"[serve] {m}"))
    for key in ("requests", "steps", "total_tokens", "tokens_per_s",
                "tokens_per_s_per_chip"):
        print(f"[serve] {key}: {stats[key]}")
    if stats["unfinished"]:
        print(f"[serve] {stats['unfinished']} request(s) still in flight "
              f"at the --steps cap")
    obs_events.close()
    # The step cap bounds the run, not its correctness — fail only when
    # the engine served nothing at all.
    return 0 if stats["requests"] > 0 else 1


def cmd_selfcheck(args) -> int:
    import jax

    from tpuframe.models.bert import BertConfig
    from tpuframe.models.transformer_lm import LMConfig
    from tpuframe.obs import events as obs_events
    from tpuframe.serve import kv_cache as kv
    from tpuframe.serve import loadgen
    from tpuframe.serve.engine import (BertClassifier, LMEngine,
                                       golden_parity_check)
    from tpuframe.utils import compile_cache

    failures = []
    buckets = (16, 32)
    block = 16
    decode_tokens = 4
    cfg = LMConfig.tiny()

    # 1. Golden-logits parity: prefill+decode == training forward, every
    #    bucket, full and ragged prompt lengths.  Capacity leaves head
    #    room for the decoded tail on top of the largest bucket.
    cap = kv.capacity_for(max(buckets) + decode_tokens, block)
    problems = golden_parity_check(cfg, buckets=buckets, capacity=cap,
                                   decode_tokens=decode_tokens)
    for p in problems:
        failures.append(f"parity: {p}")
    print(f"[serve] parity: {len(buckets)} buckets, "
          f"{len(problems)} problem(s)")

    # 2. Continuous-batching loadgen with obs events on.
    with tempfile.TemporaryDirectory(prefix="tpuframe-serve-") as tmp:
        events_dir = os.path.join(tmp, "events")
        obs_events.init(events_dir)
        engine = LMEngine(cfg, slots=3, prompt_buckets=buckets,
                          decode_block=block,
                          max_context=max(buckets) + decode_tokens)
        reqs = loadgen.synthetic_requests(
            8, buckets=buckets, vocab_size=cfg.vocab_size,
            max_new_tokens=decode_tokens, seed=args.seed)
        stats = loadgen.run_loadgen(engine, reqs)
        obs_events.close()
        if stats["requests"] != 8 or stats["unfinished"]:
            failures.append(f"loadgen: {stats['requests']}/8 requests "
                            f"completed, {stats['unfinished']} unfinished")
        print(f"[serve] loadgen: {stats['requests']} requests, "
              f"{stats['total_tokens']} tokens, "
              f"{stats['tokens_per_s']} tok/s")

        # 3. The offline analyzer reports serving latency from those
        #    events (TTFT/TPOT percentiles, tokens/sec/chip).
        proc = subprocess.run(
            [sys.executable, "-m", "tpuframe.obs", "summarize",
             events_dir],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        out = proc.stdout
        if proc.returncode != 0:
            failures.append(f"obs summarize exited {proc.returncode}: "
                            f"{proc.stderr.strip()[-200:]}")
        for needle in ("serving", "TTFT", "TPOT", "tokens/s"):
            if needle not in out:
                failures.append(f"obs summarize missing {needle!r} "
                                "in serve section")
        print("[serve] obs summarize serve section:")
        for line in out.splitlines():
            if any(k in line for k in ("serving", "TTFT", "TPOT",
                                       "tokens/s")):
                print(f"    {line.strip()}")

    # 4. Single-shot BERT classification (the non-autoregressive path).
    clf = BertClassifier(BertConfig.tiny(num_classes=3), buckets=(16, 32))
    label, probs = clf.classify(list(range(1, 11)))
    if not (0 <= label < 3 and abs(float(probs.sum()) - 1.0) < 1e-4):
        failures.append(f"bert classify: label={label} "
                        f"probs_sum={float(probs.sum()):.4f}")
    print(f"[serve] bert classify: label={label} ok")

    # 5. Persistent-cache safety of the decode outputs (int32 tokens +
    #    f32 cache only — no typed PRNG keys).
    out_avals = jax.eval_shape(lambda: engine._tokens)
    if not compile_cache.outputs_cache_safe(out_avals):
        failures.append("decode outputs flagged cache-unsafe")
    print("[serve] compile-cache safety: ok")

    # 6. Fleet smoke: 2 fake-engine replica subprocesses behind the
    #    router, seeded loadgen, one replica_crash mid-run — the
    #    zero-loss drain/redispatch contract on every CI run (the full
    #    3-replica latency proof lives in tests/test_chaos.py).
    from tpuframe.serve import router as router_lib

    try:
        fleet = router_lib.fleet_smoke(
            replicas=2, n_requests=10, kill_rank=1, kill_step=3,
            step_delay_ms=5.0, seed=args.seed,
            log=lambda m: print(f"[serve] {m}"))
    except Exception as e:  # noqa: BLE001 — a harness crash is a failure
        failures.append(f"fleet smoke crashed: {type(e).__name__}: {e}")
    else:
        if fleet["lost"] or fleet["shed"] or fleet["timed_out"]:
            failures.append(
                f"fleet smoke: lost={fleet['lost']} shed={fleet['shed']} "
                f"timed_out={fleet['timed_out']} (want 0/0/False)")
        if fleet["drains"] < 1:
            failures.append("fleet smoke: replica_crash produced no "
                            "router drain")
        print(f"[serve] fleet smoke: {fleet['requests']} requests, "
              f"{fleet['drains']} drain(s), "
              f"{fleet['redispatched']} redispatched, "
              f"exit codes {fleet['exit_codes']}")

    for f in failures:
        print(f"SERVE FAIL {f}")
    print(f"[serve] selfcheck: {len(failures)} failure(s)")
    return 1 if failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpuframe.serve",
        description="tpuframe serving loadgen / selfcheck")
    ap.add_argument("--model", default="tiny-lm")
    ap.add_argument("--steps", type=int, default=100,
                    help="max scheduler steps for the loadgen run")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--events-dir", default=None,
                    help="write obs v2 events here (else "
                         "TPUFRAME_EVENTS_DIR)")
    ap.add_argument("--selfcheck", action="store_true",
                    help="run the CPU acceptance selfcheck and exit")
    args = ap.parse_args(argv)
    if args.selfcheck:
        return cmd_selfcheck(args)
    return cmd_run(args)


if __name__ == "__main__":
    raise SystemExit(main())
