"""Open-loop synthetic load generator for the serving engine.

Open-loop means arrivals follow a fixed schedule regardless of how fast
the engine drains them (the honest way to measure serving latency —
closed-loop generators hide queueing collapse by self-throttling).
Arrivals are a seeded Poisson process on a virtual clock advanced once
per scheduler step, so a run is fully deterministic and CPU-mesh
friendly: no sleeps, no wall-clock dependence in the *schedule* (TTFT /
TPOT are still measured on the real host clock by the scheduler).

``run_loadgen`` drives a :class:`~tpuframe.serve.scheduler.Scheduler`
until every synthetic request completes (or ``max_steps`` trips), emits
a final typed ``serve_summary`` event, and returns the stats dict the
selfcheck asserts on.
"""

from __future__ import annotations

import random
import time

from tpuframe.obs import events as obs_events
from tpuframe.serve.scheduler import Request, Scheduler


def synthetic_requests(n: int, *, buckets, rate: float = 2.0,
                       max_new_tokens: int = 8, vocab_size: int = 256,
                       seed: int = 0) -> list:
    """``n`` requests with Poisson inter-arrival times (virtual seconds,
    ``rate`` = requests/virtual-second) and prompt lengths drawn per
    bucket — every bucket gets traffic, ragged lengths included, so a
    loadgen run exercises the engine's whole AOT table."""
    rng = random.Random(seed)
    out = []
    t = 0.0
    buckets = tuple(sorted(buckets))
    for rid in range(n):
        t += rng.expovariate(rate)
        bucket = buckets[rid % len(buckets)]
        lo = 1 if bucket == buckets[0] else buckets[
            buckets.index(bucket) - 1] + 1
        length = rng.randint(lo, bucket)
        prompt = [rng.randrange(vocab_size) for _ in range(length)]
        out.append(Request(rid=rid, prompt=prompt,
                           max_new_tokens=max_new_tokens, arrival_t=t))
    return out


def run_loadgen(engine, requests, *, max_steps: int = 10_000,
                steps_per_virtual_s: float = 50.0, log=None) -> dict:
    """Drive the scheduler with an open-loop arrival schedule.

    The virtual clock advances ``1 / steps_per_virtual_s`` per scheduler
    step; a request is submitted once the virtual clock passes its
    arrival time.  Returns summary stats (and emits ``serve_summary``).
    """
    sched = Scheduler(engine)
    todo = sorted(requests, key=lambda r: r.arrival_t)
    t_wall0 = time.perf_counter()
    virtual_t = 0.0
    i = 0
    steps = 0
    while (i < len(todo) or sched.has_work()) and steps < max_steps:
        while i < len(todo) and todo[i].arrival_t <= virtual_t:
            req = todo[i]
            req.arrival_t = time.perf_counter()  # host clock for latency
            sched.submit(req)
            i += 1
        if sched.has_work():
            sched.step()
            virtual_t += 1.0 / steps_per_virtual_s
            steps += 1
        else:
            # Idle gap: jump straight to the next arrival — an idle
            # engine costs no step budget (open-loop in the queueing
            # sense: arrival *spacing* is still the schedule's).
            virtual_t = todo[i].arrival_t
    # Synced: every decode step above materialized its tokens to host
    # numpy, so this wall clock covers execution, not dispatch.
    wall_s = time.perf_counter() - t_wall0  # tf-lint: ok[TF103]

    completed = sched.completed
    total_tokens = sum(len(r.tokens) for r in completed)
    tokens_per_s = total_tokens / wall_s if wall_s > 0 else 0.0
    n_devices = _local_device_count()
    stats = {
        "requests": len(completed),
        "submitted": i,
        "unfinished": i - len(completed),
        "steps": sched.step_count,
        "wall_s": round(wall_s, 3),
        "total_tokens": total_tokens,
        "tokens_per_s": round(tokens_per_s, 2),
        "tokens_per_s_per_chip": round(tokens_per_s / n_devices, 2),
        "n_devices": n_devices,
    }
    obs_events.emit("serve_summary", **stats)
    if log:
        log(f"loadgen: {stats['requests']} requests, "
            f"{stats['total_tokens']} tokens in {stats['wall_s']}s "
            f"({stats['tokens_per_s']} tok/s)")
    return stats


def _local_device_count() -> int:
    """Device count without forcing backend init order games — jax is
    already imported by any caller that built an engine."""
    import jax

    try:
        return max(1, jax.local_device_count())
    except Exception:  # noqa: BLE001 — backendless host: count as 1
        return 1
