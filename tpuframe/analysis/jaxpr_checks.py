"""Layer 2: traced-program (jaxpr) audits.

Three defect classes the compiled-HLO layer cannot attribute cleanly are
visible in the jaxpr, before any backend work:

  * **f32 upcasts in bf16 regions** — a stray ``.astype(float32)`` (or a
    library default) silently runs a matmul/conv off the bf16 MXU path,
    doubling its bytes and flops.  :func:`find_f32_matmuls` reports
    every MXU-class op whose operands are f32; a step declared
    ``compute_dtype=bfloat16`` should report none (reductions, BN
    statistics and optimizer math legitimately accumulate in f32 —
    those are not matmuls and are not flagged).
  * **trace-time constant capture** — a host array closed over instead
    of passed as an argument is baked into the program as a literal:
    it bloats the executable, defeats donation, and re-traces on every
    content change.  :func:`find_large_constants` walks the closed
    jaxpr's consts (including nested jaxprs).
  * **donation leaks** — a buffer declared donated (``donate_argnums``)
    that the compiled module does not actually alias to an output keeps
    BOTH copies live at peak; at ResNet/LM state sizes that is the
    difference between fitting HBM and not.  :func:`audit_donation`
    parses the executable's ``input_output_alias`` table and diffs it
    against the declaration.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import numpy as np

# MXU-class primitives: the ops whose dtype decides whether the step is
# actually running on the bf16 fast path.
_MATMUL_PRIMS = ("dot_general", "conv_general_dilated")

# Primitive params that hold nested (possibly closed) jaxprs.
_SUBJAXPR_PARAMS = ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr",
                    "fun_jaxpr", "fwd_jaxpr_thunk", "branches")


def _as_closed(j):
    """Accept ClosedJaxpr | Jaxpr | objects with a .jaxpr attribute."""
    if hasattr(j, "jaxpr"):      # ClosedJaxpr
        return j
    return None


def iter_eqns(closed_jaxpr):
    """Yield every eqn of a (closed) jaxpr, recursing into nested ones."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn
        for name, v in eqn.params.items():
            if name not in _SUBJAXPR_PARAMS:
                continue
            subs = v if isinstance(v, (list, tuple)) else [v]
            for sub in subs:
                if sub is None or callable(sub) and not hasattr(sub, "jaxpr"):
                    continue
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    yield from iter_eqns(sub)


def iter_consts(closed_jaxpr):
    """Yield every captured constant, recursing into nested closed
    jaxprs (whose consts are their own)."""
    consts = getattr(closed_jaxpr, "consts", None) or []
    yield from consts
    for eqn in iter_eqns(closed_jaxpr):
        for name, v in eqn.params.items():
            if name not in _SUBJAXPR_PARAMS:
                continue
            subs = v if isinstance(v, (list, tuple)) else [v]
            for sub in subs:
                sub_consts = getattr(sub, "consts", None)
                if sub_consts:
                    yield from sub_consts


@dataclass
class PrecisionFinding:
    primitive: str
    dtypes: tuple[str, ...]
    shapes: tuple[tuple[int, ...], ...]

    def __str__(self):
        ops = ", ".join(f"{d}{list(s)}"
                        for d, s in zip(self.dtypes, self.shapes))
        return f"{self.primitive} on ({ops})"


def find_f32_matmuls(traced) -> list[PrecisionFinding]:
    """MXU-class eqns with any float32 operand.

    ``traced``: a (closed) jaxpr, or anything ``jax.make_jaxpr`` already
    produced.  In a bf16-declared step this list should be empty —
    each entry is a matmul/conv that fell off the bf16 path.
    """
    findings = []
    for eqn in iter_eqns(traced):
        if eqn.primitive.name not in _MATMUL_PRIMS:
            continue
        avals = [getattr(v, "aval", None) for v in eqn.invars]
        dts = tuple(str(a.dtype) for a in avals if a is not None)
        if any(dt == "float32" for dt in dts):
            findings.append(PrecisionFinding(
                primitive=eqn.primitive.name,
                dtypes=dts,
                shapes=tuple(tuple(a.shape) for a in avals
                             if a is not None)))
    return findings


def has_bf16(traced) -> bool:
    """True if any eqn in the program touches bfloat16 — the cheap guard
    that makes :func:`find_f32_matmuls` meaningful ("bf16 region")."""
    for eqn in iter_eqns(traced):
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None and str(getattr(aval, "dtype", "")) \
                    == "bfloat16":
                return True
    return False


@dataclass
class ConstFinding:
    nbytes: int
    dtype: str
    shape: tuple[int, ...]

    def __str__(self):
        return (f"captured constant {self.dtype}{list(self.shape)} "
                f"({self.nbytes / 1e6:.2f} MB)")


def find_large_constants(traced, min_bytes: int = 1 << 20) \
        -> list[ConstFinding]:
    """Constants baked into the traced program at or above ``min_bytes``
    — host arrays that should have been step arguments."""
    findings = []
    for c in iter_consts(traced):
        try:
            arr = np.asarray(c)
        except Exception:  # noqa: BLE001 — exotic leaf: not a host bake
            continue
        if arr.nbytes >= min_bytes:
            findings.append(ConstFinding(
                nbytes=int(arr.nbytes), dtype=str(arr.dtype),
                shape=tuple(arr.shape)))
    return sorted(findings, key=lambda f: -f.nbytes)


# ---------------------------------------------------------------------------
# Donation audit — parsed from the executable text, not from warnings,
# so it works on AOT artifacts and saved HLO dumps alike.
# ---------------------------------------------------------------------------

# HloModule header form: input_output_alias={ {0}: (0, {}, may-alias),
# {1}: (2, {1}, must-alias) } — output index tree : (param_number,
# param_index tree, kind).
_ALIAS_BLOCK_RE = re.compile(r"input_output_alias=\{(.*?)\}(?:,|\s|$)")
_ALIAS_ENTRY_RE = re.compile(r"\{[0-9, ]*\}:\s*\((\d+),")


def parse_input_output_alias(hlo_text: str) -> set[int]:
    """Parameter numbers the executable aliases to some output."""
    aliased: set[int] = set()
    # The header is one (very long) line; search the whole text but the
    # alias table only ever appears in the HloModule line.
    for line in hlo_text.splitlines():
        if "input_output_alias=" not in line:
            continue
        m = _ALIAS_BLOCK_RE.search(line)
        if not m:
            continue
        # Entries may nest one brace level ({1}: (0, {2}, ...)); the
        # lazy block regex can under-capture — scan the rest of the
        # line's entries directly instead.
        tail = line[line.index("input_output_alias=") :]
        depth, end = 0, 0
        for i, ch in enumerate(tail):
            if ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        block = tail[: end + 1]
        for em in _ALIAS_ENTRY_RE.finditer(block):
            aliased.add(int(em.group(1)))
    return aliased


@dataclass
class DonationReport:
    """Declared-vs-actual buffer donation for one executable."""

    declared: set[int] = field(default_factory=set)   # flat param numbers
    aliased: set[int] = field(default_factory=set)
    platform_supports: bool = True

    @property
    def leaked(self) -> set[int]:
        return self.declared - self.aliased

    def __str__(self):
        if not self.platform_supports:
            return ("donation not implemented on this backend — audit "
                    "on a TPU topology (AOT) for a real answer")
        return (f"declared={len(self.declared)} aliased={len(self.aliased)} "
                f"leaked={len(self.leaked)}"
                + (f" (param numbers {sorted(self.leaked)[:8]}...)"
                   if self.leaked else ""))


def audit_donation(compiled, declared: set[int] | None = None,
                   platform: str | None = None) -> DonationReport:
    """Diff declared donations against the executable's alias table.

    ``compiled``: an AOT executable (``.as_text()``) or raw HLO text.
    ``declared``: flat parameter numbers expected to be donated; when
    omitted, the report only carries what IS aliased (useful as a
    baseline).  XLA:CPU ignores donation entirely — when ``platform``
    (or the executable's platform) is cpu and nothing aliased,
    ``platform_supports=False`` instead of reporting a mass leak.
    """
    txt = compiled if isinstance(compiled, str) else compiled.as_text()
    aliased = parse_input_output_alias(txt)
    if platform is None and not isinstance(compiled, str):
        try:
            platform = compiled.runtime_executable().platform()  # pragma: no cover
        except Exception:  # noqa: BLE001
            platform = None
    supports = True
    if not aliased and (platform or "").lower() in ("cpu", "host"):
        supports = False
    return DonationReport(declared=set(declared or ()), aliased=aliased,
                          platform_supports=supports)
